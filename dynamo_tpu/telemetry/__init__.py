"""dynamo_tpu.telemetry — dependency-free tracing, metrics, and live
introspection.

Four pieces (docs/observability.md is the operator-facing guide):

- **Spans** (spans.py): ``get_tracer().span("name", parent=ctx)`` with
  trace-context propagation over the existing transport. Enabled by
  ``DYN_TRACE_FILE`` (JSONL); ``dynamo-tpu trace export`` renders
  Perfetto/chrome://tracing flame graphs (export.py).
- **Metrics** (metrics.py): one process registry of labeled counters/
  gauges/histograms with Prometheus text exposition and cardinality
  guard rails; the serving stack's catalog lives in instruments.py.
- **Live introspection** (debug.py, recorder.py, hbm.py): the
  ``/debug/state``/``/debug/profile`` provider registry, the engine's
  step flight recorder with slow-step watchdog dumps, and HBM memory
  accounting. ``dynamo-tpu top`` renders the fleet view.
- **SLO/goodput** (slo.py): per-request TTFT/ITL vs configured targets
  → ``dynamo_slo_attainment``/``dynamo_goodput_tokens_total``, riding
  the worker load feed for the Planner.
"""

from dynamo_tpu.telemetry.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Metric,
    Registry,
    REGISTRY,
    check_scrape_safety,
    escape_label_value,
)
from dynamo_tpu.telemetry.debug import (  # noqa: F401
    capture_profile,
    collect_debug_state,
    debug_provider_names,
    register_debug_provider,
    unregister_debug_provider,
)
from dynamo_tpu.telemetry.attribution import (  # noqa: F401
    AttributionLedger,
    BlackBox,
    collect_attribution,
    register_attribution_provider,
    unregister_attribution_provider,
)
from dynamo_tpu.telemetry.hbm import HbmAccountant, tree_bytes  # noqa: F401
from dynamo_tpu.telemetry.hostplane import (  # noqa: F401
    HostCostLedger,
    LoopLagMonitor,
    collect_hostplane,
    note_stage,
    register_hostplane_provider,
    task_census,
    unregister_hostplane_provider,
)
from dynamo_tpu.telemetry.overlap import OverlapTracker  # noqa: F401
from dynamo_tpu.telemetry.recorder import FlightRecorder  # noqa: F401
from dynamo_tpu.telemetry.slo import SloConfig, SloTracker  # noqa: F401
from dynamo_tpu.telemetry.spans import (  # noqa: F401
    NULL_SPAN,
    JsonlSpanExporter,
    Span,
    Tracer,
    get_tracer,
    new_span_id,
    new_trace_id,
    propagation_context,
    reset_tracer,
)
