"""Continuous decode perf attribution: where every wall second went.

ROADMAP item 2's question — "the headline sits at ~0.37 of roofline;
where does the other 60% go?" — had to be answered offline, by reading
raw flight-recorder phase stamps or running ``bench.py --phases``. This
module makes the answer a *live time series*: an always-on per-step
ledger (``AttributionLedger``) decomposes the engine's decode timeline
into named loss buckets, rolls them into windowed gauges
(``dynamo_step_time_frac{component}``, ``dynamo_roofline_frac``,
``dynamo_tokens_lost_per_s{component}``), and a black-box recorder
(``BlackBox``) bundles full forensic state into one timestamped dump
dir when an anomaly trips — so a roofline regression is caught, named,
and preserved while it happens instead of reconstructed from a bench
round a week later.

## The decomposition

Each engine step record covers the engine-thread interval since the
previous record (the decode timeline is continuous under load;
``note_idle`` breaks it when the engine parks with no work, so waiting
for traffic is load, not loss). The interval partitions EXACTLY — the
buckets sum to the interval by construction — using the measured phase
stamps the flight recorder already carries plus the roofline byte model
(telemetry/roofline.py) as the device-compute split prior:

- **serial step** (``overlapped=False``): the harvest block IS the
  device executing (``sync_ms`` ≈ device compute + transfer), so the
  interval splits ``plan`` → ``dispatch`` → device compute (the sync
  span, split attention/MLP/LM-head/sampling by byte prior) →
  ``queue_wait`` (the emit/bookkeeping/drain residual). ``idle_gap``
  and ``sync`` read 0: in the serial loop the device-idle time *is*
  the exposed host time already named by plan/queue_wait.
- **overlapped step** (``overlapped=True``, the decode/window
  pipelines): the device is presumed busy except the measured
  ``idle_gap_ms`` (telemetry/overlap.py — a host-observable lower
  bound, exact in the serial loop). The idle gap is the loss; it is
  attributed ``plan`` → ``dispatch`` → ``queue_wait`` (residual host
  work: emit, drain, scheduler bookkeeping) against the measured host
  spans. ``sync`` is the residual harvest block (near zero when the
  pipeline is healthy), and everything else is device compute, split
  by the byte prior.

``roofline_frac`` is achieved tok/s over the byte-bound ceiling at the
live geometry — the same formula ``bench.py`` prints as
``vs_baseline`` (telemetry/roofline.py keeps them one implementation).
``tokens_lost_per_s{component}`` distributes the gap to the ceiling
over the loss buckets proportionally to their *excess* time (host
buckets count whole; device buckets count time beyond their byte-bound
ideal), so "the other 60%" is a first-class per-component series.

## Threading

``note_step``/``note_idle`` are engine-thread only (they mirror
``_record_step``); snapshots are read from the event loop and debug
endpoints, so the window mutates behind a lock. Everything is bounded:
the window is a ``deque(maxlen=...)`` (dynalint DL007) and gauge
refreshes run every ``GAUGE_EVERY`` steps.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Optional

from dynamo_tpu.telemetry.instruments import (
    BLACKBOX_DUMPS,
    ROOFLINE_FRAC,
    STEP_TIME_FRAC,
    TOKENS_LOST_PER_S,
)
from dynamo_tpu.telemetry.roofline import PHASES, RooflineModel

log = logging.getLogger("dynamo_tpu.telemetry.attribution")

# host-side loss buckets + the device-phase split; every step's
# fractions over BUCKETS sum to 1.0 by construction
HOST_BUCKETS = ("queue_wait", "plan", "dispatch", "sync", "idle_gap")
BUCKETS = HOST_BUCKETS + PHASES

# step kinds that are decode work (the roofline is a *decode* ceiling;
# prefill records stay in the timeline/fracs but not the ceiling math)
DECODE_KINDS = frozenset({"decode", "window_pure", "window_mixed", "spec"})

GAUGE_EVERY = 32  # steps between windowed-gauge refreshes


def _alloc(budget: float, *wants: float) -> list[float]:
    """Greedy sequential allocation: give each ``want`` up to what is
    left of ``budget``; the last element returned is the residual."""
    out = []
    rem = max(0.0, budget)
    for w in wants:
        take = min(max(0.0, w), rem)
        out.append(take)
        rem -= take
    out.append(rem)
    return out


class AttributionLedger:
    def __init__(
        self,
        roofline: Optional[RooflineModel] = None,
        window: int = 512,
        clock: Callable[[], float] = time.monotonic,
        anomaly_band: Optional[float] = None,
        anomaly_check_every: int = 64,
    ):
        self.roofline = roofline
        self._clock = clock
        self._lock = threading.Lock()
        self._window: deque = deque(maxlen=max(8, window))
        self._last_note: Optional[float] = None
        self.steps_noted = 0
        # anomaly band: current short-window roofline_frac below
        # band × trailing EMA trips a black-box capture. Defaults off
        # until enough checks have seeded the trailing estimate.
        if anomaly_band is None:
            try:
                anomaly_band = float(os.environ.get("DYN_ATTR_BAND", "0.5"))
            except ValueError:
                anomaly_band = 0.5
        self.anomaly_band = anomaly_band
        self._check_every = max(1, anomaly_check_every)
        self._since_check = 0
        self._trailing_frac: Optional[float] = None
        self._trailing_checks = 0
        self._since_gauges = 0
        # last rolled-up summary, refreshed with the gauges: the cheap
        # read for per-request paths (engine.stats() feeds admission
        # control on every HTTP request — it must not pay an O(window)
        # pass per call). Whole-dict swap: atomic under the GIL.
        self._last_summary: Optional[dict] = None

    def configure(self, roofline: RooflineModel) -> None:
        """Install the byte model once the engine knows its geometry
        (model config + quant + kv dtype resolve during init)."""
        self.roofline = roofline

    # -- engine-thread recording -------------------------------------------
    def note_idle(self) -> None:
        """The engine parked with NO work: break the timeline so the
        wait for the next request is load, not an attribution bucket."""
        self._last_note = None

    def note_step(
        self,
        kind: str,
        duration_s: float,
        *,
        batch: int = 0,
        tokens: int = 0,
        context_tokens: int = 0,
        plan_ms: float = 0.0,
        dispatch_ms: float = 0.0,
        sync_ms: float = 0.0,
        idle_gap_ms: float = 0.0,
        overlapped: bool = False,
    ) -> Optional[str]:
        """Account one engine step; returns an anomaly reason string
        when the roofline-band monitor trips (None otherwise)."""
        now = self._clock()
        interval = (
            now - self._last_note
            if self._last_note is not None
            else max(duration_s, 0.0)
        )
        self._last_note = now
        interval = max(interval, 1e-9)
        plan_s = max(0.0, plan_ms) / 1e3
        disp_s = max(0.0, dispatch_ms) / 1e3
        sync_s = max(0.0, sync_ms) / 1e3
        idle_s = max(0.0, idle_gap_ms) / 1e3

        b = dict.fromkeys(BUCKETS, 0.0)
        if overlapped:
            # device presumed busy except the measured idle gap; the
            # gap is the loss, attributed to the host spans that caused
            # it — plan first, dispatch next, the unexplained remainder
            # stays idle_gap (the host did *something* untimed: emit,
            # drain, scheduler bookkeeping)
            idle = min(idle_s, interval)
            b["plan"], b["dispatch"], b["idle_gap"] = _alloc(
                idle, plan_s, disp_s
            )
            b["sync"] = min(sync_s, interval - idle)
            device = max(0.0, interval - idle - b["sync"])
        else:
            # serial loop: plan and dispatch serialize ahead of the
            # harvest block, which is the device executing; the tail is
            # host emit/bookkeeping (queue_wait). idle_gap would double
            # count the plan/emit time and stays 0.
            plan_b, disp_b, rest = _alloc(interval, plan_s, disp_s)
            b["plan"], b["dispatch"] = plan_b, disp_b
            device = min(sync_s, rest)
            b["queue_wait"] = rest - device
        if self.roofline is not None and device > 0.0:
            frac = self.roofline.phase_fractions(
                max(batch, 1), max(context_tokens, 0)
            )
            for ph in PHASES:
                b[ph] = device * frac[ph]
        else:
            # no byte model (engine still initializing): park device
            # time under attention so the partition stays exact
            b["attention"] = device

        ideal_s = 0.0
        if (
            self.roofline is not None
            and kind in DECODE_KINDS
            and tokens > 0
            and batch > 0
        ):
            ideal_s = (
                tokens / batch
            ) * self.roofline.ideal_step_s(batch, context_tokens)
        rec = {
            "kind": kind,
            "interval_s": interval,
            "tokens": int(tokens),
            "batch": int(batch),
            "context_tokens": int(context_tokens),
            "ideal_s": ideal_s,
            "buckets": b,
        }
        with self._lock:
            self._window.append(rec)
            self.steps_noted += 1
        self._since_gauges += 1
        if self._since_gauges >= GAUGE_EVERY:
            self._since_gauges = 0
            self._refresh_gauges()
        return self._maybe_anomaly()

    # -- anomaly band -------------------------------------------------------
    def _maybe_anomaly(self) -> Optional[str]:
        self._since_check += 1
        if self._since_check < self._check_every:
            return None
        self._since_check = 0
        cur = self._short_roofline_frac()
        if cur is None:
            return None
        prev, self._trailing_checks = self._trailing_frac, self._trailing_checks + 1
        # EMA updates every check — including the anomalous one, so a
        # sustained regression becomes the new normal instead of
        # re-dumping forever (BlackBox rate-limits the burst anyway)
        self._trailing_frac = (
            cur if prev is None else 0.7 * prev + 0.3 * cur
        )
        if (
            prev is not None
            and self._trailing_checks > 3
            and prev > 1e-4
            and cur < self.anomaly_band * prev
        ):
            return (
                f"roofline_drop:frac={cur:.4f}<"
                f"{self.anomaly_band:.2f}x{prev:.4f}"
            )
        return None

    def _short_roofline_frac(self) -> Optional[float]:
        """Roofline frac over the most recent ``check_every`` decode
        records (the anomaly monitor's short window)."""
        with self._lock:
            recent = list(self._window)[-self._check_every:]
        ideal = sum(r["ideal_s"] for r in recent if r["kind"] in DECODE_KINDS)
        span = sum(
            r["interval_s"] for r in recent if r["kind"] in DECODE_KINDS
        )
        if span <= 0.0 or ideal <= 0.0:
            return None
        return ideal / span

    # -- windows / gauges / snapshots --------------------------------------
    def window_summary(self) -> dict:
        """Roll the window up: per-bucket time fractions, achieved and
        ceiling tok/s, roofline_frac, per-bucket tokens lost per second,
        and the top loss bucket."""
        with self._lock:
            recs = list(self._window)
        total = sum(r["interval_s"] for r in recs)
        out: dict = {
            "steps": len(recs),
            "span_s": round(total, 6),
            "frac": dict.fromkeys(BUCKETS, 0.0),
            "achieved_tok_s": 0.0,
            "decode_tok_s": 0.0,
            "roofline_tok_s": 0.0,
            "roofline_frac": None,
            "tokens_lost_per_s": dict.fromkeys(BUCKETS, 0.0),
            "top_loss_bucket": "",
        }
        if not recs or total <= 0.0:
            return out
        sums = dict.fromkeys(BUCKETS, 0.0)
        for r in recs:
            for k, v in r["buckets"].items():
                sums[k] += v
        out["frac"] = {k: round(v / total, 6) for k, v in sums.items()}
        tokens = sum(r["tokens"] for r in recs)
        out["achieved_tok_s"] = round(tokens / total, 3)
        dec = [r for r in recs if r["kind"] in DECODE_KINDS and r["ideal_s"] > 0]
        ideal = sum(r["ideal_s"] for r in dec)
        dec_tokens = sum(r["tokens"] for r in dec)
        dec_span = sum(r["interval_s"] for r in dec)
        if ideal > 0.0 and dec_tokens > 0 and dec_span > 0.0:
            out["roofline_tok_s"] = round(dec_tokens / ideal, 3)
            # DECODE-window ratio: decode tok/s over the decode
            # ceiling (= ideal/span). The roofline is a decode
            # ceiling, so prefill intervals must not dilute the frac —
            # a traffic-mix shift toward long prompts is not a decode
            # regression (and bench vs_baseline, measured over a
            # decode-dominated window, stays comparable).
            out["decode_tok_s"] = round(dec_tokens / dec_span, 3)
            out["roofline_frac"] = round(ideal / dec_span, 6)
            # loss attribution: host buckets lose their whole span,
            # device phases only their time beyond the byte-bound ideal
            loss_time = dict.fromkeys(BUCKETS, 0.0)
            for r in dec:
                pf = (
                    self.roofline.phase_fractions(
                        max(r["batch"], 1), r["context_tokens"]
                    )
                    if self.roofline is not None
                    else {}
                )
                for k, v in r["buckets"].items():
                    if k in PHASES:
                        loss_time[k] += max(
                            0.0, v - r["ideal_s"] * pf.get(k, 0.0)
                        )
                    else:
                        loss_time[k] += v
            lost_tok_s = max(
                0.0, out["roofline_tok_s"] - dec_tokens / max(dec_span, 1e-9)
            )
            lt = sum(loss_time.values())
            if lt > 0.0 and lost_tok_s > 0.0:
                out["tokens_lost_per_s"] = {
                    k: round(lost_tok_s * v / lt, 3)
                    for k, v in loss_time.items()
                }
                out["top_loss_bucket"] = max(
                    loss_time, key=loss_time.get
                )
        if not out["top_loss_bucket"]:
            # no ceiling yet: the biggest non-device bucket still names
            # where host time goes
            host = {k: out["frac"][k] for k in HOST_BUCKETS}
            if any(v > 0 for v in host.values()):
                out["top_loss_bucket"] = max(host, key=host.get)
        return out

    def summary_cached(self) -> dict:
        """The last gauge-refresh's window summary (recomputed every
        GAUGE_EVERY steps); computes once when nothing has rolled up
        yet. Per-request readers use this; snapshot endpoints roll a
        fresh window."""
        w = self._last_summary
        if w is None:
            w = self.window_summary()
            self._last_summary = w  # dynalint: handoff=idempotent cache fill — whole-dict swap is atomic under the GIL, any thread's computed summary is valid
        return w

    def _refresh_gauges(self) -> None:
        w = self.window_summary()
        self._last_summary = w
        for k in BUCKETS:
            STEP_TIME_FRAC.labels(k).set(w["frac"][k])
            TOKENS_LOST_PER_S.labels(k).set(w["tokens_lost_per_s"][k])
        if w["roofline_frac"] is not None:
            ROOFLINE_FRAC.set(w["roofline_frac"])

    def refresh_gauges(self) -> None:
        """Public refresh for snapshot paths (the engine's per-step
        refresh is sampled every GAUGE_EVERY steps)."""
        self._refresh_gauges()

    def snapshot(self, recent: int = 8) -> dict:
        """JSON-able state for /debug/attribution and /debug/state."""
        with self._lock:
            tail = list(self._window)[-max(0, recent):]
        return {
            "configured": self.roofline is not None,
            "steps_noted": self.steps_noted,
            "anomaly_band": self.anomaly_band,
            "trailing_roofline_frac": self._trailing_frac,
            "window": self.window_summary(),
            "recent": [
                {
                    "kind": r["kind"],
                    "interval_ms": round(r["interval_s"] * 1e3, 3),
                    "tokens": r["tokens"],
                    "batch": r["batch"],
                    "buckets_ms": {
                        k: round(v * 1e3, 3)
                        for k, v in r["buckets"].items()
                        if v > 0.0
                    },
                }
                for r in tail
            ],
        }


# ---------------------------------------------------------------------------
# Black-box capture: one timestamped dir with everything an incident needs
# ---------------------------------------------------------------------------
class BlackBox:
    """Anomaly-triggered forensic bundle. One ``trigger(reason)`` writes
    a ``dynamo_blackbox_<pid>_<seq>/`` dir containing:

    - ``meta.json`` — reason, timestamps, pid;
    - ``attribution.json`` — the ledger window + recent per-step rows;
    - ``flight.jsonl`` — the flight recorder's ring (snapshotted
      directly: the recorder's own rate limiter must not starve the
      black box, and vice versa);
    - ``state.json`` — the full ``/debug/state`` snapshot;
    - ``profile/`` — optional short ``jax.profiler`` capture
      (``DYN_BLACKBOX_PROFILE_MS``; 0 = off — it blocks the calling
      thread for the capture span, so it is opt-in).

    Rate-limited (``min_interval_s``, default ``DYN_BLACKBOX_INTERVAL_S``
    or 60 s) and disk-capped (``max_dumps`` dirs, oldest pruned) so a
    flapping anomaly produces exactly one bundle per window, not a
    disk-write loop. Dumps count in
    ``dynamo_blackbox_dumps_total{reason}``.

    Threading: ``trigger()`` runs on the ENGINE thread (it is called
    from ``_record_step``), so it only *snapshots* — in-memory dict
    builds over bounded structures — and hands serialization + disk
    I/O (+ the optional profiler capture) to a background writer
    thread. A slow or networked disk must not stall every in-flight
    request's next token exactly during the incident being captured.
    ``flush()`` joins the writer (tests, shutdown paths).
    """

    def __init__(
        self,
        recorder=None,
        ledger: Optional[AttributionLedger] = None,
        dump_dir: str = "",
        min_interval_s: Optional[float] = None,
        max_dumps: int = 8,
        clock: Callable[[], float] = time.monotonic,
        profile_ms: Optional[int] = None,
    ):
        self.recorder = recorder
        self.ledger = ledger
        self.dump_dir = (
            dump_dir
            or os.environ.get("DYN_BLACKBOX_DIR")
            or os.environ.get("DYN_FLIGHT_DIR")
            or tempfile.gettempdir()
        )
        if min_interval_s is None:
            try:
                min_interval_s = float(
                    os.environ.get("DYN_BLACKBOX_INTERVAL_S", "60")
                )
            except ValueError:
                min_interval_s = 60.0
        self.min_interval_s = min_interval_s
        if profile_ms is None:
            try:
                profile_ms = int(
                    os.environ.get("DYN_BLACKBOX_PROFILE_MS", "0")
                )
            except ValueError:
                profile_ms = 0
        self.profile_ms = max(0, profile_ms)
        self._clock = clock
        self._lock = threading.Lock()
        self._last: float = -float("inf")
        self._seq = 0
        self._dirs: deque = deque(maxlen=max(1, max_dumps))
        self._writer: Optional[threading.Thread] = None
        self.dumps_written = 0
        self.last_dump_dir: Optional[str] = None
        self.triggers_suppressed = 0

    def trigger(self, reason: str) -> Optional[str]:
        """Snapshot one bundle and enqueue its write (or None when
        rate-limited). Returns the bundle dir the writer is filling."""
        now = self._clock()
        with self._lock:
            if now - self._last < self.min_interval_s:
                self.triggers_suppressed += 1
                return None
            self._last = now
            self._seq += 1
            seq = self._seq
        d = os.path.join(
            self.dump_dir, f"dynamo_blackbox_{os.getpid()}_{seq:03d}"
        )
        # SNAPSHOT on the calling (engine) thread: bounded in-memory
        # dict builds only — the ring is <= capacity records, the
        # ledger window <= 512 rows
        files: dict[str, object] = {
            "meta.json": {
                "blackbox_dump": True,
                "reason": reason,
                "ts": time.time(),
                "pid": os.getpid(),
            },
        }
        if self.ledger is not None:
            files["attribution.json"] = self.ledger.snapshot(recent=64)
        if self.recorder is not None:
            files["flight.jsonl"] = [
                {
                    "flight_recorder_dump": True,
                    "reason": f"blackbox:{reason}",
                    "ts": time.time(),
                    "pid": os.getpid(),
                },
                *self.recorder.snapshot(self.recorder.capacity),
            ]
        try:
            # full introspection snapshot — imported lazily to keep the
            # module dependency-light for unit tests
            from dynamo_tpu.telemetry.debug import collect_debug_state

            files["state.json"] = collect_debug_state()
        except Exception:
            log.exception("black-box state snapshot failed")
        writer = threading.Thread(
            target=self._write_bundle, args=(d, files, reason, now),
            name="blackbox-writer", daemon=True,
        )
        with self._lock:
            self._writer = writer
        writer.start()
        return d

    def flush(self, timeout: float = 10.0) -> None:
        """Join the in-flight bundle write (tests/shutdown)."""
        with self._lock:
            writer = self._writer
        if writer is not None:
            writer.join(timeout)

    def _write_bundle(
        self, d: str, files: dict, reason: str, armed_at: float
    ) -> None:
        """Serialize + write one snapshotted bundle — background thread
        (plus the optional blocking profiler capture)."""
        try:
            os.makedirs(d, exist_ok=True)
            for name, payload in files.items():
                with open(os.path.join(d, name), "w") as f:
                    if name.endswith(".jsonl"):
                        for rec in payload:  # type: ignore[union-attr]
                            f.write(json.dumps(rec) + "\n")
                    else:
                        json.dump(payload, f, default=str)
            if self.profile_ms > 0:
                self._capture_profile(os.path.join(d, "profile"))
        except OSError:
            log.exception("black-box dump to %s failed", d)
            with self._lock:
                if self._last == armed_at:
                    # nothing persisted: the next trigger should retry
                    self._last = -float("inf")
            return
        evict: Optional[str] = None
        with self._lock:
            self.dumps_written += 1
            self.last_dump_dir = d
            if len(self._dirs) == self._dirs.maxlen:
                evict = self._dirs[0]
            self._dirs.append(d)
        if evict is not None:
            _rmtree_quiet(evict)
        BLACKBOX_DUMPS.labels(reason.split(":", 1)[0]).inc()
        log.warning("black-box bundle written to %s (%s)", d, reason)

    def _capture_profile(self, out_dir: str) -> None:
        """Blocking jax.profiler capture — opt-in and short; a failure
        degrades to a bundle without the profile."""
        try:
            import jax

            os.makedirs(out_dir, exist_ok=True)
            jax.profiler.start_trace(out_dir)
            try:
                time.sleep(self.profile_ms / 1e3)
            finally:
                jax.profiler.stop_trace()
        except Exception:
            log.exception("black-box profiler capture failed")

    def stats(self) -> dict:
        with self._lock:
            return {
                "dumps": self.dumps_written,
                "last_dump_dir": self.last_dump_dir,
                "suppressed": self.triggers_suppressed,
                "min_interval_s": self.min_interval_s,
                "dump_dir": self.dump_dir,
                "profile_ms": self.profile_ms,
            }


def _rmtree_quiet(path: str) -> None:
    import shutil

    try:
        shutil.rmtree(path)
    except OSError:
        pass  # already gone / external cleanup: cap still holds


# ---------------------------------------------------------------------------
# /debug/attribution provider registry — the SAME machinery as
# /debug/state (telemetry/debug.py ProviderRegistry), second instance
# ---------------------------------------------------------------------------
from dynamo_tpu.telemetry.debug import ProviderRegistry  # noqa: E402

_ATTR_PROVIDERS = ProviderRegistry("attribution")


def register_attribution_provider(name: str, fn: Callable[[], dict]) -> None:
    _ATTR_PROVIDERS.register(name, fn)


def unregister_attribution_provider(
    name: str, fn: Optional[Callable[[], dict]] = None
) -> None:
    _ATTR_PROVIDERS.unregister(name, fn)


def collect_attribution() -> dict:
    """One JSON-able snapshot for ``/debug/attribution`` — a provider
    that raises degrades to an error stanza (introspection must keep
    working exactly when things are broken)."""
    return _ATTR_PROVIDERS.collect()
