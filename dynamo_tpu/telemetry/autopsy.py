"""End-to-end request autopsy: tail-sampled per-request timelines.

The serving stack's telemetry is rich but siloed — spans land in an
opt-in ``DYN_TRACE_FILE``, the flight recorder and attribution ledger
are step-centric, the hostplane ledger keeps stage EMAs, and
migration/guided/kv-fabric outcomes each live in their own counters.
This module is the join layer: every request accumulates ONE compact
in-memory record keyed by the ``X-Request-Id``/``Context.id`` that
already rides the wire ctx frame, assembled from four sources:

- **frontend stages** — the ``HostCostLedger`` row handed over at
  ``finish()`` (preprocess/admission/dispatch/prime/ttfb, chunk counts);
- **router decisions** — worker chosen, overlap/fleet-block score,
  failover/resume re-dials (:func:`note_router`, stamped by both
  routers' dial closures);
- **engine segments** — queue-wait, prefill, decode, TTFT, spec accept
  totals, preemptions, guided flag, published by the engine at finish
  (:func:`publish_segment`). A worker process has no active record, so
  its segments park in a bounded pending table; the endpoint server
  pops them (:func:`take_pending`) and ships them to the caller on a
  ``{t:"seg"}`` wire frame, where :func:`merge_pending` folds them into
  the frontend's record — a migrated request's autopsy therefore shows
  BOTH workers' segments and the splice point;
- **fleet events** — migration splice (both worker ids), kv-fabric
  prefetch hit/miss, fault firings, deadline/shed outcomes
  (:func:`note_event`).

Retention is tail-based (the scrape-safe shape): a bounded table holds
every in-flight request; at finish a record is kept as an **exemplar**
only if it was flagged (SLO miss, migrated/aborted, faulted, shed,
rejected, error) or its total/TTFB sits at or above the rolling
window's p99 — everything else is dropped. Per-request cost is O(1)
amortized: bounded lists, p99 thresholds recomputed every
``GAUGE_EVERY`` finishes, no per-chunk work.

Surfaces: ``/debug/requests`` (exemplar index) + ``/debug/request/{rid}``
on the HTTP frontend and the metrics service via the fourth
:class:`ProviderRegistry` instance, and ``dynamo-tpu autopsy <rid>``
(ASCII waterfall with a wall-clock coverage check).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from dynamo_tpu.telemetry.instruments import (
    AUTOPSY_EXEMPLARS,
    AUTOPSY_REQUESTS,
    AUTOPSY_SEGMENTS,
)

# hard bounds on everything a request can accumulate (dynalint DL007
# discipline): a pathological stream must not grow its record unboundedly
MAX_EVENTS = 48
MAX_ROUTER = 16
MAX_SEGMENTS = 8

# recompute the p99 retention thresholds every N finishes (the same
# amortization discipline as the hostplane/attribution ledgers)
GAUGE_EVERY = 32

# below this many finished requests in the rolling window the p99 is
# noise — retain everything while the tail estimate warms up (the
# exemplar ring is bounded, so warm-up retention cannot leak)
MIN_WINDOW = 32

# flags that force exemplar retention regardless of latency
_RETAIN_FLAGS = frozenset(
    {"slo_miss", "migrated", "aborted", "faulted", "shed", "rejected",
     "error", "deadline"}
)


def _percentile(sorted_vals: list[float], p: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1, int(round(p * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


class _RequestRecord:
    """Mutable in-flight autopsy record (internal to the collector)."""

    __slots__ = (
        "rid", "endpoint", "t_start", "t_start_wall", "trace_id",
        "flags", "events", "router", "segments",
    )

    def __init__(self, rid: str, endpoint: str, t: float, wall: float):
        self.rid = rid
        self.endpoint = endpoint
        self.t_start = t
        self.t_start_wall = wall
        self.trace_id: Optional[str] = None
        self.flags: set[str] = set()
        self.events: list[dict] = []
        self.router: list[dict] = []
        self.segments: list[dict] = []


class AutopsyCollector:
    """Per-request timeline assembly + tail-based exemplar retention.

    Thread-safety matches the other ledgers: stamped from the event
    loop AND the engine thread, read from arbitrary threads (debug
    endpoints) — one lock, all accesses take it. Every table is
    bounded: the active map (FIFO-evicted past ``max_active``), the
    pending cross-process table, the exemplar ring, and the rolling
    latency window.
    """

    def __init__(
        self,
        max_active: int = 8192,
        max_exemplars: int = 256,
        window: int = 512,
        max_pending: int = 1024,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
    ):
        self._clock = clock
        self._wall = wall
        self._lock = threading.Lock()
        self._active: dict[str, _RequestRecord] = {}
        self._active_order: deque = deque()
        self._max_active = max_active
        # worker-side segments/events for rids with no active record
        # here (they belong to a frontend in another process); popped by
        # the endpoint server and shipped over the wire
        self._pending: dict[str, dict] = {}
        self._pending_order: deque = deque()
        self._max_pending = max_pending
        self._exemplars: deque = deque(maxlen=max(1, max_exemplars))
        # rolling (total_ms, ttfb_ms) window feeding the p99 thresholds
        self._window: deque = deque(maxlen=max(MIN_WINDOW, window))
        self._finished = 0
        self._retained = 0
        self._dropped = 0
        self._p99_total_ms = 0.0
        self._p99_ttfb_ms = 0.0

    # -- request lifecycle (frontend process) -----------------------------
    def begin(self, rid: str, endpoint: str) -> None:
        now, wall = self._clock(), self._wall()
        with self._lock:
            if rid in self._active:
                return
            while len(self._active) >= self._max_active and self._active_order:
                self._active.pop(self._active_order.popleft(), None)
            self._active[rid] = _RequestRecord(rid, endpoint, now, wall)
            self._active_order.append(rid)

    def set_trace(self, rid: str, trace_id: Optional[str]) -> None:
        if not trace_id:
            return
        with self._lock:
            rec = self._active.get(rid)
            if rec is not None:
                rec.trace_id = trace_id

    def note_event(
        self, rid: str, kind: str, flag: Optional[str] = None, **fields
    ) -> None:
        """Append one timeline event. Active record → straight in;
        unknown rid (worker process) → the pending table, to ride the
        wire with this worker's segments."""
        now = self._clock()
        with self._lock:
            rec = self._active.get(rid)
            if rec is not None:
                if len(rec.events) < MAX_EVENTS:
                    ev = {"t_ms": round((now - rec.t_start) * 1e3, 3),
                          "kind": kind}
                    ev.update(fields)
                    rec.events.append(ev)
                if flag:
                    rec.flags.add(flag)
                return
            pend = self._pending_locked(rid)
            if pend is not None and len(pend["events"]) < MAX_EVENTS:
                ev = {"kind": kind}
                ev.update(fields)
                if flag:
                    ev["flag"] = flag
                pend["events"].append(ev)

    def note_router(
        self,
        rid: str,
        worker_id: int,
        overlap_blocks: int = 0,
        total_blocks: int = 0,
        fleet_blocks: int = 0,
        resume: bool = False,
        mode: str = "kv",
    ) -> None:
        """One routing decision (dial). Repeat calls record failover /
        resume re-dials in order."""
        now = self._clock()
        with self._lock:
            rec = self._active.get(rid)
            if rec is None or len(rec.router) >= MAX_ROUTER:
                return
            rec.router.append({
                "t_ms": round((now - rec.t_start) * 1e3, 3),
                "worker": f"{worker_id:x}",
                "mode": mode,
                "overlap_blocks": overlap_blocks,
                "total_blocks": total_blocks,
                "fleet_blocks": fleet_blocks,
                "resume": resume,
            })

    # -- segments (engine / disagg side; any process) ---------------------
    def publish_segment(self, rid: str, segment: dict) -> None:
        """Attach one execution segment (engine finish, remote-prefill
        wait, synthesized dead-worker stub) to the request's record —
        directly when the record lives here, via the pending table when
        the frontend is another process."""
        with self._lock:
            rec = self._active.get(rid)
            if rec is not None:
                if len(rec.segments) < MAX_SEGMENTS:
                    rec.segments.append(dict(segment))
                    AUTOPSY_SEGMENTS.labels(
                        str(segment.get("source", "engine"))
                    ).inc()
                return
            pend = self._pending_locked(rid)
            if pend is not None and len(pend["segments"]) < MAX_SEGMENTS:
                pend["segments"].append(dict(segment))
                AUTOPSY_SEGMENTS.labels(
                    str(segment.get("source", "engine"))
                ).inc()

    def _pending_locked(self, rid: str) -> Optional[dict]:
        pend = self._pending.get(rid)
        if pend is None:
            while (
                len(self._pending) >= self._max_pending
                and self._pending_order
            ):
                self._pending.pop(self._pending_order.popleft(), None)
            pend = {"segments": [], "events": []}
            self._pending[rid] = pend
            self._pending_order.append(rid)
        return pend

    def take_pending(self, rid: str) -> Optional[dict]:
        """Pop the worker-side payload for ``rid`` (segments + events)
        so the endpoint server can ship it to the caller; None when
        this process accumulated nothing for the rid."""
        with self._lock:
            pend = self._pending.pop(rid, None)
            if pend is not None:
                try:
                    self._pending_order.remove(rid)
                except ValueError:
                    pass
            return pend

    def merge_pending(self, rid: str, payload: Optional[dict]) -> None:
        """Fold a worker's shipped payload (a ``take_pending`` dict off
        the wire) into the local record for ``rid`` — or park it in the
        local pending table when the record lives yet another hop up
        (disagg decode worker relaying to the frontend)."""
        if not isinstance(payload, dict):
            return
        for seg in payload.get("segments") or []:
            if isinstance(seg, dict):
                self.publish_segment(rid, seg)
        for ev in payload.get("events") or []:
            if isinstance(ev, dict):
                ev = dict(ev)
                kind = str(ev.pop("kind", "event"))
                flag = ev.pop("flag", None)
                ev.pop("t_ms", None)  # worker-relative; meaningless here
                self.note_event(rid, kind, flag=flag, **ev)

    # -- finish + retention ------------------------------------------------
    def finish(
        self, rid: str, status: str = "200", host: Optional[dict] = None
    ) -> Optional[dict]:
        """Close the record: merge any local pending payload, derive
        flags from segments/status, decide retention, and (for
        exemplars) move the assembled record into the ring. Idempotent
        — the first call wins. Returns the assembled record when it was
        retained."""
        pend = self.take_pending(rid)
        now = self._clock()
        with self._lock:
            rec = self._active.pop(rid, None)
            if rec is None:
                return None
            try:
                self._active_order.remove(rid)
            except ValueError:
                pass
            total_ms = round((now - rec.t_start) * 1e3, 3)
        if pend is not None:
            # merge outside the pop so bounded-append logic is shared;
            # the record is gone from _active, so fold manually below
            for seg in pend.get("segments") or []:
                if len(rec.segments) < MAX_SEGMENTS and isinstance(seg, dict):
                    rec.segments.append(dict(seg))
            for ev in pend.get("events") or []:
                if len(rec.events) < MAX_EVENTS and isinstance(ev, dict):
                    ev = dict(ev)
                    flag = ev.pop("flag", None)
                    if flag:
                        rec.flags.add(str(flag))
                    rec.events.append(ev)
        ttfb_ms = None
        if host:
            ttfb_ms = host.get("ttfb_ms")
        # flags derived from the assembled segments + terminal status
        for seg in rec.segments:
            if seg.get("slo_miss"):
                rec.flags.add("slo_miss")
            fr = str(seg.get("finish_reason") or "")
            if fr == "timeout":
                rec.flags.add("deadline")
            elif fr == "error":
                rec.flags.add("error")
        if status not in ("200", "499"):
            rec.flags.add("error")
        with self._lock:
            self._finished += 1
            if self._finished % GAUGE_EVERY == 0:
                totals = sorted(t for t, _ in self._window)
                ttfbs = sorted(
                    t for _, t in self._window if t is not None
                )
                self._p99_total_ms = _percentile(totals, 0.99)
                self._p99_ttfb_ms = _percentile(ttfbs, 0.99)
            slow = (
                len(self._window) < MIN_WINDOW
                or total_ms >= self._p99_total_ms
                or (
                    ttfb_ms is not None
                    and self._p99_ttfb_ms > 0
                    and ttfb_ms >= self._p99_ttfb_ms
                )
            )
            self._window.append((total_ms, ttfb_ms))
            retain = bool(rec.flags & _RETAIN_FLAGS) or slow
            if not retain:
                self._dropped += 1
        if not retain:
            AUTOPSY_REQUESTS.labels("dropped").inc()
            return None
        row = {
            "rid": rec.rid,
            "endpoint": rec.endpoint,
            "status": status,
            "ts": rec.t_start_wall,
            "total_ms": total_ms,
            "ttfb_ms": ttfb_ms,
            "flags": sorted(rec.flags),
            "retained": (
                "flag" if rec.flags & _RETAIN_FLAGS else "tail_p99"
            ),
            "host": host,
            "router": rec.router,
            "events": rec.events,
            "segments": rec.segments,
            "trace_id": rec.trace_id,
            "finished": True,
        }
        with self._lock:
            self._retained += 1
            self._exemplars.append(row)
            n = len(self._exemplars)
        AUTOPSY_REQUESTS.labels("retained").inc()
        AUTOPSY_EXEMPLARS.set(float(n))
        return row

    # -- introspection -----------------------------------------------------
    def get(self, rid: str) -> Optional[dict]:
        """The request's record: in-flight (partial, ``finished:
        False``) or a retained exemplar. None = never seen or dropped
        at finish."""
        now = self._clock()
        with self._lock:
            rec = self._active.get(rid)
            if rec is not None:
                return {
                    "rid": rec.rid,
                    "endpoint": rec.endpoint,
                    "status": None,
                    "ts": rec.t_start_wall,
                    "total_ms": round((now - rec.t_start) * 1e3, 3),
                    "ttfb_ms": None,
                    "flags": sorted(rec.flags),
                    "host": None,
                    "router": list(rec.router),
                    "events": list(rec.events),
                    "segments": list(rec.segments),
                    "trace_id": rec.trace_id,
                    "finished": False,
                }
            for row in reversed(self._exemplars):
                if row["rid"] == rid:
                    return dict(row)
        return None

    def index(self) -> list[dict]:
        """The exemplar index (newest first): one summary line per
        retained record — what ``/debug/requests`` serves and the
        ``top`` SLOW column counts."""
        with self._lock:
            rows = list(self._exemplars)
        return [
            {
                "rid": r["rid"],
                "endpoint": r["endpoint"],
                "status": r["status"],
                "total_ms": r["total_ms"],
                "ttfb_ms": r["ttfb_ms"],
                "flags": r["flags"],
                "segments": len(r["segments"]),
                "ts": r["ts"],
            }
            for r in reversed(rows)
        ]

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "requests_total": self._finished,
                "retained_total": self._retained,
                "dropped_total": self._dropped,
                "active": len(self._active),
                "pending": len(self._pending),
                "p99_total_ms": round(self._p99_total_ms, 3),
                "p99_ttfb_ms": round(self._p99_ttfb_ms, 3),
            }
        out["exemplars"] = self.index()
        return out


# ---------------------------------------------------------------------------
# process-global collector + module-level note_* functions (downstream
# layers — routers, engine, migration, faults, fabric — only know the
# request id, exactly like hostplane.note_stage)
# ---------------------------------------------------------------------------
COLLECTOR = AutopsyCollector()


def begin_request(rid: Optional[str], endpoint: str) -> None:
    if rid:
        COLLECTOR.begin(rid, endpoint)


def set_trace(rid: Optional[str], trace_id: Optional[str]) -> None:
    if rid:
        COLLECTOR.set_trace(rid, trace_id)


def note_event(
    rid: Optional[str], kind: str, flag: Optional[str] = None, **fields
) -> None:
    if rid:
        COLLECTOR.note_event(rid, kind, flag=flag, **fields)


def note_router(rid: Optional[str], worker_id: int, **fields) -> None:
    if rid:
        COLLECTOR.note_router(rid, worker_id, **fields)


def publish_segment(rid: Optional[str], segment: dict) -> None:
    if rid:
        COLLECTOR.publish_segment(rid, segment)


def take_pending(rid: Optional[str]) -> Optional[dict]:
    return COLLECTOR.take_pending(rid) if rid else None


def merge_pending(rid: Optional[str], payload: Optional[dict]) -> None:
    if rid:
        COLLECTOR.merge_pending(rid, payload)


def finish_request(
    rid: Optional[str], status: str = "200", host: Optional[dict] = None
) -> Optional[dict]:
    if rid:
        return COLLECTOR.finish(rid, status, host=host)
    return None


def get_record(rid: Optional[str]) -> Optional[dict]:
    return COLLECTOR.get(rid) if rid else None


def exemplar_index() -> list[dict]:
    return COLLECTOR.index()


# ---------------------------------------------------------------------------
# onboard context: the KVBM onboard hook is (hashes, blocks) -> int with
# no request identity, so the scheduler parks the admitting sequence's
# rid in a thread-local around the call and the fleet fabric's prefetch
# reads it back — same engine thread, synchronous call chain
# ---------------------------------------------------------------------------
_TLS = threading.local()


def set_onboard_rid(rid: Optional[str]) -> None:
    _TLS.rid = rid


def current_onboard_rid() -> Optional[str]:
    return getattr(_TLS, "rid", None)


# ---------------------------------------------------------------------------
# /debug/requests provider registry — the SAME machinery as
# /debug/state, /debug/attribution, and /debug/hostplane: fourth instance
# ---------------------------------------------------------------------------
from dynamo_tpu.telemetry.debug import ProviderRegistry  # noqa: E402

_AUTOPSY_PROVIDERS = ProviderRegistry("autopsy")
_AUTOPSY_PROVIDERS.register("collector", COLLECTOR.snapshot)


def register_autopsy_provider(name: str, fn: Callable[[], dict]) -> None:
    _AUTOPSY_PROVIDERS.register(name, fn)


def unregister_autopsy_provider(
    name: str, fn: Optional[Callable[[], dict]] = None
) -> None:
    _AUTOPSY_PROVIDERS.unregister(name, fn)


def collect_autopsy() -> dict:
    """One JSON-able snapshot for ``/debug/requests`` — a provider that
    raises degrades to an error stanza (introspection must keep working
    exactly when things are broken)."""
    return _AUTOPSY_PROVIDERS.collect()


def waterfall(record: dict) -> dict:
    """Derive the waterfall rows + wall-clock coverage check from an
    assembled record: sequential host stages, the streaming span, and
    the unattributed gap must together explain the end-to-end latency
    (the CLI renders this; tests assert the coverage bound).

    Shared here (not in the CLI) so the coverage math has one
    implementation for the renderer and the acceptance tests."""
    total_ms = float(record.get("total_ms") or 0.0)
    host = record.get("host") or {}
    stages_ms: dict[str, Any] = dict(host.get("stages_ms") or {})
    ttfb_ms = record.get("ttfb_ms")
    rows: list[dict] = []
    t = 0.0
    for name in ("preprocess", "admission", "dispatch", "prime",
                 "tool_parser"):
        dur = stages_ms.pop(name, None)
        if dur is None:
            continue
        rows.append({"name": name, "start_ms": round(t, 3),
                     "dur_ms": float(dur)})
        t += float(dur)
    for name, dur in stages_ms.items():  # any future stage names
        rows.append({"name": name, "start_ms": round(t, 3),
                     "dur_ms": float(dur)})
        t += float(dur)
    staged = t
    if ttfb_ms is not None and total_ms > 0:
        gap = max(0.0, float(ttfb_ms) - staged)
        if gap > 0:
            rows.append({"name": "(host gap)", "start_ms": round(staged, 3),
                         "dur_ms": round(gap, 3)})
        stream = max(0.0, total_ms - float(ttfb_ms))
        rows.append({"name": "stream", "start_ms": float(ttfb_ms),
                     "dur_ms": round(stream, 3)})
        explained = staged + gap + stream
    else:
        gap = max(0.0, total_ms - staged)
        if gap > 0:
            rows.append({"name": "(unattributed)",
                         "start_ms": round(staged, 3),
                         "dur_ms": round(gap, 3)})
        explained = staged + gap
    coverage = explained / total_ms if total_ms > 0 else 1.0
    return {
        "rows": rows,
        "total_ms": total_ms,
        "explained_ms": round(explained, 3),
        "coverage": round(coverage, 4),
        # the acceptance bound: stages + gaps explain the end-to-end
        # wall time to within 10%
        "covered": abs(explained - total_ms) <= 0.10 * max(total_ms, 1e-9),
    }
