"""Live-introspection plumbing behind the ``/debug/*`` endpoints.

A process-global registry of *debug-state providers*: any subsystem
that can describe "what am I doing right now" registers a zero-arg
callable returning a JSON-able dict (the engine registers its
scheduler/KV-pool/flight-recorder snapshot; a metrics service registers
its aggregator view). ``collect_debug_state()`` assembles one snapshot
— a provider that raises contributes an ``{"error": ...}`` stanza
instead of breaking the endpoint (introspection must keep working
exactly when things are broken).

``capture_profile()`` backs ``/debug/profile?ms=N``: an on-demand
``jax.profiler`` capture written where TensorBoard/Perfetto can load it
(the profiler emits ``plugins/profile/*/trace.json.gz`` under the
output dir — load it at https://ui.perfetto.dev). One capture at a
time per process; concurrent requests get a busy error.
"""

from __future__ import annotations

import asyncio
import logging
import os
import tempfile
import threading
import time
from typing import Callable, Optional

log = logging.getLogger("dynamo_tpu.telemetry.debug")

class ProviderRegistry:
    """Named zero-arg snapshot providers behind one lock — the shape
    both ``/debug/state`` and ``/debug/attribution`` share (one
    implementation so fixes to the identity-checked unregister or the
    error-stanza collect can't drift between them).

    Cross-thread contract (dynalint DL103 vocabulary, docs/
    static_analysis.md): written from the event loop (engines
    registering at launch) AND read/written from arbitrary threads
    (debug endpoints, shutdown paths) — the lock is the declared
    handoff; every access takes it.
    """

    def __init__(self, what: str):
        self._what = what
        self._providers: dict[str, Callable[[], dict]] = {}
        self._lock = threading.Lock()

    def register(self, name: str, fn: Callable[[], dict]) -> None:
        """Register (or replace) a named snapshot provider."""
        with self._lock:
            self._providers[name] = fn

    def unregister(
        self, name: str, fn: Optional[Callable[[], dict]] = None
    ) -> None:
        """Remove a provider; with ``fn`` given, only if it is still
        the registered one (an engine shutting down must not yank a
        newer engine's registration)."""
        with self._lock:
            # == (not `is`): bound methods are fresh objects per
            # attribute access but compare equal for the same
            # instance+function
            if fn is None or self._providers.get(name) == fn:
                self._providers.pop(name, None)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._providers)

    def collect(self) -> dict:
        """One JSON-able snapshot across every registered provider."""
        with self._lock:
            providers = dict(self._providers)
        out: dict = {"ts": time.time(), "pid": os.getpid()}
        for name, fn in sorted(providers.items()):
            try:
                out[name] = fn()
            except Exception as exc:
                # the snapshot reads live structures without stopping
                # the world — a torn read must degrade to an error
                # stanza, not a 500 on the one endpoint you need
                # during an incident
                log.exception("%s provider %r failed", self._what, name)
                out[name] = {"error": f"{type(exc).__name__}: {exc}"}
        return out


_DEBUG_PROVIDERS = ProviderRegistry("debug")

# one jax.profiler capture at a time (the profiler itself is global)
_profile_lock = threading.Lock()
_profile_seq = 0

MAX_PROFILE_MS = 30_000


def register_debug_provider(name: str, fn: Callable[[], dict]) -> None:
    _DEBUG_PROVIDERS.register(name, fn)


def unregister_debug_provider(
    name: str, fn: Optional[Callable[[], dict]] = None
) -> None:
    _DEBUG_PROVIDERS.unregister(name, fn)


def debug_provider_names() -> list[str]:
    return _DEBUG_PROVIDERS.names()


def collect_debug_state() -> dict:
    return _DEBUG_PROVIDERS.collect()


async def capture_profile(ms: int, out_dir: str = "") -> dict:
    """Run ``jax.profiler`` for ``ms`` milliseconds; returns
    ``{"trace_dir", "duration_ms"}`` (raises RuntimeError when a capture
    is already running or the profiler is unavailable)."""
    global _profile_seq
    ms = max(1, min(int(ms), MAX_PROFILE_MS))
    if not _profile_lock.acquire(blocking=False):
        raise RuntimeError("a profile capture is already running")
    try:
        import jax

        _profile_seq += 1
        d = out_dir or os.path.join(
            os.environ.get("DYN_PROFILE_DIR") or tempfile.gettempdir(),
            f"dynamo_profile_{os.getpid()}_{_profile_seq:03d}",
        )
        os.makedirs(d, exist_ok=True)
        jax.profiler.start_trace(d)
        try:
            await asyncio.sleep(ms / 1000.0)
        finally:
            jax.profiler.stop_trace()
        log.info("profiler capture (%d ms) -> %s", ms, d)
        return {"trace_dir": d, "duration_ms": ms}
    finally:
        _profile_lock.release()
