"""Span-log post-processing: JSONL → Chrome-trace/Perfetto JSON.

``dynamo-tpu trace export`` turns one or more ``DYN_TRACE_FILE`` span
logs (one per process in a disaggregated fleet) into a Chrome Trace
Event Format file that chrome://tracing and https://ui.perfetto.dev
render as a flame graph — a single slow request reads as nested bars:
http.request → router.dispatch → worker.generate → prefill_queue.wait /
engine.decode → kv_transfer.put.

Mapping: each trace_id becomes a "process" row (pid), each span a
complete event ("ph": "X") with microsecond timestamps; the originating
service (span attr ``service``) becomes the thread name so frontend /
decode / prefill lanes separate visually. Wall-clock start times keep
cross-process spans ordered on one machine.
"""

from __future__ import annotations

import json
from typing import Iterable, Optional, TextIO


def load_spans(paths: Iterable[str]) -> list[dict]:
    """Read spans from JSONL files; malformed lines are skipped (a
    SIGKILL'd process may leave a torn final line)."""
    spans: list[dict] = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(obj, dict) and obj.get("name"):
                    spans.append(obj)
    return spans


def build_span_tree(spans: list[dict]) -> dict[str, dict]:
    """Group spans by trace: {trace_id: {"spans": [...], "roots": [...],
    "children": {span_id: [child, ...]}}}. Roots are spans whose
    parent_id is absent or refers to a span not in the log (e.g. a
    sampled-out upstream)."""
    traces: dict[str, dict] = {}
    for s in spans:
        t = traces.setdefault(
            s.get("trace_id", ""), {"spans": [], "roots": [], "children": {}}
        )
        t["spans"].append(s)
    for t in traces.values():
        ids = {s["span_id"] for s in t["spans"] if s.get("span_id")}
        for s in t["spans"]:
            parent = s.get("parent_id")
            if parent and parent in ids:
                t["children"].setdefault(parent, []).append(s)
            else:
                t["roots"].append(s)
    return traces


def to_chrome_trace(spans: list[dict]) -> dict:
    """Chrome Trace Event Format (JSON object flavor)."""
    events: list[dict] = []
    # stable pid per trace, tid per service lane
    pids: dict[str, int] = {}
    tids: dict[tuple, int] = {}
    for s in sorted(spans, key=lambda x: x.get("start", 0.0)):
        trace_id = s.get("trace_id", "?")
        pid = pids.setdefault(trace_id, len(pids) + 1)
        attrs = s.get("attrs") or {}
        service = str(attrs.get("service", ""))
        tid = tids.setdefault((trace_id, service), len(tids) + 1)
        start_us = float(s.get("start", 0.0)) * 1e6
        dur_us = max(0.0, float(s.get("duration_s") or 0.0)) * 1e6
        args = dict(attrs)
        args["span_id"] = s.get("span_id", "")
        if s.get("parent_id"):
            args["parent_id"] = s["parent_id"]
        events.append(
            {
                "name": s["name"],
                "ph": "X",
                "ts": round(start_us, 1),
                "dur": round(dur_us, 1),
                "pid": pid,
                "tid": tid,
                "cat": service or "span",
                "args": args,
            }
        )
    # metadata rows: trace ids as process names, services as thread names
    for trace_id, pid in pids.items():
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": f"trace {trace_id[:12]}"},
            }
        )
    for (trace_id, service), tid in tids.items():
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pids[trace_id],
                "tid": tid,
                "args": {"name": service or "spans"},
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def trace_ids_for_request(paths: Iterable[str], rid: str) -> list[str]:
    """The trace ids whose spans carry ``request_id == rid`` (the HTTP
    frontend stamps it on the request's root span) — how ``trace export
    --rid`` and an autopsy record cross-link to the span tree. Usually
    one id; more means the rid was reused across requests."""
    ids = {
        str(s.get("trace_id"))
        for s in load_spans(paths)
        if s.get("trace_id")
        and str((s.get("attrs") or {}).get("request_id", "")) == rid
    }
    return sorted(ids)


def export_chrome_trace(
    in_paths: Iterable[str],
    out: TextIO,
    trace_id: Optional[str] = None,
) -> int:
    """Write the Chrome-trace JSON for the given span logs; returns the
    number of spans exported. ``trace_id`` filters to one request (prefix
    match, so the first 8-12 chars from a log line are enough)."""
    spans = load_spans(in_paths)
    if trace_id:
        spans = [
            s for s in spans
            if str(s.get("trace_id", "")).startswith(trace_id)
        ]
    json.dump(to_chrome_trace(spans), out, indent=1)
    out.write("\n")
    return len(spans)
