"""HBM memory accounting: what device memory is actually holding.

The engine's KV sizing (``_auto_num_blocks``) reasons about free HBM
once, at startup; this module keeps the answer LIVE — weight bytes, KV
pool bytes, current/peak device usage — as gauges and as a
``/debug/state`` snapshot, so "is the cache sized right" and "what ate
the headroom" are scrape-able questions instead of archaeology.

Sources, in preference order:

- ``device.memory_stats()`` (TPU runtimes report ``bytes_in_use`` /
  ``bytes_limit`` / ``peak_bytes_in_use``);
- a portable fallback that sums the tracked buffers (params + KV pool)
  when the backend reports nothing (CPU test backends, tunneled chips)
  — the gauges then carry the *accounted* footprint with
  ``source="accounted"`` so dashboards can tell the difference.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Optional

from dynamo_tpu.telemetry.instruments import (
    HBM_BYTES_IN_USE,
    HBM_BYTES_LIMIT,
    HBM_KV_POOL_BYTES,
    HBM_PEAK_BYTES,
    HBM_WEIGHT_BYTES,
)

log = logging.getLogger("dynamo_tpu.telemetry.hbm")


def tree_bytes(tree: Any) -> int:
    """Total nbytes across a pytree of arrays (int8 KV caches are
    (values, scales) tuples — tree_leaves flattens those too)."""
    try:
        import jax

        return int(sum(
            getattr(x, "nbytes", 0) for x in jax.tree_util.tree_leaves(tree)
        ))
    except Exception:
        return 0


class HbmAccountant:
    """Per-engine memory bookkeeping feeding the ``dynamo_hbm_*`` gauges.

    ``set_static()`` records the long-lived allocations (weights, KV
    pool) once after engine init; ``refresh()`` re-reads live device
    stats (cheap — one runtime call) and returns the snapshot dict the
    debug endpoint embeds.
    """

    def __init__(self, device: Optional[Any] = None):
        self._device = device
        self._lock = threading.Lock()
        self.weight_bytes = 0
        self.kv_pool_bytes = 0
        self._peak_accounted = 0

    def set_device(self, device: Optional[Any]) -> None:
        """Bind the device whose memory_stats() refresh() reads (the
        engine learns its devices after the accountant is built)."""
        self._device = device

    def set_static(self, weight_bytes: int, kv_pool_bytes: int) -> None:
        with self._lock:
            self.weight_bytes = int(weight_bytes)
            self.kv_pool_bytes = int(kv_pool_bytes)
        HBM_WEIGHT_BYTES.set(self.weight_bytes)
        HBM_KV_POOL_BYTES.set(self.kv_pool_bytes)

    def refresh(self) -> dict:
        """Update the live gauges and return the snapshot dict."""
        with self._lock:
            weight, kv = self.weight_bytes, self.kv_pool_bytes
        stats: dict = {}
        if self._device is not None:
            try:
                stats = dict(self._device.memory_stats() or {})
            except Exception:
                stats = {}
        if stats.get("bytes_in_use") is not None:
            in_use = int(stats["bytes_in_use"])
            limit = int(stats.get("bytes_limit") or 0)
            peak = int(stats.get("peak_bytes_in_use") or in_use)
            source = "device"
        else:
            # portable fallback: the accounted footprint (weights + KV
            # pool); step transients are invisible here, so peak tracks
            # the accounted max only
            in_use = weight + kv
            limit = 0
            with self._lock:
                self._peak_accounted = max(self._peak_accounted, in_use)
                peak = self._peak_accounted
            source = "accounted"
        HBM_BYTES_IN_USE.set(in_use)
        HBM_BYTES_LIMIT.set(limit)
        HBM_PEAK_BYTES.set(peak)
        return {
            "source": source,
            "weight_bytes": weight,
            "kv_pool_bytes": kv,
            "bytes_in_use": in_use,
            "bytes_limit": limit,
            "peak_bytes_in_use": peak,
            "headroom_bytes": max(0, limit - in_use) if limit else None,
        }
