"""Host data-plane observability: event-loop lag + per-stream cost.

The engine side has the attribution ledger (telemetry/attribution.py)
answering "where do the device's tokens go"; this module is its twin
for the *frontend host plane* — the single-process asyncio loop that
parses requests, sheds load, primes first chunks, and serializes SSE
deltas, and that will saturate long before the chips do (ROADMAP
item 4). Nothing here should be invisible before PR 18 shards it.

Three pieces, all surfaced at ``/debug/hostplane`` (HTTP frontend and
metrics service) via the same :class:`ProviderRegistry` machinery as
``/debug/state``:

- :class:`LoopLagMonitor` — a self-timing heartbeat task per event
  loop: sleeps a fixed interval and measures how late the loop woke it
  (p50/p99/max over a bounded window). A wake later than the stall
  threshold trips the flight-recorder/black-box path with reason
  ``loop_stall`` (exactly one bundle per holdoff window, the same
  rate-limit discipline as the engine's anomaly capture). Also keeps
  an asyncio task census (active tasks by name family) and arms
  ``loop.slow_callback_duration`` so debug-mode slow-callback logs
  name the offending handler.
- :class:`HostCostLedger` — per-request stamps for every host stage
  (preprocess, admission, router dispatch, first-chunk priming,
  per-chunk SSE serialize+write as an EMA, tool-parser time,
  write-backpressure drain waits), rolled into ``dynamo_http_*``
  histograms/gauges. ``dynamo_http_time_to_first_token_seconds``
  (frontend TTFB) minus the ``prime`` stamp (the engine-side wait for
  the first chunk) is the frontend's added latency — the
  TTFB-vs-engine-TTFT split that tells host stall from chip stall.
- the ``/debug/hostplane`` provider registry
  (``register_hostplane_provider`` / ``collect_hostplane``).

``bench.py --fanout`` drives a synthetic engine through the real
HttpService and reads this module's surface to report the frontend's
requests/sec and stream fan-out ceilings (docs/observability.md "Host
data plane").
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import re
import threading
import time
from collections import deque
from typing import Callable, Optional

from dynamo_tpu.utils.clock import SYSTEM, Clock

from dynamo_tpu.telemetry.instruments import (
    HTTP_DRAIN_WAIT,
    HTTP_FIRST_CHUNK_WAIT,
    HTTP_HOST_STAGE,
    HTTP_LOOP_LAG,
    HTTP_LOOP_LAG_MAX,
    HTTP_LOOP_LAG_P99,
    HTTP_LOOP_STALLS,
    HTTP_OPEN_STREAMS,
    HTTP_SSE_WRITE_EMA,
)

log = logging.getLogger("dynamo_tpu.telemetry.hostplane")

# ledger stage names (the bounded label set of dynamo_http_host_stage_seconds)
STAGES = ("preprocess", "admission", "dispatch", "prime", "tool_parser")

# refresh the derived gauges every N heartbeats / finished requests —
# same amortization discipline as the attribution ledger's GAUGE_EVERY
GAUGE_EVERY = 32


def _percentile(sorted_vals: list[float], p: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1, int(round(p * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


_TASK_FAMILY_RE = re.compile(r"[-_]?\d+$")


def task_census(loop: Optional[asyncio.AbstractEventLoop] = None,
                max_families: int = 32) -> dict[str, int]:
    """Active asyncio tasks grouped by name family (``Task-17`` →
    ``Task``, ``metrics-hit-pump`` stays itself): the "what is this
    loop running" answer without a debugger. Bounded to the
    ``max_families`` largest families so a task-name bug cannot bloat
    the snapshot."""
    try:
        tasks = asyncio.all_tasks(loop)
    except RuntimeError:
        return {}
    fams: dict[str, int] = {}
    for t in tasks:
        name = _TASK_FAMILY_RE.sub("", t.get_name() or "") or "unnamed"
        fams[name] = fams.get(name, 0) + 1
    if len(fams) > max_families:
        top = sorted(fams.items(), key=lambda kv: (-kv[1], kv[0]))
        rest = sum(n for _, n in top[max_families:])
        fams = dict(top[:max_families])
        fams["_other"] = rest
    return fams


class LoopLagMonitor:
    """Self-timing heartbeat: measures how late the event loop runs a
    task that asked to wake every ``interval_s``.

    Lag is THE summary statistic for a cooperative loop — every await
    in every handler waits at least this long beyond its nominal wake
    time, so lag p99 bounds the scheduling tax on all concurrent
    streams. A single wake later than ``stall_s`` means some callback
    held the loop synchronously for that span; the watchdog dumps the
    flight-recorder ring and triggers a black-box bundle with reason
    ``loop_stall`` (once per ``holdoff_s`` — the same flap-proofing as
    the engine's anomaly capture).

    ``note_lag`` is the pure core (injectable-clock unit tests call it
    directly); ``start()`` spawns the heartbeat task on the running
    loop and arms ``loop.slow_callback_duration`` so asyncio's
    debug-mode slow-callback log names the offending handler.
    """

    def __init__(
        self,
        interval_s: float = 0.1,
        window: int = 1024,
        stall_s: float = 0.05,
        holdoff_s: float = 60.0,
        recorder=None,
        blackbox=None,
        clock: Optional[Clock] = None,
        slow_callback_s: float = 0.1,
    ):
        self.interval_s = interval_s
        self.stall_s = stall_s
        self.holdoff_s = holdoff_s
        self.recorder = recorder
        self.blackbox = blackbox
        self.slow_callback_s = slow_callback_s
        # injectable Clock (utils/clock.py): the heartbeat loop and the
        # stall holdoff both run on it, so tests (and simulated runs)
        # drive the monitor on virtual time
        self.clock: Clock = clock or SYSTEM
        self._lock = threading.Lock()
        # bounded lag window (dynalint DL007 discipline)
        self._window: deque = deque(maxlen=max(2, window))
        self._task: Optional[asyncio.Task] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._beats = 0
        self._stalls = 0
        self._last_stall: float = -float("inf")
        self._last_lag_s = 0.0
        self._summary: dict = {"p50_ms": 0.0, "p99_ms": 0.0, "max_ms": 0.0}

    # -- pure core (unit-testable with an injected clock) ------------------
    def note_lag(self, lag_s: float) -> Optional[str]:
        """Record one heartbeat's measured lag; returns the black-box
        bundle dir when the stall watchdog fired (None otherwise)."""
        lag_s = max(0.0, lag_s)
        fired: Optional[str] = None
        with self._lock:
            self._beats += 1
            self._window.append(lag_s)
            self._last_lag_s = lag_s
            beats = self._beats
        HTTP_LOOP_LAG.observe(lag_s)
        if lag_s >= self.stall_s:
            fired = self._stall(lag_s)
        if beats % GAUGE_EVERY == 0:
            self._refresh_gauges()
        return fired

    def _stall(self, lag_s: float) -> Optional[str]:
        now = self.clock.monotonic()
        with self._lock:
            self._stalls += 1
            if now - self._last_stall < self.holdoff_s:
                return None  # one bundle per window, not one per beat
            self._last_stall = now
        HTTP_LOOP_STALLS.inc()
        log.warning(
            "event-loop stall: heartbeat woke %.1f ms late "
            "(threshold %.1f ms)", lag_s * 1e3, self.stall_s * 1e3,
        )
        if self.recorder is not None:
            self.recorder.record(
                "loop_stall", lag_s, lag_ms=round(lag_s * 1e3, 3),
                stall_threshold_ms=round(self.stall_s * 1e3, 3),
            )
            self.recorder.dump(reason="loop_stall")
        if self.blackbox is not None:
            return self.blackbox.trigger("loop_stall")
        return None

    def _refresh_gauges(self) -> None:
        with self._lock:
            vals = sorted(self._window)
        p50 = _percentile(vals, 0.50)
        p99 = _percentile(vals, 0.99)
        mx = vals[-1] if vals else 0.0
        HTTP_LOOP_LAG_P99.set(p99)
        HTTP_LOOP_LAG_MAX.set(mx)
        with self._lock:
            self._summary = {
                "p50_ms": round(p50 * 1e3, 3),
                "p99_ms": round(p99 * 1e3, 3),
                "max_ms": round(mx * 1e3, 3),
            }

    # -- heartbeat lifecycle ----------------------------------------------
    async def _heartbeat(self) -> None:
        while True:
            before = self.clock.monotonic()
            await self.clock.sleep(self.interval_s)
            # the sleep returned late by exactly the loop's scheduling
            # lag: every other coroutine on this loop waited at least
            # as long past ITS wake time
            self.note_lag(
                self.clock.monotonic() - before - self.interval_s
            )

    def start(self) -> None:
        """Spawn the heartbeat on the running loop (idempotent)."""
        if self._task is not None and not self._task.done():
            return
        from dynamo_tpu.utils.tasks import spawn

        self._loop = asyncio.get_running_loop()
        # debug-mode slow-callback log threshold: harmless when debug
        # is off, names the offending handler when it is on
        self._loop.slow_callback_duration = self.slow_callback_s
        self._task = spawn(self._heartbeat(), name="hostplane-heartbeat")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None

    def reset_window(self) -> None:
        """Drop the lag window (beats/stalls keep counting): the
        fan-out bench calls this between rungs so each rung's p99 is
        its own, not the ladder's history."""
        with self._lock:
            self._window.clear()
            self._summary = {"p50_ms": 0.0, "p99_ms": 0.0, "max_ms": 0.0}

    def snapshot(self) -> dict:
        self._refresh_gauges()
        with self._lock:
            out = {
                "interval_ms": round(self.interval_s * 1e3, 3),
                "stall_threshold_ms": round(self.stall_s * 1e3, 3),
                "beats": self._beats,
                "stalls": self._stalls,
                "last_lag_ms": round(self._last_lag_s * 1e3, 3),
                "lag": dict(self._summary),
                "running": self._task is not None and not self._task.done(),
                "slow_callback_ms": round(self.slow_callback_s * 1e3, 1),
            }
        out["tasks"] = task_census(self._loop)
        if self.blackbox is not None:
            out["blackbox"] = self.blackbox.stats()
        if self.recorder is not None:
            out["flight_recorder"] = self.recorder.stats()
        return out


class _RequestCost:
    """Mutable per-request stamp record (internal to the ledger)."""

    __slots__ = (
        "rid", "endpoint", "stream", "t_start", "stages", "chunks",
        "bytes", "write_ema_s", "drain_waits", "drain_wait_s", "ttfb_s",
    )

    def __init__(self, rid: str, endpoint: str, stream: bool, t: float):
        self.rid = rid
        self.endpoint = endpoint
        self.stream = stream
        self.t_start = t
        self.stages: dict[str, float] = {}
        self.chunks = 0
        self.bytes = 0
        self.write_ema_s = 0.0
        self.drain_waits = 0
        self.drain_wait_s = 0.0
        self.ttfb_s: Optional[float] = None


class HostCostLedger:
    """Per-request host-cost stamps → bounded window + instruments.

    One record per in-flight request, stamped by the HTTP handler
    (parse/validate, admission, dispatch, first-chunk priming, SSE
    chunk serialize+write, drain waits) and by downstream stages that
    only know the request id (the preprocessor's tool parser, the
    router's instance pick) via :func:`note_stage`. ``finish()`` rolls
    the record into the histograms and the rolling window the
    ``/debug/hostplane`` snapshot reads.

    Thread-safety matches the attribution ledger: stamped from the
    event loop, read from arbitrary threads (debug endpoints) — one
    lock, all accesses take it. Both the active table and the finished
    window are bounded (DL007).
    """

    def __init__(
        self,
        window: int = 512,
        max_active: int = 8192,
        ema_alpha: float = 0.2,
        drain_threshold_s: float = 0.001,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._clock = clock
        self._lock = threading.Lock()
        self._active: dict[str, _RequestCost] = {}
        self._active_order: deque = deque()
        self._max_active = max_active
        self._ema_alpha = ema_alpha
        self._drain_threshold_s = drain_threshold_s
        self._window: deque = deque(maxlen=max(1, window))
        self._finished = 0
        self._streams_open = 0
        self._streams_total = 0
        self._chunks_total = 0
        self._write_ema_s = 0.0
        self._summary_cache: dict = {}

    # -- request lifecycle -------------------------------------------------
    def begin(self, rid: str, endpoint: str, stream: bool = False) -> None:
        now = self._clock()
        with self._lock:
            if rid in self._active:
                return
            # bound the active table: a handler path that never reaches
            # finish() (crash before the finally) must not leak records
            while len(self._active) >= self._max_active and self._active_order:
                self._active.pop(self._active_order.popleft(), None)
            self._active[rid] = _RequestCost(rid, endpoint, stream, now)
            self._active_order.append(rid)
            self._summary_cache = {}

    def stage(self, rid: str, name: str, seconds: float) -> None:
        """Accumulate ``seconds`` into the request's ``name`` stamp
        (repeat calls add — tool-parser time arrives per delta)."""
        with self._lock:
            rec = self._active.get(rid)
            if rec is None:
                return
            rec.stages[name] = rec.stages.get(name, 0.0) + seconds
        if name in STAGES:
            HTTP_HOST_STAGE.labels(name).observe(seconds)
        if name == "prime":
            HTTP_FIRST_CHUNK_WAIT.observe(seconds)

    def mark_stream(self, rid: str) -> None:
        """The request committed to an SSE response (stream opened)."""
        with self._lock:
            rec = self._active.get(rid)
            if rec is not None and not rec.stream:
                rec.stream = True
            self._streams_open += 1
            self._streams_total += 1
            open_now = self._streams_open
            self._summary_cache = {}
        HTTP_OPEN_STREAMS.set(float(open_now))

    def chunk(self, rid: str, serialize_s: float, write_s: float,
              nbytes: int = 0) -> None:
        """One SSE chunk's serialize + write cost. The EMA (not a
        per-chunk series) is the scrape-safe shape: thousands of
        streams × hundreds of chunks must not mint samples."""
        total = serialize_s + write_s
        with self._lock:
            rec = self._active.get(rid)
            if rec is not None:
                rec.chunks += 1
                rec.bytes += nbytes
                rec.write_ema_s = (
                    total if rec.chunks == 1
                    else rec.write_ema_s
                    + self._ema_alpha * (total - rec.write_ema_s)
                )
                if rec.ttfb_s is None:
                    rec.ttfb_s = self._clock() - rec.t_start
                if write_s >= self._drain_threshold_s:
                    # the write awaited transport drain: backpressure
                    rec.drain_waits += 1
                    rec.drain_wait_s += write_s
            self._chunks_total += 1
            self._write_ema_s = (
                total if self._chunks_total == 1
                else self._write_ema_s
                + self._ema_alpha * (total - self._write_ema_s)
            )
            ema = self._write_ema_s
            n = self._chunks_total
        if n % GAUGE_EVERY == 0:
            HTTP_SSE_WRITE_EMA.set(ema)

    def finish(self, rid: str, status: str = "200") -> Optional[dict]:
        """Close the request's ledger entry; returns the finished row
        (None on a repeat call) so the autopsy plane can adopt the
        frontend stages without re-deriving them."""
        with self._lock:
            rec = self._active.pop(rid, None)
            if rec is None:
                return None
            try:
                self._active_order.remove(rid)
            except ValueError:
                pass
            now = self._clock()
            was_stream = rec.stream
            if was_stream:
                self._streams_open = max(0, self._streams_open - 1)
            open_now = self._streams_open
            self._finished += 1
            row = {
                "rid": rec.rid,
                "endpoint": rec.endpoint,
                "stream": was_stream,
                "status": status,
                "total_ms": round((now - rec.t_start) * 1e3, 3),
                "stages_ms": {
                    k: round(v * 1e3, 3) for k, v in rec.stages.items()
                },
                "chunks": rec.chunks,
                "bytes": rec.bytes,
                "write_ema_us": round(rec.write_ema_s * 1e6, 1),
                "drain_waits": rec.drain_waits,
                "drain_wait_ms": round(rec.drain_wait_s * 1e3, 3),
                "ttfb_ms": (
                    round(rec.ttfb_s * 1e3, 3)
                    if rec.ttfb_s is not None else None
                ),
            }
            # host-side overhead of the first byte: TTFB minus the wait
            # for the engine's first chunk — the frontend's own share
            prime = rec.stages.get("prime")
            if rec.ttfb_s is not None and prime is not None:
                row["host_ttfb_ms"] = round(
                    max(0.0, rec.ttfb_s - prime) * 1e3, 3
                )
            self._window.append(row)
            # every lifecycle edge invalidates (summary() recomputes
            # lazily on the next scrape): /debug/hostplane and the
            # `top` STRM/RPS columns must never read counts staler
            # than the requests they describe
            self._summary_cache = {}
        if was_stream:
            HTTP_OPEN_STREAMS.set(float(open_now))
            HTTP_DRAIN_WAIT.observe(rec.drain_wait_s)
        return row

    # -- introspection -----------------------------------------------------
    def summary(self) -> dict:
        """Rolling-window means (cheap; cached between refreshes)."""
        with self._lock:
            if self._summary_cache:
                return dict(self._summary_cache)
            rows = list(self._window)
            out = {
                "requests_total": self._finished,
                "streams_total": self._streams_total,
                "streams_open": self._streams_open,
                "active": len(self._active),
                "chunks_total": self._chunks_total,
                "sse_write_ema_us": round(self._write_ema_s * 1e6, 1),
            }
        if rows:
            out["window"] = {
                "requests": len(rows),
                "total_ms_mean": round(
                    sum(r["total_ms"] for r in rows) / len(rows), 3
                ),
                "stage_ms_mean": {
                    s: round(
                        sum(r["stages_ms"].get(s, 0.0) for r in rows)
                        / len(rows), 3,
                    )
                    for s in STAGES
                    if any(s in r["stages_ms"] for r in rows)
                },
                "drain_wait_ms_mean": round(
                    sum(r["drain_wait_ms"] for r in rows) / len(rows), 3
                ),
            }
            ttfbs = [r["ttfb_ms"] for r in rows if r.get("ttfb_ms") is not None]
            primes = [
                r["stages_ms"]["prime"] for r in rows
                if "prime" in r["stages_ms"]
            ]
            if ttfbs:
                out["window"]["ttfb_ms_mean"] = round(
                    sum(ttfbs) / len(ttfbs), 3
                )
            if primes:
                # the split operators read: TTFB − engine first-chunk
                # wait = the host plane's own contribution
                out["window"]["engine_first_chunk_ms_mean"] = round(
                    sum(primes) / len(primes), 3
                )
        with self._lock:
            self._summary_cache = dict(out)
        return out

    def snapshot(self, recent: int = 8) -> dict:
        out = self.summary()
        with self._lock:
            out["recent"] = list(self._window)[-max(0, recent):]
        return out


# ---------------------------------------------------------------------------
# process-global ledger + note_stage (downstream stages — the
# preprocessor's tool parser, the router's dispatch pick — only know
# the request id, so they stamp through the module singleton exactly
# like instruments are process-global)
# ---------------------------------------------------------------------------
LEDGER = HostCostLedger()


def note_stage(rid: Optional[str], stage: str, seconds: float) -> None:
    """Stamp ``seconds`` of host work onto the live request ``rid``
    (no-op when the id has no active ledger record — engines run
    outside a frontend too)."""
    if rid:
        LEDGER.stage(rid, stage, seconds)


# ---------------------------------------------------------------------------
# /debug/hostplane provider registry — the SAME machinery as
# /debug/state and /debug/attribution, third instance
# ---------------------------------------------------------------------------
from dynamo_tpu.telemetry.debug import ProviderRegistry  # noqa: E402

_HOSTPLANE_PROVIDERS = ProviderRegistry("hostplane")


def register_hostplane_provider(name: str, fn: Callable[[], dict]) -> None:
    _HOSTPLANE_PROVIDERS.register(name, fn)


def unregister_hostplane_provider(
    name: str, fn: Optional[Callable[[], dict]] = None
) -> None:
    _HOSTPLANE_PROVIDERS.unregister(name, fn)


def collect_hostplane() -> dict:
    """One JSON-able snapshot for ``/debug/hostplane`` — a provider
    that raises degrades to an error stanza (introspection must keep
    working exactly when things are broken)."""
    return _HOSTPLANE_PROVIDERS.collect()
