"""The serving stack's metric catalog — every instrument in one place.

One module so the surface is auditable (docs/observability.md mirrors
this file) and so the cardinality gate (tests/test_metric_cardinality.py)
can walk the whole registry by importing one module. Layers import their
instruments from here; nothing else registers process-global metrics.

Naming: ``dynamo_<layer>_<what>_<unit>`` with Prometheus suffix
conventions (``_total`` counters, ``_seconds`` histograms). The http
family keeps the seed's prometheus_client names so dashboards survive
the migration.
"""

from __future__ import annotations

from dynamo_tpu.telemetry.metrics import REGISTRY

# -- HTTP frontend (names unchanged from the seed's prometheus_client) ------
HTTP_REQUESTS = REGISTRY.counter(
    "dynamo_http_requests_total",
    "Total HTTP LLM requests",
    labels=("model", "endpoint", "status"),
)
HTTP_INFLIGHT = REGISTRY.gauge(
    "dynamo_http_inflight_requests",
    "In-flight HTTP LLM requests",
    labels=("model",),
)
HTTP_DURATION = REGISTRY.histogram(
    "dynamo_http_request_duration_seconds",
    "HTTP LLM request duration",
    labels=("model", "endpoint"),
)
HTTP_TTFT = REGISTRY.histogram(
    "dynamo_http_time_to_first_token_seconds",
    "Time to first streamed token",
    labels=("model",),
)

# -- host data plane (telemetry/hostplane.py; docs/observability.md
# "Host data plane") — the frontend's event-loop lag monitor and the
# per-stream host-cost ledger. Lag buckets are scheduling-tax shaped
# (sub-ms healthy loop up to the multi-second stall a watchdog dump
# should already have explained).
_LAG_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, float("inf"),
)
HTTP_LOOP_LAG = REGISTRY.histogram(
    "dynamo_http_loop_lag_seconds",
    "Event-loop scheduling lag measured by the hostplane heartbeat "
    "(how late the loop ran a task that asked to wake on a fixed "
    "interval — every concurrent stream waits at least this long)",
    buckets=_LAG_BUCKETS,
)
HTTP_LOOP_LAG_P99 = REGISTRY.gauge(
    "dynamo_http_loop_lag_p99_seconds",
    "p99 event-loop lag over the heartbeat's rolling window",
)
HTTP_LOOP_LAG_MAX = REGISTRY.gauge(
    "dynamo_http_loop_lag_max_seconds",
    "Max event-loop lag over the heartbeat's rolling window",
)
HTTP_LOOP_STALLS = REGISTRY.counter(
    "dynamo_http_loop_stalls_total",
    "Heartbeat wakes later than the stall threshold — some callback "
    "held the loop synchronously; each (rate-limited) stall also dumps "
    "the flight recorder and a black-box bundle with reason loop_stall",
)
HTTP_OPEN_STREAMS = REGISTRY.gauge(
    "dynamo_http_open_streams",
    "SSE streams currently open on this frontend",
)
HTTP_HOST_STAGE = REGISTRY.histogram(
    "dynamo_http_host_stage_seconds",
    "Per-request host-plane stage cost stamped by the cost ledger "
    "(preprocess = parse/validate/tokenize, admission, dispatch = "
    "router/engine handoff, prime = wait for the engine's first "
    "chunk, tool_parser = streaming tool-call delta parsing)",
    labels=("stage",),  # preprocess | admission | dispatch | prime | tool_parser
    buckets=_LAG_BUCKETS,
)
HTTP_FIRST_CHUNK_WAIT = REGISTRY.histogram(
    "dynamo_http_first_chunk_wait_seconds",
    "Frontend's wait for the engine's FIRST chunk (first-chunk "
    "priming): the engine-side share of TTFB — compare with "
    "dynamo_http_time_to_first_token_seconds to split host stall "
    "from chip stall",
    buckets=(
        0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
        1.0, 2.5, 5.0, 15.0, 60.0, float("inf"),
    ),
)
HTTP_SSE_WRITE_EMA = REGISTRY.gauge(
    "dynamo_http_sse_write_ema_seconds",
    "EMA of per-chunk SSE serialize+write cost across all streams "
    "(an EMA, not a per-chunk series: thousands of streams x hundreds "
    "of chunks must not mint histogram samples)",
)
HTTP_DRAIN_WAIT = REGISTRY.histogram(
    "dynamo_http_drain_wait_seconds",
    "Per-stream total time resp.write() spent awaiting transport "
    "drain (write backpressure: slow clients eating loop time)",
    buckets=(
        0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
        1.0, 2.5, 5.0, 15.0, 60.0, float("inf"),
    ),
)

# -- engine (scheduler + step loop; the instruments ISSUE 2 calls out) ------
_STEP_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 15.0, 60.0, float("inf"),
)
ENGINE_STEP_SECONDS = REGISTRY.histogram(
    "dynamo_engine_step_seconds",
    "Engine device-step wall time by step kind",
    labels=("kind",),  # prefill | decode | mixed | window | spec
    buckets=_STEP_BUCKETS,
)
ENGINE_BATCH_OCCUPANCY = REGISTRY.gauge(
    "dynamo_engine_batch_occupancy",
    "Running sequences / max_batch_size (sampled each step)",
)
ENGINE_QUEUE_DEPTH = REGISTRY.gauge(
    "dynamo_engine_queue_depth",
    "Requests waiting or prefilling (not yet decoding)",
)
ENGINE_QUEUE_WAIT = REGISTRY.histogram(
    "dynamo_engine_queue_wait_seconds",
    "Submit-to-admission wait (time in the scheduler's waiting queue)",
    buckets=_STEP_BUCKETS,
)
ENGINE_PREEMPTIONS = REGISTRY.counter(
    "dynamo_engine_preemptions_total",
    "Recompute preemptions (healthy serving sits at ~0)",
)
ENGINE_COMPILE_EVENTS = REGISTRY.counter(
    "dynamo_engine_compile_events_total",
    "Step-shape compilations by phase (prewarm vs mid-serve lazy)",
    labels=("phase",),  # prewarm | serve
)
ENGINE_PREWARM_SECONDS = REGISTRY.gauge(
    "dynamo_engine_prewarm_seconds",
    "Wall time of the startup AOT prewarm pass",
)
COMPILE_FENCE_EVENTS = REGISTRY.counter(
    "dynamo_compile_fence_events_total",
    "Serve-phase XLA compile events escalated by the compile fence "
    "(nonzero only under DYN_COMPILE_FENCE; each one is an unprewarmed "
    "jit signature compiling mid-serve)",
)
TRANSFER_FENCE_EVENTS = REGISTRY.counter(
    "dynamo_transfer_fence_events_total",
    "Serve-phase implicit host<->device transfers escalated by the "
    "transfer fence (nonzero only under DYN_TRANSFER_FENCE; each one "
    "is a device sync or upload outside the dispatch/harvest contract)",
)
ENGINE_REQUESTS_FINISHED = REGISTRY.counter(
    "dynamo_engine_requests_finished_total",
    "Sequences finished by reason",
    labels=("reason",),  # stop | length | cancelled | error | ...
)
ENGINE_TOKENS_GENERATED = REGISTRY.counter(
    "dynamo_engine_tokens_generated_total",
    "Decoded tokens emitted to request streams",
)

# -- speculative decoding (engine spec step; dynamo_tpu/spec) ---------------
SPEC_PROPOSED_TOKENS = REGISTRY.counter(
    "dynamo_spec_proposed_tokens_total",
    "Draft tokens proposed to the speculative verify step",
    labels=("drafter",),  # ngram | bigram
)
SPEC_ACCEPTED_TOKENS = REGISTRY.counter(
    "dynamo_spec_accepted_tokens_total",
    "Draft tokens accepted by rejection sampling",
    labels=("drafter",),
)
SPEC_ACCEPT_RATE = REGISTRY.gauge(
    "dynamo_spec_accept_rate",
    "Accepted/proposed draft tokens of the last speculative step",
)
SPEC_STEP_SECONDS = REGISTRY.histogram(
    "dynamo_spec_step_seconds",
    "Speculative step latency by phase (host drafting vs device verify; "
    "the overlapped pipeline adds predraft = optimistic drafting hidden "
    "under device time)",
    labels=("phase",),  # draft | verify | predraft
    buckets=_STEP_BUCKETS,
)
SPEC_DRAFT_HIDDEN_FRAC = REGISTRY.gauge(
    "dynamo_spec_draft_hidden_frac",
    "Fraction of host draft wall time the overlapped spec pipeline hid "
    "under device execution (hidden predraft / (hidden + exposed); "
    "exposed = first-step drafts + harvest-time repairs)",
)

# -- guided decoding (dynamo_tpu/guided; docs/guided_decoding.md) -----------
GUIDED_COMPILE_SECONDS = REGISTRY.histogram(
    "dynamo_guided_compile_seconds",
    "Schema/regex -> token-automaton compile time (one compile per "
    "(spec, tokenizer) pair; repeats hit the process-wide LRU)",
    labels=("kind",),  # json_schema | regex | json_object
    buckets=(0.001, 0.005, 0.025, 0.1, 0.5, 2.5, 10.0, float("inf")),
)
GUIDED_CACHE_EVENTS = REGISTRY.counter(
    "dynamo_guided_cache_events_total",
    "Guided-automaton compile-cache lookups by result",
    labels=("result",),  # hit | miss
)
GUIDED_REQUESTS = REGISTRY.counter(
    "dynamo_guided_requests_total",
    "Requests admitted with a guided-decoding constraint",
    labels=("kind",),  # json_schema | regex | json_object
)
TOOL_CALL_STREAMS = REGISTRY.counter(
    "dynamo_tool_call_streams_total",
    "Responses emitted as OpenAI tool_calls deltas",
    labels=("mode",),  # forced | auto
)

# -- KV block manager / transfer plane --------------------------------------
KV_TRANSFER_BYTES = REGISTRY.counter(
    "dynamo_kv_transfer_bytes_total",
    "KV block bytes moved over the disagg transfer plane",
    labels=("direction",),  # send | recv
)
KV_TRANSFER_SECONDS = REGISTRY.histogram(
    "dynamo_kv_transfer_seconds",
    "Wall time of one KV transfer put (connect to ack)",
    labels=("direction",),
    buckets=_STEP_BUCKETS,
)
KV_TRANSFER_BLOCKS = REGISTRY.counter(
    "dynamo_kv_transfer_blocks_total",
    "KV blocks moved over the disagg transfer plane",
    labels=("direction",),
)
KVBM_OFFLOADED_BLOCKS = REGISTRY.counter(
    "dynamo_kvbm_offloaded_blocks_total",
    "Blocks demoted from device HBM into the host tier",
)
KVBM_ONBOARDED_BLOCKS = REGISTRY.counter(
    "dynamo_kvbm_onboarded_blocks_total",
    "Blocks promoted from offload tiers back into device HBM",
)
KVBM_REMOTE_TIMEOUTS = REGISTRY.counter(
    "dynamo_kvbm_remote_timeout_total",
    "Blocking store round trips from the engine thread that hit their "
    "deadline (G4 object plane + fleet catalog), by operation — each "
    "one also books a flight-recorder record instead of killing the "
    "offload pump",
    labels=("op",),  # put | get | get_many | list | catalog.*
)

# -- fleet KV fabric (kvbm/fabric.py; docs/kvbm.md "Fleet fabric") -----------
KVBM_FLEET_HITS = REGISTRY.counter(
    "dynamo_kvbm_fleet_hits_total",
    "Prompt blocks missing every local tier but onboarded from the "
    "fleet instead of recomputed, by source (peer = another worker's "
    "host tier over the wire plane, bucket = the shared G4 object "
    "bucket adopted via the catalog)",
    labels=("source",),  # peer | bucket
)
KVBM_FLEET_FETCHED_BLOCKS = REGISTRY.counter(
    "dynamo_kvbm_fleet_fetched_blocks_total",
    "Blocks landed in local tiers by fleet prefetch at admission",
)
KVBM_FLEET_FETCH_SECONDS = REGISTRY.histogram(
    "dynamo_kvbm_fleet_fetch_seconds",
    "Wall time of one peer host-tier fetch round trip (connect to "
    "last block byte)",
    buckets=(
        0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
        0.5, 1.0, 2.5, float("inf"),
    ),
)
KVBM_FLEET_DEMOTED_BLOCKS = REGISTRY.counter(
    "dynamo_kvbm_fleet_demoted_blocks_total",
    "G2 blocks demoted by the watermark pressure lifecycle, by "
    "destination (shared = hot shared prefixes to the G4 bucket, disk "
    "= cold private blocks to local G3, dropped = no lower tier)",
    labels=("dest",),  # shared | disk | dropped
)
KVBM_FLEET_CATALOG_ENTRIES = REGISTRY.gauge(
    "dynamo_kvbm_fleet_catalog_entries",
    "Distinct block hashes in this participant's fleet-catalog view "
    "after the last snapshot refresh",
)
KVBM_FLEET_DANGLING = REGISTRY.counter(
    "dynamo_kvbm_fleet_dangling_total",
    "Catalog entries pruned because every advertised location failed "
    "to produce the block (the request falls back to recompute)",
)

# -- SLO / goodput (telemetry/slo.py; targets via --slo-ttft-ms/--slo-itl-ms)
# latency-target-shaped buckets: TTFT targets live in the tens-of-ms to
# tens-of-seconds range, ITL targets in the ms to hundreds-of-ms range
_TTFT_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
    float("inf"),
)
_ITL_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    float("inf"),
)
REQUEST_TTFT_SECONDS = REGISTRY.histogram(
    "dynamo_request_ttft_seconds",
    "Per-request time to first token, measured at the engine "
    "(submit to first emitted token)",
    buckets=_TTFT_BUCKETS,
)
REQUEST_ITL_SECONDS = REGISTRY.histogram(
    "dynamo_request_itl_seconds",
    "Per-request mean inter-token latency over the decode phase",
    buckets=_ITL_BUCKETS,
)
SLO_ATTAINMENT = REGISTRY.gauge(
    "dynamo_slo_attainment",
    "Rolling fraction of recent requests meeting the configured "
    "TTFT/ITL targets (1.0 when no targets are set)",
)
GOODPUT_TOKENS = REGISTRY.counter(
    "dynamo_goodput_tokens_total",
    "Completion tokens from requests that met their SLO targets",
)
SLO_REQUESTS = REGISTRY.counter(
    "dynamo_slo_requests_total",
    "Requests evaluated against the SLO targets, by outcome",
    labels=("outcome",),  # met | missed
)

# -- perf attribution (telemetry/attribution.py; docs/observability.md) -----
STEP_TIME_FRAC = REGISTRY.gauge(
    "dynamo_step_time_frac",
    "Fraction of the rolling decode window's wall time attributed to "
    "each loss bucket (queue_wait/plan/dispatch/sync/idle_gap + the "
    "device split attention/mlp/lm_head/sampling); sums to ~1.0",
    labels=("component",),
)
ROOFLINE_FRAC = REGISTRY.gauge(
    "dynamo_roofline_frac",
    "Achieved decode tok/s over the kv_dtype-aware byte-bound roofline "
    "at the live geometry (telemetry/roofline.py — the same formula as "
    "bench.py's headline vs_baseline)",
)
TOKENS_LOST_PER_S = REGISTRY.gauge(
    "dynamo_tokens_lost_per_s",
    "Tokens/s of roofline headroom attributed to each loss bucket — "
    "'the other 60%' as a first-class per-component series",
    labels=("component",),
)
BLACKBOX_DUMPS = REGISTRY.counter(
    "dynamo_blackbox_dumps_total",
    "Anomaly-triggered black-box forensic bundles written, by trigger "
    "(watchdog / roofline_drop / slo_miss / manual)",
    labels=("reason",),
)

# -- request autopsy (telemetry/autopsy.py; docs/observability.md
# "Request autopsy") — request-bounded only: one counter bump per
# request at finish plus one per attached segment, NEVER per chunk
AUTOPSY_REQUESTS = REGISTRY.counter(
    "dynamo_autopsy_requests_total",
    "Requests closed by the autopsy collector, by retention outcome "
    "(retained = kept as an exemplar: flagged slow/migrated/faulted/"
    "shed/rejected or at the rolling p99 tail; dropped = finished "
    "clean and fast, record discarded)",
    labels=("outcome",),  # retained | dropped
)
AUTOPSY_EXEMPLARS = REGISTRY.gauge(
    "dynamo_autopsy_exemplars",
    "Exemplar records currently held in the autopsy ring "
    "(bounded; serves /debug/requests and the top SLOW column)",
)
AUTOPSY_SEGMENTS = REGISTRY.counter(
    "dynamo_autopsy_segments_total",
    "Execution segments attached to autopsy records, by source "
    "(engine = an engine's finish summary, remote_prefill = the "
    "disagg decode-side wait, worker_died = the synthesized stub "
    "for a worker that was lost mid-stream)",
    labels=("source",),  # engine | remote_prefill | worker_died
)

# -- flight recorder + slow-step watchdog (telemetry/recorder.py) -----------
SLOW_STEPS = REGISTRY.counter(
    "dynamo_engine_slow_steps_total",
    "Engine steps that breached the slow-step watchdog threshold",
    labels=("kind",),
)
FLIGHT_DUMPS = REGISTRY.counter(
    "dynamo_flight_recorder_dumps_total",
    "Flight-recorder ring dumps written, by trigger",
    labels=("reason",),  # slow_step | slow_request | manual
)

# -- KV pool occupancy (allocator view; refreshed per step + per snapshot) --
KV_POOL_BLOCKS_ACTIVE = REGISTRY.gauge(
    "dynamo_kv_pool_blocks_active",
    "KV blocks currently referenced by sequences (excludes the "
    "reserved garbage block)",
)
KV_POOL_BLOCKS_TOTAL = REGISTRY.gauge(
    "dynamo_kv_pool_blocks_total",
    "Usable KV blocks in the device pool (excludes the reserved "
    "garbage block)",
)
KV_POOL_CACHED_FREE_BLOCKS = REGISTRY.gauge(
    "dynamo_kv_pool_cached_free_blocks",
    "Free blocks still holding content-addressed (reusable) KV — the "
    "prefix cache's evictable working set",
)

# -- HBM accounting (telemetry/hbm.py) --------------------------------------
HBM_WEIGHT_BYTES = REGISTRY.gauge(
    "dynamo_hbm_weight_bytes",
    "Bytes held by model parameters (logical, across shards)",
)
HBM_KV_POOL_BYTES = REGISTRY.gauge(
    "dynamo_hbm_kv_pool_bytes",
    "Bytes held by the device KV cache pool (logical, across shards)",
)
HBM_BYTES_IN_USE = REGISTRY.gauge(
    "dynamo_hbm_bytes_in_use",
    "Live device memory in use (device.memory_stats when available; "
    "accounted weights+KV fallback otherwise)",
)
HBM_BYTES_LIMIT = REGISTRY.gauge(
    "dynamo_hbm_bytes_limit",
    "Device memory capacity reported by the runtime (0 = unknown)",
)
HBM_PEAK_BYTES = REGISTRY.gauge(
    "dynamo_hbm_peak_bytes",
    "Peak live-buffer watermark (device-reported peak, or the "
    "accounted maximum on backends without memory stats)",
)

# -- robustness (docs/robustness.md: faults, deadlines, shedding, failover) -
FAULTS_FIRED = REGISTRY.counter(
    "dynamo_faults_fired_total",
    "Injected faults fired, by injection point and fault kind "
    "(nonzero only when a DYN_FAULTS plan is active)",
    labels=("point", "kind"),
)
WATCH_RESTARTS = REGISTRY.counter(
    "dynamo_watch_restarts_total",
    "Store watch streams resubscribed after dying (discovery watchers "
    "recover instead of freezing their registry)",
    labels=("watcher",),  # models | instances
)
STORE_RECONNECTS = REGISTRY.counter(
    "dynamo_store_reconnects_total",
    "Coordinator-store client redials after a lost connection",
)
DEADLINE_EXPIRED = REGISTRY.counter(
    "dynamo_deadline_expired_total",
    "Requests cancelled because their deadline budget expired, by the "
    "lifecycle stage that caught the expiry",
    labels=("stage",),  # admission | queue | prefill | decode | prefill_queue
)
REQUESTS_SHED = REGISTRY.counter(
    "dynamo_requests_shed_total",
    "Requests rejected 429 by admission control, by overload signal",
    labels=("reason",),  # queue_depth | kv_pressure
)
FAILOVER_RETRIES = REGISTRY.counter(
    "dynamo_failover_retries_total",
    "Requests re-dispatched to another worker after a dispatch or "
    "pre-first-token stream failure",
)
MIDSTREAM_ABORTS = REGISTRY.counter(
    "dynamo_midstream_aborts_total",
    "Streams terminated with a clean error after their worker died "
    "mid-generation AND migration could not save them (disabled, "
    "opted out, penalty-ineligible, or every resume attempt failed)",
)
MIDSTREAM_RESUMES = REGISTRY.counter(
    "dynamo_midstream_resumes_total",
    "Mid-stream migration outcomes: result=ok counts successful "
    "splices (the resumed worker's first continuation token reached "
    "the client), result=failed counts resume attempts that died "
    "before splicing a token (dispatch failure or pre-splice stream "
    "loss; the stream then retries or falls back to the abort)",
    labels=("result",),  # ok | failed
)
RESUME_SECONDS = REGISTRY.histogram(
    "dynamo_midstream_resume_seconds",
    "Mid-stream migration latency: worker-death detection to the first "
    "spliced continuation token (covers re-schedule, re-dispatch, and "
    "the resume re-prefill — cache-hot placements sit in the low "
    "buckets)",
    buckets=_STEP_BUCKETS,
)
WORKER_DRAINS = REGISTRY.counter(
    "dynamo_worker_drains_total",
    "Graceful drains run by this worker (runtime/drain.py), by result: "
    "completed = every eligible stream handed off inside the deadline, "
    "deadline = the --drain-timeout-s budget expired and leftover "
    "streams fell back to the reactive abort/resume path, no_peer = no "
    "healthy peer existed so the worker served until done or deadline "
    "instead of migrating",
    labels=("result",),  # completed | deadline | no_peer
)
DRAIN_HANDOFF_SECONDS = REGISTRY.histogram(
    "dynamo_drain_handoff_seconds",
    "Wall time of one graceful drain's handoff phase: DRAINING flag "
    "published to the moment the last eligible stream left the engine "
    "(deadline-capped; docs/robustness.md 'Graceful drain')",
    buckets=_STEP_BUCKETS,
)
DRAIN_STREAMS_MIGRATED = REGISTRY.counter(
    "dynamo_drain_streams_migrated_total",
    "Active streams a graceful drain proactively handed off with the "
    "MIGRATE marker (each becomes a reason=drain resume splice on its "
    "router)",
)

# -- autoscaling planner (planner/planner.py; docs/autoscaling.md) ----------
PLANNER_SCALE_EVENTS = REGISTRY.counter(
    "dynamo_planner_scale_events_total",
    "Successful planner scaling actions, by component and direction",
    # direction: up | down (policy) | drain (reconciliation removing a
    # surplus worker the fleet gained without the planner asking)
    labels=("component", "direction"),
)
PLANNER_REPLACEMENTS = REGISTRY.counter(
    "dynamo_planner_replacements_total",
    "Workers replaced by the planner's self-healing reconciliation "
    "(intent said N, the fleet reported fewer for reconcile_cycles)",
    labels=("component",),
)
PLANNER_DEGRADATION_LEVEL = REGISTRY.gauge(
    "dynamo_planner_degradation_level",
    "Graceful-degradation ladder position (0 normal, 1 tighten "
    "admission, 2 disable spec decode, 3 shed aggressively)",
)
PLANNER_CONNECTOR_FAILURES = REGISTRY.counter(
    "dynamo_planner_connector_failures_total",
    "Planner add/remove commands the connector refused or failed",
    labels=("op",),  # add | remove
)

# -- disaggregation (decode-side routing + prefill queue) -------------------
DISAGG_REMOTE_PREFILLS = REGISTRY.counter(
    "dynamo_disagg_remote_prefills_total",
    "Requests routed to a remote prefill worker",
)
DISAGG_LOCAL_FALLBACKS = REGISTRY.counter(
    "dynamo_disagg_local_fallbacks_total",
    "Remote prefills that timed out and fell back to local prefill",
)
PREFILL_QUEUE_DEPTH = REGISTRY.gauge(
    "dynamo_prefill_queue_depth",
    "Prefill queue depth observed at the last routing decision",
)
PREFILL_QUEUE_WAIT = REGISTRY.histogram(
    "dynamo_prefill_queue_wait_seconds",
    "Enqueue-to-KV-landed wait for remote prefills (decode side)",
    buckets=_STEP_BUCKETS,
)
