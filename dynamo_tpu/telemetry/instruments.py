"""The serving stack's metric catalog — every instrument in one place.

One module so the surface is auditable (docs/observability.md mirrors
this file) and so the cardinality gate (tests/test_metric_cardinality.py)
can walk the whole registry by importing one module. Layers import their
instruments from here; nothing else registers process-global metrics.

Naming: ``dynamo_<layer>_<what>_<unit>`` with Prometheus suffix
conventions (``_total`` counters, ``_seconds`` histograms). The http
family keeps the seed's prometheus_client names so dashboards survive
the migration.
"""

from __future__ import annotations

from dynamo_tpu.telemetry.metrics import REGISTRY

# -- HTTP frontend (names unchanged from the seed's prometheus_client) ------
HTTP_REQUESTS = REGISTRY.counter(
    "dynamo_http_requests_total",
    "Total HTTP LLM requests",
    labels=("model", "endpoint", "status"),
)
HTTP_INFLIGHT = REGISTRY.gauge(
    "dynamo_http_inflight_requests",
    "In-flight HTTP LLM requests",
    labels=("model",),
)
HTTP_DURATION = REGISTRY.histogram(
    "dynamo_http_request_duration_seconds",
    "HTTP LLM request duration",
    labels=("model", "endpoint"),
)
HTTP_TTFT = REGISTRY.histogram(
    "dynamo_http_time_to_first_token_seconds",
    "Time to first streamed token",
    labels=("model",),
)

# -- engine (scheduler + step loop; the instruments ISSUE 2 calls out) ------
_STEP_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 15.0, 60.0, float("inf"),
)
ENGINE_STEP_SECONDS = REGISTRY.histogram(
    "dynamo_engine_step_seconds",
    "Engine device-step wall time by step kind",
    labels=("kind",),  # prefill | decode | mixed | window | spec
    buckets=_STEP_BUCKETS,
)
ENGINE_BATCH_OCCUPANCY = REGISTRY.gauge(
    "dynamo_engine_batch_occupancy",
    "Running sequences / max_batch_size (sampled each step)",
)
ENGINE_QUEUE_DEPTH = REGISTRY.gauge(
    "dynamo_engine_queue_depth",
    "Requests waiting or prefilling (not yet decoding)",
)
ENGINE_QUEUE_WAIT = REGISTRY.histogram(
    "dynamo_engine_queue_wait_seconds",
    "Submit-to-admission wait (time in the scheduler's waiting queue)",
    buckets=_STEP_BUCKETS,
)
ENGINE_PREEMPTIONS = REGISTRY.counter(
    "dynamo_engine_preemptions_total",
    "Recompute preemptions (healthy serving sits at ~0)",
)
ENGINE_COMPILE_EVENTS = REGISTRY.counter(
    "dynamo_engine_compile_events_total",
    "Step-shape compilations by phase (prewarm vs mid-serve lazy)",
    labels=("phase",),  # prewarm | serve
)
ENGINE_PREWARM_SECONDS = REGISTRY.gauge(
    "dynamo_engine_prewarm_seconds",
    "Wall time of the startup AOT prewarm pass",
)
ENGINE_REQUESTS_FINISHED = REGISTRY.counter(
    "dynamo_engine_requests_finished_total",
    "Sequences finished by reason",
    labels=("reason",),  # stop | length | cancelled | error | ...
)
ENGINE_TOKENS_GENERATED = REGISTRY.counter(
    "dynamo_engine_tokens_generated_total",
    "Decoded tokens emitted to request streams",
)

# -- speculative decoding (engine spec step; dynamo_tpu/spec) ---------------
SPEC_PROPOSED_TOKENS = REGISTRY.counter(
    "dynamo_spec_proposed_tokens_total",
    "Draft tokens proposed to the speculative verify step",
    labels=("drafter",),  # ngram | bigram
)
SPEC_ACCEPTED_TOKENS = REGISTRY.counter(
    "dynamo_spec_accepted_tokens_total",
    "Draft tokens accepted by rejection sampling",
    labels=("drafter",),
)
SPEC_ACCEPT_RATE = REGISTRY.gauge(
    "dynamo_spec_accept_rate",
    "Accepted/proposed draft tokens of the last speculative step",
)
SPEC_STEP_SECONDS = REGISTRY.histogram(
    "dynamo_spec_step_seconds",
    "Speculative step latency by phase (host drafting vs device verify)",
    labels=("phase",),  # draft | verify
    buckets=_STEP_BUCKETS,
)

# -- KV block manager / transfer plane --------------------------------------
KV_TRANSFER_BYTES = REGISTRY.counter(
    "dynamo_kv_transfer_bytes_total",
    "KV block bytes moved over the disagg transfer plane",
    labels=("direction",),  # send | recv
)
KV_TRANSFER_SECONDS = REGISTRY.histogram(
    "dynamo_kv_transfer_seconds",
    "Wall time of one KV transfer put (connect to ack)",
    labels=("direction",),
    buckets=_STEP_BUCKETS,
)
KV_TRANSFER_BLOCKS = REGISTRY.counter(
    "dynamo_kv_transfer_blocks_total",
    "KV blocks moved over the disagg transfer plane",
    labels=("direction",),
)
KVBM_OFFLOADED_BLOCKS = REGISTRY.counter(
    "dynamo_kvbm_offloaded_blocks_total",
    "Blocks demoted from device HBM into the host tier",
)
KVBM_ONBOARDED_BLOCKS = REGISTRY.counter(
    "dynamo_kvbm_onboarded_blocks_total",
    "Blocks promoted from offload tiers back into device HBM",
)

# -- disaggregation (decode-side routing + prefill queue) -------------------
DISAGG_REMOTE_PREFILLS = REGISTRY.counter(
    "dynamo_disagg_remote_prefills_total",
    "Requests routed to a remote prefill worker",
)
DISAGG_LOCAL_FALLBACKS = REGISTRY.counter(
    "dynamo_disagg_local_fallbacks_total",
    "Remote prefills that timed out and fell back to local prefill",
)
PREFILL_QUEUE_DEPTH = REGISTRY.gauge(
    "dynamo_prefill_queue_depth",
    "Prefill queue depth observed at the last routing decision",
)
PREFILL_QUEUE_WAIT = REGISTRY.histogram(
    "dynamo_prefill_queue_wait_seconds",
    "Enqueue-to-KV-landed wait for remote prefills (decode side)",
    buckets=_STEP_BUCKETS,
)
