"""Unified process-local metrics registry with Prometheus exposition.

Replaces the scattered fragments the port grew organically — the
prometheus_client instruments in http/service.py and the hand-rolled
``render()`` in metrics/service.py — with ONE dependency-free registry
(reference: lib/llm/src/http/service/metrics.rs + the metrics component,
components/metrics/src/lib.rs:339-545).

Instruments: Counter, Gauge, Histogram — all optionally labeled. A
labeled instrument is a family; each distinct label-value tuple is a
series created on first touch via ``metric.labels(...)``.

Scrape safety (ISSUE 2 satellite: the metrics surface must stay
scrape-safe):

- label NAMES are validated at declaration against a denylist of
  per-request identifiers (labeling by request id would grow one series
  per request until the scrape payload OOMs the scraper);
- series counts are bounded at runtime (``max_series``): past the bound
  new label combinations collapse into a single ``{<label>="_overflow"}``
  series with one warning, so a cardinality bug degrades metrics instead
  of memory;
- ``check_scrape_safety()`` walks a registry and raises on violations —
  the pytest gate (tests/test_metric_cardinality.py) runs it over every
  instrument the serving stack declares.

Thread safety: instruments are touched from the asyncio loop AND the
dedicated jax-engine thread; all mutation happens behind per-series
locks (observations are tiny — dict lookup + float adds).
"""

from __future__ import annotations

import logging
import math
import threading
from typing import Iterable, Optional, Sequence

log = logging.getLogger("dynamo_tpu.telemetry")

# Label names that would key a series per request/trace — unbounded
# cardinality by construction. Declaration-time error, not a runtime one.
FORBIDDEN_LABEL_NAMES = frozenset(
    {"request_id", "trace_id", "span_id", "session_id", "uuid", "id"}
)

DEFAULT_MAX_SERIES = 512
OVERFLOW_LABEL_VALUE = "_overflow"

# prometheus_client's default buckets: keeps the http histograms'
# exposition shape identical to what the seed emitted.
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.075, 0.1, 0.25, 0.5, 0.75,
    1.0, 2.5, 5.0, 7.5, 10.0, float("inf"),
)

_METRIC_NAME_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:"
)


def escape_label_value(value: str) -> str:
    """Prometheus text-format label escaping: backslash, quote, newline."""
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def format_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def format_le(v: float) -> str:
    """Bucket-bound label values keep prometheus_client's formatting
    (``le="1.0"``, never ``le="1"``): the le string is part of series
    IDENTITY, so changing it would orphan every existing dashboard
    series across the migration."""
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return f"{int(v)}.0"
    return repr(float(v))


class _Series:
    """One sample cell (counter/gauge)."""

    __slots__ = ("value", "lock")

    def __init__(self) -> None:
        self.value = 0.0
        self.lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self.lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set(self, value: float) -> None:
        with self.lock:
            self.value = float(value)


class _HistogramSeries:
    __slots__ = ("buckets", "counts", "sum", "count", "lock")

    def __init__(self, buckets: tuple):
        self.buckets = buckets
        self.counts = [0] * len(buckets)
        self.sum = 0.0
        self.count = 0
        self.lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        with self.lock:
            self.sum += value
            self.count += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self.counts[i] += 1
                    break


class Metric:
    """Base family: name, help, label names, series map. Unlabeled
    metrics expose the series verbs (inc/set/observe) directly."""

    type: str = "untyped"

    def __init__(
        self,
        name: str,
        help: str,
        labels: Sequence[str] = (),
        max_series: int = DEFAULT_MAX_SERIES,
    ):
        if not name or not set(name) <= _METRIC_NAME_OK or name[0].isdigit():
            raise ValueError(f"invalid metric name {name!r}")
        if not help:
            raise ValueError(f"metric {name} needs help text")
        bad = set(labels) & FORBIDDEN_LABEL_NAMES
        if bad:
            raise ValueError(
                f"metric {name}: label(s) {sorted(bad)} key a series per "
                f"request — unbounded cardinality; put the id on the SPAN, "
                f"not the metric"
            )
        if len(set(labels)) != len(labels):
            raise ValueError(f"metric {name}: duplicate label names")
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        self.max_series = max_series
        self._series: dict[tuple, object] = {}
        self._lock = threading.Lock()
        self._overflowed = False

    def _new_series(self):  # pragma: no cover — subclasses override
        raise NotImplementedError

    def labels(self, *values, **kw):
        """The series for one label-value combination (created on first
        touch; collapses into the overflow series past ``max_series``)."""
        if kw:
            if values:
                raise ValueError("pass labels positionally OR by name")
            try:
                values = tuple(kw[n] for n in self.label_names)
            except KeyError as e:
                raise ValueError(f"metric {self.name}: missing label {e}")
        if len(values) != len(self.label_names):
            raise ValueError(
                f"metric {self.name} expects labels {self.label_names}, "
                f"got {values!r}"
            )
        key = tuple(str(v) for v in values)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                if key != () and len(self._series) >= self.max_series:
                    if not self._overflowed:
                        self._overflowed = True
                        log.warning(
                            "metric %s exceeded %d series; collapsing new "
                            "label combinations into %r",
                            self.name, self.max_series, OVERFLOW_LABEL_VALUE,
                        )
                    key = tuple(
                        OVERFLOW_LABEL_VALUE for _ in self.label_names
                    )
                    series = self._series.get(key)
                    if series is not None:
                        return series
                series = self._new_series()
                self._series[key] = series
            return series

    def clear(self) -> None:
        """Drop every series (aggregation services re-populate per
        scrape from a fresh snapshot)."""
        with self._lock:
            self._series.clear()
            self._overflowed = False

    @property
    def num_series(self) -> int:
        return len(self._series)

    # -- exposition --------------------------------------------------------
    def render(self) -> list[str]:
        lines = [
            f"# HELP {self.name} {escape_help(self.help)}",
            f"# TYPE {self.name} {self.type}",
        ]
        with self._lock:
            items = sorted(self._series.items())
        for key, series in items:
            lines.extend(self._render_series(key, series))
        return lines

    def _label_str(self, key: tuple, extra: str = "") -> str:
        parts = [
            f'{n}="{escape_label_value(v)}"'
            for n, v in zip(self.label_names, key)
        ]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def _render_series(self, key, series) -> list[str]:  # pragma: no cover
        raise NotImplementedError


class Counter(Metric):
    type = "counter"

    def _new_series(self) -> _Series:
        return _Series()

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def _render_series(self, key, series) -> list[str]:
        return [
            f"{self.name}{self._label_str(key)} {format_value(series.value)}"
        ]


class Gauge(Metric):
    type = "gauge"

    def _new_series(self) -> _Series:
        return _Series()

    def set(self, value: float) -> None:
        self.labels().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().dec(amount)

    def _render_series(self, key, series) -> list[str]:
        return [
            f"{self.name}{self._label_str(key)} {format_value(series.value)}"
        ]


class Histogram(Metric):
    type = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labels: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        max_series: int = DEFAULT_MAX_SERIES,
    ):
        super().__init__(name, help, labels, max_series)
        bs = sorted(set(float(b) for b in buckets))
        if not bs or bs[-1] != math.inf:
            bs.append(math.inf)
        self.buckets = tuple(bs)

    def _new_series(self) -> _HistogramSeries:
        return _HistogramSeries(self.buckets)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    def _render_series(self, key, series) -> list[str]:
        # snapshot under the series lock: a concurrent observe() from
        # the jax-engine thread mid-render would otherwise emit an
        # exposition where the +Inf bucket != _count (strict scrapers
        # — and tests/prom_parser.py — reject that)
        with series.lock:
            counts = list(series.counts)
            total = series.count
            sum_ = series.sum
        lines = []
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            le = f'le="{format_le(b)}"'
            lines.append(
                f"{self.name}_bucket{self._label_str(key, le)} {cum}"
            )
        lines.append(
            f"{self.name}_sum{self._label_str(key)} {format_value(sum_)}"
        )
        lines.append(f"{self.name}_count{self._label_str(key)} {total}")
        return lines


class Registry:
    """A set of metric families rendered as one Prometheus payload."""

    def __init__(self) -> None:
        self._metrics: dict[str, Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: Metric) -> Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if type(existing) is not type(metric) or (
                    existing.label_names != metric.label_names
                ):
                    raise ValueError(
                        f"metric {metric.name} re-registered with a "
                        f"different type/labels"
                    )
                return existing
            self._metrics[metric.name] = metric
            return metric

    # get-or-create helpers (idempotent: module reloads in tests must
    # not raise on duplicate names)
    def counter(self, name: str, help: str, labels: Sequence[str] = (),
                max_series: int = DEFAULT_MAX_SERIES) -> Counter:
        return self.register(Counter(name, help, labels, max_series))  # type: ignore[return-value]

    def gauge(self, name: str, help: str, labels: Sequence[str] = (),
              max_series: int = DEFAULT_MAX_SERIES) -> Gauge:
        return self.register(Gauge(name, help, labels, max_series))  # type: ignore[return-value]

    def histogram(
        self, name: str, help: str, labels: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        max_series: int = DEFAULT_MAX_SERIES,
    ) -> Histogram:
        return self.register(Histogram(name, help, labels, buckets, max_series))  # type: ignore[return-value]

    def get(self, name: str) -> Optional[Metric]:
        return self._metrics.get(name)

    def metrics(self) -> list[Metric]:
        with self._lock:
            return list(self._metrics.values())

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: list[str] = []
        for metric in sorted(self.metrics(), key=lambda m: m.name):
            lines.extend(metric.render())
        return "\n".join(lines) + "\n"


def check_scrape_safety(
    registry: Registry,
    extra_forbidden: Iterable[str] = (),
    max_series: int = 10_000,
) -> None:
    """Raise ValueError if any registered metric could produce an
    unbounded scrape payload. Construction already rejects forbidden
    label names; this re-walks a live registry (catching metrics built
    around the constructor, config drift, absurd max_series) so a test
    gate can hold the line."""
    forbidden = FORBIDDEN_LABEL_NAMES | set(extra_forbidden)
    problems: list[str] = []
    for m in registry.metrics():
        bad = set(m.label_names) & forbidden
        if bad:
            problems.append(f"{m.name}: forbidden label(s) {sorted(bad)}")
        if m.label_names and m.max_series > max_series:
            problems.append(
                f"{m.name}: max_series={m.max_series} exceeds the "
                f"scrape-safety bound {max_series}"
            )
        if not m.help:
            problems.append(f"{m.name}: missing help text")
    if problems:
        raise ValueError(
            "metrics registry is not scrape-safe:\n  "
            + "\n  ".join(problems)
        )


# -- the process registry ---------------------------------------------------
REGISTRY = Registry()
