"""Device-overlap accounting for the engine step loop.

The overlapped decode pipeline (docs/performance.md) only pays off if
the device is actually busy while the host plans, packs, and emits —
and the roofline gap only closes if we can *measure* when it is not.
``OverlapTracker`` is the engine-thread-side ledger of that overlap:

- ``note_dispatch()`` marks a device step entering the queue. When the
  queue was EMPTY and a previous step had completed, the span since
  that completion is a **device idle gap** — the device had nothing to
  execute while the host did serial work (plan/unpack/emit). The gap is
  returned (seconds) so the step record can carry it as ``idle_gap_ms``.
- ``note_complete(all_prior=False)`` marks the oldest in-flight step's
  result harvested (device execution is in-order, so harvesting step N
  proves steps <= N are done). ``all_prior=True`` retires everything —
  the serial ``_run_device_step`` path harvests its own (newest)
  dispatch, which implies every earlier async dispatch completed too.
- ``note_idle()`` resets the completion anchor when the engine parks
  with NO work: a gap spent waiting for requests is load, not overlap
  failure, and must not be billed as device idleness.

All methods are engine-thread only (mirrors ``_last_phases``); readers
(``/debug/state``, bench) take an advisory ``stats()`` snapshot.

The numbers are a **host-observable lower bound**: a step's true device
completion is only witnessed at its harvest, so idleness hidden behind
an early finish inside a still-nonempty queue is not counted. In serial
mode the bound is exact — every plan+unpack+emit span between a harvest
and the next dispatch is device idle time, which is precisely the
serialization the overlapped pipeline exists to remove.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Optional


class OverlapTracker:
    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._inflight: deque[float] = deque()  # dispatch stamps, FIFO
        self._last_complete: Optional[float] = None
        self.steps_dispatched = 0
        self.idle_events = 0
        self.idle_gap_s_total = 0.0
        self.last_idle_gap_s = 0.0
        self.max_idle_gap_s = 0.0

    @property
    def inflight(self) -> int:
        return len(self._inflight)

    def note_dispatch(self) -> float:
        """A device step was enqueued; returns the idle gap (seconds)
        that preceded it (0.0 when the device still had queued work or
        no completion anchor exists)."""
        now = self._clock()
        gap = 0.0
        if not self._inflight and self._last_complete is not None:
            gap = max(0.0, now - self._last_complete)
            if gap > 0.0:
                self.idle_events += 1
                self.idle_gap_s_total += gap
                self.max_idle_gap_s = max(self.max_idle_gap_s, gap)
        self.last_idle_gap_s = gap
        self._inflight.append(now)
        self.steps_dispatched += 1
        return gap

    def note_complete(self, all_prior: bool = False) -> None:
        """The oldest in-flight step's output reached the host (or, with
        ``all_prior``, the newest — retiring everything before it)."""
        if all_prior:
            self._inflight.clear()
        elif self._inflight:
            self._inflight.popleft()
        self._last_complete = self._clock()

    def note_idle(self) -> None:
        """The engine has NO work: drop the completion anchor so the
        wait for the next request is not billed as a device idle gap."""
        self._last_complete = None

    def reset(self) -> None:
        """Forget in-flight state (step failure/quarantine): the queue
        depth is unknowable after an aborted dispatch, and a stale
        nonempty queue would suppress idle-gap accounting forever."""
        self._inflight.clear()
        self._last_complete = None

    def stats(self) -> dict:
        return {
            "steps_dispatched": self.steps_dispatched,
            "inflight": len(self._inflight),
            "idle_events": self.idle_events,
            "idle_gap_s_total": round(self.idle_gap_s_total, 6),
            "last_idle_gap_ms": round(self.last_idle_gap_s * 1e3, 3),
            "max_idle_gap_ms": round(self.max_idle_gap_s * 1e3, 3),
        }
