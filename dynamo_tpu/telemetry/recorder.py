"""Step flight recorder: a bounded ring of the engine's recent steps.

The serving stack's post-hoc traces (spans.py) answer *where a finished
request's time went*; the flight recorder answers the harder forensic
question — *what was the engine doing in the seconds around an anomaly*
(a step that blew the ITL budget, a request that missed its SLO, a
watchdog trip). The engine records one entry per device step — kind,
batch composition, queue depth, per-phase latency, preemptions, spec
accept counts — into a ``deque(maxlen=N)``; when the slow-step watchdog
trips, the whole ring auto-dumps to JSONL so the offending step lands
on disk *with its surrounding context* instead of scrolling out of a
log buffer.

Design constraints:

- **Bounded by construction.** The ring is a ``deque(maxlen=...)`` —
  dynalint DL007 (unbounded-telemetry-buffer) exists to keep it and any
  sibling buffers that way.
- **Engine-thread cheap.** ``record()`` is a dict build + deque append
  behind a lock; the watchdog comparison is one float compare. Dumps
  are rate-limited (``min_dump_interval_s``) so a pathological phase
  can't turn the recorder into a disk-write loop.
- **Injectable clock.** ``clock`` defaults to ``time.monotonic`` but is
  a constructor argument so tests drive the watchdog deterministically.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Optional

from dynamo_tpu.telemetry.instruments import (
    FLIGHT_DUMPS,
    SLOW_STEPS,
)

log = logging.getLogger("dynamo_tpu.telemetry.recorder")


def default_dump_dir() -> str:
    return os.environ.get("DYN_FLIGHT_DIR") or tempfile.gettempdir()


class FlightRecorder:
    """Ring buffer of step records with a slow-step watchdog.

    ``slow_step_s`` — steps longer than this dump the ring (None = the
    watchdog is off; the ring still records for ``/debug/state``).
    ``idle_gap_slow_s`` — a step whose ``idle_gap_ms`` field (the
    device idle span the overlap tracker measured before its dispatch,
    telemetry/overlap.py) exceeds this dumps the ring too: a device
    that sat idle for a slow-step's worth of time is the same anomaly
    as a slow step, just spent on the host side of the pipeline
    (None = follow ``slow_step_s``).
    ``dump_dir`` — where JSONL dumps land (default: DYN_FLIGHT_DIR or
    the system temp dir).
    ``max_dump_files`` — on-disk cap: writing dump K+1 unlinks this
    recorder's oldest file, so a chronically-breaching process leaks
    neither memory NOR disk (the rate limit bounds the write rate, not
    the total).
    """

    def __init__(
        self,
        capacity: int = 256,
        slow_step_s: Optional[float] = None,
        dump_dir: str = "",
        min_dump_interval_s: float = 30.0,
        max_dump_files: int = 16,
        clock: Callable[[], float] = time.monotonic,
        idle_gap_slow_s: Optional[float] = None,
    ):
        self.capacity = max(1, int(capacity))
        self.slow_step_s = slow_step_s
        self.idle_gap_slow_s = (
            idle_gap_slow_s if idle_gap_slow_s is not None else slow_step_s
        )
        self.dump_dir = dump_dir or default_dump_dir()
        self.min_dump_interval_s = min_dump_interval_s
        self._clock = clock
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._last_dump: float = -float("inf")
        self._dump_seq = 0
        self._dump_paths: deque = deque(maxlen=max(1, max_dump_files))
        self.steps_recorded = 0
        self.slow_steps = 0
        self.dumps_written = 0
        self.last_dump_path: Optional[str] = None

    # -- recording ---------------------------------------------------------
    def record(self, kind: str, duration_s: float, **fields) -> Optional[str]:
        """Append one step record; returns a dump path when the slow-step
        watchdog tripped (None otherwise). ``fields`` should be scalar
        (they land in JSONL verbatim): batch sizes, queue depth,
        per-phase millisecond timings, preemption/spec counts."""
        rec = {
            "ts": time.time(),
            "kind": kind,
            "duration_ms": round(duration_s * 1e3, 3),
        }
        rec.update(fields)
        slow = self.slow_step_s is not None and duration_s > self.slow_step_s
        if slow:
            rec["slow"] = True
            rec["slow_threshold_ms"] = round(self.slow_step_s * 1e3, 3)
        # device-idle watchdog (telemetry/overlap.py): a large idle gap
        # before this dispatch is dump-worthy like a slow step — the
        # time went missing on the host side of the pipeline instead of
        # inside the device step
        gap_ms = fields.get("idle_gap_ms")
        idle_slow = (
            not slow
            and self.idle_gap_slow_s is not None
            and isinstance(gap_ms, (int, float))
            and gap_ms > self.idle_gap_slow_s * 1e3
        )
        if idle_slow:
            rec["slow_idle_gap"] = True
            rec["idle_gap_threshold_ms"] = round(
                self.idle_gap_slow_s * 1e3, 3
            )
        with self._lock:
            self._ring.append(rec)
            self.steps_recorded += 1
            if slow or idle_slow:
                self.slow_steps += 1
        if slow:
            SLOW_STEPS.labels(kind).inc()
            return self.dump(reason=f"slow_step:{kind}")
        if idle_slow:
            SLOW_STEPS.labels(kind).inc()
            return self.dump(reason=f"idle_gap:{kind}")
        return None

    def note_slow_request(self, request_id: str, **fields) -> Optional[str]:
        """A request-level watchdog trip (e.g. an SLO breach): record a
        marker entry and dump the ring so the steps that served the slow
        request are preserved. When the rate limiter would suppress the
        dump anyway, the marker is skipped too — sustained misses would
        otherwise flush the ring's step records (the payload the dump
        exists to preserve) with hundreds of markers per window."""
        with self._lock:
            if self._clock() - self._last_dump < self.min_dump_interval_s:
                return None
            rec = {"ts": time.time(), "kind": "slow_request",
                   "request_id": request_id}
            rec.update(fields)
            self._ring.append(rec)
        return self.dump(reason=f"slow_request:{request_id}")

    # -- dumping -----------------------------------------------------------
    def dump(self, reason: str = "manual") -> Optional[str]:
        """Write the ring as JSONL (one header line, then the records,
        oldest first). Rate-limited; returns the path or None when
        suppressed/failed."""
        now = self._clock()
        with self._lock:
            if now - self._last_dump < self.min_dump_interval_s:
                return None
            self._last_dump = now
            self._dump_seq += 1
            seq = self._dump_seq
            records = list(self._ring)
        path = os.path.join(
            self.dump_dir,
            f"dynamo_flight_{os.getpid()}_{seq:03d}.jsonl",
        )
        try:
            with open(path, "w") as f:
                f.write(json.dumps({
                    "flight_recorder_dump": True,
                    "reason": reason,
                    "ts": time.time(),
                    "pid": os.getpid(),
                    "records": len(records),
                }) + "\n")
                for rec in records:
                    f.write(json.dumps(rec) + "\n")
        except OSError:
            log.exception("flight-recorder dump to %s failed", path)
            with self._lock:
                # a FAILED dump must not arm the rate limiter: nothing
                # was persisted, so the next trigger should try again
                if self._last_dump == now:
                    self._last_dump = -float("inf")
            return None
        evict: Optional[str] = None
        with self._lock:
            self.dumps_written += 1
            self.last_dump_path = path
            if len(self._dump_paths) == self._dump_paths.maxlen:
                evict = self._dump_paths[0]  # rolls off on append
            self._dump_paths.append(path)
        if evict is not None:
            try:
                os.unlink(evict)
            except OSError:
                pass  # already gone / external cleanup: cap still holds
        FLIGHT_DUMPS.labels(reason.split(":", 1)[0]).inc()
        log.warning("flight recorder dumped %d steps to %s (%s)",
                    len(records), path, reason)
        return path

    # -- introspection -----------------------------------------------------
    def snapshot(self, n: int = 32) -> list[dict]:
        """The most recent ``n`` records, oldest first (for /debug/state)."""
        with self._lock:
            ring = list(self._ring)
        return ring[-n:]

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "recorded": self.steps_recorded,
                "slow_steps": self.slow_steps,
                "dumps": self.dumps_written,
                "last_dump": self.last_dump_path,
                "slow_threshold_ms": (
                    round(self.slow_step_s * 1e3, 3)
                    if self.slow_step_s is not None else None
                ),
            }
