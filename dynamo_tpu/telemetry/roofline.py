"""The decode roofline byte-budget model — ONE formula for bench and serving.

``bench.py``'s headline ``vs_baseline`` has always been *achieved tok/s
over the HBM byte-bound roofline*; the attribution ledger
(telemetry/attribution.py) publishes the same ratio live as
``dynamo_roofline_frac``. Both MUST compute the denominator from the
same model or the two numbers drift and "the bench says 0.37 but the
server says 0.45" becomes an argument instead of a measurement — so the
math lives here and both import it (docs/performance.md documents the
byte table this module implements).

The model (kv_dtype- and quant-aware):

- ``param_bytes``: every decode step reads all weights once — layer
  matmuls + embedding + LM head, at 1 B/elem for int8 weight-only
  quant, 2 B/elem for bf16.
- ``kv_bytes_per_token``: each sequence's KV window is read per step —
  ``2·L·Hk·Dh`` elements/token at the cache dtype (int8 pays the
  per-(slot, head) f32 scale: ``+4/Dh`` per element; fp8 is scale-free).
- ``step_bytes`` = weights + batch·ctx·kv_bytes_per_token; roofline
  tok/s = ``batch / (step_bytes / HBM_BW_BYTES)``.
- ``phase_ideal_bytes`` splits the same budget into the four decode
  phases (attention / MLP+projections / LM head / sampling) — the cost
  prior ``bench.py --phases`` reports per phase and the attribution
  ledger uses to split measured device time.
"""

from __future__ import annotations

from dataclasses import dataclass

# v5e datasheet HBM bandwidth. Kept as the roofline denominator for
# cross-round comparability (BASELINE.md round-2 revision: an amortized
# weight-streaming probe over this environment's tunneled chip reaches
# ~400 GB/s, so vs_baseline ≈ 0.5 is full *practical* utilization here).
HBM_BW_BYTES = 819e9

# decode phases, in step order (docs/performance.md byte table)
PHASES = ("attention", "mlp", "lm_head", "sampling")

_FP8_DTYPES = ("fp8", "float8", "float8_e4m3fn", "float8_e5m2")


def weight_bytes_per_elem(quant: str | None) -> int:
    return 1 if quant == "int8" else 2


def param_bytes(mc, quant: str | None) -> int:
    """Total weight bytes one decode step must stream: all layer matmul
    weights plus the embedding and LM head (``2·V·D``)."""
    D, F, V, L = (
        mc.hidden_size, mc.intermediate_size, mc.vocab_size,
        mc.num_hidden_layers,
    )
    H, Hk, Dh = mc.num_attention_heads, mc.num_key_value_heads, mc.head_dim
    per_layer = D * H * Dh + 2 * D * Hk * Dh + H * Dh * D + 3 * D * F
    return weight_bytes_per_elem(quant) * (per_layer * L + 2 * V * D)


def kv_bytes_per_token(mc, kv_dtype: str) -> float:
    """HBM bytes per cached token position (both K and V, all layers).
    int8 carries the per-(slot, head) f32 scale the Pallas decode kernel
    reads alongside the page (ops/kv_quant.py layout)."""
    if kv_dtype in _FP8_DTYPES:
        per_elem = 1.0
    elif kv_dtype == "int8":
        per_elem = 1.0 + 4.0 / mc.head_dim
    else:
        per_elem = 2.0
    return (
        2 * mc.num_hidden_layers * mc.num_key_value_heads * mc.head_dim
        * per_elem
    )


def step_bytes(
    mc, batch: int, avg_ctx: float, quant: str | None, kv_dtype: str,
) -> float:
    """Ideal HBM traffic of one decode step: weights once + each
    sequence's KV window at the average context length."""
    return param_bytes(mc, quant) + batch * avg_ctx * kv_bytes_per_token(
        mc, kv_dtype
    )


def roofline_tok_s(
    mc, batch: int, avg_ctx: float, quant: str | None, kv_dtype: str,
    hbm_bw: float = HBM_BW_BYTES,
) -> float:
    """Byte-bound decode throughput ceiling: ``batch`` tokens per
    ``step_bytes / hbm_bw`` seconds."""
    return batch / (step_bytes(mc, batch, avg_ctx, quant, kv_dtype) / hbm_bw)


def phase_ideal_bytes(
    mc, batch: int, avg_ctx: float, quant: str | None, kv_dtype: str,
) -> dict[str, int]:
    """The step byte budget split by decode phase — the table in
    docs/performance.md, and the device-time cost prior the attribution
    ledger splits measured compute with. ``mlp`` covers ALL layer
    matmul weights (attention projections included: they stream with
    the MLP weights, distinct from the KV *cache* reads billed to
    ``attention``); ``lm_head`` is the single ``D·V`` read plus the
    per-channel scales under int8; ``sampling`` is the ``[B, V]`` f32
    logits."""
    D, F, V, L = (
        mc.hidden_size, mc.intermediate_size, mc.vocab_size,
        mc.num_hidden_layers,
    )
    H, Hk, Dh = mc.num_attention_heads, mc.num_key_value_heads, mc.head_dim
    wb = weight_bytes_per_elem(quant)
    layer_weights = (D * H * Dh + 2 * D * Hk * Dh + H * Dh * D + 3 * D * F) * wb
    return {
        "attention": int(batch * avg_ctx * kv_bytes_per_token(mc, kv_dtype)),
        "mlp": int(layer_weights * L),
        "lm_head": int(D * V * wb + (V * 4 if quant == "int8" else 0)),
        "sampling": int(batch * V * 4),
    }


@dataclass(frozen=True)
class RooflineModel:
    """The scalars the attribution ledger needs per step, derived once
    at engine init so the hot path never touches the model config:
    ``ideal_step_s(batch, context_tokens)`` (the roofline denominator —
    param_bytes parity with the bench formula, embedding included) and
    the device-phase split prior. ``mlp_bytes`` is the LAYER matmul
    weights only — the same set ``phase_ideal_bytes`` bills to ``mlp``
    (the embedding gather reads B rows, not the table, so it belongs in
    neither phase) — so the ledger's device split and ``bench.py
    --phases`` decompose against the identical prior."""

    param_bytes: float
    kv_bytes_per_token: float
    mlp_bytes: float
    lm_head_bytes: float
    sampling_bytes_per_row: float
    hbm_bw: float = HBM_BW_BYTES

    def ideal_step_s(self, batch: int, context_tokens: float) -> float:
        """Byte-bound time of one decode step over ``batch`` rows whose
        context lengths sum to ``context_tokens``."""
        total = (
            self.param_bytes
            + context_tokens * self.kv_bytes_per_token
            + batch * self.sampling_bytes_per_row
        )
        return total / self.hbm_bw

    def phase_fractions(
        self, batch: int, context_tokens: float
    ) -> dict[str, float]:
        """Per-phase byte shares of one step at the live geometry — the
        prior used to split measured device time."""
        b = {
            "attention": context_tokens * self.kv_bytes_per_token,
            "mlp": self.mlp_bytes,
            "lm_head": self.lm_head_bytes,
            "sampling": batch * self.sampling_bytes_per_row,
        }
        total = sum(b.values()) or 1.0
        return {k: v / total for k, v in b.items()}


def build_roofline(
    mc, quant: str | None, kv_dtype: str, hbm_bw: float = HBM_BW_BYTES,
) -> RooflineModel:
    wb = weight_bytes_per_elem(quant)
    ph = phase_ideal_bytes(mc, 1, 0, quant, kv_dtype)
    return RooflineModel(
        param_bytes=float(param_bytes(mc, quant)),
        kv_bytes_per_token=kv_bytes_per_token(mc, kv_dtype),
        mlp_bytes=float(ph["mlp"]),
        lm_head_bytes=float(ph["lm_head"]),
        sampling_bytes_per_row=float(mc.vocab_size * 4),
        hbm_bw=hbm_bw,
    )
