"""SLO attainment + goodput tracking for per-request latency targets.

The paper's serving claims are *tail*-latency claims: a deployment is
healthy when requests meet their TTFT/ITL targets, not when mean
throughput looks fine — and the Planner should scale on the fraction of
requests that actually met their targets (goodput), not raw tokens
(PAPERS.md: Orca/vLLM show batch composition trades throughput against
ITL directly). This module turns per-request TTFT/ITL measurements into:

- ``dynamo_request_ttft_seconds`` / ``dynamo_request_itl_seconds``
  histograms (always on — the raw distributions);
- ``dynamo_slo_attainment`` — rolling fraction of recent requests that
  met BOTH configured targets (windowed over the last ``window``
  requests, bounded by construction);
- ``dynamo_goodput_tokens_total`` — completion tokens from requests
  that met their SLO (the Planner's scaling signal);
- ``dynamo_slo_requests_total{outcome}`` — met/missed counts.

Targets come from ``--slo-ttft-ms`` / ``--slo-itl-ms``
(EngineConfig.slo_ttft_ms / slo_itl_ms); with no targets set the
tracker records distributions only and reports attainment 1.0.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Optional

from dynamo_tpu.telemetry.instruments import (
    GOODPUT_TOKENS,
    REQUEST_ITL_SECONDS,
    REQUEST_TTFT_SECONDS,
    SLO_ATTAINMENT,
    SLO_REQUESTS,
)


@dataclass(frozen=True)
class SloConfig:
    """Latency targets; None disables that half of the check."""

    ttft_ms: Optional[float] = None
    itl_ms: Optional[float] = None

    @property
    def enabled(self) -> bool:
        return self.ttft_ms is not None or self.itl_ms is not None

    def to_dict(self) -> dict:
        return {"ttft_ms": self.ttft_ms, "itl_ms": self.itl_ms}


def aggregate_slo(metrics) -> tuple[float, float]:
    """Fleet rollup over an iterable of ForwardPassMetrics-shaped
    objects: (attainment mean over workers that EVALUATE targets,
    goodput token sum). One implementation for both consumers (the
    metrics service's ``llm_*`` gauges and the Planner's snapshot) so
    the two can't diverge. Workers without targets report a constant
    1.0 that would dilute the mean — they're excluded; with none
    reporting, attainment is 1.0."""
    attain: list[float] = []
    goodput = 0.0
    for m in metrics:
        if getattr(m, "slo_enabled", False):
            attain.append(getattr(m, "slo_attainment", 1.0))
        goodput += float(getattr(m, "goodput_tokens_total", 0))
    return (sum(attain) / len(attain) if attain else 1.0), goodput


class SloTracker:
    """Rolling SLO attainment over the last ``window`` finished requests.

    Thread-safety: ``observe()`` runs on the engine thread (request
    finish), readers (debug snapshot, stats publisher) on the event
    loop — the outcome window mutates behind a lock.
    """

    def __init__(self, config: Optional[SloConfig] = None, window: int = 512):
        self.config = config or SloConfig()
        self._outcomes: deque = deque(maxlen=max(1, window))
        self._lock = threading.Lock()
        self.requests_seen = 0
        self.requests_met = 0
        self.goodput_tokens = 0

    def observe(
        self,
        ttft_s: Optional[float],
        itl_s: Optional[float],
        completion_tokens: int = 0,
    ) -> bool:
        """Record one finished request. ``itl_s`` is the request's mean
        inter-token latency (None for single-token generations — the
        ITL target then doesn't apply). Returns whether the request met
        every configured target."""
        if ttft_s is not None:
            REQUEST_TTFT_SECONDS.observe(ttft_s)
        if itl_s is not None:
            REQUEST_ITL_SECONDS.observe(itl_s)
        met = True
        if self.config.ttft_ms is not None and ttft_s is not None:
            met = met and ttft_s * 1e3 <= self.config.ttft_ms
        if self.config.itl_ms is not None and itl_s is not None:
            met = met and itl_s * 1e3 <= self.config.itl_ms
        if not self.config.enabled:
            return met
        with self._lock:
            self._outcomes.append(bool(met))
            self.requests_seen += 1
            if met:
                self.requests_met += 1
                self.goodput_tokens += int(completion_tokens)
            attainment = sum(self._outcomes) / len(self._outcomes)
        SLO_REQUESTS.labels("met" if met else "missed").inc()
        if met and completion_tokens:
            GOODPUT_TOKENS.inc(completion_tokens)
        SLO_ATTAINMENT.set(attainment)
        return met

    def note_shed(self) -> None:
        """Score an admission-shed request as an SLO miss in the rolling
        window. Without this the attainment signal only sees requests the
        fleet chose to serve, so under sustained overload the admission
        controller sheds load while attainment reads ~1.0 and the
        Planner's SLO-breach scale-up never fires — the fleet rejects its
        way to a perfect score. Shed requests carry no TTFT/ITL sample
        (they never ran), so the histograms are untouched."""
        if not self.config.enabled:
            return
        with self._lock:
            self._outcomes.append(False)
            self.requests_seen += 1
            attainment = sum(self._outcomes) / len(self._outcomes)
        SLO_REQUESTS.labels("shed").inc()
        SLO_ATTAINMENT.set(attainment)

    @property
    def attainment(self) -> float:
        """Rolling attainment over the window (1.0 when no targets are
        configured or nothing finished yet)."""
        with self._lock:
            if not self._outcomes:
                return 1.0
            return sum(self._outcomes) / len(self._outcomes)

    def stats(self) -> dict:
        with self._lock:
            window_len = len(self._outcomes)
            window_met = sum(self._outcomes)
        return {
            "targets": self.config.to_dict(),
            "enabled": self.config.enabled,
            "attainment": (window_met / window_len) if window_len else 1.0,
            "window": window_len,
            "requests_seen": self.requests_seen,
            "requests_met": self.requests_met,
            "goodput_tokens_total": self.goodput_tokens,
        }
