"""Spans + trace-context propagation for the disaggregated serving path.

Analogue of the reference's ``tracing``-subscriber spans (reference:
lib/runtime/src/logging.rs span layers): every request produces ONE
connected trace through HTTP frontend → preprocessor → router → worker
→ engine → disagg prefill → KV transfer, joined by a ``trace_id`` that
rides the existing transport (runtime/service.py ``ctx`` wire dict and
disagg/protocols.py ``RemotePrefillRequest.trace``).

Design constraints (ISSUE 2 acceptance: bench throughput within noise):

- **No exporter ⇒ near-zero cost.** ``Tracer.enabled`` is a plain bool
  checked before any span allocation; the disabled path returns the
  shared ``NULL_SPAN`` singleton whose methods are no-ops.
- **Dependency-free.** Stdlib only; JSONL lines are plain dicts.
- **Thread-safe export.** The engine step thread and the asyncio loop
  both finish spans; exporters serialize behind one lock.

Timing model: ``start`` is wall-clock (``time.time()``) so spans from
different processes on one machine order/nest correctly; ``duration_s``
is measured on the monotonic clock so it never goes negative under NTP
slew. ``Tracer.record()`` builds a span from explicit timestamps for
code that only learns span boundaries after the fact (the engine emits
queue-wait/prefill/decode spans at finish time from scheduler stamps).

Env knobs:
  DYN_TRACE_FILE    append finished spans as JSONL here (enables tracing)
  DYN_TRACE_SAMPLE  root-trace sampling fraction in [0, 1] (default 1.0);
                    a propagated inbound context is always recorded — the
                    head made the sampling decision for the whole trace
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
import uuid
from typing import Any, Optional

log = logging.getLogger("dynamo_tpu.telemetry")


def new_trace_id() -> str:
    return uuid.uuid4().hex  # 32 hex chars (128-bit), W3C-sized


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]  # 16 hex chars (64-bit)


class Span:
    """One timed operation. Create via ``Tracer.span()``; finish with
    ``end()`` or a ``with`` block. Attributes must be scalar-ish (they
    land in JSONL verbatim)."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start",
        "duration_s", "attrs", "_t0", "_tracer", "_ended",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        trace_id: str,
        parent_id: Optional[str],
        attrs: Optional[dict] = None,
    ):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.start = time.time()
        self._t0 = time.monotonic()
        self.duration_s: Optional[float] = None
        self.attrs: dict = dict(attrs) if attrs else {}
        self._ended = False

    # -- recording ---------------------------------------------------------
    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def end(self) -> None:
        if self._ended:
            return
        self._ended = True
        self.duration_s = time.monotonic() - self._t0
        self._tracer._export(self)

    # -- propagation -------------------------------------------------------
    def trace_context(self) -> dict:
        """The dict that rides the wire to link downstream spans."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def to_dict(self) -> dict:
        out = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "start": self.start,
            "duration_s": self.duration_s,
        }
        if self.parent_id:
            out["parent_id"] = self.parent_id
        if self.attrs:
            out["attrs"] = self.attrs
        return out

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None and "error" not in self.attrs:
            self.attrs["error"] = exc_type.__name__
        self.end()


class _NullSpan:
    """Shared no-op span: the disabled-tracing fast path. Carries no
    identity, exports nothing, propagates nothing."""

    __slots__ = ()
    name = ""
    trace_id = ""
    span_id = ""
    parent_id = None
    duration_s = None
    attrs: dict = {}

    def set_attr(self, key: str, value: Any) -> None:
        pass

    def end(self) -> None:
        pass

    def trace_context(self) -> Optional[dict]:
        return None

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass

    def __bool__(self) -> bool:
        return False


NULL_SPAN = _NullSpan()


class JsonlSpanExporter:
    """One JSON object per finished span, appended to a file. The file
    handle opens lazily (first span) so merely constructing a tracer
    never touches the filesystem."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._fh = None

    def export(self, span: Span) -> None:
        line = json.dumps(span.to_dict()) + "\n"
        with self._lock:
            if self._fh is None:
                self._fh = open(self.path, "a")
            self._fh.write(line)
            self._fh.flush()  # spans must survive SIGTERM'd fleets

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


class Tracer:
    """Process-local span factory + exporter fan-out.

    ``enabled`` is the cheap gate callers may consult before computing
    span attributes; ``span()`` itself also degrades to ``NULL_SPAN``
    when disabled, so un-gated call sites stay correct (just marginally
    less cheap).
    """

    def __init__(self, sample: Optional[float] = None):
        self._exporters: list = []
        self._lock = threading.Lock()
        if sample is None:
            try:
                sample = float(os.environ.get("DYN_TRACE_SAMPLE", "1.0"))
            except ValueError:
                sample = 1.0
        self.sample = min(1.0, max(0.0, sample))

    @property
    def enabled(self) -> bool:
        return bool(self._exporters)

    def add_exporter(self, exporter: Any) -> None:
        with self._lock:
            self._exporters.append(exporter)

    def remove_exporter(self, exporter: Any) -> None:
        with self._lock:
            if exporter in self._exporters:
                self._exporters.remove(exporter)

    # -- span creation -----------------------------------------------------
    def span(
        self,
        name: str,
        parent: Any = None,
        attrs: Optional[dict] = None,
    ):
        """Start a span.

        ``parent`` may be a ``Span``, a trace-context dict
        (``{"trace_id", "span_id"}``), anything exposing
        ``trace_context()`` (e.g. runtime ``Context``), or None for a
        new root. Roots are subject to sampling; spans continuing an
        inbound context are always recorded (the head sampled for the
        whole trace), and an inbound ``{"sampled": False}`` mark —
        the head's negative decision — suppresses the span here too
        rather than starting an orphan root.
        """
        if not self._exporters:
            return NULL_SPAN
        ctx = _as_trace_context(parent)
        if ctx is _SAMPLED_OUT:
            return NULL_SPAN
        if ctx is None:
            if self.sample < 1.0 and random.random() >= self.sample:
                return NULL_SPAN
            return Span(self, name, new_trace_id(), None, attrs)
        return Span(self, name, ctx["trace_id"], ctx.get("span_id"), attrs)

    def record(
        self,
        name: str,
        start: float,
        duration_s: float,
        parent: Any = None,
        attrs: Optional[dict] = None,
    ) -> Optional[str]:
        """Record a span whose boundaries are already known (explicit
        wall-clock start + duration). Returns its span_id, or None when
        tracing is disabled/unsampled."""
        if not self._exporters:
            return None
        ctx = _as_trace_context(parent)
        if ctx is _SAMPLED_OUT:
            return None
        if ctx is None and self.sample < 1.0 and random.random() >= self.sample:
            return None
        span = Span.__new__(Span)
        span._tracer = self
        span.name = name
        span.trace_id = ctx["trace_id"] if ctx else new_trace_id()
        span.span_id = new_span_id()
        span.parent_id = ctx.get("span_id") if ctx else None
        span.start = start
        span._t0 = 0.0
        span.duration_s = max(0.0, duration_s)
        span.attrs = dict(attrs) if attrs else {}
        span._ended = True
        self._export(span)
        return span.span_id

    def _export(self, span: Span) -> None:
        for exporter in self._exporters:
            try:
                exporter.export(span)
            except Exception:  # a broken sink must not fail the request
                log.exception("span exporter failed")


# sentinel: the trace head explicitly sampled this request OUT
_SAMPLED_OUT: dict = {"sampled": False}


def _as_trace_context(parent: Any) -> Optional[dict]:
    if parent is None:
        return None
    if isinstance(parent, Span):
        return parent.trace_context()
    if isinstance(parent, _NullSpan):
        return None
    if isinstance(parent, dict):
        ctx = parent
    else:
        tc = getattr(parent, "trace_context", None)
        if not callable(tc):
            return None
        ctx = tc()
    if not ctx:
        return None
    if ctx.get("sampled") is False:
        return _SAMPLED_OUT
    return ctx if ctx.get("trace_id") else None


# -- process-global tracer (≈ tracing's global subscriber) ------------------
_TRACER: Optional[Tracer] = None
_TRACER_LOCK = threading.Lock()


def get_tracer() -> Tracer:
    """The process tracer. First call wires the ``DYN_TRACE_FILE`` JSONL
    exporter if the env var is set; without it the tracer stays disabled
    (every span is ``NULL_SPAN``)."""
    global _TRACER
    if _TRACER is None:
        with _TRACER_LOCK:
            if _TRACER is None:
                tracer = Tracer()
                path = os.environ.get("DYN_TRACE_FILE")
                if path:
                    tracer.add_exporter(JsonlSpanExporter(path))
                _TRACER = tracer
    return _TRACER


def reset_tracer() -> None:
    """Drop the global tracer (tests re-read DYN_TRACE_FILE)."""
    global _TRACER
    with _TRACER_LOCK:
        _TRACER = None


def propagation_context(span: Any, inbound: Any = None) -> Optional[dict]:
    """The trace dict to ship downstream from a boundary — the ONE
    implementation of the propagation rules every traced hop needs:

    - a real local span → its context (downstream nests under it);
    - a NULL local span with an inbound context → the inbound dict
      passed through verbatim (a hop without its own exporter must not
      break continuity; an inbound ``{"sampled": False}`` mark keeps
      propagating);
    - a NULL local span, no inbound, local tracer enabled → we are the
      trace head and sampling dropped the root: propagate the explicit
      negative mark so downstream tracers stay quiet;
    - tracing disabled everywhere → None (no decision was made).

    ``inbound`` may be a trace dict, a runtime ``Context``, or anything
    exposing ``trace_context()``.
    """
    ctx = span.trace_context() if span is not None else None
    if ctx:
        return ctx
    if inbound is not None:
        if isinstance(inbound, dict):
            in_ctx = inbound
        else:
            tc = getattr(inbound, "trace_context", None)
            in_ctx = tc() if callable(tc) else None
        if in_ctx:
            return in_ctx
    if get_tracer().enabled:
        return {"sampled": False}
    return None
