"""Tokenizer wrapper + incremental streaming detokenizer.

Analogue of the reference's tokenizer layer (reference:
lib/llm/src/tokenizers.rs, tokenizers/hf.rs — HF tokenizer wrapper, and
backend.rs Decoder/DecodeStream — incremental detokenization).

``DecodeStream`` implements the standard streaming-detok algorithm used
across open-source servers: keep a window [prefix_offset, read_offset) of
already-emitted ids; on each new token decode the extended window and emit
only the textual suffix, holding back while the tail decodes to an
incomplete UTF-8 sequence (the U+FFFD replacement char).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

from tokenizers import Tokenizer as _HfTokenizer

REPLACEMENT_CHAR = "�"

_U2B: Optional[dict] = None


def _unicode_to_byte() -> dict:
    """Inverse of GPT-2's bytes_to_unicode: the printable-unicode
    alphabet byte-level BPE vocabularies are written in."""
    global _U2B
    if _U2B is None:
        bs = (
            list(range(ord("!"), ord("~") + 1))
            + list(range(0xA1, 0xAC + 1))
            + list(range(0xAE, 0xFF + 1))
        )
        cs = bs[:]
        n = 0
        for b in range(256):
            if b not in bs:
                bs.append(b)
                cs.append(256 + n)
                n += 1
        _U2B = {chr(c): b for b, c in zip(bs, cs)}
    return _U2B


class Tokenizer:
    """Thin wrapper over a HuggingFace `tokenizers` fast tokenizer."""

    def __init__(self, inner: _HfTokenizer):
        self._tok = inner

    @classmethod
    def from_file(cls, path: str) -> "Tokenizer":
        """Load from a tokenizer.json file or a model directory."""
        if os.path.isdir(path):
            path = os.path.join(path, "tokenizer.json")
        return cls(_HfTokenizer.from_file(path))

    def encode(self, text: str, add_special_tokens: bool = False) -> list[int]:
        return self._tok.encode(text, add_special_tokens=add_special_tokens).ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = False) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)

    def token_to_id(self, token: str) -> Optional[int]:
        return self._tok.token_to_id(token)

    def id_to_token(self, id_: int) -> Optional[str]:
        return self._tok.id_to_token(id_)

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    def token_bytes(self, id_: int) -> bytes:
        """The RAW bytes one token contributes to the output stream —
        what OpenAI's logprob ``bytes`` field carries so clients can
        reassemble partial-UTF-8 tokens (decode([id]) alone yields
        U+FFFD for a token holding an incomplete multi-byte sequence).
        Byte-level BPE tokens map back through the GPT-2 unicode<->byte
        table; SentencePiece pieces map their word-boundary marker to a
        space; anything else falls back to the decoded text's UTF-8."""
        tok = self.id_to_token(id_)
        if tok is None:
            return self.decode([id_]).encode("utf-8")
        table = _unicode_to_byte()
        if all(ch in table for ch in tok):
            return bytes(table[ch] for ch in tok)
        if "▁" in tok:  # SentencePiece ▁ word boundary
            return tok.replace("▁", " ").encode("utf-8")
        return self.decode([id_]).encode("utf-8")

    def special_token_ids(self) -> set[int]:
        return {
            tok_id
            for tok_id, added in self._tok.get_added_tokens_decoder().items()
            if added.special
        }

    def decode_stream(self, skip_special_tokens: bool = True) -> "DecodeStream":
        return DecodeStream(self, skip_special_tokens=skip_special_tokens)


class DecodeStream:
    """Incremental detokenizer for one sequence."""

    def __init__(self, tokenizer: Tokenizer, skip_special_tokens: bool = True):
        self._tok = tokenizer
        self._skip_special = skip_special_tokens
        self.ids: list[int] = []
        self.prefix_offset = 0
        self.read_offset = 0

    def step(self, token_id: int) -> Optional[str]:
        """Feed one token id; returns newly-decodable text or None."""
        self.ids.append(int(token_id))
        prefix_text = self._tok.decode(
            self.ids[self.prefix_offset : self.read_offset],
            skip_special_tokens=self._skip_special,
        )
        new_text = self._tok.decode(
            self.ids[self.prefix_offset :], skip_special_tokens=self._skip_special
        )
        if new_text.endswith(REPLACEMENT_CHAR):
            # tail is an incomplete multi-byte sequence; hold back
            return None
        if len(new_text) > len(prefix_text):
            out = new_text[len(prefix_text) :]
            self.prefix_offset = self.read_offset
            self.read_offset = len(self.ids)
            return out
        self.read_offset = len(self.ids)
        return None

    def extend(self, token_ids: Sequence[int]) -> str:
        """Feed many ids, returning all newly-decodable text."""
        parts = [self.step(t) for t in token_ids]
        return "".join(p for p in parts if p)
