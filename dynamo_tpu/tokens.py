"""Token blocks and chained content hashing.

TPU-native analogue of the reference's token sequence machinery
(reference: lib/llm/src/tokens.rs:46-830 — ``Tokens``, ``TokenBlock``,
``PartialTokenBlock``, ``TokenBlockSequence`` with chained xxh3 sequence
hashes). The hashes here are the currency of the whole KV system: the KV
router's radix indexer, the block manager's reuse pools, and the KV event
plane all key on ``(block_hash, sequence_hash)`` pairs.

Design notes (deliberately different from the reference where it helps):
- Hashing is vectorised over numpy buffers; a whole prompt is hashed in one
  pass per block rather than token-at-a-time.
- ``SequenceHash`` chaining: ``seq_hash[i] = xxh3_64(u64le(seq_hash[i-1]) ||
  u64le(block_hash[i]))`` with the first block seeded by the salt. This keeps
  the "same prefix ⇒ same chained hash" property the radix tree needs.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Iterable, Sequence

import numpy as np
import xxhash

# Salt seed for all block hashes. The reference salts its xxh3 hashes too
# (lib/llm/src/tokens.rs: compute_hash_v2 w/ salt) so that unrelated
# deployments don't collide in shared infrastructure.
DEFAULT_SALT: int = 0x5D1_7B0_057  # "dynamo-tpu" default salt seed

TokenId = int


def compute_block_hash(tokens: Sequence[int] | np.ndarray, salt: int = DEFAULT_SALT) -> int:
    """Content hash of one block of token ids (u32 little-endian buffer)."""
    arr = np.ascontiguousarray(np.asarray(tokens, dtype=np.uint32))
    return xxhash.xxh3_64_intdigest(arr.tobytes(), seed=salt)


def chain_hash(parent_seq_hash: int | None, block_hash: int, salt: int = DEFAULT_SALT) -> int:
    """Chained sequence hash: parent ∘ block → new sequence hash."""
    if parent_seq_hash is None:
        return xxhash.xxh3_64_intdigest(struct.pack("<Q", block_hash), seed=salt)
    return xxhash.xxh3_64_intdigest(
        struct.pack("<QQ", parent_seq_hash, block_hash), seed=salt
    )


def compute_block_hashes_for_seq(
    tokens: Sequence[int] | np.ndarray, block_size: int, salt: int = DEFAULT_SALT
) -> list[int]:
    """Block hashes for every *complete* block of a token sequence.

    Analogue of the reference's ``compute_block_hash_for_seq``
    (lib/llm/src/kv_router/indexer.rs:122) — used when routing a new request:
    the router hashes the prompt into block hashes and walks the radix tree.
    """
    arr = np.ascontiguousarray(np.asarray(tokens, dtype=np.uint32))
    n_blocks = len(arr) // block_size
    return [
        compute_block_hash(arr[i * block_size : (i + 1) * block_size], salt)
        for i in range(n_blocks)
    ]


def compute_seq_hashes(block_hashes: Iterable[int], salt: int = DEFAULT_SALT) -> list[int]:
    """Chained sequence hashes for a list of block hashes."""
    out: list[int] = []
    parent: int | None = None
    for bh in block_hashes:
        parent = chain_hash(parent, bh, salt)
        out.append(parent)
    return out


def hash_sequence(
    tokens: Sequence[int] | np.ndarray, block_size: int, salt: int = DEFAULT_SALT
) -> tuple[list[int], list[int]]:
    """(block_hashes, seq_hashes) for every complete block, in one pass.

    The batch entry point used on the routing hot path. Dispatches to the
    native C++ tier (native/src/hash.cc — the analogue of the reference's
    rayon-parallel dynamo-tokens crate, lib/tokens/src/lib.rs) when built,
    bit-identical to the pure-Python fallback.
    """
    from dynamo_tpu import native

    if native.is_available():
        res = native.hash_sequence(tokens, block_size, salt)
        if res is not None:
            bh, sh = res
            return [int(x) for x in bh], [int(x) for x in sh]
    block_hashes = compute_block_hashes_for_seq(tokens, block_size, salt)
    return block_hashes, compute_seq_hashes(block_hashes, salt)


@dataclass(frozen=True)
class TokenBlock:
    """An immutable, complete block of ``block_size`` tokens.

    ``sequence_hash`` identifies the whole prefix ending at this block;
    ``block_hash`` identifies only this block's contents.
    (reference: lib/llm/src/tokens.rs TokenBlock)
    """

    tokens: tuple[int, ...]
    block_hash: int
    sequence_hash: int
    parent_sequence_hash: int | None

    @property
    def block_size(self) -> int:
        return len(self.tokens)


@dataclass
class PartialTokenBlock:
    """The mutable tail block of a sequence; commits into a TokenBlock."""

    block_size: int
    salt: int = DEFAULT_SALT
    tokens: list[int] = field(default_factory=list)
    parent_sequence_hash: int | None = None

    def push(self, token: int) -> TokenBlock | None:
        """Append one token; returns a completed TokenBlock when full."""
        self.tokens.append(int(token))
        if len(self.tokens) == self.block_size:
            return self._commit()
        return None

    def _commit(self) -> TokenBlock:
        bh = compute_block_hash(self.tokens, self.salt)
        sh = chain_hash(self.parent_sequence_hash, bh, self.salt)
        block = TokenBlock(
            tokens=tuple(self.tokens),
            block_hash=bh,
            sequence_hash=sh,
            parent_sequence_hash=self.parent_sequence_hash,
        )
        self.tokens = []
        self.parent_sequence_hash = sh
        return block

    def __len__(self) -> int:
        return len(self.tokens)


class TokenBlockSequence:
    """A token sequence chunked into hashed blocks + a partial tail.

    Supports append/extend/truncate/unwind like the reference
    (lib/llm/src/tokens.rs TokenBlockSequence). Truncation rebuilds the
    partial tail from the kept tokens; block hashes for the kept complete
    blocks are unchanged (content-addressed).
    """

    def __init__(
        self,
        tokens: Sequence[int] | None = None,
        block_size: int = 16,
        salt: int = DEFAULT_SALT,
    ):
        if block_size <= 0:
            raise ValueError(f"block_size must be positive, got {block_size}")
        self.block_size = block_size
        self.salt = salt
        self.blocks: list[TokenBlock] = []
        self.partial = PartialTokenBlock(block_size=block_size, salt=salt)
        if tokens is not None:
            self.extend(tokens)

    # -- mutation ---------------------------------------------------------
    def append(self, token: int) -> TokenBlock | None:
        """Append a single token; returns the newly completed block, if any."""
        block = self.partial.push(token)
        if block is not None:
            self.blocks.append(block)
        return block

    def extend(self, tokens: Sequence[int]) -> list[TokenBlock]:
        """Append many tokens; returns all newly completed blocks."""
        new_blocks: list[TokenBlock] = []
        for t in tokens:
            b = self.append(t)
            if b is not None:
                new_blocks.append(b)
        return new_blocks

    def truncate(self, length: int) -> None:
        """Keep only the first ``length`` tokens."""
        if length < 0 or length > len(self):
            raise ValueError(f"truncate length {length} out of range 0..{len(self)}")
        tokens = self.all_tokens()[:length]
        n_keep = length // self.block_size
        self.blocks = self.blocks[:n_keep]
        parent = self.blocks[-1].sequence_hash if self.blocks else None
        self.partial = PartialTokenBlock(
            block_size=self.block_size, salt=self.salt, parent_sequence_hash=parent
        )
        for t in tokens[n_keep * self.block_size :]:
            self.partial.push(t)

    def unwind(self, n: int = 1) -> None:
        """Remove the last ``n`` tokens (e.g. speculative-decode rollback).

        Tail-only unwinds (the speculative common case: K staged drafts
        that never crossed a block boundary) pop straight off the
        partial block — no O(sequence) all_tokens rebuild; hashes of
        complete blocks are untouched either way (content-addressed)."""
        if n < 0 or n > len(self):
            raise ValueError(f"unwind {n} out of range 0..{len(self)}")
        if n <= len(self.partial.tokens):
            if n:
                del self.partial.tokens[-n:]
            return
        self.truncate(len(self) - n)

    # -- views ------------------------------------------------------------
    def last_token(self) -> int:
        """The final token without materializing the whole sequence
        (the speculative decode hot path reads this every step)."""
        if self.partial.tokens:
            return self.partial.tokens[-1]
        if self.blocks:
            return self.blocks[-1].tokens[-1]
        raise IndexError("empty sequence has no last token")

    def all_tokens(self) -> list[int]:
        out: list[int] = []
        for b in self.blocks:
            out.extend(b.tokens)
        out.extend(self.partial.tokens)
        return out

    def tail_tokens(self, n: int) -> list[int]:
        """The last ``n`` tokens (fewer if the sequence is shorter),
        built by walking blocks from the END — O(n), not O(sequence).
        The speculative drafter's windowed history read (a full
        all_tokens() per sequence per decode step would grow without
        bound on long contexts)."""
        if n <= 0:
            return []
        # collect chunks walking backwards, flatten ONCE at the end —
        # repeated list prepends would be O(n^2 / block_size)
        chunks: list = [self.partial.tokens[-n:]]
        got = len(chunks[0])
        for b in reversed(self.blocks):
            if got >= n:
                break
            take = min(n - got, len(b.tokens))
            chunks.append(b.tokens[-take:])
            got += take
        out: list[int] = []
        for c in reversed(chunks):
            out.extend(c)
        return out

    def block_hashes(self) -> list[int]:
        return [b.block_hash for b in self.blocks]

    def sequence_hashes(self) -> list[int]:
        return [b.sequence_hash for b in self.blocks]

    @property
    def num_complete_blocks(self) -> int:
        return len(self.blocks)

    def __len__(self) -> int:
        return len(self.blocks) * self.block_size + len(self.partial)

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"TokenBlockSequence(len={len(self)}, blocks={len(self.blocks)}, "
            f"partial={len(self.partial)}, block_size={self.block_size})"
        )
