"""Thread-affinity declarations + optional runtime sanitizer.

dynamo-tpu has three concurrency domains (docs/static_analysis.md):

- ``"engine"``  — the dedicated jax step-loop thread (`engine/engine.py`)
- ``"loop"``    — the asyncio event loop the frontend/runtime run on
- ``"planner"`` — the planner control loop / watcher tasks

State that crosses a domain boundary must go through a declared handoff
(a queue, ``call_soon_threadsafe``, ``run_coroutine_threadsafe``, a
lock, or an explicit marker). This module is the *declaration
vocabulary* both enforcement planes share:

Static plane: :func:`thread_affinity` tags a function/method/class with
its home domain; dynalint's whole-program taint pass
(``analysis/taint.py``) seeds thread-affinity propagation from these
tags and DL103 flags undeclared cross-domain attribute writes.

Runtime plane (``DYN_AFFINITY_CHECK=1``): :func:`register_thread` binds
the calling thread to a domain, :func:`guard_attrs` arms an object's
attributes so a write from a thread bound to a *different* domain
raises :class:`AffinityViolation` — naming the writing thread, the
owning domain's thread, and the attribute — unless the write happens
inside a :func:`handoff` block. Catches the violations static analysis
can't see (dynamic dispatch, getattr-driven writes, third-party
callbacks). Disabled (the default) everything here is inert: the
decorator only stamps metadata and ``guard_attrs`` is a no-op, so the
serving hot path pays nothing.
"""

from __future__ import annotations

import functools
import logging
import os
import threading
from typing import Any, Callable, Dict, Optional, TypeVar

log = logging.getLogger("dynamo_tpu.utils.affinity")

DOMAINS = ("engine", "loop", "planner")

F = TypeVar("F", bound=Callable)


class AffinityViolation(RuntimeError):
    """A cross-domain write (or call) outside a declared handoff."""


# -- enablement -----------------------------------------------------------

_enabled: Optional[bool] = None


def enabled() -> bool:
    """True when the runtime sanitizer is armed (``DYN_AFFINITY_CHECK=1``
    or :func:`set_enabled`). Evaluated lazily so tests can flip the env
    var before constructing the objects they want guarded."""
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get("DYN_AFFINITY_CHECK", "") == "1"
    return _enabled


def set_enabled(value: Optional[bool]) -> None:
    """Test hook: force the sanitizer on/off; ``None`` re-reads the env."""
    global _enabled
    _enabled = value


# -- thread <-> domain registry ------------------------------------------

_registry_lock = threading.Lock()
_thread_domain: Dict[int, str] = {}  # thread ident -> domain
_domain_thread: Dict[str, str] = {}  # domain -> last registered thread name


def register_thread(domain: str, *, thread: Optional[threading.Thread] = None) -> None:
    """Bind ``thread`` (default: the calling thread) to ``domain``.

    Call this where a domain's loop starts — the engine thread's run
    loop, the asyncio entrypoint, the planner control loop. Rebinding
    the same thread is allowed (a process may restart its engine);
    idents of exited threads are reaped opportunistically."""
    if domain not in DOMAINS:
        raise ValueError(f"unknown affinity domain {domain!r} (known: {DOMAINS})")
    t = thread or threading.current_thread()
    with _registry_lock:
        _thread_domain[t.ident] = domain
        _domain_thread[domain] = t.name


def unregister_thread(thread: Optional[threading.Thread] = None) -> None:
    """Unbind a thread (call when a domain loop exits — OS thread idents
    are reused, and a stale binding would mis-attribute later writes)."""
    t = thread or threading.current_thread()
    with _registry_lock:
        _thread_domain.pop(t.ident, None)


def current_domain() -> Optional[str]:
    """The calling thread's registered domain, or None."""
    with _registry_lock:
        return _thread_domain.get(threading.get_ident())


def domain_thread_name(domain: str) -> Optional[str]:
    with _registry_lock:
        return _domain_thread.get(domain)


def reset_registry() -> None:
    """Test hook: drop every thread/domain binding."""
    with _registry_lock:
        _thread_domain.clear()
        _domain_thread.clear()


# -- handoff grace --------------------------------------------------------

_handoff = threading.local()


class handoff:
    """Context manager sanctioning cross-domain writes in its block.

    The runtime twin of the static ``# dynalint: handoff=<why>`` comment:
    use both on a deliberate cross-thread mutation so the static rule
    and the sanitizer agree it is a declared seam.
    """

    def __init__(self, why: str):
        self.why = why

    def __enter__(self) -> "handoff":
        _handoff.depth = getattr(_handoff, "depth", 0) + 1
        return self

    def __exit__(self, *exc: Any) -> None:
        _handoff.depth -= 1


def in_handoff() -> bool:
    return getattr(_handoff, "depth", 0) > 0


# -- declarations ---------------------------------------------------------

def thread_affinity(domain: str) -> Callable[[F], F]:
    """Declare a function/method/class's home concurrency domain.

    Static: the tag seeds dynalint's affinity taint (a tagged function
    and everything it transitively calls is assumed to run on that
    domain's thread; an explicit tag on a callee overrides the caller's
    propagated domain).

    Runtime (sanitizer armed): entering a tagged *function* from a
    thread registered to a different domain raises
    :class:`AffinityViolation`. Unregistered threads pass — tests and
    one-shot setup code run wherever they run; the sanitizer only
    judges threads that declared themselves.
    """
    if domain not in DOMAINS:
        raise ValueError(f"unknown affinity domain {domain!r} (known: {DOMAINS})")

    def deco(obj: F) -> F:
        if isinstance(obj, type):
            obj.__dyn_affinity__ = domain  # type: ignore[attr-defined]
            return obj

        @functools.wraps(obj)
        def wrapper(*args: Any, **kwargs: Any):
            if enabled():
                cur = current_domain()
                if cur is not None and cur != domain and not in_handoff():
                    raise AffinityViolation(
                        f"{obj.__qualname__} is {domain!r}-affine "
                        f"(owner thread {domain_thread_name(domain)!r}) but "
                        f"was called from thread "
                        f"{threading.current_thread().name!r} registered to "
                        f"domain {cur!r}; route through a declared handoff"
                    )
            return obj(*args, **kwargs)

        wrapper.__dyn_affinity__ = domain  # type: ignore[attr-defined]
        # the undecorated function, for introspection/tests
        wrapper.__wrapped__ = obj
        return wrapper  # type: ignore[return-value]

    return deco


# -- attribute guards -----------------------------------------------------

_GUARD_ATTR = "__dyn_guarded_attrs__"
_guard_classes: Dict[type, type] = {}
_guard_classes_lock = threading.Lock()


def _guard_subclass(cls: type) -> type:
    with _guard_classes_lock:
        sub = _guard_classes.get(cls)
        if sub is None:
            def __setattr__(self: Any, name: str, value: Any) -> None:
                guards = self.__dict__.get(_GUARD_ATTR)
                if guards is not None:
                    owner = guards.get(name)
                    if owner is not None:
                        cur = current_domain()
                        if cur is not None and cur != owner and not in_handoff():
                            raise AffinityViolation(
                                f"write to {type(self).__name__}.{name} "
                                f"from thread "
                                f"{threading.current_thread().name!r} "
                                f"(domain {cur!r}) but the attribute is "
                                f"{owner!r}-affine (owner thread "
                                f"{domain_thread_name(owner)!r}); wrap the "
                                f"write in affinity.handoff(...) or route "
                                f"it through a queue/call_soon_threadsafe"
                            )
                object.__setattr__(self, name, value)

            sub = type(cls.__name__, (cls,), {
                "__setattr__": __setattr__,
                # keep repr/pickle/isinstance stories untouched
                "__module__": cls.__module__,
                "__qualname__": cls.__qualname__,
            })
            _guard_classes[cls] = sub
        return sub


def guard_attrs(obj: Any, domains_by_attr: Dict[str, str]) -> Any:
    """Arm ``obj`` so writes to the named attributes from a thread bound
    to a different domain raise :class:`AffinityViolation`.

    No-op unless the sanitizer is enabled. Implemented by rebinding the
    instance to a cached ``__setattr__``-overriding subclass, so only
    guarded *instances* pay the check and the class itself is untouched.
    Safe to call repeatedly; later calls merge more attributes."""
    if not enabled():
        return obj
    for attr, domain in domains_by_attr.items():
        if domain not in DOMAINS:
            raise ValueError(
                f"unknown affinity domain {domain!r} for attr {attr!r}"
            )
    cls = type(obj)
    if cls in _guard_classes.values():
        obj.__dict__.setdefault(_GUARD_ATTR, {}).update(domains_by_attr)
        return obj
    sub = _guard_subclass(cls)
    try:
        object.__setattr__(obj, _GUARD_ATTR,
                           {**obj.__dict__.get(_GUARD_ATTR, {}),
                            **domains_by_attr})
        obj.__class__ = sub
    except TypeError:  # __slots__/extension classes can't rebind
        log.warning("affinity guard: cannot rebind %s; attrs unguarded",
                    cls.__name__)
    return obj
