"""Capped exponential backoff with jitter.

One shared implementation for every reconnect/retry loop in the stack
(store client redial, discovery watch resubscribe, router failover),
so "retry with backoff + jitter" means the same thing everywhere and
dynalint DL008 (unbounded-retry-loop) has a recognizable idiom to
accept. Half-to-full jitter (AWS architecture-blog variant): the delay
for attempt n is uniform in [cap/2, cap] of ``base * factor**n``, which
de-synchronizes a thundering herd of reconnecting clients while keeping
a deterministic lower bound on pacing.
"""

from __future__ import annotations

import random
from typing import Optional

from dynamo_tpu.utils.clock import SYSTEM, Clock


class Backoff:
    """Stateful backoff schedule: call ``next_delay()`` (or ``sleep()``)
    per failed attempt, ``reset()`` after a success.

    ``rng`` is injectable so tests (and the seeded fault-injection
    suite) get deterministic schedules; ``clock`` is injectable so
    driven/simulated control loops (dynamo_tpu/sim) pace retries on
    virtual time instead of real sleeps.
    """

    def __init__(
        self,
        base_s: float = 0.1,
        cap_s: float = 30.0,
        factor: float = 2.0,
        rng: Optional[random.Random] = None,
        clock: Optional[Clock] = None,
    ):
        self.base_s = base_s
        self.cap_s = cap_s
        self.factor = factor
        self.attempt = 0
        self._rng = rng or random.Random()
        self._clock = clock or SYSTEM

    def next_delay(self) -> float:
        """The jittered delay for the current attempt; advances state."""
        raw = min(self.cap_s, self.base_s * (self.factor ** self.attempt))
        self.attempt += 1
        return self._rng.uniform(raw / 2.0, raw)

    async def sleep(self) -> float:
        delay = self.next_delay()
        await self._clock.sleep(delay)
        return delay

    def reset(self) -> None:
        self.attempt = 0
