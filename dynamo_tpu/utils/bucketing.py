"""Shape bucketing: round sizes up to a small set so jitted functions
compile a handful of variants and then never recompile."""

from __future__ import annotations


def next_bucket(n: int, buckets: list[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    # beyond the precomputed list: next power of two (never under-allocate)
    b = buckets[-1]
    while b < n:
        b *= 2
    return b
