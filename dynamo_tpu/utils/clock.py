"""Injectable clock: the seam that makes control loops simulatable.

Every control loop in the stack (the autoscaling Planner, admission
token buckets, retry backoff) reads time and sleeps through a ``Clock``
instead of calling ``time.monotonic()`` / ``asyncio.sleep()`` directly.
Production code passes nothing and gets :data:`SYSTEM` (real monotonic
time, real asyncio sleeps); the discrete-event fleet simulator
(``dynamo_tpu/sim``) passes its virtual clock, so scaling policy runs
against millions of simulated requests with zero real sleeps and
bit-identical replays.

dynalint DL009 (``wall-clock-in-control-loop``) enforces the seam: code
that *has* an injectable clock available must not bypass it inside its
control loops.
"""

from __future__ import annotations

import asyncio
import time
from typing import Protocol, runtime_checkable


@runtime_checkable
class Clock(Protocol):
    """What a control loop needs from time.

    - ``monotonic()`` — interval math (never compared across processes);
    - ``time()`` — wall-clock stamps for logs/snapshots (a virtual clock
      returns simulated seconds here so replays are deterministic);
    - ``sleep(s)`` — pacing (a virtual clock either advances instantly
      or refuses, depending on whether the loop is driven externally).
    """

    def monotonic(self) -> float: ...
    def time(self) -> float: ...
    async def sleep(self, seconds: float) -> None: ...


class SystemClock:
    """The real thing: ``time.monotonic``/``time.time``/``asyncio.sleep``."""

    def monotonic(self) -> float:
        return time.monotonic()

    def time(self) -> float:
        return time.time()

    async def sleep(self, seconds: float) -> None:
        await asyncio.sleep(seconds)


# process-wide default; control loops take `clock: Optional[Clock] = None`
# and fall back to this
SYSTEM = SystemClock()
