"""Serve-phase compile fence — the runtime twin of dynalint DL203.

DL203 (analysis/rules/prewarm_coverage.py) proves *statically* that
every jitted callable the step loop reaches is referenced by a prewarm
path.  What static analysis cannot prove is that prewarm fed each
callable every *signature* serving will: shapes, dtypes, shardings,
sampling-feature pytree variants.  The fence closes that gap at
runtime, in the mold of the affinity sanitizer (utils/affinity.py):
inert by default, armed by an env var, catching exactly the violations
the static plane can't see.

Armed with ``DYN_COMPILE_FENCE=1``, every XLA compile event reported by
``jax.monitoring`` (the PR-2 listener in engine/engine.py) *outside an
allowed window* is collected here.  The engine drains the pending
events once per step (``_record_step``) and escalates: one
flight-recorder ``serve_compile`` record per drain (the compile lands
on disk with the steps around it), one black-box bundle
(rate-limited), and a ``dynamo_compile_fence_events_total`` bump.
``DYN_COMPILE_FENCE=fatal`` additionally raises
:class:`CompileFenceError` from the drain site — the hard-error mode
tests use to make an unprewarmed signature impossible to miss.

The **allowed window** is a refcount: the engine's prewarm span
(``JaxEngine._initialize``) wraps itself in :func:`allow`, registering
"compiles are sanctioned now" — the same span the PR-2 phase tag calls
"prewarm".  Anything outside that window is, by definition, a
mid-serve compile: the multi-second TTFT stall the static-shape
machinery exists to prevent (docs/performance.md).

Disabled (the default), ``note_compile`` is a single boolean check —
the serving hot path pays nothing.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

_MAX_PENDING = 64  # bounded by construction (dynalint DL007)


class CompileFenceError(RuntimeError):
    """A serve-phase XLA compile under DYN_COMPILE_FENCE=fatal."""


_lock = threading.Lock()
_mode: Optional[str] = None  # None = re-read env; "off" | "record" | "fatal"
_allowed = 0  # >0: compiles sanctioned (prewarm window)
_pending: deque = deque(maxlen=_MAX_PENDING)
_since_drain = 0  # true violation count since the last drain (the
# deque bounds the *detail* kept per window, never the count — a
# retrace storm past _MAX_PENDING events must not undercount)
_events_total = 0  # lifetime count, survives drains (for /debug/state)


def _resolve_mode() -> str:
    raw = os.environ.get("DYN_COMPILE_FENCE", "").strip().lower()
    if raw in ("1", "true", "record"):
        return "record"
    if raw == "fatal":
        return "fatal"
    return "off"


def mode() -> str:
    """The fence mode ("off" | "record" | "fatal"), env-resolved lazily
    so tests can flip the variable before the engine constructs."""
    global _mode
    if _mode is None:
        _mode = _resolve_mode()
    return _mode


def enabled() -> bool:
    return mode() != "off"


def fatal() -> bool:
    return mode() == "fatal"


def set_mode(value: Optional[str]) -> None:
    """Test hook: force "off"/"record"/"fatal"; None re-reads the env."""
    global _mode
    _mode = value


@contextlib.contextmanager
def allow():
    """Sanction compiles for the duration of the block (the engine's
    prewarm window).  Re-entrant across engines: a refcount, like the
    phase tag's ``_initializing_engines``."""
    global _allowed
    with _lock:
        _allowed += 1
    try:
        yield
    finally:
        with _lock:
            _allowed -= 1


def note_compile(event: str, duration_s: float) -> None:
    """Called by the engine's jax.monitoring listener for every compile
    duration event.  Collects a violation when armed and outside an
    allowed window; never raises (the listener runs inside XLA)."""
    global _events_total, _since_drain
    if not enabled():
        return
    with _lock:
        if _allowed > 0:
            return
        _events_total += 1
        _since_drain += 1
        _pending.append(
            {
                "event": event,
                "duration_ms": round(duration_s * 1e3, 3),
                "ts": time.time(),
            }
        )


def drain() -> Tuple[List[Dict], int]:
    """Return-and-clear ``(pending events, true violation count)``
    since the last drain.  The engine calls this once per recorded
    step and escalates a non-empty result (flight-recorder record +
    black-box bundle + counter; raise under fatal mode).  The count can
    exceed ``len(events)``: the detail deque is bounded, the count is
    not, so a recompile-per-step storm reports its real size."""
    global _since_drain
    with _lock:
        out = list(_pending)
        _pending.clear()
        n = _since_drain
        _since_drain = 0
    return out, n


def stats() -> Dict:
    with _lock:
        return {
            "mode": mode(),
            "pending": len(_pending),
            "events_total": _events_total,
        }


def reset() -> None:
    """Test hook: drop pending events and the counters."""
    global _events_total, _since_drain
    with _lock:
        _pending.clear()
        _events_total = 0
        _since_drain = 0
