"""JAX platform selection helpers.

Some environments register accelerator PJRT plugins at interpreter boot;
jax initializes every registered backend on first use, which can dial
remote hardware even for CPU-only dev runs. ``force_platform("cpu")``
deregisters other factories before any backend is created.

Controlled by ``DYN_JAX_PLATFORM`` (e.g. "cpu") and
``DYN_JAX_CPU_DEVICES`` (virtual device count for sharding dev-runs).
"""

from __future__ import annotations

import os


def force_platform(platform: str, cpu_devices: int | None = None) -> None:
    """Must be called before the first JAX backend initialization."""
    if cpu_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={cpu_devices}"
            ).strip()
    import jax
    import jax._src.xla_bridge as xb

    try:
        # Pallas-TPU registers MLIR lowerings for the "tpu" platform at
        # import; that registration fails once jax_platforms is
        # restricted, so pre-import while "tpu" is still known. This
        # does not initialize any backend (no hardware is dialed).
        from jax.experimental.pallas import tpu as _pltpu  # noqa: F401
    except Exception:
        pass
    jax.config.update("jax_platforms", platform)
    if platform == "cpu":
        for name in list(getattr(xb, "_backend_factories", {})):
            if name != "cpu":
                xb._backend_factories.pop(name, None)


def configure_from_env() -> None:
    plat = os.environ.get("DYN_JAX_PLATFORM")
    if plat:
        n = os.environ.get("DYN_JAX_CPU_DEVICES")
        force_platform(plat, int(n) if n else None)


_cache_enabled = False


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Enable the JAX persistent compilation cache (idempotent).

    Compiles over a tunneled chip run ~40-300 s per jit variant; the
    engine prewarms a dozen variants at startup, so a cold start costs
    many minutes. The persistent cache makes every restart after the
    first near-instant (measured: 7.3 s -> 0.1 s per variant on the
    tunneled v5e). Disable with DYN_COMPILE_CACHE=0; relocate with
    DYN_COMPILE_CACHE=<dir>."""
    global _cache_enabled
    if _cache_enabled:
        return
    knob = os.environ.get("DYN_COMPILE_CACHE", "")
    if knob == "0":
        return
    import jax

    if knob in ("", "1"):
        # CPU backends (tests, dev runs) compile in seconds and the
        # XLA:CPU AOT cache is machine-feature-pinned (loads warn/SIGILL
        # across hosts); only the remote-chip compiles are worth
        # caching. Check the RESOLVED backend, not env vars — plain CPU
        # machines leave JAX_PLATFORMS unset.
        try:
            if jax.default_backend() == "cpu":
                return
        except Exception:
            return
    if cache_dir is None:
        if knob not in ("", "1"):
            cache_dir = knob
        else:
            # default: repo-local (next to the package) so nothing
            # outside the tree is touched
            cache_dir = os.path.join(
                os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))),
                ".jax_cache",
            )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _cache_enabled = True
    except Exception:  # unsupported jax version: cache is an optimization
        pass
