"""JAX platform selection helpers.

Some environments register accelerator PJRT plugins at interpreter boot;
jax initializes every registered backend on first use, which can dial
remote hardware even for CPU-only dev runs. ``force_platform("cpu")``
deregisters other factories before any backend is created.

Controlled by ``DYN_JAX_PLATFORM`` (e.g. "cpu") and
``DYN_JAX_CPU_DEVICES`` (virtual device count for sharding dev-runs).
"""

from __future__ import annotations

import os


def force_platform(platform: str, cpu_devices: int | None = None) -> None:
    """Must be called before the first JAX backend initialization."""
    if cpu_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={cpu_devices}"
            ).strip()
    import jax
    import jax._src.xla_bridge as xb

    try:
        # Pallas-TPU registers MLIR lowerings for the "tpu" platform at
        # import; that registration fails once jax_platforms is
        # restricted, so pre-import while "tpu" is still known. This
        # does not initialize any backend (no hardware is dialed).
        from jax.experimental.pallas import tpu as _pltpu  # noqa: F401
    except Exception:
        pass
    jax.config.update("jax_platforms", platform)
    if platform == "cpu":
        for name in list(getattr(xb, "_backend_factories", {})):
            if name != "cpu":
                xb._backend_factories.pop(name, None)


def configure_from_env() -> None:
    plat = os.environ.get("DYN_JAX_PLATFORM")
    if plat:
        n = os.environ.get("DYN_JAX_CPU_DEVICES")
        force_platform(plat, int(n) if n else None)
