"""JAX platform selection helpers + version-compat shims.

Some environments register accelerator PJRT plugins at interpreter boot;
jax initializes every registered backend on first use, which can dial
remote hardware even for CPU-only dev runs. ``force_platform("cpu")``
deregisters other factories before any backend is created.

Controlled by ``DYN_JAX_PLATFORM`` (e.g. "cpu") and
``DYN_JAX_CPU_DEVICES`` (virtual device count for sharding dev-runs).

``shard_map`` / ``pcast`` below bridge the public ``jax.shard_map`` API
(jax >= 0.6: ``axis_names=`` for partial-auto, ``check_vma=``) onto the
``jax.experimental.shard_map`` API older jax ships (``auto=`` /
``check_rep=``), so the sharded model code is written once against the
current API and still runs on the pinned environment.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional


def force_platform(platform: str, cpu_devices: int | None = None) -> None:
    """Must be called before the first JAX backend initialization."""
    if cpu_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={cpu_devices}"
            ).strip()
    import jax
    import jax._src.xla_bridge as xb

    try:
        # Pallas-TPU registers MLIR lowerings for the "tpu" platform at
        # import; that registration fails once jax_platforms is
        # restricted, so pre-import while "tpu" is still known. This
        # does not initialize any backend (no hardware is dialed).
        from jax.experimental.pallas import tpu as _pltpu  # noqa: F401
    except Exception:
        pass
    jax.config.update("jax_platforms", platform)
    if platform == "cpu":
        for name in list(getattr(xb, "_backend_factories", {})):
            if name != "cpu":
                xb._backend_factories.pop(name, None)


def configure_from_env() -> None:
    plat = os.environ.get("DYN_JAX_PLATFORM")
    if plat:
        n = os.environ.get("DYN_JAX_CPU_DEVICES")
        force_platform(plat, int(n) if n else None)


_cache_enabled = False


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Enable the JAX persistent compilation cache (idempotent).

    Compiles over a tunneled chip run ~40-300 s per jit variant; the
    engine prewarms a dozen variants at startup, so a cold start costs
    many minutes. The persistent cache makes every restart after the
    first near-instant (measured: 7.3 s -> 0.1 s per variant on the
    tunneled v5e). Disable with DYN_COMPILE_CACHE=0; relocate with
    DYN_COMPILE_CACHE=<dir>."""
    global _cache_enabled
    if _cache_enabled:
        return
    knob = os.environ.get("DYN_COMPILE_CACHE", "")
    if knob == "0":
        return
    import jax

    if knob in ("", "1"):
        # CPU backends (tests, dev runs) compile in seconds and the
        # XLA:CPU AOT cache is machine-feature-pinned (loads warn/SIGILL
        # across hosts); only the remote-chip compiles are worth
        # caching. Check the RESOLVED backend, not env vars — plain CPU
        # machines leave JAX_PLATFORMS unset.
        try:
            if jax.default_backend() == "cpu":
                return
        except Exception:
            return
    if cache_dir is None:
        if knob not in ("", "1"):
            cache_dir = knob
        else:
            # default: repo-local (next to the package) so nothing
            # outside the tree is touched
            cache_dir = os.path.join(
                os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))),
                ".jax_cache",
            )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _cache_enabled = True
    except Exception:  # unsupported jax version: cache is an optimization
        pass


def shard_map(
    f: Callable,
    *,
    mesh: Any,
    in_specs: Any,
    out_specs: Any,
    axis_names: Optional[set] = None,
    check_vma: bool = True,
) -> Callable:
    """``jax.shard_map`` with the >=0.6 keyword surface, on any jax.

    ``axis_names`` lists the *manual* mesh axes (the rest stay auto, as
    in the public API); omitted means fully manual. On older jax this
    lowers to ``jax.experimental.shard_map.shard_map`` with
    ``auto = mesh.axis_names - axis_names`` and ``check_rep=False``:
    the old rep checker predates the vma system and rejects valid
    partial-auto programs, and with it off ``pcast`` is a no-op (which
    is exactly how :func:`pcast` degrades below).
    """
    import jax

    native = getattr(jax, "shard_map", None)
    if native is not None:
        kwargs: dict[str, Any] = dict(
            mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return native(f, **kwargs)
    from jax.experimental.shard_map import shard_map as _esm

    auto = (
        frozenset(mesh.axis_names) - frozenset(axis_names)
        if axis_names is not None
        else frozenset()
    )
    return _esm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False, auto=auto,
    )


def pcast(x: Any, axis_names: Any, to: str = "varying") -> Any:
    """``jax.lax.pcast`` when jax has it; identity otherwise.

    The identity fallback is only sound because the :func:`shard_map`
    fallback above always runs with ``check_rep=False`` — without
    replication tracking there is no varying/invariant distinction for
    the cast to repair.  That soundness argument is a CHECKED contract,
    not prose: a jax new enough to ship the native ``jax.shard_map``
    (whose vma system DOES track the distinction) but missing
    ``jax.lax.pcast`` would make the identity silently wrong, so that
    combination raises instead of degrading."""
    import jax

    native = getattr(jax.lax, "pcast", None)
    if native is not None:
        return native(x, axis_names, to=to)
    if getattr(jax, "shard_map", None) is not None:
        raise RuntimeError(
            "pcast identity fallback is unsound on this jax: native "
            "jax.shard_map tracks varying/invariant (vma) but jax.lax."
            "pcast is missing, so the cast cannot be skipped silently"
        )
    return x


_partial_auto_supported: Optional[bool] = None


def partial_auto_shard_map_supported() -> bool:
    """True when this jax can lower *partial-auto* shard_map (some mesh
    axes manual, the rest auto).

    The public ``jax.shard_map`` (>= 0.6) lowers it fine; the 0.4.x
    experimental fallback emits a ``PartitionId`` instruction the XLA
    SPMD partitioner rejects with UNIMPLEMENTED ("meaning is ambiguous").
    Fully-manual shard_map (every mesh axis in ``axis_names``) works on
    both — only the mixed mode needs this probe. Tests that exercise
    pp x tp / ep x tp partial-auto meshes skip on old jax via this.
    Memoized: the jax version cannot change mid-process, and callers
    probe per plan/step."""
    global _partial_auto_supported
    if _partial_auto_supported is None:
        import jax

        _partial_auto_supported = getattr(jax, "shard_map", None) is not None
    return _partial_auto_supported
