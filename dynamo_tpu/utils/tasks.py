"""Background-task bookkeeping: strong references + crash logging.

The event loop holds only weak references to tasks; a handle that is
dropped can be garbage collected mid-flight (silently cancelling the
task), and an un-awaited task's exception is never surfaced until
interpreter shutdown prints "Task exception was never retrieved".
``spawn`` fixes both: the module-level registry keeps the task alive and
a done-callback logs any crash immediately. This is the remediation the
dropped-task-handle (DL002) lint rule points at.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Coroutine, Optional

log = logging.getLogger("dynamo_tpu.tasks")

# strong references: keeps spawned tasks alive until they finish
_BACKGROUND: set[asyncio.Task] = set()


def spawn(
    coro: Coroutine[Any, Any, Any], *, name: Optional[str] = None
) -> asyncio.Task:
    """create_task + strong reference + exception-logging done-callback.

    Use for fire-and-forget loops (watchers, pumps, reconcilers). The
    returned handle supports cancel()/await like any task; callers that
    keep their own reference lose nothing by the registry also holding
    one until completion.
    """
    task = asyncio.get_running_loop().create_task(coro, name=name)
    _BACKGROUND.add(task)
    task.add_done_callback(_finalize)
    return task


def _finalize(task: asyncio.Task) -> None:
    _BACKGROUND.discard(task)
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        log.error(
            "background task %r crashed", task.get_name(), exc_info=exc
        )


def background_count() -> int:
    """Live spawned-task count (introspection/tests)."""
    return len(_BACKGROUND)
