"""Shared fake-input builders for tests, benchmarks, and the driver dryrun.

Single source of truth for the paged-KV input convention: block 0 is the
pad/scratch block, sequence b owns blocks [1 + b*n, 1 + (b+1)*n), and
slot_mapping addresses flat cache slots block_id*block_size + offset.
"""

from __future__ import annotations

import numpy as np


def make_paged_inputs(
    vocab_size: int,
    batch: int,
    seq: int,
    block_size: int,
    n_blocks_per_seq: int,
    seed: int = 0,
):
    """Build one unified-model-step input set (prefill-shaped).

    Returns (tokens, positions, slot_mapping, block_tables, context_lens,
    last_token_idx) as numpy arrays matching models.llama.forward's contract.
    """
    B, T = batch, seq
    rng = np.random.default_rng(seed)
    tokens = rng.integers(1, vocab_size, size=(B, T)).astype(np.int32)
    positions = np.tile(np.arange(T, dtype=np.int32), (B, 1))
    tables = np.zeros((B, n_blocks_per_seq), np.int32)
    for b in range(B):
        tables[b] = np.arange(
            1 + b * n_blocks_per_seq, 1 + (b + 1) * n_blocks_per_seq,
            dtype=np.int32,
        )
    slot_mapping = np.zeros((B * T,), np.int32)
    for b in range(B):
        for j in range(T):
            slot_mapping[b * T + j] = (
                tables[b, j // block_size] * block_size + j % block_size
            )
    context_lens = np.full((B,), T, np.int32)
    last_token_idx = np.full((B,), T - 1, np.int32)
    return tokens, positions, slot_mapping, tables, context_lens, last_token_idx
