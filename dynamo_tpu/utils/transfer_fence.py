"""Serve-phase transfer fence — the runtime twin of dynalint DL301.

DL301 (analysis/rules/shard_sync.py) proves *statically* that no
device->host sync hides inside a shard_map body, and DL010/DL102 pin
host syncs to the designated harvest points.  What the static plane
cannot see is an *implicit* transfer materializing at runtime — a raw
``np.ndarray`` fed straight into a jitted step (silent host->device
upload on every dispatch), or a stray ``np.asarray`` on a device value
in a code path the call graph could not resolve.  The fence closes
that gap in the mold of the compile fence (utils/compile_fence.py):
inert by default, armed by an env var, escalated through the same
flight-recorder / black-box / Prometheus spine.

Armed with ``DYN_TRANSFER_FENCE=1``, :func:`arm` (called from
``JaxEngine._initialize``) flips JAX's global ``transfer_guard`` to
``"disallow"``: implicit transfers raise at the offending site while
explicit ``jax.device_put`` / ``jax.device_get`` stay sanctioned —
exactly the discipline the engine's dispatch/harvest split encodes.
The prewarm window wraps itself in :func:`allow` (a refcount PLUS a
thread-local ``jax.transfer_guard("allow")`` scope), because warming
legitimately uploads dummy batches.  Outside that window a violation
surfaces as a ``RuntimeError`` from XLA; the engine's step-loop
handler routes it through :func:`intercept`, which recognizes the
guard's message, records the event, and lets the engine escalate: one
flight-recorder ``serve_transfer`` record per drain, one black-box
bundle (rate-limited), one ``dynamo_transfer_fence_events_total``
bump.  ``DYN_TRANSFER_FENCE=fatal`` additionally raises
:class:`TransferFenceError` from the escalation site.

Disabled (the default), nothing is armed and every hook is a single
boolean check — the serving hot path pays nothing.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

_MAX_PENDING = 64  # bounded by construction (dynalint DL007)

# substrings the XLA transfer guard puts in its RuntimeError text; the
# jaxlib exception type is not importable portably, so intercept()
# matches on message shape instead
_GUARD_MARKERS = (
    "Disallowed host-to-device transfer",
    "Disallowed device-to-host transfer",
    "Disallowed device-to-device transfer",
)


class TransferFenceError(RuntimeError):
    """A serve-phase implicit transfer under DYN_TRANSFER_FENCE=fatal."""


_lock = threading.Lock()
_mode: Optional[str] = None  # None = re-read env; "off" | "record" | "fatal"
_allowed = 0  # >0: transfers sanctioned (prewarm window)
_armed = False  # jax_transfer_guard flipped to "disallow"
_pending: deque = deque(maxlen=_MAX_PENDING)
_since_drain = 0  # true violation count since the last drain (the
# deque bounds the *detail* kept per window, never the count)
_events_total = 0  # lifetime count, survives drains (for /debug/state)


def _resolve_mode() -> str:
    raw = os.environ.get("DYN_TRANSFER_FENCE", "").strip().lower()
    if raw in ("1", "true", "record"):
        return "record"
    if raw == "fatal":
        return "fatal"
    return "off"


def mode() -> str:
    """The fence mode ("off" | "record" | "fatal"), env-resolved lazily
    so tests can flip the variable before the engine constructs."""
    global _mode
    if _mode is None:
        _mode = _resolve_mode()
    return _mode


def enabled() -> bool:
    return mode() != "off"


def fatal() -> bool:
    return mode() == "fatal"


def set_mode(value: Optional[str]) -> None:
    """Test hook: force "off"/"record"/"fatal"; None re-reads the env."""
    global _mode
    _mode = value


def arm() -> bool:
    """Flip JAX's global transfer guard to "disallow" (idempotent).
    Called from the engine's ``_initialize`` when the fence is enabled;
    explicit device_put/device_get remain sanctioned, implicit
    transfers raise at the site.  Returns whether the guard is armed."""
    global _armed
    if not enabled():
        return False
    with _lock:
        if not _armed:
            import jax

            jax.config.update("jax_transfer_guard", "disallow")
            _armed = True
    return True


def disarm() -> None:
    """Test hook: restore the permissive guard and forget armed state."""
    global _armed
    with _lock:
        if _armed:
            import jax

            jax.config.update("jax_transfer_guard", "allow")
            _armed = False


def armed() -> bool:
    with _lock:
        return _armed


@contextlib.contextmanager
def allow():
    """Sanction transfers for the duration of the block (the engine's
    prewarm window).  Re-entrant across engines: a refcount, like the
    compile fence's — plus a thread-local ``jax.transfer_guard`` scope,
    because the global "disallow" can only be overridden per-thread."""
    global _allowed
    with _lock:
        _allowed += 1
        guard_needed = _armed
    try:
        if guard_needed:
            import jax

            with jax.transfer_guard("allow"):
                yield
        else:
            yield
    finally:
        with _lock:
            _allowed -= 1


def intercept(exc: BaseException) -> bool:
    """Recognize an XLA transfer-guard violation escaping a dispatch.

    The guard raises at the offending call site, so unlike compiles the
    violation arrives as an exception, not a monitoring event.  The
    engine's step-loop handler calls this on every caught exception:
    a match records the event (like ``note_compile``) and returns True
    so the engine escalates through ``_check_transfer_fence`` instead
    of the generic quarantine path.  Never raises."""
    global _events_total, _since_drain
    if not enabled() or not isinstance(exc, RuntimeError):
        return False
    text = str(exc)
    if not any(marker in text for marker in _GUARD_MARKERS):
        return False
    with _lock:
        if _allowed > 0:
            return False
        _events_total += 1
        _since_drain += 1
        _pending.append(
            {
                "error": text.splitlines()[0][:400],
                "ts": time.time(),
            }
        )
    return True


def drain() -> Tuple[List[Dict], int]:
    """Return-and-clear ``(pending events, true violation count)``
    since the last drain.  The engine calls this from the escalation
    site (and once per recorded step, mirroring the compile fence) and
    escalates a non-empty result."""
    global _since_drain
    with _lock:
        out = list(_pending)
        _pending.clear()
        n = _since_drain
        _since_drain = 0
    return out, n


def stats() -> Dict:
    with _lock:
        return {
            "mode": mode(),
            "armed": _armed,
            "pending": len(_pending),
            "events_total": _events_total,
        }


def reset() -> None:
    """Test hook: drop pending events and the counters."""
    global _events_total, _since_drain
    with _lock:
        _pending.clear()
        _events_total = 0
        _since_drain = 0
