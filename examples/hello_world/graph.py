"""Hello-world service graph (reference: examples/hello_world): three
chained components passing a string through, each decorating it.

Serve with:
    dynamo-tpu store &
    dynamo-tpu serve examples.hello_world.graph:Frontend
"""

from dynamo_tpu.sdk.service import depends, endpoint, service


@service(dynamo={"namespace": "hello"})
class Backend:
    @endpoint()
    async def generate(self, request):
        for word in request["text"].split():
            yield {"text": f"back.{word}"}


@service(dynamo={"namespace": "hello"})
class Middle:
    backend = depends(Backend)

    @endpoint()
    async def generate(self, request):
        async for item in self.backend.generate(request):
            yield {"text": f"mid.{item['text']}"}


@service(dynamo={"namespace": "hello"})
class Frontend:
    middle = depends(Middle)

    @endpoint()
    async def generate(self, request):
        async for item in self.middle.generate(request):
            yield {"text": f"front.{item['text']}"}
