"""LLM service graph (reference: examples/llm — Frontend→Processor→
Worker): a tokenizing processor in front of a native JAX engine worker.

Configure with MODEL_PATH (an HF-format dir or .gguf; unset = random
weights with the repo's tiny test tokenizer). Serve with:

    dynamo-tpu store &
    dynamo-tpu serve examples.llm.graph:Processor

and call the processor endpoint, or front it with
``dynamo-tpu run --in http --out dyn://llm.Processor.generate``.
"""

import os

from dynamo_tpu.sdk.service import depends, endpoint, service

MODEL_PATH = os.environ.get(
    "MODEL_PATH",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "tests", "data", "tiny_llama_model",
    ),
)


@service(dynamo={"namespace": "llm"}, resources={"tpu": 1})
class Worker:
    """Tokens-in/tokens-out native engine (reference: the vLLM worker)."""

    def __init__(self):
        self.engine = None

    async def _ensure_engine(self):
        if self.engine is None:
            from dynamo_tpu.engine import EngineConfig, JaxEngine

            self.engine = await JaxEngine.launch(
                EngineConfig(
                    model_path=MODEL_PATH,
                    model_name="llm-worker",
                    random_weights=not os.environ.get("MODEL_PATH"),
                    num_blocks=int(os.environ.get("NUM_BLOCKS", "256")),
                    block_size=16,
                    max_batch_size=8,
                )
            )
        return self.engine

    @endpoint()
    async def generate(self, request):
        from dynamo_tpu.runtime.engine import Context

        engine = await self._ensure_engine()
        async for item in engine.as_async_engine().generate(request, Context()):
            yield item.model_dump(exclude_none=True)


@service(dynamo={"namespace": "llm"})
class Processor:
    """Tokenize + detokenize around the worker (reference:
    examples/llm/components/processor.py)."""

    worker = depends(Worker)

    def __init__(self):
        from dynamo_tpu.tokenizer import Tokenizer

        self.tokenizer = Tokenizer.from_file(MODEL_PATH)

    @endpoint()
    async def generate(self, request):
        from dynamo_tpu.protocols.common import (
            PreprocessedRequest,
            SamplingOptions,
            StopConditions,
        )

        req = PreprocessedRequest(
            request_id=request.get("request_id", "example"),
            token_ids=self.tokenizer.encode(request["prompt"]),
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(
                max_tokens=int(request.get("max_tokens", 16)), ignore_eos=True
            ),
        )
        async for item in self.worker.generate(req.model_dump()):
            toks = item.get("token_ids") or []
            if toks:
                yield {"text": self.tokenizer.decode(toks), "token_ids": toks}
            if item.get("finish_reason"):
                yield {"finish_reason": item["finish_reason"]}
