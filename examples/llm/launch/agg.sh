#!/bin/sh
# Aggregated serving on one host, real processes (the CLI matrix the
# reference drives via `dynamo serve`; reference:
# examples/llm/benchmarks/README.md "aggregated baseline").
set -e
MODEL=${MODEL_PATH:?set MODEL_PATH to an HF dir or .gguf}

PIDS=""
trap 'kill $PIDS 2>/dev/null' EXIT

python -m dynamo_tpu.cli.main store --port 4222 &
PIDS="$PIDS $!"

# N identical workers behind the round-robin frontend
python -m dynamo_tpu.cli.main run \
    --in dyn://dynamo.backend.generate --out jax \
    --model-path "$MODEL" --quantization int8 --decode-steps 32 &
PIDS="$PIDS $!"

python -m dynamo_tpu.cli.main run --in http --out auto \
    --router-mode round_robin --http-port 8000
