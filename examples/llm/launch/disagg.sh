#!/bin/sh
# Disaggregated prefill/decode with KV-aware routing on one host
# (reference: examples/llm flagship path; our e2e:
# tests/test_cli_disagg_e2e.py runs exactly this wiring).
set -e
MODEL=${MODEL_PATH:?set MODEL_PATH to an HF dir or .gguf}

PIDS=""
trap 'kill $PIDS 2>/dev/null' EXIT

python -m dynamo_tpu.cli.main store --port 4222 &
PIDS="$PIDS $!"

# decode worker with disaggregation enabled: prompts longer than
# --max-local-prefill-length go to the prefill queue
python -m dynamo_tpu.cli.main run \
    --in dyn://dynamo.backend.generate --out jax \
    --model-path "$MODEL" --quantization int8 --decode-steps 32 \
    --disagg --max-local-prefill-length 512 &
PIDS="$PIDS $!"

# dedicated prefill worker consuming the queue, KV pushed to decode
python -m dynamo_tpu.cli.main run \
    --role prefill --out jax \
    --model-path "$MODEL" &
PIDS="$PIDS $!"

# KV-aware frontend
python -m dynamo_tpu.cli.main run --in http --out auto \
    --router-mode kv --http-port 8000
