"""Planner vs static fleets under the reference's sinusoidal workload —
the recorded analogue of the reference planner benchmark (reference:
docs/guides/planner_benchmark/benchmark_planner.md — planner vs a
static 2p2d baseline on a sin_synth.py workload: 1.5x request
throughput per resource at -7.4% GPU-hours).

Model: a sinusoidal offered token rate (sin_synth.py's shape) hits a
fleet of decode workers, each serving ``tokens_per_worker_tick``.
Unserved demand queues (the latency proxy). Three fleets run the SAME
workload:

- ``planner``   — the REAL Planner (driven mode) scales workers from
                  kv-load / queue signals, exactly as planner_sim.py;
- ``static-peak`` — fixed at the planner's peak grant (the
                  capacity-planning answer: meets demand, burns
                  worker-hours all night);
- ``static-mean`` — fixed at mean-load sizing (cheap, melts at peaks).

Outputs one JSON line per fleet: served tokens, goodput (served /
offered), worker-ticks (the resource-hours analogue), tokens per
worker-tick (efficiency), and peak backlog. Recorded numbers live in
benchmarks/RESULTS.md; tests/test_examples.py asserts the planner's
win holds.

    python -m examples.llm.planner_benchmark
"""

from __future__ import annotations

import asyncio
import json
import math
from dataclasses import dataclass, field


@dataclass
class FleetStats:
    name: str
    served: float = 0.0
    offered: float = 0.0
    worker_ticks: int = 0
    backlog_peak: float = 0.0
    workers_trace: list = field(default_factory=list)

    def summary(self) -> dict:
        return {
            "fleet": self.name,
            "offered_tokens": round(self.offered, 1),
            "served_tokens": round(self.served, 1),
            "goodput": round(self.served / max(1e-9, self.offered), 4),
            "worker_ticks": self.worker_ticks,
            "tokens_per_worker_tick": round(
                self.served / max(1, self.worker_ticks), 2
            ),
            "backlog_peak_tokens": round(self.backlog_peak, 1),
            "peak_workers": max(self.workers_trace or [0]),
        }


def _offered(t: int, period: int, peak_tokens: float) -> float:
    """sin_synth.py's request-rate shape, scaled to tokens/tick."""
    return peak_tokens * 0.5 * (1.0 - math.cos(2 * math.pi * t / period))


async def run_fleet(
    policy: str,
    n_ticks: int,
    period: int,
    peak_tokens: float = 1200.0,
    tokens_per_worker_tick: float = 300.0,
    fixed_workers: int = 0,
    name: str = "",
) -> FleetStats:
    """One fleet over the shared workload. ``policy`` is "planner" or
    "static" (with ``fixed_workers``); ``name`` labels the stats row."""
    from dynamo_tpu.planner import Planner, PlannerConfig

    planner = None
    if policy == "planner":
        class _Grant:
            async def add_component(self, component):
                return True

            async def remove_component(self, component):
                return True

        cfg = PlannerConfig(grace_cycles=2, min_decode=1, max_decode=8,
                            min_prefill=0, max_prefill=4)
        planner = Planner(store=None, component=None, connector=_Grant(),
                          config=cfg, decode_workers=1, prefill_workers=1)

    stats = FleetStats(name=name or policy)
    backlog = 0.0
    for t in range(n_ticks):
        offered = _offered(t, period, peak_tokens)
        workers = planner.decode_workers if planner else fixed_workers
        capacity = workers * tokens_per_worker_tick
        demand = backlog + offered
        served = min(demand, capacity)
        backlog = demand - served
        stats.offered += offered
        stats.served += served
        stats.worker_ticks += workers
        stats.backlog_peak = max(stats.backlog_peak, backlog)
        stats.workers_trace.append(workers)
        if planner:
            # the same driven-mode signals planner_sim.py synthesizes:
            # utilization of the granted fleet + queue pressure
            util = demand / max(1e-9, capacity)
            snap = {
                "kv_load_mean": min(1.0, util),
                "prefill_queue_depth": max(0.0, util - 1.0) * 8.0,
                "prefill_queue_per_worker": (
                    max(0.0, util - 1.0) * 8.0
                    / max(1, planner.prefill_workers)
                ),
                "decode_workers_reporting": float(planner.decode_workers),
                "tick": t,
            }
            await planner.make_adjustments(snap)
    return stats


async def compare(period: int = 60, cycles: float = 3.0) -> list[dict]:
    n_ticks = int(period * cycles)
    dyn = await run_fleet("planner", n_ticks, period)
    peak = max(dyn.workers_trace)
    mean = max(1, round(sum(dyn.workers_trace) / len(dyn.workers_trace)))
    static_peak = await run_fleet(
        "static", n_ticks, period, fixed_workers=peak, name="static-peak"
    )
    static_mean = await run_fleet(
        "static", n_ticks, period, fixed_workers=mean, name="static-mean"
    )
    return [s.summary() for s in (dyn, static_peak, static_mean)]


def main() -> None:
    rows = asyncio.run(compare())
    for row in rows:
        print(json.dumps(row))


if __name__ == "__main__":
    main()
