"""Planner scale-up/down under sinusoidal load — the runnable analogue
of the reference's planner benchmark (reference:
docs/guides/planner_benchmark/sin_synth.py generates a sinusoidal
request rate; its README records the planner's replica trace against
it).

This drives the REAL Planner (dynamo_tpu/planner) in driven mode: a
sinusoidal offered load produces kv-cache-usage and prefill-queue
signals, scaled down by the replicas the planner has granted (adding a
worker absorbs load), and every tick is appended to a JSONL trace:

    python -m examples.llm.planner_sim --out planner_trace.jsonl

A recorded trace ships at examples/llm/planner_trace.jsonl; live-load
equivalents drive `benchmarks/load_gen.py --rate-mode sin` at a real
frontend instead.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
from dataclasses import dataclass, field


@dataclass
class RecordingConnector:
    """Grants every adjustment and remembers the story."""

    events: list = field(default_factory=list)

    async def add_component(self, component: str) -> bool:
        self.events.append(("add", component))
        return True

    async def remove_component(self, component: str) -> bool:
        self.events.append(("remove", component))
        return True


async def simulate(
    out_path: str,
    period_ticks: int = 60,
    cycles: float = 2.0,
    peak_kv_load: float = 3.2,
    peak_queue: float = 6.0,
) -> dict:
    """One adjustment per tick (adjustment_interval collapsed for the
    simulation); returns a summary dict."""
    from dynamo_tpu.planner import Planner, PlannerConfig

    conn = RecordingConnector()
    cfg = PlannerConfig(grace_cycles=2, min_decode=1, max_decode=6,
                        min_prefill=0, max_prefill=4)
    planner = Planner(
        store=None, component=None, connector=conn, config=cfg,
        decode_workers=1, prefill_workers=1,
    )
    n_ticks = int(period_ticks * cycles)
    trace = []
    with open(out_path, "w") as fh:
        for t in range(n_ticks):
            # offered load: sinusoid in [0, 1]
            offered = 0.5 * (1.0 - math.cos(2 * math.pi * t / period_ticks))
            # each granted worker absorbs a share of the offered load
            snap = {
                "kv_load_mean": min(
                    1.0, peak_kv_load * offered / planner.decode_workers
                ),
                "prefill_queue_depth": peak_queue * offered,
                "prefill_queue_per_worker": (
                    peak_queue * offered / max(1, planner.prefill_workers)
                ),
                "decode_workers_reporting": float(planner.decode_workers),
                "tick": t,
            }
            await planner.make_adjustments(snap)
            row = {
                **snap,
                "decode_workers": planner.decode_workers,
                "prefill_workers": planner.prefill_workers,
            }
            trace.append(row)
            fh.write(json.dumps(row) + "\n")
    ups = sum(1 for e in conn.events if e[0] == "add")
    downs = sum(1 for e in conn.events if e[0] == "remove")
    return {
        "ticks": n_ticks,
        "scale_ups": ups,
        "scale_downs": downs,
        "peak_decode_workers": max(r["decode_workers"] for r in trace),
        "final_decode_workers": trace[-1]["decode_workers"],
        "events": conn.events,
    }


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default="planner_trace.jsonl")
    p.add_argument("--period-ticks", type=int, default=60)
    p.add_argument("--cycles", type=float, default=2.0)
    args = p.parse_args()
    summary = asyncio.run(
        simulate(args.out, args.period_ticks, args.cycles)
    )
    summary.pop("events")
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
