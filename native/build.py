#!/usr/bin/env python3
"""Build the native tier: native/src/*.cc -> dynamo_tpu/native/_dynamo_native.so.

Usage: python native/build.py [--force]

Finds an xxhash single-header (vendored by pyarrow/tensorflow in this image;
falls back to /usr/include) for the hashing TU. Skips the compile when the
.so is newer than every source. The framework degrades gracefully to its
pure-Python paths when the .so is absent, so this is an optimization step,
not an install requirement.
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
SRC = os.path.join(HERE, "src")
OUT = os.path.join(REPO, "dynamo_tpu", "native", "_dynamo_native.so")

SOURCES = ["hash.cc", "radix.cc", "lru.cc"]


def find_xxhash_include() -> str | None:
    candidates = []
    try:
        import pyarrow  # noqa: F401

        candidates.append(
            os.path.join(
                os.path.dirname(pyarrow.__file__), "include", "arrow", "vendored", "xxhash"
            )
        )
    except Exception:
        pass
    purelib = sysconfig.get_paths().get("purelib", "")
    candidates += [
        os.path.join(
            purelib,
            "tensorflow/include/external/com_github_grpc_grpc/third_party/xxhash",
        ),
        "/usr/include",
        "/usr/local/include",
    ]
    for c in candidates:
        if os.path.exists(os.path.join(c, "xxhash.h")):
            return c
    return None


def needs_build() -> bool:
    if not os.path.exists(OUT):
        return True
    out_mtime = os.path.getmtime(OUT)
    deps = [os.path.join(SRC, s) for s in SOURCES] + [os.path.abspath(__file__)]
    return any(os.path.getmtime(d) > out_mtime for d in deps)


def build(force: bool = False) -> bool:
    """Compile the shared library; returns True if the .so exists after."""
    if not force and not needs_build():
        return True
    inc = find_xxhash_include()
    if inc is None:
        print("native: xxhash.h not found; skipping native build", file=sys.stderr)
        return os.path.exists(OUT)
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    cmd = [
        "g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
        "-Wall", "-Wextra",
        f"-I{inc}",
        *[os.path.join(SRC, s) for s in SOURCES],
        "-o", OUT,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except FileNotFoundError:
        print("native: g++ not found; skipping native build", file=sys.stderr)
        return os.path.exists(OUT)
    except subprocess.CalledProcessError as e:
        print(f"native: build failed:\n{e.stderr}", file=sys.stderr)
        return False
    return True


STORE_SRC = os.path.join(HERE, "store", "store_server.cc")
STORE_OUT = os.path.join(REPO, "dynamo_tpu", "native", "dynamo_store")


def build_store(force: bool = False) -> bool:
    """Compile the native coordinator binary (native/store/store_server.cc
    -> dynamo_tpu/native/dynamo_store). Pure C++17, no dependencies."""
    deps = [STORE_SRC, os.path.join(HERE, "store", "msgpack.h")]
    if (
        not force
        and os.path.exists(STORE_OUT)
        and all(os.path.getmtime(STORE_OUT) > os.path.getmtime(d) for d in deps)
    ):
        return True
    os.makedirs(os.path.dirname(STORE_OUT), exist_ok=True)
    cmd = [
        "g++", "-O2", "-std=c++17", "-Wall", STORE_SRC, "-o", STORE_OUT,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except FileNotFoundError:
        print("native: g++ not found; skipping store build", file=sys.stderr)
        return os.path.exists(STORE_OUT)
    except subprocess.CalledProcessError as e:
        print(f"native: store build failed:\n{e.stderr}", file=sys.stderr)
        return False
    return True


KV_SRC = os.path.join(HERE, "store", "kv_publisher_c.cc")
KV_OUT = os.path.join(REPO, "dynamo_tpu", "native", "libdynamo_kv.so")


def build_kv_publisher(force: bool = False) -> bool:
    """Compile the C-ABI KV event publisher shared library (reference:
    lib/bindings/c — lets non-python engines emit KV events)."""
    deps = [KV_SRC, os.path.join(HERE, "store", "msgpack.h")]
    if (
        not force
        and os.path.exists(KV_OUT)
        and all(os.path.getmtime(KV_OUT) > os.path.getmtime(d) for d in deps)
    ):
        return True
    os.makedirs(os.path.dirname(KV_OUT), exist_ok=True)
    cmd = [
        "g++", "-O2", "-std=c++17", "-Wall", "-shared", "-fPIC",
        KV_SRC, "-o", KV_OUT,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except FileNotFoundError:
        print("native: g++ not found; skipping kv publisher", file=sys.stderr)
        return os.path.exists(KV_OUT)
    except subprocess.CalledProcessError as e:
        print(f"native: kv publisher build failed:\n{e.stderr}", file=sys.stderr)
        return False
    return True


if __name__ == "__main__":
    force = "--force" in sys.argv
    ok = build(force=force)
    print(f"native: {'built' if ok else 'UNAVAILABLE'} -> {OUT}")
    ok2 = build_store(force=force)
    print(f"native: {'built' if ok2 else 'UNAVAILABLE'} -> {STORE_OUT}")
    ok3 = build_kv_publisher(force=force)
    print(f"native: {'built' if ok3 else 'UNAVAILABLE'} -> {KV_OUT}")
    sys.exit(0 if ok and ok2 and ok3 else 1)
