// Batch token-block hashing — the native tier of dynamo_tpu.tokens.
//
// Analogue of the reference's standalone rayon-parallel token hashing crate
// (reference: lib/tokens/src/lib.rs — dynamo-tokens) and the chained xxh3
// block/sequence hashing in lib/llm/src/tokens.rs. Bit-for-bit compatible
// with the pure-Python path (dynamo_tpu/tokens.py): block hash =
// xxh3_64(i32-LE token bytes, seed=salt); sequence hash chain =
// xxh3_64(u64-LE(parent) || u64-LE(block), seed=salt), first link omits the
// parent. Block hashes are independent, so they parallelize across a small
// thread pool; the chain walk is a trivial sequential pass over 16-byte
// inputs.

#define XXH_INLINE_ALL
#include "xxhash.h"

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

inline void le64(uint64_t v, uint8_t* out) {
  for (int i = 0; i < 8; ++i) out[i] = static_cast<uint8_t>(v >> (8 * i));
}

void hash_block_range(const int32_t* tokens, size_t block_size, uint64_t salt,
                      size_t begin, size_t end, uint64_t* out_block) {
  const size_t nbytes = block_size * sizeof(int32_t);
  for (size_t b = begin; b < end; ++b) {
    // Tokens arrive as native-endian int32; the Python side hashes
    // np.int32.tobytes() which is little-endian on every platform we
    // target (the static_assert below rejects big-endian builds rather
    // than silently diverging).
    out_block[b] = XXH3_64bits_withSeed(tokens + b * block_size, nbytes, salt);
  }
}

}  // namespace

extern "C" {

// Raw xxh3 for parity tests.
uint64_t dyn_xxh3_64(const void* data, size_t len, uint64_t seed) {
  return XXH3_64bits_withSeed(data, len, seed);
}

// Hash all complete blocks of `tokens` and the chained sequence hashes.
// Returns the number of complete blocks written to both output arrays
// (callers size them to n_tokens / block_size).
size_t dyn_hash_sequence(const int32_t* tokens, size_t n_tokens,
                         size_t block_size, uint64_t salt,
                         uint64_t* out_block, uint64_t* out_seq) {
  if (block_size == 0) return 0;
  const size_t n_blocks = n_tokens / block_size;
  if (n_blocks == 0) return 0;

  // Parallel block hashes: only bother spawning threads for real batches
  // (a long prefill re-hash); decode-path calls hash one or two blocks.
  const size_t kParallelThreshold = 64;
  unsigned hw = std::thread::hardware_concurrency();
  if (n_blocks >= kParallelThreshold && hw > 1) {
    unsigned n_threads = hw > 8 ? 8 : hw;
    std::vector<std::thread> threads;
    size_t chunk = (n_blocks + n_threads - 1) / n_threads;
    for (unsigned t = 0; t < n_threads; ++t) {
      size_t begin = t * chunk;
      if (begin >= n_blocks) break;
      size_t end = begin + chunk < n_blocks ? begin + chunk : n_blocks;
      threads.emplace_back(hash_block_range, tokens, block_size, salt, begin,
                           end, out_block);
    }
    for (auto& th : threads) th.join();
  } else {
    hash_block_range(tokens, block_size, salt, 0, n_blocks, out_block);
  }

  // Sequential chain: seq[0] = H(le64(block[0])); seq[i] =
  // H(le64(seq[i-1]) || le64(block[i])).
  uint8_t buf[16];
  le64(out_block[0], buf);
  out_seq[0] = XXH3_64bits_withSeed(buf, 8, salt);
  for (size_t i = 1; i < n_blocks; ++i) {
    le64(out_seq[i - 1], buf);
    le64(out_block[i], buf + 8);
    out_seq[i] = XXH3_64bits_withSeed(buf, 16, salt);
  }
  return n_blocks;
}

// Chain continuation for incremental decode: extend an existing chain
// (parent_valid=0 means "no parent", i.e. the first link).
uint64_t dyn_chain_hash(uint64_t parent, int parent_valid, uint64_t block_hash,
                        uint64_t salt) {
  uint8_t buf[16];
  if (!parent_valid) {
    le64(block_hash, buf);
    return XXH3_64bits_withSeed(buf, 8, salt);
  }
  le64(parent, buf);
  le64(block_hash, buf + 8);
  return XXH3_64bits_withSeed(buf, 16, salt);
}

}  // extern "C"

static_assert(sizeof(int32_t) == 4, "token width");
#if defined(__BYTE_ORDER__) && defined(__ORDER_LITTLE_ENDIAN__)
static_assert(__BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__,
              "hash parity with the Python tier assumes little-endian");
#endif
