// Content-addressed LRU block-pool index — native tier of
// dynamo_tpu.kvbm.pool.TierPool bookkeeping.
//
// Analogue of the reference's inactive block pool (reference:
// lib/llm/src/block_manager/pool/inactive.rs — FIFO + seq-hash dedupe map
// + eviction order). Tracks hash→block_id, the free list, and LRU order
// with an intrusive doubly-linked list over preallocated nodes; data
// movement stays in the storage tier (Python/numpy/jax), only the
// bookkeeping lives here.

#include <cstddef>
#include <cstdint>
#include <unordered_map>
#include <vector>

namespace {

struct Node {
  uint64_t hash = 0;
  int64_t prev = -1;  // toward LRU end
  int64_t next = -1;  // toward MRU end
  bool used = false;
};

struct Lru {
  std::vector<Node> nodes;          // indexed by block_id
  std::vector<int64_t> free_list;   // unused block ids (stack)
  std::unordered_map<uint64_t, int64_t> map;  // hash -> block_id
  int64_t head = -1;  // least recently used
  int64_t tail = -1;  // most recently used

  explicit Lru(size_t n) : nodes(n) {
    free_list.reserve(n);
    // Pop order matches the Python fallback (list.pop() from the back of
    // range(n)) so block-id assignment is identical under both backends.
    for (size_t i = 0; i < n; ++i) free_list.push_back(static_cast<int64_t>(i));
  }

  void unlink(int64_t id) {
    Node& nd = nodes[id];
    if (nd.prev >= 0) nodes[nd.prev].next = nd.next; else head = nd.next;
    if (nd.next >= 0) nodes[nd.next].prev = nd.prev; else tail = nd.prev;
    nd.prev = nd.next = -1;
  }

  void push_mru(int64_t id) {
    Node& nd = nodes[id];
    nd.prev = tail;
    nd.next = -1;
    if (tail >= 0) nodes[tail].next = id; else head = id;
    tail = id;
  }
};

}  // namespace

extern "C" {

void* dyn_lru_new(size_t num_blocks) { return new Lru(num_blocks); }

void dyn_lru_free(void* h) { delete static_cast<Lru*>(h); }

// Returns block_id or -1. touch=1 refreshes recency.
int64_t dyn_lru_lookup(void* h, uint64_t hash, int touch) {
  Lru* l = static_cast<Lru*>(h);
  auto it = l->map.find(hash);
  if (it == l->map.end()) return -1;
  if (touch) {
    l->unlink(it->second);
    l->push_mru(it->second);
  }
  return it->second;
}

// Insert `hash`. Return codes:
//   0 = already present (recency refreshed), *out_block = its block
//   1 = inserted into a free block, *out_block = new block
//   2 = inserted by evicting the LRU victim; *out_victim_hash/_block tell
//       the caller which block to demote BEFORE writing *out_block
//       (out_block == victim block: storage is reused)
int dyn_lru_insert(void* h, uint64_t hash, int64_t* out_block,
                   uint64_t* out_victim_hash, int64_t* out_victim_block) {
  Lru* l = static_cast<Lru*>(h);
  auto it = l->map.find(hash);
  if (it != l->map.end()) {
    l->unlink(it->second);
    l->push_mru(it->second);
    *out_block = it->second;
    return 0;
  }
  int rc = 1;
  if (l->free_list.empty()) {
    int64_t victim = l->head;
    if (victim < 0) return -1;  // zero-capacity pool
    *out_victim_hash = l->nodes[victim].hash;
    *out_victim_block = victim;
    l->map.erase(l->nodes[victim].hash);
    l->unlink(victim);
    l->nodes[victim].used = false;
    l->free_list.push_back(victim);
    rc = 2;
  }
  int64_t id = l->free_list.back();
  l->free_list.pop_back();
  Node& nd = l->nodes[id];
  nd.hash = hash;
  nd.used = true;
  l->push_mru(id);
  l->map.emplace(hash, id);
  *out_block = id;
  return rc;
}

// Remove `hash` if present; returns its block id or -1.
int64_t dyn_lru_evict(void* h, uint64_t hash) {
  Lru* l = static_cast<Lru*>(h);
  auto it = l->map.find(hash);
  if (it == l->map.end()) return -1;
  int64_t id = it->second;
  l->map.erase(it);
  l->unlink(id);
  l->nodes[id].used = false;
  l->free_list.push_back(id);
  return id;
}

size_t dyn_lru_len(void* h) { return static_cast<Lru*>(h)->map.size(); }

// Leading consecutive hits, no recency side effects (pool.py match_prefix).
size_t dyn_lru_match_prefix(void* h, const uint64_t* hashes, size_t n) {
  Lru* l = static_cast<Lru*>(h);
  size_t k = 0;
  while (k < n && l->map.count(hashes[k])) ++k;
  return k;
}

}  // extern "C"
