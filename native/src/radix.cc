// Global prefix index over KV block hashes — native tier of
// dynamo_tpu.kv_router.indexer.
//
// Analogue of the reference's radix indexer (reference:
// lib/llm/src/kv_router/indexer.rs:86-876 — RadixTree, apply_event,
// find_matches). As in the Python implementation, chained sequence hashes
// collapse the trie to a flat hash→owners map: a chain walk IS a trie
// descent. This runs on the router's per-request hot path, so the match
// loop avoids allocation: the active-owner set is a small sorted vector
// intersected in place.

#include <algorithm>
#include <cstdint>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct Radix {
  // hash -> sorted small vector of owning workers
  std::unordered_map<uint64_t, std::vector<int64_t>> owners;
  // worker -> hashes it owns (for O(worker size) removal)
  std::unordered_map<int64_t, std::unordered_set<uint64_t>> by_worker;
  uint64_t applied = 0;
};

inline void sorted_insert(std::vector<int64_t>& v, int64_t w) {
  auto it = std::lower_bound(v.begin(), v.end(), w);
  if (it == v.end() || *it != w) v.insert(it, w);
}

inline void sorted_erase(std::vector<int64_t>& v, int64_t w) {
  auto it = std::lower_bound(v.begin(), v.end(), w);
  if (it != v.end() && *it == w) v.erase(it);
}

void remove_worker_impl(Radix* r, int64_t worker) {
  auto it = r->by_worker.find(worker);
  if (it == r->by_worker.end()) return;
  for (uint64_t h : it->second) {
    auto oit = r->owners.find(h);
    if (oit != r->owners.end()) {
      sorted_erase(oit->second, worker);
      if (oit->second.empty()) r->owners.erase(oit);
    }
  }
  r->by_worker.erase(it);
}

}  // namespace

extern "C" {

void* dyn_radix_new() { return new Radix(); }

void dyn_radix_free(void* h) { delete static_cast<Radix*>(h); }

// op: 0 = stored, 1 = removed, 2 = cleared (hashes ignored)
void dyn_radix_apply(void* h, int64_t worker, int op, const uint64_t* hashes,
                     size_t n) {
  Radix* r = static_cast<Radix*>(h);
  if (op == 0) {
    auto& mine = r->by_worker[worker];
    for (size_t i = 0; i < n; ++i) {
      sorted_insert(r->owners[hashes[i]], worker);
      mine.insert(hashes[i]);
    }
  } else if (op == 1) {
    auto bit = r->by_worker.find(worker);
    for (size_t i = 0; i < n; ++i) {
      auto oit = r->owners.find(hashes[i]);
      if (oit != r->owners.end()) {
        sorted_erase(oit->second, worker);
        if (oit->second.empty()) r->owners.erase(oit);
      }
      if (bit != r->by_worker.end()) bit->second.erase(hashes[i]);
    }
  } else if (op == 2) {
    remove_worker_impl(r, worker);
  }
  r->applied += 1;
}

void dyn_radix_remove_worker(void* h, int64_t worker) {
  remove_worker_impl(static_cast<Radix*>(h), worker);
}

// Walk seq_hashes accumulating the longest consecutive prefix per worker,
// appending (worker, score) pairs to the output vectors.
// Semantics match indexer.py::RadixTree.find_matches: the active set is
// the intersection of owners along the walk; a worker's score is the depth
// it stayed in the intersection.
static void find_impl(Radix* r, const uint64_t* seq_hashes, size_t n,
                      std::vector<int64_t>& out_workers,
                      std::vector<uint32_t>& out_scores) {
  std::vector<int64_t> active;   // current intersection, sorted
  std::vector<int64_t> workers;  // all workers ever active, sorted
  std::vector<uint32_t> scores;  // parallel to `workers`
  bool first = true;
  std::vector<int64_t> tmp;
  for (size_t i = 0; i < n; ++i) {
    auto oit = r->owners.find(seq_hashes[i]);
    if (oit == r->owners.end() || oit->second.empty()) break;
    if (first) {
      active = oit->second;
      first = false;
    } else {
      tmp.clear();
      std::set_intersection(active.begin(), active.end(), oit->second.begin(),
                            oit->second.end(), std::back_inserter(tmp));
      active.swap(tmp);
    }
    if (active.empty()) break;
    for (int64_t w : active) {
      auto wit = std::lower_bound(workers.begin(), workers.end(), w);
      size_t idx = wit - workers.begin();
      if (wit == workers.end() || *wit != w) {
        workers.insert(wit, w);
        scores.insert(scores.begin() + idx, 0);
      }
      scores[idx] = static_cast<uint32_t>(i + 1);
    }
  }
  out_workers.insert(out_workers.end(), workers.begin(), workers.end());
  out_scores.insert(out_scores.end(), scores.begin(), scores.end());
}

// Writes up to `cap` (worker, score) pairs; returns the number written.
size_t dyn_radix_find(void* h, const uint64_t* seq_hashes, size_t n,
                      int64_t* out_workers, uint32_t* out_scores, size_t cap) {
  std::vector<int64_t> workers;
  std::vector<uint32_t> scores;
  find_impl(static_cast<Radix*>(h), seq_hashes, n, workers, scores);
  size_t out = workers.size() < cap ? workers.size() : cap;
  for (size_t i = 0; i < out; ++i) {
    out_workers[i] = workers[i];
    out_scores[i] = scores[i];
  }
  return out;
}

// Batched match over several independent trees (the sharded indexer's
// shards — worker sets are disjoint, so results simply concatenate).
// ONE ctypes crossing instead of one per shard: the per-call FFI
// overhead was the sharded indexer's match-latency floor.
size_t dyn_radix_find_multi(void* const* hs, size_t n_trees,
                            const uint64_t* seq_hashes, size_t n,
                            int64_t* out_workers, uint32_t* out_scores,
                            size_t cap) {
  std::vector<int64_t> workers;
  std::vector<uint32_t> scores;
  for (size_t t = 0; t < n_trees; ++t)
    find_impl(static_cast<Radix*>(hs[t]), seq_hashes, n, workers, scores);
  size_t out = workers.size() < cap ? workers.size() : cap;
  for (size_t i = 0; i < out; ++i) {
    out_workers[i] = workers[i];
    out_scores[i] = scores[i];
  }
  return out;
}

size_t dyn_radix_num_blocks(void* h) {
  return static_cast<Radix*>(h)->owners.size();
}

uint64_t dyn_radix_applied(void* h) { return static_cast<Radix*>(h)->applied; }

size_t dyn_radix_num_workers(void* h) {
  return static_cast<Radix*>(h)->by_worker.size();
}

}  // extern "C"
