// C ABI for the KV event publisher (reference: lib/bindings/c — a C API
// around the KV event publisher so non-Python engines, e.g. a C++
// serving stack, can emit cache stored/removed events onto the event
// plane the KV-aware router indexes).
//
// Speaks the coordinator store's wire protocol directly (4-byte LE
// length-prefixed msgpack, op="publish"): no Python in the path. The
// payload matches dynamo_tpu/kv_router/protocols.py RouterEvent:
//   {worker_id, event_id, event: {op, block_hashes, token_block_size}}
// published on "<namespace>.<component>.kv_events".
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -o libdynamo_kv.so kv_publisher_c.cc

#include <cstdint>
#include <cstring>
#include <string>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include "msgpack.h"

namespace {

struct Publisher {
  int fd = -1;
  std::string subject;
  int64_t worker_id = 0;
  int64_t token_block_size = 16;
  int64_t next_event_id = 1;
  int64_t next_req_id = 1;
};

bool send_all(int fd, const char* p, size_t n) {
  while (n) {
    ssize_t sent = send(fd, p, n, MSG_NOSIGNAL);
    if (sent <= 0) return false;
    p += sent;
    n -= (size_t)sent;
  }
  return true;
}

bool recv_all(int fd, char* p, size_t n) {
  while (n) {
    ssize_t got = recv(fd, p, n, 0);
    if (got <= 0) return false;
    p += got;
    n -= (size_t)got;
  }
  return true;
}

// Poison the connection: after a timeout or partial send the stream is
// desynchronized (a late reply would be misread as the next call's ack,
// a half-sent frame corrupts the server's parse), so fail every
// subsequent publish fast instead.
void poison(Publisher* pub) {
  if (pub->fd >= 0) close(pub->fd);
  pub->fd = -1;
}

// send one request frame and wait for ITS unary {i, ok} reply
bool roundtrip(Publisher* pub, int64_t rid, const Val& req) {
  std::string body;
  encode(req, body);
  char hdr[4] = {
      (char)(body.size() & 0xff), (char)((body.size() >> 8) & 0xff),
      (char)((body.size() >> 16) & 0xff), (char)((body.size() >> 24) & 0xff)};
  if (!send_all(pub->fd, hdr, 4) || !send_all(pub->fd, body.data(), body.size())) {
    poison(pub);
    return false;
  }
  char rhdr[4];
  if (!recv_all(pub->fd, rhdr, 4)) { poison(pub); return false; }
  uint32_t len = (uint8_t)rhdr[0] | ((uint8_t)rhdr[1] << 8) |
                 ((uint8_t)rhdr[2] << 16) | ((uint8_t)rhdr[3] << 24);
  if (len > 1u << 20) { poison(pub); return false; }
  std::string rbody(len, '\0');
  if (!recv_all(pub->fd, rbody.data(), len)) { poison(pub); return false; }
  Decoder d{(const uint8_t*)rbody.data(), rbody.size()};
  Val reply = d.decode();
  if (d.fail || reply.t != Val::MAP) { poison(pub); return false; }
  const Val* id = reply.get("i");
  if (id == nullptr || id->t != Val::INT || id->i != rid) {
    poison(pub);  // stale/mismatched reply: stream out of sync
    return false;
  }
  const Val* ok = reply.get("ok");
  return ok != nullptr && ok->t == Val::BOOL && ok->b;
}

}  // namespace

extern "C" {

// Connect to the coordinator and bind a publisher to one worker's
// kv_events subject ("<namespace>.<component>.kv_events"). NULL on error.
void* dynamo_kv_publisher_connect(const char* host, int port,
                                  const char* subject, long long worker_id,
                                  int token_block_size) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
      connect(fd, (sockaddr*)&addr, sizeof addr) != 0) {
    close(fd);
    return nullptr;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  // bound every publish round trip: a wedged coordinator must fail the
  // call, not hang the engine's event thread forever
  timeval tv{10, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
  auto* pub = new Publisher();
  pub->fd = fd;
  pub->subject = subject;
  pub->worker_id = worker_id;
  pub->token_block_size = token_block_size > 0 ? token_block_size : 16;
  return pub;
}

// op is "stored", "removed", or "cleared"; hashes are chained sequence
// hashes (position-sensitive). Returns 0 on acknowledged publish.
int dynamo_kv_publisher_publish(void* handle, const char* op,
                                const unsigned long long* hashes, int n) {
  auto* pub = (Publisher*)handle;
  if (pub == nullptr || pub->fd < 0 || op == nullptr || n < 0) return -1;
  if (n > 0 && hashes == nullptr) return -1;
  Val event = Val::map();
  event.m.emplace_back("op", Val::str(op));
  Val bh = Val::arr();
  for (int i = 0; i < n; ++i)
    bh.a.push_back(Val::uint64(hashes[i]));
  event.m.emplace_back("block_hashes", std::move(bh));
  event.m.emplace_back("token_block_size", Val::integer(pub->token_block_size));

  Val router_event = Val::map();
  router_event.m.emplace_back("worker_id", Val::integer(pub->worker_id));
  router_event.m.emplace_back("event_id", Val::integer(pub->next_event_id++));
  router_event.m.emplace_back("event", std::move(event));
  std::string payload;
  encode(router_event, payload);

  Val args = Val::arr();
  args.a.push_back(Val::str(pub->subject));
  args.a.push_back(Val::bin(std::move(payload)));
  int64_t rid = pub->next_req_id++;
  Val req = Val::map();
  req.m.emplace_back("i", Val::integer(rid));
  req.m.emplace_back("op", Val::str("publish"));
  req.m.emplace_back("a", std::move(args));
  return roundtrip(pub, rid, req) ? 0 : -1;
}

void dynamo_kv_publisher_close(void* handle) {
  auto* pub = (Publisher*)handle;
  if (pub == nullptr) return;
  if (pub->fd >= 0) close(pub->fd);
  delete pub;
}

}  // extern "C"
