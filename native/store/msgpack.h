// msgpack subset shared by the native coordinator server and the C-ABI
// KV event publisher (everything the store wire protocol uses).
#pragma once

#include <cstdint>
#include <cstring>
#include <string>
#include <utility>
#include <vector>

// ---------------------------------------------------------------------------
// msgpack subset (everything the store protocol uses)
// ---------------------------------------------------------------------------

struct Val {
  enum Type { NIL, BOOL, INT, UINT, F64, STR, BIN, ARR, MAP } t = NIL;
  bool b = false;
  int64_t i = 0;
  uint64_t u = 0;
  double f = 0;
  std::string s;                            // STR and BIN
  std::vector<Val> a;                       // ARR
  std::vector<std::pair<std::string, Val>> m;  // MAP (string keys only)

  static Val nil() { return Val{}; }
  // unsigned 64-bit (always 0xcf): values >= 2^63 must NOT be emitted as
  // negative int64 — python-side consumers (e.g. the KV router's radix
  // keys) compare against unsigned xxh3 hashes
  static Val uint64(uint64_t v) { Val x; x.t = UINT; x.u = v; return x; }
  static Val boolean(bool v) { Val x; x.t = BOOL; x.b = v; return x; }
  static Val integer(int64_t v) { Val x; x.t = INT; x.i = v; return x; }
  static Val real(double v) { Val x; x.t = F64; x.f = v; return x; }
  static Val str(std::string v) { Val x; x.t = STR; x.s = std::move(v); return x; }
  static Val bin(std::string v) { Val x; x.t = BIN; x.s = std::move(v); return x; }
  static Val arr() { Val x; x.t = ARR; return x; }
  static Val map() { Val x; x.t = MAP; return x; }

  bool is_num() const { return t == INT || t == F64; }
  double num() const { return t == INT ? (double)i : f; }
  const Val* get(const char* key) const {
    for (auto& kv : m)
      if (kv.first == key) return &kv.second;
    return nullptr;
  }
};

static void put_be(std::string& out, uint64_t v, int bytes) {
  for (int k = bytes - 1; k >= 0; --k) out.push_back((char)((v >> (8 * k)) & 0xff));
}

static void encode(const Val& v, std::string& out) {
  switch (v.t) {
    case Val::NIL: out.push_back((char)0xc0); break;
    case Val::BOOL: out.push_back((char)(v.b ? 0xc3 : 0xc2)); break;
    case Val::UINT:
      out.push_back((char)0xcf);
      put_be(out, v.u, 8);
      break;
    case Val::INT: {
      int64_t x = v.i;
      if (x >= 0 && x < 128) out.push_back((char)x);
      else if (x < 0 && x >= -32) out.push_back((char)(int8_t)x);
      else { out.push_back((char)0xd3); put_be(out, (uint64_t)x, 8); }
      break;
    }
    case Val::F64: {
      out.push_back((char)0xcb);
      uint64_t bits;
      static_assert(sizeof(bits) == sizeof(v.f), "");
      std::memcpy(&bits, &v.f, 8);
      put_be(out, bits, 8);
      break;
    }
    case Val::STR: {
      size_t n = v.s.size();
      if (n < 32) out.push_back((char)(0xa0 | n));
      else if (n < 256) { out.push_back((char)0xd9); out.push_back((char)n); }
      else if (n < 65536) { out.push_back((char)0xda); put_be(out, n, 2); }
      else { out.push_back((char)0xdb); put_be(out, n, 4); }
      out += v.s;
      break;
    }
    case Val::BIN: {
      size_t n = v.s.size();
      if (n < 256) { out.push_back((char)0xc4); out.push_back((char)n); }
      else if (n < 65536) { out.push_back((char)0xc5); put_be(out, n, 2); }
      else { out.push_back((char)0xc6); put_be(out, n, 4); }
      out += v.s;
      break;
    }
    case Val::ARR: {
      size_t n = v.a.size();
      if (n < 16) out.push_back((char)(0x90 | n));
      else if (n < 65536) { out.push_back((char)0xdc); put_be(out, n, 2); }
      else { out.push_back((char)0xdd); put_be(out, n, 4); }
      for (auto& e : v.a) encode(e, out);
      break;
    }
    case Val::MAP: {
      size_t n = v.m.size();
      if (n < 16) out.push_back((char)(0x80 | n));
      else { out.push_back((char)0xde); put_be(out, n, 2); }
      for (auto& kv : v.m) {
        encode(Val::str(kv.first), out);
        encode(kv.second, out);
      }
      break;
    }
  }
}

struct Decoder {
  const uint8_t* p;
  size_t n;
  size_t pos = 0;
  bool fail = false;
  int depth = 0;
  // Nesting bound: a crafted frame of repeated fixarray/fixmap headers
  // (unauthenticated socket) would otherwise recurse once per byte and
  // overflow the stack. The wire protocol never nests past ~6.
  static constexpr int kMaxDepth = 64;

  uint64_t be(int bytes) {
    if (pos + (size_t)bytes > n) { fail = true; return 0; }
    uint64_t v = 0;
    for (int k = 0; k < bytes; ++k) v = (v << 8) | p[pos++];
    return v;
  }
  std::string take(size_t len) {
    if (pos + len > n) { fail = true; return {}; }
    std::string s((const char*)p + pos, len);
    pos += len;
    return s;
  }
  Val decode() {
    if (fail || pos >= n) { fail = true; return Val::nil(); }
    uint8_t b = p[pos++];
    if (b < 0x80) return Val::integer(b);
    if (b >= 0xe0) return Val::integer((int8_t)b);
    if ((b & 0xf0) == 0x80) return decode_map(b & 0x0f);
    if ((b & 0xf0) == 0x90) return decode_arr(b & 0x0f);
    if ((b & 0xe0) == 0xa0) return Val::str(take(b & 0x1f));
    switch (b) {
      case 0xc0: return Val::nil();
      case 0xc2: return Val::boolean(false);
      case 0xc3: return Val::boolean(true);
      case 0xc4: return Val::bin(take(be(1)));
      case 0xc5: return Val::bin(take(be(2)));
      case 0xc6: return Val::bin(take(be(4)));
      case 0xca: {
        uint32_t bits = (uint32_t)be(4);
        float f;
        std::memcpy(&f, &bits, 4);
        return Val::real(f);
      }
      case 0xcb: {
        uint64_t bits = be(8);
        double f;
        std::memcpy(&f, &bits, 8);
        return Val::real(f);
      }
      case 0xcc: return Val::integer((int64_t)be(1));
      case 0xcd: return Val::integer((int64_t)be(2));
      case 0xce: return Val::integer((int64_t)be(4));
      case 0xcf: return Val::integer((int64_t)be(8));  // u64 (fits: ids are small)
      case 0xd0: return Val::integer((int8_t)be(1));
      case 0xd1: return Val::integer((int16_t)be(2));
      case 0xd2: return Val::integer((int32_t)be(4));
      case 0xd3: return Val::integer((int64_t)be(8));
      case 0xd9: return Val::str(take(be(1)));
      case 0xda: return Val::str(take(be(2)));
      case 0xdb: return Val::str(take(be(4)));
      case 0xdc: return decode_arr(be(2));
      case 0xdd: return decode_arr(be(4));
      case 0xde: return decode_map(be(2));
      case 0xdf: return decode_map(be(4));
      default: fail = true; return Val::nil();
    }
  }
  Val decode_arr(size_t count) {
    Val v = Val::arr();
    if (++depth > kMaxDepth || count > n - pos) { fail = true; --depth; return v; }
    for (size_t k = 0; k < count && !fail; ++k) v.a.push_back(decode());
    --depth;
    return v;
  }
  Val decode_map(size_t count) {
    Val v = Val::map();
    if (++depth > kMaxDepth || count > (n - pos) / 2) { fail = true; --depth; return v; }
    for (size_t k = 0; k < count && !fail; ++k) {
      Val key = decode();
      Val val = decode();
      v.m.emplace_back(key.s, std::move(val));
    }
    --depth;
    return v;
  }
};

