// dynamo-store: native coordinator for the distributed runtime.
//
// C++ implementation of the control plane the Python StoreServer exposes
// (dynamo_tpu/store/{server,memory}.py is the semantic reference; the
// upstream system this replaces is the reference's etcd+NATS pair,
// lib/runtime/src/transports/{etcd,nats}.rs). Wire-compatible with
// dynamo_tpu/store/client.py: 4-byte LE length-prefixed msgpack frames,
// request {i, op, a}, unary reply {i, ok, v|e}, stream push {i: sid, s},
// stream end {i: sid, end: true}.
//
// Single-threaded poll(2) event loop; a 100ms tick drives lease expiry,
// queue redelivery, and blocked-pop timeouts. A dropped connection
// revokes its leases (liveness), closes its streams, and abandons its
// parked queue pops — identical semantics to the Python server.
//
// Build: g++ -O2 -std=c++17 -o dynamo_store store_server.cc

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <set>
#include <stdexcept>
#include <string>
#include <unordered_map>
#include <vector>

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <signal.h>
#include <sys/socket.h>
#include <unistd.h>

#include "msgpack.h"

// ---------------------------------------------------------------------------
// Store state
// ---------------------------------------------------------------------------

static double now_s() {
  using namespace std::chrono;
  return duration<double>(steady_clock::now().time_since_epoch()).count();
}

static bool subject_matches(const std::string& pattern, const std::string& subject) {
  // NATS-style: '.'-separated tokens, '*' = one token, '>' = 1+ trailing
  if (pattern.find('*') == std::string::npos && pattern.find('>') == std::string::npos)
    return pattern == subject;
  auto split = [](const std::string& s) {
    std::vector<std::string> out;
    size_t start = 0;
    while (true) {
      size_t dot = s.find('.', start);
      if (dot == std::string::npos) { out.push_back(s.substr(start)); break; }
      out.push_back(s.substr(start, dot - start));
      start = dot + 1;
    }
    return out;
  };
  auto pt = split(pattern), st = split(subject);
  for (size_t i = 0; i < pt.size(); ++i) {
    if (pt[i] == ">") return st.size() >= i + 1;
    if (i >= st.size()) return false;
    if (pt[i] != "*" && pt[i] != st[i]) return false;
  }
  return pt.size() == st.size();
}

static volatile sig_atomic_t g_stop = 0;
static void on_term(int) { g_stop = 1; }

struct Conn;  // fwd

struct Entry {
  std::string value;
  int64_t version = 0;
  int64_t lease_id = 0;
};

struct Lease {
  double ttl_s = 0;
  double expires_at = 0;
  std::set<std::string> keys;
};

struct QMsg {
  int64_t id;
  std::string payload;
};

struct ParkedPop {
  Conn* conn;
  int64_t rid;
  double deadline;   // <0: no timeout
  double visibility;
  uint64_t order;
};

struct QueueState {
  int64_t next_id = 1;
  std::deque<QMsg> ready;
  std::map<int64_t, std::pair<QMsg, double>> in_flight;  // id -> (msg, redeliver at)
  std::deque<ParkedPop> parked;
};

struct WatchReg {
  Conn* conn;
  int64_t sid;
  std::string prefix;
};

struct SubReg {
  Conn* conn;
  int64_t sid;
  std::string pattern;
};

struct Conn {
  int fd;
  std::string inbuf;
  std::string outbuf;
  std::set<int64_t> leases;
  std::set<int64_t> stream_ids;
  bool dead = false;
};

struct Server {
  int listen_fd = -1;
  std::map<int, std::unique_ptr<Conn>> conns;
  // kv
  std::map<std::string, Entry> kv;  // ordered: prefix scans
  int64_t version = 0;
  // leases
  std::unordered_map<int64_t, Lease> leases;
  int64_t next_lease = 1;
  // streams
  std::vector<WatchReg> watches;
  std::vector<SubReg> subs;
  int64_t next_sid = 1;
  // queues / objects
  std::unordered_map<std::string, QueueState> queues;
  std::unordered_map<std::string, std::map<std::string, std::string>> objects;
  uint64_t pop_order = 0;
  // durability — same restart CONTRACT as the python store
  // (store/persist.py: unleased KV, queues with in-flight restored as
  // ready, the object plane; leased liveness keys ephemeral) and the
  // same MECHANISM: every surviving mutation appends one WAL record
  // (flushed before the reply is sent — kernel-buffered, so it
  // survives a kill -9; --fsync-wal additionally fsyncs per record for
  // power-loss durability, like etcd's raft log fsync). Snapshots
  // (2s tick + SIGTERM) act as WAL compaction: a successful snapshot
  // truncates the log. Replay order on boot: snapshot, then WAL
  // records; q_push records already folded into the snapshot
  // (id < its next_id) are skipped so queued work never delivers
  // twice. Reference role: etcd raft log + JetStream file store
  // (lib/runtime/src/transports/{etcd,nats}.rs).
  std::string persist_path;
  std::string wal_path;
  FILE* wal = nullptr;
  bool fsync_wal = false;
  bool dirty = false;
  double last_snap = 0;

  // ---- framing ----------------------------------------------------------
  void send_frame(Conn* c, const Val& v) {
    if (c->dead) return;
    std::string body;
    encode(v, body);
    uint32_t len = (uint32_t)body.size();
    char hdr[4];
    hdr[0] = (char)(len & 0xff);
    hdr[1] = (char)((len >> 8) & 0xff);
    hdr[2] = (char)((len >> 16) & 0xff);
    hdr[3] = (char)((len >> 24) & 0xff);
    c->outbuf.append(hdr, 4);
    c->outbuf += body;
  }

  void reply_ok(Conn* c, int64_t rid, Val v) {
    Val r = Val::map();
    r.m.emplace_back("i", Val::integer(rid));
    r.m.emplace_back("ok", Val::boolean(true));
    r.m.emplace_back("v", std::move(v));
    send_frame(c, r);
  }

  void reply_err(Conn* c, int64_t rid, const std::string& msg) {
    Val r = Val::map();
    r.m.emplace_back("i", Val::integer(rid));
    r.m.emplace_back("ok", Val::boolean(false));
    r.m.emplace_back("e", Val::str(msg));
    send_frame(c, r);
  }

  void push_stream(Conn* c, int64_t sid, Val item) {
    Val r = Val::map();
    r.m.emplace_back("i", Val::integer(sid));
    r.m.emplace_back("s", std::move(item));
    send_frame(c, r);
  }

  // ---- kv ---------------------------------------------------------------
  static Val enc_entry(const std::string& key, const Entry& e) {
    Val v = Val::map();
    v.m.emplace_back("k", Val::str(key));
    v.m.emplace_back("v", Val::bin(e.value));
    v.m.emplace_back("ver", Val::integer(e.version));
    v.m.emplace_back("l", Val::integer(e.lease_id));
    return v;
  }

  void emit_watch(const char* type, const std::string& key, const Entry& e) {
    for (auto& w : watches) {
      if (key.rfind(w.prefix, 0) == 0) {
        Val ev = Val::map();
        ev.m.emplace_back("t", Val::str(type));
        ev.m.emplace_back("e", enc_entry(key, e));
        push_stream(w.conn, w.sid, std::move(ev));
      }
    }
  }

  int64_t kv_put(const std::string& key, std::string value, int64_t lease_id) {
    auto prev = kv.find(key);
    bool durable_prev = prev != kv.end() && prev->second.lease_id == 0;
    if (prev != kv.end() && prev->second.lease_id != lease_id) {
      auto old = leases.find(prev->second.lease_id);
      if (old != leases.end()) old->second.keys.erase(key);
    }
    if (lease_id != 0) {
      auto it = leases.find(lease_id);
      if (it == leases.end()) throw std::runtime_error("KeyError: lease does not exist");
      it->second.keys.insert(key);
    }
    Entry e{std::move(value), ++version, lease_id};
    kv[key] = e;
    if (lease_id == 0) {
      dirty = true;
      wal_kv_put(key, e.version, e.value);
    } else if (durable_prev) {
      // a leased put SHADOWS a previously durable key: tombstone it,
      // or a restart would resurrect the stale value
      dirty = true;
      wal_kv_del(key);
    }
    emit_watch("put", key, e);
    return e.version;
  }

  bool kv_delete(const std::string& key) {
    auto it = kv.find(key);
    if (it == kv.end()) return false;
    Entry e = std::move(it->second);
    kv.erase(it);
    if (e.lease_id == 0) {
      dirty = true;
      wal_kv_del(key);
    }
    if (e.lease_id != 0) {
      auto l = leases.find(e.lease_id);
      if (l != leases.end()) l->second.keys.erase(key);
    }
    emit_watch("delete", key, e);
    return true;
  }

  void lease_revoke(int64_t lid) {
    auto it = leases.find(lid);
    if (it == leases.end()) return;
    std::vector<std::string> keys(it->second.keys.begin(), it->second.keys.end());
    leases.erase(it);
    for (auto& k : keys) kv_delete(k);
  }

  // ---- queues -----------------------------------------------------------
  static Val enc_qmsg(const QMsg& m) {
    Val v = Val::map();
    v.m.emplace_back("id", Val::integer(m.id));
    v.m.emplace_back("p", Val::bin(m.payload));
    return v;
  }

  void serve_parked(const std::string& qname) {
    auto& q = queues[qname];
    while (!q.ready.empty() && !q.parked.empty()) {
      ParkedPop pp = q.parked.front();
      q.parked.pop_front();
      if (pp.conn->dead) continue;
      QMsg msg = std::move(q.ready.front());
      q.ready.pop_front();
      Val v = enc_qmsg(msg);
      q.in_flight[msg.id] = {std::move(msg), now_s() + pp.visibility};
      reply_ok(pp.conn, pp.rid, std::move(v));
    }
  }

  // ---- request dispatch -------------------------------------------------
  void handle(Conn* c, const Val& msg) {
    const Val* iv = msg.get("i");
    const Val* opv = msg.get("op");
    if (!iv || !opv) return;  // malformed; drop
    int64_t rid = iv->i;
    const std::string& op = opv->s;
    const Val* av = msg.get("a");
    static const Val empty_arr = Val::arr();
    const Val& args = av ? *av : empty_arr;
    auto arg = [&](size_t k) -> const Val& {
      static Val nil_v;
      return k < args.a.size() ? args.a[k] : nil_v;
    };
    try {
      if (op == "ping") {
        reply_ok(c, rid, Val::str("pong"));
      } else if (op == "kv_put") {
        reply_ok(c, rid, Val::integer(kv_put(arg(0).s, arg(1).s, arg(2).i)));
      } else if (op == "kv_create") {
        if (kv.count(arg(0).s)) reply_ok(c, rid, Val::boolean(false));
        else {
          kv_put(arg(0).s, arg(1).s, arg(2).i);
          reply_ok(c, rid, Val::boolean(true));
        }
      } else if (op == "kv_get") {
        auto it = kv.find(arg(0).s);
        reply_ok(c, rid, it == kv.end() ? Val::nil() : enc_entry(it->first, it->second));
      } else if (op == "kv_get_prefix") {
        Val out = Val::arr();
        const std::string& prefix = arg(0).s;
        for (auto it = kv.lower_bound(prefix);
             it != kv.end() && it->first.rfind(prefix, 0) == 0; ++it)
          out.a.push_back(enc_entry(it->first, it->second));
        reply_ok(c, rid, std::move(out));
      } else if (op == "kv_delete") {
        reply_ok(c, rid, Val::boolean(kv_delete(arg(0).s)));
      } else if (op == "kv_delete_prefix") {
        const std::string& prefix = arg(0).s;
        std::vector<std::string> keys;
        for (auto it = kv.lower_bound(prefix);
             it != kv.end() && it->first.rfind(prefix, 0) == 0; ++it)
          keys.push_back(it->first);
        for (auto& k : keys) kv_delete(k);
        reply_ok(c, rid, Val::integer((int64_t)keys.size()));
      } else if (op == "watch_prefix") {
        int64_t sid = next_sid++;
        const std::string& prefix = arg(0).s;
        Val snapshot = Val::arr();
        for (auto it = kv.lower_bound(prefix);
             it != kv.end() && it->first.rfind(prefix, 0) == 0; ++it)
          snapshot.a.push_back(enc_entry(it->first, it->second));
        watches.push_back({c, sid, prefix});
        c->stream_ids.insert(sid);
        Val v = Val::map();
        v.m.emplace_back("sid", Val::integer(sid));
        v.m.emplace_back("snapshot", std::move(snapshot));
        reply_ok(c, rid, std::move(v));
      } else if (op == "lease_grant") {
        int64_t lid = next_lease++;
        double ttl = arg(0).num();
        leases[lid] = Lease{ttl, now_s() + ttl, {}};
        c->leases.insert(lid);
        reply_ok(c, rid, Val::integer(lid));
      } else if (op == "lease_keepalive") {
        auto it = leases.find(arg(0).i);
        if (it == leases.end()) reply_ok(c, rid, Val::boolean(false));
        else {
          it->second.expires_at = now_s() + it->second.ttl_s;
          reply_ok(c, rid, Val::boolean(true));
        }
      } else if (op == "lease_revoke") {
        lease_revoke(arg(0).i);
        c->leases.erase(arg(0).i);
        reply_ok(c, rid, Val::boolean(true));
      } else if (op == "publish") {
        const std::string& subject = arg(0).s;
        for (auto& s : subs) {
          if (subject_matches(s.pattern, subject)) {
            Val item = Val::map();
            item.m.emplace_back("subj", Val::str(subject));
            item.m.emplace_back("p", Val::bin(arg(1).s));
            push_stream(s.conn, s.sid, std::move(item));
          }
        }
        reply_ok(c, rid, Val::boolean(true));
      } else if (op == "subscribe") {
        int64_t sid = next_sid++;
        subs.push_back({c, sid, arg(0).s});
        c->stream_ids.insert(sid);
        Val v = Val::map();
        v.m.emplace_back("sid", Val::integer(sid));
        reply_ok(c, rid, std::move(v));
      } else if (op == "stream_close") {
        close_stream(c, arg(0).i, /*notify_end=*/true);
        reply_ok(c, rid, Val::boolean(true));
      } else if (op == "queue_push") {
        auto& q = queues[arg(0).s];
        QMsg msg{q.next_id++, arg(1).s};
        int64_t id = msg.id;
        wal_q_push(arg(0).s, id, msg.payload);
        q.ready.push_back(std::move(msg));
        dirty = true;
        serve_parked(arg(0).s);
        reply_ok(c, rid, Val::integer(id));
      } else if (op == "queue_pop") {
        const std::string& qname = arg(0).s;
        auto& q = queues[qname];
        double visibility = arg(2).is_num() ? arg(2).num() : 30.0;
        if (!q.ready.empty()) {
          QMsg msg = std::move(q.ready.front());
          q.ready.pop_front();
          Val v = enc_qmsg(msg);
          q.in_flight[msg.id] = {std::move(msg), now_s() + visibility};
          reply_ok(c, rid, std::move(v));
        } else {
          double deadline = arg(1).is_num() ? now_s() + arg(1).num() : -1.0;
          if (arg(1).is_num() && arg(1).num() <= 0) reply_ok(c, rid, Val::nil());
          else q.parked.push_back({c, rid, deadline, visibility, pop_order++});
        }
      } else if (op == "queue_ack") {
        auto& q = queues[arg(0).s];
        bool acked = q.in_flight.erase(arg(1).i) > 0;
        if (acked) {
          dirty = true;
          wal_q_ack(arg(0).s, arg(1).i);
        }
        reply_ok(c, rid, Val::boolean(acked));
      } else if (op == "queue_len") {
        auto& q = queues[arg(0).s];
        reply_ok(c, rid,
                 Val::integer((int64_t)(q.ready.size() + q.in_flight.size())));
      } else if (op == "obj_put") {
        objects[arg(0).s][arg(1).s] = arg(2).s;
        dirty = true;
        wal_obj_put(arg(0).s, arg(1).s, arg(2).s);
        reply_ok(c, rid, Val::boolean(true));
      } else if (op == "obj_get") {
        auto b = objects.find(arg(0).s);
        if (b == objects.end()) { reply_ok(c, rid, Val::nil()); return; }
        auto o = b->second.find(arg(1).s);
        reply_ok(c, rid, o == b->second.end() ? Val::nil() : Val::bin(o->second));
      } else if (op == "obj_delete") {
        auto b = objects.find(arg(0).s);
        bool deleted = b != objects.end() && b->second.erase(arg(1).s) > 0;
        if (deleted) {
          dirty = true;
          wal_obj_del(arg(0).s, arg(1).s);
        }
        reply_ok(c, rid, Val::boolean(deleted));
      } else if (op == "obj_list") {
        Val out = Val::arr();
        auto b = objects.find(arg(0).s);
        if (b != objects.end())
          for (auto& kv2 : b->second) out.a.push_back(Val::str(kv2.first));
        reply_ok(c, rid, std::move(out));
      } else {
        reply_err(c, rid, "ValueError: unknown op '" + op + "'");
      }
    } catch (const std::exception& e) {
      reply_err(c, rid, e.what());
    }
  }

  void close_stream(Conn* c, int64_t sid, bool notify_end) {
    c->stream_ids.erase(sid);
    watches.erase(std::remove_if(watches.begin(), watches.end(),
                                 [&](const WatchReg& w) {
                                   return w.conn == c && w.sid == sid;
                                 }),
                  watches.end());
    subs.erase(std::remove_if(subs.begin(), subs.end(),
                              [&](const SubReg& s) {
                                return s.conn == c && s.sid == sid;
                              }),
               subs.end());
    if (notify_end) {
      Val r = Val::map();
      r.m.emplace_back("i", Val::integer(sid));
      r.m.emplace_back("end", Val::boolean(true));
      send_frame(c, r);
    }
  }

  // ---- durability -------------------------------------------------------
  // Binary snapshot, atomic tmp+rename. Format (all ints little-endian):
  //   "DTPUSNAP1" | u64 version
  //   u32 n_kv    | { str key | u64 ver | str value }       (unleased only)
  //   u32 n_queue | { str name | u64 next_id | u32 n | { u64 id | str p } }
  //   u32 n_bkt   | { str bucket | u32 n | { str name | str data } }
  static void put_u32(std::string& b, uint32_t v) { b.append((char*)&v, 4); }
  static void put_u64(std::string& b, uint64_t v) { b.append((char*)&v, 8); }
  static void put_str(std::string& b, const std::string& s) {
    put_u32(b, (uint32_t)s.size());
    b.append(s);
  }
  struct Rd {
    const std::string& b;
    size_t off = 0;
    bool ok = true;
    uint32_t u32() {
      if (off + 4 > b.size()) { ok = false; return 0; }
      uint32_t v; memcpy(&v, b.data() + off, 4); off += 4; return v;
    }
    uint64_t u64() {
      if (off + 8 > b.size()) { ok = false; return 0; }
      uint64_t v; memcpy(&v, b.data() + off, 8); off += 8; return v;
    }
    std::string str() {
      uint32_t n = u32();
      if (!ok || off + n > b.size()) { ok = false; return {}; }
      std::string s = b.substr(off, n); off += n; return s;
    }
  };

  // ---- write-ahead log --------------------------------------------------
  // Record: u32 body_len | u8 op | op fields (strings are u32-prefixed).
  // Ops: 1 kv_put(key, u64 ver, value)  2 kv_del(key)
  //      3 q_push(name, u64 id, payload) 4 q_ack(name, u64 id)
  //      5 obj_put(bucket, name, data)   6 obj_del(bucket, name)
  enum { W_KV_PUT = 1, W_KV_DEL, W_Q_PUSH, W_Q_ACK, W_OBJ_PUT, W_OBJ_DEL };

  void wal_write(const std::string& body) {
    if (wal_path.empty()) return;
    if (!wal) {
      wal = fopen(wal_path.c_str(), "ab");
      if (!wal) { perror("wal open"); return; }
    }
    std::string rec;
    put_u32(rec, (uint32_t)body.size());
    rec += body;
    // flush before the reply goes out: acked mutations survive a
    // process kill. --fsync-wal extends that to host/power crashes.
    bool ok = fwrite(rec.data(), 1, rec.size(), wal) == rec.size();
    ok = (fflush(wal) == 0) && ok;
    if (fsync_wal) ok = (fsync(fileno(wal)) == 0) && ok;
    if (!ok) {
      // A short/failed write (ENOSPC, EIO) may leave a TORN RECORD in
      // the middle of the log — replay stops at the first bad record,
      // so every later append would be silently lost on restart.
      // Force an immediate snapshot instead: it captures current state
      // (including this mutation) and truncates the broken log.
      perror("wal write (forcing snapshot)");
      fclose(wal);
      wal = nullptr;
      dirty = true;
      save_snapshot();  // retries via the 2s tick if it also fails
    }
  }

  void wal_kv_put(const std::string& key, int64_t ver, const std::string& value) {
    if (wal_path.empty()) return;
    std::string b(1, (char)W_KV_PUT);
    put_str(b, key); put_u64(b, (uint64_t)ver); put_str(b, value);
    wal_write(b);
  }
  void wal_kv_del(const std::string& key) {
    if (wal_path.empty()) return;
    std::string b(1, (char)W_KV_DEL);
    put_str(b, key);
    wal_write(b);
  }
  void wal_q_push(const std::string& q, int64_t id, const std::string& payload) {
    if (wal_path.empty()) return;
    std::string b(1, (char)W_Q_PUSH);
    put_str(b, q); put_u64(b, (uint64_t)id); put_str(b, payload);
    wal_write(b);
  }
  void wal_q_ack(const std::string& q, int64_t id) {
    if (wal_path.empty()) return;
    std::string b(1, (char)W_Q_ACK);
    put_str(b, q); put_u64(b, (uint64_t)id);
    wal_write(b);
  }
  void wal_obj_put(const std::string& bucket, const std::string& name,
                   const std::string& data) {
    if (wal_path.empty()) return;
    std::string b(1, (char)W_OBJ_PUT);
    put_str(b, bucket); put_str(b, name); put_str(b, data);
    wal_write(b);
  }
  void wal_obj_del(const std::string& bucket, const std::string& name) {
    if (wal_path.empty()) return;
    std::string b(1, (char)W_OBJ_DEL);
    put_str(b, bucket); put_str(b, name);
    wal_write(b);
  }

  void wal_truncate() {
    if (wal_path.empty()) return;
    if (wal) { fclose(wal); wal = nullptr; }
    FILE* t = fopen(wal_path.c_str(), "wb");
    if (t) {
      fflush(t);
      fsync(fileno(t));
      fclose(t);
    }
  }

  void replay_wal(const std::unordered_map<std::string, int64_t>& snap_next) {
    if (wal_path.empty()) return;
    FILE* f = fopen(wal_path.c_str(), "rb");
    if (!f) return;
    std::string b;
    char buf[1 << 16];
    size_t n;
    while ((n = fread(buf, 1, sizeof buf, f)) > 0) b.append(buf, n);
    fclose(f);
    std::unordered_map<std::string, std::set<int64_t>> acked;
    std::unordered_map<std::string, std::deque<QMsg>> pushes;
    std::unordered_map<std::string, int64_t> q_next;
    size_t off = 0;
    size_t n_rec = 0;
    while (off + 4 <= b.size()) {
      uint32_t len;
      memcpy(&len, b.data() + off, 4);
      if (off + 4 + len > b.size() || len == 0) break;  // torn tail: stop
      Rd r{b, off + 4};
      size_t end = off + 4 + len;
      uint8_t op = (uint8_t)b[r.off++];
      if (op == W_KV_PUT) {
        std::string key = r.str();
        int64_t ver = (int64_t)r.u64();
        std::string val = r.str();
        if (r.ok) {
          kv[key] = Entry{std::move(val), ver, 0};
          version = std::max(version, ver);
        }
      } else if (op == W_KV_DEL) {
        std::string key = r.str();
        if (r.ok) kv.erase(key);
      } else if (op == W_Q_PUSH) {
        std::string qn = r.str();
        int64_t id = (int64_t)r.u64();
        std::string payload = r.str();
        if (r.ok) {
          // records already folded into the snapshot (id < its
          // next_id) must not replay: queued work would deliver twice
          auto sn = snap_next.find(qn);
          if (sn == snap_next.end() || id >= sn->second) {
            pushes[qn].push_back(QMsg{id, std::move(payload)});
            auto& nx = q_next[qn];
            nx = std::max(nx, id + 1);
          }
        }
      } else if (op == W_Q_ACK) {
        std::string qn = r.str();
        int64_t id = (int64_t)r.u64();
        if (r.ok) acked[qn].insert(id);
      } else if (op == W_OBJ_PUT) {
        std::string bucket = r.str();
        std::string name = r.str();
        std::string data = r.str();
        if (r.ok) objects[bucket][name] = std::move(data);
      } else if (op == W_OBJ_DEL) {
        std::string bucket = r.str();
        std::string name = r.str();
        if (r.ok) {
          auto it = objects.find(bucket);
          if (it != objects.end()) it->second.erase(name);
        }
      } else {
        break;  // unknown op: stop replay (forward-compat guard)
      }
      if (!r.ok) break;
      off = end;
      ++n_rec;
    }
    for (auto& pe : pushes) {
      auto& q = queues[pe.first];
      auto& ack = acked[pe.first];
      for (auto& m : pe.second)
        if (!ack.count(m.id)) q.ready.push_back(std::move(m));
    }
    for (auto& ne : q_next) {
      auto& q = queues[ne.first];
      q.next_id = std::max(q.next_id, ne.second);
    }
    // acks may target messages restored from the SNAPSHOT
    for (auto& ae : acked) {
      auto qi = queues.find(ae.first);
      if (qi == queues.end()) continue;
      auto& ready = qi->second.ready;
      ready.erase(
          std::remove_if(ready.begin(), ready.end(),
                         [&](const QMsg& m) { return ae.second.count(m.id) > 0; }),
          ready.end());
    }
    if (n_rec > 0) dirty = true;  // compact replayed records on first tick
    if (off < b.size())
      fprintf(stderr, "persist: torn WAL tail at %zu/%zu (stopped replay)\n",
              off, b.size());
  }

  void save_snapshot() {
    if (persist_path.empty()) return;
    std::string b;
    b.append("DTPUSNAP1");
    put_u64(b, (uint64_t)version);
    uint32_t n_kv = 0;
    for (auto& e : kv) if (e.second.lease_id == 0) ++n_kv;
    put_u32(b, n_kv);
    for (auto& e : kv) {
      if (e.second.lease_id != 0) continue;
      put_str(b, e.first);
      put_u64(b, (uint64_t)e.second.version);
      put_str(b, e.second.value);
    }
    put_u32(b, (uint32_t)queues.size());
    for (auto& qe : queues) {
      put_str(b, qe.first);
      put_u64(b, (uint64_t)qe.second.next_id);
      put_u32(b, (uint32_t)(qe.second.ready.size() + qe.second.in_flight.size()));
      for (auto& m : qe.second.ready) { put_u64(b, (uint64_t)m.id); put_str(b, m.payload); }
      for (auto& f : qe.second.in_flight) {
        put_u64(b, (uint64_t)f.second.first.id);
        put_str(b, f.second.first.payload);
      }
    }
    put_u32(b, (uint32_t)objects.size());
    for (auto& be : objects) {
      put_str(b, be.first);
      put_u32(b, (uint32_t)be.second.size());
      for (auto& oe : be.second) { put_str(b, oe.first); put_str(b, oe.second); }
    }
    // every failure below leaves the previous snapshot intact and keeps
    // dirty set, so the 2s tick retries — renaming a short write over
    // the last good snapshot would LOSE durably-persisted state
    std::string tmp = persist_path + ".tmp";
    FILE* f = fopen(tmp.c_str(), "wb");
    if (!f) { perror("snapshot open"); return; }
    bool ok = fwrite(b.data(), 1, b.size(), f) == b.size();
    ok = (fflush(f) == 0) && ok;
    ok = (fsync(fileno(f)) == 0) && ok;
    fclose(f);
    if (!ok) { perror("snapshot write"); unlink(tmp.c_str()); return; }
    if (rename(tmp.c_str(), persist_path.c_str()) != 0) {
      perror("snapshot rename");
      return;
    }
    dirty = false;
    last_snap = now_s();
    // a durable snapshot folds in everything the WAL recorded: truncate
    // (a crash between rename and truncate is safe — replay skips
    // q_push records the snapshot already holds, and kv/obj records
    // are idempotent)
    wal_truncate();
  }

  void load_snapshot() {
    if (persist_path.empty()) return;
    std::unordered_map<std::string, int64_t> snap_next;
    FILE* f = fopen(persist_path.c_str(), "rb");
    if (f) {
      std::string b;
      char buf[1 << 16];
      size_t n;
      while ((n = fread(buf, 1, sizeof buf, f)) > 0) b.append(buf, n);
      fclose(f);
      if (b.size() < 9 || b.compare(0, 9, "DTPUSNAP1") != 0) {
        fprintf(stderr, "persist: unrecognized snapshot header, ignoring\n");
      } else {
        Rd r{b, 9};
        version = (int64_t)r.u64();
        for (uint32_t i = r.u32(); r.ok && i > 0; --i) {
          std::string key = r.str();
          Entry e;
          e.version = (int64_t)r.u64();
          e.value = r.str();
          if (r.ok) kv[key] = std::move(e);
        }
        for (uint32_t i = r.ok ? r.u32() : 0; r.ok && i > 0; --i) {
          std::string name = r.str();
          QueueState& q = queues[name];
          q.next_id = (int64_t)r.u64();
          snap_next[name] = q.next_id;
          for (uint32_t j = r.u32(); r.ok && j > 0; --j) {
            QMsg m;
            m.id = (int64_t)r.u64();
            m.payload = r.str();
            if (r.ok) q.ready.push_back(std::move(m));  // in-flight -> ready
          }
        }
        for (uint32_t i = r.ok ? r.u32() : 0; r.ok && i > 0; --i) {
          std::string bucket = r.str();
          for (uint32_t j = r.u32(); r.ok && j > 0; --j) {
            std::string nm = r.str();
            std::string data = r.str();
            if (r.ok) objects[bucket][nm] = std::move(data);
          }
        }
        if (!r.ok)
          fprintf(stderr, "persist: truncated snapshot (partial restore)\n");
      }
    }
    // then the op log: everything acked since that snapshot
    replay_wal(snap_next);
  }

  // ---- periodic sweep ---------------------------------------------------
  void sweep() {
    // durability tick: fold mutations into a snapshot at most every 2s
    if (dirty && !persist_path.empty() && now_s() - last_snap > 2.0)
      save_snapshot();
    double now = now_s();
    std::vector<int64_t> expired;
    for (auto& kv2 : leases)
      if (kv2.second.expires_at <= now) expired.push_back(kv2.first);
    for (int64_t lid : expired) lease_revoke(lid);

    for (auto& qkv : queues) {
      auto& q = qkv.second;
      // redeliver timed-out in-flight messages (front of the queue)
      std::vector<int64_t> timed_out;
      for (auto& f : q.in_flight)
        if (f.second.second <= now) timed_out.push_back(f.first);
      for (int64_t mid : timed_out) {
        q.ready.push_front(std::move(q.in_flight[mid].first));
        q.in_flight.erase(mid);
      }
      // expire parked pops
      for (auto it = q.parked.begin(); it != q.parked.end();) {
        if (it->conn->dead) {
          it = q.parked.erase(it);
        } else if (it->deadline >= 0 && it->deadline <= now) {
          reply_ok(it->conn, it->rid, Val::nil());
          it = q.parked.erase(it);
        } else {
          ++it;
        }
      }
      if (!timed_out.empty()) serve_parked(qkv.first);
    }
  }

  // ---- connection lifecycle --------------------------------------------
  void drop_conn(Conn* c) {
    c->dead = true;
    for (int64_t sid : std::vector<int64_t>(c->stream_ids.begin(), c->stream_ids.end()))
      close_stream(c, sid, /*notify_end=*/false);
    for (int64_t lid : std::vector<int64_t>(c->leases.begin(), c->leases.end()))
      lease_revoke(lid);
    // Purge every raw Conn* reference BEFORE the Conn is destroyed: parked
    // queue pops (sweep()/serve_parked() would otherwise dereference freed
    // memory), plus any watch/sub registration whose sid drifted out of
    // c->stream_ids. conns.erase destroys the unique_ptr, so nothing may
    // point at c after this.
    for (auto& qkv : queues) {
      auto& parked = qkv.second.parked;
      parked.erase(std::remove_if(parked.begin(), parked.end(),
                                  [&](const ParkedPop& pp) { return pp.conn == c; }),
                   parked.end());
    }
    watches.erase(std::remove_if(watches.begin(), watches.end(),
                                 [&](const WatchReg& w) { return w.conn == c; }),
                  watches.end());
    subs.erase(std::remove_if(subs.begin(), subs.end(),
                              [&](const SubReg& s) { return s.conn == c; }),
               subs.end());
    close(c->fd);
    conns.erase(c->fd);
  }

  void pump_conn(Conn* c) {
    // parse complete frames from inbuf
    while (!c->dead) {
      if (c->inbuf.size() < 4) break;
      uint32_t len = (uint8_t)c->inbuf[0] | ((uint8_t)c->inbuf[1] << 8) |
                     ((uint8_t)c->inbuf[2] << 16) | ((uint8_t)c->inbuf[3] << 24);
      if (len > 256u * 1024 * 1024) { drop_conn(c); return; }
      if (c->inbuf.size() < 4 + (size_t)len) break;
      Decoder d{(const uint8_t*)c->inbuf.data() + 4, len};
      Val msg = d.decode();
      c->inbuf.erase(0, 4 + (size_t)len);
      if (!d.fail && msg.t == Val::MAP) handle(c, msg);
    }
  }

  // ---- main loop --------------------------------------------------------
  int run(const char* host, int port) {
    signal(SIGPIPE, SIG_IGN);
    load_snapshot();
    listen_fd = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1)
      addr.sin_addr.s_addr = INADDR_ANY;
    if (bind(listen_fd, (sockaddr*)&addr, sizeof addr) != 0) {
      perror("bind");
      return 1;
    }
    if (listen(listen_fd, 128) != 0) {
      perror("listen");
      return 1;
    }
    // report the actual port (port 0 = ephemeral) on stdout for drivers
    sockaddr_in bound{};
    socklen_t blen = sizeof bound;
    getsockname(listen_fd, (sockaddr*)&bound, &blen);
    printf("LISTENING %d\n", ntohs(bound.sin_port));
    fflush(stdout);

    std::vector<pollfd> fds;
    char buf[1 << 16];
    while (true) {
      fds.clear();
      fds.push_back({listen_fd, POLLIN, 0});
      for (auto& kv2 : conns) {
        short ev = POLLIN;
        if (!kv2.second->outbuf.empty()) ev |= POLLOUT;
        fds.push_back({kv2.first, ev, 0});
      }
      int rc = poll(fds.data(), (nfds_t)fds.size(), 100 /*ms: sweep tick*/);
      if (g_stop) {
        save_snapshot();
        return 0;
      }
      if (rc < 0 && errno != EINTR) {
        perror("poll");
        return 1;
      }
      if (fds[0].revents & POLLIN) {
        int fd = accept(listen_fd, nullptr, nullptr);
        if (fd >= 0) {
          fcntl(fd, F_SETFL, O_NONBLOCK);
          setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          auto c = std::make_unique<Conn>();
          c->fd = fd;
          conns[fd] = std::move(c);
        }
      }
      std::vector<Conn*> to_drop;
      for (size_t k = 1; k < fds.size(); ++k) {
        auto it = conns.find(fds[k].fd);
        if (it == conns.end()) continue;
        Conn* c = it->second.get();
        if (fds[k].revents & (POLLERR | POLLHUP)) {
          to_drop.push_back(c);
          continue;
        }
        if (fds[k].revents & POLLIN) {
          while (true) {
            ssize_t got = recv(c->fd, buf, sizeof buf, 0);
            if (got > 0) c->inbuf.append(buf, (size_t)got);
            else if (got == 0) { to_drop.push_back(c); break; }
            else if (errno == EAGAIN || errno == EWOULDBLOCK) break;
            else { to_drop.push_back(c); break; }
          }
          if (!c->dead) pump_conn(c);
        }
        if (fds[k].revents & POLLOUT) flush_conn(c, to_drop);
      }
      // writes generated by this tick's requests/streams
      for (auto& kv2 : conns)
        if (!kv2.second->outbuf.empty()) flush_conn(kv2.second.get(), to_drop);
      for (Conn* c : to_drop)
        if (conns.count(c->fd)) drop_conn(c);
      sweep();
    }
  }

  void flush_conn(Conn* c, std::vector<Conn*>& to_drop) {
    while (!c->outbuf.empty()) {
      ssize_t sent = send(c->fd, c->outbuf.data(), c->outbuf.size(), 0);
      if (sent > 0) c->outbuf.erase(0, (size_t)sent);
      else if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      else {
        if (std::find(to_drop.begin(), to_drop.end(), c) == to_drop.end())
          to_drop.push_back(c);
        break;
      }
    }
  }
};

int main(int argc, char** argv) {
  const char* host = "0.0.0.0";
  int port = 4222;
  const char* persist = nullptr;
  bool fsync_wal = false;
  for (int i = 1; i < argc; ++i) {
    if (!strcmp(argv[i], "--fsync-wal")) { fsync_wal = true; continue; }
    if (i >= argc - 1) break;
    if (!strcmp(argv[i], "--host")) host = argv[++i];
    else if (!strcmp(argv[i], "--port")) port = atoi(argv[++i]);
    else if (!strcmp(argv[i], "--persist-path")) persist = argv[++i];
  }
  Server s;
  if (persist) {
    s.persist_path = persist;
    s.wal_path = std::string(persist) + ".wal";
    s.fsync_wal = fsync_wal;
  }
  // graceful shutdown: fold state into a final snapshot (the poll loop
  // notices g_stop via EINTR / its 100ms tick)
  struct sigaction sa{};
  sa.sa_handler = on_term;
  sigaction(SIGTERM, &sa, nullptr);
  sigaction(SIGINT, &sa, nullptr);
  return s.run(host, port);
}
