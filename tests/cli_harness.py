"""Shared harness for full-process CLI e2e tests: spawn dynamo-tpu
subcommands as real subprocesses (logs to temp files so chatty workers
can't block on a full pipe), wait for HTTP readiness, and tear down
with logs surfaced."""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request
from typing import Any, Callable

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MODEL_DIR = os.path.join(REPO, "tests", "data", "tiny_llama_model")

ENV = dict(
    os.environ,
    PYTHONPATH=REPO,
    JAX_PLATFORMS="cpu",
    XLA_FLAGS="--xla_force_host_platform_device_count=1",
)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class CliFleet:
    """Spawns and tears down a set of dynamo-tpu CLI processes."""

    def __init__(self) -> None:
        self._fleet: list[tuple[subprocess.Popen | None, Any]] = []

    @property
    def procs(self) -> list[subprocess.Popen]:
        return [p for p, _ in self._fleet if p is not None]

    def spawn(self, *args: str, env: dict | None = None) -> subprocess.Popen:
        """``env`` adds/overrides variables on top of the shared ENV
        (e.g. a per-process DYN_TRACE_FILE)."""
        logf = tempfile.TemporaryFile()
        proc = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.cli.main", *args],
            env={**ENV, **(env or {})}, stdout=logf, stderr=subprocess.STDOUT,
        )
        self._fleet.append((proc, logf))
        return proc

    def forget(self, proc: subprocess.Popen) -> None:
        """Stop tracking a process the test killed deliberately (its log
        is still surfaced at teardown)."""
        self._fleet = [
            (p, f) if p is not proc else (None, f) for p, f in self._fleet
        ]

    def assert_alive(self) -> None:
        for p, _ in self._fleet:
            if p is not None:
                assert p.poll() is None, f"process died: {p.args}"

    def teardown(self) -> None:
        for p, _ in self._fleet:
            if p is not None:
                p.send_signal(signal.SIGTERM)
        chunks = []
        for p, logf in self._fleet:
            try:
                if p is not None:
                    p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
            try:
                logf.seek(0, os.SEEK_END)
                size = logf.tell()
                logf.seek(max(0, size - 1500))
                chunks.append(logf.read().decode(errors="replace"))
                logf.close()
            except Exception:
                pass
        print("\n=== process logs ===\n" + "\n---\n".join(chunks))


def wait_http(url: str, ready: Callable[[bytes], Any], timeout: float = 180.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                if ready(r.read()):
                    return
        except Exception:
            pass
        # throttle in BOTH branches: a 200-but-not-ready endpoint must not
        # be hammered during the startup it's waiting out
        time.sleep(0.5)
    raise TimeoutError(f"{url} never became ready")


def complete(port: int, prompt: str, max_tokens: int,
             model: str = "tiny_llama_model", rid: str | None = None) -> dict:
    """Non-streaming /v1/completions call; returns the parsed response.
    ignore_eos rides the ext options (extension(), protocols/openai.py).
    ``rid`` sets X-Request-Id so the request's autopsy record is
    addressable at /debug/request/{rid} afterwards."""
    body = json.dumps({
        "model": model, "prompt": prompt, "max_tokens": max_tokens,
        "ext": {"ignore_eos": True},
    }).encode()
    headers = {"Content-Type": "application/json"}
    if rid:
        headers["X-Request-Id"] = rid
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/completions", data=body,
        headers=headers,
    )
    with urllib.request.urlopen(req, timeout=180) as r:
        return json.load(r)


def fetch_autopsy(port: int, rid: str, timeout: float = 20.0) -> dict:
    """Poll /debug/request/{rid} until the record is finished (the
    streaming path closes it in a finally that can trail the last SSE
    byte by a beat)."""
    url = f"http://127.0.0.1:{port}/debug/request/{rid}"
    deadline = time.monotonic() + timeout
    last: dict = {}
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=5) as r:
                last = json.load(r)
            if last.get("finished"):
                return last
        except Exception:
            pass
        time.sleep(0.5)
    raise TimeoutError(f"no finished autopsy record for {rid}: {last}")
