"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Mirrors the reference's multi-node-without-a-cluster test ladder
(reference: SURVEY.md §4): pure-logic tests + fake accelerators. All sharding
tests run against 8 virtual CPU devices so multi-chip code paths execute
without TPU hardware.
"""

import os

# Must be set before jax is imported anywhere.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Persistent XLA compilation cache (.jax_cache/, gitignored): tier-1 is
# dominated by re-jitting the same programs on every run — and every
# CLI-e2e subprocess recompiles them again from scratch. Set through the
# environment (not jax.config) so spawned worker processes inherit it.
# setdefault keeps any externally-configured cache location in charge.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache",
    ),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


# Tests must never touch the real chip (the TPU plugin registers at
# interpreter boot and backend init dials the single-tenant TPU tunnel).
import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from dynamo_tpu.utils.jaxtools import force_platform  # noqa: E402

force_platform("cpu", cpu_devices=8)


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """Minimal asyncio support: run ``async def`` tests via asyncio.run."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        # generous hang-cap: subprocess-spawning tests (supervisor e2e) can
        # take minutes under full-suite CPU contention
        asyncio.run(asyncio.wait_for(fn(**kwargs), timeout=300))
        return True
    return None
