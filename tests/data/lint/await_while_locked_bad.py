"""Fixture: await under a threading lock (DL005 must fire)."""
import threading

_lock = threading.Lock()


async def update(shared):
    with _lock:
        await shared.flush()  # VIOLATION: suspends holding a thread lock


async def update_inline(shared):
    with threading.RLock():
        await shared.flush()  # VIOLATION
