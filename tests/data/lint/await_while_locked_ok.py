"""Fixture: clean locking patterns (DL005 must stay quiet)."""
import asyncio
import threading

_alock = asyncio.Lock()
_tlock = threading.Lock()


async def update(shared):
    async with _alock:
        await shared.flush()  # asyncio lock: suspension is safe


def sync_update(shared):
    with _tlock:
        shared.flush()  # sync code: no suspension possible


async def read_then_await(shared):
    with _tlock:
        snapshot = shared.value  # critical section stays synchronous
    await shared.publish(snapshot)
