"""Fixture: bare except (DL006 must fire)."""


def parse(payload):
    try:
        return int(payload)
    except:  # noqa: E722 — VIOLATION: swallows SystemExit/KeyboardInterrupt
        return None
