"""Fixture: typed excepts (DL006 must stay quiet)."""


def parse(payload):
    try:
        return int(payload)
    except ValueError:
        return None
    except Exception:
        return None
