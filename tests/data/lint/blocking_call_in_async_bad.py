"""Fixture: blocking calls on the event loop (DL001 must fire)."""
import subprocess
import time


async def refresh_loop():
    while True:
        time.sleep(0.5)  # VIOLATION: parks the whole event loop
        subprocess.run(["true"])  # VIOLATION: blocks until the child exits
