"""Fixture: the clean counterparts (DL001 must stay quiet)."""
import asyncio
import time


def blocking_io():
    # sync def: runs on whatever thread calls it, not the loop
    time.sleep(0.5)


async def refresh_loop():
    while True:
        await asyncio.sleep(0.5)
        await asyncio.get_running_loop().run_in_executor(None, blocking_io)
