"""Violating fixture for blocking-work-in-chunk-path (DL013): the SSE
writer loop doing heavyweight per-chunk work — every call here runs
once per delta for every open stream on ONE event loop, so each is
multiplied by streams × chunks at the fan-out ceiling."""

import json
import time


async def _stream_sse(resp, stream, tokenizer, state):
    history = []
    async for chunk in stream:
        history.append(chunk)
        payload = json.dumps(history)  # VIOLATION: whole-aggregate dump per delta
        text = tokenizer.decode(state.all_token_ids)  # VIOLATION: re-decodes history
        time.sleep(0.0005)  # VIOLATION: sync sleep parks the whole loop
        open("/tmp/sse.log", "a").write(text)  # VIOLATION: sync file op per chunk
        await resp.write(payload.encode())


def sse_write_pump(sock, chunks, agg):
    for c in chunks:
        sock.sendall(json.dumps(agg).encode())  # VIOLATION: sync socket send
        # (the json.dumps above is flagged separately — two findings on
        # one line: aggregate serialization AND a blocking socket op)


async def _stream_sse_tools(resp, stream, agg):
    async for chunk in stream:
        def render():
            # a helper defined in the loop still runs per chunk
            return json.dumps(agg)  # VIOLATION: aggregate dump in loop helper

        await resp.write(render().encode())
