"""Clean fixture for blocking-work-in-chunk-path (DL013): the SSE
writer loop serializes only the DELTA per chunk and does its one-shot
work before the loop starts; the aggregate render happens once, after
the stream completes. (Also exercised against every other rule — clean
fixtures must be clean, period.)"""

import json


def encode_delta(chunk):
    # delta-only serializer (the encode_sse idiom): the per-chunk cost
    # is proportional to the DELTA, not the stream so far
    return f"data: {json.dumps(chunk)}\n\n"


async def _stream_sse(resp, stream, tokenizer):
    # one-shot priming work BEFORE the loop is not per-chunk cost
    header = json.dumps({"object": "chat.completion.chunk"})
    await resp.write(header.encode())
    chunks = []
    async for chunk in stream:
        chunks.append(chunk)
        await resp.write(encode_delta(chunk).encode())
    # aggregate serialization happens ONCE, after the stream drained
    await resp.write(json.dumps({"chunks": len(chunks)}).encode())
