"""Violating fixture for DL302 collective-axis-mismatch: collectives
named over axes the enclosing shard_map never declared — in the body
itself and one call level down."""

import jax
from jax.sharding import PartitionSpec as P

from dynamo_tpu.utils.jaxtools import shard_map


def forward(mesh, x):
    def stage(x_l):
        total = jax.lax.psum(x_l, "pp")  # declared axis: fine
        drift = jax.lax.psum(x_l, "dp")  # VIOLATION: dp not declared
        rank = jax.lax.axis_index("mp")  # VIOLATION: mp not declared
        return reduce_helper(total + drift + rank)

    return shard_map(
        stage,
        mesh=mesh,
        in_specs=(P("pp"),),
        out_specs=P("pp"),
        axis_names={"pp"},
    )


def reduce_helper(y):
    # one call level below the mapped body
    return jax.lax.all_gather(y, "dp")  # VIOLATION: dp not declared
