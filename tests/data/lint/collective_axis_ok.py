"""Clean fixture for DL302 collective-axis-mismatch: collectives only
name axes the enclosing shard_map declares, and variable axis names
degrade to counted misses rather than guesses."""

import jax
from jax.sharding import PartitionSpec as P

from dynamo_tpu.utils.jaxtools import shard_map


def forward(mesh, x):
    def stage(x_l):
        total = jax.lax.psum(x_l, "pp")
        return jax.lax.all_gather(total, ("pp",))

    return shard_map(
        stage,
        mesh=mesh,
        in_specs=(P("pp"),),
        out_specs=P("pp"),
        axis_names={"pp"},
    )


def ring(mesh, q, axis_name):
    # axis name arrives as a parameter: the rule refuses to guess and
    # records a dynamic miss instead of flagging
    def local(q_l):
        return jax.lax.ppermute(q_l, axis_name, [(0, 1)])

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(None),),
        out_specs=P(None),
    )
