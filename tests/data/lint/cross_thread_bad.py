"""Violating fixture for DL103 cross-thread-mutation: attributes
shared between the engine thread and the event loop with no declared
handoff — the writes race silently today and break mysteriously later."""

from dynamo_tpu.utils.affinity import guard_attrs, thread_affinity


class Engine:
    def __init__(self):
        self.spec_paused = False  # construction writes are exempt
        self.steps_done = 0
        guard_attrs(self, {"spec_paused": "engine"})

    @thread_affinity("engine")
    def step_once(self):
        self.steps_done = self.steps_done + 1  # fine: engine-only attr
        if self.spec_paused:
            return None
        return self.run()

    def run(self):
        return object()


class Watcher:
    def __init__(self, engine):
        self.engine = engine

    async def on_rung_change(self, level):
        # two call levels below the coroutine: loop-affine taint rides
        # the calls down to the write
        self.apply_rung(level)

    def apply_rung(self, level):
        self.push_level(level)

    def push_level(self, level):
        self.engine.spec_paused = level >= 2  # VIOLATION: loop writes engine-affine attr


class Counter:
    """Undeclared shared attribute: written from both domains."""

    def __init__(self):
        self.total = 0

    @thread_affinity("engine")
    def bump_from_engine(self):
        self.total = self.total + 1  # VIOLATION: shares with loop write

    async def reset_from_loop(self):
        self.total = 0  # VIOLATION: shares with engine write
