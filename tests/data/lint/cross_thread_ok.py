"""Clean fixture for DL103: every cross-domain write is a declared
handoff — affinity.handoff(...), a lock, a threadsafe loop call, or an
explicit `# dynalint: handoff=` marker on the deliberate seam."""

import threading

from dynamo_tpu.utils import affinity
from dynamo_tpu.utils.affinity import guard_attrs, thread_affinity


class Engine:
    def __init__(self):
        self.spec_paused = False
        self.steps_done = 0
        self._lock = threading.Lock()
        guard_attrs(self, {"spec_paused": "engine"})

    @thread_affinity("engine")
    def step_once(self):
        with self._lock:
            self.steps_done = self.steps_done + 1
        if self.spec_paused:
            return None
        return self.run()

    def run(self):
        return object()


class Watcher:
    def __init__(self, engine, loop):
        self.engine = engine
        self.loop = loop

    async def on_rung_change(self, level):
        self.apply_rung(level)

    def apply_rung(self, level):
        # declared on BOTH planes: the runtime sanctions the write, the
        # comment tells the static rule (and the reader) why it is safe
        with affinity.handoff("rung -> engine.spec_paused"):
            self.engine.spec_paused = level >= 2  # dynalint: handoff=rung flip — engine reads the bool each step


class Counter:
    def __init__(self):
        self.total = 0
        self._lock = threading.Lock()

    @thread_affinity("engine")
    def bump_from_engine(self):
        with self._lock:
            self.total = self.total + 1

    async def reset_from_loop(self):
        with self._lock:
            self.total = 0
