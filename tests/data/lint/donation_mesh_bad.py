"""Violating fixture for DL303 donation-across-mesh: buffer donation
under a mismatched sharding story — donating jits invoked from inside
shard_map bodies (directly and via a helper), and a donated argument
whose constrained layout disagrees with the jit's declared
in_shardings."""

import functools

import jax
from jax.sharding import PartitionSpec as P

from dynamo_tpu.utils.jaxtools import shard_map


@functools.partial(jax.jit, donate_argnums=(0,))
def update(buf, delta):
    return buf + delta


def mapped_update(mesh, buf, delta):
    def body(b_l, d_l):
        return update(b_l, d_l)  # VIOLATION: donation inside the body

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P("dp"), P("dp")),
        out_specs=P("dp"),
        axis_names={"dp"},
    )


def nested_update(mesh, buf, delta):
    def body(b_l, d_l):
        return via_helper(b_l, d_l)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P("dp"), P("dp")),
        out_specs=P("dp"),
        axis_names={"dp"},
    )


def via_helper(b, d):
    # one call level below the mapped body
    return update(b, d)  # VIOLATION: donation inside the body


def dispatch(params, state):
    fn = jax.jit(
        apply_fn, in_shardings=(P("dp"), P(None)), donate_argnums=(0,)
    )
    state = jax.lax.with_sharding_constraint(state, P("mp"))
    return fn(state, params)  # VIOLATION: constrained P("mp"), declared P("dp")


def apply_fn(state, params):
    return state * params
