"""Clean fixture for DL303 donation-across-mesh: donation happens at
the unmapped boundary, and donated arguments are constrained to the
same layout the jit declares."""

import functools

import jax
from jax.sharding import PartitionSpec as P

from dynamo_tpu.utils.jaxtools import shard_map


@functools.partial(jax.jit, donate_argnums=(0,))
def update(buf, delta):
    return buf + delta


def mapped_then_update(mesh, buf, delta):
    def body(b_l, d_l):
        return b_l + d_l

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(P("dp"), P("dp")),
        out_specs=P("dp"),
        axis_names={"dp"},
    )
    summed = mapped(buf, delta)
    # donation at the unmapped boundary: the buffer's layout is settled
    return update(summed, delta)


def dispatch(params, state):
    fn = jax.jit(
        apply_fn, in_shardings=(P("dp"), P(None)), donate_argnums=(0,)
    )
    # constrained layout matches the declared in_sharding: donation is
    # a true in-place reuse, no resharding copy
    state = jax.lax.with_sharding_constraint(state, P("dp"))
    return fn(state, params)


def apply_fn(state, params):
    return state * params
