"""Fixture: fire-and-forget task with no handle (DL002 must fire)."""
import asyncio


async def pump():
    await asyncio.sleep(0)


async def start():
    asyncio.create_task(pump())  # VIOLATION: handle dropped, GC may cancel
