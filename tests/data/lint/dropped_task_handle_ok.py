"""Fixture: task handles kept (DL002 must stay quiet)."""
import asyncio


async def pump():
    await asyncio.sleep(0)


async def start():
    task = asyncio.create_task(pump())  # assigned: strong reference held
    tasks = [asyncio.create_task(pump()) for _ in range(2)]  # registered
    await asyncio.gather(task, *tasks)  # used as an argument
