"""Violating fixture for DL202 dynamic-static-arg: per-step values,
device arrays, and unhashable containers flowing into jit static slots
— each one a silent recompile (or TypeError) per step."""

import functools

import jax


@functools.partial(jax.jit, static_argnums=(1,), static_argnames=("mode",))
def bucketed_kernel(x, width, mode="decode"):
    return x[:width]


@jax.jit
def device_step(x):
    return x * 2


def pad_rows(x, width):
    # wrapper frame: `width` lands in bucketed_kernel's static slot —
    # callers one level up inherit the constraint
    return bucketed_kernel(x, width)


def run_step_loop(state):
    while state.running:
        batch = state.next_batch()
        n = len(batch)
        out = bucketed_kernel(state.x, n)  # VIOLATION: per-step local
        out = bucketed_kernel(state.x, len(batch))  # VIOLATION: computed per call
        out = pad_rows(state.x, state.width_of(batch))  # VIOLATION: dynamic, one frame up
        state.emit(out)


def traced_width(state):
    y = device_step(state.x)
    return bucketed_kernel(state.x, y)  # VIOLATION: device array as static


def unhashable_mode(x):
    return bucketed_kernel(x, 4, mode=["decode", "prefill"])  # VIOLATION: unhashable
