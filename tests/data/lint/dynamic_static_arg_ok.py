"""Clean fixture for DL202: static slots fed genuine compile-time
constants — literals, module constants, forwarded parameters — and
dynamic per-batch values routed through a bucket table hoisted OUT of
the step loop (the compile-once-per-bucket discipline)."""

import functools

import jax

BLOCK_SIZE = 16
WIDTH_BUCKETS = (8, 16, 32)


@functools.partial(jax.jit, static_argnums=(1,), static_argnames=("mode",))
def bucketed_kernel(x, width, mode="decode"):
    return x[:width]


def pad_rows(x, width):
    # forwarding a parameter keeps the constraint at the caller, where
    # the constant lives
    return bucketed_kernel(x, width)


def prewarm(state):
    # init-time loops over the bucket ladder are the SANCTIONED way to
    # feed a static slot several values: one deliberate compile each,
    # before serving
    for width in WIDTH_BUCKETS:
        bucketed_kernel(state.x, width)


def run_step_loop(state):
    while state.running:
        out = bucketed_kernel(state.x, BLOCK_SIZE)
        out = pad_rows(out, BLOCK_SIZE)
        out = bucketed_kernel(out, state.config_width, mode="decode")
        state.emit(out)
