"""Violating fixture for hidden-host-sync-in-step-loop (DL010): the
engine step loop synchronizing device->host outside the designated
harvest point — every one of these re-serializes the overlapped decode
pipeline (the device drains while the host blocks mid-plan)."""

import jax
import numpy as np

from dynamo_tpu.parallel.multihost import host_value


def step_loop(engine):
    while engine.running:
        out = engine.dispatch()
        toks = np.asarray(out)  # VIOLATION: sync mid-loop, not at harvest
        jax.block_until_ready(out)  # VIOLATION: host parks on the device
        n = engine.counter.item()  # VIOLATION: scalar read is a full sync
        lps = host_value(out)  # VIOLATION: the house sync, same problem
        engine.emit(toks, lps, n)


def decode_step_loop(engine):
    def drain(out):
        # nested helper closures are part of the loop (only
        # harvest-named defs scope apart)
        return out.tolist()  # VIOLATION: hidden sync in a loop helper

    for out in engine.pending:
        drain(out)
        out.block_until_ready()  # VIOLATION: per-item hard sync
