"""Clean fixture for hidden-host-sync-in-step-loop (DL010): the step
loop keeps device handles in flight and funnels its ONE device->host
sync through a harvest-named function — the engine's
``_harvest_device_step`` idiom (docs/performance.md). While the newest
dispatch executes on device, the host materializes only the oldest,
already-finished result."""

import numpy as np


def harvest_step(out):
    # the designated harvest point: the loop's single sync lives here,
    # waiting on a result that is already (or nearly) done
    return np.asarray(out)


def step_loop(engine):
    inflight = None
    while engine.running:
        nxt = engine.dispatch()  # device starts step N+1 ...
        if inflight is not None:
            engine.emit(harvest_step(inflight))  # ... while N lands
        inflight = nxt
    if inflight is not None:
        engine.emit(harvest_step(inflight))
