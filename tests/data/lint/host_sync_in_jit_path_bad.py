"""Fixture: host-device syncs inside jit paths (DL004 must fire)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnums=(1,))
def decode_step(tokens, width):
    probs = jnp.ones((width,))
    top = probs.item()  # VIOLATION: device->host sync at trace time
    host = np.asarray(probs)  # VIOLATION: materializes on host
    return top, host


def step(tokens):
    out = tokens + 1
    out.block_until_ready()  # VIOLATION: step is jit-compiled below
    return out


_step_fn = jax.jit(step, donate_argnums=(0,))
