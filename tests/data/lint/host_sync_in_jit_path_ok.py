"""Fixture: syncs only outside the compiled functions (DL004 quiet)."""
import jax
import jax.numpy as jnp


@jax.jit
def decode_step(tokens):
    scale = float(tokens.shape[-1]) ** -0.5  # static shape math: fine
    return jnp.argmax(tokens * scale, axis=-1)


def host_side(tokens):
    # not jit-compiled: syncing here is the correct place
    arr = decode_step(tokens)
    arr.block_until_ready()
    return arr.item()
