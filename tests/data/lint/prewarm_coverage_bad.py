"""Violating fixture for DL203 prewarm-coverage: jitted callables the
step loop reaches that no prewarm path references — each one a
mid-serve XLA compile on first use."""

import functools

import jax


def _step(x):
    return x + 1


def _chain(x, idx):
    return x[idx]


@functools.partial(jax.jit, donate_argnums=())
def extra_kernel(col):
    return col * 2


@jax.jit
def pack_pair(a, b):
    return a, b


def dispatch_extra(col):
    # one frame below the loop: the compile lands here, mid-serve
    return extra_kernel(col)  # VIOLATION: never prewarmed


class Engine:
    def __init__(self):
        self.running = True
        self._step_fn = jax.jit(_step)
        self._chain_fn = jax.jit(_chain)

    def _prewarm(self):
        # warms the step... and forgets every other serve-path variant
        self._step_fn(self.batch)

    def run_step_loop(self):
        while self.running:
            out = self._step_fn(self.batch)
            col = self._chain_fn(out, self.idx)  # VIOLATION: never prewarmed
            packed = pack_pair(out, col)  # VIOLATION: never prewarmed
            self.emit(dispatch_extra(packed))

    def emit(self, packed):
        self.sink(packed)
