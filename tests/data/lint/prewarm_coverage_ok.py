"""Clean fixture for DL203: every jitted callable the step loop can
reach is referenced on a prewarm path — directly, through a warm
helper, or one call level down."""

import functools

import jax


def _step(x):
    return x + 1


def _chain(x, idx):
    return x[idx]


@functools.partial(jax.jit, donate_argnums=())
def extra_kernel(col):
    return col * 2


@jax.jit
def pack_pair(a, b):
    return a, b


def dispatch_extra(col):
    return extra_kernel(col)


def warm_glue(engine):
    # reached FROM _prewarm: references here count as coverage
    packed = pack_pair(engine.batch, engine.batch)
    dispatch_extra(packed)


class Engine:
    def __init__(self):
        self.running = True
        self._step_fn = jax.jit(_step)
        self._chain_fn = jax.jit(_chain)

    def _prewarm(self):
        out = self._step_fn(self.batch)
        self._chain_fn(out, self.idx)
        warm_glue(self)

    def run_step_loop(self):
        while self.running:
            out = self._step_fn(self.batch)
            col = self._chain_fn(out, self.idx)
            packed = pack_pair(out, col)
            self.emit(dispatch_extra(packed))

    def emit(self, packed):
        self.sink(packed)
