"""Violating fixture for DL301 host-sync-in-shard-body: device->host
syncs reachable from inside shard_map-wrapped bodies — direct frames,
nested closures, and helpers the body calls."""

import numpy as np
from jax.sharding import PartitionSpec as P

from dynamo_tpu.utils.jaxtools import shard_map


def ring_forward(mesh):
    def local(q_l, k_l, v_l):
        # direct frame of the mapped body
        depth = int(q_l.sum().item())  # VIOLATION: per-shard host sync
        gather_stats(k_l)
        return attend(q_l, k_l, v_l) + depth

    def attend(q_l, k_l, v_l):
        # nested closure: still the body's frame family
        return deep_norm(q_l + k_l + v_l)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P("dp"), P("dp"), P("dp")),
        out_specs=P("dp"),
        axis_names={"dp"},
    )


def gather_stats(k):
    # one call level below the mapped body
    return np.asarray(k)  # VIOLATION: per-shard host sync

def deep_norm(x):
    # two call levels below the body (local -> attend -> deep_norm)
    return x / sum(x.tolist())  # VIOLATION: per-shard host sync
