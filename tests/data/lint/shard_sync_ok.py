"""Clean fixture for DL301 host-sync-in-shard-body: the mapped body
stays device-only; host materialization happens at the unmapped
boundary after the shard_map call returns."""

import numpy as np
from jax.sharding import PartitionSpec as P

from dynamo_tpu.utils.jaxtools import shard_map


def ring_forward(mesh, q, k, v):
    def local(q_l, k_l, v_l):
        return attend(q_l, k_l, v_l)

    def attend(q_l, k_l, v_l):
        return q_l + k_l + v_l

    mapped = shard_map(
        local,
        mesh=mesh,
        in_specs=(P("dp"), P("dp"), P("dp")),
        out_specs=P("dp"),
        axis_names={"dp"},
    )
    out = mapped(q, k, v)
    # host read OUTSIDE the mapped region: one sync for the whole mesh
    return np.asarray(out)


def summarize(x):
    # host sync in a plain helper nobody maps: fine
    return float(x.sum())
