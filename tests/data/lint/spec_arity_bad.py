"""Violating fixture for DL304 spec-arity-drift: literal
in_specs/out_specs tuples whose arity disagrees with the wrapped
callable's signature or return shape, and specs naming axes the site
never declared."""

from jax.sharding import PartitionSpec as P

from dynamo_tpu.utils.jaxtools import shard_map


def too_few(mesh, q, k, v):
    def body(q_l, k_l, v_l):
        return q_l + k_l + v_l

    return shard_map(  # VIOLATION: 2 in_specs for a 3-parameter body
        body,
        mesh=mesh,
        in_specs=(P("dp"), P("dp")),
        out_specs=P("dp"),
        axis_names={"dp"},
    )


def wrong_out(mesh, q, k):
    def body(q_l, k_l):
        return q_l, k_l, q_l + k_l

    return shard_map(  # VIOLATION: body returns a 3-tuple, 2 out_specs
        body,
        mesh=mesh,
        in_specs=(P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp")),
        axis_names={"dp"},
    )


def stray_axis(mesh, x):
    def body(x_l):
        return x_l

    return shard_map(  # VIOLATION: specs name mp, site declares only dp
        body,
        mesh=mesh,
        in_specs=(P("mp"),),
        out_specs=P("mp"),
        axis_names={"dp"},
    )
