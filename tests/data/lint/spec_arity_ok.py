"""Clean fixture for DL304 spec-arity-drift: specs match the wrapped
signature and declared axes; dynamic specs and variadic bodies degrade
to counted misses rather than guessed indices."""

from jax.sharding import PartitionSpec as P

from dynamo_tpu.utils.jaxtools import shard_map


def matched(mesh, q, k, v):
    def body(q_l, k_l, v_l):
        return q_l, k_l + v_l

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P("dp"), P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp")),
        axis_names={"dp"},
    )


def dynamic_specs(mesh, x, specs):
    # in_specs arrives as a value: counted miss, never a guessed index
    def body(x_l):
        return x_l

    return shard_map(
        body,
        mesh=mesh,
        in_specs=specs,
        out_specs=P(None),
        axis_names={"dp"},
    )


def variadic(mesh, args):
    # *args body: no positional arity to compare against
    def body(*xs):
        return xs[0]

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P("dp"), P("dp")),
        out_specs=P("dp"),
        axis_names={"dp"},
    )
