"""Fixture: cancellation absorbed by a handler (DL003 must fire)."""
import asyncio


async def worker(queue):
    try:
        while True:
            await queue.get()
    except (ConnectionError, asyncio.CancelledError):  # VIOLATION
        pass


async def reaper(child):
    try:
        await child
    except BaseException:  # VIOLATION: catches CancelledError, no re-raise
        return None
