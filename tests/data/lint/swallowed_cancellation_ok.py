"""Fixture: cancellation re-raised (DL003 must stay quiet)."""
import asyncio


async def worker(queue):
    try:
        while True:
            await queue.get()
    except asyncio.CancelledError:
        raise
    except ConnectionError:
        pass


async def reaper(child):
    try:
        await child
    except BaseException:
        raise  # observed, then propagated
