"""Violating fixture for DL101 transitive-blocking-call-in-async: the
blocking calls live in SYNC helpers — invisible to DL001 — reached from
coroutines through ordinary calls, partials, and callback references."""

import functools
import time

import requests


async def handle_request(payload):
    # level 0: clean async frame (nothing for DL001 here)
    return await process(payload)


async def process(payload):
    prepared = prepare(payload)  # async -> sync, level 1
    schedule(functools.partial(slow_io, prepared))  # ref via partial
    return prepared


def prepare(payload):
    return _retry_fetch(payload)  # level 2


def _retry_fetch(payload):
    for _ in range(3):
        time.sleep(0.5)  # VIOLATION: 2+ call levels below a coroutine
        out = requests.get(payload)  # VIOLATION: blocks the event loop
        if out:
            return out
    return None


def schedule(fn):
    fn()


def slow_io(prepared):
    time.sleep(1.0)  # VIOLATION: reached via functools.partial ref
    return prepared
