"""Clean fixture for DL101: the same helpers, but every blocking call
is either handed off to another thread (executor / to_thread — the
sanctioned remediation) or lives in code declared for a non-loop domain
(the engine thread may sleep; it is not the event loop)."""

import asyncio
import time

from dynamo_tpu.utils.affinity import thread_affinity


async def handle_request(payload):
    loop = asyncio.get_running_loop()
    # blocking helper runs on a pool thread: the handoff cuts the taint
    prepared = await loop.run_in_executor(None, prepare, payload)
    await asyncio.to_thread(slow_io, prepared)
    return prepared


def prepare(payload):
    return _retry_fetch(payload)


def _retry_fetch(payload):
    for _ in range(3):
        time.sleep(0.5)  # fine: only ever reached via an executor
        if payload:
            return payload
    return None


def slow_io(prepared):
    time.sleep(1.0)  # fine: asyncio.to_thread target
    return prepared


@thread_affinity("engine")
def engine_pacing(budget_s):
    # fine: declared engine-thread code — the dedicated step-loop
    # thread may sleep without stalling the event loop
    time.sleep(budget_s)
