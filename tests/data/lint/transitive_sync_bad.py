"""Violating fixture for DL102 transitive-host-sync-in-step-loop:
device->host syncs in helpers the step loop reaches through calls —
outside DL010's single-frame view."""

import numpy as np


def run_step_loop(state):
    while state.running:
        plan = make_plan(state)
        dispatch(state, plan)


def make_plan(state):
    # level 1 below the loop: DL010 cannot see this frame
    depth = int(state.queue_depth.item())  # VIOLATION: hidden sync
    return {"depth": depth}


def dispatch(state, plan):
    state.launch(plan)
    note_stats(plan)


def note_stats(plan):
    # level 2 below the loop, reached via dispatch
    tokens = np.asarray(plan["tokens"])  # VIOLATION: hidden sync
    plan["stats"] = tokens.sum()


def drain(state):
    return finalize(state)


def finalize(state):
    # two levels below step_loop_tail (a second loop entry point)
    return state.result.tolist()  # VIOLATION: hidden sync


def step_loop_tail(state):
    return drain(state)
