"""Clean fixture for DL102: the step loop's one device->host sync
happens inside the harvest-named function — helpers the harvest alone
calls inherit its exemption, and host-side planning stays sync-free."""

import numpy as np


def run_step_loop(state):
    while state.running:
        plan = make_plan(state)
        handle = dispatch(state, plan)
        out = harvest_step(handle)
        emit(state, out)


def make_plan(state):
    # host-side bookkeeping only: no device arrays touched
    return {"depth": state.queue_depth_host}


def dispatch(state, plan):
    return state.launch(plan)


def harvest_step(handle):
    # THE designated sync point: name-scoped out of DL010 and DL102
    packed = np.asarray(handle.packed)
    return unpack(packed)


def unpack(packed):
    # only the harvest calls this: it inherits the harvest exemption
    return packed.tolist()


def emit(state, out):
    state.sink(out)
