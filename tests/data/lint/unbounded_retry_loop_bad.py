"""Fixture: tight reconnect loops with no pacing (DL008 must fire)."""
import asyncio


async def reconnect_forever(host, port):
    while True:
        try:
            reader, writer = await asyncio.open_connection(host, port)  # VIOLATION: no backoff between redials
            return reader, writer
        except OSError:
            continue


async def redial_client(client):
    while True:
        try:
            await client.connect()  # VIOLATION: hammers a flapping peer
            break
        except ConnectionError:
            pass
