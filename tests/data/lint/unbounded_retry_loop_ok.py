"""Fixture: paced reconnect loops (DL008 must stay quiet)."""
import asyncio

from dynamo_tpu.utils.backoff import Backoff


async def reconnect_with_backoff(host, port):
    backoff = Backoff(base_s=0.2, cap_s=10.0)  # capped exponential + jitter
    while True:
        try:
            reader, writer = await asyncio.open_connection(host, port)
            return reader, writer
        except OSError:
            await backoff.sleep()


async def redial_with_plain_sleep(client):
    while True:
        try:
            await client.connect()
            break
        except ConnectionError:
            await asyncio.sleep(1.0)  # fixed pacing still bounds the rate


async def read_loop(reader, handle):
    # read loops block on DATA, not on connection establishment: never
    # flagged even without a sleep
    while True:
        frame = await reader.readexactly(4)
        handle(frame)
