"""DL007 violations: telemetry buffers that only ever grow."""

from collections import deque


class StepTelemetry:
    def __init__(self):
        self.step_records = []
        self.events = deque()  # deque without maxlen is just as leaky
        self.latencies: list = []

    def on_step(self, record, event, ms):
        self.step_records.append(record)  # VIOLATION: no trim anywhere
        self.events.append(event)  # VIOLATION: deque() has no maxlen
        self.latencies += [ms]  # VIOLATION: += grows the same way
