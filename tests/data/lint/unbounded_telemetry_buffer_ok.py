"""DL007 clean patterns: bounded by construction, or trimmed after
appending."""

from collections import deque


class StepTelemetry:
    def __init__(self):
        self.step_records = deque(maxlen=256)  # bounded by construction
        self.history = []
        self.events = []
        self.block_table = []  # not a telemetry buffer: out of scope

    def on_step(self, record, snap, event, block):
        self.step_records.append(record)
        self.history.append(snap)
        del self.history[:-600]  # explicit trim after append
        self.events.append(event)
        self.block_table.append(block)

    def flush(self):
        out = list(self.events)
        self.events.clear()  # drained elsewhere: has a lifecycle
        return out
