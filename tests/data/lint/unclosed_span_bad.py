"""Fixture: span lifecycles that leak on some (or all) paths."""

from telemetry import get_tracer, spans


def never_ended(request):
    span = get_tracer().span("http.request")  # VIOLATION: no end() at all
    span.set_attr("model", request.model)
    return handle(request)


def conditional_end_only(ok):
    span = get_tracer().span("work")  # VIOLATION: end() only in one arm
    if ok:
        span.end()
    return ok


def end_in_except_only(fn):
    span = spans.start("risky")  # VIOLATION: end() only on the error path
    try:
        fn()
    except ValueError:
        span.end()


def early_exit_between(items):
    span = get_tracer().span("batch")
    for it in items:
        if it is None:
            return None  # VIOLATION: leaves before span.end()
    span.end()
    return items


async def async_leak(ctx):
    span = get_tracer().span("worker.generate", parent=ctx)  # VIOLATION
    await do_work(ctx)
    if ctx.killed:
        span.end()


def handle(request):
    return request


async def do_work(ctx):
    return ctx
