"""Fixture: sanctioned span lifecycles — none of these flag."""

from telemetry import get_tracer, spans


def with_block(request):
    with get_tracer().span("http.request") as span:
        span.set_attr("model", request.model)
        return handle(request)


def named_with(request):
    span = get_tracer().span("http.request")
    with span:
        return handle(request)


def end_in_finally(request):
    span = get_tracer().span("http.request")
    try:
        if request is None:
            return None
        return handle(request)
    finally:
        span.end()


def straight_line(request):
    span = spans.start("preprocess")
    span.set_attr("kind", "chat")
    out = handle(request)
    span.end()
    return out


def escapes_as_return(request):
    # the caller owns the lifecycle now — not this function's leak
    return get_tracer().span("stream", attrs={"rid": request.rid})


def escapes_into_context(request, ctx):
    span = get_tracer().span("router.dispatch")
    ctx.set_trace(span)  # handed off: downstream ends it
    return ctx


def escapes_via_propagation(req, ctx):
    span = get_tracer().span("prefill_queue.wait", parent=ctx)
    try:
        return propagation_context(span, ctx)
    finally:
        span.end()


def truthiness_gate(ctx):
    span = get_tracer().span("maybe")
    if span:
        ctx.note("traced")
    span.end()
    return ctx


def handle(request):
    return request


def propagation_context(span, ctx):
    return ctx
