"""Violating fixture for DL201 use-after-donate: donated buffers read
after dispatch — directly, through a wrapper frame, and left poisoned
across the dispatch/harvest split."""

import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0, 1))
def fused_step(k_cache, v_cache, tokens):
    return tokens, k_cache + 1, v_cache + 1


def scatter_into(k_cache, v_cache, rows):
    # wrapper frame: params 0/1 land in fused_step's donated slots, so
    # the CALLER's buffers are gone too (one-level summary)
    return fused_step(k_cache, v_cache, rows)


def direct_read_after_donate(k, v, tokens):
    out = fused_step(k, v, tokens)
    stats = k.sum()  # VIOLATION: k was donated, buffer freed
    return out, stats


def partial_rebind(k, v, tokens):
    # only k is rebound; v stays poisoned
    _, k, _ = fused_step(k, v, tokens)
    return k, v.mean()  # VIOLATION: v read after donate


def through_wrapper(k, v, rows):
    packed = scatter_into(k, v, rows)
    return packed, v.shape  # VIOLATION: donated one call level down


class Engine:
    def __init__(self):
        self.k_cache = None
        self.v_cache = None
        self._step = fused_step

    def dispatch(self, tokens):
        # the harvest half reads self.k_cache next step — but the swap
        # idiom was skipped, so the attribute now names a freed buffer
        out = fused_step(self.k_cache, self.v_cache, tokens)  # VIOLATION ×2: never rebound
        return out[0]

    def harvest(self, handle):
        return handle
