"""Clean fixture for DL201: every donated buffer is rebound from the
call's outputs before anything reads it — the engine's swap idiom, its
intermediate-tuple variant, the ``*packed-args`` form, and a wrapper
whose caller swaps."""

import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0, 1))
def fused_step(k_cache, v_cache, tokens):
    return tokens, k_cache + 1, v_cache + 1


def scatter_into(k_cache, v_cache, rows):
    # donates its callers' buffers one level down; callers must swap
    return fused_step(k_cache, v_cache, rows)


def swap_idiom(k, v, tokens):
    toks, k, v = fused_step(k, v, tokens)
    return toks, k.shape, v.shape  # rebound: reads are the NEW buffers


def intermediate_then_swap(k, v, tokens):
    out = fused_step(k, v, tokens)
    k, v = out[-2], out[-1]
    return out[0], k, v


def wrapper_caller_swaps(k, v, rows):
    _, k, v = scatter_into(k, v, rows)
    return k.sum() + v.sum()


def branch_returns(k, v, quantized, rows):
    if quantized:
        # this arm's donation never reaches the fall-through read
        return scatter_into(k, v, rows)
    return k, v


class Engine:
    def __init__(self):
        self.k_cache = None
        self.v_cache = None

    def dispatch(self, tokens):
        # the sanctioned swap: attributes rebound in the same statement,
        # with the argument list packed through a same-frame tuple
        base_args = (self.k_cache, self.v_cache, tokens)
        toks, self.k_cache, self.v_cache = fused_step(*base_args)
        return toks
