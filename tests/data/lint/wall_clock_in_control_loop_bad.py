"""Fixture: clock-injectable code bypassing its Clock inside loops."""

import asyncio
import time


class Planner:
    """Takes an injectable clock, then ignores it in the control loop."""

    def __init__(self, clock=None):
        self.clock = clock

    async def run(self):
        last = self.clock.monotonic()
        while True:
            now = time.monotonic()  # VIOLATION: bypasses self.clock
            if now - last > 30.0:
                last = now
            await asyncio.sleep(5.0)  # VIOLATION: bypasses self.clock


class Bucket:
    def __init__(self):
        self._clock = None  # assigned later (still clock-bearing)

    def refill_forever(self):
        for _ in range(100):
            time.sleep(0.1)  # VIOLATION: bypasses self._clock


class Scheduler:
    """NOT clock-bearing itself — but the helper nested inside its
    method takes a clock parameter and must be scanned on its own."""

    def poll(self):
        def wait_step(clock, deadline):
            while clock.monotonic() < deadline:
                time.sleep(0.5)  # VIOLATION: nested def bears a clock

        return wait_step


def paced_probe(url, clock):
    while True:
        stamp = time.time()  # VIOLATION: function takes a clock param
        if stamp:
            break


def wait_for(predicate, clock, timeout=5.0):
    deadline = time.monotonic() + timeout  # straight-line: not flagged
    while time.monotonic() < deadline:  # VIOLATION: condition on wall time
        if predicate():
            return True
    return False
