"""Fixture: the idiomatic patterns DL009 must stay quiet on."""

import asyncio
import time


class Planner:
    """Clock-bearing code routing ALL loop time through the clock."""

    def __init__(self, clock=None):
        self.clock = clock

    async def run(self):
        last = self.clock.monotonic()
        while True:
            now = self.clock.monotonic()
            if now - last > 30.0:
                last = now
            await self.clock.sleep(5.0)


class PlainWatcher:
    """No injectable clock anywhere: wall time in loops is fine (there
    is no simulated timeline to diverge from)."""

    async def watch(self):
        while True:
            started = time.monotonic()
            if started:
                await asyncio.sleep(1.0)


def one_shot_stamp(clock):
    # straight-line wall-clock use in clock-bearing code is allowed;
    # only loops split the timeline
    t0 = time.monotonic()
    return clock.monotonic() - t0
