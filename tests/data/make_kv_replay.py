"""Generate the committed KV-event replay corpus + golden expectations
(reference test strategy: lib/llm/tests/data/replays/ — recorded event
streams drive router regression tests without live workers).

Deterministic: 6 workers serving 40 simulated prompts drawn from a
small set of shared system-prompt prefixes (so real cross-worker
overlap exists), with periodic evictions and one worker clear.

    python tests/data/make_kv_replay.py   # rewrites the corpus + golden
"""

import json
import os
import random

HERE = os.path.dirname(os.path.abspath(__file__))
OUT_DIR = os.path.join(HERE, "replays")
CORPUS = os.path.join(OUT_DIR, "kv_events.jsonl")
GOLDEN = os.path.join(OUT_DIR, "kv_events.golden.json")
BLOCK = 16


def main() -> None:
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(HERE)))
    from dynamo_tpu.tokens import hash_sequence

    rng = random.Random(0xC0FFEE)
    prefixes = [
        [100 + i for i in range(BLOCK * 4)],   # long shared system prompt
        [500 + i for i in range(BLOCK * 2)],   # short one
        [900 + i for i in range(BLOCK)],       # single block
    ]
    workers = [2**48 + w for w in range(6)]
    events = []
    eid = {w: 0 for w in workers}
    stored: dict[int, list[list[int]]] = {w: [] for w in workers}

    def emit(worker: int, op: str, hashes: list[int]) -> None:
        eid[worker] += 1
        events.append({
            "ts": 0.0,
            "event": {
                "worker_id": worker,
                "event_id": eid[worker],
                "event": {
                    "op": op,
                    "block_hashes": hashes,
                    "token_block_size": BLOCK,
                },
            },
        })

    prompts = []
    for i in range(40):
        prefix = prefixes[rng.randrange(len(prefixes))]
        tail_len = BLOCK * rng.randrange(1, 5)
        tail = [10_000 + i * 1000 + t for t in range(tail_len)]
        prompts.append(prefix + tail)

    for i, prompt in enumerate(prompts):
        w = workers[rng.randrange(len(workers))]
        _, hashes = hash_sequence(prompt, BLOCK)
        emit(w, "stored", hashes)
        stored[w].append(hashes)
        # periodic eviction: some worker drops the TAIL of an old seq
        if i % 7 == 6:
            victim = workers[rng.randrange(len(workers))]
            if stored[victim]:
                seq = stored[victim][rng.randrange(len(stored[victim]))]
                drop = seq[len(seq) // 2:]
                if drop:
                    emit(victim, "removed", drop)
                    del seq[len(seq) // 2:]
    # one worker restarts mid-stream
    emit(workers[3], "cleared", [])
    stored[workers[3]].clear()

    os.makedirs(OUT_DIR, exist_ok=True)
    with open(CORPUS, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")

    # golden: overlap scores for probe prompts after full replay
    from dynamo_tpu.kv_router.indexer import RadixTree
    from dynamo_tpu.kv_router.protocols import RouterEvent

    tree = RadixTree()
    for e in events:
        tree.apply_event(RouterEvent.model_validate(e["event"]))
    probes = {
        "long_prefix_plus_new_tail": prefixes[0] + [77] * BLOCK,
        "short_prefix": prefixes[1],
        "exact_prompt_0": prompts[0],
        "no_overlap": [31337 + i for i in range(BLOCK * 3)],
    }
    golden = {"num_blocks": tree.num_blocks, "queries": {}}
    for name, toks in probes.items():
        _, hashes = hash_sequence(toks, BLOCK)
        scores = tree.find_matches(hashes)
        golden["queries"][name] = {
            "tokens": toks,
            "scores": {str(k): v for k, v in scores.scores.items()},
            "total_blocks": scores.total_blocks,
        }
    with open(GOLDEN, "w") as f:
        json.dump(golden, f, indent=1)
    print(f"wrote {len(events)} events, {tree.num_blocks} blocks, "
          f"{len(probes)} golden queries")


if __name__ == "__main__":
    main()
