"""Generate the checked-in tiny test tokenizer (BPE, Llama-3-style specials).

Run once: python tests/data/make_tiny_tokenizer.py
Mirrors the reference's checked-in sample-model configs
(reference: lib/llm/tests/data/sample-models/).
"""
import json
import os

from tokenizers import Tokenizer, models, pre_tokenizers, decoders, trainers

HERE = os.path.dirname(__file__)
OUT = os.path.join(HERE, "tiny_llama_model")
os.makedirs(OUT, exist_ok=True)

SPECIALS = [
    "<|begin_of_text|>", "<|end_of_text|>", "<|start_header_id|>",
    "<|end_header_id|>", "<|eot_id|>",
]

corpus = [
    "The quick brown fox jumps over the lazy dog. ",
    "You are a helpful assistant. Hello, how are you today? ",
    "What is the capital of France? The capital of France is Paris. ",
    "def main(): print('hello world') return 0 ",
    "Deep learning on TPUs with JAX and XLA compiles fast kernels. ",
    "0 1 2 3 4 5 6 7 8 9 10 11 12 13 14 15 16 ",
    "a b c d e f g h i j k l m n o p q r s t u v w x y z ",
] * 50

tok = Tokenizer(models.BPE(unk_token=None, byte_fallback=True))
tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
tok.decoder = decoders.ByteLevel()
trainer = trainers.BpeTrainer(
    vocab_size=2048, special_tokens=SPECIALS,
    initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
)
tok.train_from_iterator(corpus, trainer)
tok.save(os.path.join(OUT, "tokenizer.json"))

# Llama-3-style chat template (public format), written fresh
chat_template = (
    "{{- bos_token }}"
    "{%- for message in messages %}"
    "{{- '<|start_header_id|>' + message['role'] + '<|end_header_id|>\n\n' }}"
    "{{- message['content'] | trim }}{{- '<|eot_id|>' }}"
    "{%- endfor %}"
    "{%- if add_generation_prompt %}"
    "{{- '<|start_header_id|>assistant<|end_header_id|>\n\n' }}"
    "{%- endif %}"
)
cfg = {
    "bos_token": "<|begin_of_text|>",
    "eos_token": "<|eot_id|>",
    "chat_template": chat_template,
    "model_max_length": 512,
    "tokenizer_class": "PreTrainedTokenizerFast",
}
with open(os.path.join(OUT, "tokenizer_config.json"), "w") as f:
    json.dump(cfg, f, indent=1)

# tiny llama config for the JAX engine tests
model_config = {
    "architectures": ["LlamaForCausalLM"],
    "model_type": "llama",
    "hidden_size": 128,
    "intermediate_size": 256,
    "num_attention_heads": 4,
    "num_key_value_heads": 2,
    "num_hidden_layers": 2,
    "vocab_size": 2048,
    "max_position_embeddings": 512,
    "rms_norm_eps": 1e-5,
    "rope_theta": 10000.0,
    "bos_token_id": 0,
    "eos_token_id": 4,
    "tie_word_embeddings": False,
    "torch_dtype": "bfloat16",
}
with open(os.path.join(OUT, "config.json"), "w") as f:
    json.dump(model_config, f, indent=1)
print("wrote", OUT)
ids = tok.encode("Hello, how are you?").ids
print("sample encode:", ids)
print("roundtrip:", tok.decode(ids))
