"""Two-process multihost engine worker (driven by test_multihost.py).

Each process: jax.distributed over a localhost coordinator, 1 local CPU
device, global mesh tp=2 spanning both processes. Rank 0 leads (serves a
request); rank 1 follows (mirrors device steps). Prints RESULT <json> on
rank 0.
"""

import asyncio
import json
import os
import sys

# 1 local CPU device per process BEFORE jax import
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    .replace("--xla_force_host_platform_device_count=8", "")
    + " --xla_force_host_platform_device_count=1"
).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def main(rank: int, coord: str, kv_dtype: str = "float32") -> None:
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    mc = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128,
    )
    engine = await JaxEngine.launch(
        EngineConfig(
            model_path="", model_name="mh", random_weights=True,
            num_blocks=14, block_size=8, max_batch_size=4,
            tensor_parallel_size=2, decode_steps=2,
            num_nodes=2, node_rank=rank, leader_addr=coord,
            kv_cache_dtype=kv_dtype,
            # sharded G2 offload: small device pool forces eviction,
            # the repeat prompt onboards through the mirrored tier
            host_kv_blocks=16,
        ),
        model_config=mc,
    )
    try:
        if rank == 0:
            async def gen(rid: str, prompt: list) -> list:
                req = PreprocessedRequest(
                    request_id=rid, token_ids=prompt,
                    sampling=SamplingOptions(use_greedy=True),
                    stop=StopConditions(max_tokens=6, ignore_eos=True),
                )
                toks = []
                async for out in engine.as_async_engine().generate(req, Context()):
                    toks.extend(out.token_ids)
                return toks

            prompt_a = list(range(1, 34))  # 4+ blocks
            toks = await gen("mh-0", prompt_a)
            # disagg KV export over the cross-process-sharded cache:
            # mirrored replicated gather assembles WHOLE blocks on the
            # leader (engine._export_blocks multihost path)
            from dynamo_tpu.tokens import TokenBlockSequence

            seq_hashes = TokenBlockSequence(
                prompt_a, block_size=8
            ).sequence_hashes()
            exp_hashes, packed = await engine.export_kv_blocks(seq_hashes)
            export_ok = (
                len(exp_hashes) >= 4
                and packed.shape[0] == len(exp_hashes)
                # full KV-head range assembled (not one process's shard)
                and packed.shape[-2] == mc.num_key_value_heads
                and float(abs(packed).sum()) > 0
            )
            # ...and the import side: land them back in the sharded G2
            # pools (every process keeps its slice, lockstep preserved)
            imported = await engine.import_kv_blocks(exp_hashes, packed)
            # multimodal under multihost: an embed-injection prefill
            # broadcasts as its own control kind (KIND_STEP_MM) so the
            # follower enters the mm step variant with real embeds
            import numpy as np

            from dynamo_tpu.multimodal.embeds import pack_segments

            mm_req = PreprocessedRequest(
                request_id="mh-mm",
                token_ids=list(range(1, 18)),
                sampling=SamplingOptions(use_greedy=True),
                stop=StopConditions(max_tokens=3, ignore_eos=True),
                mm_embeds=pack_segments(
                    [(4, np.full((6, 32), 0.1, np.float32))]
                ),
            )
            mm_toks = []
            async for out in engine.as_async_engine().generate(
                mm_req, Context()
            ):
                mm_toks.extend(out.token_ids)
            mm_ok = len(mm_toks) == 3 and all(
                0 <= t < 128 for t in mm_toks
            )
            # churn evicts A from the device pool (13 usable blocks)
            for i, base in enumerate((40, 80)):
                await gen(f"churn{i}", list(range(base, base + 33)))
            await asyncio.sleep(0.5)  # idle pump offloads shards
            offloaded = engine.kvbm.pool.num_cached if engine.kvbm else 0
            toks2 = await gen("mh-1", prompt_a)
            print("RESULT " + json.dumps({
                "tokens": toks, "repeat_matches": toks2 == toks,
                "offloaded": offloaded,
                "export_ok": export_ok,
                "imported": imported,
                "mm_ok": mm_ok,
            }), flush=True)
        else:
            # follower: the engine thread runs the mirror loop; wait for
            # it to exit on the leader's STOP broadcast
            while engine._running:
                await asyncio.sleep(0.1)
    finally:
        await engine.shutdown()


if __name__ == "__main__":
    asyncio.run(main(
        int(sys.argv[1]), sys.argv[2],
        sys.argv[3] if len(sys.argv) > 3 else "float32",
    ))
