"""Strict Prometheus text-exposition parser for tests.

Validates the invariants scrapers rely on (ISSUE 2 satellite: every
/metrics payload must be well-formed):

- each family has exactly one ``# HELP`` and one ``# TYPE`` line, HELP
  first, both before any of its samples, and families are contiguous;
- sample names match the family (histograms may add ``_bucket``/
  ``_sum``/``_count``);
- label strings parse under the escaping rules (backslash, quote,
  newline) with no duplicate label names;
- no duplicate series (same sample name + label set twice);
- histogram series: cumulative bucket counts are monotonic, the +Inf
  bucket equals ``_count``, and all three sample kinds are present.

``parse(text)`` returns {family: Family} or raises ValueError.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field


@dataclass
class Family:
    name: str
    help: str
    type: str
    # series key: (sample_name, tuple(sorted(label items)))
    samples: dict = field(default_factory=dict)


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>[^ ]+)$"
)
_LABEL_RE = re.compile(
    r'^(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<value>(?:[^"\\]|\\.)*)"'
)


def _parse_labels(raw: str) -> dict:
    labels: dict = {}
    rest = raw
    while rest:
        m = _LABEL_RE.match(rest)
        if not m:
            raise ValueError(f"malformed label segment: {rest!r}")
        name = m.group("name")
        if name in labels:
            raise ValueError(f"duplicate label name {name!r}")
        value = m.group("value")
        # unescape: \\ \" \n — anything else escaped is invalid
        out = []
        i = 0
        while i < len(value):
            c = value[i]
            if c == "\\":
                i += 1
                if i >= len(value):
                    raise ValueError(f"dangling escape in {value!r}")
                nxt = value[i]
                if nxt == "n":
                    out.append("\n")
                elif nxt in ("\\", '"'):
                    out.append(nxt)
                else:
                    raise ValueError(f"invalid escape \\{nxt} in {value!r}")
            else:
                out.append(c)
            i += 1
        labels[name] = "".join(out)
        rest = rest[m.end():]
        if rest.startswith(","):
            rest = rest[1:]
        elif rest:
            raise ValueError(f"junk after label: {rest!r}")
    return labels


def _parse_value(raw: str) -> float:
    if raw == "+Inf":
        return math.inf
    if raw == "-Inf":
        return -math.inf
    if raw == "NaN":
        return math.nan
    return float(raw)


def _family_of(sample_name: str, families: dict) -> "Family | None":
    if sample_name in families:
        return families[sample_name]
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            fam = families.get(base)
            if fam is not None and fam.type == "histogram":
                return fam
    return None


def parse(text: str) -> dict:
    families: dict[str, Family] = {}
    current: Family | None = None
    pending_help: tuple | None = None  # (name, help) awaiting TYPE
    closed: set[str] = set()  # families that may not reappear

    for lineno, line in enumerate(text.split("\n"), 1):
        if not line:
            continue
        try:
            if line.startswith("# HELP "):
                parts = line[len("# HELP "):].split(" ", 1)
                if len(parts) != 2 or not parts[0]:
                    raise ValueError("malformed HELP line")
                name, help_text = parts
                if name in families:
                    raise ValueError(f"duplicate HELP for {name}")
                if pending_help is not None:
                    raise ValueError(
                        f"HELP for {name} while {pending_help[0]} has no TYPE"
                    )
                if current is not None:
                    closed.add(current.name)
                    current = None
                pending_help = (name, help_text)
            elif line.startswith("# TYPE "):
                parts = line[len("# TYPE "):].split(" ")
                if len(parts) != 2:
                    raise ValueError("malformed TYPE line")
                name, type_ = parts
                if type_ not in ("counter", "gauge", "histogram",
                                 "summary", "untyped"):
                    raise ValueError(f"unknown metric type {type_!r}")
                if pending_help is None or pending_help[0] != name:
                    raise ValueError(f"TYPE for {name} without HELP first")
                if name in closed or name in families:
                    raise ValueError(f"family {name} re-opened")
                current = Family(name=name, help=pending_help[1], type=type_)
                families[name] = current
                pending_help = None
            elif line.startswith("#"):
                continue  # comment
            else:
                m = _SAMPLE_RE.match(line)
                if not m:
                    raise ValueError("malformed sample line")
                sname = m.group("name")
                fam = _family_of(sname, families)
                if fam is None:
                    raise ValueError(f"sample {sname} has no HELP/TYPE")
                if current is None or fam is not current:
                    raise ValueError(
                        f"sample {sname} outside its family block "
                        f"(families must be contiguous)"
                    )
                labels = _parse_labels(m.group("labels") or "")
                value = _parse_value(m.group("value"))
                key = (sname, tuple(sorted(labels.items())))
                if key in fam.samples:
                    raise ValueError(f"duplicate series {key}")
                fam.samples[key] = value
        except ValueError as e:
            raise ValueError(f"line {lineno}: {e} :: {line!r}") from None

    if pending_help is not None:
        raise ValueError(f"HELP for {pending_help[0]} without TYPE")
    _check_histograms(families)
    return families


def _check_histograms(families: dict) -> None:
    for fam in families.values():
        if fam.type != "histogram":
            continue
        # group by non-le label set
        series: dict[tuple, dict] = {}
        for (sname, labels), value in fam.samples.items():
            base_labels = tuple(kv for kv in labels if kv[0] != "le")
            entry = series.setdefault(
                base_labels, {"buckets": [], "sum": None, "count": None}
            )
            if sname == fam.name + "_bucket":
                le = dict(labels).get("le")
                if le is None:
                    raise ValueError(f"{fam.name}: bucket without le label")
                entry["buckets"].append((_parse_value(le), value))
            elif sname == fam.name + "_sum":
                entry["sum"] = value
            elif sname == fam.name + "_count":
                entry["count"] = value
            else:
                raise ValueError(
                    f"{fam.name}: unexpected histogram sample {sname}"
                )
        for base_labels, entry in series.items():
            if entry["sum"] is None or entry["count"] is None:
                raise ValueError(
                    f"{fam.name}{dict(base_labels)}: missing _sum/_count"
                )
            buckets = sorted(entry["buckets"])
            if not buckets or buckets[-1][0] != math.inf:
                raise ValueError(
                    f"{fam.name}{dict(base_labels)}: no +Inf bucket"
                )
            counts = [c for _, c in buckets]
            if any(b > a for a, b in zip(counts[1:], counts)):
                raise ValueError(
                    f"{fam.name}{dict(base_labels)}: bucket counts not "
                    f"cumulative"
                )
            if counts[-1] != entry["count"]:
                raise ValueError(
                    f"{fam.name}{dict(base_labels)}: +Inf bucket "
                    f"{counts[-1]} != count {entry['count']}"
                )
