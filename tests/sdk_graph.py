"""Test service graph for supervisor e2e (≈ reference sdk tests/pipeline.py)."""

from dynamo_tpu.sdk.service import depends, endpoint, service


@service(dynamo={"namespace": "supns"})
class Worker:
    @endpoint()
    async def generate(self, request):
        for t in request["tokens"]:
            yield {"token": t * 2}


@service(dynamo={"namespace": "supns"})
class Frontend:
    worker = depends(Worker)

    @endpoint()
    async def generate(self, request):
        async for item in self.worker.generate(request):
            yield {"token": item["token"] + 1}
