"""Runtime affinity sanitizer (dynamo_tpu/utils/affinity.py,
DYN_AFFINITY_CHECK=1): thread/domain registry, attribute guards,
handoff grace, the @thread_affinity entry check — and the engine
end-to-end under the sanitizer: a full generate must pass while a raw
cross-thread write to a guarded attribute is rejected with a diagnostic
naming both threads and the attribute."""

import asyncio
import threading

import pytest

from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.utils import affinity


@pytest.fixture(autouse=True)
def _sanitizer_armed():
    affinity.set_enabled(True)
    affinity.reset_registry()
    yield
    affinity.reset_registry()
    affinity.set_enabled(None)  # back to env-driven


class Box:
    def __init__(self):
        self.flag = False
        self.other = 0


def test_cross_thread_write_rejected_naming_threads_and_attr():
    box = Box()
    affinity.guard_attrs(box, {"flag": "engine"})
    done = threading.Event()

    def engine_side():
        affinity.register_thread("engine")
        box.flag = True  # owner domain: allowed
        done.wait(5)
        affinity.unregister_thread()

    t = threading.Thread(target=engine_side, name="fake-engine")
    t.start()
    try:
        affinity.register_thread("loop")  # this (main) thread = loop
        with pytest.raises(affinity.AffinityViolation) as exc:
            box.flag = False
        msg = str(exc.value)
        # the diagnostic must name the attribute, the writing thread +
        # domain, and the owning domain's thread
        assert "flag" in msg
        assert "loop" in msg and "engine" in msg
        assert threading.current_thread().name in msg
        assert "fake-engine" in msg
    finally:
        done.set()
        t.join(5)


def test_handoff_sanctions_cross_domain_write():
    box = Box()
    affinity.guard_attrs(box, {"flag": "engine"})
    affinity.register_thread("loop")
    with affinity.handoff("test seam"):
        box.flag = True
    assert box.flag is True
    # unguarded attrs never check
    box.other = 7
    assert box.other == 7


def test_unregistered_threads_pass():
    # pytest's main thread has no domain: writes are not judged
    box = Box()
    affinity.guard_attrs(box, {"flag": "engine"})
    box.flag = True
    assert box.flag


def test_thread_affinity_decorator_entry_check():
    @affinity.thread_affinity("engine")
    def step():
        return 42

    assert step() == 42  # unregistered caller passes
    affinity.register_thread("loop")
    with pytest.raises(affinity.AffinityViolation):
        step()
    with affinity.handoff("driving the step inline"):
        assert step() == 42
    assert step.__dyn_affinity__ == "engine"


def test_disabled_sanitizer_is_inert():
    affinity.set_enabled(False)
    box = Box()
    out = affinity.guard_attrs(box, {"flag": "engine"})
    assert type(out) is Box  # no subclass rebind
    affinity.register_thread("loop")
    box.flag = True  # nothing raises

    @affinity.thread_affinity("engine")
    def step():
        return 1

    assert step() == 1


def test_guard_attrs_merges_and_repr_stays_sane():
    box = Box()
    affinity.guard_attrs(box, {"flag": "engine"})
    affinity.guard_attrs(box, {"other": "loop"})
    affinity.register_thread("planner")
    with pytest.raises(affinity.AffinityViolation):
        box.flag = True
    with pytest.raises(affinity.AffinityViolation):
        box.other = 1
    assert type(box).__name__ == "Box"  # cosmetic identity preserved


def test_unknown_domain_rejected():
    with pytest.raises(ValueError):
        affinity.register_thread("gpu")
    with pytest.raises(ValueError):
        affinity.thread_affinity("gpu")
    with pytest.raises(ValueError):
        affinity.guard_attrs(Box(), {"flag": "gpu"})


# ---------------------------------------------------------------------------
# engine end-to-end under the sanitizer
# ---------------------------------------------------------------------------


async def test_engine_generates_under_sanitizer_and_rejects_raw_flip():
    """DYN_AFFINITY_CHECK=1 over the real engine: launch registers the
    loop, the step loop registers the engine thread, spec_suspended is
    guarded — a normal generate plus the sanctioned degradation flip
    must pass; a raw cross-thread write must raise."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.planner.degradation import ServingDegradation

    mc = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128,
    )
    engine = await JaxEngine.launch(
        EngineConfig(
            model_path="", model_name="affinity-test", random_weights=True,
            num_blocks=32, block_size=4, max_batch_size=4,
            kv_cache_dtype="float32",
        ),
        model_config=mc,
    )
    try:
        # the sanctioned seam: degradation rung flips spec_suspended
        # through affinity.handoff — must not raise on the loop thread
        deg = ServingDegradation(engine=engine)
        deg.set_level(2)
        assert engine.spec_suspended is True
        deg.set_level(0)
        assert engine.spec_suspended is False

        # a raw flip from the loop thread is exactly what the sanitizer
        # exists to catch
        with pytest.raises(affinity.AffinityViolation) as exc:
            engine.spec_suspended = True
        assert "spec_suspended" in str(exc.value)

        # and the engine still serves correctly with guards armed
        adapter = engine.as_async_engine()
        req = PreprocessedRequest(
            request_id="aff-1",
            token_ids=list(range(1, 20)),
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=4, ignore_eos=True),
        )
        toks = []
        async for item in adapter.generate(req, Context()):
            toks.extend(item.token_ids)
        assert len(toks) == 4
    finally:
        await engine.shutdown()
