"""Request autopsy (docs/observability.md "Request autopsy"): the
tail-sampled per-request timeline plane — collector retention math,
the cross-process pending table, the waterfall coverage check, the
debug-endpoint parity between frontend and metrics service, and the
migration splice appearing in a record end to end (in-process)."""

import asyncio
import json

import pytest

from dynamo_tpu.telemetry.autopsy import (
    GAUGE_EVERY,
    MIN_WINDOW,
    AutopsyCollector,
    collect_autopsy,
    register_autopsy_provider,
    unregister_autopsy_provider,
    waterfall,
)


def _collector(**kw):
    """Collector on an injectable clock: tests advance time, never
    sleep."""
    t = {"now": 0.0}

    def clock():
        return t["now"]

    c = AutopsyCollector(clock=clock, wall=lambda: 1e9 + t["now"], **kw)
    return c, t


def _finish_n(c, t, n, total_s=0.010, prefix="warm"):
    """Drive n unflagged requests of the given duration through the
    collector (fills the rolling window / p99 state)."""
    for i in range(n):
        rid = f"{prefix}-{i}"
        c.begin(rid, "/v1/completions")
        t["now"] += total_s
        c.finish(rid, "200", host={"ttfb_ms": total_s * 500,
                                   "stages_ms": {}})


# ---------------------------------------------------------------------------
# retention
# ---------------------------------------------------------------------------


def test_warmup_retains_everything():
    """Below MIN_WINDOW finished requests the p99 estimate is noise:
    every record is an exemplar (the bounded ring makes this safe)."""
    c, t = _collector()
    c.begin("r1", "/v1/completions")
    t["now"] += 0.005
    row = c.finish("r1", "200")
    assert row is not None and row["retained"] == "tail_p99"


def test_fast_unflagged_request_is_dropped_after_warmup():
    c, t = _collector()
    # warm-up past MIN_WINDOW and a GAUGE_EVERY threshold recompute
    _finish_n(c, t, max(MIN_WINDOW, GAUGE_EVERY), total_s=0.100)
    assert c.snapshot()["p99_total_ms"] > 0
    c.begin("fast", "/v1/completions")
    t["now"] += 0.001  # far below the 100ms p99
    assert c.finish("fast", "200") is None
    assert c.get("fast") is None
    snap = c.snapshot()
    assert snap["dropped_total"] >= 1


def test_p99_tail_request_is_retained():
    c, t = _collector()
    _finish_n(c, t, max(MIN_WINDOW, GAUGE_EVERY), total_s=0.010)
    c.begin("slow", "/v1/completions")
    t["now"] += 0.500  # way past the 10ms p99
    row = c.finish("slow", "200")
    assert row is not None and row["retained"] == "tail_p99"
    assert c.get("slow")["rid"] == "slow"


@pytest.mark.parametrize("flag,via", [
    ("slo_miss", "segment"),
    ("shed", "event"),
    ("migrated", "event"),
    ("faulted", "event"),
    ("deadline", "segment"),
    ("error", "status"),
])
def test_flagged_fast_request_is_retained(flag, via):
    """The whole point of tail sampling: a FAST request that was
    flagged (SLO miss, shed, migrated, faulted, deadline, error) is
    still an exemplar."""
    c, t = _collector()
    _finish_n(c, t, max(MIN_WINDOW, GAUGE_EVERY), total_s=0.100)
    c.begin("bad", "/v1/chat/completions")
    if via == "segment":
        seg = {"source": "engine"}
        if flag == "slo_miss":
            seg["slo_miss"] = True
        else:
            seg["finish_reason"] = "timeout"
        c.publish_segment("bad", seg)
    elif via == "event":
        c.note_event("bad", "whatever", flag=flag)
    t["now"] += 0.001
    status = "500" if via == "status" else "200"
    row = c.finish("bad", status)
    assert row is not None
    assert row["retained"] == "flag"
    assert flag in row["flags"]


def test_finish_is_idempotent_and_unknown_rid_is_none():
    c, t = _collector()
    c.begin("r1", "/v1/completions")
    t["now"] += 0.002
    assert c.finish("r1", "200") is not None
    assert c.finish("r1", "200") is None  # first call won
    assert c.finish("never-began", "200") is None


def test_exemplar_ring_is_bounded():
    c, t = _collector(max_exemplars=4)
    _finish_n(c, t, 10, total_s=0.010)  # warm-up retains all 10
    idx = c.index()
    assert len(idx) == 4
    assert idx[0]["rid"] == "warm-9"  # newest first


# ---------------------------------------------------------------------------
# cross-process pending table
# ---------------------------------------------------------------------------


def test_pending_take_merge_round_trip():
    """Worker-side publishes for an rid with no local record park in
    the pending table; take_pending pops them (the seg wire frame) and
    merge_pending folds them into the caller's record — including the
    flag carried inside a pending event."""
    worker, _ = _collector()
    frontend, t = _collector()
    rid = "xproc-1"
    worker.publish_segment(rid, {"source": "engine", "tokens": 5,
                                 "finish_reason": "stop"})
    worker.note_event(rid, "fault", flag="faulted", point="engine.step")
    payload = worker.take_pending(rid)
    assert payload is not None
    assert len(payload["segments"]) == 1
    assert payload["events"][0]["flag"] == "faulted"
    assert worker.take_pending(rid) is None  # popped exactly once
    # the frontend folds the shipped payload into its active record
    frontend.begin(rid, "/v1/completions")
    frontend.merge_pending(rid, payload)
    t["now"] += 0.002
    row = frontend.finish(rid, "200")
    assert row is not None and row["retained"] == "flag"
    assert row["flags"] == ["faulted"]
    assert row["segments"][0]["tokens"] == 5
    assert any(e["kind"] == "fault" for e in row["events"])


def test_finish_merges_local_pending():
    """A segment that arrives before begin() (in-process engine racing
    the frontend) still lands in the finished record."""
    c, t = _collector()
    rid = "race-1"
    c.publish_segment(rid, {"source": "engine", "slo_miss": True})
    c.begin(rid, "/v1/completions")
    t["now"] += 0.002
    row = c.finish(rid, "200")
    assert row is not None
    assert row["segments"][0]["slo_miss"] is True
    assert "slo_miss" in row["flags"]


def test_pending_table_is_bounded_fifo():
    c, _ = _collector(max_pending=3)
    for i in range(5):
        c.publish_segment(f"p-{i}", {"source": "engine"})
    assert c.take_pending("p-0") is None  # FIFO-evicted
    assert c.take_pending("p-4") is not None


# ---------------------------------------------------------------------------
# record shape
# ---------------------------------------------------------------------------


def test_router_decisions_and_inflight_view():
    c, t = _collector()
    c.begin("r1", "/v1/chat/completions")
    c.set_trace("r1", "tid-1234")
    c.note_router("r1", 0xBEEF, overlap_blocks=3, total_blocks=9,
                  fleet_blocks=2)
    t["now"] += 0.010
    c.note_router("r1", 0xCAFE, resume=True)
    live = c.get("r1")
    assert live["finished"] is False
    assert [d["worker"] for d in live["router"]] == ["beef", "cafe"]
    assert live["router"][0]["overlap_blocks"] == 3
    assert live["router"][0]["fleet_blocks"] == 2
    assert live["router"][1]["resume"] is True
    assert live["trace_id"] == "tid-1234"
    row = c.finish("r1", "200")
    assert row["router"] == live["router"]


def test_record_is_json_serializable():
    c, t = _collector()
    c.begin("r1", "/v1/completions")
    c.note_event("r1", "deadline_budget", ms=500)
    c.publish_segment("r1", {"source": "engine", "prefill_ms": 1.0})
    t["now"] += 0.002
    row = c.finish("r1", "200", host={"ttfb_ms": 1.0,
                                      "stages_ms": {"preprocess": 0.5}})
    json.dumps(row)
    json.dumps(c.snapshot())


# ---------------------------------------------------------------------------
# waterfall coverage
# ---------------------------------------------------------------------------


def test_waterfall_explains_wall_clock():
    rec = {
        "total_ms": 100.0,
        "ttfb_ms": 40.0,
        "host": {"stages_ms": {"preprocess": 3.0, "dispatch": 1.0,
                               "prime": 36.0}},
    }
    wf = waterfall(rec)
    assert wf["covered"] is True
    assert wf["explained_ms"] == pytest.approx(100.0)
    names = [r["name"] for r in wf["rows"]]
    assert names == ["preprocess", "dispatch", "prime", "stream"]
    # rows tile the span: each starts where the previous ended
    for prev, cur in zip(wf["rows"], wf["rows"][1:]):
        assert cur["start_ms"] == pytest.approx(
            prev["start_ms"] + prev["dur_ms"]
        )


def test_waterfall_surfaces_host_gap():
    """Time between the staged host work and first byte is rendered as
    an explicit (host gap) row — a growing gap IS the finding."""
    rec = {"total_ms": 50.0, "ttfb_ms": 30.0,
           "host": {"stages_ms": {"preprocess": 2.0}}}
    wf = waterfall(rec)
    gap = next(r for r in wf["rows"] if r["name"] == "(host gap)")
    assert gap["dur_ms"] == pytest.approx(28.0)
    assert wf["covered"] is True


def test_waterfall_without_ttfb_is_unattributed():
    wf = waterfall({"total_ms": 10.0, "host": {"stages_ms": {}}})
    assert [r["name"] for r in wf["rows"]] == ["(unattributed)"]
    assert wf["covered"] is True


# ---------------------------------------------------------------------------
# provider registry (fourth ProviderRegistry instance)
# ---------------------------------------------------------------------------


def test_collect_autopsy_has_collector_stanza_and_degrades():
    out = collect_autopsy()
    assert "ts" in out and "pid" in out
    assert "requests_total" in out["collector"]
    assert isinstance(out["collector"]["exemplars"], list)

    def broken() -> dict:
        raise RuntimeError("boom")

    register_autopsy_provider("broken", broken)
    try:
        out = collect_autopsy()
        assert "error" in out["broken"]  # degraded, not raised
        assert "requests_total" in out["collector"]
    finally:
        unregister_autopsy_provider("broken")


# ---------------------------------------------------------------------------
# endpoint parity: the frontend and the metrics service expose the SAME
# /debug surface (ISSUE 19 satellite — an operator mid-incident must
# not have to remember which port grew which endpoint)
# ---------------------------------------------------------------------------


def _debug_paths(app) -> set:
    return {
        r.resource.canonical
        for r in app.router.routes()
        if r.resource is not None
        and r.resource.canonical.startswith("/debug/")
    }


def test_debug_endpoint_parity_frontend_vs_metrics_service():
    from dynamo_tpu.http.service import HttpService
    from dynamo_tpu.metrics.service import MetricsService

    fe = HttpService()
    ms = MetricsService(component=None, host="127.0.0.1", port=0)  # type: ignore[arg-type]
    assert _debug_paths(fe.app) == _debug_paths(ms.build_app())
    # the autopsy pair is explicitly part of the contract
    assert "/debug/request/{rid}" in _debug_paths(fe.app)
    assert "/debug/requests" in _debug_paths(fe.app)
    assert "/debug/kvfleet" in _debug_paths(ms.build_app())


# ---------------------------------------------------------------------------
# migration splice lands in the record (in-process, real PushRouter)
# ---------------------------------------------------------------------------


async def test_migrated_request_record_shows_both_workers_and_splice():
    """Kill a fake worker after 3 tokens behind the real PushRouter:
    the autopsy record carries the dead worker's synthesized segment,
    the survivor's dial, and the resume_splice event naming BOTH
    worker ids — and the 'migrated' flag retains it as an exemplar."""
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context, collect
    from dynamo_tpu.runtime.migration import MigrationConfig
    from dynamo_tpu.runtime.push_router import PushRouter, RouterMode
    from dynamo_tpu.runtime.service import ConnectionLostError
    from dynamo_tpu.telemetry import autopsy

    class FakeWorker:
        def __init__(self, die_after=None):
            self.die_after = die_after
            self.requests = []

        async def stream(self, request):
            self.requests.append(request)
            last = list(request.token_ids)[-1]
            emitted = 0
            while emitted < request.stop.max_tokens:
                if self.die_after is not None and emitted >= self.die_after:
                    raise ConnectionLostError("worker died mid-stream")
                last = (last * 7 + 13) % 997
                emitted += 1
                yield {"request_id": request.request_id,
                       "token_ids": [last]}
                await asyncio.sleep(0)
            yield {"request_id": request.request_id, "token_ids": [],
                   "finish_reason": "length",
                   "prompt_tokens": len(request.token_ids),
                   "completion_tokens": emitted}

    class _Endpoint:
        path = "test.autopsy.generate"

    class FakeClient:
        def __init__(self, workers):
            self.workers = dict(workers)
            self.endpoint = _Endpoint()

        def instance_ids(self):
            return sorted(self.workers)

        async def wait_for_instances(self, timeout_s=None):
            return self.instance_ids()

        async def generate_direct(self, instance_id, request, context=None):
            return self.workers[instance_id].stream(request)

    # round-robin picks index 1 of the sorted ids first: the dying
    # worker sits at id 2 so the first dispatch lands on it
    dying, survivor = FakeWorker(die_after=3), FakeWorker()
    router = PushRouter(
        FakeClient({1: survivor, 2: dying}), RouterMode.ROUND_ROBIN,
        migration=MigrationConfig(instance_wait_s=0.5),
    )
    ctx = Context()
    autopsy.begin_request(ctx.id, "/v1/completions")
    req = PreprocessedRequest(
        request_id="autopsy-mig", token_ids=[1, 2, 3],
        sampling=SamplingOptions(use_greedy=True),
        stop=StopConditions(max_tokens=8),
    )
    items = await asyncio.wait_for(
        collect(router.generate(req, ctx)), timeout=10
    )
    assert items[-1]["finish_reason"] == "length"
    row = autopsy.finish_request(ctx.id, "200")
    assert row is not None and "migrated" in row["flags"]
    # the dead worker's side is a synthesized segment (its real engine
    # segment died with the process); both worker ids are on the splice
    dead = [s for s in row["segments"] if s["source"] == "worker_died"]
    assert len(dead) == 1 and dead[0]["worker"] == "2"
    assert dead[0]["tokens"] == 3
    splice = [e for e in row["events"] if e["kind"] == "resume_splice"]
    assert len(splice) == 1
    assert splice[0]["from_worker"] == "2"
    assert splice[0]["to_worker"] == "1"
    assert splice[0]["delivered"] == 3
    # both dials recorded, second one marked as the resume
    assert [d["worker"] for d in row["router"]] == ["2", "1"]
    assert row["router"][1]["resume"] is True


# ---------------------------------------------------------------------------
# CLI pieces (pure functions — no sockets)
# ---------------------------------------------------------------------------


def test_top_autopsy_cols_absence_vs_zero():
    from dynamo_tpu.cli.top import _autopsy_cols

    assert _autopsy_cols(None)["slow_requests"] is None
    assert _autopsy_cols({"collector": {"exemplars": []}}) == {
        "slow_requests": 0
    }
    assert _autopsy_cols(
        {"collector": {"exemplars": [{}, {}]}}
    )["slow_requests"] == 2
    assert _autopsy_cols({"collector": {"error": "x"}})[
        "slow_requests"
    ] is None


def test_cli_render_waterfall(capsys):
    import sys

    from dynamo_tpu.cli.autopsy import render

    c, t = _collector()
    c.begin("r1", "/v1/chat/completions")
    c.set_trace("r1", "abcd1234")
    c.note_router("r1", 0xBEEF, overlap_blocks=3, total_blocks=10)
    c.publish_segment("r1", {"source": "engine", "slo_miss": True,
                             "prefill_ms": 30.0, "decode_ms": 60.0})
    t["now"] += 0.100
    row = c.finish("r1", "200", host={
        "ttfb_ms": 40.0,
        "stages_ms": {"preprocess": 3.0, "dispatch": 1.0, "prime": 36.0},
    })
    render(row, sys.stdout)
    out = capsys.readouterr().out
    assert "[OK]" in out and "100.0% coverage" in out
    assert "slo_miss" in out
    assert "worker=beef" in out
    assert "trace export" in out and "--rid r1" in out


def test_trace_ids_for_request(tmp_path):
    from dynamo_tpu.telemetry.export import trace_ids_for_request

    log = tmp_path / "spans.jsonl"
    log.write_text("\n".join([
        json.dumps({"name": "http.request", "trace_id": "t-1",
                    "span_id": "s1", "start": 1.0, "duration_s": 0.1,
                    "attrs": {"request_id": "rid-1"}}),
        json.dumps({"name": "engine.decode", "trace_id": "t-1",
                    "span_id": "s2", "start": 1.0, "duration_s": 0.1}),
        json.dumps({"name": "http.request", "trace_id": "t-2",
                    "span_id": "s3", "start": 2.0, "duration_s": 0.1,
                    "attrs": {"request_id": "rid-2"}}),
    ]) + "\n")
    assert trace_ids_for_request([str(log)], "rid-1") == ["t-1"]
    assert trace_ids_for_request([str(log)], "rid-404") == []
