"""Build/packaging pipeline (reference: deploy/sdk/src/dynamo/sdk/cli/
bentos.py — versioned graph artifacts; deployment.py — push/pull via the
api-store)."""

import io
import json
import os
import sys
import tarfile

import pytest

from dynamo_tpu.deploy.build import (
    PackageManifest,
    build_package,
    pull_package,
    push_package,
    read_manifest,
    unpack_package,
)
from dynamo_tpu.store.memory import MemoryStore

ENTRY = "examples.hello_world.graph:Frontend"


def test_build_is_versioned_and_deterministic(tmp_path):
    p1, m1 = build_package(ENTRY, name="hello",
                           out_path=str(tmp_path / "a.tar.gz"))
    p2, m2 = build_package(ENTRY, name="hello",
                           out_path=str(tmp_path / "b.tar.gz"))
    assert m1.version == m2.version  # content-derived
    assert len(m1.version) == 12
    assert m1.entry == ENTRY
    # the graph's package source is inside
    assert any(k.startswith("src/examples/") for k in m1.files)
    with open(p1, "rb") as f1, open(p2, "rb") as f2:
        assert f1.read() == f2.read()  # byte-identical archives
    assert read_manifest(p1).to_dict() == m1.to_dict()


def test_build_embeds_config_and_deployment(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text("Backend:\n  replicas: 2\n")
    dep = {"apiVersion": "dynamo-tpu.dev/v1alpha1",
           "kind": "DynamoGraphDeployment",
           "metadata": {"name": "hello", "namespace": "hello"},
           "spec": {"services": {"Backend": {"replicas": 2}}}}
    path, m = build_package(
        ENTRY, name="hello", config_file=str(cfg), deployment_spec=dep,
        out_path=str(tmp_path / "c.tar.gz"),
    )
    assert m.config == {"Backend": {"replicas": 2}}
    assert m.deployment["metadata"]["name"] == "hello"
    assert "config.yaml" in m.files


def test_build_rejects_non_service():
    with pytest.raises(ValueError, match="not a DynamoService"):
        build_package("json:dumps")
    with pytest.raises(ValueError, match="module:Attr"):
        build_package("examples.hello_world.graph")


async def test_push_pull_unpack_roundtrip(tmp_path):
    store = MemoryStore()
    path, m = build_package(ENTRY, name="hello",
                            out_path=str(tmp_path / "p.tar.gz"))
    await push_package(store, path)
    blob, version = await pull_package(store, "hello")  # latest
    assert version == m.version
    dest, m2 = unpack_package(blob, str(tmp_path / "unpacked"))
    assert m2.version == m.version
    graph_py = os.path.join(dest, "src", "examples", "hello_world", "graph.py")
    assert os.path.exists(graph_py)
    # the unpacked source is importable and the entry resolves
    src = os.path.join(dest, "src")
    sys.path.insert(0, src)
    try:
        for k in [k for k in list(sys.modules) if k.startswith("examples")]:
            del sys.modules[k]
        import importlib

        mod = importlib.import_module("examples.hello_world.graph")
        assert hasattr(getattr(mod, "Frontend"), "graph")
    finally:
        sys.path.remove(src)
        for k in [k for k in list(sys.modules) if k.startswith("examples")]:
            del sys.modules[k]
    # explicit-version pull + missing-version errors
    blob2, _ = await pull_package(store, "hello", m.version)
    assert blob2 == blob
    with pytest.raises(KeyError):
        await pull_package(store, "hello", "deadbeef0000")
    with pytest.raises(KeyError):
        await pull_package(store, "nope")
    await store.close()


async def test_unpack_rejects_tampering(tmp_path):
    store = MemoryStore()
    path, m = build_package(ENTRY, name="hello",
                            out_path=str(tmp_path / "p.tar.gz"))
    with open(path, "rb") as f:
        blob = f.read()
    # tamper: rewrite one source file inside the archive
    src_tar = tarfile.open(fileobj=io.BytesIO(blob), mode="r:gz")
    out = io.BytesIO()
    dst = tarfile.open(fileobj=out, mode="w:gz")
    for member in src_tar.getmembers():
        data = src_tar.extractfile(member).read()
        if member.name.endswith("graph.py"):
            data = data + b"\n# evil\n"
            member.size = len(data)
        dst.addfile(member, io.BytesIO(data))
    dst.close()
    with pytest.raises(ValueError, match="hash mismatch"):
        unpack_package(out.getvalue(), str(tmp_path / "bad"))
    # traversal refusal
    out2 = io.BytesIO()
    dst2 = tarfile.open(fileobj=out2, mode="w:gz")
    mf = json.dumps(m.to_dict()).encode()
    info = tarfile.TarInfo("manifest.json")
    info.size = len(mf)
    dst2.addfile(info, io.BytesIO(mf))
    evil = tarfile.TarInfo("../escape.py")
    evil.size = 1
    dst2.addfile(evil, io.BytesIO(b"x"))
    dst2.close()
    with pytest.raises(ValueError, match="unsafe member"):
        unpack_package(out2.getvalue(), str(tmp_path / "bad2"))
    await store.close()
