"""Deterministic chaos suite (docs/robustness.md acceptance gates).

Every test here runs a fixed-seed fault plan (or a provoked failure)
and asserts a graceful-degradation contract:

- injected engine-step faults never change greedy output (quarantine
  retries absorb them);
- expired-deadline requests are cancelled at queue/decode stage and
  their KV blocks freed;
- overload sheds with 429 + Retry-After instead of queueing unboundedly;
- a worker dying mid-stream never hangs the consumer: pre-first-token
  streams fail over, mid-stream ones end with a clean error (and a
  clean SSE ``error`` event at the HTTP layer).
"""

import asyncio
import os
import time
from typing import Any, AsyncIterator

import pytest

from dynamo_tpu import faults
from dynamo_tpu.protocols.common import (
    FinishReason,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context, FnEngine, collect

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.deactivate()
    yield
    faults.deactivate()


def _engine_config(**kw):
    from dynamo_tpu.engine.config import EngineConfig

    defaults = dict(
        model_path=MODEL_DIR,
        model_name="tiny",
        random_weights=True,
        num_blocks=128,
        block_size=8,
        max_batch_size=8,
        prefill_chunk_size=32,
        max_model_len=256,
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


async def _generate(engine, prompt_ids, max_tokens=8, ctx=None, request_id="r"):
    adapter = engine.as_async_engine()
    req = PreprocessedRequest(
        request_id=request_id,
        token_ids=list(prompt_ids),
        sampling=SamplingOptions(use_greedy=True),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )
    out, final = [], None
    async for item in adapter.generate(req, ctx or Context()):
        out.extend(item.token_ids)
        if item.is_final:
            final = item
    return out, final


# ---------------------------------------------------------------------------
# Engine under the canned chaos plan
# ---------------------------------------------------------------------------


async def test_engine_greedy_bit_identical_under_step_faults():
    """The canned plan delays steps and injects one transient step
    error; quarantine retries the first failure with host state
    untouched, so greedy output must be BIT-IDENTICAL to fault-free."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_config())
    try:
        prompt = list(range(1, 40))
        baseline, fin = await _generate(engine, prompt, request_id="base")
        assert fin.finish_reason == FinishReason.LENGTH

        faults.activate(faults.parse_plan(
            "seed=1234;engine.step:delay=0.002@p=0.3;"
            "engine.step:error@after=2@max=1"
        ))
        chaotic, fin2 = await _generate(engine, prompt, request_id="chaos")
        assert fin2.finish_reason == FinishReason.LENGTH
        assert chaotic == baseline
        # the plan actually fired (determinism: error always fires once)
        stats = faults.ACTIVE.stats()
        fired = {
            (r["point"], r["kind"]): r["fires"] for r in stats["rules"]
        }
        assert fired[("engine.step", "error")] == 1
    finally:
        faults.deactivate()
        await engine.shutdown()


async def test_expired_deadline_frees_kv_blocks_mid_decode():
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_config())
    try:
        ctx = Context()
        ctx.set_deadline_ms(250.0)
        t0 = time.monotonic()
        toks, fin = await _generate(
            engine, list(range(1, 30)), max_tokens=100_000, ctx=ctx,
            request_id="deadline-decode",
        )
        assert fin is not None
        assert fin.finish_reason == FinishReason.TIMEOUT
        assert time.monotonic() - t0 < 30.0  # cancelled, not served out
        # KV blocks freed once the reap ran
        await engine.wait_for_state(
            lambda e: e.allocator.num_free == e.allocator.num_blocks - 1,
            timeout=10.0,
        )
    finally:
        await engine.shutdown()


def test_expired_deadline_reaped_from_queue_frees_blocks():
    """Scheduler-level: a request whose deadline lapses while WAITING is
    finished with TIMEOUT before it ever takes blocks; one that expires
    in PREFILL frees the blocks it held."""
    from dynamo_tpu.engine.allocator import BlockAllocator
    from dynamo_tpu.engine.scheduler import Scheduler, Sequence
    from dynamo_tpu.tokens import TokenBlockSequence

    alloc = BlockAllocator(32, 4)
    sched = Scheduler(alloc, block_size=4, max_batch_size=4)
    finishes = []
    sched.on_finish = lambda seq, reason: finishes.append(
        (seq.request_id, reason)
    )

    def make_seq(rid: str, deadline: float) -> Sequence:
        req = PreprocessedRequest(
            request_id=rid, token_ids=list(range(1, 9)),
            stop=StopConditions(max_tokens=4),
        )
        seq = Sequence(request=req, tokens=TokenBlockSequence(
            list(req.token_ids), block_size=4,
        ))
        seq.deadline = deadline
        return seq

    expired = make_seq("expired", time.monotonic() - 1.0)
    live = make_seq("live", time.monotonic() + 60.0)
    sched.add_request(expired)
    sched.add_request(live)
    free_before = alloc.num_free
    plan = sched.plan()
    assert ("expired", FinishReason.TIMEOUT) in finishes
    assert plan.kind == "prefill"
    assert [w.seq.request_id for w in plan.prefill_batch] == ["live"]
    # prefill-stage expiry: lapse the live seq's deadline mid-prefill
    live.deadline = time.monotonic() - 0.001
    plan2 = sched.plan()
    assert ("live", FinishReason.TIMEOUT) in finishes
    assert plan2.kind == "idle"
    assert alloc.num_free == free_before  # every block returned


# ---------------------------------------------------------------------------
# Overload shedding (429 + Retry-After)
# ---------------------------------------------------------------------------


async def test_overload_sheds_429_with_retry_after():
    import aiohttp

    from dynamo_tpu.http.admission import (
        AdmissionConfig,
        AdmissionController,
        LoadSnapshot,
    )
    from dynamo_tpu.http.service import HttpService, ModelManager
    from dynamo_tpu.protocols.openai import ChatDeltaGenerator

    load = LoadSnapshot(queue_depth=0, kv_usage=0.0)

    async def chat(request, ctx):
        gen = ChatDeltaGenerator(model="m")
        yield gen.text_chunk("ok ")
        yield gen.finish_chunk(FinishReason.STOP)

    manager = ModelManager()
    manager.add_chat_model("m", FnEngine(chat))
    admission = AdmissionController(
        AdmissionConfig(
            max_queue_depth=4, max_kv_usage=0.95, retry_after_s=2.0,
            probe_rate_per_s=0.0, probe_burst=0.0,  # deterministic: no probes
        ),
        lambda: load,
    )
    service = HttpService(
        manager, host="127.0.0.1", port=0, admission=admission
    )
    await service.start()
    base = f"http://127.0.0.1:{service.port}"
    body = {"model": "m", "messages": [{"role": "user", "content": "hi"}]}
    try:
        async with aiohttp.ClientSession() as s:
            # healthy: admitted
            async with s.post(f"{base}/v1/chat/completions", json=body) as r:
                assert r.status == 200
            # saturate the queue signal: shed with Retry-After
            load.queue_depth = 8
            async with s.post(f"{base}/v1/chat/completions", json=body) as r:
                assert r.status == 429
                retry_after = int(r.headers["Retry-After"])
                assert retry_after >= 1
                err = await r.json()
                assert err["error"]["type"] == "overloaded_error"
            # KV pressure sheds too
            load.queue_depth = 0
            load.kv_usage = 0.99
            async with s.post(f"{base}/v1/chat/completions", json=body) as r:
                assert r.status == 429
            # pressure gone: admitted again (recovery, not a latch)
            load.kv_usage = 0.0
            async with s.post(f"{base}/v1/chat/completions", json=body) as r:
                assert r.status == 200
        assert admission.shed_total == 2
    finally:
        await service.stop()


async def test_probe_bucket_admits_bounded_trickle_under_overload():
    from dynamo_tpu.http.admission import (
        AdmissionConfig,
        AdmissionController,
        LoadSnapshot,
    )

    now = [0.0]
    ctl = AdmissionController(
        AdmissionConfig(
            max_queue_depth=1, probe_rate_per_s=1.0, probe_burst=2.0
        ),
        lambda: LoadSnapshot(queue_depth=10),
        clock=lambda: now[0],
    )
    # burst of 2 probes admitted, the rest shed
    results = [ctl.check() is None for _ in range(6)]
    assert results == [True, True, False, False, False, False]
    now[0] += 3.0  # refill (capped at the burst of 2)
    assert ctl.check() is None
    assert ctl.check() is None
    assert ctl.check() is not None


# ---------------------------------------------------------------------------
# Mid-stream worker failure: failover or clean termination, never a hang
# ---------------------------------------------------------------------------


async def _two_worker_fleet():
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.runtime import DistributedRuntime
    from dynamo_tpu.store.memory import MemoryStore
    from dynamo_tpu.store.server import StoreServer

    server = StoreServer(MemoryStore(lease_sweep_interval_s=0.1), port=0)
    await server.start()
    cfg = lambda: RuntimeConfig(  # noqa: E731
        store_port=server.port, worker_host="127.0.0.1",
        lease_ttl_s=2.0, lease_keepalive_s=0.5,
    )
    drts = [await DistributedRuntime.create(config=cfg()) for _ in range(3)]
    w1, w2, frontend = drts

    def worker_engine(tag: str) -> FnEngine:
        async def gen(request: Any, ctx: Context) -> AsyncIterator[Any]:
            for i in range(3):
                yield {"worker": tag, "i": i}

        return FnEngine(gen)

    for drt, tag in ((w1, "w1"), (w2, "w2")):
        ep = drt.namespace("ns").component("gen").endpoint("generate")
        await ep.serve(worker_engine(tag))
    ep = frontend.namespace("ns").component("gen").endpoint("generate")
    client = await ep.client()
    await client.wait_for_instances(timeout_s=10)
    for _ in range(100):
        if len(client.instance_ids()) == 2:
            break
        await asyncio.sleep(0.05)
    assert len(client.instance_ids()) == 2
    return server, drts, client


async def test_pre_first_token_stream_loss_fails_over():
    """A connection that dies before the first item re-dispatches to a
    healthy worker and the request still completes."""
    from dynamo_tpu.runtime.push_router import PushRouter, RouterMode

    server, drts, client = await _two_worker_fleet()
    try:
        router = PushRouter(client, RouterMode.ROUND_ROBIN)
        # the FIRST frame the frontend receives is dropped -> the stream
        # dies with zero items yielded -> failover
        faults.activate(faults.parse_plan("seed=5;transport.recv:drop@max=1"))
        items = await asyncio.wait_for(
            collect(router.generate({"x": 1}, Context())), timeout=20
        )
        assert [i["i"] for i in items] == [0, 1, 2]
    finally:
        faults.deactivate()
        await client.close()
        for drt in drts:
            await drt.shutdown()
        await server.stop()


async def test_midstream_loss_terminates_cleanly_not_hangs():
    """After items have streamed, a dead worker ends the stream with
    WorkerStreamLostError promptly — never a hang, never a silent
    replay onto another worker."""
    from dynamo_tpu.runtime.push_router import (
        PushRouter,
        RouterMode,
        WorkerStreamLostError,
    )

    server, drts, client = await _two_worker_fleet()
    try:
        router = PushRouter(client, RouterMode.ROUND_ROBIN)
        faults.activate(
            faults.parse_plan("seed=5;transport.recv:drop@after=1@max=1")
        )

        async def consume():
            got = []
            with pytest.raises(WorkerStreamLostError):
                async for item in router.generate({"x": 1}, Context()):
                    got.append(item)
            return got

        got = await asyncio.wait_for(consume(), timeout=20)
        assert len(got) >= 1  # tokens had streamed: not replayable
    finally:
        faults.deactivate()
        await client.close()
        for drt in drts:
            await drt.shutdown()
        await server.stop()


async def test_sse_stream_ends_with_clean_error_event():
    """HTTP layer: a mid-stream worker loss surfaces as an SSE `error`
    event followed by end-of-stream — the client is never left hanging."""
    import aiohttp

    from dynamo_tpu.http.service import HttpService, ModelManager
    from dynamo_tpu.protocols.openai import ChatDeltaGenerator
    from dynamo_tpu.runtime.push_router import WorkerStreamLostError

    async def dying_chat(request, ctx):
        gen = ChatDeltaGenerator(model="m")
        yield gen.text_chunk("partial ")
        raise WorkerStreamLostError(
            "worker connection lost mid-stream; partial response cannot "
            "be resumed"
        )

    manager = ModelManager()
    manager.add_chat_model("m", FnEngine(dying_chat))
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    base = f"http://127.0.0.1:{service.port}"
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"{base}/v1/chat/completions",
                json={"model": "m", "stream": True,
                      "messages": [{"role": "user", "content": "hi"}]},
            ) as r:
                assert r.status == 200
                raw = await asyncio.wait_for(r.read(), timeout=15)
        text = raw.decode()
        assert "partial" in text
        assert "event: error" in text
        assert "worker connection lost" in text
    finally:
        await service.stop()


# ---------------------------------------------------------------------------
# Store reconnect + watch resubscribe (registry must never freeze)
# ---------------------------------------------------------------------------


async def test_store_client_reconnects_after_coordinator_restart():
    from dynamo_tpu.store.client import StoreClient
    from dynamo_tpu.store.memory import MemoryStore
    from dynamo_tpu.store.server import StoreServer

    server = StoreServer(MemoryStore(), host="127.0.0.1", port=0)
    await server.start()
    port = server.port
    client = await StoreClient.connect("127.0.0.1", port, reconnect=True)
    try:
        await client.kv_put("k", b"v1")
        await server.stop()
        # while down, calls fail fast with ConnectionError (no hang)
        await asyncio.sleep(0.1)
        with pytest.raises(ConnectionError):
            await asyncio.wait_for(client.kv_get("k"), timeout=5)
        # coordinator restarts on the SAME port (fresh state, as after a
        # crash without --persist-path)
        server2 = StoreServer(MemoryStore(), host="127.0.0.1", port=port)
        await server2.start()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            try:
                await client.kv_put("k2", b"v2")
                break
            except ConnectionError:
                await asyncio.sleep(0.1)
        else:
            raise AssertionError("client never reconnected")
        assert (await client.kv_get("k2")).value == b"v2"
        await server2.stop()
    finally:
        await client.close()


async def test_model_watch_resubscribes_after_watch_death():
    """ModelWatcher must resubscribe (not freeze) when its watch dies,
    and replay registry deltas from the fresh snapshot."""
    from dynamo_tpu.http.discovery import ModelWatcher
    from dynamo_tpu.http.service import ModelManager
    from dynamo_tpu.telemetry import REGISTRY

    class FakeWatch:
        def __init__(self, fail_after_start: bool):
            self.fail = fail_after_start
            self.queue: asyncio.Queue = asyncio.Queue()

        def snapshot(self):
            return []

        def __aiter__(self):
            return self._iter()

        async def _iter(self):
            if self.fail:
                raise RuntimeError("watch transport died")
            while True:
                item = await self.queue.get()
                if item is None:
                    return
                yield item

        async def close(self):
            self.queue.put_nowait(None)

    watches = [FakeWatch(True), FakeWatch(False)]
    calls = []

    class FakeStore:
        async def watch_prefix(self, prefix):
            calls.append(prefix)
            return watches[len(calls) - 1]

    class FakeDrt:
        store = FakeStore()

    metric = REGISTRY.get("dynamo_watch_restarts_total")
    before = metric.labels("models").value
    watcher = ModelWatcher(FakeDrt(), ModelManager())
    await watcher.start()
    try:
        deadline = time.monotonic() + 10
        while len(calls) < 2 and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        assert len(calls) == 2, "watch was never resubscribed"
        assert metric.labels("models").value == before + 1
    finally:
        await watcher.close()


# ---------------------------------------------------------------------------
# Deadline propagation over the worker wire
# ---------------------------------------------------------------------------


async def test_deadline_rides_the_endpoint_wire():
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.runtime import DistributedRuntime

    drt = await DistributedRuntime.create(
        config=RuntimeConfig(static=True, worker_host="127.0.0.1")
    )
    seen: dict = {}

    async def gen(request: Any, ctx: Context) -> AsyncIterator[Any]:
        seen["remaining_ms"] = ctx.remaining_ms()
        yield {"ok": True}

    try:
        ep = drt.namespace("t").component("c").endpoint("generate")
        await ep.serve(FnEngine(gen))
        client = await ep.client()
        ids = await client.wait_for_instances(timeout_s=5)
        ctx = Context()
        ctx.set_deadline_ms(5000.0)
        stream = await client.generate_direct(ids[0], {"x": 1}, ctx)
        await collect(stream)
        assert seen["remaining_ms"] is not None
        assert 0 < seen["remaining_ms"] <= 5000.0
        await client.close()
    finally:
        await drt.shutdown()
