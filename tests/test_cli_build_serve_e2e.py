"""Full-process packaging e2e (reference: `dynamo build` + cloud deploy
pull): `build --push` a @service graph into the coordinator's registry,
then `serve --package` pulls, verifies, unpacks, and supervises it —
and the served graph answers over the endpoint plane."""

import asyncio
import os
import subprocess
import sys
import time

from cli_harness import ENV, REPO, CliFleet, free_port


def test_build_push_serve_package_e2e(tmp_path):
    store_port = free_port()
    fleet = CliFleet()
    serve_proc = None
    try:
        fleet.spawn("store", "--host", "127.0.0.1", "--port", str(store_port))
        time.sleep(2)
        common = ["--store-host", "127.0.0.1", "--store-port", str(store_port)]

        # build + push (runs to completion)
        out = tmp_path / "hello.tar.gz"
        r = subprocess.run(
            [sys.executable, "-m", "dynamo_tpu.cli.main", "build",
             "examples.hello_world.graph:Frontend", "--name", "hello",
             "-o", str(out), "--push", *common],
            # generous: under full-suite load the interpreter-heavy
            # build subprocess can take far longer than its isolated
            # ~10 s (load flake otherwise)
            env=ENV, cwd=REPO, capture_output=True, text=True, timeout=300,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "pushed hello:" in r.stdout
        assert out.exists()

        # serve straight from the registry. A poison `examples` package
        # shadows the repo's on PYTHONPATH, so the graph can ONLY import
        # from the unpacked artifact (sys.path[0]) — if serve --package
        # ever stopped putting the package first, the shim raises and
        # the serve process dies loudly instead of silently falling back
        # to repo sources.
        shield = tmp_path / "shield" / "examples"
        shield.mkdir(parents=True)
        (shield / "__init__.py").write_text(
            "raise ImportError('examples must import from the unpacked "
            "package, not the repo')\n"
        )
        serve_env = dict(
            ENV,
            DYN_PACKAGE_DIR=str(tmp_path / "pkgs"),
            PYTHONPATH=f"{tmp_path / 'shield'}{os.pathsep}{ENV['PYTHONPATH']}",
        )
        serve_log = tmp_path / "serve.log"
        logf = open(serve_log, "w")
        # launch from the REPO deliberately: cmd_serve chdirs into the
        # package dir before supervising, so the checkout's examples/
        # must NOT leak into children via their cwd — combined with the
        # shim, any import of examples outside the artifact fails loud
        serve_proc = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.cli.main", "serve",
             "--package", "hello", *common],
            env=serve_env, cwd=REPO, stdout=logf,
            stderr=subprocess.STDOUT,
        )

        async def drive() -> list:
            from dynamo_tpu.runtime.config import RuntimeConfig
            from dynamo_tpu.runtime.engine import Context, collect
            from dynamo_tpu.runtime.runtime import DistributedRuntime

            drt = await DistributedRuntime.create(config=RuntimeConfig(
                store_host="127.0.0.1", store_port=store_port,
                worker_host="127.0.0.1",
            ))
            try:
                client = await (
                    drt.namespace("hello").component("frontend")
                    .endpoint("generate").client()
                )
                ids = await client.wait_for_instances(300)
                stream = await client.generate_direct(
                    ids[0], {"text": "ship it"}, Context()
                )
                return [i async for i in stream]
            finally:
                await drt.shutdown()

        items = asyncio.run(drive())
        texts = [i["text"] for i in items]
        assert texts == ["front.mid.back.ship", "front.mid.back.it"], texts
        # the package really was unpacked + imported from the state dir
        unpacked = list((tmp_path / "pkgs").glob("hello-*/src/examples"))
        assert unpacked, os.listdir(tmp_path / "pkgs")
        fleet.assert_alive()
        assert serve_proc.poll() is None
    finally:
        if serve_proc is not None:
            if serve_proc.poll() is None:
                serve_proc.terminate()
                try:
                    serve_proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    serve_proc.kill()
            logf.close()
            # surface the one log that matters when drive() fails
            print("=== serve --package log ===")
            print((tmp_path / "serve.log").read_text()[-3000:])
        fleet.teardown()
