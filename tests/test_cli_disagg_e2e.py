"""Full-process disaggregated serving e2e: the coordinator, a decode
worker with --disagg, a dedicated prefill worker, and an HTTP frontend —
all real CLI subprocesses. A prompt longer than
max-local-prefill-length exercises queue → prefill engine → KV transfer
→ host-tier onboarding → decode (the flagship path of SURVEY.md §3.3);
with random weights the assertions are structural (finish_reason and
usage counts), plus a short-prompt local-prefill request, and liveness
of every process afterwards."""

import json
import time

from cli_harness import MODEL_DIR, CliFleet, complete, free_port, wait_http


def test_disagg_serving_end_to_end():
    store_port = free_port()
    http_port = free_port()
    fleet = CliFleet()
    try:
        fleet.spawn("store", "--host", "127.0.0.1", "--port", str(store_port))
        time.sleep(2)
        common = ["--store-host", "127.0.0.1", "--store-port", str(store_port)]
        # decode worker: disagg on, low threshold so our prompt goes remote
        fleet.spawn(
            "run", "--in", "dyn://e2e.backend.generate", "--out", "jax",
            "--model-path", MODEL_DIR, "--disagg",
            "--max-local-prefill-length", "24",
            "--host-kv-blocks", "64",
            *common,
        )
        fleet.spawn(
            "run", "--role", "prefill", "--out", "jax",
            "--model-path", MODEL_DIR, "--namespace", "e2e",
            *common,
        )
        fleet.spawn(
            "run", "--in", "http", "--out", "dyn://e2e.backend.generate",
            "--model-path", MODEL_DIR, "--http-port", str(http_port),
            *common,
        )
        wait_http(
            f"http://127.0.0.1:{http_port}/v1/models",
            lambda b: json.loads(b)["data"],
        )
        # long prompt (> 24 tokens): forced through the remote-prefill path
        out = complete(http_port, "word " * 40, max_tokens=8)
        assert out["choices"][0]["finish_reason"] == "length"
        assert out["usage"]["completion_tokens"] == 8
        # short prompt: local prefill on the decode worker
        out2 = complete(http_port, "word " * 4, max_tokens=8)
        assert out2["choices"][0]["finish_reason"] == "length"
        assert out2["usage"]["completion_tokens"] == 8
        fleet.assert_alive()
    finally:
        fleet.teardown()
