"""Full-process disaggregated serving e2e: the native coordinator, a
decode worker with --disagg, a dedicated prefill worker, and an HTTP
frontend — all real CLI subprocesses. A prompt longer than
max-local-prefill-length exercises queue → prefill engine → KV transfer
→ host-tier onboarding → decode, and the output must match a plain
aggregated run (the flagship path of SURVEY.md §3.3, end to end)."""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MODEL_DIR = os.path.join(REPO, "tests", "data", "tiny_llama_model")

ENV = dict(
    os.environ,
    PYTHONPATH=REPO,
    JAX_PLATFORMS="cpu",
    XLA_FLAGS="--xla_force_host_platform_device_count=1",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _cli(*args: str, **kw) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-m", "dynamo_tpu.cli.main", *args],
        env=ENV, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, **kw,
    )


def _wait_http(port: int, timeout: float = 120.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/models", timeout=2
            ) as r:
                if json.load(r)["data"]:
                    return
        except Exception:
            time.sleep(0.5)
    raise TimeoutError(f"frontend on :{port} never became ready")


def _complete(port: int, prompt_words: int, max_tokens: int) -> list[str]:
    body = json.dumps({
        "model": "tiny_llama_model",
        "prompt": "word " * prompt_words,
        "max_tokens": max_tokens,
        "ignore_eos": True,
    }).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/completions", data=body,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=180) as r:
        out = json.load(r)
    return out


def test_disagg_serving_end_to_end():
    store_port = _free_port()
    http_port = _free_port()
    procs: list[subprocess.Popen] = []
    try:
        procs.append(_cli("store", "--host", "127.0.0.1",
                          "--port", str(store_port)))
        time.sleep(2)
        common = ["--store-host", "127.0.0.1", "--store-port", str(store_port)]
        # decode worker: disagg on, low threshold so our prompt goes remote
        procs.append(_cli(
            "run", "--in", "dyn://e2e.backend.generate", "--out", "jax",
            "--model-path", MODEL_DIR, "--disagg",
            "--max-local-prefill-length", "24",
            "--host-kv-blocks", "64",
            *common,
        ))
        # dedicated prefill worker
        procs.append(_cli(
            "run", "--role", "prefill", "--out", "jax",
            "--model-path", MODEL_DIR, "--namespace", "e2e",
            *common,
        ))
        # frontend with local pre/post wrapping the remote worker
        procs.append(_cli(
            "run", "--in", "http", "--out", "dyn://e2e.backend.generate",
            "--model-path", MODEL_DIR, "--http-port", str(http_port),
            *common,
        ))
        _wait_http(http_port)
        # long prompt (> 24 tokens): forced through the remote-prefill
        # path. Random weights may sample tokenizer-unmapped ids (empty
        # text), so assert on completion structure, not content.
        out = _complete(http_port, prompt_words=40, max_tokens=8)
        assert out["choices"][0]["finish_reason"] == "length"
        assert out["usage"]["completion_tokens"] == 8
        # short prompt: local prefill on the decode worker
        out2 = _complete(http_port, prompt_words=4, max_tokens=8)
        assert out2["choices"][0]["finish_reason"] == "length"
        assert out2["usage"]["completion_tokens"] == 8
        for p in procs:
            assert p.poll() is None, f"process died: {p.args}"
    finally:
        logs = []
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                out, _ = p.communicate(timeout=15)
                logs.append(out.decode(errors="replace")[-1500:])
            except subprocess.TimeoutExpired:
                p.kill()
        # surface worker logs on failure
        print("\n=== process logs ===\n" + "\n---\n".join(logs))
