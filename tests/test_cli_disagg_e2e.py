"""Full-process disaggregated serving e2e: the coordinator, a decode
worker with --disagg, a dedicated prefill worker, and an HTTP frontend —
all real CLI subprocesses. A prompt longer than
max-local-prefill-length exercises queue → prefill engine → KV transfer
→ host-tier onboarding → decode (the flagship path of SURVEY.md §3.3);
with random weights the assertions are structural (finish_reason and
usage counts), plus a short-prompt local-prefill request, and liveness
of every process afterwards.

Tracing (ISSUE 2 acceptance): every process runs with its own
DYN_TRACE_FILE; afterwards the merged span logs must contain ONE
connected trace for the long request — frontend root → router →
worker → prefill-queue wait → remote prefill → KV transfer → decode —
with every child span's wall-clock window nested inside the root
request span."""

import json
import os
import time

from cli_harness import (
    MODEL_DIR,
    CliFleet,
    complete,
    fetch_autopsy,
    free_port,
    wait_http,
)


def _load_spans(paths):
    from dynamo_tpu.telemetry.export import build_span_tree, load_spans

    spans = load_spans([p for p in paths if os.path.exists(p)])
    return spans, build_span_tree(spans)


def test_disagg_serving_end_to_end(tmp_path):
    store_port = free_port()
    http_port = free_port()
    trace_files = {
        role: str(tmp_path / f"{role}.jsonl")
        for role in ("frontend", "decode", "prefill")
    }
    fleet = CliFleet()
    try:
        fleet.spawn("store", "--host", "127.0.0.1", "--port", str(store_port))
        time.sleep(2)
        common = ["--store-host", "127.0.0.1", "--store-port", str(store_port)]
        # decode worker: disagg on, low threshold so our prompt goes
        # remote; an unattainable TTFT target forces an SLO miss on
        # every request, so the autopsy record below is retained as a
        # FLAG exemplar (not just tail warm-up)
        fleet.spawn(
            "run", "--in", "dyn://e2e.backend.generate", "--out", "jax",
            "--model-path", MODEL_DIR, "--disagg",
            "--max-local-prefill-length", "24",
            "--host-kv-blocks", "64",
            "--slo-ttft-ms", "0.001",
            *common,
            env={"DYN_TRACE_FILE": trace_files["decode"]},
        )
        fleet.spawn(
            "run", "--role", "prefill", "--out", "jax",
            "--model-path", MODEL_DIR, "--namespace", "e2e",
            *common,
            env={"DYN_TRACE_FILE": trace_files["prefill"]},
        )
        fleet.spawn(
            "run", "--in", "http", "--out", "dyn://e2e.backend.generate",
            "--model-path", MODEL_DIR, "--http-port", str(http_port),
            *common,
            env={"DYN_TRACE_FILE": trace_files["frontend"]},
        )
        wait_http(
            f"http://127.0.0.1:{http_port}/v1/models",
            lambda b: json.loads(b)["data"],
        )
        # long prompt (> 24 tokens): forced through the remote-prefill path
        out = complete(http_port, "word " * 40, max_tokens=8)
        assert out["choices"][0]["finish_reason"] == "length"
        assert out["usage"]["completion_tokens"] == 8
        # short prompt: local prefill on the decode worker
        out2 = complete(http_port, "word " * 4, max_tokens=8)
        assert out2["choices"][0]["finish_reason"] == "length"
        assert out2["usage"]["completion_tokens"] == 8

        # ---- request autopsy (ISSUE 19): ONE rid ties the frontend's
        # host stages, the router decision, the decode worker's engine
        # segment, and the prefill-queue segment into one record that
        # crossed three processes on the seg wire frame
        rid = "autopsy-disagg-e2e"
        # a prompt the prefix cache has NOT seen: a fully-cached repeat
        # of the first prompt would prefill locally (nothing left over
        # the remote threshold) and never produce the remote_prefill
        # segment this record must carry
        out3 = complete(http_port, "story " * 40, max_tokens=8, rid=rid)
        assert out3["choices"][0]["finish_reason"] == "length"
        rec = fetch_autopsy(http_port, rid)
        assert rec["rid"] == rid and rec["status"] == "200"
        # the worker's unattainable TTFT target flagged the record —
        # retained as an exemplar by FLAG, not warm-up luck
        assert "slo_miss" in rec["flags"], rec["flags"]
        assert rec["retained"] == "flag"
        # frontend side: real host stages on the record
        stages = (rec["host"] or {}).get("stages_ms") or {}
        assert "preprocess" in stages and "dispatch" in stages, stages
        # router side: the dial that placed it
        assert rec["router"], rec
        # engine side (decode worker, another process): the segment
        # shipped on the seg frame, with the remote-prefill wait
        sources = {s["source"] for s in rec["segments"]}
        assert "engine" in sources and "remote_prefill" in sources, sources
        eng = next(s for s in rec["segments"] if s["source"] == "engine")
        assert eng["slo_miss"] is True
        assert eng["tokens"] == 8
        assert "prefill_ms" in eng and "queue_wait_ms" in eng, eng
        # the waterfall's attributed stages explain the wall clock to
        # within the 10% acceptance bound
        from dynamo_tpu.telemetry.autopsy import waterfall

        wf = waterfall(rec)
        assert wf["covered"], wf
        fleet.assert_alive()
    finally:
        fleet.teardown()

    # ---- exported trace: one connected tree across three processes ------
    spans, traces = _load_spans(trace_files.values())
    assert spans, "no spans exported despite DYN_TRACE_FILE"
    by_name_global = {}
    for s in spans:
        by_name_global.setdefault(s["name"], []).append(s)

    # the long request's trace is the one that crossed the prefill queue
    queue_waits = by_name_global.get("prefill_queue.wait") or []
    assert queue_waits, "remote-prefill path produced no queue-wait span"
    trace_id = queue_waits[0]["trace_id"]
    trace = traces[trace_id]
    names = {s["name"] for s in trace["spans"]}
    # timeout fallback (transfer slower than transfer_timeout_s under CI
    # load): the decode worker prefilled locally and the prefill
    # worker's subtree may be incomplete/straggling — those spans are
    # then optional and exempt from nesting, everything else still holds
    fallback = bool(queue_waits[0]["attrs"].get("timeout_fallback"))
    required = {
        "http.request",        # frontend root
        "preprocess",          # frontend tokenize
        "router.dispatch",     # frontend -> worker routing
        "worker.generate",     # decode worker endpoint stream
        "prefill_queue.wait",  # decode-side enqueue-to-KV-landed wait
        "engine.prefill",      # decode engine phases
        "engine.decode",
    }
    prefill_side = {"prefill.remote", "kv_transfer.put"}
    if not fallback:
        required |= prefill_side  # prefill worker's compute + shipment
    assert required <= names, f"missing spans: {required - names}"

    by_id = {s["span_id"]: s for s in trace["spans"]}

    def one(name):
        matches = [s for s in trace["spans"] if s["name"] == name]
        assert matches, name
        return matches[0]

    root = one("http.request")
    assert "parent_id" not in root or root["parent_id"] is None
    assert root["attrs"]["request_id"]

    # parent links: each hop chains into the previous one
    assert one("router.dispatch")["parent_id"] == root["span_id"]
    assert by_id[one("worker.generate")["span_id"]]["parent_id"] == (
        one("router.dispatch")["span_id"]
    )
    assert one("prefill_queue.wait")["parent_id"] == (
        one("worker.generate")["span_id"]
    )
    if "prefill.remote" in names:
        assert one("prefill.remote")["parent_id"] == (
            one("prefill_queue.wait")["span_id"]
        )
    if "kv_transfer.put" in names:
        assert one("kv_transfer.put")["parent_id"] == (
            one("prefill.remote")["span_id"]
        )
    # decode-worker engine spans parent on the worker stream span
    decode_engines = [
        s for s in trace["spans"]
        if s["name"] == "engine.decode"
        and s.get("parent_id") == one("worker.generate")["span_id"]
    ]
    assert decode_engines, "decode engine span not linked to the worker span"

    # nesting: every child's wall-clock window sits inside the root
    # request span (same machine — one system clock; small epsilon for
    # write-time jitter). On timeout fallback the prefill worker's
    # subtree (prefill.remote and descendants) legitimately outlives
    # the request — exclude exactly that subtree then.
    stragglers: set = set()
    if fallback and "prefill.remote" in names:
        frontier = {one("prefill.remote")["span_id"]}
        while frontier:
            stragglers |= frontier
            frontier = {
                s["span_id"] for s in trace["spans"]
                if s.get("parent_id") in frontier
                and s["span_id"] not in stragglers
            }
    eps = 0.25
    r0 = root["start"]
    r1 = root["start"] + root["duration_s"]
    for s in trace["spans"]:
        if s["span_id"] == root["span_id"] or s["span_id"] in stragglers:
            continue
        s0 = s["start"]
        s1 = s["start"] + (s["duration_s"] or 0.0)
        assert s0 >= r0 - eps, f"{s['name']} starts before the root span"
        assert s1 <= r1 + eps, f"{s['name']} ends after the root span"

    # the short request produced a second, disjoint trace with NO
    # queue-wait span (local prefill) — the autopsy request's trace
    # (long prompt, remote prefill) legitimately has one
    local_traces = [
        t for tid, t in traces.items()
        if tid != trace_id
        and any(s["name"] == "http.request" for s in t["spans"])
    ]
    assert local_traces, "short request produced no trace"
    assert any(
        "prefill_queue.wait" not in {s["name"] for s in t["spans"]}
        for t in local_traces
    )
