"""Graceful-drain e2e (ISSUE 20 acceptance, docs/robustness.md
"Graceful drain & rolling restarts"): SIGTERM a serving worker
mid-stream with a healthy peer up — the client's SSE stream must splice
onto the peer byte-identically with zero SSE errors, the worker must
exit 0 within the drain deadline, and the request's autopsy must show
the planned handoff (reason=drain, no synthesized worker_died segment —
the commit log was exact, nothing was lost). Plus the operator path:
``dynamo-tpu drain <worker>`` retires one worker of two through the
worker-control subject and returns once discovery shows it gone."""

import asyncio
import json
import signal
import subprocess
import sys
import time
import urllib.request

from cli_harness import (
    ENV,
    MODEL_DIR,
    CliFleet,
    fetch_autopsy,
    free_port,
    wait_http,
)
from test_cli_failover_e2e import _metric_value


def _instance_keys(store_port: int, namespace: str) -> list[str]:
    """Discovery listing via a short-lived store client (what the
    ``drain`` subcommand itself polls)."""
    from dynamo_tpu.store.client import StoreClient

    async def go():
        client = await StoreClient.connect("127.0.0.1", store_port)
        try:
            entries = await client.kv_get_prefix(f"instances/{namespace}/")
            return sorted(e.key for e in entries)
        finally:
            await client.close()

    return asyncio.run(go())


def test_sigterm_mid_stream_drains_byte_identical():
    """The tentpole proof: a drain is INVISIBLE to the client. Compare
    with test_cli_failover_e2e's SIGKILL twin — there the victim's
    finish is synthesized (worker_died); here the worker hands the
    stream off at a step boundary with an exact commit log and exits 0."""
    store_port = free_port()
    http_port = free_port()
    metrics_port = free_port()
    fleet = CliFleet()
    try:
        fleet.spawn("store", "--host", "127.0.0.1", "--port", str(store_port))
        time.sleep(2)
        common = ["--store-host", "127.0.0.1", "--store-port", str(store_port)]
        # the victim steps slowly (output-neutral injected delay) so the
        # stream outlives the survivor's spawn + registration
        victim = fleet.spawn(
            "run", "--in", "dyn://gd.backend.generate", "--out", "jax",
            "--model-path", MODEL_DIR, *common,
            env={"DYN_FAULTS": "seed=1;engine.step:delay=0.5"},
        )
        fleet.spawn(
            "run", "--in", "http", "--out", "dyn://gd.backend.generate",
            "--model-path", MODEL_DIR, "--http-port", str(http_port),
            *common,
        )
        fleet.spawn(
            "metrics", "--namespace", "gd", "--component", "backend",
            "--port", str(metrics_port), *common,
        )
        wait_http(
            f"http://127.0.0.1:{http_port}/v1/models",
            lambda b: json.loads(b)["data"],
        )
        prompt = "graceful drain byte identity"
        n_tokens = 240  # ≥120 s of stream at the injected 0.5 s/step
        body = json.dumps({
            "model": "tiny_llama_model", "prompt": prompt,
            "max_tokens": n_tokens, "stream": True, "temperature": 0,
            "ext": {"ignore_eos": True},
        }).encode()
        rid = "autopsy-drain-e2e"
        req = urllib.request.Request(
            f"http://127.0.0.1:{http_port}/v1/completions", data=body,
            headers={"Content-Type": "application/json",
                     "X-Request-Id": rid},
        )
        resp = urllib.request.urlopen(req, timeout=60)
        first = resp.readline()
        assert first.startswith(b"data:"), first
        # tokens are flowing on the slow victim: bring up the survivor
        fleet.spawn(
            "run", "--in", "dyn://gd.backend.generate", "--out", "jax",
            "--model-path", MODEL_DIR, *common,
        )
        wait_http(
            f"http://127.0.0.1:{metrics_port}/metrics",
            lambda b: b"llm_workers_reporting 2" in b.replace(b".0", b""),
            timeout=120,
        )
        # the planned departure: SIGTERM, not SIGKILL
        victim.send_signal(signal.SIGTERM)
        # drain the stream while the handoff happens underneath it
        lines = [first]
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = resp.readline()
            if not line:
                break
            lines.append(line)
        text = b"".join(lines).decode()
        assert "event: error" not in text, text[-2000:]
        assert "[DONE]" in text, text[-2000:]
        # the worker drained and exited CLEANLY within the deadline
        assert victim.wait(timeout=60) == 0
        fleet.forget(victim)
        chunks = [
            json.loads(ln[len("data:"):].strip())
            for ln in text.splitlines()
            if ln.startswith("data:") and "[DONE]" not in ln
        ]
        streamed = "".join(
            c["choices"][0].get("text") or ""
            for c in chunks if c.get("choices")
        )
        finishes = [
            c["choices"][0].get("finish_reason")
            for c in chunks if c.get("choices")
        ]
        assert finishes[-1] == "length", finishes[-5:]
        # byte identity against the no-drain greedy baseline on the peer
        base_body = json.dumps({
            "model": "tiny_llama_model", "prompt": prompt,
            "max_tokens": n_tokens, "temperature": 0,
            "ext": {"ignore_eos": True},
        }).encode()
        base = json.load(urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{http_port}/v1/completions", data=base_body,
            headers={"Content-Type": "application/json"},
        ), timeout=180))
        assert base["choices"][0]["finish_reason"] == "length"
        assert streamed == base["choices"][0]["text"]
        # the frontend scored a planned handoff: one ok resume, no abort
        assert _metric_value(
            http_port, "dynamo_midstream_resumes_total", result="ok"
        ) >= 1
        assert _metric_value(http_port, "dynamo_midstream_aborts_total") == 0

        # autopsy: the splice is stamped reason=drain, the handoff event
        # names the departing worker, and — unlike the SIGKILL twin —
        # NOTHING was synthesized: the victim ended its own segment at
        # the step boundary with the commit log exact
        rec = fetch_autopsy(http_port, rid)
        assert "migrated" in rec["flags"], rec["flags"]
        splices = [e for e in rec["events"]
                   if e.get("kind") == "resume_splice"]
        assert splices, rec["events"]
        assert splices[0]["reason"] == "drain"
        assert splices[0]["from_worker"] != splices[0]["to_worker"]
        assert splices[0]["delivered"] >= 1
        handoffs = [e for e in rec["events"]
                    if e.get("kind") == "drain_handoff"]
        assert handoffs, rec["events"]
        assert handoffs[0]["worker"] == splices[0]["from_worker"]
        assert handoffs[0]["delivered"] == splices[0]["delivered"]
        assert not [s for s in rec["segments"]
                    if s["source"] == "worker_died"], rec["segments"]
        # both dials recorded; the survivor's is marked as the resume
        assert len(rec["router"]) >= 2
        assert rec["router"][-1]["resume"] is True
        fleet.assert_alive()
    finally:
        fleet.teardown()


def test_drain_subcommand_retires_one_worker():
    """Operator surface: ``dynamo-tpu drain <worker>`` publishes the
    control call, the worker converges onto the SIGTERM path, drains,
    deregisters, and exits 0 — and the subcommand returns success only
    once discovery shows the instance gone."""
    store_port = free_port()
    fleet = CliFleet()
    try:
        fleet.spawn("store", "--host", "127.0.0.1", "--port", str(store_port))
        time.sleep(2)
        common = ["--store-host", "127.0.0.1", "--store-port", str(store_port)]
        workers = [
            fleet.spawn(
                "run", "--in", "dyn://dd.backend.generate", "--out", "jax",
                "--model-path", MODEL_DIR, *common,
            )
            for _ in range(2)
        ]
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            keys = _instance_keys(store_port, "dd")
            if len(keys) == 2:
                break
            time.sleep(1)
        assert len(keys) == 2, keys
        target_hex = keys[0].rpartition(":")[2]
        out = subprocess.run(
            [sys.executable, "-m", "dynamo_tpu.cli.main", "drain",
             target_hex, "--namespace", "dd", *common],
            env=ENV, capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "drained and deregistered" in out.stdout
        # exactly the targeted worker exited, cleanly; its peer serves on
        remaining = _instance_keys(store_port, "dd")
        assert remaining == [k for k in keys if not k.endswith(target_hex)]
        exited = [w for w in workers if w.poll() is not None]
        assert len(exited) == 1
        assert exited[0].returncode == 0
        fleet.forget(exited[0])
        fleet.assert_alive()
    finally:
        fleet.teardown()
