"""Full-process elasticity e2e: two workers behind a frontend; killing
one mid-service must not break serving (reference: etcd-lease liveness —
lease revoke/expiry removes a dead worker from router views and traffic
continues on the survivors, docs/disagg_serving.md elasticity story)."""

import json
import signal
import socket
import time
import urllib.request

from cli_harness import (
    MODEL_DIR,
    CliFleet,
    complete,
    fetch_autopsy,
    free_port,
    wait_http,
)


def _metric_value(port: int, name: str, **labels) -> float:
    """Sum of one family's samples on a /metrics page (0 if absent),
    via the repo's strict exposition parser, filtered by label values."""
    from prom_parser import parse as prom_parse

    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ).read().decode()
    family = prom_parse(body).get(name)
    if family is None:
        return 0.0
    total = 0.0
    for (_sample, label_items), value in family.samples.items():
        if all(dict(label_items).get(k) == v for k, v in labels.items()):
            total += value
    return total


def test_worker_death_failover():
    store_port = free_port()
    http_port = free_port()
    metrics_port = free_port()
    fleet = CliFleet()
    try:
        fleet.spawn("store", "--host", "127.0.0.1", "--port", str(store_port))
        time.sleep(2)
        common = ["--store-host", "127.0.0.1", "--store-port", str(store_port)]
        workers = []
        for _ in range(2):
            workers.append(fleet.spawn(
                "run", "--in", "dyn://ha.backend.generate", "--out", "jax",
                "--model-path", MODEL_DIR, *common,
            ))
        fleet.spawn(
            "run", "--in", "http", "--out", "dyn://ha.backend.generate",
            "--model-path", MODEL_DIR, "--http-port", str(http_port),
            *common,
        )
        fleet.spawn(
            "metrics", "--namespace", "ha", "--component", "backend",
            "--port", str(metrics_port), *common,
        )
        wait_http(
            f"http://127.0.0.1:{http_port}/v1/models",
            lambda b: json.loads(b)["data"],
        )
        wait_http(
            f"http://127.0.0.1:{metrics_port}/metrics",
            lambda b: b"llm_workers_reporting 2" in b.replace(b".0", b""),
        )
        # healthy: several requests round-robin over both workers
        for _ in range(4):
            out = complete(http_port, "failover test prompt", max_tokens=4)
            assert out["choices"][0]["finish_reason"] == "length"

        # the metrics service mirrors the frontend's debug surface
        # (ISSUE 19 satellite): kvfleet and the autopsy pair answer live
        mirror = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{metrics_port}/debug/requests", timeout=10
        ))
        assert "collector" in mirror, mirror
        json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{metrics_port}/debug/kvfleet", timeout=10
        ))

        # hard-kill one worker (no graceful drain: its connection drop
        # must revoke the lease and remove it from routing)
        workers[0].send_signal(signal.SIGKILL)
        workers[0].wait(timeout=10)
        fleet.forget(workers[0])

        # traffic must keep succeeding; allow a brief window where the
        # router can still pick the dead instance before the lease sweep
        deadline = time.monotonic() + 60
        ok = 0
        while ok < 6 and time.monotonic() < deadline:
            try:
                out = complete(http_port, "failover test prompt", max_tokens=4)
                if out["choices"][0]["finish_reason"] == "length":
                    ok += 1
            except Exception:
                time.sleep(0.5)
        assert ok >= 6, f"only {ok} successful requests after worker death"
        # and the survivor is the only one reporting
        wait_http(
            f"http://127.0.0.1:{metrics_port}/metrics",
            lambda b: b"llm_workers_reporting 1" in b.replace(b".0", b""),
            timeout=60,
        )
        fleet.assert_alive()
    finally:
        fleet.teardown()


def test_mid_stream_kill_migrates_byte_identical():
    """ISSUE-14 acceptance: SIGKILL the serving worker after tokens have
    streamed; with a survivor available the client receives ONE
    uninterrupted SSE stream whose full greedy text is byte-identical
    to a no-kill run — no SSE error, no duplicate or missing tokens at
    the splice — and the frontend counts a resume, not an abort.

    Worker A (the victim) runs with an injected per-step delay (proven
    output-neutral by the chaos suite) so the stream outlives worker
    B's spawn + registration; B is clean and serves both the resumed
    continuation and the no-kill baseline."""
    store_port = free_port()
    http_port = free_port()
    metrics_port = free_port()
    fleet = CliFleet()
    try:
        fleet.spawn("store", "--host", "127.0.0.1", "--port", str(store_port))
        time.sleep(2)
        common = ["--store-host", "127.0.0.1", "--store-port", str(store_port)]
        victim = fleet.spawn(
            "run", "--in", "dyn://mig.backend.generate", "--out", "jax",
            "--model-path", MODEL_DIR, *common,
            env={"DYN_FAULTS": "seed=1;engine.step:delay=0.5"},
        )
        fleet.spawn(
            "run", "--in", "http", "--out", "dyn://mig.backend.generate",
            "--model-path", MODEL_DIR, "--http-port", str(http_port),
            *common,
        )
        fleet.spawn(
            "metrics", "--namespace", "mig", "--component", "backend",
            "--port", str(metrics_port), *common,
        )
        wait_http(
            f"http://127.0.0.1:{http_port}/v1/models",
            lambda b: json.loads(b)["data"],
        )
        prompt = "migration byte identity"
        # the victim's stream must OUTLIVE the survivor's spawn +
        # registration even on a loaded machine (JIT prewarm can take
        # ~60 s there — see wait_for_instances): 240 tokens at the
        # injected 0.5 s/step keep it alive ≥120 s, matching the
        # reporting-wait ceiling below; in the good case the kill lands
        # within seconds and the survivor finishes the rest fast
        n_tokens = 240
        body = json.dumps({
            "model": "tiny_llama_model", "prompt": prompt,
            "max_tokens": n_tokens, "stream": True, "temperature": 0,
            "ext": {"ignore_eos": True},
        }).encode()
        mig_rid = "autopsy-migration-e2e"
        req = urllib.request.Request(
            f"http://127.0.0.1:{http_port}/v1/completions", data=body,
            headers={"Content-Type": "application/json",
                     "X-Request-Id": mig_rid},
        )
        resp = urllib.request.urlopen(req, timeout=60)
        first = resp.readline()
        assert first.startswith(b"data:"), first
        # tokens are flowing on the (slow) victim: bring up the survivor
        survivor = fleet.spawn(
            "run", "--in", "dyn://mig.backend.generate", "--out", "jax",
            "--model-path", MODEL_DIR, *common,
        )
        assert survivor is not None
        wait_http(
            f"http://127.0.0.1:{metrics_port}/metrics",
            lambda b: b"llm_workers_reporting 2" in b.replace(b".0", b""),
            timeout=120,
        )
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=10)
        fleet.forget(victim)
        # drain the stream: it must complete cleanly (no error event)
        lines = [first]
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = resp.readline()
            if not line:
                break
            lines.append(line)
        text = b"".join(lines).decode()
        assert "event: error" not in text, text[-2000:]
        assert "[DONE]" in text, text[-2000:]
        chunks = [
            json.loads(ln[len("data:"):].strip())
            for ln in text.splitlines()
            if ln.startswith("data:") and "[DONE]" not in ln
        ]
        streamed = "".join(
            c["choices"][0].get("text") or "" for c in chunks if c.get("choices")
        )
        finishes = [
            c["choices"][0].get("finish_reason")
            for c in chunks if c.get("choices")
        ]
        assert finishes[-1] == "length", finishes[-5:]
        # the no-kill baseline: the same greedy request on the survivor
        base_body = json.dumps({
            "model": "tiny_llama_model", "prompt": prompt,
            "max_tokens": n_tokens, "temperature": 0,
            "ext": {"ignore_eos": True},
        }).encode()
        base = json.load(urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{http_port}/v1/completions", data=base_body,
            headers={"Content-Type": "application/json"},
        ), timeout=180))
        assert base["choices"][0]["finish_reason"] == "length"
        assert streamed == base["choices"][0]["text"]
        # the frontend counted a successful resume and NO abort
        assert _metric_value(
            http_port, "dynamo_midstream_resumes_total", result="ok"
        ) >= 1
        assert _metric_value(
            http_port, "dynamo_midstream_aborts_total"
        ) == 0

        # ---- request autopsy (ISSUE 19 acceptance): the mid-stream-
        # killed request's record shows BOTH workers' segments and the
        # splice point. The victim died by SIGKILL, so its engine
        # segment can never ship — the frontend synthesized its side
        # (worker_died); the survivor's real engine segment arrived on
        # the seg wire frame with the resume offset.
        rec = fetch_autopsy(http_port, mig_rid)
        assert "migrated" in rec["flags"], rec["flags"]
        assert rec["retained"] == "flag"
        died = [s for s in rec["segments"] if s["source"] == "worker_died"]
        engine = [s for s in rec["segments"] if s["source"] == "engine"]
        assert died and engine, rec["segments"]
        assert died[0]["tokens"] >= 1  # the victim delivered tokens
        assert engine[0]["resume_offset"] == died[0]["tokens"]
        splices = [e for e in rec["events"]
                   if e.get("kind") == "resume_splice"]
        assert splices, rec["events"]
        assert splices[0]["from_worker"] == died[0]["worker"]
        assert splices[0]["to_worker"] != splices[0]["from_worker"]
        assert splices[0]["delivered"] == died[0]["tokens"]
        # both dials recorded; the survivor's is marked as the resume
        assert len(rec["router"]) >= 2
        assert rec["router"][-1]["resume"] is True
        fleet.assert_alive()
    finally:
        fleet.teardown()


def test_worker_death_mid_stream_never_hangs():
    """Kill the worker WHILE a response is streaming: the SSE stream
    must terminate promptly — either with a clean `error` event or a
    final chunk + [DONE] — never hang the connection (docs/
    robustness.md mid-stream failover contract)."""
    store_port = free_port()
    http_port = free_port()
    fleet = CliFleet()
    try:
        fleet.spawn("store", "--host", "127.0.0.1", "--port", str(store_port))
        time.sleep(2)
        common = ["--store-host", "127.0.0.1", "--store-port", str(store_port)]
        worker = fleet.spawn(
            "run", "--in", "dyn://ms.backend.generate", "--out", "jax",
            "--model-path", MODEL_DIR, *common,
        )
        fleet.spawn(
            "run", "--in", "http", "--out", "dyn://ms.backend.generate",
            "--model-path", MODEL_DIR, "--http-port", str(http_port),
            *common,
        )
        wait_http(
            f"http://127.0.0.1:{http_port}/v1/models",
            lambda b: json.loads(b)["data"],
        )
        body = json.dumps({
            "model": "tiny_llama_model", "prompt": "mid stream kill",
            "max_tokens": 100000, "stream": True,
            "ext": {"ignore_eos": True},
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{http_port}/v1/completions", data=body,
            headers={"Content-Type": "application/json"},
        )
        # per-read socket timeout is the hang detector: any single read
        # stalling past it fails the test
        resp = urllib.request.urlopen(req, timeout=30)
        first = resp.readline()
        assert first.startswith(b"data:"), first
        # tokens are flowing: hard-kill the only worker mid-generation
        worker.send_signal(signal.SIGKILL)
        worker.wait(timeout=10)
        fleet.forget(worker)
        deadline = time.monotonic() + 60
        tail = [first]
        try:
            while time.monotonic() < deadline:
                line = resp.readline()
                if not line:
                    break  # clean EOF: the server closed the stream
                tail.append(line)
            else:
                raise AssertionError(
                    f"stream still open 60s after worker death: "
                    f"{tail[-3:]!r}"
                )
        except socket.timeout:
            raise AssertionError(
                f"stream READ hung after worker death: {tail[-3:]!r}"
            )
        text = b"".join(tail).decode(errors="replace")
        # clean termination: an SSE error event, or a final chunk +
        # [DONE] (the backend converts an ended stream into a finish)
        assert ("event: error" in text) or ("[DONE]" in text), text[-2000:]
        fleet.assert_alive()
    finally:
        fleet.teardown()
