"""Full-process elasticity e2e: two workers behind a frontend; killing
one mid-service must not break serving (reference: etcd-lease liveness —
lease revoke/expiry removes a dead worker from router views and traffic
continues on the survivors, docs/disagg_serving.md elasticity story)."""

import json
import signal
import socket
import time
import urllib.request

from cli_harness import MODEL_DIR, CliFleet, complete, free_port, wait_http


def test_worker_death_failover():
    store_port = free_port()
    http_port = free_port()
    metrics_port = free_port()
    fleet = CliFleet()
    try:
        fleet.spawn("store", "--host", "127.0.0.1", "--port", str(store_port))
        time.sleep(2)
        common = ["--store-host", "127.0.0.1", "--store-port", str(store_port)]
        workers = []
        for _ in range(2):
            workers.append(fleet.spawn(
                "run", "--in", "dyn://ha.backend.generate", "--out", "jax",
                "--model-path", MODEL_DIR, *common,
            ))
        fleet.spawn(
            "run", "--in", "http", "--out", "dyn://ha.backend.generate",
            "--model-path", MODEL_DIR, "--http-port", str(http_port),
            *common,
        )
        fleet.spawn(
            "metrics", "--namespace", "ha", "--component", "backend",
            "--port", str(metrics_port), *common,
        )
        wait_http(
            f"http://127.0.0.1:{http_port}/v1/models",
            lambda b: json.loads(b)["data"],
        )
        wait_http(
            f"http://127.0.0.1:{metrics_port}/metrics",
            lambda b: b"llm_workers_reporting 2" in b.replace(b".0", b""),
        )
        # healthy: several requests round-robin over both workers
        for _ in range(4):
            out = complete(http_port, "failover test prompt", max_tokens=4)
            assert out["choices"][0]["finish_reason"] == "length"

        # hard-kill one worker (no graceful drain: its connection drop
        # must revoke the lease and remove it from routing)
        workers[0].send_signal(signal.SIGKILL)
        workers[0].wait(timeout=10)
        fleet.forget(workers[0])

        # traffic must keep succeeding; allow a brief window where the
        # router can still pick the dead instance before the lease sweep
        deadline = time.monotonic() + 60
        ok = 0
        while ok < 6 and time.monotonic() < deadline:
            try:
                out = complete(http_port, "failover test prompt", max_tokens=4)
                if out["choices"][0]["finish_reason"] == "length":
                    ok += 1
            except Exception:
                time.sleep(0.5)
        assert ok >= 6, f"only {ok} successful requests after worker death"
        # and the survivor is the only one reporting
        wait_http(
            f"http://127.0.0.1:{metrics_port}/metrics",
            lambda b: b"llm_workers_reporting 1" in b.replace(b".0", b""),
            timeout=60,
        )
        fleet.assert_alive()
    finally:
        fleet.teardown()


def test_worker_death_mid_stream_never_hangs():
    """Kill the worker WHILE a response is streaming: the SSE stream
    must terminate promptly — either with a clean `error` event or a
    final chunk + [DONE] — never hang the connection (docs/
    robustness.md mid-stream failover contract)."""
    store_port = free_port()
    http_port = free_port()
    fleet = CliFleet()
    try:
        fleet.spawn("store", "--host", "127.0.0.1", "--port", str(store_port))
        time.sleep(2)
        common = ["--store-host", "127.0.0.1", "--store-port", str(store_port)]
        worker = fleet.spawn(
            "run", "--in", "dyn://ms.backend.generate", "--out", "jax",
            "--model-path", MODEL_DIR, *common,
        )
        fleet.spawn(
            "run", "--in", "http", "--out", "dyn://ms.backend.generate",
            "--model-path", MODEL_DIR, "--http-port", str(http_port),
            *common,
        )
        wait_http(
            f"http://127.0.0.1:{http_port}/v1/models",
            lambda b: json.loads(b)["data"],
        )
        body = json.dumps({
            "model": "tiny_llama_model", "prompt": "mid stream kill",
            "max_tokens": 100000, "stream": True,
            "ext": {"ignore_eos": True},
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{http_port}/v1/completions", data=body,
            headers={"Content-Type": "application/json"},
        )
        # per-read socket timeout is the hang detector: any single read
        # stalling past it fails the test
        resp = urllib.request.urlopen(req, timeout=30)
        first = resp.readline()
        assert first.startswith(b"data:"), first
        # tokens are flowing: hard-kill the only worker mid-generation
        worker.send_signal(signal.SIGKILL)
        worker.wait(timeout=10)
        fleet.forget(worker)
        deadline = time.monotonic() + 60
        tail = [first]
        try:
            while time.monotonic() < deadline:
                line = resp.readline()
                if not line:
                    break  # clean EOF: the server closed the stream
                tail.append(line)
            else:
                raise AssertionError(
                    f"stream still open 60s after worker death: "
                    f"{tail[-3:]!r}"
                )
        except socket.timeout:
            raise AssertionError(
                f"stream READ hung after worker death: {tail[-3:]!r}"
            )
        text = b"".join(tail).decode(errors="replace")
        # clean termination: an SSE error event, or a final chunk +
        # [DONE] (the backend converts an ended stream into a finish)
        assert ("event: error" in text) or ("[DONE]" in text), text[-2000:]
        fleet.assert_alive()
    finally:
        fleet.teardown()
