"""Full-process KV-aware routing e2e: coordinator + TWO jax workers
publishing KV events + an HTTP frontend with --router-mode kv + the
metrics service — all real CLI subprocesses. Repeating a prompt must
keep landing on the worker that cached it (the reference's flagship
3x-TTFT feature, SURVEY.md §3.3/§6), observable as a high average
prefix-overlap in the metrics service's Prometheus exposition."""

from cli_harness import MODEL_DIR, CliFleet, complete, free_port, wait_http

import json
import time
import urllib.request


def test_kv_routing_end_to_end():
    store_port = free_port()
    http_port = free_port()
    metrics_port = free_port()
    fleet = CliFleet()
    try:
        fleet.spawn("store", "--host", "127.0.0.1", "--port", str(store_port))
        time.sleep(2)
        common = ["--store-host", "127.0.0.1", "--store-port", str(store_port)]
        for _ in range(2):
            fleet.spawn(
                "run", "--in", "dyn://kvr.backend.generate", "--out", "jax",
                "--model-path", MODEL_DIR, *common,
            )
        fleet.spawn(
            "run", "--in", "http", "--out", "dyn://kvr.backend.generate",
            "--model-path", MODEL_DIR, "--http-port", str(http_port),
            "--router-mode", "kv", *common,
        )
        fleet.spawn(
            "metrics", "--namespace", "kvr", "--component", "backend",
            "--port", str(metrics_port), *common,
        )
        wait_http(
            f"http://127.0.0.1:{http_port}/v1/models",
            lambda b: json.loads(b)["data"],
        )
        # BOTH workers must be routable before measuring, or the test
        # passes vacuously with every request pinned to the only worker
        wait_http(
            f"http://127.0.0.1:{metrics_port}/metrics",
            lambda b: b"llm_workers_reporting 2" in b.replace(b".0", b""),
        )

        # a long shared prefix, repeated: after the first request caches
        # it on one worker, the KV router must keep routing there
        prompt = "alpha beta gamma delta " * 8
        for _ in range(5):
            out = complete(http_port, prompt, max_tokens=4)
            assert out["choices"][0]["finish_reason"] == "length"

        def scrape() -> dict[str, float]:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{metrics_port}/metrics", timeout=5
            ) as r:
                out = {}
                for line in r.read().decode().splitlines():
                    if line and not line.startswith("#"):
                        name = line.split("{")[0].split(" ")[0]
                        out[name] = float(line.rsplit(" ", 1)[1])
                return out

        deadline = time.monotonic() + 60
        hit = 0.0
        while time.monotonic() < deadline:
            hit = scrape().get("llm_kv_avg_hit_rate", 0.0)
            if hit > 0.5:
                break
            time.sleep(1)
        # repeats after the first must overlap the cached prefix almost
        # fully; random/RR routing across 2 workers would average far
        # lower. (4/5 requests can hit; threshold leaves slack.)
        assert hit > 0.5, f"kv routing ineffective: avg hit rate {hit}"
        fleet.assert_alive()
    finally:
        fleet.teardown()
