"""run-CLI in×out matrix additions: stdin / batch: inputs, pystr: output
(reference: launch/dynamo-run opt.rs in/out matrix; lib/engines/python
python-hosted engine)."""

import json
import os
import subprocess
import sys

import pytest

from dynamo_tpu.engines import EchoEngineFull, PythonStrEngine
from dynamo_tpu.protocols.openai import ChatCompletionRequest, CompletionRequest
from dynamo_tpu.runtime.engine import Context

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PYSTR_SRC = '''\
async def generate(request):
    prompt = request.get("prompt") or request["messages"][-1]["content"]
    for word in prompt.split():
        yield word.upper() + " "
'''


@pytest.fixture
def pystr_file(tmp_path):
    p = tmp_path / "upper_engine.py"
    p.write_text(PYSTR_SRC)
    return str(p)


async def test_pystr_engine_completion_and_chat(pystr_file):
    eng = PythonStrEngine(pystr_file)
    req = CompletionRequest.model_validate(
        {"model": "m", "prompt": "hello tpu world"}
    )
    parts = []
    async for chunk in eng.generate(req, Context()):
        parts.append(chunk.choices[0].text)
    assert "".join(parts).split() == ["HELLO", "TPU", "WORLD"]

    creq = ChatCompletionRequest.model_validate(
        {"model": "m", "messages": [{"role": "user", "content": "hi there"}]}
    )
    got = []
    async for chunk in eng.generate(creq, Context()):
        if chunk.choices[0].delta.content:
            got.append(chunk.choices[0].delta.content)
    assert "".join(got).split() == ["HI", "THERE"]


def test_pystr_engine_rejects_bad_file(tmp_path):
    p = tmp_path / "no_gen.py"
    p.write_text("x = 1\n")
    with pytest.raises(ValueError, match="generate"):
        PythonStrEngine(str(p))


async def test_batch_file_writes_results(tmp_path):
    from dynamo_tpu.cli.main import _batch_file

    inp = tmp_path / "prompts.jsonl"
    inp.write_text(
        "\n".join(json.dumps({"text": f"prompt number {i}"}) for i in range(3))
    )
    out = tmp_path / "out.jsonl"
    await _batch_file(EchoEngineFull(), "echo", str(inp), str(out), None)
    lines = [json.loads(line) for line in out.read_text().splitlines()]
    assert len(lines) == 3
    by_idx = {r["index"]: r for r in lines}
    assert by_idx[1]["response"].strip() == "prompt number 1"
    assert by_idx[1]["ttft_ms"] >= 0 and by_idx[1]["chunks"] == 3


def test_stdin_pystr_subprocess(pystr_file, tmp_path):
    """Full CLI process: echo prompt | run --in stdin --out pystr:..."""
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.cli.main", "run",
         "--in", "stdin", "--out", f"pystr:{pystr_file}", "--static"],
        input="round trip", capture_output=True, text=True, env=env,
        cwd=str(tmp_path), timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.split() == ["ROUND", "TRIP"]
