"""Standalone KV router service e2e (reference: components/router/
src/main.rs — a shared KvRouter served over an endpoint that multiple
frontends consult): coordinator + two jax workers + the router service,
all real CLI subprocesses. Exercises both endpoints: ``schedule``
(decision-only) and ``generate`` (full proxy)."""

import asyncio
import time

from cli_harness import MODEL_DIR, CliFleet, free_port


def test_standalone_router_service():
    store_port = free_port()
    fleet = CliFleet()
    try:
        fleet.spawn("store", "--host", "127.0.0.1", "--port", str(store_port))
        time.sleep(2)
        common = ["--store-host", "127.0.0.1", "--store-port", str(store_port)]
        for _ in range(2):
            fleet.spawn(
                "run", "--in", "dyn://rsvc.backend.generate", "--out", "jax",
                "--model-path", MODEL_DIR, *common,
            )
        fleet.spawn(
            "router", "--namespace", "rsvc", "--component", "backend",
            "--block-size", "16", *common,
        )

        async def drive() -> None:
            from dynamo_tpu.protocols.common import (
                PreprocessedRequest,
                SamplingOptions,
                StopConditions,
            )
            from dynamo_tpu.runtime.config import RuntimeConfig
            from dynamo_tpu.runtime.engine import Context, collect
            from dynamo_tpu.runtime.push_router import PushRouter, RouterMode
            from dynamo_tpu.runtime.runtime import DistributedRuntime

            drt = await DistributedRuntime.create(config=RuntimeConfig(
                store_host="127.0.0.1", store_port=store_port,
                worker_host="127.0.0.1",
            ))
            try:
                ns = drt.namespace("rsvc")
                sched_client = await (
                    ns.component("kv_aware_router").endpoint("schedule").client()
                )
                await sched_client.wait_for_instances(60)
                gen_client = await (
                    ns.component("kv_aware_router").endpoint("generate").client()
                )
                await gen_client.wait_for_instances(60)

                # wait until the router sees both workers (engine jit
                # compile delays registration, minutes under CI load)
                backend = await (
                    ns.component("backend").endpoint("generate").client()
                )
                await backend.wait_for_instances(180)
                deadline = time.monotonic() + 180
                while (
                    len(backend.instance_ids()) < 2
                    and time.monotonic() < deadline
                ):
                    await asyncio.sleep(0.5)
                assert len(backend.instance_ids()) == 2

                router = PushRouter(sched_client, RouterMode.ROUND_ROBIN)
                prompt = list(range(3, 60))
                # decision endpoint: a valid live worker id
                items = await collect(
                    router.generate({"token_ids": prompt}, Context())
                )
                assert len(items) == 1
                first = items[0]
                assert first["worker_id"] in backend.instance_ids()
                assert first["total_blocks"] >= 3

                # proxy endpoint: a full generation streams through
                gen_router = PushRouter(gen_client, RouterMode.ROUND_ROBIN)
                req = PreprocessedRequest(
                    request_id="r1", token_ids=prompt,
                    sampling=SamplingOptions(use_greedy=True),
                    stop=StopConditions(max_tokens=5, ignore_eos=True),
                )
                out = await collect(gen_router.generate(req, Context()))
                toks = [t for item in out for t in (item["token_ids"] or [])]
                assert len(toks) == 5

                # after the proxied generation cached the prefix, the
                # decision for the same prompt sticks to that worker
                # with a positive hit rate
                deadline = time.monotonic() + 30
                hit = 0.0
                while time.monotonic() < deadline:
                    items = await collect(
                        router.generate({"token_ids": prompt}, Context())
                    )
                    hit = items[0]["prefix_hit_rate"]
                    if hit > 0:
                        break
                    await asyncio.sleep(1)
                assert hit > 0, "router index never saw the cached blocks"
            finally:
                await drt.shutdown()

        asyncio.run(drive())
        fleet.assert_alive()
    finally:
        fleet.teardown()
