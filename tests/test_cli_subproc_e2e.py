"""Subprocess engine adapter e2e (reference:
launch/dynamo-run/src/subprocess.rs — dynamo-run spawns the engine as a
child process that connects BACK over the endpoint plane, then serves
through it; vLLM/SGLang are embedded python scripts run this way).

Here the frontend runs ``--out "subproc:python -m dynamo_tpu.cli.main
run --in {endpoint} --out jax ..."`` — the placeholders are substituted
with a generated endpoint path and the coordinator address, the child
registers there, and the frontend proxies with local pre/post. Killing
the frontend must also reap the child (atexit)."""

import os
import time

from cli_harness import MODEL_DIR, CliFleet, complete, free_port, wait_http


def test_subprocess_engine_adapter_serves_http():
    store_port = free_port()
    http_port = free_port()
    fleet = CliFleet()
    try:
        fleet.spawn("store", "--host", "127.0.0.1", "--port", str(store_port))
        time.sleep(2)
        child_cmd = (
            "subproc:python -m dynamo_tpu.cli.main run "
            "--in {endpoint} --out jax --model-path {model_path} "
            "--store-host {store_host} --store-port {store_port}"
        )
        frontend = fleet.spawn(
            "run", "--in", "http", "--out", child_cmd,
            "--model-path", MODEL_DIR,
            "--store-host", "127.0.0.1", "--store-port", str(store_port),
            "--http-host", "127.0.0.1", "--http-port", str(http_port),
        )
        wait_http(
            f"http://127.0.0.1:{http_port}/v1/models",
            lambda b: b"tiny_llama_model" in b,
            timeout=240.0,
        )
        out = complete(http_port, "subprocess engines still serve", 8)
        # token COUNT is the robust assertion: the tiny model's greedy
        # tokens can legitimately detokenize to an empty string
        assert out["usage"]["completion_tokens"] == 8
        assert out["choices"][0]["finish_reason"] == "length"
        fleet.assert_alive()
        # the adapter owns the child: killing the frontend must reap it.
        # Assert on the CHILD's actual process (its cmdline carries the
        # generated internal.subproc endpoint) — the frontend's port
        # going dark says nothing about the child, which CliFleet never
        # spawned and so would leak silently past teardown.
        import signal as _signal

        def child_pids() -> list[int]:
            pids = []
            for pid in os.listdir("/proc"):
                if not pid.isdigit():
                    continue
                try:
                    with open(f"/proc/{pid}/cmdline", "rb") as f:
                        if b"internal.subproc" in f.read():
                            pids.append(int(pid))
                except OSError:
                    pass
            return pids

        assert child_pids(), "child engine process not found"
        frontend.send_signal(_signal.SIGTERM)
        frontend.wait(timeout=20)
        fleet.forget(frontend)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and child_pids():
            time.sleep(0.5)
        leaked = child_pids()
        assert not leaked, f"child engine leaked after SIGTERM: {leaked}"
    finally:
        fleet.teardown()
