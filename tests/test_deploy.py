"""Deploy tier: graph-deployment specs, the operator-lite reconciler,
and the api-store REST surface (reference: deploy/cloud/operator CRDs +
controllers, deploy/cloud/api-store)."""

import asyncio
import json

import pytest

from dynamo_tpu.deploy import ApiStore, GraphDeploymentSpec, Reconciler, ServiceSpec
from dynamo_tpu.deploy.spec import deployment_key
from dynamo_tpu.sdk.serving import CONTROL_SUBJECT, state_key
from dynamo_tpu.store.memory import MemoryStore


def test_spec_roundtrip_and_validation(tmp_path):
    spec = GraphDeploymentSpec(
        name="disagg",
        services={
            "backend": ServiceSpec(replicas=2, tpu_chips=4),
            "prefill": ServiceSpec(replicas=1, tpu_chips=4, config={"x": 1}),
        },
    )
    spec.validate()
    back = GraphDeploymentSpec.from_bytes(spec.to_bytes())
    assert back == spec
    d = spec.to_dict()
    assert d["kind"] == "DynamoGraphDeployment"
    assert d["spec"]["services"]["backend"]["resources"]["tpu"] == 4

    yaml_path = tmp_path / "spec.yaml"
    import yaml

    yaml_path.write_text(yaml.safe_dump(d))
    assert GraphDeploymentSpec.from_yaml_file(str(yaml_path)) == spec

    with pytest.raises(ValueError, match="no services"):
        GraphDeploymentSpec(name="empty").validate()
    with pytest.raises(ValueError, match="out of range"):
        GraphDeploymentSpec(
            name="big", services={"a": ServiceSpec(replicas=99999)}
        ).validate()
    with pytest.raises(ValueError, match="kind"):
        GraphDeploymentSpec.from_dict({"kind": "Pod"})


class FakeSupervisor:
    """Answers supervisor control commands + publishes replica state
    (stands in for sdk/serving.py Supervisor)."""

    def __init__(self, store: MemoryStore, namespace: str,
                 initial: dict[str, int]):
        self.store = store
        self.namespace = namespace
        self.counts = dict(initial)
        self.fail_ops = 0  # fail the next N commands
        self._task: asyncio.Task | None = None

    async def start(self):
        await self._publish()
        self._sub = await self.store.subscribe(
            f"{self.namespace}.{CONTROL_SUBJECT}"
        )
        self._task = asyncio.create_task(self._loop())

    async def _loop(self):
        async for _subj, data in self._sub:
            cmd = json.loads(data.decode())
            comp = cmd["component"]
            if self.fail_ops > 0:
                self.fail_ops -= 1
                reply = {"ok": False, "error": "injected"}
            else:
                delta = 1 if cmd["op"] == "add" else -1
                self.counts[comp] = max(0, self.counts.get(comp, 0) + delta)
                await self._publish()
                reply = {"ok": True}
            await self.store.publish(
                cmd["reply_to"], json.dumps(reply).encode()
            )

    async def _publish(self):
        state = {
            "components": {
                c: {"replicas": n, "names": []} for c, n in self.counts.items()
            }
        }
        await self.store.kv_put(
            state_key(self.namespace), json.dumps(state).encode()
        )

    async def stop(self):
        if self._task:
            self._task.cancel()
        await self._sub.close()


async def test_reconciler_converges_and_bounds_actions():
    store = MemoryStore()
    sup = FakeSupervisor(store, "ns", {"backend": 1, "prefill": 2})
    await sup.start()
    rec = Reconciler(store, "ns", max_actions_per_pass=2)
    await rec.apply(GraphDeploymentSpec(
        name="d1", namespace="ns",
        services={"backend": ServiceSpec(replicas=4),
                  "prefill": ServiceSpec(replicas=0)},
    ))
    # pass 1: budget 2 -> +backend, +backend, not converged
    r1 = (await rec.reconcile_once())[0]
    assert r1.actions == ["+backend", "+backend"] and not r1.converged
    # pass 2: +backend, then -prefill x2
    r2 = (await rec.reconcile_once())[0]
    assert r2.actions.count("+backend") == 1
    # remaining passes finish the scale-down, then go quiescent
    for _ in range(3):
        last = (await rec.reconcile_once())[0]
        if last.converged and not last.actions:
            break
    assert last.converged and not last.actions
    assert sup.counts == {"backend": 4, "prefill": 0}

    status = await rec.status()
    assert status["d1"]["backend"] == {"desired": 4, "actual": 4}

    # failed commands surface as errors, not hangs
    sup.fail_ops = 1
    await rec.apply(GraphDeploymentSpec(
        name="d1", namespace="ns",
        services={"backend": ServiceSpec(replicas=5),
                  "prefill": ServiceSpec(replicas=0)},
    ))
    r = (await rec.reconcile_once())[0]
    assert r.errors and not r.converged
    await sup.stop()
    await store.close()


async def test_apply_rejects_namespace_mismatch():
    store = MemoryStore()
    rec = Reconciler(store, "dynamo")
    with pytest.raises(ValueError, match="namespace"):
        await rec.apply(GraphDeploymentSpec(
            name="x", namespace="prod",
            services={"a": ServiceSpec(replicas=1)},
        ))
    await store.close()


async def test_reconciler_skips_bad_specs():
    store = MemoryStore()
    await store.kv_put(deployment_key("ns", "junk"), b"{not json")
    rec = Reconciler(store, "ns")
    assert await rec.list_deployments() == []
    await store.close()


async def test_api_store_crud_and_status():
    import aiohttp

    store = MemoryStore()
    sup = FakeSupervisor(store, "ns", {"backend": 1})
    await sup.start()
    rec = Reconciler(store, "ns")
    api = ApiStore(rec, host="127.0.0.1", port=0)
    await api.start()
    base = f"http://127.0.0.1:{api.port}/api/v1"
    doc = GraphDeploymentSpec(
        name="d2", namespace="ns",
        services={"backend": ServiceSpec(replicas=1)},
    ).to_dict()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.put(f"{base}/deployments/d2", json=doc) as r:
                assert r.status == 200
            async with s.put(f"{base}/deployments/other", json=doc) as r:
                assert r.status == 400  # name mismatch
            async with s.put(f"{base}/deployments/bad", json={"kind": "Pod"}) as r:
                assert r.status == 400
            async with s.get(f"{base}/deployments") as r:
                items = (await r.json())["items"]
                assert [i["metadata"]["name"] for i in items] == ["d2"]
            async with s.get(f"{base}/deployments/d2") as r:
                assert (await r.json())["metadata"]["name"] == "d2"
            async with s.get(f"{base}/status") as r:
                st = await r.json()
                assert st["d2"]["backend"] == {"desired": 1, "actual": 1}
            async with s.delete(f"{base}/deployments/d2") as r:
                assert (await r.json())["deleted"] == "d2"
            async with s.delete(f"{base}/deployments/d2") as r:
                assert r.status == 404
    finally:
        await api.stop()
        await sup.stop()
        await store.close()
