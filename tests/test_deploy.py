"""Deploy tier: graph-deployment specs, the operator-lite reconciler,
and the api-store REST surface (reference: deploy/cloud/operator CRDs +
controllers, deploy/cloud/api-store)."""

import asyncio
import json

import pytest

from dynamo_tpu.deploy import ApiStore, GraphDeploymentSpec, Reconciler, ServiceSpec
from dynamo_tpu.deploy.spec import deployment_key
from dynamo_tpu.sdk.serving import CONTROL_SUBJECT, state_key
from dynamo_tpu.store.memory import MemoryStore


def test_spec_roundtrip_and_validation(tmp_path):
    spec = GraphDeploymentSpec(
        name="disagg",
        services={
            "backend": ServiceSpec(replicas=2, tpu_chips=4),
            "prefill": ServiceSpec(replicas=1, tpu_chips=4, config={"x": 1}),
        },
    )
    spec.validate()
    back = GraphDeploymentSpec.from_bytes(spec.to_bytes())
    assert back == spec
    d = spec.to_dict()
    assert d["kind"] == "DynamoGraphDeployment"
    assert d["spec"]["services"]["backend"]["resources"]["tpu"] == 4

    yaml_path = tmp_path / "spec.yaml"
    import yaml

    yaml_path.write_text(yaml.safe_dump(d))
    assert GraphDeploymentSpec.from_yaml_file(str(yaml_path)) == spec

    with pytest.raises(ValueError, match="no services"):
        GraphDeploymentSpec(name="empty").validate()
    with pytest.raises(ValueError, match="out of range"):
        GraphDeploymentSpec(
            name="big", services={"a": ServiceSpec(replicas=99999)}
        ).validate()
    with pytest.raises(ValueError, match="kind"):
        GraphDeploymentSpec.from_dict({"kind": "Pod"})


class FakeSupervisor:
    """Answers supervisor control commands + publishes replica state
    (stands in for sdk/serving.py Supervisor)."""

    def __init__(self, store: MemoryStore, namespace: str,
                 initial: dict[str, int]):
        self.store = store
        self.namespace = namespace
        self.counts = dict(initial)
        self.fail_ops = 0  # fail the next N commands
        self._task: asyncio.Task | None = None

    async def start(self):
        await self._publish()
        self._sub = await self.store.subscribe(
            f"{self.namespace}.{CONTROL_SUBJECT}"
        )
        self._task = asyncio.create_task(self._loop())

    async def _loop(self):
        async for _subj, data in self._sub:
            cmd = json.loads(data.decode())
            comp = cmd["component"]
            if self.fail_ops > 0:
                self.fail_ops -= 1
                reply = {"ok": False, "error": "injected"}
            else:
                delta = 1 if cmd["op"] == "add" else -1
                self.counts[comp] = max(0, self.counts.get(comp, 0) + delta)
                await self._publish()
                reply = {"ok": True}
            await self.store.publish(
                cmd["reply_to"], json.dumps(reply).encode()
            )

    async def _publish(self):
        state = {
            "components": {
                c: {"replicas": n, "names": []} for c, n in self.counts.items()
            }
        }
        await self.store.kv_put(
            state_key(self.namespace), json.dumps(state).encode()
        )

    async def stop(self):
        if self._task:
            self._task.cancel()
        await self._sub.close()


async def test_reconciler_converges_and_bounds_actions():
    store = MemoryStore()
    sup = FakeSupervisor(store, "ns", {"backend": 1, "prefill": 2})
    await sup.start()
    rec = Reconciler(store, "ns", max_actions_per_pass=2)
    await rec.apply(GraphDeploymentSpec(
        name="d1", namespace="ns",
        services={"backend": ServiceSpec(replicas=4),
                  "prefill": ServiceSpec(replicas=0)},
    ))
    # pass 1: budget 2 -> +backend, +backend, not converged
    r1 = (await rec.reconcile_once())[0]
    assert r1.actions == ["+backend", "+backend"] and not r1.converged
    # pass 2: +backend, then -prefill x2
    r2 = (await rec.reconcile_once())[0]
    assert r2.actions.count("+backend") == 1
    # remaining passes finish the scale-down, then go quiescent
    for _ in range(3):
        last = (await rec.reconcile_once())[0]
        if last.converged and not last.actions:
            break
    assert last.converged and not last.actions
    assert sup.counts == {"backend": 4, "prefill": 0}

    status = await rec.status()
    assert status["d1"]["backend"] == {"desired": 4, "actual": 4}

    # failed commands surface as errors, not hangs
    sup.fail_ops = 1
    await rec.apply(GraphDeploymentSpec(
        name="d1", namespace="ns",
        services={"backend": ServiceSpec(replicas=5),
                  "prefill": ServiceSpec(replicas=0)},
    ))
    r = (await rec.reconcile_once())[0]
    assert r.errors and not r.converged
    await sup.stop()
    await store.close()


async def test_apply_rejects_namespace_mismatch():
    store = MemoryStore()
    rec = Reconciler(store, "dynamo")
    with pytest.raises(ValueError, match="namespace"):
        await rec.apply(GraphDeploymentSpec(
            name="x", namespace="prod",
            services={"a": ServiceSpec(replicas=1)},
        ))
    await store.close()


async def test_reconciler_skips_bad_specs():
    store = MemoryStore()
    await store.kv_put(deployment_key("ns", "junk"), b"{not json")
    rec = Reconciler(store, "ns")
    assert await rec.list_deployments() == []
    await store.close()


async def test_api_store_crud_and_status():
    import aiohttp

    store = MemoryStore()
    sup = FakeSupervisor(store, "ns", {"backend": 1})
    await sup.start()
    rec = Reconciler(store, "ns")
    api = ApiStore(rec, host="127.0.0.1", port=0)
    await api.start()
    base = f"http://127.0.0.1:{api.port}/api/v1"
    doc = GraphDeploymentSpec(
        name="d2", namespace="ns",
        services={"backend": ServiceSpec(replicas=1)},
    ).to_dict()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.put(f"{base}/deployments/d2", json=doc) as r:
                assert r.status == 200
            async with s.put(f"{base}/deployments/other", json=doc) as r:
                assert r.status == 400  # name mismatch
            async with s.put(f"{base}/deployments/bad", json={"kind": "Pod"}) as r:
                assert r.status == 400
            async with s.get(f"{base}/deployments") as r:
                items = (await r.json())["items"]
                assert [i["metadata"]["name"] for i in items] == ["d2"]
            async with s.get(f"{base}/deployments/d2") as r:
                assert (await r.json())["metadata"]["name"] == "d2"
            async with s.get(f"{base}/status") as r:
                st = await r.json()
                assert st["d2"]["backend"] == {"desired": 1, "actual": 1}
            async with s.delete(f"{base}/deployments/d2") as r:
                assert (await r.json())["deleted"] == "d2"
            async with s.delete(f"{base}/deployments/d2") as r:
                assert r.status == 404
    finally:
        await api.stop()
        await sup.stop()
        await store.close()


def test_graph_manifests_render_and_validate(tmp_path):
    """GraphDeploymentSpec -> K8s Deployments/Services/ConfigMap/CRD:
    every document passes the kubectl-client-side structural checks and
    round-trips through YAML (reference: the operator's rendering,
    dynamographdeployment_controller.go)."""
    import yaml

    from dynamo_tpu.deploy.manifests import (
        crd_manifest,
        graph_manifests,
        render_yaml,
        validate_k8s_doc,
    )

    spec = GraphDeploymentSpec(
        name="disagg", namespace="prod",
        services={
            "frontend": ServiceSpec(replicas=2, config={"role": "frontend"}),
            "backend": ServiceSpec(
                replicas=3, tpu_chips=4,
                config={"out": "jax", "model_path": "/models/llama",
                        "tpu_topology": "2x2"},
            ),
        },
    )
    docs = [crd_manifest()] + graph_manifests(spec, image="reg/dyn:1")
    for d in docs:
        validate_k8s_doc(d)
    # YAML round trip
    parsed = list(yaml.safe_load_all(render_yaml(docs[1:])))
    assert len(parsed) == len(docs) - 1
    by_kind_name = {(d["kind"], d["metadata"]["name"]): d for d in parsed}
    # CR itself + store pair + configmap + 2 deployments + 2 services
    backend = by_kind_name[("Deployment", "disagg-backend")]
    pod = backend["spec"]["template"]["spec"]
    assert backend["spec"]["replicas"] == 3
    assert pod["containers"][0]["resources"]["limits"]["google.com/tpu"] == 4
    assert pod["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "2x2"
    assert "--model-path" in pod["containers"][0]["command"]
    frontend = by_kind_name[("Deployment", "disagg-frontend")]
    cmd = frontend["spec"]["template"]["spec"]["containers"][0]["command"]
    assert "--in" in cmd and "http" in cmd
    assert ("Service", "disagg-frontend") in by_kind_name
    assert ("Service", "disagg-backend") not in by_kind_name  # no port
    assert ("Deployment", "disagg-store") in by_kind_name
    cm = by_kind_name[("ConfigMap", "disagg-config")]
    assert json.loads(cm["data"]["backend.json"])["out"] == "jax"
    # CRD names/schema shape
    crd = crd_manifest()
    assert crd["spec"]["names"]["kind"] == "DynamoGraphDeployment"
    v = crd["spec"]["versions"][0]
    assert v["schema"]["openAPIV3Schema"]["properties"]["spec"]

    # the CLI path: deploy manifests -o FILE
    import subprocess
    import sys

    spec_path = tmp_path / "g.yaml"
    import yaml as _y

    spec_path.write_text(_y.safe_dump(spec.to_dict()))
    out_path = tmp_path / "all.yaml"
    r = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.cli.main", "deploy", "manifests",
         str(spec_path), "--image", "reg/dyn:1", "--include-crd",
         "-o", str(out_path)],
        capture_output=True, text=True,
        env={**__import__("os").environ, "PYTHONPATH": __import__("os").path.dirname(
            __import__("os").path.dirname(__import__("os").path.abspath(__file__)))},
    )
    assert r.returncode == 0, r.stderr
    rendered = list(yaml.safe_load_all(out_path.read_text()))
    assert rendered[0]["kind"] == "CustomResourceDefinition"


async def test_api_store_persists_to_disk(tmp_path):
    """Applied specs survive a coordinator (store) restart via the
    api-store's state dir."""
    import aiohttp

    state = str(tmp_path / "state")
    doc = GraphDeploymentSpec(
        name="durable", namespace="ns",
        services={"backend": ServiceSpec(replicas=2)},
    ).to_dict()

    store = MemoryStore()
    api = ApiStore(Reconciler(store, "ns"), host="127.0.0.1", port=0,
                   state_dir=state)
    await api.start()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.put(
                f"http://127.0.0.1:{api.port}/api/v1/deployments/durable",
                json=doc,
            ) as r:
                assert r.status == 200
    finally:
        await api.stop()
        await store.close()

    # fresh store (simulated restart): the spec is restored on start
    store2 = MemoryStore()
    rec2 = Reconciler(store2, "ns")
    api2 = ApiStore(rec2, host="127.0.0.1", port=0, state_dir=state)
    await api2.start()
    try:
        specs = await rec2.list_deployments()
        assert [s.name for s in specs] == ["durable"]
        assert specs[0].services["backend"].replicas == 2
        # delete removes the disk mirror too
        async with aiohttp.ClientSession() as s:
            async with s.delete(
                f"http://127.0.0.1:{api2.port}/api/v1/deployments/durable"
            ) as r:
                assert r.status == 200
        import os

        assert not os.listdir(state)
    finally:
        await api2.stop()
        await store2.close()


async def test_reconciler_absolute_backend():
    """A set_replicas-style backend (kubectl mode) converges in one
    action per component."""
    store = MemoryStore()

    class FakeK8s:
        def __init__(self):
            self.replicas_map = {"backend": 1, "frontend": 0}
            self.calls = []

        async def replicas(self, component):
            return self.replicas_map.get(component)

        async def set_replicas(self, component, n):
            self.calls.append((component, n))
            self.replicas_map[component] = n
            return True

    fake = FakeK8s()
    rec = Reconciler(store, "ns", connector_factory=lambda spec: fake)
    await rec.apply(GraphDeploymentSpec(
        name="k", namespace="ns",
        services={"backend": ServiceSpec(replicas=4),
                  "frontend": ServiceSpec(replicas=2)},
    ))
    results = await rec.reconcile_once()
    assert results[0].converged
    assert sorted(fake.calls) == [("backend", 4), ("frontend", 2)]
    assert fake.replicas_map == {"backend": 4, "frontend": 2}
    # converged: second pass is a no-op
    fake.calls.clear()
    await rec.reconcile_once()
    assert fake.calls == []
    await store.close()


async def test_kubectl_connector_shell_contract(tmp_path):
    """KubectlConnector drives the manifest-generated deployment names
    through kubectl's CLI surface (fake kubectl records argv)."""
    import os
    import stat

    from dynamo_tpu.deploy.operator import KubectlConnector

    logf = tmp_path / "calls.log"
    fake = tmp_path / "kubectl"
    fake.write_text(
        "#!/bin/sh\n"
        f"printf '%s\\n' \"$*\" >> {logf}\n"
        "case \"$*\" in\n"
        "  *jsonpath*) printf 3;;\n"
        "esac\n"
    )
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)

    conn = KubectlConnector("disagg", k8s_namespace="prod",
                            kubectl=str(fake))
    assert await conn.replicas("backend") == 3
    assert await conn.set_replicas("backend", 5)
    calls = logf.read_text().splitlines()
    assert calls[0].startswith("-n prod get deployment/disagg-backend")
    assert calls[1] == "-n prod scale deployment/disagg-backend --replicas=5"


async def test_mirror_only_touches_owned_files(tmp_path):
    """The state mirror must never delete files it didn't create —
    unrelated JSON and another namespace's mirror survive a sync."""
    import os

    state = str(tmp_path)
    (tmp_path / "unrelated.json").write_text("{}")
    store = MemoryStore()
    other = Reconciler(store, "other-ns", state_dir=state)
    await other.apply(GraphDeploymentSpec(
        name="theirs", namespace="other-ns",
        services={"backend": ServiceSpec(replicas=1)},
    ))
    rec = Reconciler(store, "ns", state_dir=state)
    await rec.apply(GraphDeploymentSpec(
        name="mine", namespace="ns",
        services={"backend": ServiceSpec(replicas=1)},
    ))
    # both reconcilers sync with zero desired overlap changes
    rec._sync_mirror(await rec.list_deployments())
    other._sync_mirror(await other.list_deployments())
    names = sorted(os.listdir(state))
    assert "unrelated.json" in names
    assert any("other-ns" in n and "theirs" in n for n in names)
    assert any(n.startswith("dgd.ns.") and "mine" in n for n in names)
    # delete propagates only within the owning namespace
    await rec.delete("mine")
    names = sorted(os.listdir(state))
    assert not any(n.startswith("dgd.ns.") for n in names)
    assert any("theirs" in n for n in names)
    await store.close()


async def test_reconciler_watch_triggers_immediate_reconcile():
    """The control loop is EVENT-driven (reference: the controller-
    runtime operator watches its CRDs): applying a spec must reconcile
    promptly even with a long periodic-resync interval."""
    store = MemoryStore()
    sup = FakeSupervisor(store, "ns", {"backend": 1})
    await sup.start()
    rec = Reconciler(store, "ns", interval_s=60.0)
    stop = asyncio.Event()
    task = asyncio.create_task(rec.run(stop))
    try:
        await asyncio.sleep(0.3)  # loop idle, waiting on watch/interval
        await rec.apply(GraphDeploymentSpec(
            name="d1", namespace="ns",
            services={"backend": ServiceSpec(replicas=3)},
        ))
        deadline = asyncio.get_running_loop().time() + 10.0
        while asyncio.get_running_loop().time() < deadline:
            if sup.counts.get("backend") == 3:
                break
            await asyncio.sleep(0.1)
        # far faster than the 60s resync: the watch drove it
        assert sup.counts.get("backend") == 3
    finally:
        stop.set()
        await asyncio.wait_for(task, 10)
        await sup.stop()
        await store.close()


def test_split_json_stream_framing():
    """kubectl --watch emits concatenated pretty-printed JSON docs; the
    splitter must frame them without newline assumptions and keep
    braces inside strings out of the count."""
    from dynamo_tpu.deploy.operator import split_json_stream

    a = json.dumps({"type": "ADDED", "object": {"x": "br{ace\"}"}}, indent=2)
    b = json.dumps({"type": "DELETED", "object": {"y": 1}})
    docs, tail = split_json_stream(a + "\n" + b + '{"partial"')
    assert [json.loads(d)["type"] for d in docs] == ["ADDED", "DELETED"]
    assert tail == '{"partial"'
    docs2, tail2 = split_json_stream(tail + ': 1}')
    assert json.loads(docs2[0]) == {"partial": 1} and tail2 == ""


def _cr_json(name: str, replicas: int) -> dict:
    return {
        "apiVersion": "dynamo-tpu.dev/v1alpha1",
        "kind": "DynamoGraphDeployment",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"services": {"backend": {"replicas": replicas,
                                          "resources": {"tpu": 1}}}},
    }


async def test_cr_watcher_kubectl_drives_reconcile(tmp_path):
    """envtest-style in-cluster flow through a FAKE kubectl: an applied
    CR (kubectl get) lands in the store, the reconciler converges
    replicas to the CR's spec, the status patch goes back through
    kubectl --subresource=status, and watch events (MODIFIED/DELETED)
    mutate desired state."""
    import os
    import stat

    from dynamo_tpu.deploy.operator import CrWatcher

    cr_list = {"apiVersion": "v1", "kind": "List",
               "items": [_cr_json("web", 3)]}
    patch_log = tmp_path / "patches.log"
    fake = tmp_path / "kubectl"
    fake.write_text(
        "#!/bin/sh\n"
        "case \"$*\" in\n"
        "  *patch*) echo \"$@\" >> %s; exit 0 ;;\n"
        "  *'-o json'*) cat %s ;;\n"
        "esac\n" % (patch_log, tmp_path / "crs.json")
    )
    (tmp_path / "crs.json").write_text(json.dumps(cr_list))
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)

    store = MemoryStore()

    class FakeK8s:
        def __init__(self):
            self.replicas_map = {"backend": 0}

        async def replicas(self, component):
            return self.replicas_map.get(component)

        async def set_replicas(self, component, n):
            self.replicas_map[component] = n
            return True

    conn = FakeK8s()
    rec = Reconciler(store, "dynamo", connector_factory=lambda spec: conn)
    watcher = CrWatcher(rec, kubectl=str(fake))
    # 1) kubectl apply'd CR -> store -> reconcile converges replicas
    assert await watcher.sync_once() == 1
    results = await rec.reconcile_once()
    assert conn.replicas_map == {"backend": 3}
    assert results[0].converged
    # 2) status written back to the CR through the status subresource
    await watcher.write_status(results)
    logged = patch_log.read_text()
    assert "--subresource=status" in logged
    assert "dynamographdeployments/web" in logged
    assert '\\"state\\": \\"successful\\"' in logged or '"state": "successful"' in logged
    # 3) a MODIFIED watch event re-scales
    await watcher._consume_event(json.dumps(
        {"type": "MODIFIED", "object": _cr_json("web", 5)}
    ))
    await rec.reconcile_once()
    assert conn.replicas_map == {"backend": 5}
    # 4) DELETED removes the deployment from desired state
    await watcher._consume_event(json.dumps(
        {"type": "DELETED", "object": _cr_json("web", 5)}
    ))
    assert await rec.list_deployments() == []
    # 5) a store spec with no backing CR is removed on full resync
    await rec.apply(GraphDeploymentSpec(
        name="orphan", namespace="dynamo",
        services={"backend": ServiceSpec(replicas=1)},
    ))
    await watcher.sync_once()
    names = [s.name for s in await rec.list_deployments()]
    assert names == ["web"]
    await store.close()
