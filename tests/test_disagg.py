"""Disaggregated prefill/decode tests.

Ladder (reference test strategy, SURVEY.md §4): protocol round-trips →
queue/router logic over the in-memory store → transfer plane round-trip
→ the flagship single-process two-worker simulation: a decode engine and
a prefill engine exchange KV blocks through the real queue + transfer
server, and the decode output matches a purely-local run (≈ the
reference's two-KvBlockManager blockset exchange, block_manager.rs:232).
"""

import asyncio
import os

import numpy as np
import pytest

from dynamo_tpu.disagg.prefill_queue import PrefillQueue
from dynamo_tpu.disagg.protocols import (
    DisaggConfig,
    RemotePrefillRequest,
    conf_key,
)
from dynamo_tpu.disagg.router import DisaggRouter
from dynamo_tpu.disagg.transfer import TransferClient, TransferMetadata, TransferServer
from dynamo_tpu.kvbm import BlockLayout
from dynamo_tpu.store.memory import MemoryStore

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")


def test_protocol_roundtrips():
    req = RemotePrefillRequest("r1", [1, 2, 3], 8, "ns/transfer/ab")
    assert RemotePrefillRequest.from_bytes(req.to_bytes()) == req
    conf = DisaggConfig(enabled=True, max_local_prefill_length=128)
    assert DisaggConfig.from_bytes(conf.to_bytes()) == conf


async def test_prefill_queue_roundtrip():
    store = MemoryStore()
    q = PrefillQueue(store, "ns")
    req = RemotePrefillRequest("r1", list(range(20)), 8, "k")
    await q.enqueue(req)
    assert await q.depth() == 1
    got = await q.dequeue(timeout_s=0.2)
    assert got is not None
    msg_id, back = got
    assert back == req
    assert await q.ack(msg_id)
    assert await q.dequeue(timeout_s=0.05) is None
    await store.close()


async def test_disagg_router_decision_and_hot_reload():
    store = MemoryStore()
    router = await DisaggRouter.create(
        store, "ns",
        default=DisaggConfig(enabled=True, max_local_prefill_length=100,
                             max_prefill_queue_size=4),
    )
    assert router.should_prefill_remote(prefill_len=101, queue_depth=0)
    assert not router.should_prefill_remote(prefill_len=100, queue_depth=0)
    assert not router.should_prefill_remote(prefill_len=500, queue_depth=4)
    # hot reload via the store watch
    await store.kv_put(
        conf_key("ns"),
        DisaggConfig(enabled=True, max_local_prefill_length=10).to_bytes(),
    )
    for _ in range(50):
        if router.conf.max_local_prefill_length == 10:
            break
        await asyncio.sleep(0.02)
    assert router.conf.max_local_prefill_length == 10
    assert router.should_prefill_remote(prefill_len=11, queue_depth=0)
    await router.close()
    await store.close()


async def test_transfer_roundtrip():
    layout = BlockLayout(num_layers=2, block_size=4, num_kv_heads=2, head_dim=8)
    delivered = {}

    async def deliver(hashes, packed):
        delivered["hashes"] = hashes
        delivered["packed"] = packed.copy()

    server = TransferServer(deliver, layout)
    await server.start()
    store = MemoryStore()
    key = await server.register(store, "ns", 0xAB, layout, lease_id=0)
    meta = await TransferClient.fetch_metadata(store, key)
    assert meta is not None and meta.port == server.port
    rng = np.random.default_rng(0)
    packed = rng.standard_normal((3, *layout.packed_shape)).astype(layout.np_dtype)
    done = server.completion_event("req-1")
    ok = await TransferClient.put(meta, "req-1", [11, 22, 33], packed)
    assert ok and done.is_set()
    assert delivered["hashes"] == [11, 22, 33]
    np.testing.assert_array_equal(delivered["packed"], packed)
    await server.close()
    await store.close()


async def test_transfer_rejects_bad_shape_and_late_delivery_no_leak():
    layout = BlockLayout(num_layers=2, block_size=4, num_kv_heads=2, head_dim=8)

    async def deliver(hashes, packed):
        pass

    server = TransferServer(deliver, layout)
    await server.start()
    meta = TransferMetadata("127.0.0.1", server.port, 1, layout.to_json())
    # wrong shape (claims 2 blocks of the wrong geometry) -> rejected
    bad = np.zeros((2, 1, 1, 1, 1, 1), layout.np_dtype)
    ok = await TransferClient.put(meta, "bad", [1, 2], bad, timeout_s=2)
    assert not ok
    # late delivery after the waiter discarded: must not re-create events
    good = np.zeros((1, *layout.packed_shape), layout.np_dtype)
    server.completion_event("late")
    server.discard_completion("late")
    ok = await TransferClient.put(meta, "late", [5], good, timeout_s=2)
    assert ok
    assert "late" not in server._done
    await server.close()


# ---------------------------------------------------------------------------
# Two-worker disaggregation simulation (single process, CPU-JAX)
# ---------------------------------------------------------------------------


async def _launch_engine(**kw):
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine

    cfg = dict(
        model_path=MODEL_DIR, model_name="tiny", random_weights=True,
        num_blocks=64, block_size=8, max_batch_size=4,
        prefill_chunk_size=64, max_model_len=256,
    )
    cfg.update(kw)
    return await JaxEngine.launch(EngineConfig(**cfg))


async def test_disagg_two_worker_end_to_end():
    from dynamo_tpu.disagg.worker import DisaggDecodeEngine, run_prefill_worker
    from tests.test_engine import _generate

    store = MemoryStore()
    prompt = list(range(1, 60))  # 7 full blocks + tail

    # oracle: plain local engine
    local = await _launch_engine()
    toks_local, _ = await _generate(local, prompt, request_id="oracle")
    await local.shutdown()

    decode = await _launch_engine(host_kv_blocks=64)
    prefill = await _launch_engine()
    shutdown = asyncio.Event()
    worker_task = asyncio.create_task(
        run_prefill_worker(prefill, store, "ns", shutdown, poll_s=0.05)
    )
    try:
        disagg = await DisaggDecodeEngine.create(
            decode, store, "ns", worker_id=0xD, lease_id=0,
            conf=DisaggConfig(
                enabled=True,
                max_local_prefill_length=16,  # force the remote path
                max_prefill_queue_size=8,
                transfer_timeout_s=30.0,
            ),
        )
        from dynamo_tpu.protocols.common import (
            PreprocessedRequest,
            SamplingOptions,
            StopConditions,
        )
        from dynamo_tpu.runtime.engine import Context

        req = PreprocessedRequest(
            request_id="disagg-1", token_ids=prompt,
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=8),
        )
        toks = []
        async for item in disagg.generate(req, Context()):
            toks.extend(item.token_ids)
        assert disagg.remote_prefills == 1
        assert disagg.local_fallbacks == 0
        assert toks == toks_local  # same greedy continuation
        # KV actually traveled: decode onboarded blocks it never prefilled
        assert decode.kvbm is not None
        assert decode.kvbm.stats.onboarded_blocks >= 7
        # short prompt goes local (below threshold)
        req2 = PreprocessedRequest(
            request_id="short", token_ids=list(range(1, 10)),
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=2),
        )
        async for _ in disagg.generate(req2, Context()):
            pass
        assert disagg.remote_prefills == 1  # unchanged
        await disagg.close()
    finally:
        shutdown.set()
        await worker_task
        await decode.shutdown()
        await prefill.shutdown()
        await store.close()


async def test_disagg_transfer_timeout_falls_back_local():
    """No prefill worker: the decode worker must fall back to local
    prefill after the timeout and still serve the request."""
    from dynamo_tpu.disagg.worker import DisaggDecodeEngine
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    store = MemoryStore()
    decode = await _launch_engine(host_kv_blocks=32)
    try:
        disagg = await DisaggDecodeEngine.create(
            decode, store, "ns2", worker_id=1, lease_id=0,
            conf=DisaggConfig(
                enabled=True, max_local_prefill_length=8,
                max_prefill_queue_size=8, transfer_timeout_s=0.3,
            ),
        )
        req = PreprocessedRequest(
            request_id="fallback", token_ids=list(range(1, 40)),
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=3),
        )
        toks = []
        async for item in disagg.generate(req, Context()):
            toks.extend(item.token_ids)
        assert len(toks) == 3
        assert disagg.remote_prefills == 1
        assert disagg.local_fallbacks == 1
        await disagg.close()
    finally:
        await decode.shutdown()
        await store.close()
