"""Graceful drain & rolling restarts (ISSUE 20, docs/robustness.md
"Graceful drain & rolling restarts"): the discovery-level DRAINING
flag and Client filtering, the KV scheduler's drain-aware scoring, the
engine's migrate-eligibility mirror, the fabric's hot-prefix handoff,
the DrainCoordinator state machine, the worker-control subject
round-trip, the planner's rolling_restart, and the sim's kill-vs-drain
A/B that bench.py --chaos gates. The live SIGTERM-mid-stream proof is
tests/test_cli_drain_e2e.py; the fault-point seams are covered in
tests/test_faults.py."""

import asyncio
import json
from types import SimpleNamespace

import msgpack
import pytest

from dynamo_tpu.runtime.component import Client, Instance, _decode_instance
from dynamo_tpu.runtime.drain import (
    DEFAULT_DRAIN_TIMEOUT_S,
    DrainCoordinator,
    DrainResult,
    drain_timeout_from_env,
    request_drain,
    serve_drain_control,
    worker_control_subject,
)


def _inst(iid: int, draining: bool = False) -> Instance:
    return Instance(
        instance_id=iid, host="127.0.0.1", port=9000 + iid,
        namespace="ns", component="backend", endpoint="generate",
        draining=draining,
    )


# ---------------------------------------------------------------------------
# Discovery: the DRAINING flag through decode + Client filtering
# ---------------------------------------------------------------------------


def test_decode_instance_reads_draining_flag():
    key = "instances/ns/backend/generate:a1"
    plain = msgpack.packb({"host": "h", "port": 1}, use_bin_type=True)
    flagged = msgpack.packb(
        {"host": "h", "port": 1, "draining": True}, use_bin_type=True
    )
    assert _decode_instance(key, plain).draining is False
    assert _decode_instance(key, flagged).draining is True
    # the flag rides the SAME key: a re-put flips the existing entry
    assert _decode_instance(key, flagged).instance_id == 0xA1


def test_client_excludes_draining_from_fresh_placement():
    """The satellite bugfix in one seam: BOTH routers and the resume
    path pick from instance_ids(), so filtering here keeps resumes off
    workers that are themselves on the way out."""
    c = Client(endpoint=None, static_instance=_inst(1))
    c.instances[2] = _inst(2, draining=True)
    c.instances[3] = _inst(3)
    assert c.instance_ids() == [1, 3]
    assert c.instance_ids(include_draining=True) == [1, 2, 3]
    assert c.draining_ids() == {2}


def test_client_two_draining_workers_leave_only_third():
    """Regression (ISSUE 20): with two of three workers draining, fresh
    placement AND resumes must land on the third — previously a resume
    could re-dial a draining worker and bounce."""
    c = Client(endpoint=None, static_instance=_inst(1, draining=True))
    c.instances[2] = _inst(2, draining=True)
    c.instances[3] = _inst(3)
    assert c.instance_ids() == [3]


async def test_client_wait_event_tracks_routable_instances_only():
    """wait_for_instances must not unblock onto an all-draining fleet."""
    c = Client(endpoint=None, static_instance=_inst(1))
    c.instances[1] = _inst(1, draining=True)
    c._refresh_event()
    assert not c._instances_event.is_set()
    with pytest.raises(asyncio.TimeoutError):
        await c.wait_for_instances(timeout_s=0.05)
    c.instances[2] = _inst(2)
    c._refresh_event()
    assert await c.wait_for_instances(timeout_s=1.0) == [2]


# ---------------------------------------------------------------------------
# KV scheduler: drain-aware candidate filtering + overlap reclassification
# ---------------------------------------------------------------------------


def _scheduler(fleet_catalog=None):
    from dynamo_tpu.kv_router.indexer import KvIndexer
    from dynamo_tpu.kv_router.scheduler import KvMetricsAggregator, KvScheduler

    indexer = KvIndexer(block_size=4)
    captured = {}

    def selector(overlaps, metrics, candidates):
        captured["scores"] = dict(overlaps.scores)
        captured["candidates"] = list(candidates)
        return sorted(candidates)[0]

    sched = KvScheduler(
        indexer, KvMetricsAggregator(), selector=selector,
        fleet_catalog=fleet_catalog,
    )
    return sched, indexer, captured


def test_scheduler_excludes_draining_candidates():
    sched, _, captured = _scheduler()
    d = sched.schedule(list(range(8)), [1, 2, 3], draining={1, 2})
    assert captured["candidates"] == [3]
    assert d.worker_id == 3


def test_scheduler_all_draining_falls_back_to_full_set():
    """Defensive: if filtering would empty the candidate set, serve
    SOMEWHERE rather than erroring — the draining worker still answers
    in-flight dials for its drain window."""
    sched, _, captured = _scheduler()
    sched.schedule(list(range(8)), [1, 2], draining={1, 2})
    assert captured["candidates"] == [1, 2]


def test_scheduler_counts_draining_overlap_as_fleet():
    """A draining worker's indexed prefix doesn't vanish: the drain
    retiers it into the shared bucket, so every surviving candidate
    scores it at fleet_hit_weight — not local weight, and not zero."""
    from tests.test_kv_router import _seq_hashes, _stored

    sched, indexer, captured = _scheduler()
    prompt = list(range(32))  # 8 blocks
    indexer.apply(_stored(1, _seq_hashes(prompt)[:6]))  # draining holds 6
    sched.schedule(prompt, [1, 2, 3], draining={1})
    w = sched.fleet_hit_weight
    assert captured["candidates"] == [2, 3]
    assert captured["scores"][2] == pytest.approx(w * 6)
    assert captured["scores"][3] == pytest.approx(w * 6)


# ---------------------------------------------------------------------------
# Engine: migrate-eligibility mirror of migration.resumable()
# ---------------------------------------------------------------------------


def test_engine_drain_migratable_mirrors_resume_eligibility():
    from dynamo_tpu.engine.engine import JaxEngine

    ok = SimpleNamespace(migration=None, guided=None, sampling=None)
    opted_out = SimpleNamespace(migration=False, guided=None, sampling=None)
    guided = SimpleNamespace(migration=None, guided=object(), sampling=None)
    penalties = SimpleNamespace(
        migration=None, guided=None,
        sampling=SimpleNamespace(needs_penalties=True),
    )
    plain_sampling = SimpleNamespace(
        migration=None, guided=None,
        sampling=SimpleNamespace(needs_penalties=False),
    )
    mig = JaxEngine._drain_migratable
    assert mig(ok) and mig(plain_sampling)
    assert not mig(opted_out)
    assert not mig(guided)
    assert not mig(penalties)


# ---------------------------------------------------------------------------
# Fabric: on_drain pushes hot G2 prefixes into the shared bucket
# ---------------------------------------------------------------------------


def test_fabric_on_drain_demotes_hot_blocks_to_shared(tmp_path):
    from dynamo_tpu.kvbm import DictCatalogBackend
    from dynamo_tpu.kvbm.fabric import TIER_SHARED
    from dynamo_tpu.kvbm.remote import DictObjectStore
    from tests.test_kv_fabric import (
        FakeDevice, TickClock, _commit, _fabric, _manager,
    )

    clock = TickClock()
    dev = FakeDevice(16)
    objects = DictObjectStore()
    m = _manager(dev, host_blocks=8, tmp=tmp_path, objects=objects,
                 clock=clock)
    backend = DictCatalogBackend()
    fab = _fabric(backend, worker_id=1, clock=clock)
    fab.attach(m)
    try:
        _commit(dev, m, [201, 202, 203])
        # 201/202 are hot (>= hot_min_touches); 203 is cold
        fab._resident[201].touches = 2
        fab._resident[202].touches = 3
        demoted = fab.on_drain()
        assert demoted == 2
        view = backend.snapshot()
        assert view[201][1]["tier"] == TIER_SHARED
        assert view[202][1]["tier"] == TIER_SHARED
        # the cold block keeps its host-tier claim: peer-fetchable for
        # the drain window, gone with the lease after exit
        assert view[203][1]["tier"] != TIER_SHARED
        assert not m.host.contains(201) and not m.host.contains(202)
        assert m.remote.contains(201) and m.remote.contains(202)
    finally:
        fab.close()


def test_fabric_on_drain_respects_max_blocks_and_needs_remote(tmp_path):
    from dynamo_tpu.kvbm import DictCatalogBackend
    from dynamo_tpu.kvbm.remote import DictObjectStore
    from tests.test_kv_fabric import (
        FakeDevice, TickClock, _commit, _fabric, _manager,
    )

    clock = TickClock()
    dev = FakeDevice(16)
    m = _manager(dev, host_blocks=8, tmp=tmp_path,
                 objects=DictObjectStore(), clock=clock)
    fab = _fabric(DictCatalogBackend(), worker_id=1, clock=clock)
    fab.attach(m)
    try:
        _commit(dev, m, [301, 302, 303])
        for h in (301, 302, 303):
            fab._resident[h].touches = 5
        assert fab.on_drain(max_blocks=1) == 1  # deadline-bounded sweep
    finally:
        fab.close()

    # no shared bucket attached: nothing to hand off, clean no-op
    dev2 = FakeDevice(16)
    m2 = _manager(dev2, host_blocks=8)
    fab2 = _fabric(DictCatalogBackend(), worker_id=2)
    fab2.attach(m2)
    try:
        _commit(dev2, m2, [401])
        fab2._resident[401].touches = 5
        assert fab2.on_drain() == 0
    finally:
        fab2.close()


# ---------------------------------------------------------------------------
# DrainCoordinator state machine (fault-seam paths live in test_faults.py)
# ---------------------------------------------------------------------------


class _Store:
    def __init__(self):
        self.deleted = []

    async def kv_delete(self, key):
        self.deleted.append(key)
        return True


class _Endpoint:
    def __init__(self):
        self.drained = []

    async def set_draining(self, instance):
        self.drained.append(instance)


class _Component:
    def __init__(self, instances):
        self._instances = instances

    async def list_instances(self):
        return self._instances


class _Engine:
    def __init__(self, active=0, fabric=None, migrate_on_drain=True):
        self._active = active
        self.drain_begun = False
        self.drain_migrated = 0
        self._migrate = migrate_on_drain
        self.kvbm = (
            SimpleNamespace(fabric=fabric) if fabric is not None else None
        )

    def active_streams(self):
        return self._active

    def begin_drain(self):
        self.drain_begun = True
        if self._migrate:
            self.drain_migrated += self._active
            self._active = 0

    async def acall_on_thread(self, fn, *args):
        return fn(*args)


def _coord(engine, peers="healthy", **kw):
    me = _inst(0xAA)
    if peers == "healthy":
        instances = [me, _inst(0xBB)]
    elif peers == "draining":
        instances = [me, _inst(0xBB, draining=True)]
    else:
        instances = [me]
    kw.setdefault("timeout_s", 0.2)
    return DrainCoordinator(
        SimpleNamespace(store=_Store()), _Component(instances),
        _Endpoint(), me, engine=engine, poll_interval_s=0.01, **kw,
    )


async def test_coordinator_completed_path_publishes_and_deregisters():
    eng = _Engine(active=3)
    coord = _coord(eng)
    res = await coord.drain()
    assert res == DrainResult(
        result="completed", streams_migrated=3,
        elapsed_s=res.elapsed_s, fabric_blocks_shared=0,
    )
    assert eng.drain_begun
    assert len(coord.endpoint.drained) == 1
    assert coord.drt.store.deleted == [coord.instance.path]


async def test_coordinator_fabric_handoff_counts_blocks():
    fabric = SimpleNamespace(on_drain=lambda max_blocks=None: 7)
    coord = _coord(_Engine(active=0, fabric=fabric))
    res = await coord.drain()
    assert res.fabric_blocks_shared == 7
    assert res.result == "completed"


async def test_coordinator_deadline_when_streams_cannot_migrate():
    """Ineligible streams (guided / penalties / opted out) get the
    window; past the deadline the worker leaves anyway and the reactive
    machinery owns the rest."""
    eng = _Engine(active=2, migrate_on_drain=False)
    coord = _coord(eng, timeout_s=0.1)
    res = await coord.drain()
    assert res.result == "deadline"
    assert eng.drain_begun  # proactive sweep WAS attempted
    assert coord.drt.store.deleted  # deregistration is unconditional


async def test_coordinator_no_peer_serves_out_the_window():
    """A draining-only fleet counts as no peer: MIGRATE handoffs would
    only bounce, so the engine keeps serving and the distinct no_peer
    outcome reaches the operator."""
    eng = _Engine(active=1)
    coord = _coord(eng, peers="draining", timeout_s=0.1)
    res = await coord.drain()
    assert res.result == "no_peer"
    assert not eng.drain_begun
    assert res.streams_migrated == 0


async def test_coordinator_idle_worker_with_no_peer_is_still_clean():
    coord = _coord(_Engine(active=0), peers="none")
    res = await coord.drain()
    assert res.result == "completed"


def test_drain_timeout_env_parsing(monkeypatch):
    monkeypatch.delenv("DYN_DRAIN_TIMEOUT_S", raising=False)
    assert drain_timeout_from_env() == DEFAULT_DRAIN_TIMEOUT_S
    monkeypatch.setenv("DYN_DRAIN_TIMEOUT_S", "7.5")
    assert drain_timeout_from_env() == 7.5
    monkeypatch.setenv("DYN_DRAIN_TIMEOUT_S", "not-a-number")
    assert drain_timeout_from_env() == DEFAULT_DRAIN_TIMEOUT_S


# ---------------------------------------------------------------------------
# Worker-control subject: serve_drain_control / request_drain round-trip
# ---------------------------------------------------------------------------


class _PubSubStore:
    """In-memory publish/subscribe + kv_get_prefix, shaped like the
    coordinator store client (store/base.py)."""

    def __init__(self):
        self.queues = {}
        self.instances = {}
        self.published = []

    async def subscribe(self, subject):
        q = asyncio.Queue()
        self.queues.setdefault(subject, []).append(q)

        async def _iter():
            while True:
                yield subject, await q.get()

        return _iter()

    async def publish(self, subject, payload):
        self.published.append((subject, payload))
        for q in self.queues.get(subject, []):
            q.put_nowait(payload)

    async def kv_get_prefix(self, prefix):
        return [
            SimpleNamespace(key=k, value=v)
            for k, v in self.instances.items()
            if k.startswith(prefix)
        ]


async def test_control_call_converges_onto_shutdown_and_acks():
    store = _PubSubStore()
    drt = SimpleNamespace(store=store)
    me = _inst(0xAA)
    shutdowns = []
    runtime = SimpleNamespace(shutdown=lambda: shutdowns.append(True))
    task = asyncio.ensure_future(
        serve_drain_control(drt, "ns", me, runtime)
    )
    await asyncio.sleep(0.01)
    ack_sub = await store.subscribe("_ack")
    # wrong instance: ignored; garbage: ignored; match: shutdown + ack
    subject = worker_control_subject("ns")
    await store.publish(subject, b"not json")
    await store.publish(
        subject, json.dumps({"op": "drain", "instance": "bb"}).encode()
    )
    await store.publish(
        subject,
        json.dumps(
            {"op": "drain", "instance": "aa", "reply_to": "_ack"}
        ).encode(),
    )
    _, ack = await asyncio.wait_for(ack_sub.__anext__(), 1.0)
    assert json.loads(ack.decode()) == {"ok": True, "instance": "aa"}
    assert shutdowns == [True]
    task.cancel()


async def test_request_drain_polls_until_instance_departs():
    store = _PubSubStore()
    me = _inst(0xAA)
    store.instances[me.path] = b"{}"

    async def _depart():
        await asyncio.sleep(0.05)
        del store.instances[me.path]

    asyncio.ensure_future(_depart())
    ok = await request_drain(
        store, "ns", "aa", timeout_s=2.0, poll_interval_s=0.01
    )
    assert ok
    subject, payload = store.published[0]
    assert subject == worker_control_subject("ns")
    assert json.loads(payload.decode()) == {"op": "drain", "instance": "aa"}


async def test_request_drain_times_out_when_worker_stays():
    store = _PubSubStore()
    store.instances[_inst(0xAA).path] = b"{}"
    assert not await request_drain(
        store, "ns", "aa", timeout_s=0.05, poll_interval_s=0.01
    )


# ---------------------------------------------------------------------------
# Planner: drain-preferring scale-down + rolling_restart
# ---------------------------------------------------------------------------


class _FastClock:
    def __init__(self):
        self.now = 0.0

    def monotonic(self):
        return self.now

    async def sleep(self, seconds):
        self.now += seconds


class _Connector:
    def __init__(self, replicas=3, drain_refusals=0, add_refusals=0,
                 recover=True):
        self.n = replicas
        self.drains = 0
        self.adds = 0
        self._drain_refusals = drain_refusals
        self._add_refusals = add_refusals
        self._recover = recover

    async def replicas(self, component):
        return self.n

    async def drain_component(self, component):
        if self._drain_refusals > 0:
            self._drain_refusals -= 1
            return False
        self.drains += 1
        self.n -= 1
        return True

    async def add_component(self, component):
        if self._add_refusals > 0:
            self._add_refusals -= 1
            return False
        self.adds += 1
        if self._recover:
            self.n += 1
        return True


async def test_drain_or_remove_prefers_drain_and_falls_back():
    from dynamo_tpu.planner.planner import _drain_or_remove

    c = _Connector(replicas=2)
    assert await _drain_or_remove(c, "backend")
    assert c.drains == 1

    class _Legacy:
        removed = 0

        async def remove_component(self, component):
            self.removed += 1
            return True

    legacy = _Legacy()
    assert await _drain_or_remove(legacy, "backend")
    assert legacy.removed == 1


async def test_rolling_restart_cycles_every_replica():
    from dynamo_tpu.planner.planner import rolling_restart

    c = _Connector(replicas=3)
    cycled = await rolling_restart(
        c, "backend", max_unavailable=1, health_timeout_s=5.0,
        poll_interval_s=0.01, clock=_FastClock(),
    )
    assert cycled == 3
    assert c.drains == 3 and c.adds == 3
    assert c.n == 3  # fleet back at baseline


async def test_rolling_restart_batches_by_max_unavailable():
    from dynamo_tpu.planner.planner import rolling_restart

    c = _Connector(replicas=5)
    cycled = await rolling_restart(
        c, "backend", max_unavailable=2, health_timeout_s=5.0,
        poll_interval_s=0.01, clock=_FastClock(),
    )
    assert cycled == 5
    assert c.drains == 5 and c.adds == 5


async def test_rolling_restart_aborts_on_refused_drain():
    from dynamo_tpu.planner.planner import rolling_restart

    c = _Connector(replicas=3, drain_refusals=1)
    cycled = await rolling_restart(
        c, "backend", max_unavailable=1, health_timeout_s=5.0,
        poll_interval_s=0.01, clock=_FastClock(),
    )
    assert cycled == 0
    assert c.adds == 0  # no replacement for a drain that never happened


async def test_rolling_restart_aborts_when_fleet_never_recovers():
    from dynamo_tpu.planner.planner import rolling_restart

    c = _Connector(replicas=3, recover=False)
    cycled = await rolling_restart(
        c, "backend", max_unavailable=1, health_timeout_s=0.5,
        poll_interval_s=0.01, clock=_FastClock(),
    )
    assert cycled == 0  # health gate stopped the rollout at batch one
    assert c.drains == 1 and c.adds == 1


async def test_rolling_restart_empty_fleet_is_a_noop():
    from dynamo_tpu.planner.planner import rolling_restart

    c = _Connector(replicas=0)
    assert await rolling_restart(c, "backend", clock=_FastClock()) == 0


# ---------------------------------------------------------------------------
# Simulator: drain modeling + the kill-vs-drain A/B bench.py gates
# ---------------------------------------------------------------------------


def _ab_run(point):
    from dynamo_tpu.faults.plan import parse_plan
    from dynamo_tpu.sim import FleetSim, SimConfig, bursty_trace

    trace = bursty_trace(
        600.0, seed=2026, calm_rps=30.0, burst_rps=60.0,
        mean_calm_s=90.0, mean_burst_s=30.0,
    )
    return FleetSim(
        trace, SimConfig(initial_decode=3, kill_detect_s=2.0),
        plan=parse_plan(f"seed=42;{point}:kill@after=240"),
    ).run()


def _dip(res):
    att = [s["slo_attainment_mean"] for s in res["timeline"]]
    return 1.0 - min(att) if att else 0.0


def test_sim_drain_migrates_inflight_and_conserves_requests():
    from dynamo_tpu.faults.plan import parse_plan
    from dynamo_tpu.sim import FleetSim, SimConfig, diurnal_trace

    trace = diurnal_trace(
        120.0, seed=4, base_rps=10.0, peak_rps=10.0, period_s=120.0
    )
    plan = parse_plan("seed=2;worker.drain:kill@after=30")
    res = FleetSim(trace, SimConfig(initial_decode=2), plan=plan).run()
    assert res["workers_drained"] == 1
    assert res["workers_killed"] == 0
    assert res["drained_inflight"] > 0
    # planned departure: every in-flight stream hands off, none lost
    assert res["lost_inflight"] == 0
    assert res["resumed"] + res["refailed"] == res["drained_inflight"]
    assert res["decode_workers_final"] == 1
    assert res["completed"] + res["shed"] + res["unfinished"] == res["requests"]


def test_sim_kill_vs_drain_ab_is_deterministic_and_shallower():
    """The bench.py --chaos acceptance gate, run at the bench's exact
    seeds/config: the drain's SLO-attainment dip must be STRICTLY
    shallower than the kill's, and replays bit-identical."""
    kill = _ab_run("worker.liveness")
    drain = _ab_run("worker.drain")
    assert _ab_run("worker.drain") == drain  # bit-identical replay
    assert drain["workers_drained"] == 1 and kill["workers_killed"] == 1
    assert _dip(drain) < _dip(kill)
    assert drain["lost_inflight"] == 0
    assert drain["goodput_tokens"] >= kill["goodput_tokens"]


def test_sim_connector_drain_component_routes_by_config():
    """drain_proactive=False (the default) preserves the legacy remove
    semantics bit-for-bit; True routes scale-downs through the drain."""
    from dynamo_tpu.sim import FleetSim, SimConfig
    from dynamo_tpu.sim.fleet import SimConnector

    async def scale_down(proactive):
        fleet = FleetSim([], SimConfig(
            initial_decode=2, drain_proactive=proactive,
        ))
        fleet.run()  # spawns the initial workers; empty trace, returns
        assert await SimConnector(fleet).drain_component("backend")
        return fleet.result()

    res = asyncio.run(scale_down(False))
    assert res["workers_drained"] == 0
    res = asyncio.run(scale_down(True))
    assert res["workers_drained"] == 1
