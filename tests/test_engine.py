"""JAX engine tests on the virtual CPU backend.

The load-bearing test is prefill+decode ≡ one-shot forward: running a
sequence incrementally through the paged cache must produce the same
logits/greedy tokens as processing it in a single pass.
"""

import asyncio
import os

import numpy as np
import pytest

from dynamo_tpu.engine.allocator import BlockAllocator, NoBlocksError
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.utils.jaxtools import partial_auto_shard_map_supported
from dynamo_tpu.engine.scheduler import Scheduler, Sequence
from dynamo_tpu.protocols.common import (
    FinishReason,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.tokens import TokenBlockSequence

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")


# ---------------------------------------------------------------------------
# Allocator
# ---------------------------------------------------------------------------


def test_allocator_basic_and_prefix_reuse():
    events = []
    alloc = BlockAllocator(8, 4, on_event=lambda op, h, b: events.append((op, h)))
    hashes = [101, 102, 103]
    blocks, cached = alloc.allocate_prefix(hashes)
    assert cached == 0 and len(blocks) == 3
    for b, h in zip(blocks, hashes):
        alloc.commit_block(b, h)
    assert [e[0] for e in events] == ["stored"] * 3
    # a second sequence with the same prefix reuses all three
    blocks2, cached2 = alloc.allocate_prefix(hashes)
    assert cached2 == 3 and blocks2 == blocks
    assert alloc.match_prefix([101, 102, 999]) == 2
    alloc.free_sequence(blocks)
    alloc.free_sequence(blocks2)
    # still cached after free (inactive pool keeps content)
    blocks3, cached3 = alloc.allocate_prefix(hashes)
    assert cached3 == 3
    alloc.free_sequence(blocks3)


def test_allocator_eviction_lru_and_events():
    events = []
    alloc = BlockAllocator(4, 4, on_event=lambda op, h, b: events.append((op, h[0])))
    b1, _ = alloc.allocate_prefix([1, 2, 3])
    for b, h in zip(b1, [1, 2, 3]):
        alloc.commit_block(b, h)
    alloc.free_sequence(b1)
    # allocating new content evicts the LRU cached blocks and emits removals
    b2, cached = alloc.allocate_prefix([7, 8])
    assert cached == 0
    removed = [h for op, h in events if op == "removed"]
    assert len(removed) == 2
    assert alloc.match_prefix([1]) == (1 if 1 not in removed else 0)


def test_allocator_capacity_rollback():
    alloc = BlockAllocator(4, 4)  # 3 usable
    blocks, _ = alloc.allocate_prefix([1, 2])
    with pytest.raises(NoBlocksError):
        alloc.allocate_prefix([9, 10])  # needs 2, only 1 free
    assert alloc.num_free == 1  # rollback left state intact
    alloc.free_sequence(blocks)
    assert alloc.num_free == 3


# ---------------------------------------------------------------------------
# Scheduler
# ---------------------------------------------------------------------------


def _mk_seq(tokens, block_size=4, max_tokens=None, request_id="r"):
    return Sequence(
        request=PreprocessedRequest(
            request_id=request_id,
            token_ids=list(tokens),
            stop=StopConditions(max_tokens=max_tokens),
        ),
        tokens=TokenBlockSequence(list(tokens), block_size=block_size),
    )


def test_scheduler_admission_and_chunked_prefill():
    alloc = BlockAllocator(64, 4)
    sched = Scheduler(alloc, 4, max_batch_size=4, prefill_chunk_size=8)
    seq = _mk_seq(list(range(20)))
    sched.add_request(seq)
    # chunk 1: 8 tokens
    plan = sched.plan()
    assert plan.kind == "prefill" and len(plan.prefill.tokens) == 8
    assert plan.prefill.start_pos == 0 and not plan.prefill.is_last_chunk
    sched.complete_prefill_chunk(plan.prefill)
    # chunk 2
    plan = sched.plan()
    assert plan.prefill.start_pos == 8 and len(plan.prefill.tokens) == 8
    sched.complete_prefill_chunk(plan.prefill)
    # chunk 3 (final, 4 tokens)
    plan = sched.plan()
    assert plan.prefill.is_last_chunk and len(plan.prefill.tokens) == 4
    sched.complete_prefill_chunk(plan.prefill)
    assert sched.num_running == 1
    plan = sched.plan()
    assert plan.kind == "decode" and plan.decode_seqs == [seq]


def test_scheduler_decode_arrays_shapes():
    alloc = BlockAllocator(64, 4)
    sched = Scheduler(alloc, 4, max_batch_size=8)
    seqs = []
    for i in range(3):
        s = _mk_seq(list(range(5 + i)), request_id=f"r{i}")
        sched.add_request(s)
        seqs.append(s)
    while sched.prefilling or sched.waiting:
        plan = sched.plan()
        assert plan.kind == "prefill"
        sched.complete_prefill_chunk(plan.prefill)
    plan = sched.plan()
    arrays = sched.build_decode_arrays(plan.decode_seqs)
    assert arrays["tokens"].shape[0] == 4  # bucket of 3 -> 4
    assert arrays["block_tables"].shape[1] % sched.TABLE_BUCKET == 0
    # slot mapping points at the last token's slot
    s0 = plan.decode_seqs[0]
    pos = s0.total_len - 1
    assert arrays["slot_mapping"][0] == s0.block_table[pos // 4] * 4 + pos % 4


def test_scheduler_preemption_frees_blocks():
    alloc = BlockAllocator(8, 4)  # 7 usable
    sched = Scheduler(alloc, 4, max_batch_size=4)
    a = _mk_seq(list(range(12)), request_id="a")  # 3 blocks
    b = _mk_seq(list(range(12)), request_id="b")  # 3 blocks
    sched.add_request(a)
    sched.add_request(b)
    while sched.prefilling or sched.waiting:
        plan = sched.plan()
        if plan.kind != "prefill":
            break
        sched.complete_prefill_chunk(plan.prefill)
    assert sched.num_running == 2
    # grow a: next token needs block 4 for a; only 1 free; then b needs one
    # too -> b (younger) gets preempted when pool is exhausted
    for seq in (a, b):
        sched.append_token(seq, 1)  # fills to 13 tokens
    for _ in range(4):
        plan = sched.plan()
        if plan.kind != "decode":
            break
        for s in plan.decode_seqs:
            sched.append_token(s, 1)
        if sched.waiting:
            break
    # the OLDER sequence keeps running; the younger one is the preemption
    # victim (vLLM recompute policy)
    assert a.state.value == "running"
    assert b.state.value == "waiting"
    assert b.block_table == []  # its blocks were freed


# ---------------------------------------------------------------------------
# Model correctness: incremental == one-shot
# ---------------------------------------------------------------------------


def test_paged_forward_incremental_matches_oneshot():
    import jax.numpy as jnp

    from dynamo_tpu.models import ModelConfig
    from dynamo_tpu.models.llama import forward, init_cache, init_params

    cfg = ModelConfig.from_dir(MODEL_DIR)
    cfg.num_hidden_layers = 2
    params = init_params(cfg, seed=0)
    bs = 4
    prompt = list(range(1, 11))  # 10 tokens

    def run_oneshot(tokens):
        k, v = init_cache(cfg, 16, bs, dtype=jnp.float32)
        T = len(tokens)
        n_blocks = -(-T // bs)
        tables = np.zeros((1, 8), np.int32)
        tables[0, :n_blocks] = np.arange(1, n_blocks + 1)
        slots = np.zeros((T,), np.int32)
        for j in range(T):
            slots[j] = tables[0, j // bs] * bs + j % bs
        logits, _, _ = forward(
            cfg, params, k, v,
            np.asarray([tokens], np.int32),
            np.arange(T, dtype=np.int32)[None, :],
            slots, tables,
            np.asarray([T], np.int32),
            np.asarray([T - 1], np.int32),
            bs,
        )
        return np.asarray(logits[0])

    # incremental: prefill prompt, then decode 4 tokens greedily
    k, v = init_cache(cfg, 16, bs, dtype=jnp.float32)
    tables = np.zeros((1, 8), np.int32)
    seq_tokens = list(prompt)
    n_blocks = -(-len(seq_tokens) // bs)
    tables[0, :n_blocks] = np.arange(1, n_blocks + 1)
    slots = np.zeros((len(prompt),), np.int32)
    for j in range(len(prompt)):
        slots[j] = tables[0, j // bs] * bs + j % bs
    logits, k, v = forward(
        cfg, params, k, v,
        np.asarray([prompt], np.int32),
        np.arange(len(prompt), dtype=np.int32)[None, :],
        slots, tables,
        np.asarray([len(prompt)], np.int32),
        np.asarray([len(prompt) - 1], np.int32),
        bs,
    )
    for _ in range(4):
        nxt = int(np.argmax(np.asarray(logits)[0]))
        # one-shot over the full sequence must agree on the next prediction
        oneshot_logits = run_oneshot(seq_tokens)
        assert int(np.argmax(oneshot_logits)) == nxt
        np.testing.assert_allclose(
            np.asarray(logits)[0], oneshot_logits, rtol=2e-2, atol=2e-2
        )
        seq_tokens.append(nxt)
        pos = len(seq_tokens) - 1
        n_blocks = -(-len(seq_tokens) // bs)
        tables[0, :n_blocks] = np.arange(1, n_blocks + 1)
        slot = np.asarray([tables[0, pos // bs] * bs + pos % bs], np.int32)
        logits, k, v = forward(
            cfg, params, k, v,
            np.asarray([[nxt]], np.int32),
            np.asarray([[pos]], np.int32),
            slot, tables,
            np.asarray([len(seq_tokens)], np.int32),
            np.asarray([0], np.int32),
            bs,
        )


# ---------------------------------------------------------------------------
# Engine end-to-end (async, CPU)
# ---------------------------------------------------------------------------


def _engine_config(**kw) -> EngineConfig:
    defaults = dict(
        model_path=MODEL_DIR,
        model_name="tiny",
        random_weights=True,
        num_blocks=128,
        block_size=8,
        max_batch_size=8,
        prefill_chunk_size=32,
        max_model_len=256,
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


async def _generate(engine, prompt_ids, max_tokens=8, greedy=True, request_id="r"):
    from dynamo_tpu.protocols.common import SamplingOptions

    adapter = engine.as_async_engine()
    req = PreprocessedRequest(
        request_id=request_id,
        token_ids=list(prompt_ids),
        sampling=SamplingOptions(use_greedy=greedy),
        stop=StopConditions(max_tokens=max_tokens),
    )
    out = []
    final = None
    async for item in adapter.generate(req, Context()):
        out.extend(item.token_ids)
        if item.is_final:
            final = item
    return out, final


async def test_engine_greedy_determinism_and_prefix_cache():
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_config())
    try:
        prompt = list(range(1, 40))
        toks1, fin1 = await _generate(engine, prompt, request_id="r1")
        assert len(toks1) == 8
        assert fin1.finish_reason == FinishReason.LENGTH
        assert fin1.completion_tokens == 8
        # same prompt again: identical greedy continuation + prefix-cache hit
        toks2, _ = await _generate(engine, prompt, request_id="r2")
        assert toks2 == toks1
        stats = engine.stats()
        assert stats.gpu_prefix_cache_hit_rate > 0.0
        assert stats.kv_total_blocks == 127
    finally:
        await engine.shutdown()


async def test_engine_concurrent_batching():
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_config())
    try:
        prompts = [list(range(1, 10 + i)) for i in range(5)]
        results = await asyncio.gather(
            *[
                _generate(engine, p, max_tokens=6, request_id=f"c{i}")
                for i, p in enumerate(prompts)
            ]
        )
        for toks, fin in results:
            assert len(toks) == 6
            assert fin.finish_reason == FinishReason.LENGTH
        # determinism under batching: re-run one prompt alone and compare
        solo, _ = await _generate(engine, prompts[0], max_tokens=6, request_id="solo")
        assert solo == results[0][0]
    finally:
        await engine.shutdown()


async def test_engine_cancellation_frees_blocks():
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_config())
    try:
        adapter = engine.as_async_engine()
        ctx = Context()
        req = PreprocessedRequest(
            request_id="cancel-me",
            token_ids=list(range(1, 30)),
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=200),
        )
        got = 0
        async for item in adapter.generate(req, ctx):
            if item.token_ids:
                got += 1
            if got == 3:
                ctx.stop_generating()
        await asyncio.sleep(0.3)
        assert engine.allocator.num_free == engine.allocator.num_blocks - 1
    finally:
        await engine.shutdown()


async def test_multi_step_decode_matches_single_step():
    """decode_steps=4 must produce token-identical greedy output to
    decode_steps=1 (max_tokens not divisible by the window, so the tail
    of the last fused window is discarded), and frees all blocks."""
    from dynamo_tpu.engine.engine import JaxEngine

    async def run(steps: int):
        engine = await JaxEngine.launch(_engine_config(decode_steps=steps))
        try:
            prompt = list(range(1, 30))
            toks, fin = await _generate(engine, prompt, max_tokens=6,
                                        request_id=f"ms{steps}")
            assert fin.finish_reason == FinishReason.LENGTH
            assert fin.completion_tokens == 6
            # concurrent batch under multi-step
            results = await asyncio.gather(*[
                _generate(engine, list(range(1, 12 + i)), max_tokens=7,
                          request_id=f"msb{steps}-{i}")
                for i in range(3)
            ])
            # all sequences finished: only cached (committed) blocks may
            # remain referenced; nothing should leak as active-unfreed
            assert engine.scheduler is not None
            assert not engine.scheduler.running
            return toks, [r[0] for r in results]
        finally:
            await engine.shutdown()

    t1, b1 = await run(1)
    t4, b4 = await run(4)
    assert t1 == t4
    assert b1 == b4


def test_prefill_batch_admits_free_rows_under_pinned_buckets():
    """Rows whose admission leaves the padded BxT rectangle unchanged
    are free and must be admitted even past the area budget (pinned
    batch buckets would otherwise degrade batched prefill to one real
    row per full-size dispatch)."""
    alloc = BlockAllocator(256, 4)
    sched = Scheduler(alloc, 4, max_batch_size=8, prefill_chunk_size=16,
                      max_prefill_tokens=16)
    sched.prefill_batch_buckets = [8]  # bench-style pinning
    for i in range(4):
        sched.add_request(_mk_seq(list(range(1, 17)), request_id=f"p{i}"))
    plan = sched.plan()
    # area = 8 (pinned B) * 16 (T bucket) = 128 > budget 16, but
    # every extra row is free: all 4 must batch into one step
    assert plan.kind == "prefill"
    assert len(plan.prefill_batch) == 4
    arrays = sched.build_prefill_batch_arrays(plan.prefill_batch)
    assert arrays["tokens"].shape == (8, 16)


@pytest.mark.skipif(
    not partial_auto_shard_map_supported(),
    reason="pp x tp engine path needs partial-auto shard_map; this jax's\n    experimental fallback lowers it to a PartitionId op XLA SPMD rejects\n    (UNIMPLEMENTED) — see ROADMAP open item 1",
)
async def test_multi_step_with_pipeline_parallelism():
    """Fused multi-step decode composes with pp stage rotation: output
    must match the plain single-device single-step engine."""
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.models.config import ModelConfig

    mc = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=8, num_key_value_heads=4,
        max_position_embeddings=128,
    )

    async def run(pp: int, steps: int) -> list[int]:
        engine = await JaxEngine.launch(
            EngineConfig(
                model_path="", model_name="ppms", random_weights=True,
                num_blocks=32, block_size=4, max_batch_size=4,
                pipeline_parallel_size=pp, tensor_parallel_size=2 if pp > 1 else 1,
                decode_steps=steps, kv_cache_dtype="float32",
            ),
            model_config=mc,
        )
        try:
            toks, fin = await _generate(
                engine, list(range(1, 14)), max_tokens=6, request_id="x"
            )
            assert fin.completion_tokens == 6
            return toks
        finally:
            await engine.shutdown()

    base = await run(1, 1)
    assert await run(2, 4) == base


async def test_multi_step_surplus_does_not_corrupt_full_width_table():
    """A sequence whose block table exactly fills the bucketed width at
    the last fused window used to have surplus-step KV writes clipped
    onto its LAST REAL block (take_along_axis clips out-of-range table
    indices) — corrupting a block that prefix caching then serves to
    later requests. Surplus writes must go to the garbage block instead.

    Geometry: block_size=4, TABLE_BUCKET=8 -> width 8 = 32 slots.
    prompt 26 + max_tokens 6 = 32 tokens exactly; decode_steps=4 leaves
    2 surplus steps in the final window that would write at positions
    32,33 -> table column 8,9 -> clipped to column 7 (a real block)."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(
        _engine_config(block_size=4, decode_steps=4, num_blocks=64)
    )
    try:
        prompt = list(range(1, 27))  # 26 tokens
        toks, fin = await _generate(engine, prompt, max_tokens=6,
                                    request_id="full-width")
        assert fin.completion_tokens == 6
        # continue from the full 32-token history: the last block is a
        # prefix-cache hit and must hold uncorrupted KV
        full = prompt + toks
        cont_cached, _ = await _generate(engine, full, max_tokens=4,
                                         request_id="reuse")
    finally:
        await engine.shutdown()

    # ground truth: a fresh single-step engine over the same history
    engine2 = await JaxEngine.launch(
        _engine_config(block_size=4, decode_steps=1, num_blocks=64)
    )
    try:
        cont_fresh, _ = await _generate(engine2, full, max_tokens=4,
                                        request_id="fresh")
    finally:
        await engine2.shutdown()
    assert cont_cached == cont_fresh


async def test_pipelined_decode_with_mid_stream_arrival():
    """The pipelined decode path must flush cleanly when a new request
    arrives mid-generation (the next window is already in flight when
    the scheduler sees the newcomer), and outputs must stay identical
    to solo runs."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(
        _engine_config(decode_steps=4, max_batch_size=4, num_blocks=96)
    )
    try:
        p1 = list(range(1, 30))
        p2 = list(range(5, 40))

        async def delayed_second():
            await asyncio.sleep(0.25)  # lands mid-way through p1's decode
            return await _generate(engine, p2, max_tokens=12, request_id="mid2")

        (t1, f1), (t2, f2) = await asyncio.gather(
            _generate(engine, p1, max_tokens=24, request_id="mid1"),
            delayed_second(),
        )
        assert f1.completion_tokens == 24 and len(t1) == 24
        assert f2.completion_tokens == 12 and len(t2) == 12
        # identical to unpipelined solo reruns (prefix cache warm now,
        # but greedy continuations must not change)
        s1, _ = await _generate(engine, p1, max_tokens=24, request_id="solo1")
        s2, _ = await _generate(engine, p2, max_tokens=12, request_id="solo2")
        assert s1 == t1 and s2 == t2
        assert not engine.scheduler.running
    finally:
        await engine.shutdown()


async def test_multi_step_under_block_pressure():
    """Fused windows + tight block pool: preemption/recompute must keep
    outputs correct and leak no blocks."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(
        _engine_config(num_blocks=24, decode_steps=4, max_batch_size=4)
    )
    try:
        prompts = [list(range(1, 14 + 3 * i)) for i in range(4)]
        results = await asyncio.gather(*[
            _generate(engine, p, max_tokens=10, request_id=f"bp{i}")
            for i, p in enumerate(prompts)
        ])
        for toks, fin in results:
            assert fin.finish_reason == FinishReason.LENGTH
            assert len(toks) == 10
        # solo rerun of each prompt matches (recompute preemption must
        # not corrupt KV)
        for i, p in enumerate(prompts):
            solo, _ = await _generate(engine, p, max_tokens=10,
                                      request_id=f"solo{i}")
            assert solo == results[i][0], f"prompt {i} diverged"
        assert not engine.scheduler.running
    finally:
        await engine.shutdown()


