"""The shipped example graphs serve end-to-end (reference: sdk
tests/test_e2e.py serving the examples pipeline)."""

import asyncio
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


async def test_hello_world_graph_serves():
    from examples.hello_world.graph import Backend, Frontend, Middle
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.engine import Context, collect
    from dynamo_tpu.sdk.runner import serve_service
    from dynamo_tpu.runtime.runtime import DistributedRuntime
    from dynamo_tpu.store.memory import MemoryStore
    from dynamo_tpu.store.server import StoreServer

    server = StoreServer(MemoryStore(lease_sweep_interval_s=0.1), port=0)
    await server.start()
    cfg = lambda: RuntimeConfig(  # noqa: E731
        store_host="127.0.0.1", store_port=server.port,
        worker_host="127.0.0.1",
    )
    drts = []
    try:
        for svc in (Backend, Middle, Frontend):
            drt = await DistributedRuntime.create(config=cfg())
            drts.append(drt)
            await serve_service(svc, drt)
        caller = await DistributedRuntime.create(config=cfg())
        drts.append(caller)
        client = await (
            caller.namespace("hello").component("frontend")
            .endpoint("generate").client()
        )
        await client.wait_for_instances()
        from dynamo_tpu.runtime.push_router import PushRouter, RouterMode

        router = PushRouter(client, RouterMode.ROUND_ROBIN)
        items = await collect(router.generate({"text": "a b"}, Context()))
        assert [i["text"] for i in items] == [
            "front.mid.back.a", "front.mid.back.b"
        ]
    finally:
        for drt in drts:
            await drt.shutdown()
        await server.stop()


async def test_llm_graph_generates():
    from examples.llm.graph import Processor, Worker
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.engine import Context, collect
    from dynamo_tpu.runtime.push_router import PushRouter, RouterMode
    from dynamo_tpu.runtime.runtime import DistributedRuntime
    from dynamo_tpu.sdk.runner import serve_service
    from dynamo_tpu.store.memory import MemoryStore
    from dynamo_tpu.store.server import StoreServer

    server = StoreServer(MemoryStore(lease_sweep_interval_s=0.1), port=0)
    await server.start()
    cfg = lambda: RuntimeConfig(  # noqa: E731
        store_host="127.0.0.1", store_port=server.port,
        worker_host="127.0.0.1",
    )
    drts = []
    try:
        for svc in (Worker, Processor):
            drt = await DistributedRuntime.create(config=cfg())
            drts.append(drt)
            await serve_service(svc, drt)
        caller = await DistributedRuntime.create(config=cfg())
        drts.append(caller)
        client = await (
            caller.namespace("llm").component("processor")
            .endpoint("generate").client()
        )
        await client.wait_for_instances()
        router = PushRouter(client, RouterMode.ROUND_ROBIN)
        items = await collect(
            router.generate({"prompt": "hello world", "max_tokens": 5}, Context())
        )
        toks = [t for i in items for t in i.get("token_ids", [])]
        # random weights can sample ids the tiny tokenizer leaves
        # unmapped (vocab_size > tokenizer size), so assert on tokens
        assert len(toks) == 5
        assert items[-1].get("finish_reason") == "length"
    finally:
        for drt in drts:
            await drt.shutdown()
        await server.stop()


def test_example_configs_generate_valid_manifests():
    """Every checked-in example config must parse as a
    GraphDeploymentSpec and render validating K8s manifests — configs
    stay wired to the deploy machinery, not dead YAML."""
    import glob
    import os

    from dynamo_tpu.deploy import GraphDeploymentSpec
    from dynamo_tpu.deploy.manifests import graph_manifests, validate_k8s_doc

    cfg_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "examples", "llm", "configs",
    )
    paths = sorted(glob.glob(os.path.join(cfg_dir, "*.yaml")))
    assert len(paths) >= 4, paths
    names = set()
    for path in paths:
        spec = GraphDeploymentSpec.from_yaml_file(path)
        names.add(spec.name)
        for doc in graph_manifests(spec, image="example/dyn:test"):
            validate_k8s_doc(doc)
    assert {
        "llm-agg", "llm-disagg", "llm-disagg-multinode", "vlm",
        "llm-moe-ep", "llm-vlm",
    } <= names


async def test_planner_sim_scales_up_and_down(tmp_path):
    """The planner-benchmark analogue (examples/llm/planner_sim.py):
    under a sinusoidal load the REAL planner must scale decode and
    prefill up into the peak and back down after it, and the recorded
    JSONL trace must carry the replica story."""
    import json

    from examples.llm.planner_sim import simulate

    out = str(tmp_path / "trace.jsonl")
    summary = await simulate(out, period_ticks=60, cycles=2.0)
    assert summary["scale_ups"] >= 2, summary
    assert summary["scale_downs"] >= 2, summary
    assert summary["peak_decode_workers"] > 1, summary
    assert summary["final_decode_workers"] == 1, summary  # back down
    rows = [json.loads(l) for l in open(out)]
    assert len(rows) == summary["ticks"]
    assert {"kv_load_mean", "decode_workers", "prefill_workers"} <= set(rows[0])
    # the committed example trace must match the simulator exactly
    # (deterministic; regenerate with `python -m examples.llm.planner_sim
    # --out examples/llm/planner_trace.jsonl` after planner changes)
    import os
    committed = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "examples", "llm", "planner_trace.jsonl",
    )
    committed_rows = [json.loads(l) for l in open(committed)]
    assert len(committed_rows) == len(rows)
    for a, b in zip(committed_rows, rows):
        # integer replica story must match exactly; float load signals
        # only to tolerance (libm cos differs by ulps across platforms)
        for k in ("tick", "decode_workers", "prefill_workers"):
            assert a[k] == b[k], (a, b)
        for k in ("kv_load_mean", "prefill_queue_per_worker"):
            assert abs(a[k] - b[k]) < 1e-9, (a, b)


def test_example_launch_scripts_use_real_cli_flags():
    """The shell recipes must only use flags the CLI parser accepts
    (catches drift between docs/examples and the real surface)."""
    import glob
    import os
    import re

    from dynamo_tpu.cli.main import build_parser

    parser = build_parser()
    run_parser = None
    for action in parser._subparsers._group_actions:  # type: ignore[union-attr]
        run_parser = action.choices.get("run")
    assert run_parser is not None
    known = set()
    for a in run_parser._actions:
        known.update(a.option_strings)

    launch_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "examples", "llm", "launch",
    )
    scripts = glob.glob(os.path.join(launch_dir, "*.sh"))
    assert scripts
    for path in scripts:
        text = open(path).read()
        for m in re.finditer(r"cli\.main run(.*?)(?:&|\n\n|$)", text, re.S):
            for flag in re.findall(r"(--[a-z][a-z0-9-]+)", m.group(1)):
                assert flag in known, f"{os.path.basename(path)}: {flag}"


async def test_planner_beats_static_fleets():
    """The recorded planner-vs-static claim (examples/llm/
    planner_benchmark.py; reference analogue: 1.5x per-resource at
    -7.4% GPU-hours, docs/guides/planner_benchmark/
    benchmark_planner.md): same sinusoidal workload, the planner must
    (a) match static-peak's goodput with materially fewer worker-ticks
    and (b) hold backlog far below mean-sized static."""
    from examples.llm.planner_benchmark import compare

    rows = {r["fleet"]: r for r in await compare()}
    dyn = rows["planner"]
    peak = rows["static-peak"]
    mean = rows["static-mean"]
    assert dyn["goodput"] >= 0.99
    assert peak["goodput"] >= 0.99
    # >= 25% fewer worker-ticks than capacity-planning static
    assert dyn["worker_ticks"] <= 0.75 * peak["worker_ticks"]
    # and per-resource throughput at least 1.3x static-peak
    assert (
        dyn["tokens_per_worker_tick"]
        >= 1.3 * peak["tokens_per_worker_tick"]
    )
    # mean-sized static pays in queueing: planner backlog is far lower
    assert dyn["backlog_peak_tokens"] < 0.1 * mean["backlog_peak_tokens"]
