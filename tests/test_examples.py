"""The shipped example graphs serve end-to-end (reference: sdk
tests/test_e2e.py serving the examples pipeline)."""

import asyncio
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


async def test_hello_world_graph_serves():
    from examples.hello_world.graph import Backend, Frontend, Middle
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.engine import Context, collect
    from dynamo_tpu.sdk.runner import serve_service
    from dynamo_tpu.runtime.runtime import DistributedRuntime
    from dynamo_tpu.store.memory import MemoryStore
    from dynamo_tpu.store.server import StoreServer

    server = StoreServer(MemoryStore(lease_sweep_interval_s=0.1), port=0)
    await server.start()
    cfg = lambda: RuntimeConfig(  # noqa: E731
        store_host="127.0.0.1", store_port=server.port,
        worker_host="127.0.0.1",
    )
    drts = []
    try:
        for svc in (Backend, Middle, Frontend):
            drt = await DistributedRuntime.create(config=cfg())
            drts.append(drt)
            await serve_service(svc, drt)
        caller = await DistributedRuntime.create(config=cfg())
        drts.append(caller)
        client = await (
            caller.namespace("hello").component("frontend")
            .endpoint("generate").client()
        )
        await client.wait_for_instances()
        from dynamo_tpu.runtime.push_router import PushRouter, RouterMode

        router = PushRouter(client, RouterMode.ROUND_ROBIN)
        items = await collect(router.generate({"text": "a b"}, Context()))
        assert [i["text"] for i in items] == [
            "front.mid.back.a", "front.mid.back.b"
        ]
    finally:
        for drt in drts:
            await drt.shutdown()
        await server.stop()


async def test_llm_graph_generates():
    from examples.llm.graph import Processor, Worker
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.engine import Context, collect
    from dynamo_tpu.runtime.push_router import PushRouter, RouterMode
    from dynamo_tpu.runtime.runtime import DistributedRuntime
    from dynamo_tpu.sdk.runner import serve_service
    from dynamo_tpu.store.memory import MemoryStore
    from dynamo_tpu.store.server import StoreServer

    server = StoreServer(MemoryStore(lease_sweep_interval_s=0.1), port=0)
    await server.start()
    cfg = lambda: RuntimeConfig(  # noqa: E731
        store_host="127.0.0.1", store_port=server.port,
        worker_host="127.0.0.1",
    )
    drts = []
    try:
        for svc in (Worker, Processor):
            drt = await DistributedRuntime.create(config=cfg())
            drts.append(drt)
            await serve_service(svc, drt)
        caller = await DistributedRuntime.create(config=cfg())
        drts.append(caller)
        client = await (
            caller.namespace("llm").component("processor")
            .endpoint("generate").client()
        )
        await client.wait_for_instances()
        router = PushRouter(client, RouterMode.ROUND_ROBIN)
        items = await collect(
            router.generate({"prompt": "hello world", "max_tokens": 5}, Context())
        )
        toks = [t for i in items for t in i.get("token_ids", [])]
        # random weights can sample ids the tiny tokenizer leaves
        # unmapped (vocab_size > tokenizer size), so assert on tokens
        assert len(toks) == 5
        assert items[-1].get("finish_reason") == "length"
    finally:
        for drt in drts:
            await drt.shutdown()
        await server.stop()
