"""Fault-injection subsystem unit tests (docs/robustness.md): plan
parsing, determinism by seed, and each wired injection point actually
firing through its real call site."""

import asyncio
import json

import pytest

from dynamo_tpu import faults
from dynamo_tpu.faults import (
    DroppedFrameError,
    FaultInjectedError,
    FaultPlan,
    FaultRule,
    parse_plan,
    parse_rule,
)
from dynamo_tpu.faults import injector as injector_mod


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.deactivate()
    yield
    faults.deactivate()


# ---------------------------------------------------------------------------
# Parsing
# ---------------------------------------------------------------------------


def test_parse_compact_syntax():
    plan = parse_plan(
        "seed=42;store.call:delay=0.05@p=0.5;"
        "engine.step:error@after=3@max=2;"
        "transport.recv:drop;worker.liveness:kill;header"
    )
    assert plan.seed == 42
    assert plan.allow_request_rules
    assert [r.point for r in plan.rules] == [
        "store.call", "engine.step", "transport.recv", "worker.liveness",
    ]
    delay, err, drop, kill = plan.rules
    assert delay.kind == "delay" and delay.delay_s == 0.05 and delay.p == 0.5
    assert err.after == 3 and err.max_fires == 2
    assert drop.kind == "drop"
    assert kill.max_fires == 1  # kill is one-shot unless overridden


def test_parse_rule_match_and_error_types():
    r = parse_rule("kv_transfer.put:error=conn@match=req-7")
    assert r.match == "req-7"
    assert isinstance(r.exc(), ConnectionError)
    assert isinstance(parse_rule("a.b:error").exc(), FaultInjectedError)
    assert isinstance(parse_rule("a.b:drop").exc(), DroppedFrameError)


def test_parse_rejects_garbage():
    with pytest.raises(ValueError):
        parse_rule("no-colon-here")
    with pytest.raises(ValueError):
        parse_rule("p.x:frobnicate")
    with pytest.raises(ValueError):
        parse_rule("p.x:error@p=1.5")
    with pytest.raises(ValueError):
        parse_rule("p.x:error@bogus=1")
    with pytest.raises(ValueError):
        parse_rule("p.x:delay=not-a-number")


def test_parse_json_plan(tmp_path):
    doc = {
        "seed": 9,
        "rules": [
            {"point": "store.call", "kind": "error", "p": 0.25, "max": 3}
        ],
    }
    plan = parse_plan(json.dumps(doc))
    assert plan.seed == 9 and plan.rules[0].max_fires == 3
    path = tmp_path / "plan.json"
    path.write_text(json.dumps(doc))
    plan2 = parse_plan(f"@{path}")
    assert plan2.to_dict() == plan.to_dict()


# ---------------------------------------------------------------------------
# Determinism
# ---------------------------------------------------------------------------


def _fire_pattern(seed: int, n: int = 64) -> list[bool]:
    plan = parse_plan(f"seed={seed};p.x:error@p=0.3")
    inj = faults.FaultInjector(plan)
    out = []
    for _ in range(n):
        try:
            inj.fire("p.x")
            out.append(False)
        except FaultInjectedError:
            out.append(True)
    return out


def test_same_seed_same_fire_pattern():
    assert _fire_pattern(7) == _fire_pattern(7)
    assert any(_fire_pattern(7))  # p=0.3 over 64 passes certainly fires


def test_different_seed_different_pattern():
    assert _fire_pattern(7) != _fire_pattern(8)


def test_per_point_streams_independent_of_interleave():
    """The pattern at one point must not depend on traffic at another."""
    plan = parse_plan("seed=1;a.a:error@p=0.5;b.b:error@p=0.5")

    def run(interleave: bool) -> list[bool]:
        inj = faults.FaultInjector(plan)
        out = []
        for i in range(32):
            if interleave:
                try:
                    inj.fire("b.b")
                except FaultInjectedError:
                    pass
            try:
                inj.fire("a.a")
                out.append(False)
            except FaultInjectedError:
                out.append(True)
        return out

    assert run(False) == run(True)


def test_after_and_max_modifiers():
    plan = FaultPlan(seed=0, rules=[
        FaultRule(point="p", kind="error", after=2, max_fires=2)
    ])
    inj = faults.FaultInjector(plan)
    fires = []
    for i in range(6):
        try:
            inj.fire("p")
            fires.append(False)
        except FaultInjectedError:
            fires.append(True)
    assert fires == [False, False, True, True, False, False]


def test_match_modifier_scopes_by_context():
    plan = FaultPlan(seed=0, rules=[
        FaultRule(point="p", kind="error", match="victim")
    ])
    inj = faults.FaultInjector(plan)
    inj.fire("p", request_id="innocent")  # no raise
    with pytest.raises(FaultInjectedError):
        inj.fire("p", request_id="victim-123")


def test_kill_invokes_process_exit(monkeypatch):
    calls = []
    monkeypatch.setattr(injector_mod, "_kill_process", calls.append)
    plan = parse_plan("seed=0;worker.liveness:kill")
    inj = faults.FaultInjector(plan)
    inj.fire("worker.liveness")
    inj.fire("worker.liveness")  # one-shot: second pass is a no-op
    assert calls == [injector_mod.KILL_EXIT_CODE]


def test_stats_and_counter_and_listener():
    from dynamo_tpu.telemetry import REGISTRY

    plan = parse_plan("seed=0;p.q:error@max=1")
    inj = faults.activate(plan)
    seen = []
    inj.add_listener(seen.append)
    metric = REGISTRY.get("dynamo_faults_fired_total")
    before = metric.labels("p.q", "error").value
    with pytest.raises(FaultInjectedError):
        faults.fire("p.q", request_id="r1")
    assert metric.labels("p.q", "error").value == before + 1
    assert seen and seen[0]["point"] == "p.q"
    stats = inj.stats()
    assert stats["fired_total"] == 1
    assert stats["rules"][0]["fires"] == 1
    assert stats["recent"][0]["request_id"] == "r1"


def test_arm_request_requires_plan_opt_in():
    inj = faults.FaultInjector(parse_plan("seed=0"))
    assert inj.arm_request("p.x:error", "rid") == 0  # not opted in
    inj2 = faults.FaultInjector(parse_plan("seed=0;header"))
    assert inj2.arm_request("p.x:error", "rid-9") == 1
    inj2.fire("p.x", request_id="other")  # scoped: no raise
    with pytest.raises(FaultInjectedError):
        inj2.fire("p.x", request_id="rid-9")
    inj2.fire("p.x", request_id="rid-9")  # max defaulted to 1


def test_init_from_env(monkeypatch):
    monkeypatch.setenv(faults.ENV_VAR, "seed=3;p.x:error")
    inj = faults.init_from_env()
    assert inj is not None and faults.ACTIVE is inj
    faults.deactivate()
    monkeypatch.setenv(faults.ENV_VAR, "totally;;;broken@@@")
    assert faults.init_from_env() is None  # loud log, no crash


# ---------------------------------------------------------------------------
# Wired call sites: each injection point fires through its real seam
# ---------------------------------------------------------------------------


async def test_point_store_call():
    from dynamo_tpu.store.memory import MemoryStore
    from dynamo_tpu.store.server import StoreServer
    from dynamo_tpu.store.client import StoreClient

    server = StoreServer(store=MemoryStore(), host="127.0.0.1", port=0)
    await server.start()
    client = await StoreClient.connect("127.0.0.1", server.port)
    try:
        await client.kv_put("k", b"v")
        faults.activate(parse_plan("seed=0;store.call:error@max=1"))
        with pytest.raises(FaultInjectedError):
            await client.kv_get("k")
        # max=1 exhausted: the store works again
        assert (await client.kv_get("k")).value == b"v"
    finally:
        faults.deactivate()
        await client.close()
        await server.stop()


async def test_point_transport_send_and_recv():
    from dynamo_tpu.runtime.engine import Context, FnEngine
    from dynamo_tpu.runtime.service import (
        ConnectionLostError,
        EndpointConnection,
        EndpointServer,
    )

    async def echo(req, ctx):
        yield {"ok": req}

    server = EndpointServer(host="127.0.0.1", port=0)
    server.register("ep", FnEngine(echo))
    await server.start()
    conn = await EndpointConnection.connect("127.0.0.1", server.port)
    try:
        # send: an injected conn error surfaces at the caller
        faults.activate(parse_plan("seed=0;transport.send:error=conn@max=1"))
        with pytest.raises(ConnectionError):
            await conn.request("ep", {"x": 1}, Context())
        # recv: a drop tears the connection down -> ConnectionLostError
        faults.activate(parse_plan("seed=0;transport.recv:drop@max=1"))
        stream = await conn.request("ep", {"x": 2}, Context())
        with pytest.raises(ConnectionLostError):
            async for _ in stream:
                pass
    finally:
        faults.deactivate()
        await conn.close()
        await server.stop()


async def test_point_prefill_dequeue():
    from dynamo_tpu.disagg.prefill_queue import PrefillQueue
    from dynamo_tpu.store.memory import MemoryStore

    q = PrefillQueue(MemoryStore(), "ns")
    faults.activate(parse_plan("seed=0;prefill.dequeue:error@max=1"))
    with pytest.raises(FaultInjectedError):
        await q.dequeue(timeout_s=0.01)
    assert await q.dequeue(timeout_s=0.01) is None  # recovered


async def test_point_kv_transfer_put():
    from dynamo_tpu.disagg.transfer import TransferClient, TransferMetadata

    faults.activate(parse_plan("seed=0;kv_transfer.put:error=conn@max=1"))
    import numpy as np

    meta = TransferMetadata(host="127.0.0.1", port=1, worker_id=1, layout="{}")
    with pytest.raises(ConnectionError):
        await TransferClient.put(meta, "rid", [1], np.zeros((1, 2, 2)))


def test_point_engine_step_and_liveness_names():
    """The engine fires both sync points through faults.fire; verify the
    module-level hook honors an active plan (the full engine path is
    covered by the chaos suite)."""
    faults.activate(parse_plan("seed=0;engine.step:error@max=1"))
    with pytest.raises(FaultInjectedError):
        faults.fire("engine.step")
    faults.fire("engine.step")  # exhausted
    faults.fire("worker.liveness")  # no rule: no-op


async def test_point_router_resume_fires_before_resume_dispatch():
    """router.resume (runtime/migration.py): the double-fault point —
    a plan can fail the mid-stream migration machinery itself. An
    injected error counts as a failed resume attempt; the router-level
    recovery path is covered in tests/test_migration.py."""
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.migration import (
        MigrationConfig,
        WorkerStreamLostError,
        migrating_stream,
    )
    from dynamo_tpu.runtime.service import ConnectionLostError

    async def dying_stream():
        yield {"token_ids": [5]}
        raise ConnectionLostError("gone")

    dials = []

    async def dial(req, exclude, resume, wait_s):
        dials.append(resume)
        return 1, dying_stream(), None

    faults.activate(parse_plan("seed=0;router.resume:error@max=2"))
    try:
        req = {"token_ids": [1, 2], "stop": None}
        got = []
        with pytest.raises(WorkerStreamLostError):
            async for item in migrating_stream(
                req, Context(), dial,
                MigrationConfig(max_resumes=2, instance_wait_s=0.1),
                backoff_base_s=0.001, backoff_cap_s=0.002,
            ):
                got.append(item)
        # the first dispatch streamed one token; both resume attempts
        # died at the injected point before any dial happened
        assert got and got[0]["token_ids"] == [5]
        assert dials == [False]
    finally:
        faults.deactivate()


# ---------------------------------------------------------------------------
# ISSUE 20 points: store.publish_drain / worker.drain through the real
# DrainCoordinator call sites (runtime/drain.py)
# ---------------------------------------------------------------------------


class _DrainInstance:
    def __init__(self, iid: int = 0xABC):
        self.instance_id = iid
        self.path = f"instances/ns/comp/ep:{iid:x}"
        self.draining = False


class _DrainStore:
    def __init__(self):
        self.deleted = []

    async def kv_delete(self, key):
        self.deleted.append(key)
        return True


class _DrainDrt:
    def __init__(self):
        self.store = _DrainStore()


class _DrainEndpoint:
    def __init__(self):
        self.drained = []

    async def set_draining(self, instance):
        self.drained.append(instance)


class _DrainComponent:
    def __init__(self, instances):
        self._instances = instances

    async def list_instances(self):
        return self._instances


class _DrainEngine:
    kvbm = None

    def __init__(self, active: int = 0):
        self._active = active
        self.drain_begun = False
        self.drain_migrated = 0

    def active_streams(self):
        return self._active

    def begin_drain(self):
        # proactive sweep: everything migratable hands off immediately
        self.drain_begun = True
        self.drain_migrated += self._active
        self._active = 0


def _drain_coordinator(engine, peers=None, timeout_s=0.2):
    from dynamo_tpu.runtime.drain import DrainCoordinator

    me = _DrainInstance()
    peer = _DrainInstance(0xDEF)
    return DrainCoordinator(
        _DrainDrt(),
        _DrainComponent([me, peer] if peers is None else peers),
        _DrainEndpoint(),
        me,
        engine=engine,
        timeout_s=timeout_s,
        poll_interval_s=0.01,
    )


async def test_point_store_publish_drain_degrades_flag_publish():
    """An injected store.publish_drain error must NOT abort the drain:
    the DRAINING publish is skipped (routers fall back to lease expiry)
    but the handoff, wait, and deregistration all still run."""
    coord = _drain_coordinator(_DrainEngine(active=2))
    faults.activate(parse_plan("seed=0;store.publish_drain:error@max=1"))
    res = await coord.drain()
    assert res.result == "completed"
    assert res.streams_migrated == 2
    assert coord.endpoint.drained == []  # publish was the injected fault
    assert coord.drt.store.deleted == [coord.instance.path]  # still deregisters


async def test_point_worker_drain_forces_deadline_fallback():
    """An injected worker.drain error skips the proactive MIGRATE sweep;
    with streams still attached the coordinator rides the deadline and
    reports the reactive-fallback outcome."""
    eng = _DrainEngine(active=1)
    coord = _drain_coordinator(eng, timeout_s=0.1)
    faults.activate(parse_plan("seed=0;worker.drain:error@max=1"))
    res = await coord.drain()
    assert res.result == "deadline"
    assert not eng.drain_begun
    assert res.streams_migrated == 0
    # deregistration is unconditional — reactive path needs the key gone
    assert coord.drt.store.deleted == [coord.instance.path]


async def test_drain_clean_when_no_fault_active():
    """Baseline for the two tests above: same coordinator, no plan."""
    coord = _drain_coordinator(_DrainEngine(active=3))
    res = await coord.drain()
    assert res.result == "completed"
    assert res.streams_migrated == 3
    assert len(coord.endpoint.drained) == 1


def test_drain_points_have_independent_seeded_streams():
    """The two new points draw from per-rule seeded streams like every
    other point: same plan → same pattern, and the two points' streams
    are independent of each other."""
    def pattern(point: str) -> list[bool]:
        inj = faults.FaultInjector(parse_plan(
            "seed=9;worker.drain:error@p=0.5;store.publish_drain:error@p=0.5"
        ))
        out = []
        for _ in range(64):
            try:
                inj.fire(point)
                out.append(False)
            except FaultInjectedError:
                out.append(True)
        return out

    assert pattern("worker.drain") == pattern("worker.drain")
    assert pattern("store.publish_drain") == pattern("store.publish_drain")
    assert pattern("worker.drain") != pattern("store.publish_drain")
