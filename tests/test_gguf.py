"""GGUF reader/writer, dequantization, config + tokenizer extraction,
and weight loading (reference: lib/llm/src/gguf/*.rs,
model_card/create.rs from_gguf)."""

import numpy as np
import pytest

from dynamo_tpu.gguf import (
    GGUFReader,
    config_from_gguf,
    load_params_from_gguf,
    tokenizer_from_gguf,
    write_gguf,
)
from dynamo_tpu.gguf.reader import GGML_F16, GGML_F32, GGML_Q8_0
from dynamo_tpu.models.config import ModelConfig


def test_metadata_roundtrip(tmp_path):
    path = str(tmp_path / "m.gguf")
    md = {
        "general.architecture": "llama",
        "llama.block_count": 2,
        "llama.rope.freq_base": 5000.0,
        "general.some_flag": True,
        "tokenizer.ggml.tokens": ["a", "b", "c"],
        "llama.scores": [1.0, -2.5],
    }
    write_gguf(path, md, {"t": np.zeros((2, 3), np.float32)})
    with GGUFReader(path) as r:
        for k, v in md.items():
            assert r.metadata[k] == v, k
        assert r.tensors["t"].shape == (2, 3)
        assert r.tensors["t"].dims == (3, 2)  # ne order on disk


def test_tensor_dtypes_and_dequant(tmp_path):
    path = str(tmp_path / "t.gguf")
    rng = np.random.default_rng(0)
    f32 = rng.standard_normal((4, 8)).astype(np.float32)
    f16 = rng.standard_normal((2, 32)).astype(np.float16)
    q = rng.standard_normal((2, 64)).astype(np.float32)
    write_gguf(path, {}, {"f32": f32, "f16": f16, "q8": q},
               quantize={"q8": GGML_Q8_0})
    with GGUFReader(path) as r:
        assert r.tensors["f32"].ggml_type == GGML_F32
        assert r.tensors["f16"].ggml_type == GGML_F16
        assert r.tensors["q8"].ggml_type == GGML_Q8_0
        np.testing.assert_array_equal(r.load("f32"), f32)
        np.testing.assert_array_equal(r.load("f16"), f16)
        deq = r.load("q8")
        assert deq.shape == q.shape
        # Q8_0: one f16 scale per 32 values -> ~1% relative error
        np.testing.assert_allclose(deq, q, atol=np.abs(q).max() / 100)


def test_config_from_gguf(tmp_path):
    path = str(tmp_path / "c.gguf")
    write_gguf(path, {
        "general.architecture": "llama",
        "llama.embedding_length": 64,
        "llama.block_count": 3,
        "llama.attention.head_count": 8,
        "llama.attention.head_count_kv": 2,
        "llama.feed_forward_length": 128,
        "llama.context_length": 2048,
        "llama.rope.freq_base": 50000.0,
        "llama.attention.layer_norm_rms_epsilon": 1e-6,
        "tokenizer.ggml.tokens": ["x"] * 100,
        "tokenizer.ggml.eos_token_id": 7,
    }, {"t": np.zeros((1, 32), np.float32)})
    with GGUFReader(path) as r:
        cfg = config_from_gguf(r)
    assert cfg.hidden_size == 64 and cfg.num_hidden_layers == 3
    assert cfg.num_attention_heads == 8 and cfg.num_key_value_heads == 2
    assert cfg.vocab_size == 100 and cfg.eos_token_id == 7
    assert cfg.rope_theta == 50000.0 and cfg.rms_norm_eps == 1e-6


def test_config_from_gguf_detects_qkv_bias(tmp_path):
    path = str(tmp_path / "b.gguf")
    write_gguf(path, {"general.architecture": "llama"}, {
        "blk.0.attn_q.bias": np.zeros((8,), np.float32),
    })
    with GGUFReader(path) as r:
        assert config_from_gguf(r).attention_bias
    path2 = str(tmp_path / "q.gguf")
    write_gguf(path2, {"general.architecture": "qwen2"},
               {"t": np.zeros((1, 32), np.float32)})
    with GGUFReader(path2) as r:
        assert config_from_gguf(r).attention_bias


def test_config_from_gguf_applies_gemma_semantics(tmp_path):
    """Gemma GGUFs must pick up the model_type fixups from
    ModelConfig.from_dict (embedding scaling, +1 norm bias, gelu, tied
    embeddings, wide head_dim) — a plain-llama load silently corrupts
    logits."""
    path = str(tmp_path / "g.gguf")
    write_gguf(path, {
        "general.architecture": "gemma",
        "gemma.embedding_length": 2048,
        "gemma.block_count": 2,
        "gemma.attention.head_count": 8,
        "gemma.attention.head_count_kv": 1,
        "gemma.attention.key_length": 256,
    }, {"t": np.zeros((1, 32), np.float32)})
    with GGUFReader(path) as r:
        cfg = config_from_gguf(r)
    assert cfg.model_type == "gemma"
    assert cfg.scale_embeddings and cfg.norm_bias_one
    assert cfg.hidden_act == "gelu" and cfg.tie_word_embeddings
    assert cfg.head_dim == 256  # not hidden/heads == 256 != 2048/8


def test_config_from_gguf_rejects_unknown_arch(tmp_path):
    path = str(tmp_path / "phi.gguf")
    write_gguf(path, {"general.architecture": "phi2"},
               {"t": np.zeros((1, 32), np.float32)})
    with GGUFReader(path) as r:
        with pytest.raises(ValueError, match="unsupported GGUF architecture"):
            config_from_gguf(r)


def test_tokenizer_from_gguf_unigram_byte_fallback(tmp_path):
    path = str(tmp_path / "u.gguf")
    tokens = ["<unk>", "▁hi", "▁there", "▁"] + [f"<0x{b:02X}>" for b in range(256)]
    scores = [0.0, -1.0, -1.0, -5.0] + [-10.0] * 256
    write_gguf(path, {
        "tokenizer.ggml.model": "llama",
        "tokenizer.ggml.tokens": tokens,
        "tokenizer.ggml.scores": scores,
        "tokenizer.ggml.unknown_token_id": 0,
    }, {"t": np.zeros((1, 32), np.float32)})
    with GGUFReader(path) as r:
        tok = tokenizer_from_gguf(r)
    # sentencepiece normalization: words match their ▁-prefixed vocab
    # entries instead of degenerating to byte fallback
    assert tok.encode("hi there") == [1, 2]
    assert tok.decode([1, 2]) == "hi there"
    # newline has no vocab token: must byte-fallback, not collapse to unk
    ids = tok.encode("\n")
    assert ids and all(i != 0 for i in ids)
    assert tok.decode(ids) == "\n"


def test_write_gguf_nondefault_alignment_roundtrips(tmp_path):
    path = str(tmp_path / "a.gguf")
    arr = np.arange(24, dtype=np.float32).reshape(4, 6)
    write_gguf(path, {}, {"t": arr}, alignment=64)
    with GGUFReader(path) as r:
        np.testing.assert_array_equal(r.load("t"), arr)


def test_config_from_gguf_sliding_window(tmp_path):
    path = str(tmp_path / "sw.gguf")
    write_gguf(path, {
        "general.architecture": "mistral",
        "mistral.attention.sliding_window": 4096,
    }, {"t": np.zeros((1, 32), np.float32)})
    with GGUFReader(path) as r:
        assert config_from_gguf(r).sliding_window == 4096
    path2 = str(tmp_path / "sw2.gguf")
    write_gguf(path2, {
        "general.architecture": "qwen2",
        "qwen2.attention.sliding_window": 32768,
    }, {"t": np.zeros((1, 32), np.float32)})
    with GGUFReader(path2) as r:
        assert config_from_gguf(r).sliding_window is None


def test_tokenizer_from_gguf_bpe(tmp_path):
    path = str(tmp_path / "tok.gguf")
    # byte-level BPE: base vocab of the two words' bytes + merges
    vocab = ["h", "e", "l", "o", " ", "he", "hel", "hell", "hello"]
    merges = ["h e", "he l", "hel l", "hell o"]
    write_gguf(path, {
        "tokenizer.ggml.model": "gpt2",
        "tokenizer.ggml.tokens": vocab,
        "tokenizer.ggml.merges": merges,
    }, {"t": np.zeros((1, 32), np.float32)})
    with GGUFReader(path) as r:
        tok = tokenizer_from_gguf(r)
    ids = tok.encode("hello")
    assert ids == [vocab.index("hello")]
    assert tok.decode(ids) == "hello"


def test_load_params_and_forward(tmp_path):
    cfg = ModelConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64,
    )
    rng = np.random.default_rng(1)
    D, H, Hk, Dh = (cfg.hidden_size, cfg.num_attention_heads,
                    cfg.num_key_value_heads, cfg.head_dim)
    F, V, L = cfg.intermediate_size, cfg.vocab_size, cfg.num_hidden_layers

    def t(*shape):
        return (rng.standard_normal(shape) * 0.05).astype(np.float32)

    tensors = {
        "token_embd.weight": t(V, D),
        "output_norm.weight": np.ones((D,), np.float32),
        # no output.weight: exercises tied-embeddings fallback
    }
    for i in range(L):
        tensors.update({
            f"blk.{i}.attn_norm.weight": np.ones((D,), np.float32),
            f"blk.{i}.attn_q.weight": t(H * Dh, D),
            f"blk.{i}.attn_k.weight": t(Hk * Dh, D),
            f"blk.{i}.attn_v.weight": t(Hk * Dh, D),
            f"blk.{i}.attn_output.weight": t(D, H * Dh),
            f"blk.{i}.ffn_norm.weight": np.ones((D,), np.float32),
            f"blk.{i}.ffn_gate.weight": t(F, D),
            f"blk.{i}.ffn_up.weight": t(F, D),
            f"blk.{i}.ffn_down.weight": t(D, F),
        })
    path = str(tmp_path / "model.gguf")
    write_gguf(path, {"general.architecture": "llama"}, tensors,
               quantize={"blk.0.ffn_up.weight": GGML_Q8_0})
    with GGUFReader(path) as r:
        params = load_params_from_gguf(cfg, r)
    # wq stacks transposed per-layer projections
    np.testing.assert_allclose(
        np.asarray(params["wq"][1], np.float32),
        tensors["blk.1.attn_q.weight"].T, rtol=1e-2, atol=1e-2,
    )
    from dynamo_tpu.models.llama import forward, init_cache

    import jax.numpy as jnp

    bs = 4
    k, v = init_cache(cfg, 8, bs, dtype=jnp.float32)
    T = 6
    tables = np.zeros((1, 8), np.int32)
    tables[0, :2] = [1, 2]
    slots = np.array([tables[0, j // bs] * bs + j % bs for j in range(T)], np.int32)
    logits, _, _ = forward(
        cfg, params, k, v,
        np.arange(1, T + 1, dtype=np.int32)[None, :],
        np.arange(T, dtype=np.int32)[None, :],
        slots, tables, np.asarray([T], np.int32), np.asarray([T - 1], np.int32),
        bs,
    )
    assert np.isfinite(np.asarray(logits)).all()


async def test_engine_serves_gguf_model(tmp_path):
    """Single-file GGUF -> engine bring-up (config + weights from the
    file) -> generation."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    rng = np.random.default_rng(3)
    D, H, Hk, Dh, F, V, L = 16, 4, 2, 4, 32, 64, 2

    def t(*shape):
        return (rng.standard_normal(shape) * 0.05).astype(np.float32)

    tensors = {"token_embd.weight": t(V, D),
               "output_norm.weight": np.ones((D,), np.float32)}
    for i in range(L):
        tensors.update({
            f"blk.{i}.attn_norm.weight": np.ones((D,), np.float32),
            f"blk.{i}.attn_q.weight": t(H * Dh, D),
            f"blk.{i}.attn_k.weight": t(Hk * Dh, D),
            f"blk.{i}.attn_v.weight": t(Hk * Dh, D),
            f"blk.{i}.attn_output.weight": t(D, H * Dh),
            f"blk.{i}.ffn_norm.weight": np.ones((D,), np.float32),
            f"blk.{i}.ffn_gate.weight": t(F, D),
            f"blk.{i}.ffn_up.weight": t(F, D),
            f"blk.{i}.ffn_down.weight": t(D, F),
        })
    path = str(tmp_path / "m.gguf")
    write_gguf(path, {
        "general.architecture": "llama",
        "llama.embedding_length": D,
        "llama.block_count": L,
        "llama.attention.head_count": H,
        "llama.attention.head_count_kv": Hk,
        "llama.feed_forward_length": F,
        "llama.context_length": 64,
        "llama.vocab_size": V,
        "tokenizer.ggml.eos_token_id": 0,
    }, tensors)
    engine = await JaxEngine.launch(EngineConfig(
        model_path=path, model_name="gguf-test", num_blocks=16,
        block_size=4, max_batch_size=2,
    ))
    assert engine.model_config is not None
    assert engine.model_config.hidden_size == D
    req = PreprocessedRequest(
        request_id="g1", token_ids=[1, 2, 3, 4, 5],
        sampling=SamplingOptions(use_greedy=True),
        stop=StopConditions(max_tokens=4, ignore_eos=True),
    )
    toks = []
    async for item in engine.as_async_engine().generate(req, Context()):
        toks.extend(item.token_ids)
    assert len(toks) == 4 and all(0 <= t < V for t in toks)
    await engine.shutdown()


def test_reader_rejects_garbage(tmp_path):
    bad = tmp_path / "bad.gguf"
    bad.write_bytes(b"NOPE" + b"\x00" * 64)
    with pytest.raises(ValueError, match="not a GGUF"):
        GGUFReader(str(bad))


def test_kquant_roundtrip_all_formats(tmp_path):
    """Q4_0/Q5_0/Q4_K/Q5_K/Q6_K: encode -> file -> dequantize within
    each format's quantization error (reference: gguf/content.rs loads
    these via candle; ggml-quants.c defines the layouts)."""
    from dynamo_tpu.gguf.reader import (
        GGML_Q4_0,
        GGML_Q4_K,
        GGML_Q5_0,
        GGML_Q5_K,
        GGML_Q6_K,
    )

    rng = np.random.default_rng(3)
    w = rng.standard_normal((4, 512)).astype(np.float32)
    spread = float(np.ptp(w))
    # per-format worst-case step ~ spread/levels; allow 1.5 steps for
    # the two-level (super+sub) scale quantization of the k-quants
    tolerances = {
        # the symmetric formats lose one level on the positive side
        # (q-8 in [-8, 7]): up to a full step of one-sided error
        GGML_Q4_0: ("q4_0", spread / 15 * 1.25),
        GGML_Q5_0: ("q5_0", spread / 31 * 1.25),
        GGML_Q4_K: ("q4_k", spread / 15 * 1.5),
        GGML_Q5_K: ("q5_k", spread / 31 * 1.5),
        GGML_Q6_K: ("q6_k", spread / 63 * 1.5),
    }
    path = str(tmp_path / "kq.gguf")
    names = {f"t_{tag}": gt for gt, (tag, _) in tolerances.items()}
    write_gguf(path, {}, {n: w for n in names},
               quantize={n: gt for n, gt in names.items()})
    with GGUFReader(path) as r:
        for name, gt in names.items():
            deq = r.load(name)
            assert deq.shape == w.shape
            tol = tolerances[gt][1]
            err = np.abs(deq - w).max()
            assert err <= tol, f"{name}: max err {err} > {tol}"
            # and not degenerate: correlated with the source
            corr = np.corrcoef(deq.reshape(-1), w.reshape(-1))[0, 1]
            assert corr > 0.98, f"{name}: corr {corr}"


def test_q6k_scale_sign_and_block_edges(tmp_path):
    """Q6_K carries signed int8 sub-scales; values at block boundaries
    (positions 31/32, 127/128) must land in the right sub-blocks."""
    from dynamo_tpu.gguf.reader import GGML_Q6_K

    x = np.zeros((1, 256), np.float32)
    x[0, 0] = -5.0     # sub-block 0
    x[0, 31] = 5.0
    x[0, 32] = -3.0    # sub-block 2
    x[0, 127] = 2.0    # last sub-block of first half
    x[0, 128] = -7.0   # first sub-block of second half
    x[0, 255] = 1.0
    path = str(tmp_path / "q6.gguf")
    write_gguf(path, {}, {"t": x}, quantize={"t": GGML_Q6_K})
    with GGUFReader(path) as r:
        deq = r.load("t")
    for pos in (0, 31, 32, 127, 128, 255):
        assert abs(deq[0, pos] - x[0, pos]) <= abs(x[0, pos]) * 0.1 + 0.05, pos
    # zeros stay zero-ish
    assert np.abs(deq[0, 1:31]).max() < 0.2


async def test_engine_serves_q4k_gguf(tmp_path):
    """End-to-end: a Q4_K-quantized GGUF model serves through the native
    engine (the format practically every distributed GGUF uses)."""
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.gguf.reader import GGML_Q4_K
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    # dims multiple of 256 so every projection can be Q4_K
    cfg = ModelConfig(
        vocab_size=512, hidden_size=256, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128,
    )
    rng = np.random.default_rng(1)
    D, H, Hk, Dh = (cfg.hidden_size, cfg.num_attention_heads,
                    cfg.num_key_value_heads, cfg.head_dim)
    F, V, L = cfg.intermediate_size, cfg.vocab_size, cfg.num_hidden_layers

    def t(*shape):
        return (rng.standard_normal(shape) * 0.05).astype(np.float32)

    tensors = {
        "token_embd.weight": t(V, D),
        "output_norm.weight": np.ones((D,), np.float32),
    }
    quantize = {}
    for i in range(L):
        for gname, shape in (
            (f"blk.{i}.attn_q.weight", (H * Dh, D)),
            (f"blk.{i}.attn_k.weight", (Hk * Dh, D)),
            (f"blk.{i}.attn_v.weight", (Hk * Dh, D)),
            (f"blk.{i}.attn_output.weight", (D, H * Dh)),
            (f"blk.{i}.ffn_gate.weight", (F, D)),
            (f"blk.{i}.ffn_up.weight", (F, D)),
            (f"blk.{i}.ffn_down.weight", (D, F)),
        ):
            tensors[gname] = t(*shape)
            quantize[gname] = GGML_Q4_K
        tensors[f"blk.{i}.attn_norm.weight"] = np.ones((D,), np.float32)
        tensors[f"blk.{i}.ffn_norm.weight"] = np.ones((D,), np.float32)
    path = str(tmp_path / "q4k.gguf")
    write_gguf(path, {
        "general.architecture": "llama",
        "llama.vocab_size": V,
        "llama.embedding_length": D,
        "llama.block_count": L,
        "llama.attention.head_count": H,
        "llama.attention.head_count_kv": Hk,
        "llama.feed_forward_length": F,
        "llama.context_length": 128,
    }, tensors, quantize=quantize)

    engine = await JaxEngine.launch(
        EngineConfig(
            model_path=path, model_name="q4k",
            num_blocks=32, block_size=8, max_batch_size=2,
            kv_cache_dtype="float32",
        )
    )
    try:
        req = PreprocessedRequest(
            request_id="g", token_ids=list(range(1, 20)),
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=6, ignore_eos=True),
        )
        toks = []
        async for out in engine.as_async_engine().generate(req, Context()):
            toks.extend(out.token_ids)
        assert len(toks) == 6
        assert all(0 <= t < cfg.vocab_size for t in toks)
    finally:
        await engine.shutdown()
