"""Guided decoding (ISSUE 15; docs/guided_decoding.md): compiler units,
automaton-vs-reference fuzz over the real tokenizer vocab, engine e2e
(greedy + seeded-sampled completions parse against the schema), guided
spec bit-identity vs serial guided decode, the tool-call delta stream,
and the prewarmed-guided compile-fence acceptance case."""

import glob
import json
import os
import random
import re

import numpy as np
import pytest

from dynamo_tpu.guided.automaton import (
    GuidedState,
    TokenAutomaton,
    automaton_for,
    build_trie,
    normalize_spec,
)
from dynamo_tpu.guided.fsm import JsonAutomaton, compile_regex
from dynamo_tpu.guided.schema import compile_schema
from dynamo_tpu.guided.tools import (
    ToolCallStreamParser,
    forced_tool_name,
    tool_parameters_schema,
)
from dynamo_tpu.tokenizer import Tokenizer

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")

# bounded everywhere so a random-weights model always terminates the
# document inside a small token budget (strings capped, enum, boolean)
SCHEMA = {
    "type": "object",
    "properties": {
        "name": {"type": "string", "maxLength": 4},
        "ok": {"type": "boolean"},
        "mood": {"enum": ["happy", "sad"]},
    },
    "required": ["name", "ok", "mood"],
}


def _accepts(auto, s: str) -> bool:
    st = auto.start()
    for b in s.encode():
        st = auto.step(st, b)
        if st is None:
            return False
    return auto.is_final(st)


# ---------------------------------------------------------------------------
# byte-automaton units
# ---------------------------------------------------------------------------


def test_regex_fuzz_matches_re_fullmatch():
    """The regex subset compiles to a DFA that agrees with Python's
    ``re.fullmatch`` on random strings (the compiler's ground truth)."""
    patterns = [
        r"[a-z]+",
        r"\d{2,4}",
        r"(foo|bar)*baz?",
        r"a.c",
        r"[^0-9]{1,3}",
        r"-?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][+-]?[0-9]+)?",
        r"\w+@\w+\.(com|org)",
        r"^abc$",
        r"x{3}",
        r"(ab){1,2}c",
        r"[A-Fa-f0-9]{2}(:[A-Fa-f0-9]{2})*",
    ]
    rng = random.Random(0)
    alphabet = "abcxyz019.@-eE:fo r\n"
    for pat in patterns:
        dfa = compile_regex(pat)
        for _ in range(300):
            s = "".join(
                rng.choice(alphabet) for _ in range(rng.randrange(0, 8))
            )
            assert _accepts(dfa, s) == (re.fullmatch(pat, s) is not None), (
                pat, s,
            )


def test_regex_rejects_unsupported_syntax():
    for bad in [r"a{1,500}", r"(?P<x>a)", r"a|*", r"[z-a]", r"ab$cd",
                "[é]"]:  # classes are byte sets; non-ASCII members lie
        with pytest.raises(ValueError):
            compile_regex(bad)
    # non-ASCII literals match their full byte sequence via alternation
    dfa = compile_regex("(é|è)x")
    assert _accepts(dfa, "éx") and _accepts(dfa, "èx")
    assert not _accepts(dfa, "\xc3x")  # a lone lead byte is not é


def test_json_object_automaton():
    ja = JsonAutomaton()
    good = [
        "{}",
        '{"a": 1}',
        '{"a": [1, 2.5, -3e2], "b": {"c": null}}',
        '{"s": "he\\"llo", "t": true} ',
        '{ "k" : [ ] }',
        '{"u": "\\u00e9"}',
    ]
    bad = [
        "",
        "[1]",  # json_object mode: top level must be an object
        '{"a": }',
        '{"a": 1,}',
        '{a: 1}',
        '{"a": 01}',
        '{"a": 1} x',
        '{"a": "unterminated',
        '{"a": 1 "b": 2}',
    ]
    for g in good:
        assert _accepts(ja, g), g
    for b in bad:
        assert not _accepts(ja, b), b
    # depth bound: opening past MAX_JSON_DEPTH is disallowed
    deep = JsonAutomaton(max_depth=3)
    assert _accepts(deep, '{"a": {"b": 1}}')
    assert not _accepts(deep, '{"a": {"b": {"c": {"d": 1}}}}')


def test_schema_compiler_accepts_and_rejects():
    schema = {
        "$defs": {"tag": {"type": "string", "maxLength": 3}},
        "type": "object",
        "properties": {
            "name": {"type": "string", "maxLength": 8},
            "age": {"type": "integer"},
            "tags": {
                "type": "array",
                "items": {"$ref": "#/$defs/tag"},
                "maxItems": 3,
            },
            "mood": {"enum": ["happy", "sad"]},
            "extra": {"anyOf": [{"type": "null"}, {"type": "number"}]},
        },
        "required": ["name", "age"],
    }
    dfa = compile_schema(schema)
    good = [
        '{"name": "bob", "age": 3}',
        '{"name":"a","age":-12,"tags":["x","yz"],"mood":"sad"}',
        '{"name":"a","age":0,"mood":"happy","extra":null}',
        '{"name":"a","age":7,"extra":-1.5e3}',
    ]
    bad = [
        '{"age": 3}',  # missing required
        '{"name":"bob"}',
        '{"name":"bob","age":3.5}',  # float for integer
        '{"age":3,"name":"bob"}',  # declared property order enforced
        '{"name":"toolongname","age":1}',
        '{"name":"b","age":1,"tags":["wxyz"]}',  # item too long
        '{"name":"b","age":1,"mood":"angry"}',
        '{"name":"b","age":1,"tags":["a","b","c","d"]}',  # maxItems
    ]
    for g in good:
        assert _accepts(dfa, g), g
        json.loads(g)  # the fixtures themselves are valid JSON
    for b in bad:
        assert not _accepts(dfa, b), b


def test_schema_pattern_cannot_break_string_framing():
    """Review fix: metacharacter patterns (., [^...], \\S) are
    intersected with string-legal content bytes, so they can never
    admit a raw quote/backslash that would terminate the JSON string
    early; patterns REQUIRING such a byte are rejected at compile."""
    dfa = compile_schema({
        "type": "object",
        "properties": {"v": {"type": "string", "pattern": ".+"}},
        "required": ["v"],
    })
    assert _accepts(dfa, '{"v": "ab c"}')
    # a raw quote inside the pattern-matched body is NOT mask-legal
    # (the '.' edge was stripped of 0x22/0x5C/control bytes)
    assert not _accepts(dfa, '{"v": "a"b"}')
    assert not _accepts(dfa, '{"v": "a\\z"}')  # raw backslash in body
    for pat in [r'a"b', r"a\\b"]:
        with pytest.raises(ValueError):
            compile_schema({
                "type": "object",
                "properties": {"v": {"type": "string", "pattern": pat}},
                "required": ["v"],
            })
    # a class that PARTIALLY strips stays satisfiable on the legal
    # subset: ["x] degrades to [x] (subset semantics, not an error)
    dfa = compile_schema({
        "type": "object",
        "properties": {"v": {"type": "string", "pattern": r'["x]'}},
        "required": ["v"],
    })
    assert _accepts(dfa, '{"v": "x"}')
    assert not _accepts(dfa, '{"v": """}')


def test_schema_compiler_rejects_unsupported():
    for bad in [
        {"allOf": [{"type": "string"}]},
        {"enum": []},
        {},  # unconstrained subschema
        {"type": "object", "properties": {"a": {}}, "required": ["a"]},
        {"type": "object", "required": ["ghost"]},
        {"$ref": "#/external/thing"},
    ]:
        with pytest.raises(ValueError):
            compile_schema(bad)


# ---------------------------------------------------------------------------
# token layer: automaton-vs-reference fuzz over the REAL tokenizer vocab
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tok():
    return Tokenizer.from_file(MODEL_DIR)


def _naive_mask(auto: TokenAutomaton, state) -> np.ndarray:
    """Reference mask: re-validate EVERY token id by walking its bytes
    through the byte automaton from ``state`` — the O(V * len) path the
    trie walk exists to avoid."""
    m = np.zeros((auto.vocab_pad,), dtype=bool)
    for tid in range(auto.vocab_pad):
        if auto.token_step(state, tid) is not None:
            m[tid] = True
    if auto.is_final(state):
        for e in auto.eos_ids:
            m[e] = True
    return m


@pytest.mark.parametrize(
    "spec",
    [
        {"kind": "json_schema", "json_schema": SCHEMA},
        {"kind": "regex", "regex": r"(yes|no), [0-9]{3}"},
        {"kind": "json_object"},
    ],
    ids=["json_schema", "regex", "json_object"],
)
def test_mask_matches_naive_revalidation_fuzz(tok, spec):
    """THE automaton-vs-reference fuzz (ISSUE 15 satellite): at every
    state along random mask-legal walks, the trie-computed vocab mask
    equals a naive per-token re-validation over the real tokenizer
    vocabulary."""
    auto = automaton_for(spec, tok, MODEL_DIR, 2048, {4})
    rng = random.Random(2026)
    for _walk in range(4):
        state = auto.start_state()
        for _step in range(16):
            fast = auto.mask(state)
            slow = _naive_mask(auto, state)
            diff = np.flatnonzero(fast != slow)
            assert diff.size == 0, (
                f"mask mismatch at ids {diff[:8].tolist()} "
                f"(walk state {state!r})"
            )
            choices = [
                t for t in np.flatnonzero(fast).tolist()
                if t not in auto.eos_ids
            ]
            if not choices:
                break
            nxt = rng.choice(choices)
            state = auto.token_step(state, nxt)
            assert state is not None


def test_guided_state_advance_eos_and_done(tok):
    auto = automaton_for(
        {"kind": "regex", "regex": "ab"}, tok, MODEL_DIR, 2048, {4}
    )
    gs = GuidedState(auto)
    a, b = tok.encode("a")[0], tok.encode("b")[0]
    assert gs.allow_mask()[a] and not gs.allow_mask()[4]
    gs.advance(a)
    gs.advance(b)
    # document complete: only stopping is legal
    m = gs.allow_mask()
    assert m[4] and m.sum() == 1
    gs.advance(4)
    assert gs.done and not gs.broken
    # drafts filter through the automaton (and never propose eos)
    gs2 = GuidedState(auto)
    # 'ab' accepted; the third draft ('aba' is illegal) is cut
    assert gs2.filter_drafts([a, b, a]) == [a, b]
    assert gs2.filter_drafts([b]) == []  # 'b' illegal at the start
    masks = gs2.masks_for_drafts([a])
    assert masks.shape == (2, 2048)
    assert masks[0][a] and masks[1][b] and not masks[1][a]


def test_compile_cache_hits_and_metrics(tok):
    from dynamo_tpu.telemetry import REGISTRY

    spec = {"kind": "json_schema", "json_schema": {
        "type": "object",
        "properties": {"cachekey": {"type": "boolean"}},
        "required": ["cachekey"],
    }}
    a1 = automaton_for(spec, tok, MODEL_DIR, 2048, {4})
    a2 = automaton_for(dict(spec), tok, MODEL_DIR, 2048, {4})
    assert a1 is a2  # LRU hit on the canonicalized spec key
    text = REGISTRY.render()
    assert 'dynamo_guided_cache_events_total{result="hit"}' in text
    assert 'dynamo_guided_cache_events_total{result="miss"}' in text
    assert "dynamo_guided_compile_seconds" in text


def test_vocab_larger_than_model_rejected_at_compile(tok):
    """Review fix: a tokenizer vocab larger than the model head fails
    the REQUEST at automaton compile (admission), never as an
    IndexError inside mask() on the engine step path."""
    with pytest.raises(ValueError, match="exceeds the model vocab"):
        automaton_for(
            {"kind": "json_object"}, tok, MODEL_DIR, tok.vocab_size - 1,
            {4},
        )


def test_normalize_spec_rejects_malformed():
    for bad in [
        None,
        {"kind": "json_schema"},
        {"kind": "regex"},
        {"kind": "mystery"},
    ]:
        with pytest.raises(ValueError):
            normalize_spec(bad)


def test_trie_excludes_special_tokens():
    trie = build_trie([b"ab", None, b"a", b""])
    assert trie.children[ord("a")].ids == [2]
    assert trie.children[ord("a")].children[ord("b")].ids == [0]


# ---------------------------------------------------------------------------
# tool-call streaming parser
# ---------------------------------------------------------------------------


def test_tool_parser_forced_mode_streams_arguments():
    p = ToolCallStreamParser(forced_name="get_weather")
    evs = p.feed('{"city": "Par') + p.feed('is"}') + p.finish()
    assert evs[0].kind == "tool_start" and evs[0].value == "get_weather"
    args = "".join(e.value for e in evs if e.kind == "tool_args")
    assert json.loads(args) == {"city": "Paris"}
    assert p.tool_call_detected


def test_tool_parser_detects_inline_call_across_chunks():
    p = ToolCallStreamParser()
    chunks = ['{"na', 'me": "f", "argu', 'ments": {"x": "a}b", "n": {"m": 1}}}']
    evs = []
    for c in chunks:
        evs += p.feed(c)
    evs += p.finish()
    assert p.tool_call_detected
    assert [e.value for e in evs if e.kind == "tool_start"] == ["f"]
    args = "".join(e.value for e in evs if e.kind == "tool_args")
    # brace tracking is string-aware: "a}b" did not close the object
    assert json.loads(args) == {"x": "a}b", "n": {"m": 1}}


def test_tool_parser_flushes_plain_text_untouched():
    p = ToolCallStreamParser()
    evs = p.feed("Hello ") + p.feed("world") + p.finish()
    assert not p.tool_call_detected
    assert "".join(e.value for e in evs if e.kind == "text") == "Hello world"
    # near-miss prefix: buffers, then flushes intact on mismatch
    p2 = ToolCallStreamParser()
    evs2 = p2.feed('{"nam') + p2.feed('ing": 1}') + p2.finish()
    assert not p2.tool_call_detected
    assert "".join(e.value for e in evs2 if e.kind == "text") == '{"naming": 1}'


def test_tool_parser_non_object_arguments_degrade_with_no_header():
    """Review fix: the tool_start header is deferred until the
    arguments value proves to be an object — `"arguments": null`
    replays as plain text with NO phantom call header."""
    p = ToolCallStreamParser()
    evs = p.feed('{"name": "f", "arguments": null}') + p.finish()
    assert not p.tool_call_detected
    assert [e.kind for e in evs] == ["text"]
    assert evs[0].value == '{"name": "f", "arguments": null}'
    # a header whose args object never arrives flushes intact at finish
    p2 = ToolCallStreamParser()
    assert p2.feed('{"name": "f", "arguments": ') == []
    evs2 = p2.finish()
    assert not p2.tool_call_detected
    assert "".join(e.value for e in evs2) == '{"name": "f", "arguments": '


def test_tool_parser_arguments_complete_tracking():
    """Review fix: only a CLOSED arguments object counts as complete —
    forced and auto mode alike."""
    p = ToolCallStreamParser(forced_name="f")
    p.feed('{"a": {"b": 1}')
    assert p.tool_call_detected and not p.arguments_complete
    p.feed("}")
    assert p.arguments_complete
    p2 = ToolCallStreamParser()
    p2.feed('{"name": "f", "arguments": {"a": 1')
    assert p2.tool_call_detected and not p2.arguments_complete
    p2.feed("}}")
    assert p2.arguments_complete


def test_tool_parser_buffer_bound_and_unfinished_prefix():
    p = ToolCallStreamParser()
    big = "x" * 300
    evs = p.feed(big)
    assert "".join(e.value for e in evs if e.kind == "text") == big
    # a stream that ENDS mid-detection flushes at finish()
    p2 = ToolCallStreamParser()
    assert p2.feed('{"name": "par') == []
    evs2 = p2.finish()
    assert "".join(e.value for e in evs2 if e.kind == "text") == '{"name": "par'


def test_forced_tool_name_and_parameters_lookup():
    tools = [
        {"type": "function", "function": {
            "name": "f", "parameters": {"type": "object", "properties": {}},
        }},
    ]
    assert forced_tool_name(
        {"type": "function", "function": {"name": "f"}}, tools
    ) == "f"
    assert forced_tool_name({"name": "f"}, tools) == "f"
    assert forced_tool_name("required", tools) == "f"
    assert forced_tool_name("auto", tools) is None
    assert forced_tool_name(None, tools) is None
    assert tool_parameters_schema(tools, "f") == {
        "type": "object", "properties": {},
    }
    assert tool_parameters_schema(tools, "ghost") is None


# ---------------------------------------------------------------------------
# OpenAI adaptation: response_format / tools -> GuidedOptions
# ---------------------------------------------------------------------------


def test_guided_options_adaptation():
    from dynamo_tpu.protocols.openai import (
        ChatCompletionRequest,
        guided_options,
    )

    base = {"model": "m", "messages": [{"role": "user", "content": "hi"}]}
    assert guided_options(ChatCompletionRequest(**base)) is None
    g = guided_options(ChatCompletionRequest(
        **base, response_format={"type": "json_object"},
    ))
    assert g.kind == "json_object"
    g = guided_options(ChatCompletionRequest(
        **base,
        response_format={
            "type": "json_schema",
            "json_schema": {"name": "s", "schema": SCHEMA},
        },
    ))
    assert g.kind == "json_schema" and g.json_schema == SCHEMA
    # a forcing tool_choice wins: the tool's parameters schema guides
    g = guided_options(ChatCompletionRequest(
        **base,
        tools=[{"type": "function",
                "function": {"name": "f", "parameters": SCHEMA}}],
        tool_choice={"type": "function", "function": {"name": "f"}},
    ))
    assert g.kind == "json_schema" and g.json_schema == SCHEMA
    # per-request opt-out mirrors ext.speculative
    assert guided_options(ChatCompletionRequest(
        **base,
        response_format={"type": "json_object"},
        ext={"guided": False},
    )) is None
    # engine regex extension
    g = guided_options(ChatCompletionRequest(
        **base, ext={"guided_regex": "[0-9]+"},
    ))
    assert g.kind == "regex" and g.regex == "[0-9]+"
    with pytest.raises(ValueError):
        guided_options(ChatCompletionRequest(
            **base, response_format={"type": "json_schema"},
        ))
    with pytest.raises(ValueError):
        guided_options(ChatCompletionRequest(
            **base, response_format={"type": "grammar"},
        ))


def test_preprocessor_wires_guided_and_migration_refuses_it(tok):
    from dynamo_tpu.preprocessor.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.protocols.openai import CompletionRequest
    from dynamo_tpu.runtime.migration import resumable

    pre = OpenAIPreprocessor(tok, formatter=None, model_name="tiny")
    req = pre.preprocess_completion(CompletionRequest(
        model="tiny", prompt="ab",
        response_format={"type": "json_object"},
    ))
    assert req.guided is not None and req.guided.kind == "json_object"
    # guided requests are not migratable (docs/guided_decoding.md)
    assert resumable(req) is False
    plain = pre.preprocess_completion(
        CompletionRequest(model="tiny", prompt="ab")
    )
    assert plain.guided is None and resumable(plain) is True


# ---------------------------------------------------------------------------
# SSE tool-call delta stream e2e (preprocessor backward)
# ---------------------------------------------------------------------------


async def _collect_backward(pre, state, items):
    async def stream():
        for it in items:
            yield it

    from dynamo_tpu.runtime.engine import Context

    return [c async for c in pre.backward(stream(), state, Context())]


async def test_tool_call_delta_stream_e2e(tok):
    """ISSUE 15 satellite: the streamed chunk sequence reassembles to
    valid JSON arguments with finish_reason == "tool_calls" — both
    forced mode and auto-detection."""
    from dynamo_tpu.preprocessor.preprocessor import (
        OpenAIPreprocessor,
        _ReqState,
    )
    from dynamo_tpu.protocols.common import FinishReason, LLMEngineOutput

    pre = OpenAIPreprocessor(tok, formatter=None, model_name="tiny")

    def mk_state(mode, name=None):
        return _ReqState(
            kind="chat", model="tiny", request_id="r", prompt_tokens=3,
            include_usage=True, logprobs=False, tool_mode=mode,
            tool_name=name,
        )

    def items(texts, reason=FinishReason.STOP):
        out = [
            LLMEngineOutput(request_id="r", token_ids=[1], text=t)
            for t in texts
        ]
        out.append(LLMEngineOutput(
            request_id="r", finish_reason=reason,
            prompt_tokens=3, completion_tokens=len(texts),
        ))
        return out

    # forced: every delta is an arguments fragment
    chunks = await _collect_backward(
        pre, mk_state("forced", "get_weather"),
        items(['{"city": ', '"Paris"', "}"]),
    )
    tool_deltas = [
        tc
        for c in chunks
        for ch in c.choices
        if ch.delta.tool_calls
        for tc in ch.delta.tool_calls
    ]
    header = tool_deltas[0]
    assert header["function"]["name"] == "get_weather"
    assert header["id"].startswith("call_") and header["type"] == "function"
    args = "".join(
        tc["function"].get("arguments", "") for tc in tool_deltas
    )
    assert json.loads(args) == {"city": "Paris"}
    finishes = [
        ch.finish_reason
        for c in chunks
        for ch in c.choices
        if ch.finish_reason
    ]
    assert finishes == ["tool_calls"]
    usage = [c.usage for c in chunks if c.usage is not None]
    assert usage and usage[0].completion_tokens == 3

    # auto-detection on the inline-JSON call shape
    chunks = await _collect_backward(
        pre, mk_state("auto"),
        items(['{"name": "f", "argu', 'ments": {"x": 1}}']),
    )
    tool_deltas = [
        tc
        for c in chunks
        for ch in c.choices
        if ch.delta.tool_calls
        for tc in ch.delta.tool_calls
    ]
    assert tool_deltas[0]["function"]["name"] == "f"
    args = "".join(
        tc["function"].get("arguments", "") for tc in tool_deltas
    )
    assert json.loads(args) == {"x": 1}
    assert [
        ch.finish_reason for c in chunks for ch in c.choices
        if ch.finish_reason
    ] == ["tool_calls"]

    # auto mode, plain text: content deltas untouched, normal finish
    chunks = await _collect_backward(
        pre, mk_state("auto"), items(["Hello ", "world"]),
    )
    text = "".join(
        ch.delta.content or "" for c in chunks for ch in c.choices
    )
    assert text == "Hello world"
    assert [
        ch.finish_reason for c in chunks for ch in c.choices
        if ch.finish_reason
    ] == ["stop"]

    # a call truncated by max_tokens mid-arguments keeps "length"
    # (OpenAI semantics) — clients must not json.loads the fragment
    chunks = await _collect_backward(
        pre, mk_state("forced", "g"),
        items(['{"a": tr'], reason=FinishReason.LENGTH),
    )
    assert [
        ch.finish_reason for c in chunks for ch in c.choices
        if ch.finish_reason
    ] == ["length"]
    # ... and an eos mid-arguments (auto mode: nothing forces the model
    # to close the object) keeps "stop", never "tool_calls"
    chunks = await _collect_backward(
        pre, mk_state("auto"),
        items(['{"name": "g", "arguments": {"a": 1'],
              reason=FinishReason.STOP),
    )
    assert [
        ch.finish_reason for c in chunks for ch in c.choices
        if ch.finish_reason
    ] == ["stop"]

    # non-streaming aggregation folds the deltas into message.tool_calls
    from dynamo_tpu.protocols.aggregators import ChatAggregator

    chunks = await _collect_backward(
        pre, mk_state("forced", "g"), items(['{"a": true}']),
    )
    resp = ChatAggregator.aggregate(chunks)
    msg = resp.choices[0].message
    assert msg.content is None
    assert msg.tool_calls[0]["function"]["name"] == "g"
    assert json.loads(msg.tool_calls[0]["function"]["arguments"]) == {
        "a": True,
    }
    assert resp.choices[0].finish_reason == "tool_calls"


# ---------------------------------------------------------------------------
# engine e2e: greedy + seeded-sampled guided completions parse; guided
# spec decode is bit-identical to serial guided decode
# ---------------------------------------------------------------------------


def _engine_config(**kw):
    from dynamo_tpu.engine.config import EngineConfig

    defaults = dict(
        model_path=MODEL_DIR,
        model_name="tiny",
        random_weights=True,
        num_blocks=128,
        block_size=8,
        max_batch_size=8,
        prefill_chunk_size=32,
        max_model_len=512,
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


async def _generate(engine, rid, guided=None, temperature=None,
                    max_tokens=150, speculative=None, prompt=(1, 2, 3, 4, 5)):
    from dynamo_tpu.protocols.common import (
        GuidedOptions,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    sampling = (
        SamplingOptions(use_greedy=True)
        if temperature is None
        else SamplingOptions(temperature=temperature, seed=11)
    )
    req = PreprocessedRequest(
        request_id=rid,
        token_ids=list(prompt),
        sampling=sampling,
        stop=StopConditions(max_tokens=max_tokens),
        guided=GuidedOptions(**guided) if guided else None,
        speculative=speculative,
    )
    toks, fin = [], None
    async for item in engine.as_async_engine().generate(req, Context()):
        toks.extend(item.token_ids)
        if item.is_final:
            fin = item.finish_reason
    return toks, fin


async def test_engine_guided_greedy_and_sampled_parse(tok):
    """ISSUE 15 acceptance: a JSON-schema request returns output that
    parses and validates against the schema under greedy AND seeded
    sampling; a regex request fullmatches; seeded sampling is
    deterministic; /metrics carries the guided series."""
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.telemetry import REGISTRY

    engine = await JaxEngine.launch(_engine_config())
    g = {"kind": "json_schema", "json_schema": SCHEMA}
    try:
        toks, _ = await _generate(engine, "greedy", guided=g)
        doc = json.loads(tok.decode(toks, skip_special_tokens=True))
        assert isinstance(doc["name"], str) and len(doc["name"]) <= 4
        assert isinstance(doc["ok"], bool)
        assert doc["mood"] in ("happy", "sad")
        s1, _ = await _generate(engine, "samp", guided=g, temperature=0.9)
        d2 = json.loads(tok.decode(s1, skip_special_tokens=True))
        assert d2["mood"] in ("happy", "sad") and isinstance(d2["ok"], bool)
        s2, _ = await _generate(engine, "samp", guided=g, temperature=0.9)
        assert s1 == s2  # same request id + seed => same stream
        rx = r"(yes|no), [0-9]{3}"
        toks, _ = await _generate(
            engine, "rx", guided={"kind": "regex", "regex": rx},
        )
        assert re.fullmatch(rx, tok.decode(toks, skip_special_tokens=True))
        # unguided traffic on the same engine is unaffected
        toks, fin = await _generate(engine, "plain", max_tokens=6)
        assert len(toks) == 6
    finally:
        await engine.shutdown()
    text = REGISTRY.render()
    assert 'dynamo_guided_requests_total{kind="json_schema"}' in text
    assert 'dynamo_guided_requests_total{kind="regex"}' in text


async def test_engine_guided_spec_bit_identical(tok):
    """ISSUE 15 acceptance: guided spec decode is bit-identical to
    serial guided decode (the per-request spec opt-out IS the literal
    serial masked path), with drafts genuinely proposed through the
    automaton filter; seeded-sampled guided spec is deterministic and
    schema-valid."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(
        _engine_config(spec_decode="ngram", spec_tokens=4)
    )
    g = {"kind": "json_schema", "json_schema": SCHEMA}
    prompt = (1, 2, 3, 4, 5, 6, 1, 2, 3, 4, 5, 6, 1, 2, 3)
    try:
        spec_toks, _ = await _generate(
            engine, "spec", guided=g, prompt=prompt, max_tokens=120,
        )
        base_toks, _ = await _generate(
            engine, "base", guided=g, prompt=prompt, max_tokens=120,
            speculative=False,
        )
        assert spec_toks == base_toks
        assert engine.spec_proposed_total > 0  # drafting really happened
        json.loads(tok.decode(spec_toks, skip_special_tokens=True))
        s1, _ = await _generate(
            engine, "samp", guided=g, prompt=prompt, temperature=0.9,
            max_tokens=120,
        )
        s2, _ = await _generate(
            engine, "samp", guided=g, prompt=prompt, temperature=0.9,
            max_tokens=120,
        )
        assert s1 == s2
        json.loads(tok.decode(s1, skip_special_tokens=True))
    finally:
        await engine.shutdown()


async def test_engine_rejects_guided_on_fused_windows():
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_config(decode_steps=4))
    try:
        with pytest.raises(ValueError, match="decode_steps"):
            await _generate(
                engine, "bad", guided={"kind": "json_object"}, max_tokens=4,
            )
    finally:
        await engine.shutdown()


async def test_http_guided_sse_e2e(tok):
    """Full-stack HTTP e2e: (a) a streaming request with an
    uncompilable schema is a 400, not a 200 SSE stream (the primed
    first chunk surfaces admission failures before headers commit);
    (b) a valid json_schema SSE stream reassembles to schema-valid
    JSON; (c) a forced tool call streams tool_calls deltas whose
    arguments reassemble and finish with "tool_calls"."""
    import aiohttp

    from dynamo_tpu.backend import Backend
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.http.service import HttpService, ModelManager
    from dynamo_tpu.preprocessor import OpenAIPreprocessor, PromptFormatter
    from dynamo_tpu.preprocessor.fanout import ChoiceFanout
    from dynamo_tpu.protocols.sse import SseDecoder
    from dynamo_tpu.runtime.pipeline import build_pipeline

    engine = await JaxEngine.launch(_engine_config())
    formatter = PromptFormatter.from_model_dir(MODEL_DIR)
    pre = OpenAIPreprocessor(tok, formatter, model_name="tiny")
    pipeline = build_pipeline(
        pre,
        ChoiceFanout(build_pipeline(
            Backend(tok, eos_token_ids=engine.eos_token_ids),
            engine.as_async_engine(),
        )),
    )
    manager = ModelManager()
    manager.add_chat_model("tiny", pipeline)
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    base = f"http://127.0.0.1:{service.port}"

    async def sse_events(r):
        dec = SseDecoder()
        out = []
        async for chunk, _ in r.content.iter_chunks():
            for msg in dec.feed(chunk.decode()):
                if msg.data and msg.data != "[DONE]":
                    out.append(json.loads(msg.data))
        return out

    try:
        async with aiohttp.ClientSession() as s:
            # (a) uncompilable schema (allOf) under stream=true -> 400
            async with s.post(f"{base}/v1/chat/completions", json={
                "model": "tiny",
                "messages": [{"role": "user", "content": "x"}],
                "stream": True, "max_tokens": 8,
                "response_format": {"type": "json_schema", "json_schema": {
                    "name": "bad",
                    "schema": {"allOf": [{"type": "string"}]},
                }},
            }) as r:
                assert r.status == 400
                body = await r.json()
                assert body["error"]["type"] == "invalid_request_error"
            # (b) valid schema SSE stream -> schema-valid JSON
            async with s.post(f"{base}/v1/chat/completions", json={
                "model": "tiny",
                "messages": [{"role": "user", "content": "person"}],
                "stream": True, "max_tokens": 150,
                "response_format": {"type": "json_schema", "json_schema": {
                    "name": "person", "schema": SCHEMA,
                }},
            }) as r:
                assert r.status == 200
                events = await sse_events(r)
            text = "".join(
                ch["delta"].get("content") or ""
                for e in events for ch in e.get("choices", [])
            )
            doc = json.loads(text)
            assert doc["mood"] in ("happy", "sad")
            assert isinstance(doc["ok"], bool)
            # (c) forced tool call over SSE
            async with s.post(f"{base}/v1/chat/completions", json={
                "model": "tiny",
                "messages": [{"role": "user", "content": "weather"}],
                "stream": True, "max_tokens": 150,
                "tools": [{"type": "function", "function": {
                    "name": "get_weather",
                    "parameters": {
                        "type": "object",
                        "properties": {
                            "city": {"type": "string", "maxLength": 5},
                            "units": {"enum": ["c", "f"]},
                        },
                        "required": ["city", "units"],
                    },
                }}],
                "tool_choice": {
                    "type": "function", "function": {"name": "get_weather"},
                },
            }) as r:
                assert r.status == 200
                events = await sse_events(r)
            name = None
            args = ""
            finishes = []
            for e in events:
                for ch in e.get("choices", []):
                    if ch.get("finish_reason"):
                        finishes.append(ch["finish_reason"])
                    for tc in (ch["delta"].get("tool_calls") or []):
                        fn = tc.get("function") or {}
                        if fn.get("name"):
                            name = fn["name"]
                        args += fn.get("arguments", "")
            assert name == "get_weather" and finishes == ["tool_calls"]
            doc = json.loads(args)
            assert doc["units"] in ("c", "f") and len(doc["city"]) <= 5
    finally:
        await service.stop()
        await engine.shutdown()


@pytest.fixture
def fence():
    from dynamo_tpu.utils import compile_fence

    compile_fence.set_mode("fatal")
    compile_fence.reset()
    yield compile_fence
    compile_fence.set_mode(None)
    compile_fence.reset()


async def test_guided_prewarm_is_compile_fence_clean(tmp_path, fence):
    """ISSUE 15 acceptance: a prewarmed guided run produces ZERO
    serve_compile records under the FATAL fence — the masked prefill
    and decode variants _prewarm_guided compiles are exactly the
    signatures guided serving reaches."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_config(
        prewarm=True, prewarm_guided=True, overlap=False,
        flight_dump_dir=str(tmp_path),
    ))
    try:
        assert fence.stats()["events_total"] == 0  # prewarm sanctioned
        toks, _ = await _generate(
            engine, "g", guided={"kind": "json_schema", "json_schema": SCHEMA},
            max_tokens=100,
        )
        assert toks
        recs = [
            r for r in engine.recorder.snapshot(256)
            if r["kind"] == "serve_compile"
        ]
        assert recs == [], recs
        assert glob.glob(str(tmp_path / "dynamo_blackbox_*")) == []
    finally:
        await engine.shutdown()
