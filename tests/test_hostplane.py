"""Host data-plane observability (ISSUE 17): the event-loop lag
monitor, the per-stream host-cost ledger, the /debug/hostplane
surface, the fan-out bench gate, and the `top` host columns —
docs/observability.md "Host data plane"."""

import asyncio
import json
import os
import subprocess
import sys
import time
from typing import Any, AsyncIterator

import aiohttp

from dynamo_tpu.http.service import HttpService, ModelManager
from dynamo_tpu.protocols.common import FinishReason
from dynamo_tpu.protocols.openai import ChatCompletionRequest, ChatDeltaGenerator
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineStream
from dynamo_tpu.telemetry import REGISTRY
from dynamo_tpu.telemetry.attribution import BlackBox
from dynamo_tpu.telemetry.hostplane import (
    LEDGER,
    STAGES,
    HostCostLedger,
    LoopLagMonitor,
    collect_hostplane,
    note_stage,
    register_hostplane_provider,
    task_census,
    unregister_hostplane_provider,
)
from dynamo_tpu.telemetry.recorder import FlightRecorder

from tests.prom_parser import parse as prom_parse

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# LoopLagMonitor units (injectable clock — no real sleeping)
# ---------------------------------------------------------------------------
class FakeClock:
    """utils/clock.Clock implementation on virtual time; ``sleep``
    returns immediately, advancing by the requested span plus the
    injected per-sleep lag (one event-loop yield keeps the heartbeat
    cooperative instead of spinning)."""

    def __init__(self):
        self.t = 100.0
        self.extra_lag = 0.0

    def monotonic(self) -> float:
        return self.t

    async def sleep(self, seconds: float) -> None:
        self.t += seconds + self.extra_lag
        await asyncio.sleep(0)


def test_note_lag_window_and_percentiles():
    clk = FakeClock()
    mon = LoopLagMonitor(interval_s=0.01, window=64, clock=clk)
    for i in range(100):
        mon.note_lag(0.001 * (i % 10))
    snap = mon.snapshot()
    assert snap["beats"] == 100
    # window bounded: only the last 64 lags back the summary
    assert snap["lag"]["max_ms"] == 9.0
    assert 0.0 <= snap["lag"]["p50_ms"] <= 9.0
    assert snap["lag"]["p50_ms"] <= snap["lag"]["p99_ms"] <= 9.0
    assert snap["last_lag_ms"] == 9.0
    assert snap["stalls"] == 0 and snap["running"] is False


def test_note_lag_negative_clamped_and_reset_window():
    mon = LoopLagMonitor(interval_s=0.01, clock=FakeClock())
    mon.note_lag(-0.5)  # clock jitter must not mint negative lag
    assert mon.snapshot()["lag"]["max_ms"] == 0.0
    mon.note_lag(0.02)
    assert mon.snapshot()["lag"]["max_ms"] == 20.0
    mon.reset_window()
    snap = mon.snapshot()
    # beats keep counting; the window (and its summary) start over
    assert snap["beats"] == 2 and snap["lag"]["max_ms"] == 0.0


def test_stall_fires_exactly_one_bundle_per_holdoff(tmp_path):
    clk = FakeClock()
    rec = FlightRecorder(
        capacity=16, dump_dir=str(tmp_path), min_dump_interval_s=0.0
    )
    bb = BlackBox(
        recorder=rec, dump_dir=str(tmp_path), min_interval_s=0.0
    )
    mon = LoopLagMonitor(
        interval_s=0.01, stall_s=0.05, holdoff_s=60.0,
        recorder=rec, blackbox=bb, clock=clk,
    )
    d1 = mon.note_lag(0.08)  # stall -> bundle
    d2 = mon.note_lag(0.09)  # still inside the holdoff -> suppressed
    assert d1 is not None and d2 is None
    bb.flush()
    assert bb.stats()["dumps"] == 1
    with open(os.path.join(d1, "meta.json")) as f:
        assert json.load(f)["reason"] == "loop_stall"
    snap = mon.snapshot()
    assert snap["stalls"] == 2  # every stall counts, one bundle fires
    assert snap["blackbox"]["dumps"] == 1
    # the flight ring carries the loop_stall record
    kinds = [r["kind"] for r in rec.snapshot(16)]
    assert "loop_stall" in kinds
    # advancing the virtual clock past the holdoff re-arms the watchdog
    clk.t += 61.0
    d3 = mon.note_lag(0.07)
    assert d3 is not None
    bb.flush()
    assert bb.stats()["dumps"] == 2


async def test_heartbeat_measures_injected_lag_on_virtual_time():
    clk = FakeClock()
    clk.extra_lag = 0.25
    mon = LoopLagMonitor(interval_s=0.01, clock=clk)
    mon.start()
    mon.start()  # idempotent: one heartbeat task, not two
    try:
        for _ in range(20):
            await asyncio.sleep(0)
        snap = mon.snapshot()
        assert snap["running"] is True
        assert snap["beats"] >= 1
        # every virtual sleep returned exactly extra_lag late
        assert snap["last_lag_ms"] == 250.0
        assert snap["tasks"].get("hostplane-heartbeat") == 1
    finally:
        await mon.stop()
    assert mon.snapshot()["running"] is False


def test_task_census_groups_name_families():
    async def run():
        async def idle():
            await asyncio.sleep(10)

        tasks = [
            asyncio.ensure_future(idle(), loop=asyncio.get_running_loop())
            for _ in range(3)
        ]
        for i, t in enumerate(tasks):
            t.set_name(f"sse-pump-{i}")
        await asyncio.sleep(0)
        fams = task_census()
        for t in tasks:
            t.cancel()
        return fams

    fams = asyncio.run(run())
    assert fams["sse-pump"] == 3


# ---------------------------------------------------------------------------
# HostCostLedger units (manual clock)
# ---------------------------------------------------------------------------
def test_ledger_stamps_all_stages_and_ttfb_split():
    t = [1000.0]
    led = HostCostLedger(clock=lambda: t[0])
    led.begin("r1", "chat")
    for s in STAGES:
        led.stage("r1", s, 0.010)
    led.stage("r1", "tool_parser", 0.005)  # repeat calls accumulate
    led.mark_stream("r1")
    assert led.summary()["streams_open"] == 1
    t[0] += 0.1  # first chunk lands 100 ms after begin
    led.chunk("r1", serialize_s=0.001, write_s=0.002, nbytes=64)
    led.chunk("r1", serialize_s=0.001, write_s=0.0001, nbytes=64)
    led.finish("r1", "200")
    led.finish("r1", "200")  # idempotent: one row, not two
    snap = led.snapshot(recent=4)
    assert snap["requests_total"] == 1
    assert snap["streams_open"] == 0 and snap["streams_total"] == 1
    assert snap["chunks_total"] == 2
    rows = snap["recent"]
    assert len(rows) == 1
    row = rows[0]
    assert row["stream"] is True and row["status"] == "200"
    assert set(row["stages_ms"]) == set(STAGES)
    assert row["stages_ms"]["tool_parser"] == 15.0  # 10 + 5 accumulated
    assert row["chunks"] == 2 and row["bytes"] == 128
    # one write (2 ms) crossed the 1 ms drain threshold
    assert row["drain_waits"] == 1
    assert row["drain_wait_ms"] == 2.0
    assert row["ttfb_ms"] == 100.0
    # host TTFB = TTFB minus the engine's first-chunk wait (prime)
    assert row["host_ttfb_ms"] == 90.0
    assert snap["window"]["stage_ms_mean"]["prime"] == 10.0
    assert snap["window"]["engine_first_chunk_ms_mean"] == 10.0


def test_ledger_bounds_active_table_and_ignores_unknown_rids():
    led = HostCostLedger(max_active=4)
    for i in range(10):
        led.begin(f"r{i}", "chat")
    assert led.summary()["active"] <= 4
    led.stage("nope", "prime", 1.0)  # unknown rid: no-op, no crash
    led.chunk("nope", 0.1, 0.1)
    led.finish("nope")
    note_stage(None, "prime", 1.0)  # rid-less engines stamp nowhere


def test_note_stage_routes_to_global_ledger():
    rid = "hostplane-note-stage-test"
    LEDGER.begin(rid, "chat")
    try:
        note_stage(rid, "dispatch", 0.004)
        note_stage(rid, "dispatch", 0.002)
    finally:
        LEDGER.finish(rid, "200")
    row = next(
        r for r in LEDGER.snapshot(recent=64)["recent"] if r["rid"] == rid
    )
    assert row["stages_ms"]["dispatch"] == 6.0


# ---------------------------------------------------------------------------
# /debug/hostplane provider registry
# ---------------------------------------------------------------------------
def test_collect_hostplane_providers_and_error_stanza():
    register_hostplane_provider("t_ok", lambda: {"x": 1})

    def boom():
        raise RuntimeError("torn")

    register_hostplane_provider("t_bad", boom)
    try:
        snap = collect_hostplane()
        assert snap["t_ok"] == {"x": 1}
        assert "RuntimeError" in snap["t_bad"]["error"]
        assert "ts" in snap and "pid" in snap
    finally:
        unregister_hostplane_provider("t_ok")
        unregister_hostplane_provider("t_bad")


# ---------------------------------------------------------------------------
# e2e through the real HttpService (CounterEngine pattern,
# tests/test_http_service.py)
# ---------------------------------------------------------------------------
class CounterEngine(AsyncEngine):
    def __init__(self, n: int = 3, delay: float = 0.0, block_s: float = 0.0):
        self.n = n
        self.delay = delay
        self.block_s = block_s

    async def _gen(self, request: Any, ctx: Context) -> AsyncIterator[Any]:
        assert isinstance(request, ChatCompletionRequest)
        gen = ChatDeltaGenerator(model=request.model)
        if self.block_s:
            time.sleep(self.block_s)  # deliberate sync loop stall
        for i in range(self.n):
            if self.delay:
                await asyncio.sleep(self.delay)
            yield gen.text_chunk(f"w{i} ")
        yield gen.finish_chunk(FinishReason.STOP)

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._gen(request, context)


async def _start_service(engine, **kw) -> tuple[HttpService, str]:
    manager = ModelManager()
    manager.add_chat_model("foo", engine)
    service = HttpService(manager, host="127.0.0.1", port=0, **kw)
    await service.start()
    return service, f"http://127.0.0.1:{service.port}"


def _recent_rows(hp: dict) -> list:
    return hp["frontend"]["ledger"]["recent"]


async def test_ledger_rows_nonstream_and_stream_e2e():
    from dynamo_tpu.http.admission import AdmissionConfig, AdmissionController

    # permissive admission (unknown load admits) so the admission
    # stage + stanza are live without shedding anything
    service, base = await _start_service(
        CounterEngine(n=3),
        admission=AdmissionController(AdmissionConfig(), load_fn=lambda: None),
    )
    try:
        async with aiohttp.ClientSession() as s:
            payload = {
                "model": "foo",
                "messages": [{"role": "user", "content": "hi"}],
            }
            async with s.post(f"{base}/v1/chat/completions", json=payload) as r:
                assert r.status == 200
                await r.json()
            async with s.get(f"{base}/debug/hostplane") as r:
                hp = await r.json()
            row = _recent_rows(hp)[-1]
            assert row["stream"] is False and row["status"] == "200"
            # the non-stream path stamps every frontend-visible stage
            # (prime is streaming-only: it times the first SSE chunk)
            for stage in ("preprocess", "admission", "dispatch"):
                assert stage in row["stages_ms"], row["stages_ms"]
            assert row["chunks"] == 0 and row["ttfb_ms"] is None

            async with s.post(
                f"{base}/v1/chat/completions",
                json=dict(payload, stream=True),
            ) as r:
                assert r.status == 200
                async for _ in r.content:
                    pass
            async with s.get(f"{base}/debug/hostplane") as r:
                hp = await r.json()
            row = _recent_rows(hp)[-1]
            assert row["stream"] is True
            for stage in ("preprocess", "admission", "dispatch", "prime"):
                assert stage in row["stages_ms"], row["stages_ms"]
            # chunks counted, TTFB recorded, and the split resolves
            assert row["chunks"] > 0 and row["bytes"] > 0
            assert row["ttfb_ms"] is not None
            assert "host_ttfb_ms" in row
            assert row["host_ttfb_ms"] <= row["ttfb_ms"]
            # loop + admission stanzas ride the same payload
            assert hp["frontend"]["loop"]["running"] is True
            assert hp["frontend"]["admission"]["checks_total"] >= 2
            assert "check_ema_us" in hp["frontend"]["admission"]
    finally:
        await service.stop()


async def test_debug_hostplane_agrees_with_metrics():
    service, base = await _start_service(CounterEngine(n=4, delay=0.2))
    try:
        async with aiohttp.ClientSession() as s:
            payload = {
                "model": "foo",
                "messages": [{"role": "user", "content": "hi"}],
                "stream": True,
            }

            async def drain():
                async with s.post(
                    f"{base}/v1/chat/completions", json=payload
                ) as r:
                    async for _ in r.content:
                        pass

            task = asyncio.ensure_future(drain())
            await asyncio.sleep(0.3)  # mid-stream: the stream is OPEN
            async with s.get(f"{base}/debug/hostplane") as r:
                hp = await r.json()
            async with s.get(f"{base}/metrics") as r:
                fams = prom_parse(await r.text())
            open_streams = hp["frontend"]["ledger"]["streams_open"]
            assert open_streams >= 1
            assert fams["dynamo_http_open_streams"].samples[
                ("dynamo_http_open_streams", ())
            ] == open_streams
            # stall agreement: one induced stall moves the snapshot
            # counter and the counter series in lockstep
            stalls_before = hp["frontend"]["loop"]["stalls"]
            metric_before = fams["dynamo_http_loop_stalls_total"].samples[
                ("dynamo_http_loop_stalls_total", ())
            ]
            service.lag_monitor.note_lag(0.06)
            async with s.get(f"{base}/debug/hostplane") as r:
                hp2 = await r.json()
            async with s.get(f"{base}/metrics") as r:
                fams2 = prom_parse(await r.text())
            assert hp2["frontend"]["loop"]["stalls"] == stalls_before + 1
            assert fams2["dynamo_http_loop_stalls_total"].samples[
                ("dynamo_http_loop_stalls_total", ())
            ] == metric_before + 1
            # lag histogram + gauges exist on the scrape surface
            for fam in (
                "dynamo_http_loop_lag_seconds",
                "dynamo_http_loop_lag_p99_seconds",
                "dynamo_http_host_stage_seconds",
                "dynamo_http_sse_write_ema_seconds",
            ):
                assert fam in fams2, fam
            await task
    finally:
        await service.stop()


async def test_induced_sync_stall_dumps_exactly_one_bundle(tmp_path):
    """The acceptance drill: a handler that blocks the loop for 120 ms
    produces exactly ONE loop_stall black-box bundle, visible in
    /debug/hostplane."""
    rec = FlightRecorder(
        capacity=32, dump_dir=str(tmp_path), min_dump_interval_s=0.0
    )
    bb = BlackBox(recorder=rec, dump_dir=str(tmp_path), min_interval_s=0.0)
    monitor = LoopLagMonitor(
        interval_s=0.01, stall_s=0.05, holdoff_s=60.0,
        recorder=rec, blackbox=bb,
    )
    service, base = await _start_service(
        CounterEngine(n=1, block_s=0.12), lag_monitor=monitor
    )
    try:
        async with aiohttp.ClientSession() as s:
            payload = {
                "model": "foo",
                "messages": [{"role": "user", "content": "hi"}],
            }
            for _ in range(2):  # two stalls, one holdoff window
                async with s.post(
                    f"{base}/v1/chat/completions", json=payload
                ) as r:
                    assert r.status == 200
                    await r.json()
                await asyncio.sleep(0.05)  # let the heartbeat catch up
            bb.flush()
            async with s.get(f"{base}/debug/hostplane") as r:
                hp = await r.json()
        loop_snap = hp["frontend"]["loop"]
        assert loop_snap["stalls"] >= 1
        assert loop_snap["blackbox"]["dumps"] == 1
        bundle = loop_snap["blackbox"]["last_dump_dir"]
        with open(os.path.join(bundle, "meta.json")) as f:
            assert json.load(f)["reason"] == "loop_stall"
        # the ring inside the bundle carries the stall record
        flight = open(os.path.join(bundle, "flight.jsonl")).read()
        assert "loop_stall" in flight
    finally:
        await service.stop()


async def test_tool_parser_stamp_rides_note_stage():
    """The preprocessor's backward pass stamps tool_parser time onto
    the live ledger record by request id (Context.child preserves it)."""
    from dynamo_tpu.preprocessor.preprocessor import (
        OpenAIPreprocessor,
        _ReqState,
    )
    from dynamo_tpu.protocols.common import LLMEngineOutput
    from dynamo_tpu.tokenizer import Tokenizer

    pre = OpenAIPreprocessor(
        Tokenizer.from_file(MODEL_DIR), formatter=None, model_name="tiny"
    )
    state = _ReqState(
        kind="chat", model="tiny", request_id="r", prompt_tokens=3,
        include_usage=True, logprobs=False, tool_mode="forced",
        tool_name="get_weather",
    )

    async def stream():
        for t in ['{"city": ', '"Oslo"}']:
            yield LLMEngineOutput(request_id="r", token_ids=[1], text=t)
        yield LLMEngineOutput(
            request_id="r", finish_reason=FinishReason.STOP,
            prompt_tokens=3, completion_tokens=2,
        )

    rid = "hostplane-toolcall-test"
    LEDGER.begin(rid, "chat")
    try:
        chunks = [
            c async for c in pre.backward(stream(), state, Context(id=rid))
        ]
        assert chunks
    finally:
        LEDGER.finish(rid, "200")
    row = next(
        r for r in LEDGER.snapshot(recent=64)["recent"] if r["rid"] == rid
    )
    assert "tool_parser" in row["stages_ms"]


# ---------------------------------------------------------------------------
# fan-out bench: pure compare logic + a smoke run of the real ladder
# ---------------------------------------------------------------------------
def test_fanout_compare_verdicts():
    import bench

    base = {"rps": 1000.0, "streams": 1000, "noise_frac": 0.2}
    ok = bench._fanout_compare({"rps": 900.0, "streams": 900}, base)
    assert ok["regressed"] is False
    assert ok["floor_rps"] == 800.0 and ok["floor_streams"] == 800
    # either headline under its floor regresses
    assert bench._fanout_compare(
        {"rps": 700.0, "streams": 900}, base
    )["regressed"] is True
    assert bench._fanout_compare(
        {"rps": 900.0, "streams": 700}, base
    )["regressed"] is True
    # noise_frac defaults wide (0.5) when the profile omits it
    loose = bench._fanout_compare(
        {"rps": 501.0, "streams": 501}, {"rps": 1000.0, "streams": 1000}
    )
    assert loose["noise_frac"] == 0.5 and loose["regressed"] is False


def test_fanout_bench_smoke(tmp_path):
    """One tiny rung per ladder through the REAL server + client path;
    gated against a permissive temp baseline so the smoke asserts the
    machinery, not this box's throughput."""
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({
        "profiles": {
            "cpu-fanout-quick": {"rps": 0.1, "streams": 1, "noise_frac": 0.5}
        }
    }))
    report = tmp_path / "report.json"
    env = dict(
        os.environ,
        DYN_BENCH_FANOUT_SMOKE="1",
        DYN_BENCH_FANOUT_CHUNKS="2",
        DYN_BENCH_FANOUT_INTERVAL_S="0.01",
        DYN_SENTINEL_REPORT=str(report),
        JAX_PLATFORMS="cpu",
    )
    proc = subprocess.run(
        [sys.executable, "bench.py", "--fanout", "--quick",
         "--baseline", str(baseline)],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    by_metric = {l["metric"]: l for l in lines}
    rps = by_metric["frontend_fanout_rps"]
    streams = by_metric["frontend_fanout_streams"]
    assert rps["value"] > 0 and rps["vs_baseline"] > 0
    assert streams["value"] == 8  # the smoke rung completed clean
    cfg = rps["config"]
    assert cfg["profile"] == "cpu-fanout-quick"
    assert cfg["rps_rungs"] and cfg["stream_rungs"]
    assert cfg["stream_rungs"][0]["failures"] == 0
    assert cfg["regressed"] is False
    # the CI artifact mirrors both headline lines
    rep = json.loads(report.read_text())
    assert rep["rps"]["metric"] == "frontend_fanout_rps"
    assert rep["streams"]["value"] == 8


def test_committed_fanout_baselines_present():
    with open(os.path.join(REPO_ROOT, "BENCH_BASELINE.json")) as f:
        profiles = json.load(f)["profiles"]
    for key in ("cpu-fanout-quick", "cpu-fanout-full"):
        prof = profiles[key]
        assert prof["rps"] > 0 and prof["streams"] > 0
        assert 0.0 < prof["noise_frac"] < 1.0


# ---------------------------------------------------------------------------
# `dynamo-tpu top` host columns
# ---------------------------------------------------------------------------
def _hp_payload(total: int, streams: int = 2, p99: float = 3.5) -> dict:
    return {
        "frontend": {
            "loop": {"lag": {"p50_ms": 1.0, "p99_ms": p99, "max_ms": 9.0}},
            "ledger": {"requests_total": total, "streams_open": streams},
        }
    }


def test_top_hostplane_cols_rules():
    from dynamo_tpu.cli.top import _hostplane_cols

    # no payload at all: every column renders the absence marker
    cols = _hostplane_cols(None, None, now=10.0, prev_ts=5.0)
    assert cols == {"loop_lag_p99_ms": None, "streams_open": None, "rps": None}
    # first poll: lag + streams resolve, RPS needs a prior sample
    cols = _hostplane_cols(_hp_payload(100), None, now=10.0, prev_ts=None)
    assert cols["loop_lag_p99_ms"] == 3.5
    assert cols["streams_open"] == 2
    assert cols["rps"] is None
    # second poll: RPS from the counter delta over the poll gap
    cols = _hostplane_cols(
        _hp_payload(150), _hp_payload(100), now=15.0, prev_ts=10.0
    )
    assert cols["rps"] == 10.0
    # counter rewind (frontend restart) and zero gap both render `-`
    assert _hostplane_cols(
        _hp_payload(50), _hp_payload(100), now=15.0, prev_ts=10.0
    )["rps"] is None
    assert _hostplane_cols(
        _hp_payload(150), _hp_payload(100), now=10.0, prev_ts=10.0
    )["rps"] is None


async def test_top_fetch_hostplane_live_and_down():
    from dynamo_tpu.cli.top import fetch_hostplane

    service, base = await _start_service(CounterEngine())
    try:
        async with aiohttp.ClientSession() as s:
            hp = await fetch_hostplane(s, base)
            assert hp is not None and "frontend" in hp
            # a dead endpoint degrades to None (columns render `-`)
            assert await fetch_hostplane(s, "http://127.0.0.1:9") is None
    finally:
        await service.stop()


def test_top_header_renders_host_columns():
    from dynamo_tpu.cli import top as top_mod

    assert "LAG99" in top_mod.HEADER
    assert "STRM" in top_mod.HEADER
    assert "RPS" in top_mod.HEADER
