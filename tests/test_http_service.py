"""HTTP service tests with a counting mock engine over real sockets
(≈ reference lib/llm/tests/http-service.rs CounterEngine)."""

import asyncio
import json
from typing import Any, AsyncIterator

import aiohttp

from dynamo_tpu.http.service import HttpService, ModelManager
from dynamo_tpu.protocols.common import FinishReason
from dynamo_tpu.protocols.openai import ChatCompletionRequest, ChatDeltaGenerator
from dynamo_tpu.protocols.sse import SseDecoder
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineStream


class CounterEngine(AsyncEngine):
    """Streams N words; counts requests and cancellations."""

    def __init__(self, n: int = 5, delay: float = 0.0):
        self.n = n
        self.delay = delay
        self.requests = 0
        self.cancelled = 0

    async def _gen(self, request: Any, ctx: Context) -> AsyncIterator[Any]:
        self.requests += 1
        self.produced = 0
        assert isinstance(request, ChatCompletionRequest)
        gen = ChatDeltaGenerator(model=request.model)
        for i in range(self.n):
            if ctx.is_stopped:
                self.cancelled += 1
                return
            if self.delay:
                await asyncio.sleep(self.delay)
            self.produced += 1
            yield gen.text_chunk(f"w{i} ")
        yield gen.finish_chunk(FinishReason.STOP)

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._gen(request, context)


async def _start_service(engine) -> tuple[HttpService, str]:
    manager = ModelManager()
    manager.add_chat_model("foo", engine)
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    return service, f"http://127.0.0.1:{service.port}"


async def test_models_and_health():
    service, base = await _start_service(CounterEngine())
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/v1/models") as r:
                assert r.status == 200
                body = await r.json()
                assert [m["id"] for m in body["data"]] == ["foo"]
            async with s.get(f"{base}/health") as r:
                assert (await r.json())["status"] == "healthy"
    finally:
        await service.stop()


async def test_chat_streaming_sse():
    service, base = await _start_service(CounterEngine(n=3))
    try:
        async with aiohttp.ClientSession() as s:
            payload = {
                "model": "foo",
                "messages": [{"role": "user", "content": "hi"}],
                "stream": True,
            }
            async with s.post(f"{base}/v1/chat/completions", json=payload) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/event-stream")
                dec = SseDecoder()
                msgs = []
                async for chunk, _ in r.content.iter_chunks():
                    msgs.extend(dec.feed(chunk))
        assert msgs[-1].is_done
        chunks = [m.json() for m in msgs[:-1]]
        text = "".join(
            c["choices"][0]["delta"].get("content", "")
            for c in chunks
            if c["choices"]
        )
        assert text == "w0 w1 w2 "
        assert chunks[-1]["choices"][0]["finish_reason"] == "stop"
    finally:
        await service.stop()


async def test_chat_non_streaming_aggregates():
    service, base = await _start_service(CounterEngine(n=4))
    try:
        async with aiohttp.ClientSession() as s:
            payload = {
                "model": "foo",
                "messages": [{"role": "user", "content": "hi"}],
            }
            async with s.post(f"{base}/v1/chat/completions", json=payload) as r:
                assert r.status == 200
                body = await r.json()
        assert body["object"] == "chat.completion"
        assert body["choices"][0]["message"]["content"] == "w0 w1 w2 w3 "
        assert body["choices"][0]["finish_reason"] == "stop"
    finally:
        await service.stop()


async def test_unknown_model_404_and_bad_json_400():
    service, base = await _start_service(CounterEngine())
    try:
        async with aiohttp.ClientSession() as s:
            payload = {"model": "nope", "messages": [{"role": "user", "content": "x"}]}
            async with s.post(f"{base}/v1/chat/completions", json=payload) as r:
                assert r.status == 404
                assert "not found" in (await r.json())["error"]["message"]
            async with s.post(
                f"{base}/v1/chat/completions",
                data=b"{not json",
                headers={"Content-Type": "application/json"},
            ) as r:
                assert r.status == 400
            # missing required field
            async with s.post(f"{base}/v1/chat/completions", json={"model": "foo"}) as r:
                assert r.status == 400
    finally:
        await service.stop()


async def test_client_disconnect_cancels_engine():
    engine = CounterEngine(n=1000, delay=0.01)
    service, base = await _start_service(engine)
    try:
        async with aiohttp.ClientSession() as s:
            payload = {
                "model": "foo",
                "messages": [{"role": "user", "content": "x"}],
                "stream": True,
            }
            resp = await s.post(f"{base}/v1/chat/completions", json=payload)
            # read a few chunks then slam the connection shut
            await resp.content.read(64)
            resp.close()
        await asyncio.sleep(0.5)
        n = engine.produced
        assert n < 1000, "engine was not interrupted"
        await asyncio.sleep(0.3)
        assert engine.produced == n, "engine kept producing after disconnect"
    finally:
        await service.stop()


async def test_metrics_endpoint():
    service, base = await _start_service(CounterEngine(n=1))
    try:
        async with aiohttp.ClientSession() as s:
            payload = {"model": "foo", "messages": [{"role": "user", "content": "x"}]}
            async with s.post(f"{base}/v1/chat/completions", json=payload) as r:
                await r.json()
            async with s.get(f"{base}/metrics") as r:
                text = await r.text()
        assert "dynamo_http_requests_total" in text
        assert 'model="foo"' in text
    finally:
        await service.stop()
