"""HTTP service tests with a counting mock engine over real sockets
(≈ reference lib/llm/tests/http-service.rs CounterEngine)."""

import asyncio
import json
from typing import Any, AsyncIterator

import aiohttp

from dynamo_tpu.http.service import HttpService, ModelManager
from dynamo_tpu.protocols.common import FinishReason
from dynamo_tpu.protocols.openai import ChatCompletionRequest, ChatDeltaGenerator
from dynamo_tpu.protocols.sse import SseDecoder
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineStream


class CounterEngine(AsyncEngine):
    """Streams N words; counts requests and cancellations."""

    def __init__(self, n: int = 5, delay: float = 0.0):
        self.n = n
        self.delay = delay
        self.requests = 0
        self.cancelled = 0

    async def _gen(self, request: Any, ctx: Context) -> AsyncIterator[Any]:
        self.requests += 1
        self.produced = 0
        assert isinstance(request, ChatCompletionRequest)
        gen = ChatDeltaGenerator(model=request.model)
        for i in range(self.n):
            if ctx.is_stopped:
                self.cancelled += 1
                return
            if self.delay:
                await asyncio.sleep(self.delay)
            self.produced += 1
            yield gen.text_chunk(f"w{i} ")
        yield gen.finish_chunk(FinishReason.STOP)

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._gen(request, context)


async def _start_service(engine) -> tuple[HttpService, str]:
    manager = ModelManager()
    manager.add_chat_model("foo", engine)
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    return service, f"http://127.0.0.1:{service.port}"


async def test_models_and_health():
    service, base = await _start_service(CounterEngine())
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/v1/models") as r:
                assert r.status == 200
                body = await r.json()
                assert [m["id"] for m in body["data"]] == ["foo"]
            async with s.get(f"{base}/health") as r:
                assert (await r.json())["status"] == "healthy"
    finally:
        await service.stop()


async def test_chat_streaming_sse():
    service, base = await _start_service(CounterEngine(n=3))
    try:
        async with aiohttp.ClientSession() as s:
            payload = {
                "model": "foo",
                "messages": [{"role": "user", "content": "hi"}],
                "stream": True,
            }
            async with s.post(f"{base}/v1/chat/completions", json=payload) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/event-stream")
                dec = SseDecoder()
                msgs = []
                async for chunk, _ in r.content.iter_chunks():
                    msgs.extend(dec.feed(chunk))
        assert msgs[-1].is_done
        chunks = [m.json() for m in msgs[:-1]]
        text = "".join(
            c["choices"][0]["delta"].get("content", "")
            for c in chunks
            if c["choices"]
        )
        assert text == "w0 w1 w2 "
        assert chunks[-1]["choices"][0]["finish_reason"] == "stop"
    finally:
        await service.stop()


async def test_chat_non_streaming_aggregates():
    service, base = await _start_service(CounterEngine(n=4))
    try:
        async with aiohttp.ClientSession() as s:
            payload = {
                "model": "foo",
                "messages": [{"role": "user", "content": "hi"}],
            }
            async with s.post(f"{base}/v1/chat/completions", json=payload) as r:
                assert r.status == 200
                body = await r.json()
        assert body["object"] == "chat.completion"
        assert body["choices"][0]["message"]["content"] == "w0 w1 w2 w3 "
        assert body["choices"][0]["finish_reason"] == "stop"
    finally:
        await service.stop()


async def test_unknown_model_404_and_bad_json_400():
    service, base = await _start_service(CounterEngine())
    try:
        async with aiohttp.ClientSession() as s:
            payload = {"model": "nope", "messages": [{"role": "user", "content": "x"}]}
            async with s.post(f"{base}/v1/chat/completions", json=payload) as r:
                assert r.status == 404
                assert "not found" in (await r.json())["error"]["message"]
            async with s.post(
                f"{base}/v1/chat/completions",
                data=b"{not json",
                headers={"Content-Type": "application/json"},
            ) as r:
                assert r.status == 400
            # missing required field
            async with s.post(f"{base}/v1/chat/completions", json={"model": "foo"}) as r:
                assert r.status == 400
    finally:
        await service.stop()


async def test_client_disconnect_cancels_engine():
    engine = CounterEngine(n=1000, delay=0.01)
    service, base = await _start_service(engine)
    try:
        async with aiohttp.ClientSession() as s:
            payload = {
                "model": "foo",
                "messages": [{"role": "user", "content": "x"}],
                "stream": True,
            }
            resp = await s.post(f"{base}/v1/chat/completions", json=payload)
            # read a few chunks then slam the connection shut
            await resp.content.read(64)
            resp.close()
        await asyncio.sleep(0.5)
        n = engine.produced
        assert n < 1000, "engine was not interrupted"
        await asyncio.sleep(0.3)
        assert engine.produced == n, "engine kept producing after disconnect"
    finally:
        await service.stop()


async def test_metrics_endpoint():
    service, base = await _start_service(CounterEngine(n=1))
    try:
        async with aiohttp.ClientSession() as s:
            payload = {"model": "foo", "messages": [{"role": "user", "content": "x"}]}
            async with s.post(f"{base}/v1/chat/completions", json=payload) as r:
                await r.json()
            async with s.get(f"{base}/metrics") as r:
                text = await r.text()
        assert "dynamo_http_requests_total" in text
        assert 'model="foo"' in text
    finally:
        await service.stop()


# ---------------------------------------------------------------------------
# OpenAI wire-schema conformance for logprobs / top_logprobs / n>1 over the
# REAL pipeline (preprocessor -> fanout -> backend -> JaxEngine), asserted
# from raw SSE — the serialization layer the engine-level tests in
# test_logprobs_n.py never cross (reference schema:
# lib/llm/src/protocols/common.rs:323-372 ChatCompletionLogprobs/TopLogprob).
# ---------------------------------------------------------------------------

import os

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")


async def _real_pipeline_service():
    """HttpService over the full serving pipeline on the tiny model."""
    from dynamo_tpu.backend import Backend
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.preprocessor import OpenAIPreprocessor, PromptFormatter
    from dynamo_tpu.preprocessor.fanout import ChoiceFanout
    from dynamo_tpu.runtime.pipeline import build_pipeline
    from dynamo_tpu.tokenizer import Tokenizer

    engine = await JaxEngine.launch(EngineConfig(
        model_path=MODEL_DIR, model_name="tiny", random_weights=True,
        num_blocks=64, block_size=8, max_batch_size=4,
        prefill_chunk_size=32, max_model_len=128,
    ))
    tokenizer = Tokenizer.from_file(MODEL_DIR)
    formatter = PromptFormatter.from_model_dir(MODEL_DIR)
    pre = OpenAIPreprocessor(tokenizer, formatter, model_name="tiny")
    pipeline = build_pipeline(
        pre,
        ChoiceFanout(build_pipeline(
            Backend(tokenizer, eos_token_ids=engine.eos_token_ids),
            engine.as_async_engine(),
        )),
    )
    manager = ModelManager()
    manager.add_chat_model("tiny", pipeline)
    manager.add_completion_model("tiny", pipeline)
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    return service, f"http://127.0.0.1:{service.port}", engine


async def _sse_json_events(resp) -> list:
    dec = SseDecoder()
    events = []
    async for chunk, _ in resp.content.iter_chunks():
        for msg in dec.feed(chunk.decode()):
            if msg.data and msg.data != "[DONE]":
                events.append(json.loads(msg.data))
    return events


async def test_http_chat_sse_logprobs_wire_schema():
    """Raw SSE chat stream with logprobs+top_logprobs: every content
    delta carries OpenAI's nested logprob schema — content[] entries of
    {token, logprob, bytes, top_logprobs[{token, logprob, bytes}]} —
    with exactly one finish-reason chunk and one trailing usage chunk."""
    service, base, engine = await _real_pipeline_service()
    try:
        async with aiohttp.ClientSession() as s:
            payload = {
                "model": "tiny",
                "messages": [{"role": "user", "content": "hello world"}],
                "stream": True,
                "stream_options": {"include_usage": True},
                "max_tokens": 4,
                "logprobs": True,
                "top_logprobs": 2,
                "temperature": 0,
                "ignore_eos": True,
            }
            async with s.post(f"{base}/v1/chat/completions", json=payload) as r:
                assert r.status == 200
                events = await _sse_json_events(r)

        lp_entries = []
        finish_chunks = []
        usage_chunks = [e for e in events if e.get("usage")]
        for e in events:
            assert e["object"] == "chat.completion.chunk"
            for ch in e.get("choices", []):
                assert ch["index"] == 0
                if ch.get("finish_reason"):
                    finish_chunks.append(ch["finish_reason"])
                lp = ch.get("logprobs")
                if lp:
                    lp_entries.extend(lp["content"])
        assert len(lp_entries) == 4  # one per generated token
        for entry in lp_entries:
            assert set(entry) >= {"token", "logprob", "bytes", "top_logprobs"}
            assert isinstance(entry["logprob"], float) and entry["logprob"] <= 0
            assert isinstance(entry["bytes"], list)
            assert len(entry["top_logprobs"]) == 2
            for alt in entry["top_logprobs"]:
                assert set(alt) >= {"token", "logprob", "bytes"}
            # greedy: chosen token must be the argmax alternative
            assert entry["logprob"] == max(
                a["logprob"] for a in entry["top_logprobs"]
            )
        assert finish_chunks == ["length"]
        # exactly ONE trailing usage chunk, after all choice chunks
        assert len(usage_chunks) == 1
        assert usage_chunks[0]["choices"] == []
        assert usage_chunks[0]["usage"]["completion_tokens"] == 4
        assert events[-1].get("usage") is not None
    finally:
        await service.stop()
        await engine.shutdown()


async def test_http_chat_sse_n2_wire_schema():
    """n=2 over raw SSE: per-choice index/role/finish_reason and a
    single usage accounting BOTH choices' completion tokens."""
    service, base, engine = await _real_pipeline_service()
    try:
        async with aiohttp.ClientSession() as s:
            payload = {
                "model": "tiny",
                "messages": [{"role": "user", "content": "hi"}],
                "stream": True,
                "stream_options": {"include_usage": True},
                "max_tokens": 3,
                "n": 2,
                "temperature": 0.9,
                "seed": 7,
                "ignore_eos": True,
            }
            async with s.post(f"{base}/v1/chat/completions", json=payload) as r:
                assert r.status == 200
                events = await _sse_json_events(r)
        finishes = {}
        roles = set()
        for e in events:
            for ch in e.get("choices", []):
                assert ch["index"] in (0, 1)
                if ch.get("delta", {}).get("role"):
                    roles.add(ch["index"])
                if ch.get("finish_reason"):
                    finishes[ch["index"]] = ch["finish_reason"]
        assert roles == {0, 1}
        assert finishes == {0: "length", 1: "length"}
        usage_chunks = [e for e in events if e.get("usage")]
        assert len(usage_chunks) == 1
        assert usage_chunks[0]["usage"]["completion_tokens"] == 6
    finally:
        await service.stop()
        await engine.shutdown()


async def test_http_completions_logprobs_wire_schema():
    """Non-streaming /v1/completions with logprobs=2: OpenAI completions
    schema — parallel tokens/token_logprobs/top_logprobs/text_offset
    arrays, offsets indexing into the returned text."""
    service, base, engine = await _real_pipeline_service()
    try:
        async with aiohttp.ClientSession() as s:
            payload = {
                "model": "tiny", "prompt": "one two three",
                "max_tokens": 4, "logprobs": 2, "temperature": 0,
                "ignore_eos": True,
            }
            async with s.post(f"{base}/v1/completions", json=payload) as r:
                assert r.status == 200
                body = await r.json()
        choice = body["choices"][0]
        assert choice["finish_reason"] == "length"
        lp = choice["logprobs"]
        assert set(lp) >= {"tokens", "token_logprobs", "top_logprobs", "text_offset"}
        assert len(lp["tokens"]) == 4
        assert len(lp["token_logprobs"]) == 4
        assert len(lp["top_logprobs"]) == 4
        assert len(lp["text_offset"]) == 4
        # offsets are monotonically non-decreasing and start at 0
        assert lp["text_offset"][0] == 0
        assert lp["text_offset"] == sorted(lp["text_offset"])
        for t_lp, tops in zip(lp["token_logprobs"], lp["top_logprobs"]):
            assert t_lp <= 0
            # the dict is keyed by token STRING: distinct ids decoding to
            # the same text collapse (keep-max), so 1 <= len <= 2
            assert 1 <= len(tops) <= 2 and all(v <= 0 for v in tops.values())
            assert t_lp == max(tops.values())  # greedy pick is the argmax
        assert body["usage"]["completion_tokens"] == 4
    finally:
        await service.stop()
        await engine.shutdown()
