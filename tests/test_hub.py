"""Gated hub resolution (reference: lib/llm/src/hub.rs + local_model.rs
— repo ids resolve to local checkpoints; here downloads are off by
default for zero-egress serving nodes)."""

import os

import pytest

from dynamo_tpu.models.hub import is_repo_id, resolve_hub_model


def test_is_repo_id(tmp_path):
    assert is_repo_id("meta-llama/Llama-3-8B")
    assert not is_repo_id(str(tmp_path))        # existing dir
    assert not is_repo_id("model.gguf")
    assert not is_repo_id("a/b/c")
    assert not is_repo_id("")
    assert not is_repo_id("./relative/path")


def test_local_paths_pass_through(tmp_path):
    assert resolve_hub_model(str(tmp_path)) == str(tmp_path)
    assert resolve_hub_model("") == ""


def test_uncached_repo_refused_without_optin(monkeypatch, tmp_path):
    monkeypatch.delenv("DYN_ALLOW_HUB_DOWNLOAD", raising=False)
    monkeypatch.setenv("DYN_HUB_CACHE", str(tmp_path / "cache"))
    with pytest.raises(ValueError, match="DYN_ALLOW_HUB_DOWNLOAD"):
        resolve_hub_model("no-such-org/no-such-model")


def test_download_gated_by_env(monkeypatch, tmp_path):
    """With the opt-in set, resolution calls snapshot_download in
    network mode (mocked: no egress in CI)."""
    calls = []

    def fake_snapshot_download(repo, **kw):
        calls.append((repo, kw.get("local_files_only", False)))
        if kw.get("local_files_only"):
            raise FileNotFoundError(repo)
        return str(tmp_path / "snap")

    import huggingface_hub

    monkeypatch.setattr(huggingface_hub, "snapshot_download",
                        fake_snapshot_download)
    monkeypatch.setenv("DYN_ALLOW_HUB_DOWNLOAD", "1")
    out = resolve_hub_model("org/model")
    assert out == str(tmp_path / "snap")
    assert calls == [("org/model", False)]

    # without the env: cache-only attempt, then a clear refusal
    monkeypatch.delenv("DYN_ALLOW_HUB_DOWNLOAD")
    calls.clear()
    with pytest.raises(ValueError, match="not cached locally"):
        resolve_hub_model("org/model")
    assert calls == [("org/model", True)]


def test_local_path_typo_not_treated_as_repo(tmp_path, monkeypatch):
    """A nonexistent two-segment path whose first segment IS a local
    directory is a typo'd local path, not a hub repo."""
    monkeypatch.chdir(tmp_path)
    (tmp_path / "ckpts").mkdir()
    assert not is_repo_id("ckpts/no-such-model")
    # passes through untouched -> downstream raises a missing-path error
    assert resolve_hub_model("ckpts/no-such-model") == "ckpts/no-such-model"
