"""JAX-semantics analysis (ISSUE 13): the jit-site inventory
(analysis/jaxsem.py), targeted DL201/DL202/DL203 behaviors beyond the
fixture pairs, the DL2xx cache self-invalidation regression, and the
compile fence — units plus the e2e acceptance case (an unprewarmed
shape under DYN_COMPILE_FENCE=1 produces exactly one flight-recorder
``serve_compile`` record and one black-box bundle; a prewarmed run
produces none)."""

import ast
import glob
import os
import textwrap

import pytest

from dynamo_tpu.analysis import jaxsem, load_config
from dynamo_tpu.analysis.callgraph import build_callgraph
from dynamo_tpu.analysis.findings import format_text
from dynamo_tpu.analysis.program import get_program_rule
from dynamo_tpu.analysis.walker import lint_sources_program
from dynamo_tpu.utils import compile_fence

MODEL_DIR = os.path.join(
    os.path.dirname(__file__), "data", "tiny_llama_model"
)


def _inventory(source: str, path: str = "mod.py") -> jaxsem.JitInventory:
    graph = build_callgraph([(path, ast.parse(textwrap.dedent(source)))])
    return jaxsem.build_inventory(graph)


def _run(rule: str, source: str, config=None):
    return lint_sources_program(
        {"mod.py": textwrap.dedent(source)},
        rules=[get_program_rule(rule)],
        config=config,
    )


# ---------------------------------------------------------------------------
# jit-site inventory
# ---------------------------------------------------------------------------


def test_inventory_decorator_forms():
    inv = _inventory(
        """
        import functools
        import jax
        from jax import jit as jjit

        @jax.jit
        def plain(x):
            return x

        @jjit
        def aliased(x):
            return x

        @functools.partial(jax.jit, donate_argnums=(0, 1),
                           static_argnums=2,
                           static_argnames=("mode", "width"))
        def fused(k, v, size, mode="d", width=8):
            return k
        """
    )
    assert set(inv.by_qualname) == {"mod:plain", "mod:aliased", "mod:fused"}
    fused = inv.by_qualname["mod:fused"]
    assert fused.donate == (0, 1)
    assert fused.static == (2,)
    assert fused.static_names == ("mode", "width")
    assert fused.kind == "decorator" and fused.wrapped == "mod:fused"


def test_inventory_attr_local_conditional_and_alias_bindings():
    inv = _inventory(
        """
        import jax

        def _step(k, v, t):
            return t, k, v

        def _window(k, v, t):
            return t, k, v

        class Engine:
            def build(self, multi):
                self._step_fn = jax.jit(_step, donate_argnums=(0, 1))
                self._window_fn = (
                    jax.jit(_window, donate_argnums=(0, 1))
                    if multi else None
                )
                self._step_fn_mm = self._step_fn  # alias
                local = jax.jit(_step)
                return local
        """
    )
    step = inv.by_attr[("mod:Engine", "_step_fn")]
    assert step.donate == (0, 1) and step.wrapped == "mod:_step"
    # the `jit(...) if cond else None` arm is still a binding
    window = inv.by_attr[("mod:Engine", "_window_fn")]
    assert window.donate == (0, 1) and window.wrapped == "mod:_window"
    # alias shares the SOURCE site (coverage follows the callable)
    assert inv.by_attr[("mod:Engine", "_step_fn_mm")] is step
    assert ("mod:Engine.build", "local") in inv.by_local


def test_inventory_one_level_param_summaries():
    inv = _inventory(
        """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0, 1),
                           static_argnums=(3,))
        def _scatter(k, v, rows, block_size):
            return k, v

        def scatter_blocks(k, v, rows, block_size):
            return _scatter(k, v, rows, block_size)
        """
    )
    donating = inv.donating_params["mod:scatter_blocks"]
    assert set(donating) == {0, 1}
    assert donating[0].site.key == "mod:_scatter"
    static = inv.static_params["mod:scatter_blocks"]
    assert set(static) == {3} and static[3].param == "block_size"


def test_effective_positional_expands_same_frame_tuple():
    tree = ast.parse("base = (a, b, c)\nfn(*base, d)")
    tup = tree.body[0].value
    call = tree.body[1].value
    args = jaxsem.effective_positional(call, {"base": tup})
    assert len(args) == 4
    assert [getattr(a, "id", None) for a in args] == ["a", "b", "c", "d"]
    # unexpandable star: later indexes are unknowable, never wrong
    assert jaxsem.effective_positional(call, {}) == []


# ---------------------------------------------------------------------------
# DL201 behaviors beyond the fixture pair
# ---------------------------------------------------------------------------

_DONATING_PRELUDE = """
    import functools
    import jax

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(k, v, t):
        return t, k, v
"""


def test_dl201_loop_carried_poison_is_seen():
    findings = _run(
        "use-after-donate",
        _DONATING_PRELUDE
        + """
        def loop(k, v, batches):
            for t in batches:
                out = k.mean()   # second iteration reads donated k
                _ = step(k, v, t)
            return out
        """,
    )
    # two loop-carried bugs: iteration 2 reads donated k AND re-passes
    # donated v into the next dispatch
    assert len(findings) == 2, format_text(findings)
    assert {"`k`" in f.message or "`v`" in f.message
            for f in findings} == {True}


def test_dl201_branch_rebind_in_both_arms_is_clean():
    findings = _run(
        "use-after-donate",
        _DONATING_PRELUDE
        + """
        def both(k, v, t, flag):
            if flag:
                _, k, v = step(k, v, t)
            else:
                _, k, v = step(k, v, t + 1)
            return k, v
        """,
    )
    assert findings == [], format_text(findings)


def test_dl201_rebind_in_one_arm_only_still_poisons():
    findings = _run(
        "use-after-donate",
        _DONATING_PRELUDE
        + """
        def one_arm(k, v, t, flag):
            if flag:
                _, k, v = step(k, v, t)
            else:
                step(k, v, t)
            return k
        """,
    )
    assert len(findings) == 1, format_text(findings)


def test_dl201_closure_reads_and_calls_are_not_this_frame():
    # a lambda/def body runs LATER (usually after the rebind): neither
    # its reads nor its donating calls belong to this frame's dataflow
    findings = _run(
        "use-after-donate",
        _DONATING_PRELUDE
        + """
        def callback_capture(k, v, t):
            out = step(k, v, t)
            cb = lambda: k.shape      # runs after the rebind below
            def later():
                return step(k, v, t)  # not dispatched here
            _, k, v = out
            return cb, later
        """,
    )
    assert findings == [], format_text(findings)


def test_dl201_starred_tuple_args_analyze_like_explicit():
    findings = _run(
        "use-after-donate",
        _DONATING_PRELUDE
        + """
        def packed(k, v, t):
            base = (k, v, t)
            out = step(*base)
            return out, k     # k was donated through *base
        """,
    )
    assert len(findings) == 1, format_text(findings)
    assert "`k`" in findings[0].message


# ---------------------------------------------------------------------------
# DL202 behaviors beyond the fixture pair
# ---------------------------------------------------------------------------

_STATIC_PRELUDE = """
    import functools
    import jax

    @functools.partial(jax.jit, static_argnums=(1,))
    def kernel(x, width):
        return x[:width]
"""


def test_dl202_call_expression_only_flags_in_step_loop_context():
    clean = _run(
        "dynamic-static-arg",
        _STATIC_PRELUDE
        + """
        def prewarm(state, widths):
            for w in widths:
                kernel(state.x, int(w))   # init-time: sanctioned
        """,
    )
    assert clean == [], format_text(clean)
    hot = _run(
        "dynamic-static-arg",
        _STATIC_PRELUDE
        + """
        def run_step_loop(state):
            while state.running:
                kernel(state.x, int(state.n))
        """,
    )
    assert len(hot) == 1, format_text(hot)
    assert "per call" in hot[0].message


def test_dl202_for_loop_target_is_a_per_step_local():
    findings = _run(
        "dynamic-static-arg",
        _STATIC_PRELUDE
        + """
        def run_step_loop(state):
            for width in state.widths:
                kernel(state.x, width)
        """,
    )
    assert len(findings) == 1, format_text(findings)
    assert "per-step local" in findings[0].message


def test_dl202_device_array_flags_everywhere():
    findings = _run(
        "dynamic-static-arg",
        _STATIC_PRELUDE
        + """
        import jax

        @jax.jit
        def produce(x):
            return x

        def anywhere(x):
            y = produce(x)
            return kernel(x, y)
        """,
    )
    assert len(findings) == 1, format_text(findings)
    assert "device array" in findings[0].message


# ---------------------------------------------------------------------------
# DL203 behaviors beyond the fixture pair
# ---------------------------------------------------------------------------


def test_dl203_alias_reference_counts_as_coverage():
    # prewarm references the SOURCE binding; the loop invokes the alias
    findings = _run(
        "prewarm-coverage",
        """
        import jax

        def _step(x):
            return x

        class Engine:
            def __init__(self):
                self._step_fn = jax.jit(_step)
                self._step_fn_mm = self._step_fn

            def _prewarm(self):
                self._step_fn(0)

            def run_step_loop(self):
                while True:
                    self._step_fn_mm(0)
        """,
    )
    assert findings == [], format_text(findings)


def test_dl203_config_prewarm_functions_extends_roots():
    src = """
        import jax

        def _step(x):
            return x

        class Engine:
            def __init__(self):
                self._step_fn = jax.jit(_step)

            def warm_everything(self):
                self._step_fn(0)

            def run_step_loop(self):
                while True:
                    self._step_fn(0)
        """
    # no *prewarm* name anywhere: uncovered
    findings = _run("prewarm-coverage", src)
    assert len(findings) == 1, format_text(findings)
    assert "mid-serve" in findings[0].message
    # config names the oddly-named warmer as a root
    cfg = dict(load_config(start="."))
    cfg["prewarm-functions"] = ["warm_everything"]
    assert _run("prewarm-coverage", src, config=cfg) == []


# ---------------------------------------------------------------------------
# cache: DL2xx findings invalidate when jaxsem.py itself changes
# ---------------------------------------------------------------------------


def test_rule_signature_folds_in_jaxsem_source(tmp_path, monkeypatch):
    """ISSUE 13 satellite: the ruleset-signature self-invalidation
    (cache._package_hash hashes the analysis package's own sources)
    must cover the NEW module — editing jaxsem.py has to invalidate
    every cached DL2xx finding without a version knob."""
    from dynamo_tpu.analysis import cache as cache_mod
    from dynamo_tpu.analysis.cache import LintCache, rule_signature

    # the real package hash walks a file set that includes jaxsem.py
    real_pkg = os.path.dirname(cache_mod.__file__)
    walked = {os.path.basename(str(p))
              for p in __import__("pathlib").Path(real_pkg).rglob("*.py")}
    assert "jaxsem.py" in walked

    # end-to-end on a fake package: same walk, jaxsem.py edited between
    pkg = tmp_path / "analysis"
    pkg.mkdir()
    (pkg / "jaxsem.py").write_text("INVENTORY = 1\n")
    monkeypatch.setattr(cache_mod, "__file__", str(pkg / "cache.py"))
    monkeypatch.setattr(cache_mod, "_pkg_hash", None)
    rules = ["use-after-donate", "dynamic-static-arg", "prewarm-coverage"]
    sig_v1 = rule_signature(rules, {})

    store = LintCache(tmp_path / "c")
    key_v1 = LintCache.program_key({"m.py": "sha"}, sig_v1)
    store.put(key_v1, [])
    assert store.get(key_v1) == []

    (pkg / "jaxsem.py").write_text("INVENTORY = 2  # rule semantics moved\n")
    monkeypatch.setattr(cache_mod, "_pkg_hash", None)
    sig_v2 = rule_signature(rules, {})
    assert sig_v2 != sig_v1
    key_v2 = LintCache.program_key({"m.py": "sha"}, sig_v2)
    assert store.get(key_v2) is None  # the edit is a miss, not a replay


# ---------------------------------------------------------------------------
# compile fence: units
# ---------------------------------------------------------------------------


@pytest.fixture
def fence():
    compile_fence.set_mode("record")
    compile_fence.reset()
    yield compile_fence
    compile_fence.set_mode(None)
    compile_fence.reset()


def test_fence_mode_resolution(monkeypatch):
    compile_fence.set_mode(None)
    monkeypatch.delenv("DYN_COMPILE_FENCE", raising=False)
    assert compile_fence.mode() == "off" and not compile_fence.enabled()
    for raw, want in (("1", "record"), ("fatal", "fatal"),
                      ("true", "record"), ("", "off")):
        compile_fence.set_mode(None)
        monkeypatch.setenv("DYN_COMPILE_FENCE", raw)
        assert compile_fence.mode() == want
    compile_fence.set_mode(None)


def test_fence_collects_outside_allowed_window(fence):
    with fence.allow():
        fence.note_compile("/jax/backend_compile", 0.5)  # sanctioned
    assert fence.drain() == ([], 0)
    fence.note_compile("/jax/backend_compile", 0.25)
    fence.note_compile("/jax/core/compile/jaxpr_trace_duration", 0.05)
    events, n = fence.drain()
    assert n == 2
    assert [e["event"] for e in events] == [
        "/jax/backend_compile", "/jax/core/compile/jaxpr_trace_duration",
    ]
    assert events[0]["duration_ms"] == 250.0
    assert fence.drain() == ([], 0)  # drained
    assert fence.stats()["events_total"] == 2  # lifetime count survives


def test_fence_disabled_is_inert_and_pending_is_bounded(fence):
    fence.set_mode("off")
    fence.note_compile("/jax/backend_compile", 1.0)
    assert fence.stats()["events_total"] == 0
    fence.set_mode("record")
    for i in range(200):
        fence.note_compile(f"/jax/backend_compile/{i}", 0.001)
    assert fence.stats()["pending"] <= 64  # deque(maxlen): DL007 holds
    # the DETAIL window is bounded; the violation count is not — a
    # retrace storm past the bound must not undercount the metric
    events, n = fence.drain()
    assert len(events) <= 64 and n == 200
    assert fence.fatal() is False
    fence.set_mode("fatal")
    assert fence.fatal() is True


# ---------------------------------------------------------------------------
# compile fence: the e2e acceptance case
# ---------------------------------------------------------------------------


async def test_fence_e2e_unprewarmed_shape_dumps_once(tmp_path, fence):
    """ISSUE 13 acceptance: a normal prewarmed generate produces ZERO
    serve_compile records; a deliberately un-prewarmed signature (a
    penalties batch — the opt-in variant prewarm skips by default)
    produces EXACTLY ONE flight-recorder serve_compile record and one
    black-box bundle."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    async def gen(engine, rid, **samp):
        req = PreprocessedRequest(
            request_id=rid, token_ids=list(range(1, 9)),
            sampling=SamplingOptions(**samp),
            stop=StopConditions(max_tokens=1),
        )
        out = []
        async for item in engine.as_async_engine().generate(req, Context()):
            out.extend(item.token_ids)
        return out

    engine = await JaxEngine.launch(EngineConfig(
        model_path=MODEL_DIR, model_name="tiny", random_weights=True,
        num_blocks=128, block_size=8, max_batch_size=8,
        prefill_chunk_size=32, max_model_len=256,
        prewarm=True, overlap=False,
        flight_dump_dir=str(tmp_path),
    ))
    try:
        def fence_records():
            return [r for r in engine.recorder.snapshot(256)
                    if r["kind"] == "serve_compile"]

        def bundles():
            return glob.glob(str(tmp_path / "dynamo_blackbox_*"))

        # prewarm itself compiled plenty — all inside the allowed window
        assert fence.stats()["events_total"] == 0

        out = await gen(engine, "warm", use_greedy=True)
        assert out, "prewarmed generate produced no tokens"
        assert fence_records() == [] and bundles() == []

        out = await gen(engine, "cold", temperature=1.0,
                        repetition_penalty=1.3)
        assert out, "penalties generate produced no tokens"
        recs = fence_records()
        assert len(recs) == 1, recs
        assert recs[0]["compiles"] >= 1
        assert recs[0]["duration_ms"] > 0
        assert len(bundles()) == 1, bundles()
        assert engine.debug_state()["compile_fence"]["events_total"] >= 1
    finally:
        await engine.shutdown()
