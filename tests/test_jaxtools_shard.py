"""jaxtools shard_map shim (ISSUE 16 satellite): the axis_names ->
auto-complement mapping the DL3xx sharding inventory models, the
partial-auto support probe's memoization, and the pcast identity
fallback's checked soundness contract."""

import jax
import jax.experimental.shard_map as esm_mod
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from dynamo_tpu.utils import jaxtools


def _two_axis_mesh() -> Mesh:
    return Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("dp", "tp"))


# ---------------------------------------------------------------------------
# axis_names -> auto complement (the mapping shardsem.py's DL302/DL304
# model statically: declared manual axes vs the mesh's full axis set)
# ---------------------------------------------------------------------------


def test_axis_names_maps_to_auto_complement(monkeypatch):
    captured = {}

    def stub(f, *, mesh, in_specs, out_specs, check_rep, auto):
        captured.update(
            mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_rep, auto=auto,
        )
        return f

    monkeypatch.delattr(jax, "shard_map", raising=False)
    monkeypatch.setattr(esm_mod, "shard_map", stub)
    mesh = _two_axis_mesh()

    jaxtools.shard_map(
        lambda x: x, mesh=mesh, in_specs=(P("tp"),), out_specs=P("tp"),
        axis_names={"tp"},
    )
    # manual {tp} over a (dp, tp) mesh: dp stays auto
    assert captured["auto"] == frozenset({"dp"})
    assert captured["check_rep"] is False

    jaxtools.shard_map(
        lambda x: x, mesh=mesh, in_specs=(P(),), out_specs=P(),
        axis_names={"dp", "tp"},
    )
    assert captured["auto"] == frozenset()

    # omitted axis_names means fully manual: nothing left auto
    jaxtools.shard_map(
        lambda x: x, mesh=mesh, in_specs=(P(),), out_specs=P(),
    )
    assert captured["auto"] == frozenset()


def test_fully_manual_two_axis_mesh_executes_on_cpu():
    """The fully-manual mode must EXECUTE on the pinned jax (only the
    partial-auto mixed mode needs the version probe): both declared
    axes are live inside the body as collective targets."""
    mesh = _two_axis_mesh()

    def body(x):
        # psum over size-1 axes is identity; naming both axes proves
        # they are manual (an auto axis would reject the collective)
        return x * jax.lax.psum(1, "dp") * jax.lax.psum(1, "tp")

    mapped = jaxtools.shard_map(
        body, mesh=mesh, in_specs=(P(),), out_specs=P(),
        axis_names={"dp", "tp"},
    )
    out = mapped(jnp.arange(4.0))
    assert np.allclose(np.asarray(jax.device_get(out)), np.arange(4.0))


# ---------------------------------------------------------------------------
# probe memoization
# ---------------------------------------------------------------------------


@pytest.fixture
def fresh_probe():
    jaxtools._partial_auto_supported = None
    yield
    jaxtools._partial_auto_supported = None


def test_partial_auto_probe_is_memoized(fresh_probe, monkeypatch):
    first = jaxtools.partial_auto_shard_map_supported()
    assert isinstance(first, bool)
    # flip what a re-probe WOULD see; the memo must keep the first answer
    if first:
        monkeypatch.delattr(jax, "shard_map", raising=False)
    else:
        monkeypatch.setattr(jax, "shard_map", lambda *a, **k: None,
                            raising=False)
    assert jaxtools.partial_auto_shard_map_supported() is first
    assert jaxtools._partial_auto_supported is first


def test_partial_auto_probe_tracks_native_shard_map(fresh_probe, monkeypatch):
    monkeypatch.setattr(jax, "shard_map", lambda *a, **k: None,
                        raising=False)
    assert jaxtools.partial_auto_shard_map_supported() is True
    jaxtools._partial_auto_supported = None
    monkeypatch.delattr(jax, "shard_map", raising=False)
    assert jaxtools.partial_auto_shard_map_supported() is False


# ---------------------------------------------------------------------------
# pcast soundness contract
# ---------------------------------------------------------------------------


def test_pcast_identity_only_without_native_shard_map(monkeypatch):
    monkeypatch.delattr(jax.lax, "pcast", raising=False)
    monkeypatch.delattr(jax, "shard_map", raising=False)
    x = jnp.arange(3.0)
    assert jaxtools.pcast(x, ("tp",)) is x  # check_rep=False world: sound

    # native shard_map (vma tracking) WITHOUT pcast: the identity would
    # be silently wrong — the contract raises instead
    monkeypatch.setattr(jax, "shard_map", lambda *a, **k: None,
                        raising=False)
    with pytest.raises(RuntimeError, match="unsound"):
        jaxtools.pcast(x, ("tp",))


def test_pcast_prefers_native(monkeypatch):
    calls = {}

    def native(x, axis_names, to="varying"):
        calls.update(axis_names=axis_names, to=to)
        return x

    monkeypatch.setattr(jax.lax, "pcast", native, raising=False)
    x = jnp.arange(2.0)
    assert jaxtools.pcast(x, ("tp",), to="invariant") is x
    assert calls == {"axis_names": ("tp",), "to": "invariant"}
