"""Fleet KV fabric tests (docs/kvbm.md "Fleet fabric").

Ladder: catalog semantics on the dict backend, the pressure-driven G2
lifecycle on a virtual clock, never-dangling catalog invariants across
failed fetches, two-worker onboarding (in-process peer plane, then the
real store wire plane over loopback sockets), the router's discounted
fleet scoring (incl. the resume-racing-a-demotion regression), the
remote-bridge timeout surfacing, and the simulator A/B the bench gates.
"""

import asyncio
import threading

import numpy as np
import pytest

from dynamo_tpu.kvbm import (
    BlockLayout,
    DictCatalogBackend,
    FleetKvFabric,
    FleetPrefixCatalog,
    KvbmConfig,
    KvBlockManager,
    LocalPeerRegistry,
    PeerBlockServer,
    PressureConfig,
    StoreCatalogBackend,
    TcpPeerClient,
)
from dynamo_tpu.kvbm.fabric import TIER_DISK, TIER_HOST, TIER_SHARED
from dynamo_tpu.kvbm.remote import DictObjectStore

LAYOUT = BlockLayout(num_layers=2, block_size=4, num_kv_heads=2, head_dim=8)


def _block(seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.standard_normal(LAYOUT.packed_shape).astype(LAYOUT.np_dtype)


class FakeDevice:
    """Numpy 'device' cache + allocator hash index (test_kvbm.py)."""

    def __init__(self, num_blocks):
        self.blocks = np.zeros(
            (num_blocks, *LAYOUT.packed_shape), LAYOUT.np_dtype
        )
        self.hash_index: dict[int, int] = {}

    def gather(self, ids):
        return self.blocks[np.asarray(ids)]

    def scatter(self, ids, data):
        self.blocks[np.asarray(ids)] = data

    def resolve(self, h):
        return self.hash_index.get(h)


class TickClock:
    """Virtual time: the fabric's refresh throttle, touch recency, and
    catalog timestamps all read through this seam (DL009 vocabulary)."""

    def __init__(self):
        self.now = 1000.0

    def monotonic(self):
        return self.now

    def time(self):
        return self.now

    async def sleep(self, seconds):
        self.now += seconds


def _manager(dev, host_blocks=8, disk_blocks=0, tmp=None, objects=None,
             clock=None):
    return KvBlockManager(
        KvbmConfig(
            host_num_blocks=host_blocks,
            disk_num_blocks=disk_blocks,
            disk_path=str(tmp / "kv.bin") if tmp else "",
            offload_batch=64,
            remote_bucket="kvg4" if objects is not None else "",
        ),
        LAYOUT,
        gather_fn=dev.gather,
        scatter_fn=dev.scatter,
        resolve_fn=dev.resolve,
        remote_objects=objects,
        clock=clock,
    )


def _fabric(backend, worker_id, clock=None, fetcher=None, addr="",
            pressure=None):
    cat = FleetPrefixCatalog(backend, worker_id=worker_id, clock=clock)
    return FleetKvFabric(
        cat, fetcher=fetcher, pressure=pressure, clock=clock, addr=addr,
        name=f"w{worker_id}",
    )


def _commit(dev, m, hashes, base_slot=0):
    """Commit blocks on the device and pump them into G2 (the offload
    batch is clamped to the host-tier size, so drain in a loop)."""
    for i, h in enumerate(hashes):
        dev.blocks[base_slot + i] = _block(h)
        dev.hash_index[h] = base_slot + i
        m.on_block_committed(h, base_slot + i)
    m.pump()
    while m.pending_offloads:
        m.pump()


# ---------------------------------------------------------------------------
# Catalog semantics
# ---------------------------------------------------------------------------


def test_catalog_publish_match_and_tier_preference():
    backend = DictCatalogBackend()
    clock = TickClock()
    a = FleetPrefixCatalog(backend, worker_id=1, clock=clock)
    b = FleetPrefixCatalog(backend, worker_id=2, clock=clock)
    a.publish(11, TIER_HOST, 64, addr="a:1")
    clock.now += 1.0
    b.publish(11, TIER_SHARED, 64)
    a.publish(12, TIER_HOST, 64, addr="a:1")
    a.publish(13, TIER_DISK, 64)  # g3 is private: not fleet-fetchable
    b.refresh()
    # shared-bucket copies sort first (no peer round trip needed)
    locs = b.locations(11)
    assert [e["tier"] for _, e in locs] == [TIER_SHARED, TIER_HOST]
    # leading-run semantics: 11, 12 fetchable; 13 only has a g3 copy
    assert b.match_prefix([11, 12, 13]) == 2
    # a worker's own copies don't count as fleet-fetchable for itself
    assert b.match_prefix([11], exclude_worker=2) == 1
    a.refresh()
    assert a.match_prefix([11], exclude_worker=1) == 1  # b's g4 copy
    # prune-on-evict: a's retier to g3 leaves only b's g4 claim
    a.retier(11, TIER_DISK)
    b.refresh()
    assert [e["tier"] for _, e in b.locations(11)] == [TIER_SHARED]
    b.prune(11)
    b.refresh()
    assert b.match_prefix([11]) == 0


def test_pump_publishes_and_evictions_never_dangle(tmp_path):
    """Every G2 landing publishes; every eviction retiers (g3/g4) or
    prunes — after arbitrary churn, every catalog entry names a tier
    that really holds the block."""
    backend = DictCatalogBackend()
    clock = TickClock()
    dev = FakeDevice(16)
    objects = DictObjectStore()
    m = _manager(dev, host_blocks=2, disk_blocks=2, tmp=tmp_path,
                 objects=objects, clock=clock)
    fab = _fabric(backend, worker_id=1, clock=clock)
    fab.attach(m)
    try:
        _commit(dev, m, [101, 102, 103, 104, 105])  # churn 5 through 2+2
        view = backend.snapshot()
        for h in (101, 102, 103, 104, 105):
            entry = view[h][1]
            tier = entry["tier"]
            if tier == TIER_HOST:
                assert m.host.contains(h)
            elif tier == TIER_DISK:
                assert m.disk.contains(h)
            elif tier == TIER_SHARED:
                assert m.remote.contains(h)
            else:  # pragma: no cover - would be the dangling bug
                pytest.fail(f"unknown tier {tier!r} for {h:x}")
        assert fab.stats.published_blocks >= 5
    finally:
        m.close()


def test_host_evict_without_lower_tier_prunes():
    backend = DictCatalogBackend()
    dev = FakeDevice(8)
    m = _manager(dev, host_blocks=1)  # no disk, no remote: evict = drop
    # watermarks above 1.0 disable pressure so the LRU path is isolated
    fab = _fabric(backend, worker_id=1,
                  pressure=PressureConfig(high_watermark=2.0,
                                          low_watermark=1.5))
    fab.attach(m)
    _commit(dev, m, [21])
    assert backend.snapshot()[21][1]["tier"] == TIER_HOST
    _commit(dev, m, [22], base_slot=2)  # LRU-evicts 21 with nowhere to go
    assert 21 not in backend.snapshot()  # pruned, not dangling
    assert backend.snapshot()[22][1]["tier"] == TIER_HOST
    assert fab.stats.pruned_blocks >= 1


# ---------------------------------------------------------------------------
# Pressure-driven lifecycle (virtual clock)
# ---------------------------------------------------------------------------


def test_pressure_demotes_popularity_weighted_victims(tmp_path):
    """Fill G2 past the high watermark on virtual time: cold blocks go
    to private disk, hot (touched) ones to the shared bucket, and
    occupancy lands at the low watermark."""
    backend = DictCatalogBackend()
    clock = TickClock()
    dev = FakeDevice(16)
    objects = DictObjectStore()
    m = _manager(dev, host_blocks=10, disk_blocks=8, tmp=tmp_path,
                 objects=objects, clock=clock)
    pressure = PressureConfig(high_watermark=0.85, low_watermark=0.5,
                              hot_min_touches=2)
    fab = _fabric(backend, worker_id=1, clock=clock, pressure=pressure)
    fab.attach(m)
    try:
        hashes = list(range(201, 209))  # 8 of 10: below the watermark
        _commit(dev, m, hashes)
        assert m.host.num_cached == 8
        assert fab.stats.demoted_shared == fab.stats.demoted_disk == 0
        # popularity: the first two blocks are hot (2 touches)
        fab.note_touch([201, 202])
        clock.now += 1.0
        fab.note_touch([201, 202])
        # two more landings push occupancy to 10 > 8.5: demote to 5
        _commit(dev, m, [209, 210], base_slot=10)
        assert m.host.num_cached == 5
        # hot survivors stay in G2 (cold blocks were better victims)
        assert m.host.contains(201) and m.host.contains(202)
        demoted = [h for h in range(201, 211) if not m.host.contains(h)]
        view = backend.snapshot()
        for h in demoted:
            tier = view[h][1]["tier"]
            assert tier in (TIER_DISK, TIER_SHARED)
            # cold victims are private-disk bound in this config
            assert tier == TIER_DISK
            assert m.disk.contains(h)
        assert fab.stats.demoted_disk == 5
    finally:
        m.close()


def test_pressure_routes_hot_victims_to_shared_bucket():
    """With a tiny low watermark even hot blocks demote — and they land
    in the shared G4 bucket (fleet-fetchable), not private disk."""
    backend = DictCatalogBackend()
    clock = TickClock()
    dev = FakeDevice(16)
    objects = DictObjectStore()
    m = _manager(dev, host_blocks=4, objects=objects, clock=clock)
    pressure = PressureConfig(high_watermark=0.6, low_watermark=0.2,
                              hot_min_touches=2)
    fab = _fabric(backend, worker_id=1, clock=clock, pressure=pressure)
    fab.attach(m)
    _commit(dev, m, [301, 302])
    for _ in range(2):
        fab.note_touch([301, 302])
        clock.now += 1.0
    _commit(dev, m, [303], base_slot=4)  # 3 > 2.4: demote to <= 0.8
    view = backend.snapshot()
    shared = [h for h in (301, 302, 303)
              if view.get(h, {}).get(1, {}).get("tier") == TIER_SHARED]
    assert shared and all(m.remote.contains(h) for h in shared)
    assert fab.stats.demoted_shared == len(shared) > 0


def test_degradation_rung_tightens_watermarks():
    """The planner ladder's "demote cold KV" rung scales the fabric's
    watermarks down — rung N makes the same occupancy demote earlier."""
    from dynamo_tpu.planner.degradation import LadderPolicy, ServingDegradation

    policy = LadderPolicy()
    assert policy.fabric_pressure_scale(0) == 1.0
    assert policy.fabric_pressure_scale(1) == pytest.approx(0.75)
    assert policy.fabric_pressure_scale(2) == pytest.approx(0.5625)
    assert policy.fabric_pressure_scale(9) == pytest.approx(
        max(0.25, 0.75 ** 3)
    )

    backend = DictCatalogBackend()
    clock = TickClock()
    dev = FakeDevice(16)
    m = _manager(dev, host_blocks=10, clock=clock)
    fab = _fabric(backend, worker_id=1, clock=clock,
                  pressure=PressureConfig(high_watermark=0.9,
                                          low_watermark=0.6))
    fab.attach(m)
    _commit(dev, m, list(range(401, 409)))  # 8 of 10: below 9.0
    assert m.host.num_cached == 8
    hooks = ServingDegradation(policy=policy, fabric=fab)
    hooks.set_level(2)  # scale 0.5625: high watermark now 5.06 blocks
    assert fab._pressure_scale == pytest.approx(0.5625)
    m.pump()  # no new offloads; the pressure pass runs anyway
    assert m.host.num_cached <= int(0.6 * 0.5625 * 10)
    hooks.set_level(0)
    assert fab._pressure_scale == 1.0


# ---------------------------------------------------------------------------
# Two-worker onboarding (the tentpole's acceptance path)
# ---------------------------------------------------------------------------


def test_two_workers_share_prefix_via_peer_plane():
    """Worker A prefills a prefix; worker B onboards it from A's host
    tier through the peer plane — B never recomputes, and the bytes are
    bit-identical."""
    backend = DictCatalogBackend()
    clock = TickClock()
    peers = LocalPeerRegistry()

    dev_a = FakeDevice(8)
    a = _manager(dev_a, host_blocks=8, clock=clock)
    fab_a = _fabric(backend, worker_id=1, clock=clock, fetcher=peers)
    fab_a.addr = peers.register("a", a.export_host_blocks)
    fab_a.attach(a)
    hashes = [501, 502, 503]
    _commit(dev_a, a, hashes)  # A prefilled: blocks live in A's G2

    dev_b = FakeDevice(8)
    b = _manager(dev_b, host_blocks=8, clock=clock)
    fab_b = _fabric(backend, worker_id=2, clock=clock, fetcher=peers)
    fab_b.attach(b)
    fab_b.catalog.refresh()
    assert fab_b.catalog.match_prefix(hashes, exclude_worker=2) == 3
    assert b.match_offloaded(hashes) == 0  # nothing local yet

    n = b.onboard(hashes, [3, 4, 5])
    assert n == 3  # onboarded, not recomputed
    for slot, h in zip((3, 4, 5), hashes):
        np.testing.assert_array_equal(dev_b.blocks[slot], _block(h))
    assert fab_b.stats.fleet_hits_peer == 3
    assert b.host.contains(501)  # fetched blocks now serve B's repeats
    # and B now advertises its own G2 copies
    assert len(backend.snapshot()[501]) == 2


def test_two_workers_share_via_bucket_adoption():
    """A catalog g4 entry onboards through bucket adoption (no peer
    round trip): worker B learns the key exists without waiting for the
    periodic G4 list refresh."""
    backend = DictCatalogBackend()
    clock = TickClock()
    objects = DictObjectStore()

    dev_a = FakeDevice(8)
    a = _manager(dev_a, host_blocks=1, objects=objects, clock=clock)
    fab_a = _fabric(backend, worker_id=1, clock=clock)
    fab_a.attach(a)
    _commit(dev_a, a, [601])
    _commit(dev_a, a, [602], base_slot=2)  # evicts 601 -> shared bucket
    assert backend.snapshot()[601][1]["tier"] == TIER_SHARED

    dev_b = FakeDevice(8)
    b = _manager(dev_b, host_blocks=4, objects=DictObjectStore(),
                 clock=clock)
    # B's own bucket is EMPTY; share A's object plane like production
    b.remote.objects = objects
    b.remote._known.clear()
    fab_b = _fabric(backend, worker_id=2, clock=clock)
    fab_b.attach(b)
    fab_b.catalog.refresh()
    assert b.onboard([601], [3]) == 1
    np.testing.assert_array_equal(dev_b.blocks[3], _block(601))
    assert fab_b.stats.fleet_hits_bucket == 1


def test_two_workers_over_store_wire_plane():
    """The full store-plane path: catalog in a real (in-memory) store
    reached through the blocking bridge, blocks served over loopback
    sockets with store/wire.py framing."""
    from dynamo_tpu.store.memory import MemoryStore

    loop = asyncio.new_event_loop()
    ready = threading.Event()

    def _run_loop():
        asyncio.set_event_loop(loop)
        ready.set()
        loop.run_forever()

    t = threading.Thread(target=_run_loop, name="store-loop", daemon=True)
    t.start()
    ready.wait(5)

    async def _mkstore():
        return MemoryStore()

    store = asyncio.run_coroutine_threadsafe(_mkstore(), loop).result(5)
    try:
        backend_a = StoreCatalogBackend(store, "testns", loop, timeout_s=5.0)
        backend_b = StoreCatalogBackend(store, "testns", loop, timeout_s=5.0)

        dev_a = FakeDevice(8)
        a = _manager(dev_a, host_blocks=8)
        server = PeerBlockServer(a.export_host_blocks)
        addr = asyncio.run_coroutine_threadsafe(server.start(), loop).result(5)
        fab_a = _fabric(backend_a, worker_id=1, addr=addr)
        fab_a.attach(a)
        hashes = [701, 702]
        _commit(dev_a, a, hashes)

        dev_b = FakeDevice(8)
        b = _manager(dev_b, host_blocks=8)
        fab_b = _fabric(backend_b, worker_id=2, fetcher=TcpPeerClient())
        fab_b.attach(b)
        fab_b.catalog.refresh()  # snapshot over the store plane
        assert fab_b.catalog.match_prefix(hashes, exclude_worker=2) == 2
        assert b.onboard(hashes, [3, 4]) == 2
        for slot, h in zip((3, 4), hashes):
            np.testing.assert_array_equal(dev_b.blocks[slot], _block(h))
        assert fab_b.stats.fleet_hits_peer == 2
        asyncio.run_coroutine_threadsafe(server.stop(), loop).result(5)
    finally:
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=5)


def test_failed_fetch_prunes_and_falls_back_to_recompute():
    """A catalog hit whose every advertised copy is gone must prune the
    entries and read as a clean miss — the engine recomputes, nothing
    raises, nothing dangles."""

    class DeadPeer(LocalPeerRegistry):
        def fetch(self, addr, seq_hashes):
            return None  # peer unreachable

    backend = DictCatalogBackend()
    clock = TickClock()
    backend.put(801, 9, {"tier": TIER_HOST, "bytes": 64, "t": 0.0,
                         "addr": "dead:1"})
    dev = FakeDevice(8)
    m = _manager(dev, host_blocks=4, clock=clock)
    fab = _fabric(backend, worker_id=2, clock=clock, fetcher=DeadPeer())
    fab.attach(m)
    fab.catalog.refresh()
    assert fab.catalog.match_prefix([801], exclude_worker=2) == 1
    assert m.onboard([801], [3]) == 0  # clean miss: engine recomputes
    assert fab.stats.dangling_pruned == 1
    assert 801 not in backend.snapshot()  # advertised owner pruned
    fab.catalog.refresh()
    assert fab.catalog.match_prefix([801]) == 0


def test_fetch_length_mismatch_is_a_miss():
    class ShortPeer(LocalPeerRegistry):
        def fetch(self, addr, seq_hashes):
            return [b"\x00" * 7 for _ in seq_hashes]  # wrong size

    backend = DictCatalogBackend()
    backend.put(811, 9, {"tier": TIER_HOST, "bytes": 64, "t": 0.0,
                         "addr": "short:1"})
    dev = FakeDevice(8)
    m = _manager(dev, host_blocks=4)
    fab = _fabric(backend, worker_id=2, fetcher=ShortPeer())
    fab.attach(m)
    fab.catalog.refresh()
    assert m.onboard([811], [3]) == 0
    assert fab.stats.fetch_failures >= 1
    assert not m.host.contains(811)  # corrupt bytes never land


# ---------------------------------------------------------------------------
# Router: discounted fleet scoring + the resume/demotion race
# ---------------------------------------------------------------------------


class _FixedCatalog:
    def __init__(self, blocks):
        self.blocks = blocks

    def match_prefix(self, seq_hashes):
        return min(self.blocks, len(seq_hashes))


def _scheduler(catalog=None):
    from dynamo_tpu.kv_router.indexer import KvIndexer
    from dynamo_tpu.kv_router.scheduler import KvMetricsAggregator, KvScheduler

    indexer = KvIndexer(block_size=4)
    agg = KvMetricsAggregator()
    captured = {}

    def selector(overlaps, metrics, candidates):
        captured["scores"] = dict(overlaps.scores)
        return sorted(candidates)[0]

    sched = KvScheduler(indexer, agg, selector=selector,
                        fleet_catalog=catalog)
    return sched, indexer, captured


def test_fleet_blocks_score_at_discounted_weight():
    from dynamo_tpu.kv_router.protocols import ForwardPassMetrics

    sched, indexer, captured = _scheduler(_FixedCatalog(blocks=4))
    sched.aggregator.update(ForwardPassMetrics(worker_id=1))
    sched.aggregator.update(ForwardPassMetrics(worker_id=2))
    prompt = list(range(32))  # 8 blocks
    decision = sched.schedule(prompt, [1, 2])
    w = sched.fleet_hit_weight
    # no local overlap anywhere: both candidates score w*fleet
    assert captured["scores"][1] == pytest.approx(w * 4)
    assert captured["scores"][2] == pytest.approx(w * 4)
    assert decision.fleet_blocks == 4
    assert decision.overlap_blocks == 0  # decision reports TRUE overlap


def test_local_overlap_dominates_fleet_extension():
    """A worker's local blocks count at full weight; the fleet term only
    tops up the REMAINDER at the discount — local copies never get
    double-counted and fleet blocks never reach local weight."""
    from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
    from tests.test_kv_router import _seq_hashes, _stored

    sched, indexer, captured = _scheduler(_FixedCatalog(blocks=6))
    prompt = list(range(32))  # 8 blocks
    indexer.apply(_stored(1, _seq_hashes(prompt)[:6]))
    indexer.apply(_stored(2, _seq_hashes(prompt)[:2]))
    sched.aggregator.update(ForwardPassMetrics(worker_id=1))
    sched.aggregator.update(ForwardPassMetrics(worker_id=2))
    sched.schedule(prompt, [1, 2])
    w = sched.fleet_hit_weight
    assert captured["scores"][1] == pytest.approx(6)  # local covers fleet
    assert captured["scores"][2] == pytest.approx(2 + w * 4)
    assert captured["scores"][2] < captured["scores"][1]


def test_resume_racing_demotion_keeps_fleet_discount():
    """The satellite regression: a resume whose prefix was JUST demoted
    off every device (local overlap gone, catalog still hits) must score
    boost*weight*fleet — never boost*fleet as if still resident."""
    from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
    from tests.test_kv_router import _seq_hashes, _stored

    sched, indexer, captured = _scheduler(_FixedCatalog(blocks=8))
    prompt = list(range(32))  # 8 blocks
    sched.aggregator.update(ForwardPassMetrics(worker_id=1))
    sched.aggregator.update(ForwardPassMetrics(worker_id=2))
    # the demotion race: NO worker has local overlap anymore
    decision = sched.schedule(prompt, [1, 2], resume=True)
    boost = sched.resume_overlap_boost
    w = sched.fleet_hit_weight
    assert captured["scores"][1] == pytest.approx(boost * w * 8)
    assert captured["scores"][1] < boost * 8  # never local weight
    assert decision.fleet_blocks == 8

    # contrast: a resume onto a still-resident prefix boosts LOCAL weight
    indexer.apply(_stored(1, _seq_hashes(prompt)))
    sched.schedule(prompt, [1, 2], resume=True)
    assert captured["scores"][1] == pytest.approx(boost * 8)
    # the fleet-only candidate stays discounted under the same boost
    assert captured["scores"][2] == pytest.approx(boost * w * 8)


def test_catalog_failure_never_breaks_routing():
    from dynamo_tpu.kv_router.protocols import ForwardPassMetrics

    class Exploding:
        def match_prefix(self, seq_hashes):
            raise RuntimeError("store down")

    sched, _, captured = _scheduler(Exploding())
    sched.aggregator.update(ForwardPassMetrics(worker_id=1))
    decision = sched.schedule(list(range(8)), [1])
    assert decision.worker_id == 1 and decision.fleet_blocks == 0


# ---------------------------------------------------------------------------
# Remote-bridge timeout surfacing (satellite: remote.py _run)
# ---------------------------------------------------------------------------


def test_store_timeout_surfaces_op_and_books_counter():
    from dynamo_tpu.kvbm.remote import StoreRoundTripTimeout, run_on_loop
    from dynamo_tpu.telemetry.instruments import KVBM_REMOTE_TIMEOUTS

    records = []

    class Recorder:
        def record(self, kind, **kw):
            records.append((kind, kw))

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()

    async def hang():
        await asyncio.sleep(60)

    before = KVBM_REMOTE_TIMEOUTS.labels("get_many").value
    try:
        with pytest.raises(StoreRoundTripTimeout) as exc:
            run_on_loop(hang(), loop, timeout_s=0.05, op="get_many",
                        recorder=Recorder())
    finally:
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=5)
    # the exception carries WHICH plane stalled, not a bare TimeoutError
    assert exc.value.op == "get_many"
    assert exc.value.timeout_s == pytest.approx(0.05)
    assert "get_many" in str(exc.value)
    assert isinstance(exc.value, TimeoutError)  # callers' except clauses
    assert KVBM_REMOTE_TIMEOUTS.labels("get_many").value == before + 1
    assert records and records[0][0] == "kvbm_remote_timeout"
    assert records[0][1]["op"] == "get_many"


def test_catalog_timeout_degrades_not_raises_into_routing():
    """A StoreCatalogBackend timeout surfaces as StoreRoundTripTimeout
    with op=catalog.*; the fabric's refresh path swallows it (the pump
    must degrade to single-worker behavior, not die)."""
    from dynamo_tpu.kvbm.remote import StoreRoundTripTimeout

    class HangingStore:
        async def kv_get_prefix(self, prefix):
            await asyncio.sleep(60)

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    try:
        backend = StoreCatalogBackend(HangingStore(), "ns", loop,
                                      timeout_s=0.05)
        cat = FleetPrefixCatalog(backend, worker_id=1)
        with pytest.raises(StoreRoundTripTimeout) as exc:
            cat.refresh()
        assert exc.value.op == "catalog.snapshot"
        fab = FleetKvFabric(cat)
        fab._last_refresh = -1e9
        fab.maybe_refresh()  # swallowed: logged, not raised
    finally:
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=5)


# ---------------------------------------------------------------------------
# Simulator A/B + the bench gate's compare function
# ---------------------------------------------------------------------------


def _sim_ab(duration=120.0, seed=7):
    from dynamo_tpu.sim import FleetSim, SimConfig, diurnal_trace
    from dynamo_tpu.sim.traces import PrefixModel

    trace = diurnal_trace(duration, seed, base_rps=8.0, peak_rps=24.0,
                          period_s=duration, prefixes=PrefixModel())
    out = {}
    for fabric in (False, True):
        cfg = SimConfig(initial_decode=4, initial_prefill=1,
                        max_queue_depth=200, fabric=fabric)
        out[fabric] = FleetSim(trace, cfg).run()["fabric"]
    return out


def test_sim_fabric_ab_fewer_reprefill_tokens():
    """The acceptance A/B: fabric on shows a positive fleet hit rate and
    STRICTLY fewer prefilled (recomputed) tokens than fabric off."""
    res = _sim_ab()
    off, on = res[False], res[True]
    assert off["enabled"] is False and on["enabled"] is True
    assert on["fleet_hit_rate"] > 0
    assert on["reprefill_tokens_avoided"] > 0
    assert on["prefilled_tokens"] < off["prefilled_tokens"]
    # conservation: every prompt token is either recomputed or fetched
    assert (on["prefilled_tokens"] + on["fleet_fetched_tokens"]
            == off["prefilled_tokens"])


def test_sim_fabric_ab_deterministic():
    a, b = _sim_ab(duration=60.0), _sim_ab(duration=60.0)
    assert a == b


def test_kvfleet_compare_gate_directions():
    import bench

    base = {"hit_rate": 0.6, "avoided_frac": 0.3, "noise_frac": 0.25}
    ok = bench._kvfleet_compare(
        {"hit_rate": 0.55, "avoided_frac": 0.28}, base
    )
    assert not ok["regressed"]
    # either headline under its floor regresses
    assert bench._kvfleet_compare(
        {"hit_rate": 0.4, "avoided_frac": 0.28}, base
    )["regressed"]
    assert bench._kvfleet_compare(
        {"hit_rate": 0.55, "avoided_frac": 0.1}, base
    )["regressed"]
    # the A/B invariant is unconditional: zero hits / no win always gates
    wide = {"hit_rate": 0.001, "avoided_frac": 0.001, "noise_frac": 1.0}
    assert bench._kvfleet_compare(
        {"hit_rate": 0.0, "avoided_frac": 0.5}, wide
    )["regressed"]
    assert bench._kvfleet_compare(
        {"hit_rate": 0.5, "avoided_frac": 0.0}, wide
    )["regressed"]


def test_fabric_debug_stanza_registered():
    from dynamo_tpu.telemetry.debug import collect_debug_state

    backend = DictCatalogBackend()
    dev = FakeDevice(8)
    m = _manager(dev, host_blocks=4)
    fab = _fabric(backend, worker_id=3)
    fab.attach(m)
    try:
        _commit(dev, m, [901, 902])
        state = collect_debug_state()
        stanza = state["kvfleet:w3"]
        assert stanza["catalog"]["entries"] == 2
        assert stanza["watermarks"]["high"] == pytest.approx(0.90)
        assert stanza["resident_tracked"] == 2
    finally:
        m.close()  # unregisters the provider
    assert "kvfleet:w3" not in collect_debug_state()
