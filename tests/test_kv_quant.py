"""Quantized (fp8 E4M3) KV cache: kernel parity and bounded logit error.

The engine's ``kv_cache_dtype="float8_e4m3fn"`` halves KV bytes per
token (doubling long-context residency and halving decode-attention HBM
reads — reference analogue: the vLLM ``--kv-cache-dtype fp8`` option
the reference's engine args pass through). Storage is scale-free E4M3;
the Pallas kernels and the XLA reference path upcast to the compute
dtype at the read edge (exact: every e4m3 value is representable in
bf16). These tests pin down:

- kernel ≡ reference on the SAME quantized contents (both dequantize
  exactly, so they must agree to normal kernel tolerance), and
- the end-to-end quantization error vs a bf16 cache is bounded at the
  logit level (the e4m3 mantissa gives ~2^-4 per-element rounding that
  averages out over the Dh/seq reductions).
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import (
    forward,
    init_cache,
    init_params,
    paged_attention_reference,
)
from dynamo_tpu.ops.paged_attention import (
    paged_attention_decode,
    paged_attention_prefill_stacked,
)

F8 = jnp.float8_e4m3fn


def _setup(B, H, Hk, Dh, num_blocks, bs, ctx_lens, seed=0):
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((B, H, Dh)).astype(np.float32)
    k = rng.standard_normal((num_blocks * bs, Hk, Dh)).astype(np.float32)
    v = rng.standard_normal((num_blocks * bs, Hk, Dh)).astype(np.float32)
    W = max((c + bs - 1) // bs for c in ctx_lens)
    tables = np.zeros((B, W), np.int32)
    next_page = 1
    for b, c in enumerate(ctx_lens):
        n = (c + bs - 1) // bs
        tables[b, :n] = np.arange(next_page, next_page + n, dtype=np.int32)
        next_page += n
    ctx = np.asarray(ctx_lens, np.int32)
    return (
        jnp.asarray(q, jnp.bfloat16),
        jnp.asarray(k, jnp.bfloat16),
        jnp.asarray(v, jnp.bfloat16),
        jnp.asarray(tables),
        jnp.asarray(ctx),
    )


def test_decode_kernel_fp8_matches_reference_same_contents():
    """Kernel vs XLA reference over one shared fp8 cache: both read the
    identical quantized values, so outputs agree to kernel tolerance."""
    Dh, bs, num_blocks = 128, 16, 16
    B, H, Hk = 2, 4, 2
    q, k, v, tables, ctx = _setup(B, H, Hk, Dh, num_blocks, bs, [23, 37])
    k8, v8 = k.astype(F8), v.astype(F8)
    out = paged_attention_decode(q, k8, v8, tables, ctx, bs, interpret=True)
    assert out.dtype == q.dtype
    ref = paged_attention_reference(
        q[:, None], k8, v8, tables, (ctx - 1)[:, None], ctx, bs
    )[:, 0]
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=1e-1, atol=1e-1,
    )


def test_decode_kernel_fp8_error_vs_bf16_bounded():
    """Per-element e4m3 rounding (~6%) must average out over the Dh=128
    and sequence reductions: attention outputs within a few % of bf16."""
    Dh, bs, num_blocks = 128, 16, 16
    B, H, Hk = 2, 8, 4
    q, k, v, tables, ctx = _setup(B, H, Hk, Dh, num_blocks, bs, [40, 64])
    out16 = paged_attention_decode(q, k, v, tables, ctx, bs, interpret=True)
    out8 = paged_attention_decode(
        q, k.astype(F8), v.astype(F8), tables, ctx, bs, interpret=True
    )
    a16 = np.asarray(out16, np.float32)
    a8 = np.asarray(out8, np.float32)
    # relative to the output scale, not elementwise (outputs near zero)
    denom = max(1e-6, float(np.abs(a16).max()))
    assert float(np.abs(a8 - a16).max()) / denom < 0.08


def test_prefill_kernel_fp8_matches_reference():
    """Flash prefill over an fp8 cache (chunk already scattered in, as
    the model does) matches the reference path on the same contents."""
    Dh, bs, num_blocks = 128, 16, 16
    B, H, Hk, T = 2, 4, 2, 16
    rng = np.random.default_rng(3)
    ctx_lens = [16, 9]
    q = jnp.asarray(
        rng.standard_normal((B, T, H, Dh)), jnp.bfloat16
    )
    k = jnp.asarray(
        rng.standard_normal((num_blocks * bs, Hk, Dh)), jnp.bfloat16
    ).astype(F8)
    v = jnp.asarray(
        rng.standard_normal((num_blocks * bs, Hk, Dh)), jnp.bfloat16
    ).astype(F8)
    tables = np.zeros((B, 2), np.int32)
    tables[0], tables[1] = [1, 2], [3, 4]
    tables = jnp.asarray(tables)
    ctx = jnp.asarray(ctx_lens, np.int32)
    starts = jnp.zeros((B,), jnp.int32)
    out = paged_attention_prefill_stacked(
        q, k[None], v[None], jnp.int32(0), tables, starts, ctx, bs,
        interpret=True,
    )
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    ref = paged_attention_reference(
        q, k, v, tables, positions, ctx, bs
    )
    # rows past ctx are padding — compare valid tokens only
    for b, c in enumerate(ctx_lens):
        np.testing.assert_allclose(
            np.asarray(out, np.float32)[b, :c],
            np.asarray(ref, np.float32)[b, :c],
            rtol=1e-1, atol=1e-1,
        )


def _tiny_cfg() -> ModelConfig:
    return ModelConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128,
    )


def test_forward_fp8_cache_bounded_logit_error():
    """One full model step (prefill write + attend) with an fp8 cache:
    logits within a bounded distance of the bf16-cache run — the
    end-to-end 'bounded logit error' contract for quantized KV."""
    cfg = _tiny_cfg()
    params = init_params(cfg, seed=0)
    bs, num_blocks = 8, 16
    B, T = 2, 16
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(1, 255, (B, T)), jnp.int32
    )
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T)).astype(jnp.int32)
    tables = jnp.asarray([[1, 2, 0, 0], [3, 4, 0, 0]], jnp.int32)
    slot = (
        jnp.take_along_axis(
            tables, (positions // bs), axis=1
        ) * bs + positions % bs
    ).reshape(-1)
    ctx = jnp.asarray([T, T], jnp.int32)
    last = jnp.asarray([T - 1, T - 1], jnp.int32)

    outs = {}
    for name, dtype in [("bf16", jnp.bfloat16), ("fp8", F8)]:
        kc, vc = init_cache(cfg, num_blocks, bs, dtype=dtype)
        logits, _, _ = forward(
            cfg, params, kc, vc, tokens, positions, slot, tables, ctx,
            last, bs,
        )
        outs[name] = np.asarray(logits, np.float32)
    d = outs["fp8"] - outs["bf16"]
    scale = max(float(np.abs(outs["bf16"]).max()), 1e-6)
    # E4M3 error budget (the audited bound — storage is deliberately
    # scale-free, write = RN cast, read = exact upcast, so rounding is
    # the WHOLE error): 3 mantissa bits give <= 2^-4 relative error per
    # stored element, entering twice per layer (K jitters the softmax
    # weights, V the weighted sum) and compounding over 3 residual
    # layers of a near-init model with no logit gaps to hide under.
    # Measured on this seed: rms 3.1%, p99 10%, max 11.7% — zero-mean
    # rounding noise (corr(err, logit) ~= -0.13), NOT a systematic
    # scale error, which would show O(1) correlated deviation. The rms
    # bound is the bug-catcher (a 2x dequant-scale bug lands ~0.5);
    # the max-norm bound at 2x the observed tail keeps the contract
    # end-to-end without flaking on a single worst element.
    assert np.sqrt((d * d).mean()) / scale < 0.06, "rms beyond e4m3 budget"
    assert np.abs(d).max() / scale < 0.25, "max-norm beyond e4m3 budget"
    # and the quantization must actually be lossy-but-close, not zeroed
    assert np.abs(outs["fp8"]).max() > 0


async def test_engine_fp8_kv_generates(monkeypatch):
    """Engine e2e with kv_cache_dtype=fp8 (alias accepted): launches,
    prefills through the paged cache, decodes valid tokens."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from tests.test_engine import MODEL_DIR, _generate

    cfg = EngineConfig(
        model_path=MODEL_DIR, model_name="tiny", random_weights=True,
        num_blocks=32, block_size=8, max_batch_size=4,
        prefill_chunk_size=32, max_model_len=128,
        kv_cache_dtype="fp8",
    )
    assert cfg.kv_cache_dtype == "float8_e4m3fn"  # alias normalized
    eng = await JaxEngine.launch(cfg)
    try:
        assert eng.k_cache.dtype == F8
        toks, _ = await _generate(eng, list(range(1, 20)), max_tokens=8)
        assert len(toks) == 8
        assert all(0 <= t < 2048 for t in toks)  # tiny model vocab
    finally:
        await eng.shutdown()


# ---------------------------------------------------------------------------
# int8 cache with per-(token, head) scales (ops/kv_quant.py)
# ---------------------------------------------------------------------------


def _quantize_layer(k):
    """Float [S, Hk, Dh] -> (int8 [S, Hk, Dh], scales [N, Hk*bs]) in the
    kernel's hk-major page layout, for bs inferred by the caller."""
    from dynamo_tpu.ops.kv_quant import quantize_kv

    q8, sc = quantize_kv(k)  # sc [S, Hk]
    return q8, sc


def _scales_to_layout(sc, bs):
    S, Hk = sc.shape
    N = S // bs
    return sc.reshape(N, bs, Hk).transpose(0, 2, 1)  # [N, Hk, bs]


def test_decode_kernel_int8_matches_dequant_reference():
    """int8 kernel (scales applied in-register) vs the XLA reference on
    the SAME quantized contents: K's scale lands on f32 scores and V's
    on f32 probabilities, so agreement is at bf16-dot tolerance."""
    Dh, bs, num_blocks = 128, 16, 16
    B, H, Hk = 2, 8, 4
    q, k, v, tables, ctx = _setup(B, H, Hk, Dh, num_blocks, bs, [23, 37])
    k8, ksc = _quantize_layer(k)
    v8, vsc = _quantize_layer(v)
    out = paged_attention_decode(
        q, k8, v8, tables, ctx, bs, interpret=True,
        k_scale=jnp.asarray(_scales_to_layout(ksc, bs)),
        v_scale=jnp.asarray(_scales_to_layout(vsc, bs)),
    )
    from dynamo_tpu.models.llama import paged_attention_reference

    ref = paged_attention_reference(
        q[:, None],
        (k8, jnp.asarray(_scales_to_layout(ksc, bs))),
        (v8, jnp.asarray(_scales_to_layout(vsc, bs))),
        tables, (ctx - 1)[:, None], ctx, bs,
    )[:, 0]
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=5e-2, atol=5e-2,
    )


def test_decode_kernel_int8_error_vs_bf16_bounded():
    """Per-(token, head) int8 rounding (~0.4%/elem) must leave decode
    attention outputs within ~2% of the bf16 cache."""
    Dh, bs, num_blocks = 128, 16, 16
    B, H, Hk = 2, 8, 4
    q, k, v, tables, ctx = _setup(B, H, Hk, Dh, num_blocks, bs, [40, 64])
    out16 = paged_attention_decode(q, k, v, tables, ctx, bs, interpret=True)
    k8, ksc = _quantize_layer(k)
    v8, vsc = _quantize_layer(v)
    out8 = paged_attention_decode(
        q, k8, v8, tables, ctx, bs, interpret=True,
        k_scale=jnp.asarray(_scales_to_layout(ksc, bs)),
        v_scale=jnp.asarray(_scales_to_layout(vsc, bs)),
    )
    a16 = np.asarray(out16, np.float32)
    a8 = np.asarray(out8, np.float32)
    denom = max(1e-6, float(np.abs(a16).max()))
    assert float(np.abs(a8 - a16).max()) / denom < 0.02


def test_prefill_kernel_int8_matches_reference():
    Dh, bs, num_blocks = 128, 16, 16
    B, H, Hk, T = 2, 4, 2, 16
    rng = np.random.default_rng(3)
    ctx_lens = [16, 9]
    q = jnp.asarray(rng.standard_normal((B, T, H, Dh)), jnp.bfloat16)
    k = jnp.asarray(
        rng.standard_normal((num_blocks * bs, Hk, Dh)), jnp.bfloat16
    )
    v = jnp.asarray(
        rng.standard_normal((num_blocks * bs, Hk, Dh)), jnp.bfloat16
    )
    k8, ksc = _quantize_layer(k)
    v8, vsc = _quantize_layer(v)
    ks_l = jnp.asarray(_scales_to_layout(ksc, bs))
    vs_l = jnp.asarray(_scales_to_layout(vsc, bs))
    tables = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    ctx = jnp.asarray(ctx_lens, np.int32)
    starts = jnp.zeros((B,), jnp.int32)
    out = paged_attention_prefill_stacked(
        q, k8[None], v8[None], jnp.int32(0), tables, starts, ctx, bs,
        interpret=True, k_scale=ks_l[None], v_scale=vs_l[None],
    )
    from dynamo_tpu.models.llama import paged_attention_reference

    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    ref = paged_attention_reference(
        q, (k8, ks_l), (v8, vs_l), tables, positions, ctx, bs
    )
    for b, c in enumerate(ctx_lens):
        np.testing.assert_allclose(
            np.asarray(out, np.float32)[b, :c],
            np.asarray(ref, np.float32)[b, :c],
            rtol=5e-2, atol=5e-2,
        )


def test_forward_int8_cache_bounded_logit_error():
    """Full model step with the int8 (values, scales) cache: logits
    within ~3% of the bf16 run — int8-per-token beats fp8's e4m3
    rounding by an order of magnitude."""
    cfg = _tiny_cfg()
    params = init_params(cfg, seed=0)
    bs, num_blocks = 8, 16
    B, T = 2, 16
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(1, 255, (B, T)), jnp.int32
    )
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T)).astype(jnp.int32)
    tables = jnp.asarray([[1, 2, 0, 0], [3, 4, 0, 0]], jnp.int32)
    slot = (
        jnp.take_along_axis(tables, (positions // bs), axis=1) * bs
        + positions % bs
    ).reshape(-1)
    ctx = jnp.asarray([T, T], jnp.int32)
    last = jnp.asarray([T - 1, T - 1], jnp.int32)

    outs = {}
    for name, dtype in [("bf16", jnp.bfloat16), ("int8", jnp.int8)]:
        kc, vc = init_cache(cfg, num_blocks, bs, dtype=dtype)
        logits, kc2, vc2 = forward(
            cfg, params, kc, vc, tokens, positions, slot, tables, ctx,
            last, bs,
        )
        outs[name] = np.asarray(logits, np.float32)
        if name == "int8":
            # the carried cache stays a (values, scales) pair
            assert isinstance(kc2, tuple) and kc2[0].dtype == jnp.int8
    diff = np.abs(outs["int8"] - outs["bf16"]).max()
    scale = np.abs(outs["bf16"]).max()
    assert diff / max(scale, 1e-6) < 0.03, (diff, scale)


def test_int8_block_gather_scatter_roundtrip():
    """Tier boundary: int8 cache -> packed bf16 blocks -> scatter back.
    The bf16 wire rounds dequantized values to 8 mantissa bits, so a
    round-trip reproduces values within ±1 int8 step and scales within
    bf16 precision — the same error order as quantization itself."""
    from dynamo_tpu.ops.block_copy import gather_blocks, scatter_blocks
    from dynamo_tpu.ops.kv_quant import kv_scale_shape, quantize_kv

    L, bs, num_blocks, Hk, Dh = 3, 16, 8, 2, 128
    rng = np.random.default_rng(0)
    raw = rng.standard_normal((L, num_blocks * bs, Hk, Dh)).astype(np.float32)
    q8, sc = quantize_kv(jnp.asarray(raw))
    sc_l = jnp.asarray(
        np.asarray(sc).reshape(L, num_blocks, bs, Hk).transpose(0, 1, 3, 2)
    )
    k = (q8, sc_l)
    v = (jnp.array(q8), jnp.array(sc_l))  # distinct buffers: scatter donates
    packed = gather_blocks(k, v, [2, 5], bs)
    assert packed.dtype == np.asarray(jnp.zeros(1, jnp.bfloat16)).dtype
    assert packed.shape == (2, 2, L, bs, Hk, Dh)
    # wipe the two blocks, scatter the packed copy back
    sc_np = np.asarray(sc_l)  # snapshot: scatter DONATES its cache args
    kz = (q8.at[:, 2 * bs:3 * bs].set(0), jnp.array(sc_l))
    (k2, ks2), _ = scatter_blocks(kz, v, [2, 5], packed, bs)
    dv = (
        np.asarray(k2[:, 2 * bs:3 * bs], np.int32)
        - np.asarray(q8[:, 2 * bs:3 * bs], np.int32)
    )
    assert np.abs(dv).max() <= 1, np.abs(dv).max()
    np.testing.assert_allclose(np.asarray(ks2), sc_np, rtol=1e-2)


async def test_engine_int8_kv_generates():
    """Engine e2e with kv_cache_dtype=int8 on the CPU reference path."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from tests.test_engine import MODEL_DIR, _generate

    cfg = EngineConfig(
        model_path=MODEL_DIR, model_name="tiny", random_weights=True,
        num_blocks=32, block_size=8, max_batch_size=4,
        prefill_chunk_size=32, max_model_len=128,
        kv_cache_dtype="int8",
    )
    eng = await JaxEngine.launch(cfg)
    try:
        assert isinstance(eng.k_cache, tuple)
        toks, _ = await _generate(eng, list(range(1, 20)), max_tokens=8)
        assert len(toks) == 8
        assert all(0 <= t < 2048 for t in toks)
    finally:
        await eng.shutdown()


async def test_engine_int8_kv_matches_bf16_greedy():
    """Greedy decode tokens under the int8 cache match the bf16 cache on
    the tiny model (quantization noise far below the logit gaps)."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from tests.test_engine import MODEL_DIR, _generate

    base = dict(
        model_path=MODEL_DIR, model_name="tiny", random_weights=True,
        num_blocks=32, block_size=8, max_batch_size=4,
        prefill_chunk_size=32, max_model_len=128,
    )
    prompt = list(range(1, 20))
    eng = await JaxEngine.launch(EngineConfig(**base))
    try:
        ref_toks, _ = await _generate(eng, prompt, max_tokens=6)
    finally:
        await eng.shutdown()
    eng = await JaxEngine.launch(EngineConfig(**base, kv_cache_dtype="int8"))
    try:
        q_toks, _ = await _generate(eng, prompt, max_tokens=6)
    finally:
        await eng.shutdown()
    assert q_toks == ref_toks
