"""TP-mismatch KV resharding (reference: Triton kv_rearrange kernels,
vLLM patch :914-1046; here a logical head-axis transform + transfer-plane
assembly of per-rank head slices)."""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.disagg.transfer import TransferClient, TransferMetadata, TransferServer
from dynamo_tpu.kvbm.layout import BlockLayout
from dynamo_tpu.ops.kv_rearrange import (
    cast_packed,
    extract_tp_shard,
    head_range,
    is_primary_rank,
    merge_tp_shards,
    rearrange_tp,
    rearrange_tp_device,
)


def _packed(n_blocks=3, L=2, bs=4, Hkv=8, Dh=5, dtype=np.float32):
    rng = np.random.default_rng(0)
    return rng.standard_normal((n_blocks, 2, L, bs, Hkv, Dh)).astype(dtype)


def test_head_range_even_and_replicated():
    assert head_range(8, 4, 1) == (2, 2)
    assert head_range(8, 8, 7) == (7, 1)
    # replicated: 2 heads over tp=8 -> 4 replicas each
    assert head_range(2, 8, 0) == (0, 1)
    assert head_range(2, 8, 3) == (0, 1)
    assert head_range(2, 8, 4) == (1, 1)
    assert is_primary_rank(2, 8, 0) and not is_primary_rank(2, 8, 1)
    assert is_primary_rank(2, 8, 4)
    assert is_primary_rank(8, 4, 3)  # even sharding: all primary
    with pytest.raises(ValueError):
        head_range(6, 4, 0)
    with pytest.raises(ValueError):
        head_range(8, 4, 4)


def test_rearrange_tp_roundtrip():
    full = _packed()
    # tp1 -> tp4 -> tp2 -> merge back
    tp4 = rearrange_tp([full], 1, 4, 8)
    assert len(tp4) == 4 and tp4[0].shape[-2] == 2
    tp2 = rearrange_tp(tp4, 4, 2, 8)
    merged = merge_tp_shards(tp2, 2, 8)
    np.testing.assert_array_equal(merged, full)
    # replicated destination: every dst rank gets its (single) head copy
    small = _packed(Hkv=2)
    reps = rearrange_tp([small], 1, 4, 2)
    assert len(reps) == 4
    np.testing.assert_array_equal(reps[0], reps[1])
    np.testing.assert_array_equal(reps[0], small[..., 0:1, :])


def test_rearrange_tp_device_matches_numpy():
    full = _packed(Hkv=8)
    src = np.stack([extract_tp_shard(full, 2, r) for r in range(2)])
    out = np.asarray(rearrange_tp_device(src, 2, 4))
    want = np.stack(rearrange_tp(list(src), 2, 4, 8))
    np.testing.assert_allclose(out, want)


def test_cast_packed():
    x = _packed(n_blocks=1, dtype=np.float32)
    import ml_dtypes

    y = cast_packed(x, np.dtype(ml_dtypes.bfloat16))
    assert y.dtype == np.dtype(ml_dtypes.bfloat16)
    assert cast_packed(y, y.dtype) is y


async def test_transfer_head_slice_assembly_and_cast():
    """Two TP2 prefill ranks ship f32 head slices; the server assembles
    full-head blocks, casts to its bf16 layout, delivers exactly once."""
    import ml_dtypes

    layout = BlockLayout(num_layers=2, block_size=4, num_kv_heads=8,
                         head_dim=5, dtype="bfloat16")
    delivered: list[tuple[list[int], np.ndarray]] = []

    async def deliver(hashes, packed):
        delivered.append((hashes, packed))

    server = TransferServer(deliver, layout)
    await server.start()
    try:
        meta = TransferMetadata("127.0.0.1", server.port, 1, layout.to_json())
        full = _packed(n_blocks=2, dtype=np.float32)
        hashes = [11, 22]
        ev = server.completion_event("r1")
        for rank in range(2):
            start, count = head_range(8, 2, rank)
            ok = await TransferClient.put(
                meta, "r1", hashes, extract_tp_shard(full, 2, rank),
                head_start=start, head_count=count,
            )
            assert ok
        await asyncio.wait_for(ev.wait(), 5)
        assert len(delivered) == 1
        got_hashes, got = delivered[0]
        assert got_hashes == hashes
        assert got.dtype == np.dtype(ml_dtypes.bfloat16)
        np.testing.assert_allclose(
            got.astype(np.float32), full.astype(ml_dtypes.bfloat16).astype(np.float32)
        )
        assert not server._assembling
    finally:
        await server.close()


async def test_transfer_partial_budget_rejected_not_evicted():
    """At the assembly byte budget, a NEW partial transfer is refused
    (ok=false) while the in-flight assembly stays alive and completes."""
    layout = BlockLayout(num_layers=1, block_size=2, num_kv_heads=2,
                         head_dim=3, dtype="float32")
    delivered = []

    async def deliver(h, p):
        delivered.append(h)

    server = TransferServer(deliver, layout)
    await server.start()
    server.MAX_ASSEMBLY_BYTES = layout.block_bytes  # room for one 1-block asm
    try:
        meta = TransferMetadata("127.0.0.1", server.port, 1, layout.to_json())
        full = _packed(n_blocks=1, L=1, bs=2, Hkv=2, Dh=3)
        first = extract_tp_shard(full, 2, 0)
        assert await TransferClient.put(meta, "a", [1], first,
                                        head_start=0, head_count=1)
        # budget exhausted: a second request's partial slice is rejected
        assert not await TransferClient.put(meta, "b", [2], first,
                                            head_start=0, head_count=1)
        # ...but request "a" still completes
        assert await TransferClient.put(
            meta, "a", [1], extract_tp_shard(full, 2, 1),
            head_start=1, head_count=1,
        )
        assert delivered == [[1]]
        assert not server._assembling
    finally:
        await server.close()


async def test_late_slice_after_abandon_rejected():
    """A slice arriving after its assembly was abandoned must be refused
    (its sibling slices were acked then dropped — re-seeding could never
    complete while both senders saw success)."""
    layout = BlockLayout(num_layers=1, block_size=2, num_kv_heads=2,
                         head_dim=3, dtype="float32")
    server = TransferServer(lambda h, p: asyncio.sleep(0), layout)
    await server.start()
    try:
        meta = TransferMetadata("127.0.0.1", server.port, 1, layout.to_json())
        full = _packed(n_blocks=1, L=1, bs=2, Hkv=2, Dh=3)
        assert await TransferClient.put(
            meta, "gone", [5], extract_tp_shard(full, 2, 0),
            head_start=0, head_count=1,
        )
        server.discard_completion("gone")  # request abandoned
        assert not await TransferClient.put(
            meta, "gone", [5], extract_tp_shard(full, 2, 1),
            head_start=1, head_count=1,
        )
        assert not server._assembling
    finally:
        await server.close()


async def test_batch_file_error_isolation(tmp_path):
    """One failing prompt must not discard the batch (gather isolates
    errors; bad lines are rejected at load)."""
    import json as _json

    from dynamo_tpu.cli.main import _batch_file
    from dynamo_tpu.engines import EchoEngineFull

    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"prompt": "wrong key"}\n')
    with pytest.raises(SystemExit, match="line 1"):
        await _batch_file(EchoEngineFull(), "echo", str(bad), None, None)


async def test_transfer_rejects_bad_head_slice():
    layout = BlockLayout(num_layers=1, block_size=2, num_kv_heads=4,
                         head_dim=3, dtype="float32")
    server = TransferServer(lambda h, p: asyncio.sleep(0), layout)
    await server.start()
    try:
        meta = TransferMetadata("127.0.0.1", server.port, 1, layout.to_json())
        bad = np.zeros((1, 2, 1, 2, 3, 3), np.float32)  # 3 heads: no valid slice
        ok = await TransferClient.put(meta, "r", [1], bad, head_start=2,
                                      head_count=3)
        assert not ok
    finally:
        await server.close()
