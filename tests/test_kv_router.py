"""KV router tests (≈ reference kv_router/indexer.rs + scheduler.rs tests,
plus an end-to-end routed-serving test over the real runtime)."""

import asyncio
import random

from dynamo_tpu.kv_router.indexer import KvIndexer, RadixTree
from dynamo_tpu.kv_router.protocols import (
    ForwardPassMetrics,
    KvCacheEvent,
    RouterEvent,
)
from dynamo_tpu.kv_router.recorder import KvRecorder, replay_into
from dynamo_tpu.kv_router.scheduler import (
    KvMetricsAggregator,
    KvScheduler,
    default_selector,
)
from dynamo_tpu.tokens import compute_block_hashes_for_seq, compute_seq_hashes


def _stored(worker, hashes, eid=0, block_size=4):
    return RouterEvent(
        worker_id=worker,
        event_id=eid,
        event=KvCacheEvent(
            op="stored", block_hashes=hashes, token_block_size=block_size
        ),
    )


def _removed(worker, hashes, eid=0, block_size=4):
    return RouterEvent(
        worker_id=worker,
        event_id=eid,
        event=KvCacheEvent(
            op="removed", block_hashes=hashes, token_block_size=block_size
        ),
    )


def _seq_hashes(tokens, block_size=4):
    return compute_seq_hashes(compute_block_hashes_for_seq(tokens, block_size))


def test_radix_overlap_longest_prefix():
    tree = RadixTree()
    prompt = list(range(40))
    h = _seq_hashes(prompt)  # 10 blocks
    tree.apply_event(_stored(1, h[:8]))
    tree.apply_event(_stored(2, h[:3]))
    scores = tree.find_matches(h)
    assert scores.scores == {1: 8, 2: 3}
    assert scores.total_blocks == 10
    # divergent suffix: only the shared prefix counts
    other = _seq_hashes(list(range(12)) + [99] * 28)
    scores2 = tree.find_matches(other)
    assert scores2.scores == {1: 3, 2: 3}


def test_radix_non_prefix_gap_breaks_match():
    tree = RadixTree()
    h = _seq_hashes(list(range(24)))  # 6 blocks
    # worker has blocks 0,1 and 3.. (gap at 2): usable overlap is 2
    tree.apply_event(_stored(1, h[:2] + h[3:]))
    assert tree.find_matches(h).scores == {1: 2}


def test_radix_removal_and_worker_cleanup():
    tree = RadixTree()
    h = _seq_hashes(list(range(16)))
    tree.apply_event(_stored(1, h))
    tree.apply_event(_stored(2, h))
    tree.apply_event(_removed(1, h[2:]))
    assert tree.find_matches(h).scores == {1: 2, 2: 4}
    tree.remove_worker(2)
    assert tree.find_matches(h).scores == {1: 2}
    assert tree.workers() == {1}
    tree.apply_event(
        RouterEvent(worker_id=1, event=KvCacheEvent(op="cleared"))
    )
    assert tree.num_blocks == 0


def test_default_selector_cost_function():
    h = _seq_hashes(list(range(32)))  # 8 blocks
    tree = RadixTree()
    tree.apply_event(_stored(1, h[:6]))  # big overlap
    tree.apply_event(_stored(2, h[:1]))
    overlaps = tree.find_matches(h)
    metrics = {
        1: ForwardPassMetrics(worker_id=1, gpu_cache_usage_perc=0.5, num_requests_waiting=4),
        2: ForwardPassMetrics(worker_id=2, gpu_cache_usage_perc=0.1, num_requests_waiting=0),
    }
    # 2*6 - 0.5 - 1.0 = 10.5 vs 2*1 - 0.1 - 0 = 1.9 -> worker 1
    assert default_selector(overlaps, metrics, [1, 2]) == 1
    # if worker 1 loses its overlap edge, load wins
    overlaps2 = tree.find_matches(_seq_hashes([999] * 32))
    random.seed(0)
    assert default_selector(overlaps2, metrics, [1, 2]) == 2


def test_scheduler_decision_and_hit_rate_event():
    indexer = KvIndexer(block_size=4)
    agg = KvMetricsAggregator()
    events = []
    sched = KvScheduler(indexer, agg, on_hit_rate=events.append)
    prompt = list(range(40))
    indexer.apply(_stored(7, _seq_hashes(prompt)[:5]))
    agg.update(ForwardPassMetrics(worker_id=7))
    decision = sched.schedule(prompt, [7, 8])
    assert decision.worker_id == 7
    assert decision.overlap_blocks == 5 and decision.total_blocks == 10
    assert decision.prefix_hit_rate == 0.5
    assert events[0].worker_id == 7 and events[0].overlap_blocks == 5


def test_recorder_roundtrip(tmp_path):
    path = str(tmp_path / "events.jsonl")
    h = _seq_hashes(list(range(8)))
    with KvRecorder(path) as rec:
        rec.record(_stored(1, h, eid=1))
        rec.record(_removed(1, h[1:], eid=2))
    tree = RadixTree()
    n = replay_into(path, tree.apply_event)
    assert n == 2
    assert tree.find_matches(h).scores == {1: 1}


async def test_kv_routed_serving_end_to_end():
    """Two engine-less mock workers publish KV events; the KvPushRouter
    routes a request with a matching prefix to the owning worker."""
    from dynamo_tpu.kv_router.publisher import KvEventPublisher, KvMetricsPublisher
    from dynamo_tpu.kv_router.router import KvPushRouter, KvRouter
    from dynamo_tpu.protocols.common import PreprocessedRequest
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.engine import Context, FnEngine
    from dynamo_tpu.runtime.runtime import DistributedRuntime

    drt = await DistributedRuntime.create(
        config=RuntimeConfig(static=True, worker_host="127.0.0.1")
    )
    try:
        comp = drt.namespace("ns").component("worker")
        ep = comp.endpoint("generate")

        served_by = []

        def make_engine(tag):
            async def gen(request, ctx):
                served_by.append(tag)
                yield {"worker": tag}

            return FnEngine(gen)

        # two instances on explicit lease ids
        lease_a = await drt.store.lease_grant(30)
        lease_b = await drt.store.lease_grant(30)
        await ep.serve(make_engine("A"), lease_id=lease_a)
        # same process serves both (one TCP server, one engine per path is
        # keyed by endpoint path... use a second endpoint server trick):
        # instead, register engine B under a second DRT to get a distinct
        # instance.
        drt2 = await DistributedRuntime.create(
            config=RuntimeConfig(static=True, worker_host="127.0.0.1"),
            store=drt.store,
        )
        ep2 = drt2.namespace("ns").component("worker").endpoint("generate")
        await ep2.serve(make_engine("B"), lease_id=lease_b)

        client = await ep.client()
        await client.wait_for_instances()
        for _ in range(100):
            if len(client.instance_ids()) == 2:
                break
            await asyncio.sleep(0.02)

        router = await KvRouter.create(comp, client, block_size=4)
        pub_a = KvEventPublisher(comp, worker_id=lease_a, block_size=4)

        prompt = list(range(32))
        pub_a.sink("stored", _seq_hashes(prompt), [])
        await asyncio.sleep(0.1)  # let the event flow through pub/sub

        push = KvPushRouter(router)
        req = PreprocessedRequest(request_id="r1", token_ids=prompt)
        items = [x async for x in push.generate(req, Context())]
        assert items == [{"worker": "A"}]
        assert "kv_hit_rate:1.000" in req.annotations

        await router.close()
        await client.close()
        await drt2.shutdown()
    finally:
        await drt.shutdown()


def _load_replay_corpus():
    import json
    import os

    d = os.path.join(os.path.dirname(__file__), "data", "replays")
    corpus = os.path.join(d, "kv_events.jsonl")
    with open(os.path.join(d, "kv_events.golden.json")) as f:
        return corpus, json.load(f)


def test_replay_corpus_regression_python_tree():
    """The committed replay corpus must produce the committed golden
    overlap scores (reference strategy: lib/llm/tests/data/replays/).
    Catches any behavioral drift in event application or matching."""
    from dynamo_tpu.kv_router.indexer import RadixTree
    from dynamo_tpu.kv_router.recorder import replay_into
    from dynamo_tpu.tokens import hash_sequence

    corpus, golden = _load_replay_corpus()
    tree = RadixTree()
    n = replay_into(corpus, tree.apply_event)
    assert n == 46
    assert tree.num_blocks == golden["num_blocks"]
    for name, q in golden["queries"].items():
        _, hashes = hash_sequence(q["tokens"], 16)
        scores = tree.find_matches(hashes)
        assert scores.scores == {int(k): v for k, v in q["scores"].items()}, name
        assert scores.total_blocks == q["total_blocks"], name


def test_replay_corpus_regression_native_and_sharded():
    """Native C++ tree and the sharded indexer must match the python
    tree's golden scores exactly."""
    from dynamo_tpu import native
    from dynamo_tpu.kv_router.indexer import KvIndexerSharded, NativeRadixTree
    from dynamo_tpu.kv_router.recorder import iter_replay
    from dynamo_tpu.tokens import hash_sequence

    corpus, golden = _load_replay_corpus()
    impls = {}
    if native.is_available():
        impls["native"] = NativeRadixTree()
    for impl_name, tree in impls.items():
        for ev in iter_replay(corpus):
            tree.apply_event(ev)
        assert tree.num_blocks == golden["num_blocks"], impl_name
        for name, q in golden["queries"].items():
            _, hashes = hash_sequence(q["tokens"], 16)
            scores = tree.find_matches(hashes)
            assert scores.scores == {
                int(k): v for k, v in q["scores"].items()
            }, f"{impl_name}:{name}"

    for n_shards in (1, 4):
        idx = KvIndexerSharded(num_shards=n_shards, block_size=16)
        try:
            for ev in iter_replay(corpus):
                idx.apply(ev)
            # queues drain asynchronously: poll until applied
            import time

            for _ in range(100):
                if idx.applied_events == 46:
                    break
                time.sleep(0.02)
            assert idx.applied_events == 46
            if n_shards == 1:
                assert idx.num_blocks == golden["num_blocks"]
            else:
                # hashes shared by workers on different shards count per
                # shard: per-shard sum bounds unique count from above
                assert idx.num_blocks >= golden["num_blocks"]
            for name, q in golden["queries"].items():
                _, hashes = hash_sequence(q["tokens"], 16)
                scores = idx.find_matches(hashes)
                assert scores.scores == {
                    int(k): v for k, v in q["scores"].items()
                }, f"shards={n_shards}:{name}"
        finally:
            idx.close_threads()


def test_sharded_indexer_worker_lifecycle():
    """Worker assignment balances across shards; remove_worker drops all
    of that worker's blocks; find_matches_for_request hashes correctly."""
    from dynamo_tpu.kv_router.indexer import KvIndexerSharded
    from dynamo_tpu.kv_router.protocols import KvCacheEvent, RouterEvent
    from dynamo_tpu.tokens import hash_sequence

    idx = KvIndexerSharded(num_shards=3, block_size=4)
    try:
        toks = list(range(1, 13))  # 3 blocks
        _, hashes = hash_sequence(toks, 4)
        for wid in (11, 22, 33, 44, 55, 66):
            idx.apply(RouterEvent(
                worker_id=wid, event_id=1,
                event=KvCacheEvent(op="stored", block_hashes=hashes,
                                   token_block_size=4),
            ))
        # 6 workers over 3 shards -> 2 each (least-loaded assignment)
        assert sorted(idx._counts) == [2, 2, 2]
        import time

        for _ in range(100):
            if idx.applied_events == 6:
                break
            time.sleep(0.02)
        scores = idx.find_matches_for_request(toks)
        assert scores.scores == {w: 3 for w in (11, 22, 33, 44, 55, 66)}
        idx.remove_worker(33)
        for _ in range(100):
            if 33 not in idx.find_matches(hashes).scores:
                break
            time.sleep(0.02)
        assert 33 not in idx.find_matches(hashes).scores
        assert idx._counts.count(1) == 1  # freed a slot on 33's shard
    finally:
        idx.close_threads()
