"""Multi-tier KV block manager tests.

Ladder mirrors the reference's block-manager test strategy (SURVEY.md §4):
pure pool/layout logic with Null/host storage, then gather/scatter ops on
the virtual CPU backend, then the full engine with offload tiers enabled
— the CPU-JAX equivalent of testing against NullDeviceStorage.
"""

import asyncio
import os

import numpy as np
import pytest

from dynamo_tpu.kvbm import (
    BlockLayout,
    DiskBlockStorage,
    HostBlockStorage,
    KvbmConfig,
    KvBlockManager,
    NullBlockStorage,
    TierPool,
)

LAYOUT = BlockLayout(num_layers=2, block_size=4, num_kv_heads=2, head_dim=8)


def _block(seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.standard_normal(LAYOUT.packed_shape).astype(LAYOUT.np_dtype)


# ---------------------------------------------------------------------------
# Layout
# ---------------------------------------------------------------------------


def test_layout_roundtrip_and_sizes():
    s = LAYOUT.to_json()
    back = BlockLayout.from_json(s)
    assert back == LAYOUT
    assert LAYOUT.packed_shape == (2, 2, 4, 2, 8)
    assert LAYOUT.block_elems == 2 * 2 * 4 * 2 * 8
    assert LAYOUT.block_bytes == LAYOUT.block_elems * 2  # bf16


# ---------------------------------------------------------------------------
# Tier pool
# ---------------------------------------------------------------------------


def test_tier_pool_insert_read_dedupe():
    pool = TierPool(HostBlockStorage(LAYOUT, 4))
    b1, b2 = _block(1), _block(2)
    pool.insert(101, b1)
    pool.insert(102, b2)
    pool.insert(101, _block(99))  # dedupe: ignored
    assert pool.num_cached == 2
    np.testing.assert_array_equal(pool.read([101])[0], b1)
    np.testing.assert_array_equal(pool.read([102])[0], b2)
    assert pool.match_prefix([101, 102, 103]) == 2
    assert pool.match_prefix([999, 101]) == 0


def test_tier_pool_lru_eviction_and_demotion_hook():
    demoted = []
    pool = TierPool(
        HostBlockStorage(LAYOUT, 2),
        on_evict=lambda h, d: demoted.append((h, d.copy())),
    )
    pool.insert(1, _block(1))
    pool.insert(2, _block(2))
    pool.read([1])  # touch 1 -> 2 becomes LRU
    pool.insert(3, _block(3))  # evicts 2
    assert not pool.contains(2) and pool.contains(1) and pool.contains(3)
    assert len(demoted) == 1 and demoted[0][0] == 2
    np.testing.assert_array_equal(demoted[0][1], _block(2))


def test_tier_pool_insert_many_null_storage():
    pool = TierPool(NullBlockStorage(LAYOUT, 8))
    data = np.stack([_block(i) for i in range(5)])
    pool.insert_many([10, 11, 12, 13, 14], data)
    assert pool.num_cached == 5
    assert pool.match_prefix([10, 11, 12, 13, 14, 15]) == 5


def test_tier_pool_insert_many_overflow_demotes_real_data():
    """A batch larger than the tier must demote same-batch victims with
    their real contents (writes may not be deferred past evictions)."""
    demoted = []
    pool = TierPool(
        HostBlockStorage(LAYOUT, 2),
        on_evict=lambda h, d: demoted.append((h, d.copy())),
    )
    data = np.stack([_block(i) for i in range(4)])
    pool.insert_many([0, 1, 2, 3], data)
    assert pool.num_cached == 2
    assert [h for h, _ in demoted] == [0, 1]
    np.testing.assert_array_equal(demoted[0][1], _block(0))
    np.testing.assert_array_equal(demoted[1][1], _block(1))


def test_disk_storage_roundtrip(tmp_path):
    st = DiskBlockStorage(LAYOUT, 4, str(tmp_path / "kv.bin"))
    data = np.stack([_block(7), _block(8)])
    st.write_blocks([0, 3], data)
    got = st.read_blocks([3, 0])
    np.testing.assert_array_equal(got[0], _block(8))
    np.testing.assert_array_equal(got[1], _block(7))
    st.close()
    assert not os.path.exists(st.path)


# ---------------------------------------------------------------------------
# Device gather/scatter ops (CPU-JAX)
# ---------------------------------------------------------------------------


def test_gather_scatter_roundtrip():
    import jax.numpy as jnp

    from dynamo_tpu.ops.block_copy import gather_blocks, scatter_blocks

    L, N, bs, H, D = 2, 8, 4, 2, 8
    k = jnp.zeros((L, N * bs, H, D), jnp.bfloat16)
    v = jnp.zeros((L, N * bs, H, D), jnp.bfloat16)
    layout = BlockLayout(L, bs, H, D)
    data = np.stack([_block(i) for i in range(3)])
    assert data.shape == (3, *layout.packed_shape)
    k, v = scatter_blocks(k, v, [2, 5, 7], data, bs)
    got = gather_blocks(k, v, [5, 2, 7], bs)
    np.testing.assert_array_equal(got[0], data[1])
    np.testing.assert_array_equal(got[1], data[0])
    np.testing.assert_array_equal(got[2], data[2])
    # block 0 (garbage) may have been written by padding; blocks 1,3 untouched
    got_zero = gather_blocks(k, v, [1, 3], bs)
    assert not np.any(got_zero.astype(np.float32))


# ---------------------------------------------------------------------------
# Manager: offload pump, staleness, onboarding, demotion cascade
# ---------------------------------------------------------------------------


class FakeDevice:
    """Numpy 'device' cache + allocator hash index."""

    def __init__(self, num_blocks):
        self.blocks = np.zeros((num_blocks, *LAYOUT.packed_shape), LAYOUT.np_dtype)
        self.hash_index: dict[int, int] = {}

    def gather(self, ids):
        return self.blocks[np.asarray(ids)]

    def scatter(self, ids, data):
        self.blocks[np.asarray(ids)] = data

    def resolve(self, h):
        return self.hash_index.get(h)


def _manager(dev, host_blocks=4, disk_blocks=0, tmp=None, batch=16):
    return KvBlockManager(
        KvbmConfig(
            host_num_blocks=host_blocks,
            disk_num_blocks=disk_blocks,
            disk_path=str(tmp / "kv.bin") if tmp else "",
            offload_batch=batch,
        ),
        LAYOUT,
        gather_fn=dev.gather,
        scatter_fn=dev.scatter,
        resolve_fn=dev.resolve,
    )


def test_manager_offload_and_onboard():
    dev = FakeDevice(8)
    m = _manager(dev)
    for i, h in enumerate([11, 12, 13]):
        dev.blocks[i + 1] = _block(h)
        dev.hash_index[h] = i + 1
        m.on_block_committed(h, i + 1)
    assert m.pending_offloads == 3
    assert m.pump() == 3
    assert m.host.num_cached == 3
    # simulate device eviction, then a new request onboards from host
    dev.hash_index.clear()
    dev.blocks[:] = 0
    n = m.onboard([11, 12, 99], [5, 6, 7])
    assert n == 2
    np.testing.assert_array_equal(dev.blocks[5], _block(11))
    np.testing.assert_array_equal(dev.blocks[6], _block(12))
    assert m.stats.offloaded_blocks == 3 and m.stats.onboarded_blocks == 2


def test_manager_stale_pending_dropped():
    dev = FakeDevice(4)
    m = _manager(dev)
    dev.blocks[1] = _block(5)
    dev.hash_index[50] = 1
    m.on_block_committed(50, 1)
    # device block got evicted + reassigned before the pump
    dev.hash_index[50] = 2
    assert m.pump() == 0
    assert m.host.num_cached == 0


def test_manager_demotion_to_disk_and_promote(tmp_path):
    dev = FakeDevice(8)
    m = _manager(dev, host_blocks=2, disk_blocks=4, tmp=tmp_path)
    for i, h in enumerate([21, 22, 23]):  # 3 blocks through a 2-block host tier
        dev.blocks[i + 1] = _block(h)
        dev.hash_index[h] = i + 1
        m.on_block_committed(h, i + 1)
        m.pump()
    assert m.host.num_cached == 2
    assert m.disk is not None and m.disk.num_cached == 1  # 21 demoted
    assert m.match_offloaded([21, 22, 23]) == 3
    n = m.onboard([21], [7])
    assert n == 1
    np.testing.assert_array_equal(dev.blocks[7], _block(21))
    assert m.host.contains(21)  # promoted on access
    m.close()


# ---------------------------------------------------------------------------
# Engine end-to-end with tiers (CPU-JAX)
# ---------------------------------------------------------------------------

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")


async def test_engine_offload_tier_extends_prefix_cache():
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from tests.test_engine import _generate

    # tiny device pool (12 usable blocks) + roomy host tier: after churn
    # evicts the first prompt from HBM, the host tier restores it
    engine = await JaxEngine.launch(
        EngineConfig(
            model_path=MODEL_DIR,
            model_name="tiny",
            random_weights=True,
            num_blocks=13,
            block_size=8,
            max_batch_size=4,
            prefill_chunk_size=32,
            max_model_len=128,
            host_kv_blocks=64,
            kv_offload_batch=8,
        )
    )
    try:
        prompt_a = list(range(1, 41))  # 5 full blocks
        toks_a, _ = await _generate(engine, prompt_a, request_id="a")
        # churn: different prompts large enough to evict A's blocks
        for i, base in enumerate((50, 100, 150)):
            await _generate(
                engine, list(range(base, base + 40)), request_id=f"churn{i}"
            )
        # idle pump runs in the engine loop; give it a beat
        await asyncio.sleep(0.3)
        assert engine.kvbm is not None
        assert engine.kvbm.stats.offloaded_blocks > 0
        before = engine.kvbm.stats.onboarded_blocks
        toks_a2, _ = await _generate(engine, prompt_a, request_id="a2")
        assert toks_a2 == toks_a  # identical greedy continuation
        assert engine.kvbm.stats.onboarded_blocks > before
    finally:
        await engine.shutdown()


# ---------------------------------------------------------------------------
# G4 remote tier
# ---------------------------------------------------------------------------


def _manager_g4(dev, objects, host_blocks=2, disk_blocks=0, tmp=None):
    return KvBlockManager(
        KvbmConfig(
            host_num_blocks=host_blocks,
            disk_num_blocks=disk_blocks,
            disk_path=str(tmp / "kv.bin") if tmp else "",
            offload_batch=16,
            remote_bucket="kvg4",
        ),
        LAYOUT,
        gather_fn=dev.gather,
        scatter_fn=dev.scatter,
        resolve_fn=dev.resolve,
        remote_objects=objects,
    )


def test_g4_demotion_cascade_and_onboard(tmp_path):
    """G2 -> G3 -> G4 demotion cascade; onboarding reads back through
    the tiers (reference: block_manager.rs CacheLevel::G4)."""
    from dynamo_tpu.kvbm.remote import DictObjectStore

    dev = FakeDevice(8)
    objects = DictObjectStore()
    m = _manager_g4(dev, objects, host_blocks=1, disk_blocks=1, tmp=tmp_path)
    for i, h in enumerate([31, 32, 33]):  # 3 blocks through 1+1 tier slots
        dev.blocks[i + 1] = _block(h)
        dev.hash_index[h] = i + 1
        m.on_block_committed(h, i + 1)
        m.pump()
    # 33 in host, 32 in disk, 31 pushed all the way to remote
    assert m.host.contains(33) and m.disk.contains(32)
    assert m.remote is not None and m.remote.contains(31)
    assert m.stats.remote_put_blocks == 1
    assert m.match_offloaded([31, 32, 33]) == 3
    dev.hash_index.clear()
    n = m.onboard([31, 32, 33], [5, 6, 7])
    assert n == 3
    for slot, h in ((5, 31), (6, 32), (7, 33)):
        np.testing.assert_array_equal(dev.blocks[slot], _block(h))
    assert m.stats.remote_got_blocks == 1
    m.close()


def test_g4_without_disk_demotes_host_evictions():
    from dynamo_tpu.kvbm.remote import DictObjectStore

    dev = FakeDevice(8)
    objects = DictObjectStore()
    m = _manager_g4(dev, objects, host_blocks=1)
    for i, h in enumerate([41, 42]):
        dev.blocks[i + 1] = _block(h)
        dev.hash_index[h] = i + 1
        m.on_block_committed(h, i + 1)
        m.pump()
    assert m.remote.contains(41)  # evicted straight to G4 (no G3)
    assert m.match_offloaded([41, 42]) == 2


def test_g4_shared_across_workers():
    """The remote bucket is shared: worker B discovers and onboards
    blocks worker A demoted (the cross-worker win of a remote tier)."""
    from dynamo_tpu.kvbm.remote import DictObjectStore

    objects = DictObjectStore()
    dev_a = FakeDevice(8)
    a = _manager_g4(dev_a, objects, host_blocks=1)
    for i, h in enumerate([51, 52]):
        dev_a.blocks[i + 1] = _block(h)
        dev_a.hash_index[h] = i + 1
        a.on_block_committed(h, i + 1)
        a.pump()
    assert a.remote.contains(51)

    dev_b = FakeDevice(8)
    b = _manager_g4(dev_b, objects, host_blocks=2)
    assert b.match_offloaded([51]) == 0  # not discovered yet
    # the engine's pump runs the periodic index refresh
    b.REMOTE_REFRESH_S = 0.0
    b.pump()
    assert b.match_offloaded([51]) == 1
    assert b.onboard([51], [3]) == 1
    np.testing.assert_array_equal(dev_b.blocks[3], _block(51))
    # promoted into B's host tier on access
    assert b.host.contains(51)


def test_g4_refresh_throttle_is_clock_driven():
    """ISSUE 15 satellite: the G4 refresh throttle reads time through
    the injectable Clock seam (DL009 vocabulary), so a virtual clock
    drives the refresh deterministically — no sleeps, no monkeypatching
    time.monotonic."""
    from dynamo_tpu.kvbm.remote import DictObjectStore

    class TickClock:
        def __init__(self):
            self.now = 100.0

        def monotonic(self):
            return self.now

        def time(self):
            return self.now

        async def sleep(self, seconds):
            self.now += seconds

    objects = DictObjectStore()
    dev_a = FakeDevice(8)
    a = _manager_g4(dev_a, objects, host_blocks=1)
    clock = TickClock()
    dev_b = FakeDevice(8)
    b = KvBlockManager(
        KvbmConfig(host_num_blocks=2, offload_batch=16, remote_bucket="kvg4"),
        LAYOUT,
        gather_fn=dev_b.gather,
        scatter_fn=dev_b.scatter,
        resolve_fn=dev_b.resolve,
        remote_objects=objects,
        clock=clock,
    )
    # the construction-time refresh saw an empty bucket; worker A
    # demotes AFTERWARDS
    for i, h in enumerate([71, 72]):
        dev_a.blocks[i + 1] = _block(h)
        dev_a.hash_index[h] = i + 1
        a.on_block_committed(h, i + 1)
        a.pump()
    assert a.remote.contains(71)
    b._last_remote_refresh = clock.monotonic()
    b.pump()  # inside the throttle window: no refresh
    assert b.match_offloaded([71]) == 0
    clock.now += b.REMOTE_REFRESH_S - 0.001
    b.pump()  # still 1 ms short of the window
    assert b.match_offloaded([71]) == 0
    clock.now += 0.001
    b.pump()  # window elapsed ON THE INJECTED CLOCK: refresh fires
    assert b.match_offloaded([71]) == 1
    # default construction still runs on the system clock
    assert _manager_g4(FakeDevice(4), DictObjectStore()).clock is not None


def test_g4_missing_remote_truncates_onboard():
    """A block that vanished from the remote bucket (GC, eviction) must
    truncate the onboarded prefix, not corrupt it."""
    from dynamo_tpu.kvbm.remote import DictObjectStore

    dev = FakeDevice(8)
    objects = DictObjectStore()
    m = _manager_g4(dev, objects, host_blocks=1)
    for i, h in enumerate([61, 62]):
        dev.blocks[i + 1] = _block(h)
        dev.hash_index[h] = i + 1
        m.on_block_committed(h, i + 1)
        m.pump()
    assert m.remote.contains(61)
    objects.data.clear()  # remote GC'd everything
    dev.hash_index.clear()
    # 61 is G4 (gone), 62 is host: prefix truncates at the missing row
    assert m.onboard([61, 62], [5, 6]) == 0
    assert not m.remote.contains(61)  # negative result un-indexes


async def test_engine_g4_tier_round_trip():
    """Engine-level G4: a tiny host tier cascades into the remote
    object store, and a repeat prompt onboards back through it."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.kvbm.remote import DictObjectStore
    from tests.test_engine import _generate

    objects = DictObjectStore()
    engine = await JaxEngine.launch(
        EngineConfig(
            model_path=MODEL_DIR, model_name="tiny", random_weights=True,
            num_blocks=13, block_size=8, max_batch_size=4,
            prefill_chunk_size=32, max_model_len=128,
            host_kv_blocks=4, kv_offload_batch=8,
            remote_kv_bucket="kvg4",
        ),
        remote_kv_objects=objects,
    )
    try:
        assert engine.kvbm is not None and engine.kvbm.remote is not None
        prompt_a = list(range(1, 41))
        toks_a, _ = await _generate(engine, prompt_a, request_id="a")
        for i, base in enumerate((50, 100, 150)):  # churn both G1 and G2
            await _generate(
                engine, list(range(base, base + 40)), request_id=f"churn{i}"
            )
        await asyncio.sleep(0.3)
        assert engine.kvbm.stats.remote_put_blocks > 0
        assert objects.data  # blocks really landed in the object plane
        toks_a2, _ = await _generate(engine, prompt_a, request_id="a2")
        assert toks_a2 == toks_a
    finally:
        await engine.shutdown()


def test_g4_flaky_remote_reads_as_miss_not_crash():
    """A raising remote store must degrade to a cache miss — one G4
    timeout must not take the host/disk tiers down (engine._safe_onboard
    disables the whole kvbm on exceptions)."""
    from dynamo_tpu.kvbm.remote import DictObjectStore

    class Flaky(DictObjectStore):
        def get_many(self, keys):
            raise TimeoutError("store stall")

    dev = FakeDevice(8)
    objects = Flaky()
    m = _manager_g4(dev, objects, host_blocks=1)
    for i, h in enumerate([71, 72]):
        dev.blocks[i + 1] = _block(h)
        dev.hash_index[h] = i + 1
        m.on_block_committed(h, i + 1)
        m.pump()
    assert m.remote.contains(71)
    dev.hash_index.clear()
    # remote read raises -> treated as missing prefix row, no exception
    assert m.onboard([71, 72], [5, 6]) == 0


async def test_restore_vs_recompute_gate():
    """The G2 tier auto-disables when the probed host<->device copy
    bandwidth cannot beat recompute (kv_recompute_tok_per_s absurdly
    high simulates a slow link), and kv_offload_force keeps it."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine

    base = dict(
        model_path=MODEL_DIR, model_name="tiny", random_weights=True,
        num_blocks=13, block_size=8, max_batch_size=4,
        prefill_chunk_size=32, max_model_len=128, host_kv_blocks=64,
    )
    # threshold no real link can meet -> tier dropped at startup
    engine = await JaxEngine.launch(
        EngineConfig(**base, kv_recompute_tok_per_s=1e15)
    )
    try:
        assert engine.kvbm is None
    finally:
        await engine.shutdown()
    # force overrides the gate
    engine = await JaxEngine.launch(
        EngineConfig(
            **base, kv_recompute_tok_per_s=1e15, kv_offload_force=True
        )
    )
    try:
        assert engine.kvbm is not None
    finally:
        await engine.shutdown()
