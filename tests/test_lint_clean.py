"""Self-clean gate: `dynamo-tpu lint` over dynamo_tpu/ must report zero
unsuppressed findings — per-file rules AND the whole-program DL1xx pass
(call graph + taints). This test IS the CI wiring — it runs inside the
tier-1 pytest command on every change, so a new blocking call, hidden
transitive device sync, or undeclared cross-thread write fails the
merge without any extra CI configuration. It also measures the warm
path: a second run through the on-disk result cache must finish in
under 5s, which is what keeps whole-repo lint viable inside tier-1."""

import time
from pathlib import Path

import pytest

from dynamo_tpu.analysis import (
    format_text,
    lint_paths,
    load_config,
    unsuppressed,
)
from dynamo_tpu.analysis.cache import LintCache

REPO = Path(__file__).resolve().parents[1]

# the self-clean contract extends beyond the package: the benchmark
# driver and the test-infrastructure helpers run the same async/engine
# machinery, so a blocking call or hidden sync there skews the numbers
# the package's own rules protect (fixture data under tests/data stays
# out — violating fixtures exist to violate)
EXTRA_CLEAN_PATHS = [
    str(REPO / "bench.py"),
    str(REPO / "tests" / "cli_harness.py"),
    str(REPO / "tests" / "prom_parser.py"),
    str(REPO / "tests" / "sdk_graph.py"),
]


@pytest.mark.pre_merge
def test_repo_is_lint_clean():
    cfg = load_config(start=str(REPO))
    cache = LintCache(REPO / ".dynalint_cache")
    findings = lint_paths(cfg["include"], config=cfg, cache=cache)
    live = unsuppressed(findings)
    assert live == [], (
        "unsuppressed dynalint findings (fix them, or waive a deliberate "
        "pattern in place with `# dynalint: disable=<rule> — why`; declare "
        "a deliberate cross-thread write with `# dynalint: handoff=<why>`"
        "):\n" + format_text(findings)
    )


@pytest.mark.pre_merge
def test_bench_and_test_helpers_are_lint_clean():
    # a separate lint_paths call (not config `include`): these files
    # live outside the package root, and folding them into the main
    # walk would change the whole-program pass's module universe (and
    # its cache key) for every other consumer
    for p in EXTRA_CLEAN_PATHS:
        assert Path(p).exists(), f"extra clean path vanished: {p}"
    cfg = load_config(start=str(REPO))
    cache = LintCache(REPO / ".dynalint_cache")
    findings = lint_paths(EXTRA_CLEAN_PATHS, config=cfg, cache=cache)
    live = unsuppressed(findings)
    assert live == [], (
        "unsuppressed dynalint findings in bench.py / tests helpers:\n"
        + format_text(findings)
    )


@pytest.mark.pre_merge
def test_warm_whole_repo_lint_under_5s():
    # the acceptance bound for keeping lint inside tier-1: with the
    # cache primed, a full-repo lint hits the per-file AND program
    # entries and never parses a file. Prime explicitly so the test
    # holds standalone, then measure a fresh cache instance (true
    # cold-process warm path: read cache.json, hash files, look up).
    cfg = load_config(start=str(REPO))
    lint_paths(cfg["include"], config=cfg,
               cache=LintCache(REPO / ".dynalint_cache"))
    cache = LintCache(REPO / ".dynalint_cache")
    t0 = time.monotonic()
    findings = lint_paths(cfg["include"], config=cfg, cache=cache)
    dt = time.monotonic() - t0
    assert unsuppressed(findings) == []
    assert cache.misses == 0, (
        f"warm run missed the cache {cache.misses} time(s) — key drift?"
    )
    assert dt < 5.0, f"warm whole-repo lint took {dt:.1f}s (budget 5s)"


@pytest.mark.pre_merge
def test_lint_actually_scanned_the_package():
    # guard against a silently-empty walk (bad include/exclude config)
    from dynamo_tpu.analysis import iter_files

    cfg = load_config(start=str(REPO))
    files = iter_files(cfg["include"], exclude=cfg["exclude"])
    assert len(files) > 50, "walk found suspiciously few files"
    names = {f.name for f in files}
    assert "engine.py" in names and "service.py" in names
    assert not any("native" in str(f) for f in files), "exclude broken"


def test_suppressions_carry_justifications():
    # every in-tree waiver must say why: a bare disable comment rots
    import re

    cfg = load_config(start=str(REPO))
    pat = re.compile(r"#\s*dynalint:\s*disable=[\w\-, ]+")
    from dynamo_tpu.analysis import iter_files

    scope = iter_files(cfg["include"], exclude=cfg["exclude"])
    scope += [Path(p) for p in EXTRA_CLEAN_PATHS]
    for f in scope:
        for i, line in enumerate(f.read_text().splitlines(), start=1):
            m = pat.search(line)
            if m is None:
                continue
            comment_and_code = line[m.end():].strip(" -—:")
            before = line[: m.start()].strip()
            assert comment_and_code or _nearby_comment(f, i), (
                f"{f}:{i}: suppression without justification "
                f"(add `— why` after the disable, or a comment above)"
            )
            assert before, (
                f"{f}:{i}: suppression on a comment-only line does "
                "nothing (it must share the violating line)"
            )


def _nearby_comment(path: Path, line: int, window: int = 3) -> bool:
    lines = path.read_text().splitlines()
    lo = max(0, line - 1 - window)
    return any(ln.strip().startswith("#") for ln in lines[lo:line - 1])
