"""Whole-program dynalint: call-graph construction, taint propagation,
the DL101/DL102/DL103 fixture pairs, the on-disk result cache, and the
new CLI surfaces (--changed / --format github / --baseline)."""

import ast
import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from dynamo_tpu.analysis import load_config
from dynamo_tpu.analysis.callgraph import build_callgraph
from dynamo_tpu.analysis.cache import LintCache, rule_signature
from dynamo_tpu.analysis.findings import format_text, unsuppressed
from dynamo_tpu.analysis.program import all_program_rules, get_program_rule
from dynamo_tpu.analysis.taint import compute_taints
from dynamo_tpu.analysis.walker import (
    lint_paths,
    lint_sources_program,
)

DATA = Path(__file__).parent / "data" / "lint"
REPO = Path(__file__).resolve().parents[1]

# (program rule name, fixture stem, expected minimum findings)
PROGRAM_CASES = [
    ("transitive-blocking-call-in-async", "transitive_blocking", 3),
    ("transitive-host-sync-in-step-loop", "transitive_sync", 3),
    ("cross-thread-mutation", "cross_thread", 3),
    ("use-after-donate", "use_after_donate", 4),
    ("dynamic-static-arg", "dynamic_static_arg", 5),
    ("prewarm-coverage", "prewarm_coverage", 3),
    ("host-sync-in-shard-body", "shard_sync", 3),
    ("collective-axis-mismatch", "collective_axis", 3),
    ("donation-across-mesh", "donation_mesh", 3),
    ("spec-arity-drift", "spec_arity", 3),
]


def _graph_of(source: str, path: str = "mod.py"):
    return build_callgraph([(path, ast.parse(textwrap.dedent(source)))])


# ---------------------------------------------------------------------------
# call graph
# ---------------------------------------------------------------------------


def test_callgraph_direct_and_method_calls():
    g = _graph_of(
        """
        class Sched:
            def plan(self):
                return self.pick()
            def pick(self):
                return 1

        class Engine:
            def __init__(self):
                self.sched = Sched()
            def step(self):
                return self.sched.plan()

        def run(e):
            return e.step()
        """
    )
    fns = g.functions
    assert "mod:Sched.plan" in fns and "mod:Engine.step" in fns
    # self.method()
    assert any(
        e.callee == "mod:Sched.pick"
        for e in g.out_edges("mod:Sched.plan")
    )
    # one-level attribute-type inference: self.sched.plan()
    assert any(
        e.callee == "mod:Sched.plan"
        for e in g.out_edges("mod:Engine.step")
    )
    # e.step() is dynamic (untyped parameter): counted, not resolved
    assert "e.step" in g.unresolved.get("mod:run", [])


def test_callgraph_decorated_functions_keep_identity():
    g = _graph_of(
        """
        import functools

        def deco(fn):
            return fn

        @deco
        @functools.lru_cache
        def helper():
            return 1

        def caller():
            return helper()
        """
    )
    assert any(
        e.callee == "mod:helper" for e in g.out_edges("mod:caller")
    )
    assert g.functions["mod:helper"].decorators == [
        "deco", "functools.lru_cache"
    ]


def test_callgraph_partial_and_callback_refs():
    g = _graph_of(
        """
        import functools

        def work(x):
            return x

        def sink(cb):
            cb()

        def a():
            sink(functools.partial(work, 1))

        def b():
            sink(work)
        """
    )
    for caller in ("mod:a", "mod:b"):
        kinds = {
            (e.callee, e.kind) for e in g.out_edges(caller)
        }
        assert ("mod:work", "ref") in kinds, (caller, kinds)


def test_callgraph_spawn_edges_are_not_same_context():
    g = _graph_of(
        """
        import asyncio
        import threading

        def blocking():
            pass

        async def main():
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, blocking)
            threading.Thread(target=blocking).start()
        """
    )
    kinds = {
        (e.callee, e.kind) for e in g.out_edges("mod:main")
    }
    assert ("mod:blocking", "spawn-other") in kinds
    assert ("mod:blocking", "call") not in kinds
    assert ("mod:blocking", "ref") not in kinds


def test_callgraph_nested_functions_and_bound_methods():
    g = _graph_of(
        """
        class C:
            def outer(self):
                def inner():
                    return self.helper()
                return inner()
            def helper(self):
                return 2
        """
    )
    inner = "mod:C.outer.<locals>.inner"
    assert inner in g.functions
    # outer -> inner (definition ref + the call)
    assert any(e.callee == inner for e in g.out_edges("mod:C.outer"))
    # the closure's self.helper() resolves through the enclosing class
    assert any(
        e.callee == "mod:C.helper" for e in g.out_edges(inner)
    )


def test_callgraph_unresolved_dynamic_calls_counted():
    g = _graph_of(
        """
        def dispatch(handlers, name):
            handlers[name]()
            getattr(handlers, name)()
            fn = handlers.get(name)
            fn()
        """
    )
    unres = g.unresolved.get("mod:dispatch", [])
    assert len(unres) >= 3
    stats = g.stats()
    assert stats["unresolved_calls"] >= 3
    assert stats["functions"] == 1


def test_callgraph_imports_resolve_across_modules():
    mods = [
        ("pkg/__init__.py", ast.parse("")),
        ("pkg/a.py", ast.parse(
            "def util():\n    return 1\n"
        )),
        ("pkg/b.py", ast.parse(
            "from pkg.a import util\n"
            "import pkg.a\n"
            "def one():\n    return util()\n"
            "def two():\n    return pkg.a.util()\n"
        )),
    ]
    # ensure module naming works without real __init__ files on disk:
    # build from a temp dir instead
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        root = Path(td)
        (root / "pkg").mkdir()
        (root / "pkg" / "__init__.py").write_text("")
        (root / "pkg" / "a.py").write_text("def util():\n    return 1\n")
        (root / "pkg" / "b.py").write_text(
            "from pkg.a import util\n"
            "import pkg.a\n"
            "def one():\n    return util()\n"
            "def two():\n    return pkg.a.util()\n"
        )
        mods = [
            (str(p), ast.parse(p.read_text()))
            for p in sorted((root / "pkg").rglob("*.py"))
        ]
        g = build_callgraph(mods)
    for caller in ("pkg.b:one", "pkg.b:two"):
        assert any(
            e.callee == "pkg.a:util" for e in g.out_edges(caller)
        ), (caller, g.out_edges(caller))


# ---------------------------------------------------------------------------
# taints
# ---------------------------------------------------------------------------


def test_async_taint_crosses_calls_but_not_handoffs():
    g = _graph_of(
        """
        import asyncio

        async def serve():
            helper()
            await asyncio.to_thread(offloaded)

        def helper():
            deeper()

        def deeper():
            pass

        def offloaded():
            pass
        """
    )
    taints = compute_taints(g, {})
    assert "mod:helper" in taints.async_ctx
    assert taints.async_ctx["mod:deeper"] == [
        "mod:serve", "mod:helper", "mod:deeper"
    ]
    assert "mod:offloaded" not in taints.async_ctx


def test_step_loop_taint_stops_at_harvest():
    g = _graph_of(
        """
        def run_step_loop(s):
            plan(s)
            harvest_out(s)

        def plan(s):
            deep(s)

        def deep(s):
            pass

        def harvest_out(s):
            below_harvest(s)

        def below_harvest(s):
            pass
        """
    )
    taints = compute_taints(g, {})
    assert "mod:deep" in taints.step_loop
    assert "mod:harvest_out" not in taints.step_loop
    assert "mod:below_harvest" not in taints.step_loop


def test_affinity_taint_declarations_and_retarget():
    g = _graph_of(
        """
        from dynamo_tpu.utils.affinity import thread_affinity

        @thread_affinity("engine")
        def step():
            helper()

        def helper():
            pass

        async def watcher(loop):
            helper()
            loop.call_soon_threadsafe(on_loop)

        def on_loop():
            pass
        """
    )
    taints = compute_taints(g, {})
    assert taints.domains("mod:step") == {"engine"}
    # helper is reached from both domains
    assert taints.domains("mod:helper") == {"engine", "loop"}
    # call_soon_threadsafe retargets to the loop, whoever calls it
    assert taints.domains("mod:on_loop") == {"loop"}


def test_affinity_entry_point_config_seeds():
    g = _graph_of(
        """
        def control_loop():
            tick()

        def tick():
            pass
        """
    )
    taints = compute_taints(
        g, {"affinity-entry-points": ["control_loop=planner"]}
    )
    assert taints.domains("mod:control_loop") == {"planner"}
    assert taints.domains("mod:tick") == {"planner"}


# ---------------------------------------------------------------------------
# DL101/DL102/DL103 fixture pairs
# ---------------------------------------------------------------------------


def test_program_case_table_covers_every_program_rule():
    assert {n for n, _, _ in PROGRAM_CASES} == {
        r.name for r in all_program_rules()
    }


@pytest.mark.pre_merge
@pytest.mark.parametrize("rule_name,stem,min_hits", PROGRAM_CASES)
def test_program_rule_fires_on_violating_fixture(rule_name, stem, min_hits):
    path = DATA / f"{stem}_bad.py"
    src = path.read_text()
    findings = lint_sources_program(
        {str(path): src}, rules=[get_program_rule(rule_name)]
    )
    assert len(findings) >= min_hits, format_text(findings)
    assert all(f.rule == rule_name for f in findings)
    assert all(not f.suppressed for f in findings)
    lines = src.splitlines()
    for f in findings:
        assert "VIOLATION" in lines[f.line - 1], (
            f"finding at unmarked line {f.line}: {lines[f.line - 1]!r}"
        )
    # the acceptance bar: at least one finding routed >= 2 call levels
    assert any("2 call level" in f.message or "3 call level" in f.message
               or "->" in f.message for f in findings)


@pytest.mark.pre_merge
@pytest.mark.parametrize("rule_name,stem,min_hits", PROGRAM_CASES)
def test_program_rules_quiet_on_clean_fixture(rule_name, stem, min_hits):
    path = DATA / f"{stem}_ok.py"
    findings = lint_sources_program({str(path): path.read_text()})
    assert findings == [], format_text(findings)


@pytest.mark.parametrize("stem", [s for _, s, _ in PROGRAM_CASES])
def test_clean_fixtures_pass_per_file_rules_too(stem):
    # the ok fixtures document the idiomatic remediation; the idiom must
    # itself be clean under the whole rule set, both passes
    from dynamo_tpu.analysis import lint_source

    path = DATA / f"{stem}_ok.py"
    findings = lint_source(path.read_text(), path=str(path))
    assert findings == [], format_text(findings)


def test_program_finding_chain_names_at_least_two_levels():
    path = DATA / "transitive_blocking_bad.py"
    findings = lint_sources_program(
        {str(path): path.read_text()},
        rules=[get_program_rule("transitive-blocking-call-in-async")],
    )
    deep = [f for f in findings if "2 call level" in f.message]
    assert deep, format_text(findings)
    assert all(" -> " in f.message for f in deep)


def test_program_findings_suppressable_in_place():
    src = (
        "import time\n"
        "async def serve():\n"
        "    helper()\n"
        "def helper():\n"
        "    time.sleep(1)  # dynalint: disable=transitive-blocking-call-in-async — test waiver\n"
    )
    findings = lint_sources_program({"mod.py": src})
    assert len(findings) == 1 and findings[0].suppressed


def test_multi_file_transitive_finding():
    # the finding lands in the file that CONTAINS the sync, with the
    # chain crossing the module boundary
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        root = Path(td)
        (root / "app").mkdir()
        (root / "app" / "__init__.py").write_text("")
        (root / "app" / "front.py").write_text(
            "from app.util import helper\n"
            "async def serve():\n"
            "    helper()\n"
        )
        (root / "app" / "util.py").write_text(
            "import time\n"
            "def helper():\n"
            "    deeper()\n"
            "def deeper():\n"
            "    time.sleep(1)\n"
        )
        sources = {
            str(p): p.read_text()
            for p in sorted((root / "app").rglob("*.py"))
        }
        findings = lint_sources_program(
            sources,
            rules=[get_program_rule("transitive-blocking-call-in-async")],
        )
    assert len(findings) == 1
    assert findings[0].path.endswith("util.py")
    assert "serve" in findings[0].message


# ---------------------------------------------------------------------------
# cache
# ---------------------------------------------------------------------------


def test_cache_roundtrip_and_invalidation(tmp_path):
    proj = tmp_path / "proj"
    (proj / "pkg").mkdir(parents=True)
    (proj / "pyproject.toml").write_text("[tool.dynalint]\n")
    mod = proj / "pkg" / "m.py"
    mod.write_text(
        "import time\nasync def f():\n    helper()\n"
        "def helper():\n    time.sleep(1)\n"
    )
    cfg = load_config(start=str(proj))

    cache = LintCache(proj / ".dynalint_cache")
    first = lint_paths([str(proj / "pkg")], config=cfg, cache=cache)
    assert cache.misses > 0 and cache.hits == 0
    assert {f.code for f in first} == {"DL101"}

    warm = LintCache(proj / ".dynalint_cache")
    second = lint_paths([str(proj / "pkg")], config=cfg, cache=warm)
    assert warm.misses == 0 and warm.hits > 0
    assert [
        (f.rule, f.path, f.line) for f in second
    ] == [(f.rule, f.path, f.line) for f in first]

    # edit the file: both the per-file and the program entry must miss
    mod.write_text(mod.read_text() + "\n# touched\n")
    cold = LintCache(proj / ".dynalint_cache")
    third = lint_paths([str(proj / "pkg")], config=cfg, cache=cold)
    assert cold.misses > 0
    assert {f.code for f in third} == {"DL101"}


def test_cache_key_binds_rule_set_and_config():
    sig_a = rule_signature(["a", "b"], {"disable": []})
    assert sig_a == rule_signature(["b", "a"], {"disable": []})
    assert sig_a != rule_signature(["a"], {"disable": []})
    assert sig_a != rule_signature(["a", "b"], {"disable": ["a"]})


def test_cache_survives_corruption(tmp_path):
    d = tmp_path / "c"
    d.mkdir()
    (d / "cache.json").write_text("{not json")
    cache = LintCache(d)
    assert cache.get("f:zzz:sig") is None
    from dynamo_tpu.analysis.findings import Finding

    cache.put("k", [Finding("r", "DL999", "p", 1, 0, "m")])
    cache.save()
    again = LintCache(d)
    got = again.get("k")
    assert got and got[0].code == "DL999"


# ---------------------------------------------------------------------------
# CLI surfaces
# ---------------------------------------------------------------------------


def _run_cli(*argv, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.cli.main", "lint", *argv],
        cwd=cwd, capture_output=True, text=True, timeout=180,
    )


@pytest.mark.pre_merge
def test_cli_list_rules_includes_program_rules():
    out = _run_cli("--list-rules")
    assert out.returncode == 0
    for code in ("DL101", "DL102", "DL103"):
        assert code in out.stdout


def test_cli_github_format_and_exit_code():
    bad = _run_cli(str(DATA / "transitive_blocking_bad.py"),
                   "--format", "github", "--no-cache")
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "::error file=" in bad.stdout
    assert ",line=" in bad.stdout and ",col=" in bad.stdout
    ok = _run_cli(str(DATA / "transitive_blocking_ok.py"),
                  "--format", "github", "--no-cache")
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert "::error" not in ok.stdout


def test_cli_baseline_demotes_then_new_findings_fail(tmp_path):
    base = tmp_path / "baseline.json"
    target = str(DATA / "transitive_blocking_bad.py")
    wrote = _run_cli(target, "--no-cache", "--baseline", str(base),
                     "--update-baseline")
    assert wrote.returncode == 0, wrote.stdout + wrote.stderr
    entries = json.loads(base.read_text())["findings"]
    assert len(entries) >= 3

    # everything grandfathered: warns, exits 0
    demoted = _run_cli(target, "--no-cache", "--baseline", str(base),
                       "--format", "github")
    assert demoted.returncode == 0, demoted.stdout + demoted.stderr
    assert "::warning" in demoted.stdout and "::error" not in demoted.stdout

    # a baseline that misses one finding: that one still gates
    partial = {"version": 1, "findings": entries[:-1]}
    base.write_text(json.dumps(partial))
    gated = _run_cli(target, "--no-cache", "--baseline", str(base))
    assert gated.returncode == 1, gated.stdout + gated.stderr
    assert "(baseline)" in gated.stdout


def test_cli_baseline_warns_on_stale_entries_and_update_prunes(tmp_path):
    """ISSUE 13 satellite: a baseline fingerprint matching no current
    finding is a fixed violation whose grandfather entry lingers — it
    must warn on every run, and --update-baseline must prune it, so
    the backlog list shrinks monotonically."""
    base = tmp_path / "baseline.json"
    target = str(DATA / "transitive_blocking_bad.py")
    wrote = _run_cli(target, "--no-cache", "--baseline", str(base),
                     "--update-baseline")
    assert wrote.returncode == 0, wrote.stdout + wrote.stderr
    payload = json.loads(base.read_text())
    n_live = len(payload["findings"])
    # graft a stale entry: a finding that no longer exists
    payload["findings"].append({
        "rule": "transitive-blocking-call-in-async",
        "path": "pkg/deleted_module.py",
        "message": "long since fixed",
    })
    base.write_text(json.dumps(payload))

    run = _run_cli(target, "--no-cache", "--baseline", str(base))
    assert run.returncode == 0, run.stdout + run.stderr
    assert "stale baseline entry" in run.stderr
    assert "deleted_module.py" in run.stderr
    assert "prune with --update-baseline" in run.stderr

    pruned = _run_cli(target, "--no-cache", "--baseline", str(base),
                      "--update-baseline")
    assert pruned.returncode == 0, pruned.stdout + pruned.stderr
    assert "pruned 1 stale" in pruned.stderr
    after = json.loads(base.read_text())["findings"]
    assert len(after) == n_live
    assert not any(e["path"] == "pkg/deleted_module.py" for e in after)

    # pruned baseline: no stale warning, grandfathering still works
    clean = _run_cli(target, "--no-cache", "--baseline", str(base))
    assert clean.returncode == 0
    assert "stale baseline entry" not in clean.stderr


def test_stale_baseline_entries_api(tmp_path):
    from dynamo_tpu.analysis import Finding, stale_baseline_entries

    base = tmp_path / "b.json"
    base.write_text(json.dumps({"version": 1, "findings": [
        {"rule": "r", "path": "a.py", "message": "live"},
        {"rule": "r", "path": "b.py", "message": "stale"},
    ]}))
    live = [Finding(rule="r", code="DL000", path="a.py", line=1, col=0,
                    message="live")]
    assert stale_baseline_entries(live, base) == [("r", "b.py", "stale")]
    # suppressed findings don't keep an entry alive
    waived = [dataclasses_replace_suppressed(live[0])]
    assert len(stale_baseline_entries(waived, base)) == 2
    # unreadable baseline: no stale reports (degrade like apply_baseline)
    base.write_text("{broken")
    assert stale_baseline_entries(live, base) == []


def dataclasses_replace_suppressed(f):
    import dataclasses

    return dataclasses.replace(f, suppressed=True)


def test_cli_changed_scopes_report(tmp_path):
    proj = tmp_path / "proj"
    (proj / "pkg").mkdir(parents=True)
    (proj / "pyproject.toml").write_text(
        "[tool.dynalint]\ninclude = [\"pkg\"]\n"
    )
    clean = "def ok():\n    return 1\n"
    dirty = (
        "import time\nasync def f():\n    helper()\n"
        "def helper():\n    time.sleep(1)\n"
    )
    (proj / "pkg" / "committed.py").write_text(dirty)
    (proj / "pkg" / "fresh.py").write_text(clean)
    subprocess.run(["git", "init", "-q"], cwd=proj, check=True,
                   timeout=30)
    subprocess.run(["git", "add", "-A"], cwd=proj, check=True, timeout=30)
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "-qm", "seed"],
        cwd=proj, check=True, timeout=30,
    )
    # an untracked NEW dirty file is in scope; the committed dirty file
    # is not (unchanged vs HEAD)
    (proj / "pkg" / "new_dirty.py").write_text(dirty)
    # cwd stays at REPO (the package import root); --changed anchors
    # its git queries at the linted tree's pyproject, not the cwd
    out = _run_cli(str(proj / "pkg"), "--changed", "--no-cache",
                   "--format", "json")
    assert out.returncode == 1, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    paths = {f["path"] for f in payload["findings"]}
    assert paths and all("new_dirty.py" in p for p in paths), paths

    # with no edits at all, --changed reports nothing and exits 0
    (proj / "pkg" / "new_dirty.py").unlink()
    out = _run_cli(str(proj / "pkg"), "--changed", "--no-cache")
    assert out.returncode == 0, out.stdout + out.stderr


def test_sarif_emitter_schema_shape():
    """ISSUE 16 satellite: the SARIF document must carry the 2.1.0
    schema shape GitHub code scanning validates — versioned envelope,
    driver rule catalog with consistent ruleIndex back-references, and
    physical locations with 1-based line/column under SRCROOT."""
    from dynamo_tpu.analysis import format_sarif

    path = DATA / "transitive_blocking_bad.py"
    findings = lint_sources_program({str(path): path.read_text()})
    doc = json.loads(format_sarif(findings))
    assert doc["$schema"].endswith("sarif-2.1.0.json")
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "dynalint"
    assert "static_analysis.md" in driver["informationUri"]
    # every registered rule (per-file AND program) has a descriptor
    names = {r["name"] for r in driver["rules"]}
    assert {pr.name for pr in all_program_rules()} <= names
    assert run["originalUriBaseIds"]["SRCROOT"]["uri"].startswith("file:")
    assert run["results"], "expected findings from the bad fixture"
    for res in run["results"]:
        assert res["ruleId"] == driver["rules"][res["ruleIndex"]]["id"]
        assert res["level"] in ("error", "warning")
        assert res["message"]["text"]
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uriBaseId"] == "SRCROOT"
        assert "\\" not in loc["artifactLocation"]["uri"]
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["startColumn"] >= 1


def test_sarif_suppressed_findings_stay_visible():
    src = (
        "import time\n"
        "async def serve():\n"
        "    helper()\n"
        "def helper():\n"
        "    time.sleep(1)  # dynalint: disable=transitive-blocking-call-in-async — test waiver\n"
    )
    from dynamo_tpu.analysis import format_sarif

    findings = lint_sources_program({"mod.py": src})
    assert len(findings) == 1 and findings[0].suppressed
    doc = json.loads(format_sarif(findings))
    res = doc["runs"][0]["results"][0]
    assert res["suppressions"] == [
        {"kind": "inSource", "status": "accepted"}
    ]


def test_cli_sarif_format():
    bad = _run_cli(str(DATA / "transitive_blocking_bad.py"),
                   "--format", "sarif", "--no-cache")
    assert bad.returncode == 1, bad.stdout + bad.stderr
    doc = json.loads(bad.stdout)
    assert doc["version"] == "2.1.0"
    assert doc["runs"][0]["results"]


# ---------------------------------------------------------------------------
# catalog metadata
# ---------------------------------------------------------------------------


def test_program_rule_catalog_metadata():
    rules = all_program_rules()
    assert len(rules) == 10
    codes = [r.code for r in rules]
    assert codes == [
        "DL101", "DL102", "DL103", "DL201", "DL202", "DL203",
        "DL301", "DL302", "DL303", "DL304",
    ]
    assert all(r.name == r.name.lower() and " " not in r.name
               for r in rules)


def test_self_clean_gate_sees_program_rules():
    # the gate runs lint_paths with default rule selection: DL1xx must
    # be in that set or the whole tentpole silently stops gating
    cfg = load_config(start=str(REPO))
    cfg = dict(cfg)
    findings = lint_paths(
        [str(REPO / "tests" / "data" / "lint" / "transitive_sync_bad.py")],
        config={**cfg, "include": []},
    )
    assert any(f.code == "DL102" for f in unsuppressed(findings))