"""Per-rule dynalint tests: every rule fires on its violating fixture
and stays quiet on the clean one; suppression comments, config, and the
CLI exit-code contract are covered here too."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from dynamo_tpu.analysis import (
    all_rules,
    format_json,
    format_text,
    get_rule,
    lint_source,
    unsuppressed,
)

DATA = Path(__file__).parent / "data" / "lint"
REPO = Path(__file__).resolve().parents[1]

# (rule name, fixture stem, expected minimum findings in the bad fixture)
CASES = [
    ("blocking-call-in-async", "blocking_call_in_async", 2),
    ("dropped-task-handle", "dropped_task_handle", 1),
    ("swallowed-cancellation", "swallowed_cancellation", 2),
    ("host-sync-in-jit-path", "host_sync_in_jit_path", 3),
    ("await-while-locked", "await_while_locked", 2),
    ("bare-except", "bare_except", 1),
    ("unbounded-telemetry-buffer", "unbounded_telemetry_buffer", 3),
    ("unbounded-retry-loop", "unbounded_retry_loop", 2),
    ("wall-clock-in-control-loop", "wall_clock_in_control_loop", 6),
    ("hidden-host-sync-in-step-loop", "hidden_host_sync", 6),
    ("unclosed-span", "unclosed_span", 5),
    ("blocking-work-in-chunk-path", "blocking_chunk_path", 7),
]


def test_case_table_covers_every_rule():
    assert {name for name, _, _ in CASES} == {r.name for r in all_rules()}


@pytest.mark.pre_merge
@pytest.mark.parametrize("rule_name,stem,min_hits", CASES)
def test_rule_fires_on_violating_fixture(rule_name, stem, min_hits):
    src = (DATA / f"{stem}_bad.py").read_text()
    findings = lint_source(src, path=f"{stem}_bad.py",
                           rules=[get_rule(rule_name)])
    assert len(findings) >= min_hits, format_text(findings)
    assert all(f.rule == rule_name for f in findings)
    assert all(not f.suppressed for f in findings)
    # every violation is marked in the fixture for human readers
    lines = src.splitlines()
    for f in findings:
        assert "VIOLATION" in lines[f.line - 1], (
            f"finding at unmarked line {f.line}: {lines[f.line - 1]!r}"
        )


@pytest.mark.pre_merge
@pytest.mark.parametrize("rule_name,stem,min_hits", CASES)
def test_all_rules_quiet_on_clean_fixture(rule_name, stem, min_hits):
    # clean fixtures must pass EVERY rule, not just their own: each one
    # shows the idiomatic replacement pattern, which must itself be clean
    src = (DATA / f"{stem}_ok.py").read_text()
    findings = lint_source(src, path=f"{stem}_ok.py")
    assert findings == [], format_text(findings)


@pytest.mark.pre_merge
def test_suppression_comment_waives_finding():
    src = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # dynalint: disable=blocking-call-in-async\n"
    )
    findings = lint_source(src)
    assert len(findings) == 1 and findings[0].suppressed
    assert unsuppressed(findings) == []


def test_suppression_requires_matching_rule_name():
    src = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # dynalint: disable=bare-except\n"
    )
    assert len(unsuppressed(lint_source(src))) == 1


def test_disable_all_waives_everything_on_the_line():
    src = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # dynalint: disable=all\n"
    )
    assert unsuppressed(lint_source(src)) == []


def test_disable_file_waives_whole_file():
    src = (
        "# dynalint: disable-file=bare-except\n"
        "def f():\n"
        "    try:\n"
        "        return 1\n"
        "    except:\n"
        "        return 0\n"
        "def g():\n"
        "    try:\n"
        "        return 1\n"
        "    except:\n"
        "        return 0\n"
    )
    findings = lint_source(src)
    assert len(findings) == 2 and all(f.suppressed for f in findings)


def test_suppression_with_ascii_hyphen_justification():
    # `disable=<rule> - why` (plain hyphen, not em-dash) must not fold
    # the justification into the rule-name list
    src = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # dynalint: disable=blocking-call-in-async - CLI\n"
    )
    assert unsuppressed(lint_source(src)) == []


def test_unknown_rule_in_suppression_is_reported():
    # a typo'd rule name waives nothing; that must be loud, not silent
    src = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # dynalint: disable=blocking-call-in-asink\n"
    )
    findings = unsuppressed(lint_source(src))
    assert {f.rule for f in findings} == {
        "bad-suppression", "blocking-call-in-async",
    }


def test_nested_locks_yield_one_finding_per_await():
    src = (
        "import threading\n"
        "async def f(s):\n"
        "    with threading.Lock():\n"
        "        with threading.Lock():\n"
        "            await s.flush()\n"
    )
    findings = lint_source(src, rules=[get_rule("await-while-locked")])
    assert len(findings) == 1


def test_cli_missing_path_exits_2():
    out = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.cli.main", "lint",
         "no/such/dir"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 2, out.stdout + out.stderr
    assert "no such path" in out.stderr


def test_suppression_inside_string_literal_is_inert():
    # docs/prose quoting the directive must not waive anything
    src = (
        'DOC = """example: # dynalint: disable-file=bare-except"""\n'
        "import time\n"
        "async def f():\n"
        '    s = "# dynalint: disable=blocking-call-in-async"\n'
        "    time.sleep(1)\n"
        "    try:\n"
        "        return s\n"
        "    except:\n"
        "        pass\n"
    )
    live = unsuppressed(lint_source(src))
    assert {f.rule for f in live} == {"blocking-call-in-async", "bare-except"}


def test_taskgroup_create_task_not_flagged():
    src = (
        "import asyncio\n"
        "async def f():\n"
        "    async with asyncio.TaskGroup() as tg:\n"
        "        tg.create_task(asyncio.sleep(0))\n"
        "    loop = asyncio.get_running_loop()\n"
        "    loop.create_task(asyncio.sleep(0))\n"
    )
    findings = lint_source(src, rules=[get_rule("dropped-task-handle")])
    # the TaskGroup spawn is structured concurrency (group keeps the
    # reference); the bare loop.create_task is still a dropped handle
    assert len(findings) == 1 and findings[0].line == 6


def test_block_names_are_not_locks():
    src = (
        "async def alloc(self):\n"
        "    with self.free_blocks:\n"
        "        await self.notify()\n"
        "    with self.write_lock:\n"
        "        await self.notify()\n"
    )
    findings = lint_source(src, rules=[get_rule("await-while-locked")])
    assert len(findings) == 1 and findings[0].line == 5


def test_config_disable_honored_by_api_entry_point():
    # `disable` must bind lint_source/lint_paths (the pytest gate), not
    # just the CLI, or the two gates disagree
    src = "def f():\n    try:\n        return 1\n    except:\n        pass\n"
    assert len(lint_source(src)) == 1
    assert lint_source(src, config={"disable": ["bare-except"]}) == []


def test_unqualified_create_task_import_flagged():
    src = (
        "from asyncio import create_task\n"
        "async def f():\n"
        "    create_task(f())\n"
    )
    findings = lint_source(src, rules=[get_rule("dropped-task-handle")])
    assert len(findings) == 1 and findings[0].line == 3


def test_config_anchored_at_lint_path_and_stderr_diagnostics(tmp_path):
    # config comes from the linted tree (not the cwd), unknown config
    # keys warn on stderr, and usage errors never pollute stdout
    proj = tmp_path / "proj"
    (proj / "pkg").mkdir(parents=True)
    (proj / "pyproject.toml").write_text(
        "[tool.dynalint]\ndisable = [\"bare-except\"]\nbogus_key = 1\n"
    )
    (proj / "pkg" / "mod.py").write_text(
        "def f():\n    try:\n        return 1\n    except:\n        pass\n"
    )

    def run(*argv):
        return subprocess.run(
            [sys.executable, "-m", "dynamo_tpu.cli.main", "lint", *argv],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )

    out = run(str(proj / "pkg"))
    assert out.returncode == 0, out.stdout + out.stderr  # disable honored
    assert "bogus_key" in out.stderr and "bogus_key" not in out.stdout
    bad = run(str(tmp_path / "nope"), "--format", "json")
    assert bad.returncode == 2 and bad.stdout.strip() == ""


def test_loop_create_task_chain_flagged():
    # the house idiom roots the attribute chain in a Call — must not
    # slip past the rule
    src = (
        "import asyncio\n"
        "async def f():\n"
        "    asyncio.get_running_loop().create_task(asyncio.sleep(0))\n"
    )
    findings = lint_source(src, rules=[get_rule("dropped-task-handle")])
    assert len(findings) == 1 and findings[0].line == 3


def test_comma_justification_does_not_break_suppression():
    # natural English after the rule name must not parse as rule names
    src = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # dynalint: disable=blocking-call-in-async, legacy kept\n"
    )
    assert unsuppressed(lint_source(src)) == []


def test_misplaced_disable_file_is_reported():
    src = "\n" * 10 + (
        "# dynalint: disable-file=bare-except\n"
        "def f():\n"
        "    try:\n"
        "        return 1\n"
        "    except:\n"
        "        pass\n"
    )
    live = unsuppressed(lint_source(src))
    assert {f.rule for f in live} == {"bad-suppression", "bare-except"}
    assert any("no effect" in f.message for f in live)


def test_raise_in_nested_def_is_not_a_reraise():
    src = (
        "import asyncio\n"
        "async def f(child):\n"
        "    try:\n"
        "        await child\n"
        "    except BaseException:\n"
        "        def h():\n"
        "            raise ValueError()\n"
        "        return h\n"
    )
    findings = lint_source(src, rules=[get_rule("swallowed-cancellation")])
    assert len(findings) == 1


def test_async_for_under_thread_lock_flagged():
    src = (
        "async def f(s):\n"
        "    with s._lock:\n"
        "        async for item in s.watch():\n"
        "            s.apply(item)\n"
    )
    findings = lint_source(src, rules=[get_rule("await-while-locked")])
    assert len(findings) == 1 and findings[0].line == 3


def test_dropped_task_message_names_the_chain():
    src = (
        "import asyncio\n"
        "async def f():\n"
        "    asyncio.get_running_loop().create_task(asyncio.sleep(0))\n"
    )
    (f,) = lint_source(src, rules=[get_rule("dropped-task-handle")])
    assert "asyncio.get_running_loop().create_task" in f.message


def test_include_globs_expand(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("def f():\n    try:\n        return 1\n"
                                "    except:\n        pass\n")
    from dynamo_tpu.analysis import iter_files

    assert iter_files([str(tmp_path / "*")]) == [pkg / "mod.py"]
    findings = lint_source((pkg / "mod.py").read_text())
    assert len(findings) == 1


def test_syntax_error_becomes_parse_finding():
    findings = lint_source("def broken(:\n")
    assert len(findings) == 1
    assert findings[0].code == "DL000" and findings[0].rule == "parse-error"


def test_rule_catalog_metadata():
    rules = all_rules()
    assert len(rules) == 12
    codes = [r.code for r in rules]
    assert codes == sorted(codes) and len(set(codes)) == len(codes)
    assert all(r.name == r.name.lower() and " " not in r.name for r in rules)


def test_json_report_shape():
    src = "def f():\n    try:\n        return 1\n    except:\n        return 0\n"
    payload = json.loads(format_json(lint_source(src)))
    assert payload["summary"]["unsuppressed"] == 1
    (f,) = payload["findings"]
    assert f["code"] == "DL006" and f["line"] == 4 and not f["suppressed"]


@pytest.mark.pre_merge
def test_cli_exit_codes_gate_on_findings():
    # non-zero on a violating file, zero on a clean one: the CI contract
    def run(*argv):
        return subprocess.run(
            [sys.executable, "-m", "dynamo_tpu.cli.main", "lint", *argv],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
    bad = run(str(DATA / "bare_except_bad.py"), "--format", "json")
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert json.loads(bad.stdout)["summary"]["unsuppressed"] >= 1
    ok = run(str(DATA / "bare_except_ok.py"))
    assert ok.returncode == 0, ok.stdout + ok.stderr


def test_cli_list_rules():
    out = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.cli.main", "lint", "--list-rules"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0
    for r in all_rules():
        assert r.code in out.stdout and r.name in out.stdout
