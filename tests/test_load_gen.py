"""Load generator (benchmarks/load_gen.py) against an in-process echo
HTTP service — percentile report sanity."""

import importlib.util
import os

from dynamo_tpu.engines import EchoEngineFull
from dynamo_tpu.http.service import HttpService, ModelManager

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_gen():
    spec = importlib.util.spec_from_file_location(
        "load_gen", os.path.join(REPO, "benchmarks", "load_gen.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


async def test_closed_loop_against_echo():
    lg = _load_gen()
    manager = ModelManager()
    manager.add_completion_model("echo", EchoEngineFull())
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    try:
        args = type("A", (), dict(
            url=f"http://127.0.0.1:{service.port}", model="echo",
            isl=6, osl=8, duration=1.5, request_timeout=30.0,
        ))()
        stats = await lg.run_closed_loop(args, concurrency=2)
        assert stats.completed >= 2 and stats.errors == 0
        assert stats.tokens > 0
        p = lg._percentiles(stats.ttft)
        assert p["p50"] >= 0
    finally:
        await service.stop()


async def test_multiturn_conversations_against_echo():
    """Multi-turn mode: each user's history grows turn over turn and
    TTFT is split into first-turn vs returning-turn buckets (the
    KV-offload benchmark's workload shape)."""
    lg = _load_gen()
    manager = ModelManager()
    manager.add_completion_model("echo", EchoEngineFull())
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    try:
        args = type("A", (), dict(
            url=f"http://127.0.0.1:{service.port}", model="echo",
            isl=4, osl=6, duration=0.0, request_timeout=30.0,
        ))()
        users, turns = 3, 3
        stats = await lg.run_multiturn(args, users, turns, think=0.0)
        assert stats.errors == 0
        assert stats.completed == users * turns
        assert len(stats.ttft_first) == users
        assert len(stats.ttft_later) == users * (turns - 1)
        assert stats.tokens > 0
    finally:
        await service.stop()
