"""Structured logging parity (reference: lib/runtime/src/logging.rs —
env-filter levels, JSONL output, config file, file target)."""

import json
import logging

from dynamo_tpu.runtime.logging import (
    JsonlFormatter,
    init_logging,
    parse_env_filter,
)


def _restore_root():
    root = logging.getLogger()
    root.handlers[:] = []
    root.setLevel(logging.WARNING)
    # clear per-target overrides set by tests
    for name in ("dynamo_tpu.engine", "aiohttp", "noisy.dep"):
        logging.getLogger(name).setLevel(logging.NOTSET)


def test_parse_env_filter():
    default, targets = parse_env_filter(
        "info,dynamo_tpu.engine=debug,aiohttp=warning"
    )
    assert default == logging.INFO
    assert targets == {
        "dynamo_tpu.engine": logging.DEBUG,
        "aiohttp": logging.WARNING,
    }
    # bare level only
    assert parse_env_filter("debug") == (logging.DEBUG, {})
    # unknown names fall back to INFO, empty parts ignored
    assert parse_env_filter("bogus,,x=nope") == (
        logging.INFO, {"x": logging.INFO}
    )


def test_jsonl_formatter_shape():
    rec = logging.LogRecord(
        "dynamo_tpu.engine", logging.INFO, __file__, 1, "hello %s", ("w",), None
    )
    out = json.loads(JsonlFormatter().format(rec))
    assert out["level"] == "INFO"
    assert out["target"] == "dynamo_tpu.engine"
    assert out["message"] == "hello w"
    assert out["ts"].endswith("Z")
    # local-tz variant drops the Z suffix
    out2 = json.loads(JsonlFormatter(local_tz=True).format(rec))
    assert not out2["ts"].endswith("Z")


def test_init_logging_env_filter_and_file(tmp_path, monkeypatch):
    log_path = str(tmp_path / "out.jsonl")
    monkeypatch.setenv("DYN_LOG_LEVEL", "warning,dynamo_tpu.engine=debug")
    monkeypatch.setenv("DYN_LOGGING_JSONL", "1")
    monkeypatch.setenv("DYN_LOG_FILE", log_path)
    try:
        init_logging()
        logging.getLogger("noisy.dep").info("dropped")  # below warning
        logging.getLogger("dynamo_tpu.engine").debug("kept by override")
        logging.getLogger("other").error("kept by level")
        for h in logging.getLogger().handlers:
            h.flush()
        lines = [json.loads(x) for x in open(log_path).read().splitlines()]
        messages = [x["message"] for x in lines]
        assert "dropped" not in messages
        assert "kept by override" in messages
        assert "kept by level" in messages
        assert all(set(x) >= {"ts", "level", "target", "message"} for x in lines)
    finally:
        _restore_root()


def test_init_logging_config_file(tmp_path, monkeypatch):
    log_path = str(tmp_path / "cfg.log")
    cfg = tmp_path / "logging.toml"
    cfg.write_text(
        f'level = "error"\njsonl = true\nfile = "{log_path}"\n'
    )
    monkeypatch.delenv("DYN_LOG_LEVEL", raising=False)
    monkeypatch.delenv("DYN_LOGGING_JSONL", raising=False)
    monkeypatch.delenv("DYN_LOG_FILE", raising=False)
    monkeypatch.setenv("DYN_LOGGING_CONFIG_PATH", str(cfg))
    try:
        init_logging()
        logging.getLogger("x").warning("dropped")
        logging.getLogger("x").error("kept")
        for h in logging.getLogger().handlers:
            h.flush()
        lines = [json.loads(x) for x in open(log_path).read().splitlines()]
        assert [x["message"] for x in lines] == ["kept"]
    finally:
        _restore_root()


def test_init_logging_env_overrides_config(tmp_path, monkeypatch):
    cfg = tmp_path / "logging.json"
    cfg.write_text(json.dumps({"level": "error"}))
    monkeypatch.setenv("DYN_LOGGING_CONFIG_PATH", str(cfg))
    monkeypatch.setenv("DYN_LOG_LEVEL", "debug")
    monkeypatch.delenv("DYN_LOG_FILE", raising=False)
    monkeypatch.delenv("DYN_LOGGING_JSONL", raising=False)
    try:
        init_logging()
        assert logging.getLogger().level == logging.DEBUG
    finally:
        _restore_root()


def test_reinit_resets_previous_target_overrides(monkeypatch):
    monkeypatch.setenv("DYN_LOG_LEVEL", "warning,dynamo_tpu.engine=debug")
    monkeypatch.delenv("DYN_LOG_FILE", raising=False)
    monkeypatch.delenv("DYN_LOGGING_CONFIG_PATH", raising=False)
    try:
        init_logging()
        assert logging.getLogger("dynamo_tpu.engine").level == logging.DEBUG
        # re-init with a plain filter: the stale DEBUG pin must clear
        monkeypatch.setenv("DYN_LOG_LEVEL", "warning")
        init_logging()
        assert logging.getLogger("dynamo_tpu.engine").level == logging.NOTSET
        assert not logging.getLogger("dynamo_tpu.engine").isEnabledFor(
            logging.DEBUG
        )
    finally:
        _restore_root()
