"""Response-side logprobs / top_logprobs / n>1 (VERDICT r3 item 4).

Reference parity target: the protocol layer carries per-token logprob
content (reference: lib/llm/src/protocols/common.rs:323-372
ChatCompletionLogprobs / TopLogprob) and n>1 produces multiple choices.
"""

import asyncio
import os

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    OutputOptions,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import AsyncEngine, Context

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")


def _engine_config(**kw) -> EngineConfig:
    defaults = dict(
        model_path=MODEL_DIR,
        model_name="tiny",
        random_weights=True,
        num_blocks=128,
        block_size=8,
        max_batch_size=8,
        prefill_chunk_size=32,
        max_model_len=256,
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


async def _collect(engine, req):
    items = []
    async for item in engine.as_async_engine().generate(req, Context()):
        items.append(item)
    return items


# ---------------------------------------------------------------------------
# Engine: top-logprob device slice
# ---------------------------------------------------------------------------


async def test_engine_top_logprobs_greedy():
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_config())
    try:
        req = PreprocessedRequest(
            request_id="lp1",
            token_ids=list(range(1, 20)),
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=6),
            output=OutputOptions(logprobs=3),
        )
        items = await _collect(engine, req)
        toks, lps, tops = [], [], []
        for it in items:
            toks.extend(it.token_ids)
            if it.log_probs:
                lps.extend(it.log_probs)
            if it.top_logprobs:
                tops.extend(it.top_logprobs)
        assert len(toks) == 6
        assert len(lps) == 6 and all(np.isfinite(lps))
        assert len(tops) == 6
        for tok, lp, top in zip(toks, lps, tops):
            assert len(top) == 3
            # greedy: the chosen token IS the most likely one, so it must
            # appear in the top slice with (approximately) its logprob
            assert tok in top
            assert abs(top[tok] - lp) < 1e-3
            assert max(top.values()) <= top[tok] + 1e-5
    finally:
        await engine.shutdown()


async def test_engine_top_logprobs_windowed_matches_chosen():
    """Fused multi-step windows must carry per-step top slices too."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_config(decode_steps=4))
    try:
        req = PreprocessedRequest(
            request_id="lpw",
            token_ids=list(range(1, 30)),
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=8),
            output=OutputOptions(logprobs=2),
        )
        items = await _collect(engine, req)
        toks, tops = [], []
        for it in items:
            toks.extend(it.token_ids)
            if it.top_logprobs:
                tops.extend(it.top_logprobs)
        assert len(toks) == 8 and len(tops) == 8
        for tok, top in zip(toks, tops):
            assert len(top) == 2 and tok in top
        # same request WITHOUT logprobs decodes identically (the variant
        # must not perturb sampling)
        req2 = req.model_copy(deep=True)
        req2.request_id = "lpw2"
        req2.output = OutputOptions()
        items2 = await _collect(engine, req2)
        toks2 = [t for it in items2 for t in it.token_ids]
        assert toks2 == toks
    finally:
        await engine.shutdown()


async def test_engine_chosen_logprob_base_path():
    """logprobs without top_logprobs rides the base step (no variant)."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_config())
    try:
        req = PreprocessedRequest(
            request_id="lp0",
            token_ids=list(range(1, 16)),
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=4),
            output=OutputOptions(logprobs=0),
        )
        items = await _collect(engine, req)
        lps = [l for it in items if it.log_probs for l in it.log_probs]
        tops = [t for it in items if it.top_logprobs for t in it.top_logprobs]
        assert len(lps) == 4 and not tops
    finally:
        await engine.shutdown()


# ---------------------------------------------------------------------------
# ChoiceFanout
# ---------------------------------------------------------------------------


class _ScriptEngine(AsyncEngine):
    """Yields a per-request scripted token stream (id-dependent)."""

    def generate(self, request, context):
        return self._gen(request, context)

    async def _gen(self, request, context):
        # distinguishable content per sub-request id
        tag = sum(ord(c) for c in request.request_id) % 97
        for k in range(3):
            yield LLMEngineOutput(
                request_id=request.request_id,
                token_ids=[tag + k],
                text=f"<{tag}:{k}>",
            )
        yield LLMEngineOutput(
            request_id=request.request_id,
            finish_reason=FinishReason.LENGTH,
            prompt_tokens=len(request.token_ids),
            completion_tokens=3,
        )


async def test_choice_fanout_two_choices():
    from dynamo_tpu.preprocessor.fanout import ChoiceFanout

    fan = ChoiceFanout(_ScriptEngine())
    req = PreprocessedRequest(
        request_id="fan", token_ids=[1, 2, 3],
        sampling=SamplingOptions(n=2, seed=7),
    )
    by_idx = {}
    async for item in fan.generate(req, Context()):
        assert item.request_id == "fan"
        by_idx.setdefault(item.index, []).append(item)
    assert set(by_idx) == {0, 1}
    for idx, items in by_idx.items():
        assert items[-1].finish_reason == FinishReason.LENGTH
        assert sum(len(i.token_ids) for i in items) == 3


class _StopperEngine(AsyncEngine):
    """Choice 0 triggers its stream's stop (the Backend does this when a
    stop condition fires); choice 1 keeps generating but aborts with
    CANCELLED if ITS context got stopped — the sibling-cancellation
    regression shape."""

    def generate(self, request, context):
        return self._gen(request, context)

    async def _gen(self, request, context):
        if request.request_id.endswith("-c0"):
            yield LLMEngineOutput(request_id=request.request_id, token_ids=[1])
            context.stop_generating()
            yield LLMEngineOutput(
                request_id=request.request_id,
                finish_reason=FinishReason.STOP, completion_tokens=1,
            )
            return
        for k in range(4):
            await asyncio.sleep(0.01)
            if context.is_stopped:
                yield LLMEngineOutput(
                    request_id=request.request_id,
                    finish_reason=FinishReason.CANCELLED,
                )
                return
            yield LLMEngineOutput(request_id=request.request_id, token_ids=[k])
        yield LLMEngineOutput(
            request_id=request.request_id,
            finish_reason=FinishReason.LENGTH, completion_tokens=4,
        )


async def test_choice_stop_does_not_cancel_siblings():
    from dynamo_tpu.preprocessor.fanout import ChoiceFanout

    fan = ChoiceFanout(_StopperEngine())
    req = PreprocessedRequest(
        request_id="sib", token_ids=[1], sampling=SamplingOptions(n=2)
    )
    finish = {}
    toks = {}
    async for item in fan.generate(req, Context()):
        toks.setdefault(item.index, []).extend(item.token_ids)
        if item.finish_reason:
            finish[item.index] = item.finish_reason
    assert finish[0] == FinishReason.STOP
    # the sibling must run to its own finish, not get cancelled
    assert finish[1] == FinishReason.LENGTH and len(toks[1]) == 4


async def test_choice_fanout_passthrough_n1():
    from dynamo_tpu.preprocessor.fanout import ChoiceFanout

    fan = ChoiceFanout(_ScriptEngine())
    req = PreprocessedRequest(request_id="solo", token_ids=[1])
    items = [i async for i in fan.generate(req, Context())]
    assert all(i.index == 0 for i in items)
    assert items[-1].finish_reason == FinishReason.LENGTH


async def test_engine_n2_distinct_sampled_choices():
    """n=2 through the real engine: the prefix cache makes the second
    choice's prompt a full cache hit, and sampled choices differ."""
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.preprocessor.fanout import ChoiceFanout

    engine = await JaxEngine.launch(_engine_config())
    try:
        prompt = list(range(1, 24))
        # prime the prefix cache so the fanned choices' prompts are hits
        # (concurrently-admitted choices can't hit each other's
        # still-uncommitted blocks — the cache dedupes across requests)
        warm = PreprocessedRequest(
            request_id="warm", token_ids=prompt,
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=1),
        )
        async for _ in engine.as_async_engine().generate(warm, Context()):
            pass
        fan = ChoiceFanout(engine.as_async_engine())
        req = PreprocessedRequest(
            request_id="nfan",
            token_ids=prompt,
            sampling=SamplingOptions(temperature=1.0, seed=3, n=2),
            stop=StopConditions(max_tokens=6),
        )
        by_idx = {}
        async for item in fan.generate(req, Context()):
            by_idx.setdefault(item.index, []).extend(item.token_ids)
        assert set(by_idx) == {0, 1}
        assert len(by_idx[0]) == 6 and len(by_idx[1]) == 6
        # seeds differ (seed+j) so the streams should diverge
        assert by_idx[0] != by_idx[1]
        # prompt prefix shared via the cache: choices were hits
        assert engine.stats().gpu_prefix_cache_hit_rate > 0.0
    finally:
        await engine.shutdown()


# ---------------------------------------------------------------------------
# Preprocessor backward: chunk shapes for logprobs + n>1
# ---------------------------------------------------------------------------


class _FakeTok:
    def decode(self, ids, skip_special_tokens=False):
        return "".join(f"t{i}" for i in ids)

    def encode(self, text, add_special_tokens=False):
        return [1, 2]


async def _run_backward(state_kind, items, n=1, logprobs=True):
    from dynamo_tpu.preprocessor.preprocessor import OpenAIPreprocessor, _ReqState

    pre = OpenAIPreprocessor.__new__(OpenAIPreprocessor)
    pre.tokenizer = _FakeTok()
    pre.formatter = None
    pre.model_name = "m"
    state = _ReqState(
        kind=state_kind, model="m", request_id="x", prompt_tokens=2,
        include_usage=True, logprobs=logprobs, n=n,
    )

    async def stream():
        for it in items:
            yield it

    return [c async for c in pre.backward(stream(), state, Context())]


async def test_backward_chat_logprob_content():
    items = [
        LLMEngineOutput(
            token_ids=[5], text="hi", log_probs=[-0.5],
            top_logprobs=[{5: -0.5, 9: -1.2}],
        ),
        LLMEngineOutput(finish_reason=FinishReason.STOP, completion_tokens=1),
    ]
    chunks = await _run_backward("chat", items)
    lp = chunks[0].choices[0].logprobs
    assert lp is not None
    entry = lp["content"][0]
    assert entry["token"] == "t5" and abs(entry["logprob"] + 0.5) < 1e-9
    assert {t["token"] for t in entry["top_logprobs"]} == {"t5", "t9"}
    assert entry["bytes"] == list(b"t5")
    # usage trails after the finish chunk
    assert chunks[-1].usage is not None and chunks[-1].usage.completion_tokens == 1


async def test_backward_completion_logprob_offsets():
    items = [
        LLMEngineOutput(token_ids=[3, 4], text="t3t4", log_probs=[-0.1, -0.2]),
        LLMEngineOutput(token_ids=[5], text="t5", log_probs=[-0.3]),
        LLMEngineOutput(finish_reason=FinishReason.LENGTH, completion_tokens=3),
    ]
    chunks = await _run_backward("completion", items)
    lp0 = chunks[0].choices[0].logprobs
    lp1 = chunks[1].choices[0].logprobs
    assert lp0["tokens"] == ["t3", "t4"] and lp0["text_offset"] == [0, 2]
    assert lp1["tokens"] == ["t5"] and lp1["text_offset"] == [4]
    assert lp1["token_logprobs"] == [-0.3]


async def test_backward_n2_per_choice_finish_and_single_usage():
    items = [
        LLMEngineOutput(token_ids=[1], text="a", index=0),
        LLMEngineOutput(token_ids=[2], text="b", index=1),
        LLMEngineOutput(
            finish_reason=FinishReason.STOP, completion_tokens=1, index=0
        ),
        LLMEngineOutput(token_ids=[3], text="c", index=1),
        LLMEngineOutput(
            finish_reason=FinishReason.LENGTH, completion_tokens=2, index=1
        ),
    ]
    chunks = await _run_backward("chat", items, n=2, logprobs=False)
    finishes = [
        (c.choices[0].index, c.choices[0].finish_reason)
        for c in chunks
        if c.choices and c.choices[0].finish_reason
    ]
    assert ("0", "stop") not in finishes  # indices are ints, not strings
    assert (0, "stop") in finishes and (1, "length") in finishes
    usages = [c for c in chunks if c.usage is not None]
    assert len(usages) == 1 and usages[0].usage.completion_tokens == 3
    # both choices' first delta carries the assistant role
    roles = {
        c.choices[0].index
        for c in chunks
        if c.choices and c.choices[0].delta.role
    }
    assert roles == {0, 1}


# ---------------------------------------------------------------------------
# Validation (400 class)
# ---------------------------------------------------------------------------


def test_request_validation_rejects_bad_params():
    from dynamo_tpu.protocols.openai import (
        ChatCompletionRequest,
        CompletionRequest,
    )

    base = dict(model="m", messages=[{"role": "user", "content": "x"}])
    with pytest.raises(Exception):
        ChatCompletionRequest.model_validate({**base, "n": 0})
    with pytest.raises(Exception):
        ChatCompletionRequest.model_validate({**base, "n": 99})
    with pytest.raises(Exception):
        ChatCompletionRequest.model_validate(
            {**base, "logprobs": True, "top_logprobs": 25}
        )
    with pytest.raises(Exception):
        ChatCompletionRequest.model_validate({**base, "top_logprobs": 5})
    # valid forms pass
    r = ChatCompletionRequest.model_validate(
        {**base, "logprobs": True, "top_logprobs": 5, "n": 2}
    )
    assert r.output_options().logprobs == 5 and r.sampling_options().n == 2
    with pytest.raises(Exception):
        CompletionRequest.model_validate(
            {"model": "m", "prompt": "x", "logprobs": 25}
        )


def test_top_k_clamped_at_validation_boundary():
    opts = SamplingOptions(top_k=4096, temperature=0.7).normalized()
    assert opts.top_k == SamplingOptions.TOP_K_CAP


def test_token_bytes_reassemble_multibyte():
    """OpenAI's logprob ``bytes`` field must carry each token's RAW
    byte contribution: per-token decode() of a byte-level BPE yields
    U+FFFD for partial UTF-8 sequences, but concatenating token_bytes
    reconstructs the exact text (the field's whole purpose)."""
    from dynamo_tpu.tokenizer import Tokenizer

    t = Tokenizer.from_file(
        os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")
    )
    s = "héllo \U0001F30D 你好"
    ids = t.encode(s)
    # the failure mode this guards against: single-id decode garbles
    assert any("�" in t.decode([i]) for i in ids)
    joined = b"".join(t.token_bytes(i) for i in ids)
    assert joined == t.decode(ids).encode("utf-8")


async def test_logit_bias_variant_end_to_end():
    """logit_bias is presence-keyed (a separate jit variant): a biased
    request must actually steer sampling, and bias-free requests on the
    same engine keep using the bias-free variant."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_config())
    try:
        prompt = list(range(1, 16))
        base = PreprocessedRequest(
            request_id="nb", token_ids=prompt,
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=3),
        )
        toks_base = [
            t for it in await _collect(engine, base) for t in it.token_ids
        ]
        # +30 bias on a fixed token overwhelms a random-weight model's
        # logits: every greedy pick becomes that token
        forced = 7
        biased = PreprocessedRequest(
            request_id="wb", token_ids=prompt,
            sampling=SamplingOptions(
                use_greedy=True, logit_bias={forced: 30.0}
            ),
            stop=StopConditions(max_tokens=3),
        )
        toks_b = [
            t for it in await _collect(engine, biased) for t in it.token_ids
        ]
        assert toks_b == [forced] * 3
        # and the engine still serves unbiased traffic identically
        base2 = base.model_copy(deep=True)
        base2.request_id = "nb2"
        toks_base2 = [
            t for it in await _collect(engine, base2) for t in it.token_ids
        ]
        assert toks_base2 == toks_base
    finally:
        await engine.shutdown()
