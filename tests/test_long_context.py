"""Sequence-parallel long-context prefill (parallel/long_context.py):
ring/Ulysses-sharded prompt processing whose KV feeds the paged decode
engine through the disagg plane. The reference has no long-context
scaling (SURVEY.md §5) — this is TPU-native added capability, so the
tests pin it to the engine's own prefill for equivalence."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import init_params
from dynamo_tpu.parallel.long_context import (
    LongContextPrefiller,
    kv_to_packed_blocks,
    long_prefill,
)
from dynamo_tpu.parallel.mesh import MeshConfig, build_mesh

CFG = ModelConfig(
    vocab_size=128, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    max_position_embeddings=256,
)


def _dense_oracle(cfg, params, tokens):
    """Plain full attention forward returning (last_logits, per-layer KV)."""
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import (
        _moe_mlp, layer_param_names, rmsnorm, rope,
    )

    H, Hk, Dh = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    B, T = tokens.shape
    positions = jnp.arange(T, dtype=jnp.int32)[None, :]
    x = jnp.take(params["embed"], tokens, axis=0)
    ks, vs = [], []
    lp_all = {n: params[n] for n in layer_param_names(params)}
    for i in range(cfg.num_hidden_layers):
        lp = {n: lp_all[n][i] for n in lp_all}
        h = rmsnorm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q = (h @ lp["wq"]).reshape(B, T, H, Dh)
        k = (h @ lp["wk"]).reshape(B, T, Hk, Dh)
        v = (h @ lp["wv"]).reshape(B, T, Hk, Dh)
        q, k = rope(q, k, positions, cfg.rope_theta)
        ks.append(k[0]); vs.append(v[0])
        group = H // Hk
        kk = jnp.repeat(k, group, axis=2)
        vv = jnp.repeat(v, group, axis=2)
        s = jnp.einsum("bthd,bshd->bhts", q, kk).astype(jnp.float32) / np.sqrt(Dh)
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(x.dtype)
        a = jnp.einsum("bhts,bshd->bthd", p, vv)
        x = x + (a.reshape(B, T, H * Dh) @ lp["wo"]).astype(x.dtype)
        h = rmsnorm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        mlp = (jax.nn.silu(h @ lp["w_gate"]) * (h @ lp["w_up"])) @ lp["w_down"]
        x = x + mlp.astype(x.dtype)
    x = rmsnorm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = (x[:, -1] @ params["lm_head"]).astype(jnp.float32)
    return np.asarray(logits), np.stack([np.asarray(k) for k in ks]), np.stack(
        [np.asarray(v) for v in vs]
    )


@pytest.mark.parametrize("attn", ["ring", "ulysses"])
def test_long_prefill_matches_dense_oracle(attn):
    params = init_params(CFG, seed=0)
    T = 32
    tokens = np.random.default_rng(0).integers(1, 100, (1, T)).astype(np.int32)
    # ulysses reshards heads over sp: needs Hkv (=2) divisible by sp
    sp = 4 if attn == "ring" else 2
    mesh = build_mesh(MeshConfig(sp=sp), jax.devices()[:sp])
    logits, k, v = jax.jit(
        lambda p, t: long_prefill(CFG, p, t, mesh, attn=attn)
    )(params, tokens)
    ref_logits, ref_k, ref_v = _dense_oracle(CFG, params, tokens)
    # bf16 weights/activations: tolerate one ulp of bf16 around |x|~2
    np.testing.assert_allclose(np.asarray(logits), ref_logits, rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(np.asarray(k), ref_k, rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(np.asarray(v), ref_v, rtol=5e-2, atol=5e-2)


def test_kv_to_packed_blocks_layout():
    L, T, Hk, Dh, bs = 2, 10, 2, 4, 4
    k = np.arange(L * T * Hk * Dh, dtype=np.float32).reshape(L, T, Hk, Dh)
    v = -k
    packed = kv_to_packed_blocks(k, v, bs, T)
    assert packed.shape == (2, 2, L, bs, Hk, Dh)  # tail (2 tokens) dropped
    np.testing.assert_array_equal(packed[1, 0, 1], k[1, bs : 2 * bs])
    np.testing.assert_array_equal(packed[0, 1, 0], v[0, :bs])


def test_sp_prefill_kv_matches_engine_prefill():
    """The contract behind the import path, asserted at the KV seam
    itself: the sp prefiller's per-position K/V must agree with what
    the decode engine's own paged prefill writes for the same prompt.

    Diagnosis of the old "last-token drift" skip (2026-08-03): there is
    NO indexing off-by-one. Layer-0 K/V — which see embedding, norm,
    qkv matmul and RoPE but no attention — are BIT-EXACT between the
    two paths (asserted below: an off-by-one in positions, slots, or
    rope angles would break this loudly). The drift enters at the first
    ATTENTION output: ring attention's per-shard online softmax and the
    engine's single-pass reference attention accumulate in different
    orders, so their bf16 outputs differ by ~1-2 ulp, and every
    layer>=1 position inherits that noise (measured max ~0.03 at
    |x|~2). Greedy decode over imported KV can therefore flip a token
    whose top-2 logit gap is inside the noise — which is what the old
    skip saw at its final decoded token."""
    import functools

    import jax.numpy as jnp

    from dynamo_tpu.models.llama import forward, init_cache

    params = init_params(CFG, seed=0)
    mesh = build_mesh(MeshConfig(sp=4), jax.devices()[:4])
    bs = 4
    prefiller = LongContextPrefiller(
        CFG, params, mesh, block_size=bs, kv_dtype="float32"
    )
    prompt = list(np.random.default_rng(1).integers(1, 100, 19))
    last, k_sp, v_sp = prefiller.prefill(prompt)

    # the engine's own prefill of the same prompt: one paged forward
    T = len(prompt)
    k_cache, v_cache = init_cache(CFG, 16, bs, dtype=jnp.float32)
    table = np.arange(1, 7, dtype=np.int32)[None]
    slots = (
        table[0][np.arange(T) // bs] * bs + np.arange(T) % bs
    ).astype(np.int32)
    fwd = jax.jit(functools.partial(forward, CFG, block_size=bs))
    logits, k_c, v_c = fwd(
        params, k_cache, v_cache,
        jnp.asarray([prompt], jnp.int32),
        jnp.arange(T, dtype=jnp.int32)[None],
        jnp.asarray(slots), jnp.asarray(table),
        jnp.asarray([T], jnp.int32), jnp.asarray([T - 1], jnp.int32),
    )
    k_eng = np.asarray(k_c)[:, slots]  # [L, T, Hk, Dh]
    v_eng = np.asarray(v_c)[:, slots]

    # layer 0 = the off-by-one detector: no attention upstream, so any
    # position/slot/rope indexing bug shows as O(1) error here
    np.testing.assert_array_equal(k_sp[0], k_eng[0])
    np.testing.assert_array_equal(v_sp[0], v_eng[0])
    # layers >= 1 carry the cross-algorithm attention rounding — every
    # position must stay within bf16-ulp-scale tolerance (an indexing
    # bug would be O(1), orders of magnitude past this bound)
    np.testing.assert_allclose(k_sp, k_eng, rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(v_sp, v_eng, rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(
        last, np.asarray(logits)[0], rtol=5e-2, atol=5e-2
    )


async def test_sp_prefiller_feeds_decode_engine():
    """Flagship: KV computed by the sp=4 ring prefiller is imported by
    a decode engine, which then decodes the same continuation as a
    purely-local run — up to greedy near-ties. Exact token equality is
    NOT the contract: the imported KV differs from the engine's own
    prefill by ~1-2 bf16 ulp of attention-algorithm rounding (see
    test_sp_prefill_kv_matches_engine_prefill for the diagnosis), so at
    any position where the two runs disagree, the chosen tokens must be
    a near-tie — their greedy logprobs within the noise band. A real
    KV bug (wrong block, wrong position) would make the divergent
    logprobs differ by O(1) and fail loudly."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.protocols.common import (
        OutputOptions,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    bs = 4
    params = init_params(CFG, seed=0)
    mesh = build_mesh(MeshConfig(sp=4), jax.devices()[:4])
    prefiller = LongContextPrefiller(
        CFG, params, mesh, block_size=bs, kv_dtype="float32"
    )
    prompt = list(np.random.default_rng(1).integers(1, 100, 19))
    hashes, packed = await prefiller.prefill_export(prompt)
    assert len(hashes) == len(prompt) // bs == packed.shape[0]

    # padded prompt (19 -> 20): logits must be the last REAL token's
    last, _, _ = prefiller.prefill(prompt)
    ref_last, _, _ = _dense_oracle(
        CFG, params, np.asarray([prompt], np.int32)
    )
    np.testing.assert_allclose(last, ref_last[0], rtol=5e-2, atol=5e-2)

    async def decode(with_import: bool) -> tuple[list[int], list[float]]:
        engine = await JaxEngine.launch(
            EngineConfig(
                model_path="", model_name="d", random_weights=True,
                num_blocks=32, block_size=bs, max_batch_size=2,
                host_kv_blocks=16, kv_cache_dtype="float32",
            ),
            model_config=CFG,
        )
        # same weights as the prefiller
        engine.params = {k: v for k, v in params.items()}
        if with_import:
            n = await engine.import_kv_blocks(hashes, packed)
            assert n == len(hashes)
        req = PreprocessedRequest(
            request_id="sp1", token_ids=list(prompt),
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=6, ignore_eos=True),
            output=OutputOptions(logprobs=0),
        )
        toks: list[int] = []
        lps: list[float] = []
        async for item in engine.as_async_engine().generate(req, Context()):
            toks.extend(item.token_ids)
            if item.log_probs:
                lps.extend(item.log_probs)
        await engine.shutdown()
        return toks, lps

    toks_imp, lps_imp = await decode(True)
    toks_loc, lps_loc = await decode(False)
    assert len(toks_imp) == len(toks_loc) == 6
    assert len(lps_imp) == len(lps_loc) == 6
    for i, (a, b) in enumerate(zip(toks_imp, toks_loc)):
        if a == b:
            continue
        # divergence is only legitimate as a greedy near-tie: both
        # runs' chosen-token logprobs must sit within the KV-rounding
        # noise band of each other
        assert abs(lps_imp[i] - lps_loc[i]) < 0.1, (
            f"token {i} diverged ({a} vs {b}) with logprob gap "
            f"{abs(lps_imp[i] - lps_loc[i]):.4f} — a real KV bug, not "
            f"attention-rounding noise"
        )
        # after a flip the runs walk different paths; nothing further
        # is comparable position-by-position
        break
