"""Cardinality gate (ISSUE 2 satellite): walk every metric the serving
stack registers and fail the build if the surface could become
scrape-unsafe — per-request identifier labels, absurd series bounds, or
missing help text. Importing the layer modules below is what populates
the process registry, so a new instrument anywhere in the stack is
automatically in scope."""

import importlib

import pytest

from dynamo_tpu.telemetry import REGISTRY, check_scrape_safety
from dynamo_tpu.telemetry.metrics import (
    DEFAULT_MAX_SERIES,
    FORBIDDEN_LABEL_NAMES,
    Registry,
)

# every module that declares or touches process-global instruments
_INSTRUMENTED_MODULES = [
    "dynamo_tpu.telemetry.instruments",
    "dynamo_tpu.http.service",
    "dynamo_tpu.metrics.service",
    "dynamo_tpu.disagg.worker",
    "dynamo_tpu.disagg.transfer",
    "dynamo_tpu.engine.scheduler",
    "dynamo_tpu.kvbm.manager",
]


def _load_all() -> None:
    for mod in _INSTRUMENTED_MODULES:
        importlib.import_module(mod)


def test_process_registry_is_scrape_safe():
    _load_all()
    check_scrape_safety(REGISTRY)


def test_every_instrument_has_bounded_labels():
    _load_all()
    for m in REGISTRY.metrics():
        # denylist enforced at declaration; belt-and-braces here
        assert not (set(m.label_names) & FORBIDDEN_LABEL_NAMES), m.name
        assert m.max_series <= DEFAULT_MAX_SERIES, (
            f"{m.name}: raise the gate bound deliberately if a metric "
            f"really needs more than {DEFAULT_MAX_SERIES} series"
        )
        assert m.help, m.name


def test_metrics_service_registry_is_scrape_safe():
    """The aggregation service builds a per-instance registry; its
    declarations must pass the same gate (constructed without a
    component — declaration happens in __init__ before any I/O)."""
    from dynamo_tpu.metrics.service import MetricsService

    svc = MetricsService(component=None, host="127.0.0.1", port=0)  # type: ignore[arg-type]
    check_scrape_safety(svc.registry)


def test_gate_catches_a_request_id_label():
    r = Registry()
    with pytest.raises(ValueError):
        r.counter("bad_total", "h", labels=("request_id",))
