"""Cardinality gate (ISSUE 2 satellite): walk every metric the serving
stack registers and fail the build if the surface could become
scrape-unsafe — per-request identifier labels, absurd series bounds, or
missing help text. Importing the layer modules below is what populates
the process registry, so a new instrument anywhere in the stack is
automatically in scope."""

import importlib

import pytest

from dynamo_tpu.telemetry import REGISTRY, check_scrape_safety
from dynamo_tpu.telemetry.metrics import (
    DEFAULT_MAX_SERIES,
    FORBIDDEN_LABEL_NAMES,
    Registry,
)

# every module that declares or touches process-global instruments
_INSTRUMENTED_MODULES = [
    "dynamo_tpu.telemetry.instruments",
    "dynamo_tpu.telemetry.recorder",
    "dynamo_tpu.telemetry.slo",
    "dynamo_tpu.telemetry.hbm",
    "dynamo_tpu.telemetry.attribution",
    "dynamo_tpu.telemetry.hostplane",
    "dynamo_tpu.telemetry.autopsy",
    "dynamo_tpu.http.service",
    "dynamo_tpu.metrics.service",
    "dynamo_tpu.disagg.worker",
    "dynamo_tpu.disagg.transfer",
    "dynamo_tpu.engine.scheduler",
    "dynamo_tpu.kvbm.manager",
    "dynamo_tpu.planner.planner",
]

# the ISSUE 4 observability surface: these series must exist in the
# process registry (catalog drift fails here, not in a dashboard)
_REQUIRED_SERIES = [
    "dynamo_slo_attainment",
    "dynamo_goodput_tokens_total",
    "dynamo_slo_requests_total",
    "dynamo_request_ttft_seconds",
    "dynamo_request_itl_seconds",
    "dynamo_engine_slow_steps_total",
    "dynamo_flight_recorder_dumps_total",
    "dynamo_kv_pool_blocks_active",
    "dynamo_kv_pool_blocks_total",
    "dynamo_kv_pool_cached_free_blocks",
    "dynamo_hbm_weight_bytes",
    "dynamo_hbm_kv_pool_bytes",
    "dynamo_hbm_bytes_in_use",
    "dynamo_hbm_bytes_limit",
    "dynamo_hbm_peak_bytes",
    # ISSUE 6: the self-healing planner surface
    "dynamo_planner_scale_events_total",
    "dynamo_planner_replacements_total",
    "dynamo_planner_degradation_level",
    "dynamo_planner_connector_failures_total",
    # ISSUE 10: the perf-attribution surface (telemetry/attribution.py)
    "dynamo_step_time_frac",
    "dynamo_roofline_frac",
    "dynamo_tokens_lost_per_s",
    "dynamo_blackbox_dumps_total",
    # ISSUE 12: the overlapped spec pipeline surface
    "dynamo_spec_draft_hidden_frac",
    "dynamo_spec_accept_rate",
    "dynamo_spec_proposed_tokens_total",
    "dynamo_spec_accepted_tokens_total",
    # ISSUE 13: the serve-phase compile fence (DYN_COMPILE_FENCE)
    "dynamo_compile_fence_events_total",
    # ISSUE 16: the serve-phase transfer fence (DYN_TRANSFER_FENCE)
    "dynamo_transfer_fence_events_total",
    # ISSUE 14: mid-stream migration (docs/robustness.md)
    "dynamo_midstream_resumes_total",
    "dynamo_midstream_resume_seconds",
    "dynamo_midstream_aborts_total",
    "dynamo_failover_retries_total",
    # ISSUE 15: guided decoding / tool calls (docs/guided_decoding.md)
    "dynamo_guided_compile_seconds",
    "dynamo_guided_cache_events_total",
    "dynamo_guided_requests_total",
    "dynamo_tool_call_streams_total",
    # ISSUE 17: the host data plane (telemetry/hostplane.py)
    "dynamo_http_loop_lag_seconds",
    "dynamo_http_loop_lag_p99_seconds",
    "dynamo_http_loop_lag_max_seconds",
    "dynamo_http_loop_stalls_total",
    "dynamo_http_open_streams",
    "dynamo_http_host_stage_seconds",
    "dynamo_http_first_chunk_wait_seconds",
    "dynamo_http_sse_write_ema_seconds",
    "dynamo_http_drain_wait_seconds",
    # ISSUE 18: the fleet KV fabric (kvbm/fabric.py, docs/kvbm.md)
    "dynamo_kvbm_remote_timeout_total",
    "dynamo_kvbm_fleet_hits_total",
    "dynamo_kvbm_fleet_fetched_blocks_total",
    "dynamo_kvbm_fleet_fetch_seconds",
    "dynamo_kvbm_fleet_demoted_blocks_total",
    "dynamo_kvbm_fleet_catalog_entries",
    "dynamo_kvbm_fleet_dangling_total",
    # ISSUE 19: request autopsy (telemetry/autopsy.py) — request-bounded
    # counters only; the per-request detail lives in the exemplar ring,
    # never as labeled series
    "dynamo_autopsy_requests_total",
    "dynamo_autopsy_exemplars",
    "dynamo_autopsy_segments_total",
    # ISSUE 20: graceful drain (runtime/drain.py, docs/robustness.md)
    "dynamo_worker_drains_total",
    "dynamo_drain_handoff_seconds",
    "dynamo_drain_streams_migrated_total",
]


def _load_all() -> None:
    for mod in _INSTRUMENTED_MODULES:
        importlib.import_module(mod)


def test_process_registry_is_scrape_safe():
    _load_all()
    check_scrape_safety(REGISTRY)


def test_every_instrument_has_bounded_labels():
    _load_all()
    for m in REGISTRY.metrics():
        # denylist enforced at declaration; belt-and-braces here
        assert not (set(m.label_names) & FORBIDDEN_LABEL_NAMES), m.name
        assert m.max_series <= DEFAULT_MAX_SERIES, (
            f"{m.name}: raise the gate bound deliberately if a metric "
            f"really needs more than {DEFAULT_MAX_SERIES} series"
        )
        assert m.help, m.name


def test_metrics_service_registry_is_scrape_safe():
    """The aggregation service builds a per-instance registry; its
    declarations must pass the same gate (constructed without a
    component — declaration happens in __init__ before any I/O)."""
    from dynamo_tpu.metrics.service import MetricsService

    svc = MetricsService(component=None, host="127.0.0.1", port=0)  # type: ignore[arg-type]
    check_scrape_safety(svc.registry)


def test_observability_series_are_registered():
    _load_all()
    missing = [n for n in _REQUIRED_SERIES if REGISTRY.get(n) is None]
    assert not missing, f"catalog drifted: {missing}"
    # bounded label sets on the labeled ones
    assert REGISTRY.get("dynamo_slo_requests_total").label_names == (
        "outcome",
    )
    assert REGISTRY.get("dynamo_engine_slow_steps_total").label_names == (
        "kind",
    )
    assert REGISTRY.get(
        "dynamo_flight_recorder_dumps_total"
    ).label_names == ("reason",)
    assert REGISTRY.get(
        "dynamo_planner_scale_events_total"
    ).label_names == ("component", "direction")
    assert REGISTRY.get(
        "dynamo_planner_replacements_total"
    ).label_names == ("component",)
    # the attribution families key on the bounded loss-bucket set
    assert REGISTRY.get("dynamo_step_time_frac").label_names == (
        "component",
    )
    assert REGISTRY.get("dynamo_tokens_lost_per_s").label_names == (
        "component",
    )
    assert REGISTRY.get("dynamo_roofline_frac").label_names == ()
    assert REGISTRY.get("dynamo_blackbox_dumps_total").label_names == (
        "reason",
    )
    # migration outcomes key on the bounded {ok, failed} result set
    assert REGISTRY.get("dynamo_midstream_resumes_total").label_names == (
        "result",
    )
    assert REGISTRY.get("dynamo_midstream_resume_seconds").label_names == ()
    # guided decoding keys on the bounded spec-kind / result / mode sets
    assert REGISTRY.get("dynamo_guided_compile_seconds").label_names == (
        "kind",
    )
    assert REGISTRY.get(
        "dynamo_guided_cache_events_total"
    ).label_names == ("result",)
    assert REGISTRY.get("dynamo_guided_requests_total").label_names == (
        "kind",
    )
    assert REGISTRY.get("dynamo_tool_call_streams_total").label_names == (
        "mode",
    )
    # the host-stage histogram keys on the fixed ledger stage set
    assert REGISTRY.get("dynamo_http_host_stage_seconds").label_names == (
        "stage",
    )
    assert REGISTRY.get("dynamo_http_loop_lag_seconds").label_names == ()
    assert REGISTRY.get("dynamo_http_loop_stalls_total").label_names == ()
    assert REGISTRY.get("dynamo_http_open_streams").label_names == ()
    # fleet fabric: hit source and demotion destination are fixed enums
    assert REGISTRY.get("dynamo_kvbm_fleet_hits_total").label_names == (
        "source",
    )
    assert REGISTRY.get(
        "dynamo_kvbm_fleet_demoted_blocks_total"
    ).label_names == ("dest",)
    assert REGISTRY.get("dynamo_kvbm_remote_timeout_total").label_names == (
        "op",
    )
    assert REGISTRY.get(
        "dynamo_kvbm_fleet_catalog_entries"
    ).label_names == ()
    # autopsy: retention outcome and segment source are fixed enums;
    # the rid itself must never become a label (gate below enforces)
    assert REGISTRY.get("dynamo_autopsy_requests_total").label_names == (
        "outcome",
    )
    assert REGISTRY.get("dynamo_autopsy_exemplars").label_names == ()
    assert REGISTRY.get("dynamo_autopsy_segments_total").label_names == (
        "source",
    )


def test_metric_catalog_docs_match_registry():
    """docs/observability.md's catalog table IS the documentation
    contract for the metric surface: every series the process registers
    must have a row, and every row must name a live series.  Catalog
    rot was a review nit before this test; now it's a tier-1 failure
    in both directions (ISSUE 13 satellite)."""
    import re
    from pathlib import Path

    _load_all()
    registered = {m.name for m in REGISTRY.metrics()}
    docs = (
        Path(__file__).resolve().parents[1] / "docs" / "observability.md"
    ).read_text()
    documented = {
        m.group(1)
        for m in re.finditer(r"^\|\s*`(dynamo_[a-z0-9_]+)`", docs, re.M)
    }
    undocumented = sorted(registered - documented)
    assert not undocumented, (
        "series registered but missing from docs/observability.md's "
        f"catalog table: {undocumented}"
    )
    ghosts = sorted(documented - registered)
    assert not ghosts, (
        "docs/observability.md catalog rows naming no registered "
        f"series: {ghosts}"
    )


def test_gate_catches_a_request_id_label():
    r = Registry()
    with pytest.raises(ValueError):
        r.counter("bad_total", "h", labels=("request_id",))
