"""Mid-stream request migration (docs/robustness.md "Mid-stream
migration"): the routers' resume/splice machinery, the scheduler's
cache-hot resume bias, the admission bypass, and the engine's
resume_offset RNG contract.

The fake worker here is a *faithful* miniature of the engine contract:
deterministic next-token function of the LAST token only (so a resume
from an extended prompt continues exactly like greedy decoding would),
segment-local cum_log_probs, and a final chunk carrying its own
prompt/completion counts — which is precisely what the splice must
re-anchor."""

import asyncio

import pytest

from dynamo_tpu import faults
from dynamo_tpu.http.admission import AdmissionConfig, AdmissionController
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context, collect
from dynamo_tpu.runtime.migration import (
    MigrationConfig,
    StreamProgress,
    WorkerStreamLostError,
    resumable,
)
from dynamo_tpu.runtime.push_router import PushRouter, RouterMode
from dynamo_tpu.runtime.service import ConnectionLostError
from dynamo_tpu.telemetry.instruments import (
    MIDSTREAM_ABORTS,
    MIDSTREAM_RESUMES,
)

pytestmark = pytest.mark.chaos


def _next_tok(t: int) -> int:
    return (t * 7 + 13) % 997


def _reference_run(token_ids, n):
    """What an unkilled greedy run emits for this prompt."""
    out, t = [], token_ids[-1]
    for _ in range(n):
        t = _next_tok(t)
        out.append(t)
    return out


class FakeWorker:
    """Engine-contract fake: yields one token per item (dict-shaped,
    like the wire), then a final chunk; optionally dies after
    ``die_after`` items. Records every request it served."""

    def __init__(self, die_after=None):
        self.die_after = die_after
        self.requests = []

    async def stream(self, request):
        self.requests.append(request)
        toks = list(request.token_ids)
        budget = request.stop.max_tokens
        emitted = 0
        cum = 0.0
        while budget is None or emitted < budget:
            if self.die_after is not None and emitted >= self.die_after:
                raise ConnectionLostError("worker died mid-stream")
            t = _next_tok(toks[-1])
            toks.append(t)
            emitted += 1
            cum -= 0.5
            yield {
                "request_id": request.request_id,
                "token_ids": [t],
                "cum_log_probs": cum,
            }
            await asyncio.sleep(0)
        yield {
            "request_id": request.request_id,
            "token_ids": [],
            "finish_reason": "length",
            "prompt_tokens": len(request.token_ids),
            "completion_tokens": emitted,
        }


class _Endpoint:
    path = "test.migration.generate"


class FakeClient:
    """Duck-typed runtime Client: a dict of live workers."""

    def __init__(self, workers):
        self.workers = dict(workers)
        self.endpoint = _Endpoint()

    def instance_ids(self):
        return sorted(self.workers)

    async def wait_for_instances(self, timeout_s=None):
        ids = self.instance_ids()
        if not ids:
            raise asyncio.TimeoutError("no instances")
        return ids

    async def generate_direct(self, instance_id, request, context=None):
        worker = self.workers.get(instance_id)
        if worker is None:
            raise KeyError(f"instance {instance_id:x} not found")
        return worker.stream(request)


def _req(prompt=None, max_tokens=8, **kw):
    return PreprocessedRequest(
        request_id="mig-1",
        token_ids=list(prompt or [1, 2, 3]),
        sampling=SamplingOptions(use_greedy=True),
        stop=StopConditions(max_tokens=max_tokens),
        **kw,
    )


def _val(metric, *labels):
    return metric.labels(*labels).value


def _router(client, **kw):
    kw.setdefault("migration", MigrationConfig(instance_wait_s=0.5))
    return PushRouter(client, RouterMode.ROUND_ROBIN, **kw)


# ---------------------------------------------------------------------------
# the splice
# ---------------------------------------------------------------------------


async def test_midstream_death_resumes_and_splices_exactly():
    """Kill after 3 delivered tokens: the client sees ONE stream whose
    token sequence is bit-identical to an unkilled run — no repeats, no
    gaps — and the abort counter stays untouched."""
    # round-robin picks index 1 of the sorted ids first: the dying
    # worker sits at id 2 so the first dispatch lands on it
    dying, survivor = FakeWorker(die_after=3), FakeWorker()
    client = FakeClient({1: survivor, 2: dying})
    router = _router(client)
    ok0 = _val(MIDSTREAM_RESUMES, "ok")
    aborts0 = MIDSTREAM_ABORTS.labels().value
    req = _req(max_tokens=8)

    items = await asyncio.wait_for(
        collect(router.generate(req, Context())), timeout=10
    )
    toks = [t for i in items for t in i.get("token_ids", [])]
    assert toks == _reference_run(req.token_ids, 8)
    assert _val(MIDSTREAM_RESUMES, "ok") == ok0 + 1
    assert MIDSTREAM_ABORTS.labels().value == aborts0

    # the resume the survivor saw: prompt extended by the 3 delivered
    # tokens, budget shrunk, RNG offset advanced, same request id
    assert len(survivor.requests) == 1
    res = survivor.requests[0]
    assert res.token_ids == req.token_ids + toks[:3]
    assert res.stop.max_tokens == 5
    assert res.resume_offset == 3
    assert res.request_id == req.request_id

    # usage on the final chunk is re-anchored to the ORIGINAL request
    final = items[-1]
    assert final["finish_reason"] == "length"
    assert final["prompt_tokens"] == len(req.token_ids)
    assert final["completion_tokens"] == 8

    # cum_log_probs is continuous across the splice (each segment
    # restarts at 0 engine-side; the splice re-anchors)
    cums = [i["cum_log_probs"] for i in items if "cum_log_probs" in i]
    assert cums == pytest.approx([-0.5 * (k + 1) for k in range(8)])


async def test_double_migration_survives_two_spaced_deaths():
    """Each splice that delivers tokens resets the resume budget: a
    stream can migrate any number of times as long as it progresses."""
    # dispatch order under round-robin + exclusion: 2 (dies after 2
    # tokens), 1 (dies after 3 more), 3 (completes)
    w1, w2, w3 = FakeWorker(die_after=3), FakeWorker(die_after=2), FakeWorker()
    client = FakeClient({1: w1, 2: w2, 3: w3})
    router = _router(client)
    req = _req(max_tokens=10)
    items = await asyncio.wait_for(
        collect(router.generate(req, Context())), timeout=10
    )
    toks = [t for i in items for t in i.get("token_ids", [])]
    assert toks == _reference_run(req.token_ids, 10)
    # second resume extends by BOTH segments' deliveries
    assert w3.requests[0].token_ids == req.token_ids + toks[:5]
    assert w3.requests[0].resume_offset == 5
    assert items[-1]["completion_tokens"] == 10


async def test_budget_exhausted_death_synthesizes_final():
    """The worker died having delivered every budgeted token — only the
    finish marker was lost. Nothing remains to resume; the router
    completes the stream itself with stitched usage."""
    dying = FakeWorker(die_after=4)
    client = FakeClient({1: FakeWorker(), 2: dying})
    router = _router(client)
    req = _req(max_tokens=4)
    items = await asyncio.wait_for(
        collect(router.generate(req, Context())), timeout=10
    )
    toks = [t for i in items for t in i.get("token_ids", [])]
    assert toks == _reference_run(req.token_ids, 4)
    final = items[-1]
    assert final["finish_reason"] == "length"
    assert final["completion_tokens"] == 4
    assert final["prompt_tokens"] == len(req.token_ids)


async def test_death_after_delivered_finish_does_not_resume():
    """The finish chunk reached the client, then the transport died
    before the stream's clean end: the answer is complete — no resume,
    no extra tokens, no duplicate final, no abort."""

    class FinishThenDie(FakeWorker):
        async def stream(self, request):
            async for item in super().stream(request):
                yield item
            raise ConnectionLostError("died after the finish chunk")

    survivor = FakeWorker()
    client = FakeClient({1: survivor, 2: FinishThenDie()})
    router = _router(client)
    ok0 = _val(MIDSTREAM_RESUMES, "ok")
    aborts0 = MIDSTREAM_ABORTS.labels().value
    req = _req(max_tokens=4)
    items = await asyncio.wait_for(
        collect(router.generate(req, Context())), timeout=10
    )
    toks = [t for i in items for t in i.get("token_ids", [])]
    assert toks == _reference_run(req.token_ids, 4)
    # exactly one final, no tokens after it, and the survivor never ran
    finals = [i for i in items if i.get("finish_reason")]
    assert len(finals) == 1 and items[-1] is finals[0]
    assert survivor.requests == []
    assert _val(MIDSTREAM_RESUMES, "ok") == ok0
    assert MIDSTREAM_ABORTS.labels().value == aborts0


async def test_transient_dial_failure_does_not_bar_recovered_worker():
    """A resume dial that fails transiently excludes the worker for the
    next pick, but exclusion must not become a permanent bar: when it
    empties the candidate set, _pick falls back to the full live set
    (mirroring KvRouter.schedule) and the recovered worker completes
    the stream."""

    class FlakyClient(FakeClient):
        def __init__(self, workers, flaky, failures):
            super().__init__(workers)
            self.flaky = flaky
            self.failures = failures

        async def generate_direct(self, instance_id, request, context=None):
            if instance_id == self.flaky and self.failures > 0:
                self.failures -= 1
                raise asyncio.TimeoutError("transient dial timeout")
            return await super().generate_direct(
                instance_id, request, context
            )

    # worker 2 dies mid-stream and stays dead (dial always refused via
    # its absence after death); worker 1 refuses ONE resume dial then
    # recovers
    dying = FakeWorker(die_after=3)

    class DyingGoneClient(FlakyClient):
        async def generate_direct(self, instance_id, request, context=None):
            if instance_id == 2 and dying.requests:
                raise OSError("connection refused")  # stays dead
            return await super().generate_direct(
                instance_id, request, context
            )

    client = DyingGoneClient({1: FakeWorker(), 2: dying}, flaky=1, failures=1)
    router = _router(
        client, migration=MigrationConfig(max_resumes=4, instance_wait_s=0.2)
    )
    req = _req(max_tokens=8)
    items = await asyncio.wait_for(
        collect(router.generate(req, Context())), timeout=15
    )
    toks = [t for i in items for t in i.get("token_ids", [])]
    assert toks == _reference_run(req.token_ids, 8)
    assert items[-1]["finish_reason"] == "length"


async def test_dial_failure_excludes_the_instance():
    """A picked instance that refuses the dial is excluded from the
    retry, so a selector that deterministically prefers it cannot burn
    the whole attempt budget on one corpse (the PR-5 exclusion,
    preserved through DialFailedError)."""

    class RefusingClient(FakeClient):
        def __init__(self, workers, refuse):
            super().__init__(workers)
            self.refuse = set(refuse)
            self.dials = []

        async def generate_direct(self, instance_id, request, context=None):
            self.dials.append(instance_id)
            if instance_id in self.refuse:
                raise OSError("connection refused")
            return await super().generate_direct(
                instance_id, request, context
            )

    survivor = FakeWorker()
    client = RefusingClient({1: survivor, 2: FakeWorker()}, refuse={2})
    router = _router(client)  # round-robin dials the refusing 2 first
    req = _req(max_tokens=4)
    items = await asyncio.wait_for(
        collect(router.generate(req, Context())), timeout=10
    )
    toks = [t for i in items for t in i.get("token_ids", [])]
    assert toks == _reference_run(req.token_ids, 4)
    # the corpse was dialed exactly once, then excluded
    assert client.dials == [2, 1]


# ---------------------------------------------------------------------------
# the abort fallback
# ---------------------------------------------------------------------------


async def test_opt_out_keeps_clean_abort():
    dying = FakeWorker(die_after=3)
    client = FakeClient({1: FakeWorker(), 2: dying})
    router = _router(client)
    aborts0 = MIDSTREAM_ABORTS.labels().value
    req = _req(max_tokens=8, migration=False)
    got = []
    with pytest.raises(WorkerStreamLostError):
        async for item in router.generate(req, Context()):
            got.append(item)
    assert len(got) == 3  # delivered tokens stand; no resume happened
    assert MIDSTREAM_ABORTS.labels().value == aborts0 + 1


async def test_penalty_requests_are_not_migratable():
    req = _req(max_tokens=8)
    req.sampling.frequency_penalty = 0.5
    assert not resumable(req)
    dying = FakeWorker(die_after=2)
    client = FakeClient({1: FakeWorker(), 2: dying})
    router = _router(client)
    with pytest.raises(WorkerStreamLostError):
        await collect(router.generate(req, Context()))


async def test_exhausted_resumes_fall_back_to_abort():
    """Every candidate dies pre-splice: bounded attempts, failed
    counter, then the PR-5 abort."""
    client = FakeClient({
        1: FakeWorker(die_after=0),
        2: FakeWorker(die_after=3),
        3: FakeWorker(die_after=0),
        4: FakeWorker(die_after=0),
    })
    router = _router(
        client,
        migration=MigrationConfig(max_resumes=3, instance_wait_s=0.2),
    )
    failed0 = _val(MIDSTREAM_RESUMES, "failed")
    aborts0 = MIDSTREAM_ABORTS.labels().value
    with pytest.raises(WorkerStreamLostError):
        await asyncio.wait_for(
            collect(router.generate(_req(max_tokens=8), Context())),
            timeout=20,
        )
    assert _val(MIDSTREAM_RESUMES, "failed") == failed0 + 3
    assert MIDSTREAM_ABORTS.labels().value == aborts0 + 1


async def test_no_survivors_aborts_within_resume_window():
    """The lone worker dies mid-stream: resume attempts hit the bounded
    instance wait (NOT the 300 s discovery budget) and fall back to the
    abort promptly."""
    dying = FakeWorker(die_after=2)

    class LonelyClient(FakeClient):
        async def generate_direct(self, instance_id, request, context=None):
            stream = await super().generate_direct(
                instance_id, request, context
            )
            # after the death the worker is gone entirely
            async def wrap():
                try:
                    async for item in stream:
                        yield item
                except ConnectionLostError:
                    self.workers.clear()
                    raise

            return wrap()

    client = LonelyClient({1: dying})
    router = _router(
        client, migration=MigrationConfig(max_resumes=2, instance_wait_s=0.2)
    )
    with pytest.raises(WorkerStreamLostError):
        await asyncio.wait_for(
            collect(router.generate(_req(), Context())), timeout=10
        )


# ---------------------------------------------------------------------------
# admission bypass
# ---------------------------------------------------------------------------


def test_admission_resume_flag_never_sheds():
    ctl = AdmissionController(AdmissionConfig(), load_fn=lambda: None)
    ctl.force_shed = True
    ctl._probes.take(ctl.config.probe_burst)  # drain the probe trickle
    assert ctl.check() is not None  # fresh requests shed
    assert ctl.check(resume=True) is None  # resumes always admitted
    assert ctl.resumed_total == 1


async def test_saturated_frontend_still_completes_migrated_stream():
    """ISSUE-14 satellite: with admission shedding every fresh request
    (force_shed, probe bucket drained), a stream that was admitted
    before saturation still migrates and completes."""
    ctl = AdmissionController(AdmissionConfig(), load_fn=lambda: None)
    # round-robin picks index 1 of the sorted ids first: the dying
    # worker sits at id 2 so the first dispatch lands on it
    dying, survivor = FakeWorker(die_after=3), FakeWorker()
    client = FakeClient({1: survivor, 2: dying})
    router = _router(client, admission=ctl)
    req = _req(max_tokens=8)
    stream = router.generate(req, Context())
    items = [await stream.__anext__() for _ in range(2)]
    # saturation arrives mid-stream
    ctl.force_shed = True
    ctl._probes.take(ctl.config.probe_burst)
    assert ctl.check() is not None  # fresh traffic 429s
    async for item in stream:
        items.append(item)
    toks = [t for i in items for t in i.get("token_ids", [])]
    assert toks == _reference_run(req.token_ids, 8)
    assert items[-1]["finish_reason"] == "length"
    assert ctl.resumed_total >= 1


# ---------------------------------------------------------------------------
# the router.resume fault point (double fault)
# ---------------------------------------------------------------------------


async def test_fault_point_kills_first_resume_then_recovers():
    # round-robin picks index 1 of the sorted ids first: the dying
    # worker sits at id 2 so the first dispatch lands on it
    dying, survivor = FakeWorker(die_after=3), FakeWorker()
    client = FakeClient({1: survivor, 2: dying})
    router = _router(client)
    ok0 = _val(MIDSTREAM_RESUMES, "ok")
    failed0 = _val(MIDSTREAM_RESUMES, "failed")
    faults.activate(faults.parse_plan("seed=3;router.resume:error@max=1"))
    try:
        req = _req(max_tokens=8)
        items = await asyncio.wait_for(
            collect(router.generate(req, Context())), timeout=10
        )
    finally:
        faults.deactivate()
    toks = [t for i in items for t in i.get("token_ids", [])]
    assert toks == _reference_run(req.token_ids, 8)
    assert _val(MIDSTREAM_RESUMES, "failed") == failed0 + 1
    assert _val(MIDSTREAM_RESUMES, "ok") == ok0 + 1


# ---------------------------------------------------------------------------
# KV-routed migration: cache-hot resume placement
# ---------------------------------------------------------------------------


def test_kv_scheduler_resume_boost_prefers_cache_hot():
    """schedule(resume=True) doubles the overlap term the selector
    sees (crossing load gradients a fresh request would respect) while
    the decision still reports the TRUE overlap, and the boundary case
    — a cache-hot worker maximally loaded vs an idle cold one — flips
    from a tie to a deterministic cache-hot pick."""
    from dynamo_tpu.kv_router.indexer import KvIndexer
    from dynamo_tpu.kv_router.protocols import (
        ForwardPassMetrics,
        KvCacheEvent,
        RouterEvent,
    )
    from dynamo_tpu.kv_router.scheduler import KvMetricsAggregator, KvScheduler
    from dynamo_tpu.tokens import hash_sequence

    indexer = KvIndexer(block_size=4)
    agg = KvMetricsAggregator()
    tokens = list(range(4))  # one block
    _, hashes = hash_sequence(tokens, 4)
    indexer.apply(RouterEvent(
        worker_id=1, event_id=1,
        event=KvCacheEvent(op="stored", block_hashes=hashes,
                           token_block_size=4),
    ))
    # worker 1: holds the prefix, but KV-full with the deepest queue
    # (logit 2*1 - 1.0 - 1.0 = 0); worker 2: idle and cold (logit 0) —
    # a dead tie for a fresh request
    agg.update(ForwardPassMetrics(
        worker_id=1, gpu_cache_usage_perc=1.0, num_requests_waiting=4,
    ))
    agg.update(ForwardPassMetrics(
        worker_id=2, gpu_cache_usage_perc=0.0, num_requests_waiting=0,
    ))
    seen = []

    def capture(overlaps, metrics, candidates):
        seen.append(dict(overlaps.scores))
        from dynamo_tpu.kv_router.scheduler import default_selector

        return default_selector(overlaps, metrics, candidates)

    sched = KvScheduler(indexer, agg, selector=capture)
    sched.inflight_ttl_s = 0.0  # isolate the overlap term
    resume = sched.schedule(tokens, [1, 2], resume=True)
    # the boosted overlap breaks the tie deterministically toward the
    # cache-hot worker (2*2*1 - 2.0 = 2 > 0)
    assert resume.worker_id == 1
    assert seen[0] == {1: 1 * sched.resume_overlap_boost}
    # the decision reports the TRUE overlap, not the boosted score
    assert resume.overlap_blocks == 1
    # a fresh request's selector sees the raw (unboosted) overlap —
    # the dead-tie stands and either worker is a legitimate pick
    fresh = sched.schedule(tokens, [1, 2])
    assert seen[1] == {1: 1}
    assert fresh.worker_id in (1, 2)


async def test_kv_push_router_migrates_with_resume_scheduling():
    """KvPushRouter end to end over a stub KvRouter: the resume is
    scheduled with resume=True and the splice is exact."""
    from dynamo_tpu.kv_router.router import KvPushRouter
    from dynamo_tpu.kv_router.scheduler import SchedulingDecision

    # the stub scheduler picks the lowest non-excluded id: the dying
    # worker sits at id 1 so the first dispatch lands on it
    dying, survivor = FakeWorker(die_after=3), FakeWorker()
    client = FakeClient({1: dying, 2: survivor})
    calls = []
    released = []

    class StubScheduler:
        def note_done(self, wid, token=None):
            released.append((wid, token))

    class StubRouter:
        def __init__(self):
            self.client = client
            self.scheduler = StubScheduler()

        def schedule(self, token_ids, exclude=None, resume=False):
            calls.append((len(token_ids), set(exclude or ()), resume))
            wid = min(w for w in client.instance_ids()
                      if w not in (exclude or ()))
            return SchedulingDecision(
                worker_id=wid, overlap_blocks=0, total_blocks=1,
                dispatch_token=float(len(calls)),
            )

    router = KvPushRouter(
        StubRouter(), migration=MigrationConfig(instance_wait_s=0.5)
    )
    req = _req(max_tokens=8)
    items = await asyncio.wait_for(
        collect(router.generate(req, Context())), timeout=10
    )
    toks = [t for i in items for t in i.get("token_ids", [])]
    assert toks == _reference_run(req.token_ids, 8)
    # first dispatch fresh, second a resume with the dead worker
    # excluded and the token_ids extended by the delivered tokens
    assert calls[0] == (len(req.token_ids), set(), False)
    assert calls[1] == (len(req.token_ids) + 3, {1}, True)
    # every segment released its in-flight scheduling charge
    assert [w for w, _ in released] == [1, 2]


# ---------------------------------------------------------------------------
# StreamProgress units
# ---------------------------------------------------------------------------


def test_resume_request_composes_from_the_original():
    req = _req(prompt=[5, 6], max_tokens=10)
    req.stop.min_tokens = 4
    p = StreamProgress(req)
    p.note({"token_ids": [7, 8], "cum_log_probs": -1.0})
    r1 = p.resume_request()
    assert r1.token_ids == [5, 6, 7, 8]
    assert r1.stop.max_tokens == 8
    assert r1.stop.min_tokens == 2
    assert r1.resume_offset == 2
    # a later migration still builds from the ORIGINAL request
    p.note({"token_ids": [9], "cum_log_probs": -0.25})
    r2 = p.resume_request()
    assert r2.token_ids == [5, 6, 7, 8, 9]
    assert r2.stop.max_tokens == 7
    assert r2.resume_offset == 3
    # continuation items are re-anchored
    item = p.note({"token_ids": [10], "cum_log_probs": -0.5})
    assert item["cum_log_probs"] == pytest.approx(-1.75)


def test_resumable_shapes():
    assert resumable(_req())
    assert not resumable({"x": 1})
    assert not resumable(_req(migration=False))
    assert resumable(
        {"token_ids": [1, 2], "sampling": {"temperature": 0.7}}
    )
    assert not resumable(
        {"token_ids": [1, 2], "sampling": {"presence_penalty": 1.0}}
    )


# ---------------------------------------------------------------------------
# the engine RNG contract: resume_offset continues the sample stream
# ---------------------------------------------------------------------------


async def _engine_tokens(engine, req):
    out = []
    async for item in engine.as_async_engine().generate(req, Context()):
        out.extend(item.token_ids)
    return out


@pytest.mark.parametrize("seed", [11, None])
async def test_engine_resume_offset_continues_sampled_stream(seed):
    """The acceptance contract behind bit-identical migration: a resume
    whose prompt carries the delivered tokens and whose resume_offset
    equals their count regenerates EXACTLY the tokens the original
    request would have produced — for an explicit seed AND for the
    request-id-hashed default stream."""
    import os

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine

    model_dir = os.path.join(
        os.path.dirname(__file__), "data", "tiny_llama_model"
    )
    engine = await JaxEngine.launch(EngineConfig(
        model_path=model_dir, model_name="tiny", random_weights=True,
        num_blocks=128, block_size=8, max_batch_size=8,
        prefill_chunk_size=32, max_model_len=256,
    ))
    try:
        prompt = list(range(1, 24))
        sampling = SamplingOptions(temperature=0.9, top_k=20, seed=seed)
        full = await _engine_tokens(engine, PreprocessedRequest(
            request_id="resume-contract", token_ids=prompt,
            sampling=sampling.model_copy(),
            stop=StopConditions(max_tokens=10, ignore_eos=True),
        ))
        assert len(full) == 10
        # resume from the 4-token splice point: same request id, prompt
        # extended by the delivered tokens, offset = delivered count
        cont = await _engine_tokens(engine, PreprocessedRequest(
            request_id="resume-contract", token_ids=prompt + full[:4],
            sampling=sampling.model_copy(),
            stop=StopConditions(max_tokens=6, ignore_eos=True),
            resume_offset=4,
        ))
        assert cont == full[4:]
        # and WITHOUT the offset the streams diverge (the contract is
        # doing real work) — greedy would mask this, sampling cannot
        cont_no_off = await _engine_tokens(engine, PreprocessedRequest(
            request_id="resume-contract", token_ids=prompt + full[:4],
            sampling=sampling.model_copy(),
            stop=StopConditions(max_tokens=6, ignore_eos=True),
        ))
        assert cont_no_off != full[4:]
    finally:
        await engine.shutdown()
