"""Mixed prefill+decode batching: a straggler's prefill rides the fused
decode window's dispatch instead of stalling decode for a dedicated
full-weight pass (reference: vLLM's mixed continuous-batching scheduler,
container/deps/vllm/vllm_v0.8.4-dynamo-kv-disagg-patch.patch :535,
docs/architecture.md:55-68).

Correctness bar: greedy outputs must be IDENTICAL whether a request's
prefill ran mixed or dedicated (paged attention only ever reads a
sequence's own pages)."""

import asyncio
import os

import numpy as np

from dynamo_tpu.engine.allocator import BlockAllocator
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.scheduler import Scheduler, SeqState, Sequence
from dynamo_tpu.protocols.common import (
    FinishReason,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.tokens import TokenBlockSequence

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")


def _mk_seq(tokens, block_size=4, max_tokens=8, request_id="r"):
    return Sequence(
        request=PreprocessedRequest(
            request_id=request_id,
            token_ids=list(tokens),
            stop=StopConditions(max_tokens=max_tokens),
        ),
        tokens=TokenBlockSequence(list(tokens), block_size=block_size),
    )


# ---------------------------------------------------------------------------
# Scheduler planning
# ---------------------------------------------------------------------------


def test_scheduler_emits_mixed_plan():
    alloc = BlockAllocator(256, 4)
    sched = Scheduler(alloc, 4, max_batch_size=8, prefill_chunk_size=64)
    sched.mixed_prefill_rows = 4
    sched.mixed_prefill_len = 32
    # get one sequence decoding
    a = _mk_seq(list(range(10)), request_id="a")
    sched.add_request(a)
    plan = sched.plan()
    assert plan.kind == "prefill"
    sched.complete_prefill_chunk(plan.prefill)
    assert sched.num_running == 1
    # a straggler arrives while decode has work -> mixed plan with both
    b = _mk_seq(list(range(5, 25)), request_id="b")
    sched.add_request(b)
    plan = sched.plan()
    assert plan.kind == "mixed"
    assert [w.seq.request_id for w in plan.prefill_batch] == ["b"]
    assert [s.request_id for s in plan.decode_seqs] == ["a"]
    # chunk capped to the rectangle length
    assert len(plan.prefill.tokens) <= 32


def test_scheduler_mixed_backlog_falls_back_to_dedicated_prefill():
    alloc = BlockAllocator(1024, 4)
    sched = Scheduler(alloc, 4, max_batch_size=16, prefill_chunk_size=512)
    sched.mixed_prefill_rows = 2
    sched.mixed_prefill_len = 16  # tiny rectangle: capacity 32, thresh 64
    a = _mk_seq(list(range(8)), request_id="a")
    sched.add_request(a)
    sched.complete_prefill_chunk(sched.plan().prefill)
    # a long prompt exceeding 2x rectangle capacity -> dedicated prefill
    b = _mk_seq(list(range(200)), request_id="b")
    sched.add_request(b)
    plan = sched.plan()
    assert plan.kind == "prefill"
    assert len(plan.prefill.tokens) > 16  # full chunking, not the rect


def test_scheduler_wide_rect_at_low_occupancy():
    """A long prompt with few decoders swaps the mixed rectangle for
    the wide variant (same token budget, fewer rows) so it stops
    trickling at mixed_prefill_len per window; high decode occupancy
    keeps the narrow rectangle's extra rows."""
    alloc = BlockAllocator(2048, 4)
    sched = Scheduler(alloc, 4, max_batch_size=16, prefill_chunk_size=512)
    sched.mixed_prefill_rows = 4
    sched.mixed_prefill_len = 32
    sched.mixed_prefill_wide_rows = 1
    sched.mixed_prefill_wide_len = 128
    sched.mixed_wide_max_running = 4
    a = _mk_seq(list(range(8)), request_id="a")
    sched.add_request(a)
    sched.complete_prefill_chunk(sched.plan().prefill)
    # long prompt (backlog > narrow len), 1 decoder -> wide rect
    b = _mk_seq(list(range(200)), request_id="b")
    sched.add_request(b)
    plan = sched.plan()
    assert plan.kind == "mixed"
    assert plan.rect == (1, 128)
    assert len(plan.prefill.tokens) == 128  # wide chunk, not 32
    # drain b's prefill; then raise decode occupancy past the ceiling
    while True:
        p = sched.plan()
        if p.kind != "mixed" or not p.prefill_batch:
            break
        for w in p.prefill_batch:
            sched.complete_prefill_chunk(w)
    for i in range(5):
        s = _mk_seq(list(range(6)), request_id=f"d{i}")
        sched.add_request(s)
        p = sched.plan()
        for w in p.prefill_batch:
            sched.complete_prefill_chunk(w)
    assert sched.num_running >= 5
    c = _mk_seq(list(range(200)), request_id="c")
    sched.add_request(c)
    plan = sched.plan()
    assert plan.kind == "mixed"
    assert plan.rect == (4, 32)  # narrow: occupancy above the ceiling


def test_scheduler_mixed_disabled_keeps_either_or():
    alloc = BlockAllocator(256, 4)
    sched = Scheduler(alloc, 4, max_batch_size=8, prefill_chunk_size=64)
    assert sched.mixed_prefill_rows == 0  # default off at scheduler level
    a = _mk_seq(list(range(10)), request_id="a")
    sched.add_request(a)
    sched.complete_prefill_chunk(sched.plan().prefill)
    b = _mk_seq(list(range(5, 25)), request_id="b")
    sched.add_request(b)
    assert sched.plan().kind == "prefill"


def test_cohort_takes_dedicated_prefill_not_trickle():
    """A cohort (more prompts than rectangle rows, whole backlog within
    one prefill budget) takes a dedicated batched step even when decode
    occupancy is high — trickling it 'rows' per window staggers the
    population into partial-width waves (measured: B=64 closed batch
    924 vs 2181 tok/s)."""
    alloc = BlockAllocator(4096, 4)
    sched = Scheduler(
        alloc, 4, max_batch_size=64, prefill_chunk_size=64,
        max_prefill_tokens=512,
    )
    sched.mixed_prefill_rows = 4
    sched.mixed_prefill_len = 32
    for i in range(16):
        s = _mk_seq(list(range(8)), request_id=f"r{i}")
        sched.add_request(s)
        p = sched.plan()
        for w in p.prefill_batch:
            sched.complete_prefill_chunk(w)
    assert sched.num_running == 16
    # cohort: 12 prompts x 20 tokens = 240 <= 512 budget, count > rows.
    # CRITICAL test geometry: 240 is also <= the mixed-gate bound
    # 2*rows*rlen (256) and running(16) >= prefilling(12), so the
    # PRE-cohort gate trickled exactly this through the 4-row
    # rectangle — the assertion below fails without the cohort gate.
    for i in range(12):
        sched.add_request(
            _mk_seq([200 + i] + list(range(300, 319)), request_id=f"c{i}")
        )
    plan = sched.plan()
    assert plan.kind == "prefill", "cohort must take the dedicated step"
    assert len(plan.prefill_batch) > sched.mixed_prefill_rows
    # a straggler (single prompt) still rides the mixed rectangle
    while sched.prefilling:
        p = sched.plan()
        if not p.prefill_batch:
            break
        for w in p.prefill_batch:
            sched.complete_prefill_chunk(w)
    sched.add_request(_mk_seq(list(range(400, 420)), request_id="s"))
    assert sched.plan().kind == "mixed"


def test_admission_reserves_population_growth():
    """Admission must leave the blocks the RUNNING population still
    needs to finish: without the reserve, a freed block is instantly
    eaten by the next waiting prompt and decode growth preempts a
    running sequence — a recompute cascade under closed-loop pressure
    (observed as the ISL-3000 c=64 collapse)."""
    alloc = BlockAllocator(16, 4)
    sched = Scheduler(alloc, 4, max_batch_size=8, prefill_chunk_size=64)
    sched.decode_lookahead = 4
    # A: 20-token prompt (5 blocks), will generate 12 more -> needs 8
    # blocks total, i.e. growth reserve 3 once prefilled
    a = _mk_seq(list(range(20)), max_tokens=12, request_id="a")
    sched.add_request(a)
    plan = sched.plan()
    assert plan.kind == "prefill"
    for w in plan.prefill_batch:
        sched.complete_prefill_chunk(w)
    assert sched.num_running == 1
    # B: 36-token prompt (9 blocks), DISTINCT from A (a shared prefix
    # would be charged only for its fresh tail). free = 11, but A's
    # growth needs 3 -> 9 + 3 > 11: B must WAIT (no reserve would
    # admit it and later preempt A)
    b = _mk_seq(list(range(100, 136)), max_tokens=4, request_id="b")
    sched.add_request(b)
    plan = sched.plan()
    assert plan.kind == "decode"  # B not admitted
    assert len(sched.waiting) == 1
    # A decodes to completion without ever being preempted
    while a.state == SeqState.RUNNING:
        sched.plan()
        sched.append_token(a, 1)
        r = sched.should_finish(a)
        if r is not None:
            sched.finish(a, r)
    assert sched.preemptions == 0
    # A's blocks freed -> B admits now
    plan = sched.plan()
    assert plan.kind == "prefill"
    assert plan.prefill.seq.request_id == "b"


# ---------------------------------------------------------------------------
# Engine end-to-end
# ---------------------------------------------------------------------------


def _engine_config(**kw) -> EngineConfig:
    defaults = dict(
        model_path=MODEL_DIR,
        model_name="tiny",
        random_weights=True,
        num_blocks=128,
        block_size=8,
        max_batch_size=8,
        prefill_chunk_size=32,
        max_model_len=256,
        decode_steps=4,
        mixed_prefill_rows=2,
        mixed_prefill_len=16,
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


async def _generate(engine, prompt_ids, max_tokens=8, request_id="r"):
    adapter = engine.as_async_engine()
    req = PreprocessedRequest(
        request_id=request_id,
        token_ids=list(prompt_ids),
        sampling=SamplingOptions(use_greedy=True),
        stop=StopConditions(max_tokens=max_tokens),
    )
    out = []
    final = None
    async for item in adapter.generate(req, Context()):
        out.extend(item.token_ids)
        if item.is_final:
            final = item
    return out, final


async def test_mixed_engine_straggler_rides_mixed_window():
    """A straggler arriving while another request decodes must ride a
    mixed window (not stall decode with a dedicated pass), and greedy
    outputs must match a mixed-off engine run of the same prompts."""
    from dynamo_tpu.engine.engine import JaxEngine

    prompts = [list(range(1, 14 + 3 * i)) for i in range(3)]

    async def run(mixed: bool):
        engine = await JaxEngine.launch(
            _engine_config(mixed_prefill_rows=2 if mixed else 0)
        )
        n_mixed = 0
        if mixed:
            orig = engine._dispatch_mixed

            def counting(*a, **kw):
                nonlocal n_mixed
                n_mixed += 1
                return orig(*a, **kw)

            engine._dispatch_mixed = counting
        try:
            adapter = engine.as_async_engine()

            async def consume(req, out: list):
                async for item in adapter.generate(req, Context()):
                    out.extend(item.token_ids)

            # A decodes a LONG generation...
            a_out: list = []
            a_req = PreprocessedRequest(
                request_id="a", token_ids=prompts[0],
                sampling=SamplingOptions(use_greedy=True),
                stop=StopConditions(max_tokens=120),
            )
            a_task = asyncio.create_task(consume(a_req, a_out))
            while len(a_out) < 8:  # guaranteed mid-decode
                await asyncio.sleep(0.01)
            # ...when stragglers B and C arrive: their prefills must
            # ride the decode window's dispatch
            b = await _generate(engine, prompts[1], max_tokens=24,
                                request_id="b")
            c = await _generate(engine, prompts[2], max_tokens=24,
                                request_id="c")
            await a_task
            assert len(a_out) == 120
            return a_out, b[0], c[0], n_mixed
        finally:
            await engine.shutdown()

    a1, b1, c1, n_mixed = await run(True)
    a2, b2, c2, _ = await run(False)
    assert n_mixed > 0, "stragglers never took the mixed path"
    assert (a1, b1, c1) == (a2, b2, c2)


async def test_pipelined_mixed_chain_matches_dedicated():
    """Continuous staggered arrivals with long generations force CHAINS
    of pipelined mixed windows (prefill graduation chained on device);
    greedy outputs must still match the mixed-off engine exactly."""
    from dynamo_tpu.engine.engine import JaxEngine

    prompts = [list(range(1, 10 + 2 * i)) for i in range(6)]

    async def run(mixed: bool):
        engine = await JaxEngine.launch(
            _engine_config(
                mixed_prefill_rows=2 if mixed else 0, max_batch_size=8
            )
        )
        try:
            async def staggered(i: int):
                await asyncio.sleep(0.1 * i)
                return await _generate(
                    engine, prompts[i], max_tokens=24, request_id=f"pl{i}"
                )

            results = await asyncio.gather(*[staggered(i) for i in range(6)])
            for toks, fin in results:
                assert len(toks) == 24, fin
            return [r[0] for r in results]
        finally:
            await engine.shutdown()

    mixed_out = await run(True)
    dedicated_out = await run(False)
    assert mixed_out == dedicated_out


async def test_wide_rect_engine_matches_narrow_only():
    """A long prompt arriving while one request decodes takes the WIDE
    mixed rectangle (fewer windows to first token); greedy outputs must
    match an engine with the wide variant disabled. (Static shapes
    bucket the narrow len 16 up to 128, so the wide variant here must
    be 256 to differ.)"""
    from dynamo_tpu.engine.engine import JaxEngine

    long_prompt = list(np.random.RandomState(7).randint(1, 250, size=180))

    async def run(wide_len: int):
        # prefill_chunk_size must cover the wide len: the engine clamps
        # the wide rectangle to one chunk (longer would pad dead tokens)
        engine = await JaxEngine.launch(
            _engine_config(
                mixed_prefill_rows=2, mixed_prefill_len=16,
                mixed_prefill_wide_len=wide_len, num_blocks=256,
                prefill_chunk_size=256,
            )
        )
        wide_rects = 0
        orig = engine._dispatch_mixed

        def counting(*a, **kw):
            nonlocal wide_rects
            r = kw.get("rect")
            if r is not None and r[1] > engine.config.mixed_prefill_len:
                wide_rects += 1
            return orig(*a, **kw)

        engine._dispatch_mixed = counting
        try:
            adapter = engine.as_async_engine()
            a_out: list = []

            async def consume(req, out: list):
                async for item in adapter.generate(req, Context()):
                    out.extend(item.token_ids)

            a_req = PreprocessedRequest(
                request_id="a", token_ids=list(range(1, 12)),
                sampling=SamplingOptions(use_greedy=True),
                stop=StopConditions(max_tokens=80),
            )
            a_task = asyncio.create_task(consume(a_req, a_out))
            while len(a_out) < 4:
                await asyncio.sleep(0.01)
            b = await _generate(engine, long_prompt, max_tokens=16,
                                request_id="b")
            await a_task
            return a_out, b[0], wide_rects
        finally:
            await engine.shutdown()

    a1, b1, n_wide = await run(256)
    a2, b2, n_off = await run(0)
    assert n_wide > 0, "long prompt never took the wide rectangle"
    assert n_off == 0
    assert (a1, b1) == (a2, b2)


async def test_mixed_engine_long_prompt_and_pressure():
    """Long prompts (multi-chunk through the rectangle) and more
    requests than decode slots still finish correctly under mixed."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(
        _engine_config(max_batch_size=4, num_blocks=64)
    )
    try:
        first, _ = await _generate(
            engine, list(range(1, 10)), max_tokens=30, request_id="warm"
        )
        assert len(first) == 30
        # now pile on while nothing decodes vs while decoding
        tasks = [
            _generate(engine, list(range(1, 60)), max_tokens=6,
                      request_id=f"p{i}")
            for i in range(6)
        ]
        results = await asyncio.gather(*tasks)
        for toks, fin in results:
            assert len(toks) == 6
        # determinism: same long prompt solo matches its batched run
        solo, _ = await _generate(
            engine, list(range(1, 60)), max_tokens=6, request_id="solo"
        )
        assert solo == results[0][0]
    finally:
        await engine.shutdown()


def test_admission_gate_ignores_actively_shared_prefix():
    """The growth-reserve admission gate charges only what admission
    takes from the FREE pool: a prompt whose prefix blocks are pinned
    by running sequences admits even when free blocks < total prompt
    blocks (shared-prefix workloads must not stall on phantom need)."""
    alloc = BlockAllocator(16, 4)
    sched = Scheduler(alloc, 4, max_batch_size=8, prefill_chunk_size=64)
    sched.decode_lookahead = 1
    # A: 40-token prompt = 10 blocks, pinned and running
    a = _mk_seq(list(range(40)), max_tokens=2, request_id="a")
    sched.add_request(a)
    plan = sched.plan()
    while plan.kind == "prefill":
        for w in plan.prefill_batch:
            sched.complete_prefill_chunk(w)
        plan = sched.plan()
    assert sched.num_running == 1
    assert alloc.num_free < 10  # free pool cannot hold the prompt fresh
    # B: SAME 40-token prompt + 4 extra tokens = 11 blocks total, but
    # 10 are actively shared with A -> only ~1-2 fresh needed
    b = _mk_seq(list(range(40)) + [99, 98, 97, 96], max_tokens=2,
                request_id="b")
    sched.add_request(b)
    plan = sched.plan()
    assert plan.kind in ("prefill", "mixed")
    assert any(
        w.seq.request_id == "b"
        for w in plan.prefill_batch
    ), "shared-prefix prompt was not admitted"


def test_mid_decode_bucket_selection():
    """Wide-pad engines get a mid decode bucket: a half-occupancy
    population decodes in [pad/2]-padded windows instead of the full
    pad (measured ~11% at c=32 on a max_batch=64 engine)."""
    alloc = BlockAllocator(4096, 4)
    sched = Scheduler(alloc, 4, max_batch_size=64)
    sched.decode_batch_small = 4
    sched.decode_batch_mid = 32
    sched.decode_batch_pad = 64
    assert sched._decode_batch(3) == 4
    assert sched._decode_batch(4) == 4
    assert sched._decode_batch(5) == 32
    assert sched._decode_batch(32) == 32
    assert sched._decode_batch(33) == 64
    assert sched._decode_batch(64) == 64


async def test_mid_decode_bucket_override_semantics():
    """Explicit decode_batch_mid rounds DOWN to a real bucket strictly
    between the small bucket and the pad; 0 disables the auto mid; out
    of range values are ignored (never a no-op mid == pad or dead
    mid <= small)."""
    from dynamo_tpu.engine.engine import JaxEngine

    async def launch(**kw):
        return await JaxEngine.launch(_engine_config(
            max_batch_size=64, num_blocks=512, **kw
        ))

    if True:
        e = await launch(decode_batch_mid=48)
        try:
            assert e.scheduler.decode_batch_mid == 32  # rounds DOWN
        finally:
            await e.shutdown()
        e = await launch(decode_batch_mid=0)
        try:
            assert e.scheduler.decode_batch_mid is None  # 0 disables auto
        finally:
            await e.shutdown()
        e = await launch(decode_batch_mid=2)
        try:
            assert e.scheduler.decode_batch_mid is None  # below small
        finally:
            await e.shutdown()
        e = await launch()  # auto: pad 64 -> mid 32
        try:
            assert e.scheduler.decode_batch_mid == 32
        finally:
            await e.shutdown()

