"""Model deployment cards: publish/fetch, registration, ModelWatcher.

Mirrors the reference's model-card + discovery tests (reference:
lib/llm/tests/model_card.rs; http/service/discovery.rs ModelWatcher):
cards ship tokenizer artifacts through the object store, per-instance
ModelEntry keys ride the worker's lease, and the frontend's watcher adds/
removes models as instances come and go.
"""

from __future__ import annotations

import asyncio
import os

import pytest

from dynamo_tpu.model_card import (
    ModelDeploymentCard,
    fetch_card,
    list_entries,
    publish_card,
    register_llm,
    unregister_model,
)
from dynamo_tpu.store.memory import MemoryStore

DATA_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")


def test_card_from_local():
    card = ModelDeploymentCard.from_local(DATA_DIR, "tiny-llama")
    assert "tokenizer.json" in card.artifacts
    assert "config.json" in card.artifacts
    assert card.model_info.vocab_size is not None
    again = ModelDeploymentCard.from_json(card.to_json())
    assert again == card


async def test_publish_fetch_roundtrip(tmp_path):
    store = MemoryStore()
    card = ModelDeploymentCard.from_local(DATA_DIR, "tiny/llama-chat")
    assert await publish_card(store, card, DATA_DIR) is True
    # idempotent: second publisher sees the existing card
    assert await publish_card(store, card, DATA_DIR) is False

    fetched, local_dir = await fetch_card(
        store, "tiny/llama-chat", cache_dir=str(tmp_path)
    )
    assert fetched.service_name == "tiny/llama-chat"
    for fname in fetched.artifacts:
        with open(os.path.join(DATA_DIR, fname), "rb") as f:
            want = f.read()
        with open(os.path.join(local_dir, fname), "rb") as f:
            assert f.read() == want
    # the materialized dir is loadable by the tokenizer layer
    from dynamo_tpu.tokenizer import Tokenizer

    tok = Tokenizer.from_file(local_dir)
    assert tok.encode("hello") != []
    await store.close()


async def test_republish_updates_artifacts(tmp_path):
    """A re-registered model with changed artifacts must not serve stale
    cached files (content-addressed cache + last-writer-wins card)."""
    import shutil

    store = MemoryStore()
    model_dir = tmp_path / "model"
    shutil.copytree(DATA_DIR, model_dir)
    card1 = ModelDeploymentCard.from_local(str(model_dir), "m")
    assert await publish_card(store, card1, str(model_dir)) is True
    cache = str(tmp_path / "cache")
    _, dir1 = await fetch_card(store, "m", cache_dir=cache)

    # update an artifact and re-publish
    cfg_path = model_dir / "config.json"
    cfg = cfg_path.read_text().replace("{", '{"_updated": true, ', 1)
    cfg_path.write_text(cfg)
    card2 = ModelDeploymentCard.from_local(str(model_dir), "m")
    assert await publish_card(store, card2, str(model_dir)) is True
    assert card2.revision == card1.revision + 1

    fetched, dir2 = await fetch_card(store, "m", cache_dir=cache)
    assert dir2 != dir1  # fresh content-addressed dir
    assert "_updated" in open(os.path.join(dir2, "config.json")).read()
    # identical re-publish is a no-op
    card3 = ModelDeploymentCard.from_local(str(model_dir), "m")
    assert await publish_card(store, card3, str(model_dir)) is False
    await store.close()


async def test_register_list_unregister():
    store = MemoryStore()
    lease = await store.lease_grant(30.0)
    await register_llm(
        store, DATA_DIR, "tiny-llama", "dyn://dynamo.backend.generate", lease_id=lease
    )
    entries = await list_entries(store)
    assert len(entries) == 1
    assert entries[0].name == "tiny-llama"
    assert entries[0].endpoint == "dyn://dynamo.backend.generate"
    assert await unregister_model(store, "tiny-llama") >= 2
    assert await list_entries(store) == []
    assert await store.obj_list("mdc") == []
    await store.close()


async def test_entry_vanishes_with_lease():
    store = MemoryStore(lease_sweep_interval_s=0.05)
    lease = await store.lease_grant(0.1)
    await register_llm(
        store, DATA_DIR, "tiny-llama", "dyn://dynamo.backend.generate", lease_id=lease
    )
    assert len(await list_entries(store)) == 1
    await asyncio.sleep(0.4)  # lease expires, sweeper deletes the entry
    assert await list_entries(store) == []
    # the card itself persists (artifacts are content, not liveness)
    fetched, _ = await fetch_card(store, "tiny-llama", cache_dir="/tmp/dyn-mdc-test")
    assert fetched.service_name == "tiny-llama"
    await store.close()


async def test_model_watcher_end_to_end(tmp_path):
    """Worker registers -> frontend watcher serves the model -> worker dies
    -> model disappears. Exercises the full card fetch + pipeline build."""
    from dynamo_tpu.engines import EchoEngineCore
    from dynamo_tpu.http.discovery import ModelWatcher
    from dynamo_tpu.http.service import ModelManager
    from dynamo_tpu.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.runtime import DistributedRuntime
    from dynamo_tpu.store.server import StoreServer

    server = StoreServer(MemoryStore(lease_sweep_interval_s=0.1), port=0)
    await server.start()
    cfg = lambda: RuntimeConfig(  # noqa: E731
        store_port=server.port,
        worker_host="127.0.0.1",
        lease_ttl_s=1.0,
        lease_keepalive_s=0.2,
    )

    worker = await DistributedRuntime.create(config=cfg())
    ep = worker.namespace("dynamo").component("backend").endpoint("generate")
    await ep.serve(EchoEngineCore())
    await register_llm(
        worker.store,
        DATA_DIR,
        "tiny-llama",
        "dyn://dynamo.backend.generate",
        lease_id=worker.primary_lease_id,
    )

    frontend = await DistributedRuntime.create(config=cfg())
    manager = ModelManager()
    watcher = ModelWatcher(frontend, manager, cache_dir=str(tmp_path))
    await watcher.start()
    for _ in range(100):
        if "tiny-llama" in manager.chat_engines:
            break
        await asyncio.sleep(0.05)
    assert "tiny-llama" in manager.chat_engines
    assert "tiny-llama" in manager.completion_engines

    # drive a chat request through the discovered pipeline (pre -> backend
    # -> push router -> worker echo engine, across the wire)
    req = ChatCompletionRequest(
        model="tiny-llama",
        messages=[{"role": "user", "content": "hello world"}],
        max_tokens=4,
        stream=False,
    )
    stream = manager.chat_engines["tiny-llama"].generate(req, Context())
    chunks = [c async for c in stream]
    assert chunks, "no response from discovered pipeline"

    # worker death: lease revoked -> entry gone -> model removed
    await worker.shutdown()
    for _ in range(100):
        if "tiny-llama" not in manager.chat_engines:
            break
        await asyncio.sleep(0.05)
    assert "tiny-llama" not in manager.chat_engines

    await watcher.close()
    await frontend.shutdown()
    await server.stop()
