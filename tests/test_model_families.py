"""Model-family coverage: Qwen2 (QKV bias) and Mistral (sliding window)
on the shared Llama-architecture decoder (reference serves these through
its engine adapters; here they're native config variants)."""

import json
import math
import os

import numpy as np
import pytest

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import (
    forward,
    init_cache,
    init_params,
    paged_attention_reference,
    param_shapes,
)

TINY = dict(
    vocab_size=128, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    max_position_embeddings=128,
)


def _run_forward(cfg, params, tokens, bs=4):
    import jax.numpy as jnp

    T = len(tokens)
    k, v = init_cache(cfg, 16, bs, dtype=jnp.float32)
    n_blocks = -(-T // bs)
    tables = np.zeros((1, 8), np.int32)
    tables[0, :n_blocks] = np.arange(1, n_blocks + 1)
    slots = np.array([tables[0, j // bs] * bs + j % bs for j in range(T)],
                     np.int32)
    logits, _, _ = forward(
        cfg, params, k, v,
        np.asarray([tokens], np.int32),
        np.arange(T, dtype=np.int32)[None, :],
        slots, tables,
        np.asarray([T], np.int32),
        np.asarray([T - 1], np.int32),
        bs,
    )
    return np.asarray(logits[0])


def test_qwen2_config_infers_bias():
    cfg = ModelConfig.from_dict({"model_type": "qwen2", **TINY})
    assert cfg.attention_bias
    # explicit override wins
    cfg2 = ModelConfig.from_dict(
        {"model_type": "qwen2", "attention_bias": False, **TINY}
    )
    assert not cfg2.attention_bias
    # llama default: no bias
    assert not ModelConfig.from_dict({"model_type": "llama", **TINY}).attention_bias


def test_use_sliding_window_false_disables_swa():
    cfg = ModelConfig.from_dict(
        {"model_type": "qwen2", "sliding_window": 32768,
         "use_sliding_window": False, **TINY}
    )
    assert cfg.sliding_window is None
    cfg2 = ModelConfig.from_dict(
        {"model_type": "mistral", "sliding_window": 4096, **TINY}
    )
    assert cfg2.sliding_window == 4096


def test_qwen2_bias_params_affect_output():
    cfg = ModelConfig(model_type="qwen2", attention_bias=True, **TINY)
    assert {"bq", "bk", "bv"} <= set(param_shapes(cfg))
    params = init_params(cfg, seed=0)
    tokens = list(range(1, 9))
    base = _run_forward(cfg, params, tokens)
    # zeroing the biases must change the logits (they were random-init)
    zeroed = dict(params)
    for b in ("bq", "bk", "bv"):
        zeroed[b] = params[b] * 0
    assert not np.allclose(base, _run_forward(cfg, zeroed, tokens))


def test_mistral_sliding_window_masks_old_keys():
    """Windowed paged attention == dense attention restricted to the
    window, and != full attention once the context exceeds the window."""
    rng = np.random.default_rng(0)
    B, T, H, Hk, Dh, bs = 1, 12, 2, 2, 8, 4
    window = 5
    q = rng.standard_normal((B, T, H, Dh)).astype(np.float32)
    S = 16
    kc = rng.standard_normal((S, Hk, Dh)).astype(np.float32)
    vc = rng.standard_normal((S, Hk, Dh)).astype(np.float32)
    tables = np.arange(4, dtype=np.int32)[None, :]  # identity layout
    positions = np.arange(T, dtype=np.int32)[None, :]
    ctx = np.asarray([T], np.int32)

    def dense(window_):
        scale = 1.0 / math.sqrt(Dh)
        out = np.zeros((B, T, H, Dh), np.float32)
        for t in range(T):
            lo = 0 if window_ is None else max(0, t - window_ + 1)
            keys = kc[lo : t + 1]  # [s, Hk, Dh]
            vals = vc[lo : t + 1]
            for h in range(H):
                s = (q[0, t, h] @ keys[:, h % Hk].T) * scale
                p = np.exp(s - s.max())
                p /= p.sum()
                out[0, t, h] = p @ vals[:, h % Hk]
        return out

    got = np.asarray(
        paged_attention_reference(q, kc, vc, tables, positions, ctx, bs,
                                  sliding_window=window)
    )
    np.testing.assert_allclose(got, dense(window), rtol=2e-4, atol=2e-5)
    full = np.asarray(
        paged_attention_reference(q, kc, vc, tables, positions, ctx, bs)
    )
    assert not np.allclose(got, full)
    np.testing.assert_allclose(full, dense(None), rtol=2e-4, atol=2e-5)


def test_mistral_forward_runs_with_window():
    cfg = ModelConfig(model_type="mistral", sliding_window=4, **TINY)
    params = init_params(cfg, seed=0)
    logits = _run_forward(cfg, params, list(range(1, 11)))
    assert logits.shape == (cfg.vocab_size,)
    assert np.isfinite(logits).all()


def test_qwen2_checkpoint_loads_biases(tmp_path):
    """Round-trip a tiny qwen2-style safetensors checkpoint through the
    loader and check bias tensors land (and shift the output)."""
    from safetensors.numpy import save_file

    from dynamo_tpu.models.loader import load_params

    cfg = ModelConfig(model_type="qwen2", attention_bias=True, **TINY)
    rng = np.random.default_rng(1)
    D, H, Hk, Dh = (cfg.hidden_size, cfg.num_attention_heads,
                    cfg.num_key_value_heads, cfg.head_dim)
    F, V, L = cfg.intermediate_size, cfg.vocab_size, cfg.num_hidden_layers

    def t(*shape):
        return rng.standard_normal(shape).astype(np.float32) * 0.05

    tensors = {
        "model.embed_tokens.weight": t(V, D),
        "model.norm.weight": np.ones((D,), np.float32),
        "lm_head.weight": t(V, D),
    }
    for i in range(L):
        p = f"model.layers.{i}"
        tensors.update({
            f"{p}.input_layernorm.weight": np.ones((D,), np.float32),
            f"{p}.self_attn.q_proj.weight": t(H * Dh, D),
            f"{p}.self_attn.k_proj.weight": t(Hk * Dh, D),
            f"{p}.self_attn.v_proj.weight": t(Hk * Dh, D),
            f"{p}.self_attn.q_proj.bias": t(H * Dh),
            f"{p}.self_attn.k_proj.bias": t(Hk * Dh),
            f"{p}.self_attn.v_proj.bias": t(Hk * Dh),
            f"{p}.self_attn.o_proj.weight": t(D, H * Dh),
            f"{p}.post_attention_layernorm.weight": np.ones((D,), np.float32),
            f"{p}.mlp.gate_proj.weight": t(F, D),
            f"{p}.mlp.up_proj.weight": t(F, D),
            f"{p}.mlp.down_proj.weight": t(D, F),
        })
    save_file(tensors, str(tmp_path / "model.safetensors"))
    params = load_params(cfg, str(tmp_path))
    assert params["bq"].shape == (L, H * Dh)
    np.testing.assert_allclose(
        np.asarray(params["bk"][0], np.float32),
        tensors["model.layers.0.self_attn.k_proj.bias"],
        rtol=1e-2, atol=1e-2,  # bf16 storage
    )
    logits = _run_forward(cfg, params, [1, 2, 3, 4, 5])
    assert np.isfinite(logits).all()


def test_gemma_config_inference():
    cfg = ModelConfig.from_dict({
        "model_type": "gemma", "hidden_act": "gelu_pytorch_tanh", **TINY,
    })
    assert cfg.scale_embeddings and cfg.norm_bias_one
    assert cfg.hidden_act == "gelu" and cfg.tie_word_embeddings
    # llama untouched
    base = ModelConfig.from_dict({"model_type": "llama", **TINY})
    assert not base.scale_embeddings and not base.norm_bias_one
    assert base.hidden_act == "silu"


def test_gemma_semantics_change_outputs():
    """Each gemma-specific behavior (embed scaling, (1+w) norm, gelu)
    must actually alter the forward pass vs plain llama semantics."""
    base = ModelConfig(model_type="llama", **TINY)
    params = init_params(base, seed=0)
    tokens = list(range(1, 9))
    ref = _run_forward(base, params, tokens)
    for field in ("scale_embeddings", "norm_bias_one", "hidden_act"):
        kw = dict(TINY)
        cfg = ModelConfig(
            model_type="gemma-variant",
            scale_embeddings=(field == "scale_embeddings"),
            norm_bias_one=(field == "norm_bias_one"),
            hidden_act="gelu" if field == "hidden_act" else "silu",
            **kw,
        )
        out = _run_forward(cfg, params, tokens)
        assert not np.allclose(out, ref), f"{field} had no effect"


def test_gemma_rmsnorm_matches_hf_formula():
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import rmsnorm

    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 8)).astype(np.float32)
    w = rng.standard_normal(8).astype(np.float32) * 0.1  # stored as (w-1)
    got = np.asarray(rmsnorm(jnp.asarray(x), jnp.asarray(w), 1e-6,
                             bias_one=True))
    var = np.mean(x * x, axis=-1, keepdims=True)
    want = x / np.sqrt(var + 1e-6) * (1.0 + w)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_gemma_checkpoint_tied_embeddings(tmp_path):
    """Gemma ships no lm_head tensor: the loader must tie to embed.T,
    and the full forward must run."""
    from safetensors.numpy import save_file

    from dynamo_tpu.models.loader import load_params

    cfg = ModelConfig.from_dict({"model_type": "gemma", **TINY})
    rng = np.random.default_rng(2)
    D, H, Hk, Dh = (cfg.hidden_size, cfg.num_attention_heads,
                    cfg.num_key_value_heads, cfg.head_dim)
    F, V, L = cfg.intermediate_size, cfg.vocab_size, cfg.num_hidden_layers

    def t(*shape):
        return rng.standard_normal(shape).astype(np.float32) * 0.05

    tensors = {
        "model.embed_tokens.weight": t(V, D),
        "model.norm.weight": t(D),  # gemma stores (w-1): any values
    }
    for i in range(L):
        p = f"model.layers.{i}"
        tensors.update({
            f"{p}.input_layernorm.weight": t(D),
            f"{p}.self_attn.q_proj.weight": t(H * Dh, D),
            f"{p}.self_attn.k_proj.weight": t(Hk * Dh, D),
            f"{p}.self_attn.v_proj.weight": t(Hk * Dh, D),
            f"{p}.self_attn.o_proj.weight": t(D, H * Dh),
            f"{p}.post_attention_layernorm.weight": t(D),
            f"{p}.mlp.gate_proj.weight": t(F, D),
            f"{p}.mlp.up_proj.weight": t(F, D),
            f"{p}.mlp.down_proj.weight": t(D, F),
        })
    save_file(tensors, str(tmp_path / "model.safetensors"))
    params = load_params(cfg, str(tmp_path))
    np.testing.assert_allclose(
        np.asarray(params["lm_head"], np.float32),
        np.asarray(params["embed"], np.float32).T,
        rtol=1e-2, atol=1e-2,
    )
    logits = _run_forward(cfg, params, [1, 2, 3, 4])
    assert np.isfinite(logits).all()
