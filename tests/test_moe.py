"""Sparse-routed MoE: top-k grouped matmuls must match the dense
all-experts oracle exactly (same routing, same experts, same math) —
single device, ep-sharded mesh, and int8 experts.

Reference analogue: the role of expert parallelism in SURVEY §2.6 and
BASELINE config 4 (Mixtral-style EP decode); the dense formulation pays
E/k× the FLOPs, which is what the sparse path removes.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dynamo_tpu.utils.jaxtools import partial_auto_shard_map_supported

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import (
    _moe_mlp_dense,
    _moe_mlp_sparse,
    init_params,
    layer_param_names,
    set_attention_mesh,
)
from dynamo_tpu.parallel.mesh import MeshConfig, build_mesh

CFG = ModelConfig(
    vocab_size=512, hidden_size=64, intermediate_size=128,
    num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=4,
    max_position_embeddings=256, num_local_experts=4, num_experts_per_tok=2,
)


def _layer_params(cfg, quantize=False, mesh=None):
    if quantize:
        from dynamo_tpu.models.llama import param_specs
        from dynamo_tpu.models.quant import init_params_quantized

        params = init_params_quantized(
            cfg, seed=0, mesh=mesh, specs=param_specs(cfg) if mesh else None
        )
    else:
        params = init_params(cfg, seed=0, mesh=mesh)
    return {k: params[k][0] for k in layer_param_names(params)}


def _h(B=2, T=3, D=64, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((B, T, D)), jnp.bfloat16)


def _assert_close(a, b, atol=2e-2):
    np.testing.assert_allclose(
        np.asarray(a, np.float32), np.asarray(b, np.float32), atol=atol
    )


def test_sparse_matches_dense_single_device():
    lp = _layer_params(CFG)
    h = _h()
    dense = _moe_mlp_dense(CFG, lp, h)
    sparse = jax.jit(lambda l, x: _moe_mlp_sparse(CFG, l, x))(lp, h)
    _assert_close(dense, sparse)


def test_sparse_matches_dense_int8():
    lp = _layer_params(CFG, quantize=True)
    h = _h()
    dense = _moe_mlp_dense(CFG, lp, h)
    sparse = jax.jit(lambda l, x: _moe_mlp_sparse(CFG, l, x))(lp, h)
    _assert_close(dense, sparse)


@pytest.mark.skipif(
    not partial_auto_shard_map_supported(),
    reason="ep x tp sparse dispatch needs partial-auto shard_map; this jax's\n    experimental fallback lowers it to a PartitionId op XLA SPMD rejects\n    (UNIMPLEMENTED) — see ROADMAP open item 1",
)
@pytest.mark.parametrize("quantize", [False, True])
def test_sparse_ep_sharded_matches_dense(quantize):
    """Fully-manual ep×tp shard_map: every shard computes only its
    local experts' rows; the psum combine must reproduce the dense
    oracle."""
    mesh = build_mesh(MeshConfig(dp=2, ep=2, tp=2), jax.devices())
    lp_ref = _layer_params(CFG, quantize=quantize)
    h = _h()
    dense = _moe_mlp_dense(CFG, lp_ref, h)
    lp_sh = _layer_params(CFG, quantize=quantize, mesh=mesh)
    set_attention_mesh(mesh)
    try:
        with mesh:
            sparse = jax.jit(lambda l, x: _moe_mlp_sparse(CFG, l, x))(lp_sh, h)
    finally:
        set_attention_mesh(None)
    _assert_close(dense, sparse)


def test_sparse_routing_skews_to_selected_experts():
    """Zeroing one expert's weights changes outputs ONLY for tokens
    routed to it — evidence the grouped matmul actually routes rather
    than evaluating everything."""
    lp = dict(_layer_params(CFG))
    h = _h(B=4, T=8)
    from dynamo_tpu.models.llama import _moe_routing

    x = h.reshape(-1, CFG.hidden_size)
    _, topi = _moe_routing(CFG, lp, x)
    victim = 2
    routed = np.any(np.asarray(topi) == victim, axis=-1)
    assert routed.any() and not routed.all()  # interesting split

    base = np.asarray(
        jax.jit(lambda l, a: _moe_mlp_sparse(CFG, l, a))(lp, h), np.float32
    ).reshape(-1, CFG.hidden_size)
    lp2 = dict(lp)
    lp2["w_down"] = lp["w_down"].at[victim].set(0.0)
    out2 = np.asarray(
        jax.jit(lambda l, a: _moe_mlp_sparse(CFG, l, a))(lp2, h), np.float32
    ).reshape(-1, CFG.hidden_size)
    changed = np.abs(base - out2).max(axis=-1) > 1e-6
    np.testing.assert_array_equal(changed, routed)
