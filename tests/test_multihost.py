"""Multi-host engine bring-up: two real jax.distributed processes
(num_nodes=2), global tp=2 mesh spanning them, leader/follower step
protocol (reference: lib/llm/src/engines.rs:41-58 MultiNodeConfig;
design: dynamo_tpu/parallel/multihost.py)."""

import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# XLA capability probe (jax 0.4.37): the CPU backend cannot run
# computations spanning jax.distributed processes — the very first
# cross-process device_put trips multihost_utils.assert_equal's
# broadcast psum with "INVALID_ARGUMENT: Multiprocess computations
# aren't implemented on the CPU backend". Nothing downstream (lockstep
# steps, mirrored gathers) can work either, so the 2-process protocol
# tests skip on this toolchain instead of failing — ROADMAP item 1
# style, like jaxtools.partial_auto_shard_map_supported. On a real
# multi-chip backend (or a jaxlib with CPU collectives) they run.
_CPU_MULTIPROCESS_UNSUPPORTED = (
    "Multiprocess computations aren't implemented on the CPU backend"
)


def _run_pair(kv_dtype: str) -> dict:
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ, PYTHONPATH=REPO)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(rank), coord, kv_dtype],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for rank in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
        if any(_CPU_MULTIPROCESS_UNSUPPORTED in o for o in outs):
            pytest.skip(
                "XLA CPU backend lacks multiprocess computations "
                "(jax 0.4.37: cross-process device_put/psum raise "
                f"INVALID_ARGUMENT {_CPU_MULTIPROCESS_UNSUPPORTED!r}); "
                "the 2-process step protocol needs a backend with "
                "cross-host collectives — multi-chip tier, ROADMAP "
                "open item 1"
            )
        assert all(p.returncode == 0 for p in procs), (
            f"rank0:\n{outs[0][-3000:]}\nrank1:\n{outs[1][-3000:]}"
        )
        result_lines = [
            ln for ln in outs[0].splitlines() if ln.startswith("RESULT ")
        ]
        assert result_lines, outs[0][-3000:]
        result = json.loads(result_lines[0][len("RESULT "):])
        assert len(result["tokens"]) == 6
        # sharded G2 offload: shards were pumped into the per-process
        # pool, and the repeat prompt (onboarding through the mirrored
        # tier after device eviction) continues identically
        assert result["offloaded"] > 0, result
        assert result["repeat_matches"], result
        # disagg KV export/import over the cross-process-sharded cache:
        # whole blocks assembled on the leader, re-imported into the
        # lockstep shard pools (engine.{_export,_import}_blocks)
        assert result["export_ok"], result
        assert result["imported"] >= 4, result
        # multimodal embed-injection prefill over the step broadcast
        # (KIND_STEP_MM): the follower mirrored the mm step variant
        assert result["mm_ok"], result
        return result
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_two_process_engine_serves_request():
    _run_pair("float32")


def test_two_process_engine_int8_kv():
    """The same 2-process protocol over an int8 (values, scales) cache:
    quantized writes inside the lockstep steps, mirrored offload /
    export / import dequantizing to the bf16 wire at the block-copy
    boundary (mirror_gather/_scatter tuple dispatch) — the combination
    the 70B ladder budget assumes (docs/multihost.md)."""
    _run_pair("int8")


def test_hash_halves_survive_broadcast_canonicalization():
    """xxh3 hashes are 64-bit; jax canonicalizes uint64 -> uint32 on the
    broadcast path (x64 off), so they travel as two uint32 halves."""
    from dynamo_tpu.parallel.multihost import _join_hashes, _split_hashes

    hashes = [0, 1, 2**32 - 1, 2**32, 2**40 + 5, 2**63 + 17, 2**64 - 1]
    halves = _split_hashes(hashes)
    assert halves.dtype == __import__("numpy").uint32
    assert halves.shape == (2, len(hashes))
    assert _join_hashes(halves) == hashes
    # and the canonicalization that motivated this: with x64 disabled
    # (this repo's default), a uint64 round trip through jnp would NOT
    # have survived
    import jax
    import jax.numpy as jnp
    import numpy as np

    if not jax.config.jax_enable_x64:
        truncated = np.asarray(jnp.asarray(np.asarray([2**40 + 5], np.uint64)))
        assert int(truncated[0]) != 2**40 + 5  # the bug this guards against
