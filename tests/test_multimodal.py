"""Vision-language path: ViT tower, embedding wire format, multimodal
preprocessor splicing, and end-to-end engine injection (reference:
examples/multimodal encode-worker → LLM pipeline)."""

import base64
import io
import os

import numpy as np
import pytest

from dynamo_tpu.models.vision import (
    VisionConfig,
    encode_images,
    init_vision_params,
    patchify,
)
from dynamo_tpu.multimodal.embeds import pack_segments, unpack_segments
from dynamo_tpu.multimodal.preprocessor import (
    IMAGE_PLACEHOLDER,
    MultimodalPreprocessor,
    extract_image_urls,
)
from dynamo_tpu.multimodal.processor import ImageProcessor
from dynamo_tpu.protocols.openai import ChatCompletionRequest

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")

TINY_VIT = VisionConfig(
    image_size=28, patch_size=14, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, projection_dim=48,
)


def _png_data_url(size=28, color=(200, 30, 30)) -> str:
    from PIL import Image

    buf = io.BytesIO()
    Image.new("RGB", (size, size), color).save(buf, format="PNG")
    return "data:image/png;base64," + base64.b64encode(buf.getvalue()).decode()


def test_patchify_and_encode_shapes():
    cfg = TINY_VIT
    params = init_vision_params(cfg, seed=0)
    pixels = np.random.default_rng(0).standard_normal(
        (2, cfg.image_size, cfg.image_size, 3)
    ).astype(np.float32)
    patches = np.asarray(patchify(cfg, pixels))
    assert patches.shape == (2, cfg.num_patches, cfg.patch_dim)
    # patchify is a pure relayout: first patch == top-left tile
    np.testing.assert_array_equal(
        patches[0, 0], pixels[0, :14, :14, :].reshape(-1)
    )
    out = np.asarray(encode_images(cfg, params, pixels))
    assert out.shape == (2, cfg.num_patches, cfg.projection_dim)
    assert np.isfinite(out).all()
    # different images -> different embeddings
    assert not np.allclose(out[0], out[1])


def test_image_processor_data_url_and_policy(tmp_path):
    proc = ImageProcessor(image_size=28)
    arr = proc.load(_png_data_url())
    assert arr.shape == (28, 28, 3)
    with pytest.raises(ValueError, match="data: URL"):
        proc.load("data:image/png,notbase64")
    with pytest.raises(ValueError, match="remote image"):
        proc.load("http://example.com/x.png")
    # no image_root configured: API clients must not be able to make the
    # worker open arbitrary local files
    with pytest.raises(ValueError, match="image_root"):
        proc.load("/etc/passwd")
    with pytest.raises(ValueError, match="image_root"):
        proc.load("file:///etc/passwd")


def test_image_processor_image_root_containment(tmp_path):
    import base64 as b64

    head, _, payload = _png_data_url().partition(",")
    png = b64.b64decode(payload)
    (tmp_path / "ok.png").write_bytes(png)
    outside = tmp_path.parent / "outside.png"
    outside.write_bytes(png)
    (tmp_path / "link.png").symlink_to(outside)

    proc = ImageProcessor(image_size=28, image_root=str(tmp_path))
    # relative + absolute-in-root + file:// all resolve inside the root
    assert proc.load("ok.png").shape == (28, 28, 3)
    assert proc.load(str(tmp_path / "ok.png")).shape == (28, 28, 3)
    assert proc.load(f"file://{tmp_path}/ok.png").shape == (28, 28, 3)
    # traversal and symlink escapes are refused
    with pytest.raises(ValueError, match="escapes"):
        proc.load("../outside.png")
    with pytest.raises(ValueError, match="escapes"):
        proc.load("link.png")


def test_embeds_roundtrip_and_validation():
    segs = [(3, np.random.default_rng(0).standard_normal((4, 8)).astype(np.float32))]
    packed = pack_segments(segs)
    back = unpack_segments(packed)
    assert back[0][0] == 3
    np.testing.assert_array_equal(back[0][1], segs[0][1])
    bad = dict(packed[0], shape=[4, 8, 1])
    with pytest.raises(ValueError, match="2-D"):
        unpack_segments([bad])
    bad2 = dict(packed[0], dtype="int32")
    with pytest.raises(ValueError, match="float"):
        unpack_segments([bad2])
    bad3 = dict(packed[0], shape=[400, 8])
    with pytest.raises(ValueError, match="payload"):
        unpack_segments([bad3])


def _mm_preprocessor(tokens_per_image=4, D=16):
    from dynamo_tpu.preprocessor import PromptFormatter
    from dynamo_tpu.tokenizer import Tokenizer

    tok = Tokenizer.from_file(MODEL_DIR)
    formatter = PromptFormatter.from_model_dir(MODEL_DIR)
    calls = []

    def encode(urls):
        calls.append(urls)
        rng = np.random.default_rng(len(urls))
        return rng.standard_normal((len(urls), tokens_per_image, D)).astype(
            np.float32
        )

    pre = MultimodalPreprocessor(
        tok, formatter, encode=encode, image_token_id=0,
        tokens_per_image=tokens_per_image, model_name="vlm",
    )
    return pre, calls


def test_multimodal_preprocess_splices_placeholders():
    pre, calls = _mm_preprocessor()
    req = ChatCompletionRequest.model_validate({
        "model": "vlm",
        "messages": [{
            "role": "user",
            "content": [
                {"type": "text", "text": "describe "},
                {"type": "image_url", "image_url": {"url": _png_data_url()}},
                {"type": "text", "text": " briefly"},
            ],
        }],
    })
    assert len(extract_image_urls(req)) == 1
    out = pre.preprocess_chat(req)
    assert calls and len(calls[0]) == 1
    assert out.mm_embeds is not None and len(out.mm_embeds) == 1
    segs = unpack_segments(out.mm_embeds)
    offset, arr = segs[0]
    assert arr.shape == (4, 16)
    # the 4 placeholder tokens sit exactly at the recorded offset
    assert out.token_ids[offset : offset + 4] == [0, 0, 0, 0]
    # text-only requests fall back to the plain path
    plain = ChatCompletionRequest.model_validate({
        "model": "vlm",
        "messages": [{"role": "user", "content": "hi"}],
    })
    assert pre.preprocess_chat(plain).mm_embeds is None


async def test_mm_requests_do_not_poison_prefix_cache():
    """Same placeholder tokens + different images must NOT share prefix
    KV (block hashes are salted with embedding content), and malformed
    embeds fail only their own request."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    mc = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128,
    )
    cfg = EngineConfig(
        model_path="", model_name="vlm-test", random_weights=True,
        num_blocks=32, block_size=4, max_batch_size=4,
        enable_prefix_caching=True,  # the poisoning vector
    )
    engine = await JaxEngine.launch(cfg, model_config=mc)
    adapter = engine.as_async_engine()

    async def run(seed: int) -> list[int]:
        rng = np.random.default_rng(seed)
        embeds = rng.standard_normal((8, mc.hidden_size)).astype(np.float32) * 8
        req = PreprocessedRequest(
            request_id=f"mmp-{seed}",
            token_ids=[5, 6] + [0] * 8 + [7, 9],
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=4, ignore_eos=True),
            mm_embeds=pack_segments([(2, embeds)]),
        )
        toks: list[int] = []
        async for item in adapter.generate(req, Context()):
            toks.extend(item.token_ids)
        return toks

    a = await run(1)  # commits image-1-conditioned blocks
    b = await run(2)  # same tokens, different image: must not reuse them
    assert a != b
    # image-1 again: cache hit is fine, output must match the first run
    assert await run(1) == a

    # malformed dim: only this request errors; the engine stays up
    bad = PreprocessedRequest(
        request_id="bad-dim",
        token_ids=[5, 0, 7],
        sampling=SamplingOptions(use_greedy=True),
        stop=StopConditions(max_tokens=2, ignore_eos=True),
        mm_embeds=pack_segments([(1, np.zeros((1, 16), np.float32))]),
    )
    with pytest.raises(ValueError, match="hidden"):
        async for _ in adapter.generate(bad, Context()):
            pass
    oob = PreprocessedRequest(
        request_id="bad-off",
        token_ids=[5, 0, 7],
        sampling=SamplingOptions(use_greedy=True),
        stop=StopConditions(max_tokens=2, ignore_eos=True),
        mm_embeds=pack_segments([(2, np.zeros((5, 32), np.float32))]),
    )
    with pytest.raises(ValueError, match="outside"):
        async for _ in adapter.generate(oob, Context()):
            pass
    assert await run(1) == a  # engine still healthy
    await engine.shutdown()


async def test_engine_injects_image_embeddings():
    """E2E: generation output must depend on the injected embeddings —
    same tokens, different image embeds => different continuation."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    mc = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128,
    )
    cfg = EngineConfig(
        model_path="", model_name="vlm-test", random_weights=True,
        num_blocks=32, block_size=4, max_batch_size=4,
        enable_prefix_caching=False,
    )
    engine = await JaxEngine.launch(cfg, model_config=mc)
    adapter = engine.as_async_engine()

    async def run(seed: int) -> list[int]:
        rng = np.random.default_rng(seed)
        embeds = rng.standard_normal((6, mc.hidden_size)).astype(np.float32) * 8
        req = PreprocessedRequest(
            request_id=f"mm-{seed}",
            token_ids=[5, 6] + [0] * 6 + [7],
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=8, ignore_eos=True),
            mm_embeds=pack_segments([(2, embeds)]),
        )
        toks: list[int] = []
        async for item in adapter.generate(req, Context()):
            toks.extend(item.token_ids)
        return toks

    a = await run(1)
    a2 = await run(1)
    b = await run(2)
    assert a == a2  # deterministic given the same image
    assert a != b  # embeddings actually reach the model
    await engine.shutdown()


def test_load_vision_params_npz(tmp_path):
    from dynamo_tpu.models.vision import (
        load_vision_params,
        vision_param_shapes,
    )

    cfg = TINY_VIT
    shapes = vision_param_shapes(cfg)
    rng = np.random.default_rng(0)
    arrays = {
        name: rng.standard_normal(shape).astype(np.float32)
        for name, (shape, _) in shapes.items()
    }
    path = tmp_path / "vit.npz"
    np.savez(path, **arrays)
    params = load_vision_params(cfg, str(path))
    assert set(params) == set(shapes)
    np.testing.assert_allclose(
        np.asarray(params["proj_1"], np.float32), arrays["proj_1"],
        rtol=1e-2, atol=1e-2,
    )
    # missing key fails loudly
    partial = {k: v for k, v in arrays.items() if k != "wq"}
    bad = tmp_path / "bad.npz"
    np.savez(bad, **partial)
    with pytest.raises(ValueError, match="missing"):
        load_vision_params(cfg, str(bad))


def test_cli_builds_mm_preprocessor(tmp_path):
    """--vision-config wires MultimodalPreprocessor into the pipeline
    head; a tokenizer without the placeholder token fails loudly."""
    import argparse
    import json as _json

    from dynamo_tpu.cli.main import _build_mm_preprocessor
    from dynamo_tpu.preprocessor import PromptFormatter
    from dynamo_tpu.tokenizer import Tokenizer

    vcfg_path = tmp_path / "vit.json"
    vcfg_path.write_text(_json.dumps({
        "image_size": 28, "patch_size": 14, "hidden_size": 32,
        "intermediate_size": 64, "num_hidden_layers": 1,
        "num_attention_heads": 4, "projection_dim": 16,
    }))
    tok = Tokenizer.from_file(MODEL_DIR)
    fmt = PromptFormatter.from_model_dir(MODEL_DIR)
    args = argparse.Namespace(
        vision_config=str(vcfg_path), vision_weights=None,
        image_token="<|end_header_id|>",  # exists in the tiny vocab
    )
    pre = _build_mm_preprocessor(args, tok, fmt, "vlm")
    assert pre.tokens_per_image == 4  # (28/14)^2
    out = pre.preprocess_chat(ChatCompletionRequest.model_validate({
        "model": "vlm",
        "messages": [{"role": "user", "content": [
            {"type": "image_url", "image_url": {"url": _png_data_url()}},
        ]}],
    }))
    assert out.mm_embeds and len(out.mm_embeds) == 1
    args.image_token = "<missing-token>"
    with pytest.raises(SystemExit, match="no"):
        _build_mm_preprocessor(args, tok, fmt, "vlm")
